#!/bin/bash
python -m heterofl_tpu.entry.test_classifier_fed --data_name MNIST --model_name conv --init_seed 0 --num_experiments 1 --resume_mode 0 --control_name 1_100_0.1_iid_fix_a1_bn_1_1 --synthetic 1 --output_dir output_interp --synthetic_sizes '{"train":4000,"test":1000}' --override '{"num_epochs": {"global": 30, "local": 2}, "conv": {"hidden_size": [16, 32]}, "batch_size": {"train": 10, "test": 50}}'
wait
python -m heterofl_tpu.entry.test_classifier_fed --data_name MNIST --model_name conv --init_seed 0 --num_experiments 1 --resume_mode 0 --control_name 1_100_0.1_iid_fix_b1_bn_1_1 --synthetic 1 --output_dir output_interp --synthetic_sizes '{"train":4000,"test":1000}' --override '{"num_epochs": {"global": 30, "local": 2}, "conv": {"hidden_size": [16, 32]}, "batch_size": {"train": 10, "test": 50}}'
wait
python -m heterofl_tpu.entry.test_classifier_fed --data_name MNIST --model_name conv --init_seed 0 --num_experiments 1 --resume_mode 0 --control_name 1_100_0.1_iid_fix_a1-b9_bn_1_1 --synthetic 1 --output_dir output_interp --synthetic_sizes '{"train":4000,"test":1000}' --override '{"num_epochs": {"global": 30, "local": 2}, "conv": {"hidden_size": [16, 32]}, "batch_size": {"train": 10, "test": 50}}'
wait
python -m heterofl_tpu.entry.test_classifier_fed --data_name MNIST --model_name conv --init_seed 0 --num_experiments 1 --resume_mode 0 --control_name 1_100_0.1_iid_fix_a3-b7_bn_1_1 --synthetic 1 --output_dir output_interp --synthetic_sizes '{"train":4000,"test":1000}' --override '{"num_epochs": {"global": 30, "local": 2}, "conv": {"hidden_size": [16, 32]}, "batch_size": {"train": 10, "test": 50}}'
wait
python -m heterofl_tpu.entry.test_classifier_fed --data_name MNIST --model_name conv --init_seed 0 --num_experiments 1 --resume_mode 0 --control_name 1_100_0.1_iid_fix_a5-b5_bn_1_1 --synthetic 1 --output_dir output_interp --synthetic_sizes '{"train":4000,"test":1000}' --override '{"num_epochs": {"global": 30, "local": 2}, "conv": {"hidden_size": [16, 32]}, "batch_size": {"train": 10, "test": 50}}'
wait
python -m heterofl_tpu.entry.test_classifier_fed --data_name MNIST --model_name conv --init_seed 0 --num_experiments 1 --resume_mode 0 --control_name 1_100_0.1_iid_fix_a7-b3_bn_1_1 --synthetic 1 --output_dir output_interp --synthetic_sizes '{"train":4000,"test":1000}' --override '{"num_epochs": {"global": 30, "local": 2}, "conv": {"hidden_size": [16, 32]}, "batch_size": {"train": 10, "test": 50}}'
wait
python -m heterofl_tpu.entry.test_classifier_fed --data_name MNIST --model_name conv --init_seed 0 --num_experiments 1 --resume_mode 0 --control_name 1_100_0.1_iid_fix_a9-b1_bn_1_1 --synthetic 1 --output_dir output_interp --synthetic_sizes '{"train":4000,"test":1000}' --override '{"num_epochs": {"global": 30, "local": 2}, "conv": {"hidden_size": [16, 32]}, "batch_size": {"train": 10, "test": 50}}'
wait
