#!/usr/bin/env python
"""Headline benchmark: federated rounds/sec on the BASELINE.json config --
100-client CIFAR10 ResNet-18, 5-level heterogeneity a1-b1-c1-d1-e1, 10 active
clients x 5 local epochs x 50 steps per round, full HeteroFL semantics
(masked widths, Scaler, sBN-free local BN, label masks, counted-average
aggregation), all inside one jitted round program.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} where
vs_baseline is rounds/sec relative to the 10 rounds/sec north star
(BASELINE.json; the reference itself publishes no wall-clock numbers).

Env knobs: BENCH_ROUNDS (timed rounds, default 5), BENCH_USERS (default 100),
BENCH_SYNTH_N (train images, default 50000), BENCH_CPU=1 to force the
virtual-CPU path (debug), BENCH_DEADLINE (total wall-clock budget in seconds
for the whole bench incl. fallbacks, default 1500), BENCH_TPU_TIMEOUT
(seconds the supervised TPU attempt may take before the CPU fallback;
default = half the deadline), BENCH_SKIP_TPU=1 to skip the TPU attempt.

Deadline contract (VERDICT r1 item 1): the supervisor carves the deadline
into a TPU attempt (<= half), a tiny-model CPU fallback sized to print within
~2 minutes, and a last-resort synthetic record -- ONE JSON line is printed on
stdout no matter what wedges, always with rc 0.
"""

import json
import os
import signal
import subprocess
import sys
import time


def _force_cpu():
    for _v in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE",
               "AXON_LOOPBACK_RELAY", "AXON_POOL_SVC_OVERRIDE"):
        os.environ.pop(_v, None)
    os.environ["JAX_PLATFORMS"] = "cpu"


def _emit_if_json(text) -> bool:
    """Forward the child's result if it printed one; keeps the contract of
    exactly ONE JSON line on stdout even when the child wedges during
    teardown AFTER finishing the measurement."""
    for line in reversed((text or "").strip().splitlines()):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            print(line)
            return True
    return False


def _supervise() -> int:
    """Run the real bench in children with hard timeouts under a total
    deadline.

    The TPU tunnel here is single-client and can hang indefinitely (stale
    grants); probing and then re-initialising would claim the chip twice, so
    instead ONE child owns the whole TPU attempt, and on timeout we kill it
    and rerun a tiny CPU fallback with whatever deadline remains.  If even
    that fails, a synthetic failure record is printed: one JSON line, always,
    rc 0 -- a bench that never prints is worse than any degraded bench.
    """
    def env_float(name, default):
        try:
            return float(os.environ.get(name) or default)
        except ValueError:
            print(f"bench: ignoring malformed {name}={os.environ[name]!r}",
                  file=sys.stderr)
            return float(default)

    start = time.time()
    deadline = env_float("BENCH_DEADLINE", 1500)

    def remaining():
        return deadline - (time.time() - start)

    def run_child(extra_env, budget):
        # Popen in its own session + killpg: jax/tunnel helpers inherit the
        # capture pipes, and a plain subprocess.run timeout-kill would leave
        # them holding the pipes, blocking communicate() forever -- the
        # parsed:null failure mode all over again.
        env = dict(os.environ)
        env.update(extra_env)
        p = subprocess.Popen([sys.executable, os.path.abspath(__file__)],
                             env=env, stdout=subprocess.PIPE,
                             stderr=subprocess.PIPE, text=True,
                             start_new_session=True)
        try:
            out, err = p.communicate(timeout=budget)
            sys.stderr.write(err or "")
            if _emit_if_json(out):  # salvage the result even on teardown crash
                if p.returncode != 0:
                    print(f"bench: child crashed (rc {p.returncode}) after "
                          f"printing its result; using it", file=sys.stderr)
                return True
            return False
        except subprocess.TimeoutExpired:
            try:
                os.killpg(p.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            try:
                out, err = p.communicate(timeout=10)
            except subprocess.TimeoutExpired:
                out, err = "", ""
            sys.stderr.write(err or "")
            if _emit_if_json(out):
                print("bench: child wedged after printing its result "
                      "(teardown hang); using it", file=sys.stderr)
                return True
            print(f"bench: child exceeded {budget:.0f}s", file=sys.stderr)
            return False

    # TPU attempt: at most half the deadline, always leaving room for the CPU
    # fallback (the full 120s reserve by default; an operator-set explicit
    # budget is honored down to a 45s reserve).  Skipped when too little time
    # remains for a meaningful attempt.
    raw = os.environ.get("BENCH_TPU_TIMEOUT")
    try:
        explicit_timeout = float(raw) if raw else None
    except ValueError:
        print(f"bench: ignoring malformed BENCH_TPU_TIMEOUT={raw!r}", file=sys.stderr)
        explicit_timeout = None
    explicit = explicit_timeout is not None
    tpu_budget = min(explicit_timeout if explicit else deadline / 2,
                     remaining() - (45 if explicit else 120))
    if os.environ.get("BENCH_SKIP_TPU") == "1":
        print("bench: skipping TPU attempt (BENCH_SKIP_TPU=1)", file=sys.stderr)
    elif tpu_budget < (1 if explicit else 60):
        print("bench: skipping TPU attempt (no budget)", file=sys.stderr)
    else:
        if run_child({"BENCH_SUPERVISED": "1"}, tpu_budget):
            return 0
        print("bench: TPU attempt failed (wedged tunnel?); falling back to "
              "tiny CPU run", file=sys.stderr)

    # CPU fallback: tiny model + shrunk round so it prints in ~2 min.  Never
    # overrun the deadline -- a driver killing us at the deadline would lose
    # even the last-resort record.
    cpu_budget = remaining() - 15
    if cpu_budget >= 20 and run_child({"BENCH_CPU": "1", "BENCH_FALLBACK": "1"},
                                      cpu_budget):
        return 0

    # Last resort: never leave the driver with parsed: null again.
    print(json.dumps({
        "metric": "federated_rounds_per_sec_cifar10_resnet18_a1-e1_100c",
        "value": 0.0, "unit": "rounds/sec", "vs_baseline": 0.0,
        "extra": {"error": "both TPU attempt and CPU fallback failed/timed "
                           "out within BENCH_DEADLINE",
                  "deadline_sec": deadline},
    }))
    return 0


def main():
    if os.environ.get("BENCH_FAKE_WEDGE") == "1" and os.environ.get("BENCH_SUPERVISED") == "1":
        time.sleep(10_000)  # test hook: simulate a wedged TPU tunnel claim

    fallback = os.environ.get("BENCH_FALLBACK") == "1"
    if os.environ.get("BENCH_CPU") == "1":
        _force_cpu()

    import jax
    import jax.numpy as jnp
    import numpy as np

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from heterofl_tpu import config as C
    from heterofl_tpu.data import fetch_dataset, label_split_masks, split_dataset, stack_client_shards
    from heterofl_tpu.models import make_model
    from heterofl_tpu.parallel import RoundEngine, make_mesh

    # The fallback must PRINT within ~2 min on CPU: tiny widths compile in
    # ~20s and 20 users x 2000 imgs gives 50 local steps/round.
    users = int(os.environ.get("BENCH_USERS", "20" if fallback else "100"))
    n_train = int(os.environ.get("BENCH_SYNTH_N", "2000" if fallback else "50000"))
    timed_rounds = int(os.environ.get("BENCH_ROUNDS", "2" if fallback else "5"))

    cfg = C.default_cfg()
    cfg["control"] = C.parse_control_name(f"1_{users}_0.1_iid_fix_a1-b1-c1-d1-e1_bn_1_1")
    cfg["data_name"] = "CIFAR10"
    cfg["model_name"] = "resnet18"
    cfg["synthetic"] = True
    # bf16 matmul/conv operands with f32 accumulation: the TPU MXU recipe.
    cfg["compute_dtype"] = os.environ.get("BENCH_DTYPE", "bfloat16")
    cfg = C.process_control(cfg)

    hidden = os.environ.get("BENCH_HIDDEN")
    degraded = None
    if hidden:  # debug-only shrink, e.g. BENCH_HIDDEN=8,16,16,16
        cfg["resnet"] = {"hidden_size": [int(h) for h in hidden.split(",")]}
    elif jax.devices()[0].platform == "cpu":
        # even quarter-width ResNet-18 can take >5 min to compile on CPU;
        # the fallback's ONLY job is an honest-schema line, fast
        cfg["resnet"] = {"hidden_size": [8, 16, 16, 16]}
        degraded = "cpu-fallback-tiny-width"

    ds = fetch_dataset("CIFAR10", synthetic=True, seed=0,
                       synthetic_sizes={"train": n_train, "test": 1000})
    rng = np.random.default_rng(0)
    split, lsplit = split_dataset(ds, users, "iid", rng)
    x, y, m = stack_client_shards(ds["train"].data, ds["train"].target, split["train"],
                                  list(range(users)))
    lm = label_split_masks(lsplit, users, 10)
    cfg["classes_size"] = 10
    model = make_model(cfg)
    params = model.init(jax.random.key(0))
    mesh = make_mesh(len(jax.devices()), 1)
    engine = RoundEngine(model, cfg, mesh)
    data = (jnp.asarray(x), jnp.asarray(y), jnp.asarray(m), jnp.asarray(lm))

    n_active = int(np.ceil(cfg["frac"] * users))
    def round_once(params, r):
        user_idx = rng.permutation(users)[:n_active].astype(np.int32)
        params, ms = engine.train_round(params, jax.random.key(r), 0.1, user_idx, data)
        return params, ms

    # compile + warmup
    t0 = time.time()
    params, ms = round_once(params, 0)
    jax.block_until_ready(params)
    compile_s = time.time() - t0
    # timed
    t0 = time.time()
    for r in range(1, timed_rounds + 1):
        params, ms = round_once(params, r)
    jax.block_until_ready(params)
    dt = (time.time() - t0) / timed_rounds
    rps = 1.0 / dt

    loss = float(np.asarray(ms["loss_sum"]).sum() / np.asarray(ms["n"]).sum())
    print(json.dumps({
        "metric": "federated_rounds_per_sec_cifar10_resnet18_a1-e1_100c",
        "value": round(rps, 4),
        "unit": "rounds/sec",
        "vs_baseline": round(rps / 10.0, 4),
        "extra": {"round_sec": round(dt, 3), "compile_sec": round(compile_s, 1),
                  "devices": len(jax.devices()), "platform": jax.devices()[0].platform,
                  "active_clients": n_active, "final_loss": round(loss, 4),
                  **({"degraded": degraded} if degraded else {})},
    }))


if __name__ == "__main__":
    if os.environ.get("BENCH_CPU") == "1" or os.environ.get("BENCH_SUPERVISED") == "1":
        main()
    else:
        sys.exit(_supervise())
