#!/usr/bin/env python
"""Headline benchmark: federated rounds/sec on the BASELINE.json config --
100-client CIFAR10 ResNet-18, 5-level heterogeneity a1-b1-c1-d1-e1, 10 active
clients x 5 local epochs x 50 steps per round, full HeteroFL semantics
(masked widths, Scaler, sBN-free local BN, label masks, counted-average
aggregation), all inside one jitted round program.

The supervised entry (plain `python bench.py`) prints ONE JSON line:
{"metric", "value", "unit", "vs_baseline"} where vs_baseline is rounds/sec
relative to the 10 rounds/sec north star (BASELINE.json; the reference itself
publishes no wall-clock numbers).  Direct debug runs (BENCH_CPU=1 /
BENCH_SUPERVISED=1 in the operator's env) print one refined line per timed
round; take the last.

Env knobs: BENCH_ROUNDS (timed rounds, default 5), BENCH_USERS (default 100),
BENCH_SYNTH_N (train images, default 50000), BENCH_CPU=1 to force the
virtual-CPU path (debug), BENCH_DEADLINE (total wall-clock budget in seconds
for the whole bench incl. fallbacks, default 1500), BENCH_TPU_TIMEOUT
(seconds the supervised TPU attempt may take before the CPU fallback;
default = half the deadline), BENCH_SKIP_TPU=1 to skip the TPU attempt.

Deadline contract (VERDICT r1 item 1): the supervisor carves the deadline
into TPU attempts (<= half), a tiny-model CPU fallback sized to print within
~2 minutes, and a last-resort synthetic record -- ONE JSON line is printed on
stdout no matter what wedges, always with rc 0.

Diagnosability contract (VERDICT r3 item 1): the child stamps every stage
(imported / devices acquired / data staged / compile done / round k/N) on
stderr so a wedge is attributable from the artifact tail, and it prints a
refined JSON line after EVERY timed round -- a mid-run kill still preserves a
real measurement (the supervisor forwards the last complete JSON line).
"""

import json
import os
import signal
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.abspath(__file__))


_CACHE_DIR = None


def _cache_dir():
    """Persistent compile cache: round-2 measured 16-21s compiles (40.3s for
    the flagship program, BENCH_r05); a warm cache under the repo survives
    across bench runs/rounds and shrinks the window in which a wedged tunnel
    can eat the whole TPU budget.  Shared with the fed drivers and the tier-1
    test gate via heterofl_tpu/utils/compile_cache.py (CPU-feature-
    fingerprinted dir -- see that module for the SIGILL rationale).  Loaded
    by FILE PATH, not via the package: the supervisor must stay jax-free
    (importing heterofl_tpu.utils pulls jax through checkpoint.py, adding a
    multi-second import and a failure surface to the must-not-fail path)."""
    global _CACHE_DIR
    if _CACHE_DIR is None:
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "_heterofl_compile_cache",
            os.path.join(_REPO, "heterofl_tpu", "utils", "compile_cache.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)  # imports hashlib/os/sys only
        _CACHE_DIR = mod.default_cache_dir(_REPO)
    return _CACHE_DIR


def _force_cpu():
    for _v in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE",
               "AXON_LOOPBACK_RELAY", "AXON_POOL_SVC_OVERRIDE"):
        os.environ.pop(_v, None)
    os.environ["JAX_PLATFORMS"] = "cpu"


def _emit_if_json(text) -> bool:
    """Forward the child's result if it printed one; keeps the contract of
    exactly ONE JSON line on stdout even when the child wedges during
    teardown AFTER finishing the measurement.  The child prints a refined
    line after every timed round; the LAST complete line wins."""
    for line in reversed((text or "").strip().splitlines()):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            print(line)
            return True
    return False


def _supervise() -> int:
    """Run the real bench in children with hard timeouts under a total
    deadline.

    The TPU tunnel here is single-client and can hang indefinitely (stale
    grants); probing and then re-initialising would claim the chip twice, so
    instead ONE child owns the whole TPU attempt, and on timeout we kill it
    and rerun a tiny CPU fallback with whatever deadline remains.  If even
    that fails, a synthetic failure record is printed: one JSON line, always,
    rc 0 -- a bench that never prints is worse than any degraded bench.
    """
    def env_float(name, default):
        try:
            return float(os.environ.get(name) or default)
        except ValueError:
            print(f"bench: ignoring malformed {name}={os.environ[name]!r}",
                  file=sys.stderr)
            return float(default)

    start = time.time()
    deadline = env_float("BENCH_DEADLINE", 1500)
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _cache_dir())

    def remaining():
        return deadline - (time.time() - start)

    def run_child(extra_env, budget):
        # Popen in its own session + killpg: jax/tunnel helpers inherit the
        # capture pipes, and a plain subprocess.run timeout-kill would leave
        # them holding the pipes, blocking communicate() forever -- the
        # parsed:null failure mode all over again.
        env = dict(os.environ)
        env.update(extra_env)
        # children inherit the warm compile cache (the supervisor setdefaults
        # it above; this keeps the wiring explicit for operator env overrides)
        env.setdefault("JAX_COMPILATION_CACHE_DIR", _cache_dir())
        p = subprocess.Popen([sys.executable, os.path.abspath(__file__)],
                             env=env, stdout=subprocess.PIPE,
                             stderr=subprocess.PIPE, text=True,
                             start_new_session=True)
        try:
            out, err = p.communicate(timeout=budget)
            sys.stderr.write(err or "")
            if _emit_if_json(out):  # salvage the result even on teardown crash
                if p.returncode != 0:
                    print(f"bench: child crashed (rc {p.returncode}) after "
                          f"printing its result; using it", file=sys.stderr)
                return True
            return False
        except subprocess.TimeoutExpired:
            try:
                os.killpg(p.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            try:
                out, err = p.communicate(timeout=10)
            except subprocess.TimeoutExpired:
                out, err = "", ""
            sys.stderr.write(err or "")
            if _emit_if_json(out):
                print("bench: child wedged after printing a result "
                      "(kill mid-run or teardown hang); using the last "
                      "completed-round measurement", file=sys.stderr)
                return True
            print(f"bench: child exceeded {budget:.0f}s", file=sys.stderr)
            return False

    # TPU attempts: at most half the deadline in total, always leaving room
    # for the CPU fallback (the full 120s reserve by default; an operator-set
    # explicit budget is honored down to a 45s reserve).  A wedged tunnel
    # claim sometimes clears on a fresh process, so if the first attempt dies
    # EARLY (well under its budget -- a crash, not a wedge) or there is ample
    # budget left, one retry is made.
    raw = os.environ.get("BENCH_TPU_TIMEOUT")
    try:
        explicit_timeout = float(raw) if raw else None
    except ValueError:
        print(f"bench: ignoring malformed BENCH_TPU_TIMEOUT={raw!r}", file=sys.stderr)
        explicit_timeout = None
    explicit = explicit_timeout is not None
    tpu_total = min(explicit_timeout if explicit else deadline / 2,
                    remaining() - (45 if explicit else 120))
    if os.environ.get("BENCH_SKIP_TPU") == "1":
        print("bench: skipping TPU attempt (BENCH_SKIP_TPU=1)", file=sys.stderr)
    elif tpu_total < (1 if explicit else 60):
        print("bench: skipping TPU attempt (no budget)", file=sys.stderr)
    else:
        tpu_deadline = time.time() + tpu_total
        for attempt in (1, 2):
            budget = tpu_deadline - time.time()
            if budget < (1 if explicit else 60):
                break
            print(f"bench: TPU attempt {attempt} (budget {budget:.0f}s)",
                  file=sys.stderr)
            if run_child({"BENCH_SUPERVISED": "1"}, budget):
                return 0
        print("bench: TPU attempts failed (wedged tunnel?); falling back to "
              "tiny CPU run", file=sys.stderr)

    # CPU fallbacks (VERDICT r4 item 5): first try the REAL flagship program
    # -- 100 users, 10 active clients, full ResNet-18 widths -- with only the
    # per-round data volume cut so it can print on a single core (slow but
    # *about the right program*, honestly labelled).  Only if there is no
    # budget for that, or it wedges, run the tiny-width insurance line.
    # Both are `degraded` and report vs_baseline: null.
    tiny_reserve = 200  # keep room for the tiny insurance child + slack
    real_budget = remaining() - tiny_reserve
    if real_budget >= 420:
        print(f"bench: CPU real-width attempt (budget {real_budget:.0f}s)",
              file=sys.stderr)
        if run_child({"BENCH_CPU": "1", "BENCH_REALWIDTH": "1"}, real_budget):
            return 0
        print("bench: real-width CPU run did not finish; tiny fallback",
              file=sys.stderr)
    cpu_budget = remaining() - 15
    if cpu_budget >= 20 and run_child({"BENCH_CPU": "1", "BENCH_FALLBACK": "1"},
                                      cpu_budget):
        return 0

    # Last resort: never leave the driver with parsed: null again.
    print(json.dumps({
        "metric": "federated_rounds_per_sec_cifar10_resnet18_a1-e1_100c",
        "value": 0.0, "unit": "rounds/sec", "vs_baseline": 0.0,
        "extra": {"error": "both TPU attempt and CPU fallback failed/timed "
                           "out within BENCH_DEADLINE",
                  "deadline_sec": deadline},
    }))
    return 0


def main():
    if os.environ.get("BENCH_FAKE_WEDGE") == "1" and os.environ.get("BENCH_SUPERVISED") == "1":
        time.sleep(10_000)  # test hook: simulate a wedged TPU tunnel claim

    t_start = time.time()

    def hb(stage):
        # Stage-stamped heartbeat: the supervisor forwards child stderr into
        # the driver-captured tail, so the LAST stamp tells exactly where a
        # wedge happened (tunnel claim vs data staging vs compile vs round k).
        print(f"bench[child]: {stage} t=+{time.time() - t_start:.1f}s",
              file=sys.stderr, flush=True)

    fallback = os.environ.get("BENCH_FALLBACK") == "1"
    realwidth = os.environ.get("BENCH_REALWIDTH") == "1"
    if os.environ.get("BENCH_CPU") == "1":
        _force_cpu()
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _cache_dir())

    hb("importing jax")
    import jax
    import jax.numpy as jnp
    import numpy as np

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from heterofl_tpu import config as C
    from heterofl_tpu.data import fetch_dataset, label_split_masks, split_dataset, stack_client_shards
    from heterofl_tpu.models import make_model
    from heterofl_tpu.parallel import (MetricsPipeline, PendingMetrics, PhaseTimer,
                                       RoundEngine, make_mesh)

    hb("claiming devices")
    devs = jax.devices()  # first touch claims the tunnel -- the wedge point
    platform = devs[0].platform
    hb(f"devices acquired: {len(devs)}x {platform}")

    # Both CPU fallbacks keep the flagship's 100-user/10-active federation
    # structure (VERDICT r4 item 5); the tiny one shrinks widths for a fast
    # insurance line, the real-width one shrinks only per-round data volume.
    users = int(os.environ.get("BENCH_USERS", "100"))
    n_train = int(os.environ.get("BENCH_SYNTH_N",
                                 "2000" if (fallback or realwidth) else "50000"))
    timed_rounds = int(os.environ.get("BENCH_ROUNDS",
                                      "1" if realwidth else "2" if fallback else "5"))

    cfg = C.default_cfg()
    cfg["control"] = C.parse_control_name(f"1_{users}_0.1_iid_fix_a1-b1-c1-d1-e1_bn_1_1")
    cfg["data_name"] = "CIFAR10"
    cfg["model_name"] = "resnet18"
    cfg["synthetic"] = True
    # bf16 matmul/conv operands with f32 accumulation: the TPU MXU recipe.
    cfg["compute_dtype"] = os.environ.get("BENCH_DTYPE", "bfloat16")
    cfg = C.process_control(cfg)

    hidden = os.environ.get("BENCH_HIDDEN")
    degraded = None
    if hidden:  # debug-only shrink, e.g. BENCH_HIDDEN=8,16,16,16
        cfg["resnet"] = {"hidden_size": [int(h) for h in hidden.split(",")]}
        degraded = f"hidden-shrink-{hidden}"  # never comparable to baseline
    elif platform == "cpu" and realwidth:
        # flagship widths and federation structure; only the per-client data
        # volume (and with it local steps/round) is cut so a single core can
        # print inside the deadline -- slow but the right program
        degraded = "cpu-real-width-short-shards"
        cfg["num_epochs"] = dict(cfg["num_epochs"], local=1)
    elif platform == "cpu":
        # tiny-width insurance line: must PRINT within ~2 min even cold
        cfg["resnet"] = {"hidden_size": [8, 16, 16, 16]}
        degraded = "cpu-fallback-tiny-width"
    if platform == "cpu":
        # XLA:CPU executes the client-vmapped grouped conv catastrophically
        # (measured 3.7x round slowdown); the numerically-identical im2col
        # lowering is the right default off-TPU (MEASUREMENTS.md round 4)
        cfg["conv_impl"] = os.environ.get("BENCH_CONV_IMPL", "im2col")

    ds = fetch_dataset("CIFAR10", synthetic=True, seed=0,
                       synthetic_sizes={"train": n_train, "test": 1000})
    rng = np.random.default_rng(0)
    split, lsplit = split_dataset(ds, users, "iid", rng)
    x, y, m = stack_client_shards(ds["train"].data, ds["train"].target, split["train"],
                                  list(range(users)))
    lm = label_split_masks(lsplit, users, 10)
    cfg["classes_size"] = 10
    model = make_model(cfg)
    params = model.init(jax.random.key(0))
    mesh = make_mesh(len(devs), 1)
    # BENCH_STRATEGY=grouped: rate-grouped dense per-level programs
    # (parallel/grouped.py) instead of the masked full-width engine -- the
    # on-device A/B for the ~3.9x FLOP reduction (MEASUREMENTS.md roofline)
    strategy = os.environ.get("BENCH_STRATEGY", "masked")
    rates_vec = np.asarray(cfg["model_rate"], np.float32)
    if strategy == "grouped":
        from heterofl_tpu.parallel import GroupedRoundEngine

        engine = GroupedRoundEngine(cfg, mesh)
    else:
        engine = RoundEngine(model, cfg, mesh)
    data = (jnp.asarray(x), jnp.asarray(y), jnp.asarray(m), jnp.asarray(lm))
    hb(f"data staged + engine built (strategy {strategy})")

    n_active = int(np.ceil(cfg["frac"] * users))
    # stage/dispatch/compute/fetch attribution for every timed round, plus
    # BENCH_FETCH_EVERY>1 to pipeline the D2H metric fetch behind the next
    # round's dispatch (parallel/staging.py; default 1 = synchronous parity)
    timer = PhaseTimer()
    try:
        # clamp to >=1 so the emitted fetch_every matches what the pipeline
        # actually does (MetricsPipeline clamps internally too)
        fetch_every = max(1, int(os.environ.get("BENCH_FETCH_EVERY") or 1))
    except ValueError:
        print(f"bench: ignoring malformed "
              f"BENCH_FETCH_EVERY={os.environ['BENCH_FETCH_EVERY']!r}",
              file=sys.stderr)
        fetch_every = 1
    pipe = MetricsPipeline(fetch_every)

    def round_once(params, r):
        user_idx = rng.permutation(users)[:n_active].astype(np.int32)
        if strategy == "grouped":
            params, pending = engine.train_round(params, user_idx, rates_vec[user_idx],
                                                 data, 0.1, jax.random.key(r),
                                                 timer=timer, async_metrics=True)
        else:
            params, ms = engine.train_round(params, jax.random.key(r), 0.1, user_idx,
                                            data, timer=timer)
            pending = PendingMetrics(ms)
        return params, pending

    def emit(rps, dt, compile_s, ms, ms_round, rounds_done, rtimes):
        # a degraded (non-flagship-volume / wrong-platform) run must not
        # pretend to be comparable to the 10 rps north star (VERDICT r4
        # item 5): vs_baseline is null unless this is the real program.
        # With BENCH_FETCH_EVERY>1 the loss lags the timed round by up to K
        # rounds; final_loss_round marks which round it belongs to so a
        # mid-run kill's salvaged line is not silently stale.
        loss = float(np.asarray(ms["loss_sum"]).sum() / np.asarray(ms["n"]).sum())
        print(json.dumps({
            "metric": "federated_rounds_per_sec_cifar10_resnet18_a1-e1_100c",
            "value": round(rps, 4),
            "unit": "rounds/sec",
            "vs_baseline": None if degraded else round(rps / 10.0, 4),
            "extra": {"round_sec": round(dt, 3),
                      # both statistics for BOTH strategies (ADVICE r5 item 1):
                      # 'value' keeps its documented per-strategy semantics, but
                      # cross-strategy comparisons should use like-for-like
                      "round_sec_avg": round(sum(rtimes) / len(rtimes), 3),
                      "round_sec_best": round(min(rtimes), 3),
                      "phases": {k: round(v, 3)
                                 for k, v in sorted(timer.delta(phases_warm).items())},
                      "compile_sec": round(compile_s, 1),
                      "devices": len(devs), "platform": platform,
                      "active_clients": n_active, "users": users,
                      "n_train": n_train, "final_loss": round(loss, 4),
                      "rounds_timed": rounds_done, "strategy": strategy,
                      **({"fetch_every": fetch_every,
                          "final_loss_round": ms_round} if fetch_every != 1 else {}),
                      **({"degraded": degraded} if degraded else {})},
        }), flush=True)

    # compile + warmup
    hb("compiling (warmup round)")
    t0 = time.time()
    params, pending = round_once(params, 0)
    jax.block_until_ready(params)
    last_ms, last_ms_round = pending.fetch(), 0  # warmup metrics, synchronous
    compile_s = time.time() - t0
    # phases are reported RELATIVE to this snapshot so the breakdown shows
    # steady-state cost, not the warmup compile baked into 'dispatch'
    phases_warm = timer.snapshot()
    hb(f"compile done ({compile_s:.1f}s incl. warmup round)")
    # timed; a refined JSON line lands after EVERY round so a mid-run kill
    # still leaves the supervisor a real measurement to forward.  The
    # grouped strategy compiles per-level programs per slot-count bucket, so
    # a timed round can hit a fresh-bucket compile; its 'value' statistic is
    # the BEST (steady-state) round, the masked engine's the running average
    # -- extra.round_sec_avg/_best carry both for either strategy.
    rtimes = []
    for r in range(1, timed_rounds + 1):
        t0 = time.time()
        params, pending = round_once(params, r)
        with timer.phase("compute"):
            jax.block_until_ready(params)
        rtimes.append(time.time() - t0)
        with timer.phase("fetch"):
            due = pipe.push(r, pending)
        if due:
            last_ms_round, last_ms = due[-1]
        dt = min(rtimes) if strategy == "grouped" else sum(rtimes) / len(rtimes)
        hb(f"round {r}/{timed_rounds} done ({dt:.2f}s/round "
           f"{'best' if strategy == 'grouped' else 'avg'})")
        emit(1.0 / dt, dt, compile_s, last_ms, last_ms_round, r, rtimes)
    due = pipe.flush()
    if due:  # deferred-fetch tail: re-emit with the final round's loss
        emit(1.0 / dt, dt, compile_s, due[-1][1], due[-1][0], timed_rounds, rtimes)


if __name__ == "__main__":
    if os.environ.get("BENCH_CPU") == "1" or os.environ.get("BENCH_SUPERVISED") == "1":
        main()
    else:
        sys.exit(_supervise())
