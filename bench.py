#!/usr/bin/env python
"""Headline benchmark: federated rounds/sec on the BASELINE.json config --
100-client CIFAR10 ResNet-18, 5-level heterogeneity a1-b1-c1-d1-e1, 10 active
clients x 5 local epochs x 50 steps per round, full HeteroFL semantics
(masked widths, Scaler, sBN-free local BN, label masks, counted-average
aggregation), all inside one jitted round program.

The supervised entry (plain `python bench.py`) prints ONE JSON line:
{"metric", "value", "unit", "vs_baseline"} where vs_baseline is rounds/sec
relative to the 10 rounds/sec north star (BASELINE.json; the reference itself
publishes no wall-clock numbers).  Direct debug runs (BENCH_CPU=1 /
BENCH_SUPERVISED=1 in the operator's env) print one refined line per timed
round; take the last.

Env knobs: BENCH_ROUNDS (timed rounds, default 5), BENCH_USERS (default 100),
BENCH_SYNTH_N (train images, default 50000), BENCH_CPU=1 to force the
virtual-CPU path (debug), BENCH_DEADLINE (total wall-clock budget in seconds
for the whole bench incl. fallbacks, default 1500), BENCH_TPU_TIMEOUT
(seconds the supervised TPU attempt may take before the CPU fallback;
default = half the deadline), BENCH_SKIP_TPU=1 to skip the TPU attempt,
BENCH_STRATEGY=masked|grouped (primary engine), BENCH_SUPERSTEP=K to fuse K
rounds per compiled dispatch (train_superstep; phases amortize per round),
BENCH_BOTH=0/1 to disable/force the second-strategy record in
extra.strategies (default: on except budget-constrained fallbacks),
BENCH_WIRE_CODEC=dense|int8|signsgd|topk (ISSUE 8) to compress the
aggregation payload inside the fused superstep (extra.wire then records the
measured compressed bytes/round and ratio_vs_dense next to the analytic
per-codec frontier, all from fed.core.level_codec_byte_table -- the same
table staticcheck budgets by equality), BENCH_FETCH_EVERY=K to batch the
D2H metric fetch, BENCH_EVAL_INTERVAL=E to
run the sBN+eval cadence every E rounds -- the primary record then uses the
EVAL-FUSED superstep (eval inside the compiled scan, ISSUE 4) and
extra.strategies carries `<engine>+eval-fused` vs `<engine>+eval-host`
rows, the host row paying the PR 2 clamp (dispatch windows shortened to
min(K, E)) plus a host `eval` phase per window.

BENCH_SCENARIO=<tokens> (ISSUE 9): the scheduler scenario matrix --
comma/plus separated tokens of {uniform, markov, trace, deadline,
buffered} building cfg['schedule'] (heterofl_tpu/sched/): markov/trace =
replayable on/off availability (p_on .7 / p_off .3), deadline = straggler
local-step truncation (min_frac .25), buffered = buffered-async
staleness-weighted aggregation (needs BENCH_SUPERSTEP>1).  Scenario runs
draw cohorts host-side through the one sampling stream and record
per-round participation stats + rounds/sec into extra.scenario.

BENCH_SAMPLER=prp|perm (ISSUE 11): the population sampler behind the one
sampling stream (cfg['sampler'], heterofl_tpu/fed/sampling.py) -- 'prp'
(default) is the O(active) pseudorandom-permutation index-map draw, 'perm'
the legacy full-permutation stream.  Every record carries extra.sampler: the
kind plus a host draw microbench of BOTH samplers at this run's population
(seconds per [1, A] schedule draw, prp-vs-perm speedup) -- at
BENCH_POPULATION=1e6 this is the O(U log U) -> O(active) acceptance
measurement.  The two samplers are DIFFERENT streams: the bench refuses to
record against a newest BENCH_r*.json drawn under the other sampler unless
BENCH_ALLOW_STREAM_CHANGE=1 (trajectory re-baseline must be deliberate).

BENCH_POPULATION=N (ISSUE 6): a population axis.  The federation grows to N
synthetic users (up to 1e6) WITHOUT densifying per-user stacks: users window
onto the shared synthetic sample pool via data.partition.span_population
(O(N) metadata) and the engines stream each dispatch's sampled cohort
through the ClientStore + stage_cohort pipeline, prefetching dispatch i+1's
cohort while dispatch i computes (heavy-traffic sampling: BENCH_ACTIVE
clients/round, default 10, round after round out of N users).
extra.population records the store metadata bytes, peak host RSS
(ru_maxrss) and the prefetched/sync staging counts -- with extra.phases'
`stage` row this is the stage-time-and-RSS-stay-flat-in-population
evidence.  The bench REFUSES to record a population run whose timed
dispatches fell back to synchronous staging unless BENCH_ALLOW_SYNC_STAGE=1
(the warmup dispatch is inherently synchronous and exempt);
BENCH_STREAM_SYNC=1 forces the sync path (the refusal's test hook).
Population runs pin eval off and the second-strategy record off by default,
and are labelled degraded (a different workload than the 100-user
flagship).

MFU (ISSUE 5): extra.mfu reports the analytic FLOPs/round from
fed.core.level_flop_table (expected over the uniform active-client draw)
and, when BENCH_PEAK_FLOPS is set (the hardware peak in FLOP/s, e.g.
2.75e14 for one v4 chip in bf16 x devices), the achieved model FLOP
utilisation mfu = flops_per_round * rounds_per_sec / peak.
BENCH_STEP_AB=1 additionally records the fused-epilogue vs reference-chain
step A/B into extra.step_ab: both measured with the shared procedure plus
the optimized-HLO scan-body kernel counts of the primary engine's hot
program (cfg['fused_update'] on vs off; the staticcheck step-body budget
gates the same counts).

BENCH_TELEMETRY=1 (ISSUE 10): the runtime-telemetry A/B -- one measure
with cfg['telemetry']='on' (in-program health probes riding the metrics
fetch, a TraceRecorder writing trace.json + events.jsonl under
BENCH_TRACE_DIR, default ./obs_trace) against one with telemetry off,
recorded into extra.obs with the overhead percentage, the last round's
probe record and the trace artifact path.  The watchdog (warn mode) runs
over every fetched round's probes; if it FIRED the A/B is refused --
extra.obs carries the trip evidence instead of on/off numbers, because a
rounds/sec figure measured through a diverging run is not a telemetry
overhead.  Needs BENCH_SUPERSTEP>1 for the grouped strategy; ignored in
population mode (the A/B measures the eager flagship program).

BENCH_ARMS=E (ISSUE 14): the experiment-arms multiplexer A/B -- ONE E-arm
fused superstep program vs E serial solo runs on equal per-arm devices,
into extra.arms (aggregate arm-rounds/sec both ways, speedup, compile
counts, peak RSS).  BENCH_ARMS_PLACEMENT=mesh (default when the device
count divides: each arm on its own mesh rows, executing concurrently) or
vmap (batched per device).  Needs BENCH_SUPERSTEP>1; skipped under
population/scenario/codec knobs.

BENCH_CHAOS=1 (ISSUE 15): the fault-tolerance drill measurements -- one
watchdog-rollback poison drill (seeded NaN client update, auto-recovery)
and one quarantine poison drill on the drill's small synthetic
federation, recorded into extra.chaos: rollback-recovery MTTR (trip ->
first replayed train record) and wall clock, trip/recovery counts, and
the quarantined-client count.  If the rollback recovery ESCALATES to
abort the record is refused -- extra.chaos carries the escalation
evidence instead of an MTTR, because a recovery time measured through a
run that needed human intervention is not a recovery time.

BENCH_POD=1 (ISSUE 17): the 2-process pod probe -- a real jax.distributed
CPU mesh (gloo collectives) runs the fused grouped-slices superstep with
the levels host-aligned on disjoint processes, recording per-process
rounds/sec + checkpoint-write times, the DCN classification from the real
process grid, and the bitwise-vs-single-process gate into extra.pod.
Refused when STATICCHECK.json reports a failed multi-host DCN budget
audit (extra.wire also carries the analytic per-link ICI-vs-DCN split
per strategy either way).

BENCH_LEDGER=1 (ISSUE 12): the population-observatory A/B -- one measure
with telemetry='hist' (cohort histograms riding the metrics fetch) PLUS a
host-side ClientLedger folded O(active) per fetch from the recomputed
schedule rows, against one with both off, recorded into extra.obs.ledger
(overhead percentage, resident ledger bytes + bytes/user, coverage, the
last hist record).  ledger.npz and the per-fetch {"tag":"ledger"} summary
lines land under BENCH_TRACE_DIR (default ./obs_trace) for
`python -m heterofl_tpu.obs.report`.  Unlike BENCH_TELEMETRY this runs in
population mode too -- BENCH_POPULATION=1e6 IS the acceptance scale for
the <= ~32 bytes/user resident bound.  Needs BENCH_SUPERSTEP>1 (the
schedule re-draw covers superstep dispatches); a fired warn-mode watchdog
refuses the record.

'value' is like-for-like across strategies: the average per-round seconds
over timed rounds EXCLUDING rounds that compiled a fresh program shape
(grouped slot-bucket compiles, superstep shape changes; detected via
engine.program_cache_size() growth), inverted to rounds/sec.
extra.compile_cache carries persistent-cache hit/miss counts so recompiles
are visible in the artifact.

Deadline contract (VERDICT r1 item 1): the supervisor carves the deadline
into TPU attempts (<= half), a tiny-model CPU fallback sized to print within
~2 minutes, and a last-resort synthetic record -- ONE JSON line is printed on
stdout no matter what wedges, always with rc 0.

Diagnosability contract (VERDICT r3 item 1): the child stamps every stage
(imported / devices acquired / data staged / compile done / round k/N) on
stderr so a wedge is attributable from the artifact tail, and it prints a
refined JSON line after EVERY timed round -- a mid-run kill still preserves a
real measurement (the supervisor forwards the last complete JSON line).
"""

import json
import os
import signal
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.abspath(__file__))


_CACHE_DIR = None


def _cache_dir():
    """Persistent compile cache: round-2 measured 16-21s compiles (40.3s for
    the flagship program, BENCH_r05); a warm cache under the repo survives
    across bench runs/rounds and shrinks the window in which a wedged tunnel
    can eat the whole TPU budget.  Shared with the fed drivers and the tier-1
    test gate via heterofl_tpu/utils/compile_cache.py (CPU-feature-
    fingerprinted dir -- see that module for the SIGILL rationale).  Loaded
    by FILE PATH, not via the package: the supervisor must stay jax-free
    (importing heterofl_tpu.utils pulls jax through checkpoint.py, adding a
    multi-second import and a failure surface to the must-not-fail path)."""
    global _CACHE_DIR
    if _CACHE_DIR is None:
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "_heterofl_compile_cache",
            os.path.join(_REPO, "heterofl_tpu", "utils", "compile_cache.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)  # imports hashlib/os/sys only
        _CACHE_DIR = mod.default_cache_dir(_REPO)
    return _CACHE_DIR


def _load_staticcheck():
    """Summarise the STATICCHECK.json artifact (the staticcheck auditor's
    program report, ISSUE 3) for ``extra.staticcheck``: audit status, per-
    program peak temp bytes from ``memory_analysis()``, lint finding count.
    None when the artifact is absent/unreadable -- the bench still runs,
    but a FRESH failing audit makes the bench refuse to record (see main).

    ``stale`` flags an artifact older than the newest package source file:
    a stale green artifact proves nothing about the current tree (the
    record says so instead of implying a guarantee), and a stale FAILING
    artifact no longer blocks a tree that may already be fixed -- rerun
    ``python -m heterofl_tpu.staticcheck`` to refresh either way."""
    path = os.path.join(_REPO, "STATICCHECK.json")
    try:
        with open(path) as f:
            rec = json.load(f)
        artifact_mtime = os.path.getmtime(path)
    except (OSError, json.JSONDecodeError):
        return None
    newest_src = 0.0
    for dirpath, dirnames, filenames in os.walk(os.path.join(_REPO, "heterofl_tpu")):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if fn.endswith(".py"):
                try:
                    newest_src = max(newest_src,
                                     os.path.getmtime(os.path.join(dirpath, fn)))
                except OSError:
                    pass
    progs = rec.get("programs") or {}
    mem = {name: (p.get("memory") or {}).get("temp_size_in_bytes")
           for name, p in progs.items()}
    # ratchet summary (ISSUE 7): ratchet_ok is None when the artifact was
    # produced without --diff-baseline; a checked-and-regressed ratchet
    # blocks recording the same way a failing audit does (see main)
    ratchet = rec.get("ratchet") or {}
    # DCN budget status (ISSUE 17): the multi-host program entries' wire
    # findings plus the AOT v4-128 record -- BENCH_POD refuses to record
    # pod numbers against a failed DCN budget audit.  None when the
    # artifact predates the multi-host matrix.
    mh_findings = [f for name, p in progs.items() if name.endswith("/mh")
                   for f in (p.get("findings") or [])]
    aot = (rec.get("config") or {}).get("aot_v4128") or {}
    dcn_audit_ok = None
    if any(name.endswith("/mh") for name in progs):
        dcn_audit_ok = (not mh_findings
                        and aot.get("ok", True) is not False)
    return {"ok": bool(rec.get("ok")),
            "dcn_audit_ok": dcn_audit_ok,
            "stale": newest_src > artifact_mtime,
            "generated_at": rec.get("generated_at"),
            "programs_audited": len(progs),
            "lint_findings": len(rec.get("lint") or []),
            "ratchet_ok": (bool(ratchet.get("ok"))
                           if ratchet.get("checked") else None),
            "ratchet_regressions": len(ratchet.get("regressions") or []),
            "program_temp_bytes": {k: v for k, v in mem.items() if v}}


def _latest_bench_record():
    """The newest committed BENCH_r*.json (by round number), or None: the
    baseline the sampling-stream comparability gate (ISSUE 11) checks this
    run's sampler kind against.  The loaded record carries its path under
    ``_path`` for the refusal message."""
    import re

    best, best_n = None, -1
    try:
        names = os.listdir(_REPO)
    except OSError:
        return None
    for fn in names:
        m = re.fullmatch(r"BENCH_r(\d+)\.json", fn)
        if m and int(m.group(1)) > best_n:
            best_n, best = int(m.group(1)), fn
    if best is None:
        return None
    try:
        with open(os.path.join(_REPO, best)) as f:
            rec = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(rec, dict):
        return None
    rec["_path"] = best
    return rec


def _force_cpu():
    for _v in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE",
               "AXON_LOOPBACK_RELAY", "AXON_POOL_SVC_OVERRIDE"):
        os.environ.pop(_v, None)
    os.environ["JAX_PLATFORMS"] = "cpu"


def _emit_if_json(text) -> bool:
    """Forward the child's result if it printed one; keeps the contract of
    exactly ONE JSON line on stdout even when the child wedges during
    teardown AFTER finishing the measurement.  The child prints a refined
    line after every timed round; the LAST complete line wins."""
    for line in reversed((text or "").strip().splitlines()):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            print(line)
            return True
    return False


def _supervise() -> int:
    """Run the real bench in children with hard timeouts under a total
    deadline.

    The TPU tunnel here is single-client and can hang indefinitely (stale
    grants); probing and then re-initialising would claim the chip twice, so
    instead ONE child owns the whole TPU attempt, and on timeout we kill it
    and rerun a tiny CPU fallback with whatever deadline remains.  If even
    that fails, a synthetic failure record is printed: one JSON line, always,
    rc 0 -- a bench that never prints is worse than any degraded bench.
    """
    def env_float(name, default):
        try:
            return float(os.environ.get(name) or default)
        except ValueError:
            print(f"bench: ignoring malformed {name}={os.environ[name]!r}",
                  file=sys.stderr)
            return float(default)

    start = time.time()
    deadline = env_float("BENCH_DEADLINE", 1500)
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _cache_dir())

    def remaining():
        return deadline - (time.time() - start)

    def run_child(extra_env, budget):
        # Popen in its own session + killpg: jax/tunnel helpers inherit the
        # capture pipes, and a plain subprocess.run timeout-kill would leave
        # them holding the pipes, blocking communicate() forever -- the
        # parsed:null failure mode all over again.
        env = dict(os.environ)
        env.update(extra_env)
        # children inherit the warm compile cache (the supervisor setdefaults
        # it above; this keeps the wiring explicit for operator env overrides)
        env.setdefault("JAX_COMPILATION_CACHE_DIR", _cache_dir())
        p = subprocess.Popen([sys.executable, os.path.abspath(__file__)],
                             env=env, stdout=subprocess.PIPE,
                             stderr=subprocess.PIPE, text=True,
                             start_new_session=True)
        try:
            out, err = p.communicate(timeout=budget)
            sys.stderr.write(err or "")
            if _emit_if_json(out):  # salvage the result even on teardown crash
                if p.returncode != 0:
                    print(f"bench: child crashed (rc {p.returncode}) after "
                          f"printing its result; using it", file=sys.stderr)
                return True
            return False
        except subprocess.TimeoutExpired:
            try:
                os.killpg(p.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            try:
                out, err = p.communicate(timeout=10)
            except subprocess.TimeoutExpired:
                out, err = "", ""
            sys.stderr.write(err or "")
            if _emit_if_json(out):
                print("bench: child wedged after printing a result "
                      "(kill mid-run or teardown hang); using the last "
                      "completed-round measurement", file=sys.stderr)
                return True
            print(f"bench: child exceeded {budget:.0f}s", file=sys.stderr)
            return False

    # TPU attempts: at most half the deadline in total, always leaving room
    # for the CPU fallback (the full 120s reserve by default; an operator-set
    # explicit budget is honored down to a 45s reserve).  A wedged tunnel
    # claim sometimes clears on a fresh process, so if the first attempt dies
    # EARLY (well under its budget -- a crash, not a wedge) or there is ample
    # budget left, one retry is made.
    raw = os.environ.get("BENCH_TPU_TIMEOUT")
    try:
        explicit_timeout = float(raw) if raw else None
    except ValueError:
        print(f"bench: ignoring malformed BENCH_TPU_TIMEOUT={raw!r}", file=sys.stderr)
        explicit_timeout = None
    explicit = explicit_timeout is not None
    tpu_total = min(explicit_timeout if explicit else deadline / 2,
                    remaining() - (45 if explicit else 120))
    if os.environ.get("BENCH_SKIP_TPU") == "1":
        print("bench: skipping TPU attempt (BENCH_SKIP_TPU=1)", file=sys.stderr)
    elif tpu_total < (1 if explicit else 60):
        print("bench: skipping TPU attempt (no budget)", file=sys.stderr)
    else:
        tpu_deadline = time.time() + tpu_total
        for attempt in (1, 2):
            budget = tpu_deadline - time.time()
            if budget < (1 if explicit else 60):
                break
            print(f"bench: TPU attempt {attempt} (budget {budget:.0f}s)",
                  file=sys.stderr)
            if run_child({"BENCH_SUPERVISED": "1"}, budget):
                return 0
        print("bench: TPU attempts failed (wedged tunnel?); falling back to "
              "tiny CPU run", file=sys.stderr)

    # CPU fallbacks (VERDICT r4 item 5): first try the REAL flagship program
    # -- 100 users, 10 active clients, full ResNet-18 widths -- with only the
    # per-round data volume cut so it can print on a single core (slow but
    # *about the right program*, honestly labelled).  Only if there is no
    # budget for that, or it wedges, run the tiny-width insurance line.
    # Both are `degraded` and report vs_baseline: null.
    tiny_reserve = 200  # keep room for the tiny insurance child + slack
    real_budget = remaining() - tiny_reserve
    if real_budget >= 420:
        print(f"bench: CPU real-width attempt (budget {real_budget:.0f}s)",
              file=sys.stderr)
        if run_child({"BENCH_CPU": "1", "BENCH_REALWIDTH": "1"}, real_budget):
            return 0
        print("bench: real-width CPU run did not finish; tiny fallback",
              file=sys.stderr)
    cpu_budget = remaining() - 15
    if cpu_budget >= 20 and run_child({"BENCH_CPU": "1", "BENCH_FALLBACK": "1"},
                                      cpu_budget):
        return 0

    # Last resort: never leave the driver with parsed: null again.
    print(json.dumps({
        "metric": "federated_rounds_per_sec_cifar10_resnet18_a1-e1_100c",
        "value": 0.0, "unit": "rounds/sec", "vs_baseline": 0.0,
        "extra": {"error": "both TPU attempt and CPU fallback failed/timed "
                           "out within BENCH_DEADLINE",
                  "deadline_sec": deadline},
    }))
    return 0


def main():
    if os.environ.get("BENCH_FAKE_WEDGE") == "1" and os.environ.get("BENCH_SUPERVISED") == "1":
        time.sleep(10_000)  # test hook: simulate a wedged TPU tunnel claim

    t_start = time.time()

    def hb(stage):
        # Stage-stamped heartbeat: the supervisor forwards child stderr into
        # the driver-captured tail, so the LAST stamp tells exactly where a
        # wedge happened (tunnel claim vs data staging vs compile vs round k).
        print(f"bench[child]: {stage} t=+{time.time() - t_start:.1f}s",
              file=sys.stderr, flush=True)

    fallback = os.environ.get("BENCH_FALLBACK") == "1"
    realwidth = os.environ.get("BENCH_REALWIDTH") == "1"
    if os.environ.get("BENCH_CPU") == "1":
        _force_cpu()
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _cache_dir())

    hb("importing jax")
    import jax
    import jax.numpy as jnp
    import numpy as np

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from heterofl_tpu import config as C
    from heterofl_tpu.data import fetch_dataset, label_split_masks, split_dataset, stack_client_shards
    from heterofl_tpu.fed.core import round_users
    from heterofl_tpu.models import make_model
    from heterofl_tpu.parallel import (MetricsPipeline, PendingMetrics, PhaseTimer,
                                       RoundEngine, make_mesh)
    from heterofl_tpu.utils.compile_cache import install_cache_counters

    # persistent-compile-cache visibility (ISSUE 2 satellite): hit/miss
    # counts land in extra.compile_cache so a superstep recompile (a new
    # program shape per K) is attributable instead of silently eating the
    # ~40s flagship compile
    cache_counters = install_cache_counters()

    # staticcheck gate (ISSUE 3 satellite): a bench record against a tree
    # whose program audit FAILED would launder a known-broken round program
    # into the trajectory -- refuse (still one JSON line, rc 0) unless the
    # operator explicitly overrides.  An absent artifact does not block, and
    # a STALE one (older than the newest package source) neither blocks nor
    # vouches -- extra.staticcheck carries the stale flag either way.
    staticcheck = _load_staticcheck()
    if staticcheck is not None \
            and (not staticcheck["ok"] or staticcheck["ratchet_ok"] is False) \
            and not staticcheck["stale"] \
            and os.environ.get("BENCH_SKIP_STATICCHECK") != "1":
        what = ("a failing program audit" if not staticcheck["ok"]
                else "a regressed baseline ratchet")
        print(json.dumps({
            "metric": "federated_rounds_per_sec_cifar10_resnet18_a1-e1_100c",
            "value": 0.0, "unit": "rounds/sec", "vs_baseline": None,
            "extra": {"error": f"STATICCHECK.json reports {what}; refusing "
                               f"to record a bench run. Rerun `python -m "
                               f"heterofl_tpu.staticcheck --diff-baseline` "
                               f"(or set BENCH_SKIP_STATICCHECK=1 to "
                               f"override).",
                      "staticcheck": staticcheck},
        }), flush=True)
        return

    # sampling-stream comparability gate (ISSUE 11): a prp record landing
    # next to a perm baseline (or vice versa) compares two different seeded
    # trajectories as if they were one series -- refuse unless the operator
    # explicitly acknowledges the re-baseline.  Records before ISSUE 11
    # carry no extra.sampler and drew the legacy permutation stream.
    sampler_kind = os.environ.get("BENCH_SAMPLER", "") or "prp"
    if sampler_kind not in ("perm", "prp"):
        print(f"bench: ignoring unknown BENCH_SAMPLER={sampler_kind!r} "
              f"(one of perm|prp)", file=sys.stderr)
        sampler_kind = "prp"
    prev_bench = _latest_bench_record()
    if prev_bench is not None \
            and os.environ.get("BENCH_ALLOW_STREAM_CHANGE") != "1":
        prev_kind = ((prev_bench.get("extra") or {}).get("sampler") or {}) \
            .get("kind", "perm")
        if prev_kind != sampler_kind:
            print(json.dumps({
                "metric": "federated_rounds_per_sec_cifar10_resnet18_a1-e1_100c",
                "value": 0.0, "unit": "rounds/sec", "vs_baseline": None,
                "extra": {"error": f"sampling-stream change: this run draws "
                                   f"sampler={sampler_kind!r} but the newest "
                                   f"committed bench record "
                                   f"({prev_bench.get('_path')}) was drawn "
                                   f"under {prev_kind!r} -- every seeded "
                                   f"trajectory differs, so the records are "
                                   f"not comparable.  Set "
                                   f"BENCH_ALLOW_STREAM_CHANGE=1 to record "
                                   f"the deliberate re-baseline.",
                          "sampler": {"kind": sampler_kind,
                                      "previous_kind": prev_kind}},
            }), flush=True)
            return

    hb("claiming devices")
    devs = jax.devices()  # first touch claims the tunnel -- the wedge point
    platform = devs[0].platform
    hb(f"devices acquired: {len(devs)}x {platform}")

    # Both CPU fallbacks keep the flagship's 100-user/10-active federation
    # structure (VERDICT r4 item 5); the tiny one shrinks widths for a fast
    # insurance line, the real-width one shrinks only per-round data volume.
    users = int(os.environ.get("BENCH_USERS", "100"))
    # BENCH_POPULATION=N (ISSUE 6): grow the federation to N streaming users
    try:
        population = int(float(os.environ.get("BENCH_POPULATION", "0") or 0))
    except ValueError:
        print(f"bench: ignoring malformed BENCH_POPULATION="
              f"{os.environ['BENCH_POPULATION']!r}", file=sys.stderr)
        population = 0
    if population:
        users = population
    n_train = int(os.environ.get("BENCH_SYNTH_N",
                                 "2000" if (fallback or realwidth) else "50000"))
    timed_rounds = int(os.environ.get("BENCH_ROUNDS",
                                      "1" if realwidth else "2" if fallback else "5"))

    cfg = C.default_cfg()
    cfg["control"] = C.parse_control_name(f"1_{users}_0.1_iid_fix_a1-b1-c1-d1-e1_bn_1_1")
    cfg["data_name"] = "CIFAR10"
    cfg["model_name"] = "resnet18"
    cfg["sampler"] = sampler_kind  # ISSUE 11 (validated by process_control)
    cfg["synthetic"] = True
    # bf16 matmul/conv operands with f32 accumulation: the TPU MXU recipe.
    cfg["compute_dtype"] = os.environ.get("BENCH_DTYPE", "bfloat16")
    cfg = C.process_control(cfg)

    hidden = os.environ.get("BENCH_HIDDEN")
    degraded = None
    if hidden:  # debug-only shrink, e.g. BENCH_HIDDEN=8,16,16,16
        cfg["resnet"] = {"hidden_size": [int(h) for h in hidden.split(",")]}
        degraded = f"hidden-shrink-{hidden}"  # never comparable to baseline
    elif platform == "cpu" and realwidth:
        # flagship widths and federation structure; only the per-client data
        # volume (and with it local steps/round) is cut so a single core can
        # print inside the deadline -- slow but the right program
        degraded = "cpu-real-width-short-shards"
        cfg["num_epochs"] = dict(cfg["num_epochs"], local=1)
    elif platform == "cpu":
        # tiny-width insurance line: must PRINT within ~2 min even cold
        cfg["resnet"] = {"hidden_size": [8, 16, 16, 16]}
        degraded = "cpu-fallback-tiny-width"
    if population:
        # a different federation (N users, fixed 10-client cohorts) -- never
        # comparable to the 100-user 10 rps north star
        degraded = f"population-{population}" + (f"+{degraded}" if degraded else "")
    if platform == "cpu":
        # XLA:CPU executes the client-vmapped grouped conv catastrophically
        # (measured 3.7x round slowdown); the numerically-identical im2col
        # lowering is the right default off-TPU (MEASUREMENTS.md round 4)
        cfg["conv_impl"] = os.environ.get("BENCH_CONV_IMPL", "im2col")

    ds = fetch_dataset("CIFAR10", synthetic=True, seed=0,
                       synthetic_sizes={"train": n_train, "test": 1000})
    store = None
    pop_stats = {"prefetched": 0, "sync": 0}
    pop_prefetch = os.environ.get("BENCH_STREAM_SYNC") != "1"
    if population:
        # streaming population (ISSUE 6): users window onto the shared
        # synthetic pool -- O(population) metadata, no [U, ...] stacks, the
        # flagship per-user shard volume (500 samples) regardless of N
        from heterofl_tpu.data import span_population
        from heterofl_tpu.parallel import ClientStore

        cfg["client_store"] = "stream"
        shard = min(int(os.environ.get("BENCH_POP_SHARD", "500")), n_train)
        starts, sizes = span_population(n_train, population, shard)
        store = ClientStore.from_spans(ds["train"].data, ds["train"].target,
                                       starts, sizes, 10)
        split = lsplit = None
        x = np.zeros((0, shard), np.int8)  # population mode never stacks
        lm = None
        if os.environ.get("BENCH_EVAL_INTERVAL"):
            print("bench: BENCH_EVAL_INTERVAL ignored in population mode "
                  "(local eval is O(population); the axis measures staging)",
                  file=sys.stderr)
            os.environ["BENCH_EVAL_INTERVAL"] = "0"
    else:
        rng = np.random.default_rng(0)
        split, lsplit = split_dataset(ds, users, "iid", rng)
        x, y, m = stack_client_shards(ds["train"].data, ds["train"].target, split["train"],
                                      list(range(users)))
        lm = label_split_masks(lsplit, users, 10)
    cfg["classes_size"] = 10
    model = make_model(cfg)
    params = model.init(jax.random.key(0))
    mesh = make_mesh(len(devs), 1)
    # BENCH_STRATEGY=grouped: rate-grouped dense per-level programs
    # (parallel/grouped.py) instead of the masked full-width engine -- the
    # on-device A/B for the ~3.9x FLOP reduction (MEASUREMENTS.md roofline)
    strategy = os.environ.get("BENCH_STRATEGY", "masked")
    rates_vec = np.asarray(cfg["model_rate"], np.float32)
    # BENCH_WIRE_CODEC (ISSUE 8): compress the aggregation payload inside
    # the fused round (heterofl_tpu/compress/).  Lossy codecs need the
    # fused superstep (the grouped K=1 path has no single global psum), so
    # a codec without BENCH_SUPERSTEP>1 falls back to dense with a note --
    # the bench must still print its one JSON line.
    wire_codec = os.environ.get("BENCH_WIRE_CODEC", "dense") or "dense"
    try:
        from heterofl_tpu.compress import resolve_codec_cfg

        wire_codec, _ = resolve_codec_cfg({"wire_codec": wire_codec})
    except ValueError as e:
        print(f"bench: ignoring BENCH_WIRE_CODEC: {e}", file=sys.stderr)
        wire_codec = "dense"
    try:
        _superstep_env = int(os.environ.get("BENCH_SUPERSTEP") or 1)
    except ValueError:
        _superstep_env = 1  # env_int warns + defaults later; keep its rule
    if wire_codec != "dense" and _superstep_env <= 1:
        print(f"bench: BENCH_WIRE_CODEC={wire_codec} needs BENCH_SUPERSTEP>1 "
              f"(compression lives in the fused superstep); falling back to "
              f"dense", file=sys.stderr)
        wire_codec = "dense"
    cfg["wire_codec"] = wire_codec

    # BENCH_SCENARIO (ISSUE 9): scheduler scenario matrix -- comma/plus
    # separated tokens of {uniform, markov, trace, deadline, buffered}
    # building cfg['schedule'] (markov availability p_on=.7/p_off=.3,
    # deadline min_frac=.25, buffered-async staleness .5).  Scenario runs
    # draw their cohorts HOST-side through the one sampling stream
    # (fed.core.superstep_user_schedule) so participation is countable, and
    # extra.scenario records the per-round active-slot statistics next to
    # the run's rounds/sec.
    scenario_raw = os.environ.get("BENCH_SCENARIO", "") or ""
    scenario_tokens = [t.strip() for t in scenario_raw.replace("+", ",").split(",")
                       if t.strip()]
    sched_cfg = {}
    for t in scenario_tokens:
        if t == "uniform":
            continue
        if t in ("markov", "trace"):
            # 'trace' records/replays the markov-generated availability
            # matrix -- the replayable-trace path with a built-in source
            sched_cfg.update({"kind": "markov",
                              "markov": {"p_on": 0.7, "p_off": 0.3,
                                         "length": 64, "seed": 0}})
        elif t == "deadline":
            sched_cfg["deadline"] = {"min_frac": 0.25}
        elif t == "buffered":
            sched_cfg["aggregation"] = "buffered"
        else:
            print(f"bench: ignoring unknown BENCH_SCENARIO token {t!r}",
                  file=sys.stderr)
    if sched_cfg.get("aggregation") == "buffered" and _superstep_env <= 1:
        print("bench: BENCH_SCENARIO buffered needs BENCH_SUPERSTEP>1 (the "
              "staleness buffer rides the fused scan carry); dropping the "
              "buffered token", file=sys.stderr)
        sched_cfg.pop("aggregation")
    sched_spec = None
    if sched_cfg:
        from heterofl_tpu.sched import resolve_schedule_cfg

        cfg["schedule"] = sched_cfg
        sched_spec = resolve_schedule_cfg(cfg)
    part_stats = {"filled": []}

    def track_participation(us):
        """Count filled (id >= 0) slots per drawn round -- the scenario's
        participation record."""
        if sched_spec is not None:
            part_stats["filled"].extend(
                (np.asarray(us) >= 0).sum(axis=1).tolist())

    def make_engine(strat, cfg_over=None):
        c = cfg if not cfg_over else dict(cfg, **cfg_over)
        if strat == "grouped":
            from heterofl_tpu.parallel import GroupedRoundEngine

            return GroupedRoundEngine(c, mesh)
        return RoundEngine(model, c, mesh)

    engine = make_engine(strategy)
    if population:
        data = None
        hb(f"population store built ({population} users, "
           f"{store.metadata_nbytes} metadata bytes; strategy {strategy})")
    else:
        data = (jnp.asarray(x), jnp.asarray(y), jnp.asarray(m), jnp.asarray(lm))
        hb(f"data staged + engine built (strategy {strategy})")

    if population:
        # heavy-traffic sampling: a bounded cohort per round, drawn from the
        # whole population round after round (frac*N would melt any host)
        n_active = int(os.environ.get("BENCH_ACTIVE", "10"))
    else:
        n_active = int(np.ceil(cfg["frac"] * users))
    # MFU account (ISSUE 5): analytic FLOPs per round from the ONE level
    # FLOP source of truth (fed.core.level_flop_table -- the same table the
    # staticcheck FLOP budget and scripts/grouped_flops.py consume),
    # expected over the uniform active-client draw; BENCH_PEAK_FLOPS (the
    # hardware peak in FLOP/s) turns it into achieved utilisation.
    from heterofl_tpu.fed.core import level_flop_table

    flop_table = level_flop_table(cfg)
    # wire account (ISSUE 7): the dense bytes-on-the-wire per fused round
    # from the analytic byte table (the SAME table the staticcheck wire
    # budget enforces by equality against the traced psum operands) -- the
    # recorded dense baseline the compressed-aggregation frontier lands
    # against.  Both strategies' fused rounds join ONE global reduction of
    # the level-a footprint (sums + count masks, f32); the per-level rows
    # are the sliced payloads of the grouped engine's K=1 per-level psums.
    from heterofl_tpu.compress import LOSSY_CODECS
    from heterofl_tpu.fed.core import level_byte_table, level_codec_byte_table
    from heterofl_tpu.staticcheck.wire import (codec_round_wire,
                                               dense_round_wire, link_split)

    byte_table = level_byte_table(cfg)
    top_rate = max(byte_table)
    dense_payload = byte_table[top_rate]["wire_bytes"]
    # per-codec compressed bytes/round from the SAME table staticcheck
    # budgets by equality against the traced psum operand avals (ISSUE 8:
    # no second bytes formula); `codecs` is the analytic frontier, the
    # per-strategy rows record what THIS run's engines actually moved
    # (both strategies' fused rounds reduce at the level-a footprint)
    n_dev_wire = mesh.shape["clients"]
    codec_bytes = {c: level_codec_byte_table(cfg, c, n_leaves=len(params))[top_rate]
                   for c in LOSSY_CODECS}

    def strategy_wire():
        if wire_codec == "dense":
            return dense_round_wire(byte_table[top_rate]["param_bytes"],
                                    n_dev_wire)
        return codec_round_wire(wire_codec, codec_bytes[wire_codec],
                                dense_payload, n_dev_wire)

    wire_extra = {
        "source": "fed.core.level_byte_table + level_codec_byte_table",
        "unit": "bytes/round",
        "codec": wire_codec,
        "per_level_wire_bytes": {f"{r:g}": v["wire_bytes"]
                                 for r, v in sorted(byte_table.items(),
                                                    reverse=True)},
        "codecs": {c: codec_round_wire(c, b, dense_payload, n_dev_wire)
                   for c, b in sorted(codec_bytes.items())},
        "strategies": {s: strategy_wire() for s in ("masked", "grouped")},
        # per-link ICI-vs-DCN split (ISSUE 17 satellite): the same
        # analytic payload priced per bidirectional-ring link -- all-ICI
        # at this run's process layout, plus the 2-process pod-probe
        # projection where the host-aligned slices placement puts exactly
        # h links on DCN (staticcheck.wire.link_split)
        "link_split": {s: {
            "this_run": link_split(
                dense_payload if wire_codec == "dense"
                else codec_bytes[wire_codec],
                n_dev_wire, jax.process_count()),
            "pod_2proc": link_split(
                dense_payload if wire_codec == "dense"
                else codec_bytes[wire_codec],
                n_dev_wire, 2),
        } for s in ("masked", "grouped")},
    }
    shard_n = store.shard_max if population else x.shape[1]
    local_steps = cfg["num_epochs"]["local"] * int(
        np.ceil(shard_n / cfg["batch_size"]["train"]))
    flops_per_round = n_active * local_steps * float(
        np.mean([flop_table[float(r)] for r in rates_vec]))
    try:
        peak_flops = float(os.environ.get("BENCH_PEAK_FLOPS") or 0) or None
    except ValueError:
        print(f"bench: ignoring malformed BENCH_PEAK_FLOPS="
              f"{os.environ['BENCH_PEAK_FLOPS']!r}", file=sys.stderr)
        peak_flops = None

    def mfu_extra(rps):
        out = {"analytic_flops_per_round": flops_per_round,
               "source": "fed.core.level_flop_table",
               "peak_flops": peak_flops}
        if peak_flops:
            out["mfu"] = round(flops_per_round * rps / peak_flops, 6)
        return out

    # stage/dispatch/compute/fetch attribution for every timed round, plus
    # BENCH_FETCH_EVERY>1 to pipeline the D2H metric fetch behind the next
    # round's dispatch (parallel/staging.py; default 1 = synchronous parity)
    timer = PhaseTimer()

    def env_int(name, default):
        try:
            return max(1, int(os.environ.get(name) or default))
        except ValueError:
            print(f"bench: ignoring malformed {name}={os.environ[name]!r}",
                  file=sys.stderr)
            return default

    # clamp to >=1 so the emitted fetch_every matches what the pipeline
    # actually does (MetricsPipeline clamps internally too)
    fetch_every = env_int("BENCH_FETCH_EVERY", 1)
    # BENCH_SUPERSTEP=K: fuse K rounds into one lax.scan program
    # (train_superstep) -- each timed dispatch then covers K rounds and the
    # phase breakdown is amortized per round (the ISSUE 2 acceptance metric)
    superstep = env_int("BENCH_SUPERSTEP", 1)
    # BENCH_EVAL_INTERVAL=E (ISSUE 4 satellite): sBN+eval cadence.  0 = off.
    try:
        eval_iv = max(0, int(os.environ.get("BENCH_EVAL_INTERVAL", "0") or 0))
    except ValueError:
        print(f"bench: ignoring malformed BENCH_EVAL_INTERVAL="
              f"{os.environ['BENCH_EVAL_INTERVAL']!r}", file=sys.stderr)
        eval_iv = 0
    evaluator = fused_ev = eval_local = eval_global = eval_sbn = None
    if eval_iv:
        # staged through the driver's own assembly (entry.common) so the
        # benched eval operands are laid out exactly as the driver commits
        from heterofl_tpu.entry.common import stage_eval_operands
        from heterofl_tpu.parallel.evaluation import Evaluator

        eval_sbn, eval_local, eval_global = stage_eval_operands(
            cfg, ds["train"], ds["test"], split["test"], lm)
        evaluator = Evaluator(model, cfg, mesh, seed=0)
        fused_ev = evaluator.fused(sbn_batches=eval_sbn, local_eval=eval_local,
                                   global_eval=eval_global)
    pipe = MetricsPipeline(fetch_every)
    base_key = jax.random.key(0)

    # sampler microbench (ISSUE 11): the host draw cost of ONE [1, A] round
    # schedule under BOTH samplers at THIS run's population, through the
    # very stream the run consumes (fed.core.superstep_user_schedule).  At
    # BENCH_POPULATION=1e6 this is the acceptance measurement: perm pays
    # the O(U log U) permutation, prp the O(active) index map.
    from heterofl_tpu.fed.core import superstep_user_schedule

    def _draw_sec(kind, reps=3):
        superstep_user_schedule(base_key, 1, 1, users, n_active,
                                sampler=kind)  # warm the dispatch caches
        best = float("inf")
        for i in range(reps):
            t0 = time.time()
            superstep_user_schedule(base_key, 2 + i, 1, users, n_active,
                                    sampler=kind)
            best = min(best, time.time() - t0)
        return best

    hb(f"sampler microbench (kind {sampler_kind}, {users} users)")
    _draw = {k: _draw_sec(k) for k in ("prp", "perm")}
    sampler_extra = {
        "kind": sampler_kind,
        "users": users,
        "num_active": n_active,
        "draw_sec": {k: round(v, 6) for k, v in _draw.items()},
        "speedup_prp_vs_perm": round(_draw["perm"] / max(_draw["prp"], 1e-9),
                                     2),
        "source": "fed.core.superstep_user_schedule([1, A] draw, best of 3)",
    }
    hb(f"sampler draw: prp {_draw['prp']:.4f}s perm {_draw['perm']:.4f}s "
       f"({sampler_extra['speedup_prp_vs_perm']}x)")

    # population mode (ISSUE 6): per-engine prefetched cohorts -- dispatch
    # i+1's cohort stages while dispatch i's scanned program computes
    _pop_cohorts = {}

    def stage_pop(eng, strat, epoch0, k_disp, tmr):
        from heterofl_tpu.fed.core import superstep_rate_schedule

        with tmr.phase("sample"):
            us = superstep_user_schedule(base_key, epoch0, k_disp, users,
                                         n_active, schedule=sched_spec,
                                         sampler=sampler_kind)
        track_participation(us)
        if strat == "grouped":
            rates = superstep_rate_schedule(base_key, epoch0, k_disp, cfg, us)
            return eng.stage_cohort(store, us, rates, timer=tmr)
        return eng.stage_cohort(store, us, timer=tmr)

    def dispatch(eng, strat, params, i, tmr, rng_, eval_mode=None, k_disp=None):
        """One timed dispatch: a single round (superstep==1) or a fused
        K-round superstep -- with BENCH_EVAL_INTERVAL, either eval-fused
        (the mask rides the compiled scan) or host-loop (eval dispatched
        between windows under tmr.phase('eval'), PR 2 semantics).  Returns
        (params, PendingMetrics)."""
        k_disp = k_disp or superstep
        if store is not None:
            # streaming population: cohort staged ahead (prefetch depth 1);
            # the warmup dispatch (i=0) is inherently synchronous and exempt
            # from the sync-fallback refusal
            epoch0 = 1 + i * k_disp
            coh = _pop_cohorts.pop((id(eng), i), None)
            if coh is None:
                if i > 0:
                    pop_stats["sync"] += 1
                coh = stage_pop(eng, strat, epoch0, k_disp, tmr)
            else:
                pop_stats["prefetched"] += 1
            params, pending = eng.train_superstep(
                params, base_key, epoch0, k_disp, timer=tmr, cohort=coh)
            if pop_prefetch and i < timed_rounds:
                # the final timed dispatch has no successor; staging a
                # cohort for it would bill a full host gather + device
                # commit to the last round and never consume it
                _pop_cohorts[(id(eng), i + 1)] = stage_pop(
                    eng, strat, epoch0 + k_disp, k_disp, tmr)
            return params, pending
        if k_disp > 1:
            epoch0 = 1 + i * k_disp
            mask = None
            if eval_mode == "fused":
                mask = tuple((epoch0 + j) % eval_iv == 0 for j in range(k_disp))
                if not any(mask):
                    mask = None
            if strat == "grouped":
                with tmr.phase("sample"):
                    us = superstep_user_schedule(base_key, epoch0, k_disp,
                                                 users, n_active,
                                                 schedule=sched_spec,
                                                 sampler=sampler_kind)
                track_participation(us)
                params, pending = eng.train_superstep(
                    params, base_key, epoch0, k_disp, us, rates_vec[us], data,
                    timer=tmr, eval_mask=mask,
                    fused_eval=fused_ev if mask else None)
            else:
                us = None
                if sched_spec is not None:
                    # scenario runs take the host-drawn schedule (same
                    # stream as the in-jit draw) so participation is
                    # countable per round
                    with tmr.phase("sample"):
                        us = superstep_user_schedule(base_key, epoch0,
                                                     k_disp, users, n_active,
                                                     schedule=sched_spec,
                                                     sampler=sampler_kind)
                    track_participation(us)
                params, pending = eng.train_superstep(
                    params, base_key, epoch0, k_disp, data, user_schedule=us,
                    num_active=n_active, timer=tmr, eval_mask=mask,
                    fused_eval=fused_ev if mask else None)
        else:
            if sched_spec is not None:
                epoch = 1 + i
                with tmr.phase("sample"):
                    user_idx = np.asarray(round_users(
                        jax.random.fold_in(base_key, epoch), users, n_active,
                        avail=sched_spec.avail_row(epoch),
                        sampler=sampler_kind))
                track_participation(user_idx[None])
            elif sampler_kind == "perm":
                # the drivers' legacy numpy K=1 stream (reference parity)
                user_idx = rng_.permutation(users)[:n_active].astype(np.int32)
            else:
                with tmr.phase("sample"):
                    user_idx = np.asarray(round_users(
                        jax.random.fold_in(base_key, 1 + i), users, n_active,
                        sampler=sampler_kind))
            if strat == "grouped":
                params, pending = eng.train_round(
                    params, user_idx, rates_vec[user_idx], data, 0.1,
                    jax.random.key(i), timer=tmr, async_metrics=True)
            else:
                params, ms = eng.train_round(params, jax.random.key(i), 0.1,
                                             user_idx, data, timer=tmr)
                pending = PendingMetrics(ms)
        if eval_mode == "host":
            # the PR 2 host-loop eval: one host eval round-trip per window
            # CONTAINING an eval epoch (for eval_iv <= K the clamp makes
            # that the window's last round; for eval_iv > K windows the
            # cadence doesn't divide, the eval lands at the window end --
            # same round-trip count per eval_iv rounds, which is what the
            # A/B measures)
            epoch0_w = 1 + i * k_disp
            if any((epoch0_w + j) % eval_iv == 0 for j in range(k_disp)):
                epoch = epoch0_w + k_disp - 1
                # sync the train window FIRST so the `eval` phase row
                # measures the eval round-trip itself, not the async train
                # compute the eval's first D2H would otherwise absorb
                with tmr.phase("compute"):
                    jax.block_until_ready(params)
                with tmr.phase("eval"):
                    bn = evaluator.sbn_stats(params, *eval_sbn)
                    evaluator.eval_users(params, bn, *eval_local, epoch=epoch)
                    evaluator.eval_global(params, bn, *eval_global, epoch=epoch)
        return params, pending

    def last_loss(fetched):
        """Superstep fetches return a list of per-round dicts (or the
        train/eval dict when eval-fused); take the latest round's sums."""
        if isinstance(fetched, dict) and "train" in fetched:
            fetched = fetched["train"]
        return fetched[-1] if isinstance(fetched, list) else fetched

    def steady_stats(rsec, compile_flags):
        """Like-for-like 'value' statistic for BOTH strategies (ADVICE r5
        item 1): the average per-round seconds EXCLUDING rounds that
        compiled a fresh program (grouped slot-bucket compiles, superstep
        shape changes), falling back to all rounds when every timed round
        compiled.  Detected via engine.program_cache_size() growth."""
        steady = [t for t, c in zip(rsec, compile_flags) if not c] or list(rsec)
        return sum(steady) / len(steady)

    def summarize(rsec, compile_flags, compile_s, tmr, phases0, rounds_done,
                  k_disp=None):
        steady_avg = steady_stats(rsec, compile_flags)
        n_compile = sum(bool(c) for c in compile_flags)
        return {
            "value": round(1.0 / steady_avg, 4),
            "round_sec_avg": round(sum(rsec) / len(rsec), 3),
            "round_sec_best": round(min(rsec), 3),
            "round_sec_steady_avg": round(steady_avg, 3),
            # rounds that compiled a fresh shape, ALWAYS reported -- when
            # every round compiled the steady avg falls back to all rounds
            # and the next flag says so instead of hiding the recompiles
            "compile_rounds": n_compile,
            "steady_excludes_compile_rounds": n_compile < len(rsec),
            "compile_sec": round(compile_s, 1),
            "rounds_timed": rounds_done,
            # per-ROUND amortized host phases: one stage+dispatch+fetch
            # cycle serves all rounds of a dispatch window (an eval-host
            # record additionally carries the per-window `eval` phase)
            "phases": {k: round(v, 4)
                       for k, v in sorted(tmr.amortized(
                           phases0, rounds_done * (k_disp or superstep)).items())},
        }

    def measure(strat, eng, params0, tmr, hb_prefix="", on_round=None,
                eval_mode=None):
        """Warmup + timed loop: THE single measurement procedure, shared by
        the primary strategy (``on_round`` handles its pipelined fetch and
        refined per-round emits), the alternate-strategy record, and the
        eval-fused vs eval-host rows -- one copy, so every like-for-like
        claim compares identical procedures.  ``eval_mode`` (with
        BENCH_EVAL_INTERVAL): 'fused' rides the eval mask inside the
        superstep; 'host' clamps dispatch windows to min(K, E) and pays the
        host eval round-trip per window (the PR 2 semantics).  Returns
        (summary, ctx) where ctx carries rsec/flags/compile_s/phases0/ms."""
        k_disp = superstep
        if eval_mode == "fused" and superstep == 1:
            eval_mode = "host"  # nothing to fuse into at K=1
        if eval_mode == "host" and eval_iv:
            k_disp = min(superstep, eval_iv)
        rng_ = np.random.default_rng(0)
        part_start = len(part_stats["filled"])  # this measure()'s own draws
        t0 = time.time()
        p, pending = dispatch(eng, strat, params0, 0, tmr, rng_,
                              eval_mode=eval_mode, k_disp=k_disp)
        jax.block_until_ready(p)
        warm_ms = last_loss(pending.fetch())
        compile_s = time.time() - t0
        # phases are reported RELATIVE to this snapshot so the breakdown
        # shows steady-state cost, not the warmup compile in 'dispatch'
        phases0 = tmr.snapshot()
        hb(f"{hb_prefix}compile done ({compile_s:.1f}s incl. warmup dispatch)")
        ctx = {"compile_s": compile_s, "phases0": phases0, "k_disp": k_disp,
               "rsec": [], "flags": [], "ms": warm_ms, "ms_round": 0}
        for r in range(1, timed_rounds + 1):
            size0 = eng.program_cache_size()
            t0 = time.time()
            p, pending = dispatch(eng, strat, p, r, tmr, rng_,
                                  eval_mode=eval_mode, k_disp=k_disp)
            with tmr.phase("compute"):
                jax.block_until_ready(p)
            ctx["rsec"].append((time.time() - t0) / k_disp)
            ctx["flags"].append(eng.program_cache_size() > size0)
            if on_round is not None:
                on_round(r, pending, ctx)
            else:
                with tmr.phase("fetch"):
                    ctx["ms"] = last_loss(pending.fetch())
            hb(f"{hb_prefix}round {r}/{timed_rounds} done "
               f"({ctx['rsec'][-1]:.2f}s/round)")
        summary = summarize(ctx["rsec"], ctx["flags"], compile_s, tmr, phases0,
                            timed_rounds, k_disp=k_disp)
        # scenario participation of THIS measure's draws only (warmup +
        # timed dispatches of this strategy/mode) -- without the slice the
        # second-strategy and eval-host records would pollute the primary
        # record's statistics
        ctx["participation"] = list(part_stats["filled"][part_start:])
        if eval_mode is not None:
            summary["eval_mode"] = eval_mode
            summary["rounds_per_dispatch"] = k_disp
        return summary, ctx

    step_ab = {}  # filled by the BENCH_STEP_AB pass; emitted when non-empty
    obs_ab = {}   # filled by the BENCH_TELEMETRY pass; emitted when non-empty
    arms_ab = {}  # filled by the BENCH_ARMS pass (ISSUE 14)
    chaos_ab = {}  # filled by the BENCH_CHAOS pass (ISSUE 15)
    pod_ab = {}   # filled by the BENCH_POD pass (ISSUE 17)

    def emit(ctx, rounds_done, strategies=None):
        # a degraded (non-flagship-volume / wrong-platform) run must not
        # pretend to be comparable to the 10 rps north star (VERDICT r4
        # item 5): vs_baseline is null unless this is the real program.
        # With BENCH_FETCH_EVERY>1 the loss lags the timed round by up to K
        # rounds; final_loss_round marks which round it belongs to so a
        # mid-run kill's salvaged line is not silently stale.
        ms = ctx["ms"]
        loss = float(np.asarray(ms["loss_sum"]).sum() / np.asarray(ms["n"]).sum())
        pop_extra = {}
        if population:
            import resource

            pop_extra["population"] = {
                "users": population, "active_clients": n_active,
                "shard_size": store.shard_max,
                "store_metadata_bytes": store.metadata_nbytes,
                "rss_max_kb": resource.getrusage(resource.RUSAGE_SELF).ru_maxrss,
                "prefetched_stages": pop_stats["prefetched"],
                "sync_stages": pop_stats["sync"]}
        dt = steady_stats(ctx["rsec"], ctx["flags"])
        rps = 1.0 / dt
        scenario_extra = {}
        if sched_cfg:
            filled = ctx.get("participation") or part_stats["filled"]
            scenario_extra["scenario"] = {
                "schedule": scenario_tokens,
                "config": sched_cfg,
                "participation": {
                    "slots_per_round": n_active,
                    "rounds_sampled": len(filled),
                    "mean_active": (round(float(np.mean(filled)), 3)
                                    if filled else None),
                    "min_active": int(min(filled)) if filled else None,
                    "max_active": int(max(filled)) if filled else None,
                },
                "rounds_per_sec": round(rps, 4),
            }
        summary = summarize(ctx["rsec"], ctx["flags"], ctx["compile_s"], timer,
                            ctx["phases0"], rounds_done,
                            k_disp=ctx.get("k_disp"))
        del summary["value"]  # the top-level "value" IS this number
        cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR")
        print(json.dumps({
            "metric": "federated_rounds_per_sec_cifar10_resnet18_a1-e1_100c",
            "value": round(rps, 4),
            "unit": "rounds/sec",
            "vs_baseline": None if degraded else round(rps / 10.0, 4),
            "extra": {"round_sec": round(dt, 3),
                      **summary,
                      "devices": len(devs), "platform": platform,
                      "active_clients": n_active, "users": users,
                      "n_train": n_train, "final_loss": round(loss, 4),
                      "strategy": strategy,
                      "sampler": sampler_extra,
                      "mfu": mfu_extra(rps),
                      "wire": wire_extra,
                      "compile_cache": {
                          "enabled": bool(cache_dir),
                          "requests": cache_counters["requests"],
                          "hits": cache_counters["hits"],
                          "misses": cache_counters["requests"] - cache_counters["hits"]},
                      **({"staticcheck": staticcheck} if staticcheck else {}),
                      **({"superstep_rounds": superstep} if superstep != 1 else {}),
                      **({"eval_interval": eval_iv} if eval_iv else {}),
                      **({"fetch_every": fetch_every,
                          "final_loss_round": ctx["ms_round"]} if fetch_every != 1 else {}),
                      **pop_extra,
                      **scenario_extra,
                      **({"strategies": strategies} if strategies else {}),
                      **({"step_ab": step_ab} if step_ab else {}),
                      **({"obs": obs_ab} if obs_ab else {}),
                      **({"arms": arms_ab} if arms_ab else {}),
                      **({"chaos": chaos_ab} if chaos_ab else {}),
                      **({"pod": pod_ab} if pod_ab else {}),
                      **({"degraded": degraded} if degraded else {})},
        }), flush=True)

    # primary strategy: a refined JSON line lands after EVERY timed round so
    # a mid-run kill still leaves the supervisor a real measurement to
    # forward.  'value' is the LIKE-FOR-LIKE statistic for both strategies
    # (ADVICE r5 item 1): per-round steady average excluding fresh-compile
    # rounds (extra.round_sec_avg/_best/_steady_avg carry the full picture).
    hb("compiling (warmup dispatch)")

    def pop_sync_refused():
        """The population-axis refusal (ISSUE 6) covers the per-round
        salvage emits too: once a timed dispatch staged synchronously,
        every line a supervisor might forward measures serialised staging,
        not just the final summary."""
        return (population and pop_stats["sync"]
                and os.environ.get("BENCH_ALLOW_SYNC_STAGE") != "1")

    def on_round(r, pending, ctx):
        with timer.phase("fetch"):
            # tag with the last ROUND the dispatch covered, not the dispatch
            # index: final_loss_round documents which round the (possibly
            # deferred) loss belongs to, and one dispatch is K rounds
            due = pipe.push(r * ctx.get("k_disp", superstep), pending)
        if due:
            ctx["ms_round"], ctx["ms"] = due[-1][0], last_loss(due[-1][1])
        if not pop_sync_refused():
            emit(ctx, r)

    primary_summary, ctx = measure(strategy, engine, params, timer,
                                   on_round=on_round,
                                   eval_mode="fused" if eval_iv else None)
    due = pipe.flush()
    if due and not pop_sync_refused():
        # deferred-fetch tail: re-emit with the final round's loss
        ctx["ms_round"], ctx["ms"] = due[-1][0], last_loss(due[-1][1])
        emit(ctx, timed_rounds)

    # population-mode staging contract (ISSUE 6): a record whose timed
    # dispatches staged SYNCHRONOUSLY measures serialised staging, not the
    # double-buffered pipeline -- refuse to record it as the population
    # axis unless the operator explicitly overrides
    if pop_sync_refused():
        print(json.dumps({
            "metric": "federated_rounds_per_sec_cifar10_resnet18_a1-e1_100c",
            "value": 0.0, "unit": "rounds/sec", "vs_baseline": None,
            "extra": {"error": f"{pop_stats['sync']} timed dispatch(es) fell "
                               f"back to SYNCHRONOUS cohort staging; the "
                               f"population axis measures the prefetched "
                               f"pipeline (set BENCH_ALLOW_SYNC_STAGE=1 to "
                               f"record anyway)",
                      "population": {"users": population,
                                     "prefetched_stages": pop_stats["prefetched"],
                                     "sync_stages": pop_stats["sync"]}},
        }), flush=True)
        return

    def try_measure(strat, hb_prefix, eval_mode=None):
        """An extra record must never kill the primary one."""
        hb(f"{hb_prefix}building engine")
        try:
            s, _ = measure(strat, make_engine(strat),
                           model.init(jax.random.key(0)), PhaseTimer(),
                           hb_prefix=hb_prefix, eval_mode=eval_mode)
            return s
        except Exception as e:
            print(f"bench: extra record {hb_prefix.strip()} failed: {e!r}",
                  file=sys.stderr)
            return {"error": repr(e)}

    # both-strategy record (ISSUE 2 satellite): measure the OTHER engine on
    # the same config so the grouped engine's small-width FLOP reduction
    # lands in the BENCH_*.json trajectory, not only in scripts/
    # grouped_flops.py.  Skipped on the budget-constrained fallback paths
    # (the insurance line must print); BENCH_BOTH=0 disables, =1 forces.
    # With BENCH_EVAL_INTERVAL the strategies dict carries eval-fused vs
    # eval-host rows per engine (ISSUE 4 satellite) -- the A/B that shows
    # the last per-eval-window host round-trip disappearing.
    both_default = "0" if (fallback or realwidth or population) else "1"
    both = os.environ.get("BENCH_BOTH", both_default) == "1"
    alt = "grouped" if strategy != "grouped" else "masked"
    strategies = {}
    if eval_iv:
        # key each row by the mode that actually RAN (measure() degrades
        # fused->host at superstep==1, where there is no scan to fuse into)
        pmode = primary_summary.get("eval_mode", "fused")
        strategies[f"{strategy}+eval-{pmode}"] = primary_summary
        if pmode == "fused":
            strategies[f"{strategy}+eval-host"] = try_measure(
                strategy, f"[{strategy}/eval-host] ", eval_mode="host")
        if both:
            alt_fused = try_measure(alt, f"[{alt}/eval-fused] ",
                                    eval_mode="fused")
            amode = alt_fused.get("eval_mode", "fused")
            strategies[f"{alt}+eval-{amode}"] = alt_fused
            if amode == "fused":
                strategies[f"{alt}+eval-host"] = try_measure(
                    alt, f"[{alt}/eval-host] ", eval_mode="host")
    elif both:
        strategies[strategy] = primary_summary
        strategies[alt] = try_measure(alt, f"[{alt}] ")
    if strategies:
        emit(ctx, timed_rounds, strategies=strategies)

    # BENCH_STEP_AB=1 (ISSUE 5): fused-epilogue vs reference-op-chain step
    # A/B -- both arms measured with the SAME shared procedure (plain train
    # windows; eval rides the primary record, not this one), plus the
    # optimized-HLO scan-body kernel counts in both modes.  The counted
    # program is the engine's K=1 hot program at the bench shapes (masked:
    # the one-round train program; grouped: the full-width level-a span
    # program) -- its LOCAL-STEP scan body is the same step body the
    # K-round superstep scans, and the same body the staticcheck budget
    # gates; the record labels which program was lowered.  Failures never
    # kill the primary record.
    if os.environ.get("BENCH_STEP_AB") == "1" and population:
        print("bench: BENCH_STEP_AB ignored in population mode (the step "
              "A/B lowers the eager-data programs)", file=sys.stderr)
    elif os.environ.get("BENCH_STEP_AB") == "1":
        try:
            from heterofl_tpu.staticcheck.jaxpr_walk import scan_body_kernel_count

            psds = jax.tree_util.tree_map(
                lambda v: jax.ShapeDtypeStruct(v.shape, v.dtype), dict(params))

            def body_counts(fused):
                eng = make_engine(strategy, {"fused_update": fused})
                lr0 = np.float32(0.1)
                if strategy == "grouped":
                    from heterofl_tpu.parallel.grouped import _bucket_pow2

                    slots = _bucket_pow2(1) * len(devs)
                    sds = jax.ShapeDtypeStruct((slots,), np.int32)
                    low = eng._level_prog(1.0, slots).lower(
                        psds, base_key, lr0, sds, *data)
                    prog_name = "grouped/span/level-1/k1"
                else:
                    fix = (eng.fix_rates,) if eng.fix_rates is not None else ()
                    slots = users + ((-users) % len(devs))
                    sds = jax.ShapeDtypeStruct((slots,), np.int32)
                    low = eng._build_train().lower(
                        psds, base_key, lr0, sds, sds, *(data + fix))
                    prog_name = "masked/k1"
                return {"program": prog_name,
                        **scan_body_kernel_count(low.compile().as_text())}

            hb("[step-ab] measuring fused vs reference epilogue")
            ab_fused, _ = measure(strategy, make_engine(strategy),
                                  model.init(jax.random.key(0)), PhaseTimer(),
                                  hb_prefix="[step-ab/fused] ")
            ab_ref, _ = measure(strategy,
                                make_engine(strategy, {"fused_update": False}),
                                model.init(jax.random.key(0)), PhaseTimer(),
                                hb_prefix="[step-ab/reference] ")
            kf, kr = body_counts(True), body_counts(False)
            step_ab.update({
                "fused": ab_fused,
                "reference": ab_ref,
                "speedup": round(ab_ref["round_sec_steady_avg"]
                                 / ab_fused["round_sec_steady_avg"], 4),
                "scan_body_kernels": {
                    "fused": kf, "reference": kr,
                    "fusion_drop_pct": round(
                        100.0 * (1.0 - kf["fusions"] / max(1, kr["fusions"])), 1)},
            })
        except Exception as e:
            step_ab.update({"error": repr(e)})
            print(f"bench: step A/B failed: {e!r}", file=sys.stderr)
        emit(ctx, timed_rounds, strategies=strategies or None)

    # BENCH_TELEMETRY=1 (ISSUE 10): the runtime-telemetry on-vs-off A/B --
    # both arms measured with the SAME shared procedure; the ON arm carries
    # the in-program health probes through every fetch, feeds them to a
    # warn-mode watchdog, and records the run's Chrome trace.  A fired
    # watchdog REFUSES the record: a rounds/sec number measured through a
    # diverging run is not a telemetry overhead.
    if os.environ.get("BENCH_TELEMETRY") == "1" and population:
        print("bench: BENCH_TELEMETRY ignored in population mode (the A/B "
              "measures the eager flagship program)", file=sys.stderr)
    elif os.environ.get("BENCH_TELEMETRY") == "1" \
            and strategy == "grouped" and superstep <= 1:
        print("bench: BENCH_TELEMETRY with the grouped strategy needs "
              "BENCH_SUPERSTEP>1 (the probes live in the fused superstep); "
              "skipping the A/B", file=sys.stderr)
    elif os.environ.get("BENCH_TELEMETRY") == "1":
        try:
            from heterofl_tpu.obs import resolve_telemetry_cfg, split_probes
            from heterofl_tpu.obs.trace import TraceRecorder
            from heterofl_tpu.obs.watchdog import Watchdog

            trace_dir = os.environ.get("BENCH_TRACE_DIR") \
                or os.path.join(os.getcwd(), "obs_trace")
            rec = TraceRecorder(trace_dir)
            tel_timer = PhaseTimer()
            tel_timer.trace = rec  # phases file onto the run timeline
            wd = Watchdog(resolve_telemetry_cfg({"telemetry": "on"}).watchdog)
            tel_state = {"probes": None, "round": 0}

            def tel_on_round(r, pending, ctx2):
                with tel_timer.phase("fetch"):
                    out = pending.fetch()
                if isinstance(out, dict) and "train" in out:
                    rounds_l, probes = out["train"], out.get("obs")
                else:  # the K=1 train_round path: raw obs_ leaves in ms
                    clean, probes = split_probes(out, len(devs))
                    rounds_l = [clean]
                ctx2["ms"] = rounds_l[-1]
                for j, pr in enumerate(probes or []):
                    msr = rounds_l[j] if j < len(rounds_l) else rounds_l[-1]
                    n_j = float(np.asarray(msr["n"]).sum())
                    loss_j = (float(np.asarray(msr["loss_sum"]).sum()) / n_j
                              if n_j > 0 else None)
                    tel_state["round"] += 1
                    tel_state["probes"] = pr
                    rec.instant("probes", cat="obs",
                                args={"round": tel_state["round"],
                                      "loss": loss_j, **pr})
                    wd.check(tel_state["round"], probes=pr, loss=loss_j)

            hb("[obs] telemetry on-vs-off A/B")
            try:
                on_sum, _on_ctx = measure(
                    strategy, make_engine(strategy, {"telemetry": "on"}),
                    model.init(jax.random.key(0)), tel_timer,
                    hb_prefix="[obs/on] ", on_round=tel_on_round)
            finally:
                # a failed ON arm must still leave its trace on disk --
                # that trace is the artifact that explains the failure
                trace_path = rec.close()
            off_sum, _ = measure(strategy, make_engine(strategy),
                                 model.init(jax.random.key(0)), PhaseTimer(),
                                 hb_prefix="[obs/off] ")
            if wd.fired:
                obs_ab.update({
                    "error": "watchdog fired during the telemetry measure; "
                             "refusing to record the on-vs-off A/B",
                    "watchdog_fired": wd.fired[:8],
                    "trace": trace_path})
            else:
                obs_ab.update({
                    "on": on_sum, "off": off_sum,
                    "overhead_pct": round(
                        100.0 * (on_sum["round_sec_steady_avg"]
                                 / off_sum["round_sec_steady_avg"] - 1.0), 2),
                    "probes_last": tel_state["probes"],
                    "watchdog_fired": [],
                    "trace": trace_path})
        except Exception as e:
            obs_ab.update({"error": repr(e)})
            print(f"bench: telemetry A/B failed: {e!r}", file=sys.stderr)
        emit(ctx, timed_rounds, strategies=strategies or None)

    # BENCH_LEDGER=1 (ISSUE 12): the population-observatory A/B -- the ON
    # arm runs telemetry='hist' (cohort histograms in the fetch) and folds
    # a host-side ClientLedger O(active) per fetch from the re-drawn
    # schedule rows (the host twin of the in-jit draw: bit-identical by
    # the sampler-stream contract); the OFF arm is the plain engine.  A
    # fired warn-mode watchdog refuses the record, like BENCH_TELEMETRY.
    # Works in population mode -- 1e6 users IS the bytes/user acceptance
    # measurement -- but needs BENCH_SUPERSTEP>1 (the schedule re-draw
    # addresses whole superstep dispatches).
    if os.environ.get("BENCH_LEDGER") == "1" and superstep <= 1:
        print("bench: BENCH_LEDGER needs BENCH_SUPERSTEP>1 (the per-fetch "
              "ledger fold re-draws superstep schedule rows); skipping",
              file=sys.stderr)
    elif os.environ.get("BENCH_LEDGER") == "1":
        try:
            from heterofl_tpu.obs import resolve_telemetry_cfg
            from heterofl_tpu.obs.ledger import ClientLedger
            from heterofl_tpu.obs.watchdog import Watchdog

            trace_dir = os.environ.get("BENCH_TRACE_DIR") \
                or os.path.join(os.getcwd(), "obs_trace")
            os.makedirs(trace_dir, exist_ok=True)
            ledger = ClientLedger(
                users, sorted({float(r) for r in cfg["model_rate"]},
                              reverse=True))
            wd = Watchdog(resolve_telemetry_cfg({"telemetry": "hist"})
                          .watchdog)
            led_state = {"round": 0, "hist": None}
            led_jsonl_path = os.path.join(trace_dir, "ledger.jsonl")
            led_jsonl = open(led_jsonl_path, "w")

            def led_on_round(r, pending, ctx2):
                out = pending.fetch()
                rounds_l, probes = out["train"], out.get("obs") or []
                ctx2["ms"] = rounds_l[-1]
                epoch0 = 1 + r * superstep
                us = superstep_user_schedule(base_key, epoch0, superstep,
                                             users, n_active,
                                             schedule=sched_spec,
                                             sampler=sampler_kind)
                a = us.shape[1]
                for j, msr in enumerate(rounds_l):
                    s = ledger.update(epoch0 + j, us[j],
                                      np.asarray(msr["rate"])[:a],
                                      np.asarray(msr["loss_sum"])[:a],
                                      np.asarray(msr["n"])[:a])
                    led_jsonl.write(json.dumps({"tag": "ledger", **s}) + "\n")
                led_jsonl.flush()
                for j, pr in enumerate(probes):
                    msr = rounds_l[j]
                    n_j = float(np.asarray(msr["n"]).sum())
                    loss_j = (float(np.asarray(msr["loss_sum"]).sum()) / n_j
                              if n_j > 0 else None)
                    led_state["round"] += 1
                    led_state["hist"] = {n: v for n, v in pr.items()
                                         if n.startswith("hist_")}
                    wd.check(led_state["round"], probes=pr, loss=loss_j)

            hb("[ledger] observatory on-vs-off A/B")
            try:
                led_on, _ = measure(
                    strategy, make_engine(strategy, {"telemetry": "hist"}),
                    model.init(jax.random.key(0)), PhaseTimer(),
                    hb_prefix="[ledger/on] ", on_round=led_on_round)
            finally:
                led_jsonl.close()
            led_off, _ = measure(strategy, make_engine(strategy),
                                 model.init(jax.random.key(0)), PhaseTimer(),
                                 hb_prefix="[ledger/off] ")
            npz_path = ledger.save(os.path.join(trace_dir, "ledger.npz"))
            if wd.fired:
                obs_ab["ledger"] = {
                    "error": "watchdog fired during the ledger measure; "
                             "refusing to record the on-vs-off A/B",
                    "watchdog_fired": wd.fired[:8],
                    "ledger_npz": npz_path}
            else:
                obs_ab["ledger"] = {
                    "on": led_on, "off": led_off,
                    "overhead_pct": round(
                        100.0 * (led_on["round_sec_steady_avg"]
                                 / led_off["round_sec_steady_avg"] - 1.0), 2),
                    "users": users,
                    "ledger_bytes": ledger.nbytes,
                    "bytes_per_user": round(ledger.nbytes / users, 3),
                    "coverage": round(ledger.seen / users, 6),
                    "participations": int(ledger.count.sum()),
                    "hist_last": led_state["hist"],
                    "watchdog_fired": [],
                    "ledger_npz": npz_path,
                    "ledger_jsonl": led_jsonl_path}
        except Exception as e:
            obs_ab["ledger"] = {"error": repr(e)}
            print(f"bench: ledger A/B failed: {e!r}", file=sys.stderr)
        emit(ctx, timed_rounds, strategies=strategies or None)

    # BENCH_ARMS=E (ISSUE 14): the experiment-arms multiplexer A/B -- ONE
    # E-arm fused superstep program vs E SERIAL solo runs, both through the
    # shared measure() procedure on equal per-arm device resources.  The
    # default placement lays the arms over a dedicated mesh axis
    # (make_mesh(n_arms=E): each arm's federation on its own device rows,
    # executing concurrently -- the mesh-filling story); BENCH_ARMS_
    # PLACEMENT=vmap forces the batched-per-device layout instead (the two
    # are bitwise-identical per arm, tests/test_arms.py).  The serial
    # baseline runs ONE arm on the per-arm submesh -- E sequential such
    # runs is the reference's process-grid shape with the compile already
    # amortized, so the steady-state speedup under-counts the reference's
    # per-process compile (reported separately via compile_sec).  Records
    # aggregate ARM-rounds/sec both ways, program/compile counts and RSS
    # into extra.arms.  Skipped in population mode and under scenario/
    # codec knobs (the A/B measures the plain dense program).
    bench_arms = env_int("BENCH_ARMS", 0)
    if bench_arms:
        if population or sched_cfg or wire_codec != "dense":
            print("bench: BENCH_ARMS ignored with population/scenario/codec "
                  "knobs (the A/B measures the plain dense program)",
                  file=sys.stderr)
        elif superstep <= 1:
            print("bench: BENCH_ARMS needs BENCH_SUPERSTEP>1 (arms ride "
                  "the fused superstep); skipping the A/B", file=sys.stderr)
        else:
            import resource

            try:
                E = bench_arms
                n_dev_total = len(devs)
                placement = os.environ.get("BENCH_ARMS_PLACEMENT") or \
                    ("mesh" if n_dev_total % E == 0
                     and n_dev_total >= E else "vmap")
                if placement not in ("mesh", "vmap"):
                    print(f"bench: unknown BENCH_ARMS_PLACEMENT="
                          f"{placement!r}; using mesh", file=sys.stderr)
                    placement = "mesh"
                if placement == "mesh":
                    sub_clients = n_dev_total // E
                    arms_mesh = make_mesh(sub_clients, 1, n_arms=E)
                    solo_mesh = make_mesh(sub_clients, 1)
                else:
                    sub_clients = mesh.shape["clients"]
                    arms_mesh = mesh
                    solo_mesh = mesh
                hb(f"arms A/B: E={E} placement={placement} "
                   f"({E}x{sub_clients} of {n_dev_total} devices)")
                solo_eng = RoundEngine(model, dict(cfg), solo_mesh)
                serial_sum, _ = measure("masked", solo_eng,
                                        model.init(jax.random.key(0)),
                                        PhaseTimer(),
                                        hb_prefix="arms-serial ")
                rss_serial = resource.getrusage(
                    resource.RUSAGE_SELF).ru_maxrss
                arms_eng = RoundEngine(model, dict(cfg, arms=E), arms_mesh)
                p0 = model.init(jax.random.key(0))
                p_stack = jax.tree_util.tree_map(
                    lambda v: jnp.stack([v] * E), p0)

                # the fetch must charge THIS measure()'s timer, not the
                # already-summarized primary pass's (the serial baseline
                # pays fetch through measure's own tmr -- like-for-like)
                arms_tmr = PhaseTimer()

                def arms_fetch(r, pending, ctx):
                    with arms_tmr.phase("fetch"):
                        out = pending.fetch()
                    a0 = out["arms"][0]
                    ctx["ms"] = a0["train"][-1] if isinstance(a0, dict) \
                        else a0[-1]

                arms_sum, _ = measure("masked", arms_eng, p_stack,
                                      arms_tmr,
                                      hb_prefix=f"arms-E{E} ",
                                      on_round=arms_fetch)
                rss_arms = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
                agg_arms = E / arms_sum["round_sec_steady_avg"]
                agg_serial = 1.0 / serial_sum["round_sec_steady_avg"]
                arms_ab.update({
                    "E": E, "placement": placement,
                    "mesh": {"arms": E if placement == "mesh" else 0,
                             "clients_per_arm": sub_clients,
                             "total_devices": n_dev_total},
                    "one_program": arms_sum,
                    "serial_per_arm": serial_sum,
                    "aggregate_arm_rounds_per_sec": round(agg_arms, 4),
                    "serial_aggregate_arm_rounds_per_sec":
                        round(agg_serial, 4),
                    "speedup": round(agg_arms / agg_serial, 4),
                    # one compiled program + one warmup dispatch serve all
                    # E arms; the reference's process grid compiles E times
                    "compile_count": {"one_program": 1, "serial_runs": E},
                    "compile_sec": {
                        "one_program": arms_sum["compile_sec"],
                        "serial_per_run": serial_sum["compile_sec"]},
                    # ru_maxrss is the process PEAK (monotonic): the delta
                    # after the arms pass bounds its extra footprint
                    "rss_max_kb": {"after_serial": rss_serial,
                                   "after_arms": rss_arms},
                })
            except Exception as e:
                arms_ab.update({"error": repr(e)})
                print(f"bench: arms A/B failed: {e!r}", file=sys.stderr)
            emit(ctx, timed_rounds, strategies=strategies or None)

    # BENCH_CHAOS=1 (ISSUE 15): the fault-tolerance drill measurements --
    # a watchdog-rollback poison drill (seeded NaN, auto-recovery MTTR)
    # and a quarantine poison drill, on the drill's small synthetic
    # federation (its own programs; the flagship measure above is
    # untouched).  An escalation to abort REFUSES the record: a recovery
    # that needed human intervention has no MTTR.
    if os.environ.get("BENCH_CHAOS") == "1":
        try:
            import tempfile

            from heterofl_tpu.chaos.drill import run_poison_drill
            from heterofl_tpu.obs.watchdog import WatchdogError

            hb("[chaos] rollback + quarantine poison drills")
            chaos_root = tempfile.mkdtemp(prefix="bench_chaos_")
            try:
                roll = run_poison_drill(
                    "rollback", {}, os.path.join(chaos_root, "rollback"))
            except WatchdogError as e:
                chaos_ab.update({
                    "error": "rollback recovery escalated to abort; "
                             "refusing to record an MTTR",
                    "escalation": repr(e)})
            else:
                quar = run_poison_drill(
                    "quarantine", {}, os.path.join(chaos_root, "quarantine"))
                chaos_ab.update({
                    "rollback": {
                        "ok": roll["ok"], "poison": roll["poison"],
                        "trips": roll["trips"],
                        "recoveries": roll["recoveries"],
                        "mttr_sec": roll["mttr_sec"],
                        "wall_sec": roll["wall_sec"]},
                    "quarantine": {
                        "ok": quar["ok"], "poison": quar["poison"],
                        "quarantined_total": quar["quarantined_total"],
                        "wall_sec": quar["wall_sec"]},
                })
        except Exception as e:
            chaos_ab.update({"error": repr(e)})
            print(f"bench: chaos drills failed: {e!r}", file=sys.stderr)
        emit(ctx, timed_rounds, strategies=strategies or None)

    # BENCH_POD=1 (ISSUE 17): the 2-process pod probe -- a REAL
    # jax.distributed CPU mesh (gloo collectives) runs the fused
    # grouped-slices superstep with levels on disjoint processes, recorded
    # into extra.pod: per-process rounds/sec + checkpoint-write times, the
    # DCN classification from the real process grid (exactly one dense
    # reduction per training round), and the bitwise gate vs the
    # 1-process gloo reference.  A failed multi-host DCN budget audit
    # REFUSES the numbers: pod rounds/sec against an unaudited wire
    # contract would launder broken placement into the trajectory.
    if os.environ.get("BENCH_POD") == "1":
        if staticcheck is not None \
                and staticcheck.get("dcn_audit_ok") is False:
            pod_ab.update({
                "error": "STATICCHECK.json reports a failed multi-host DCN "
                         "budget audit; refusing to record pod numbers. "
                         "Rerun `python -m heterofl_tpu.staticcheck "
                         "--aot-v4128`."})
        else:
            try:
                import tempfile

                from heterofl_tpu.parallel.pod import (bitwise_match,
                                                       run_pod_probe)

                hb("[pod] 2-process distributed probe + 1-process reference")
                pod_root = tempfile.mkdtemp(prefix="bench_pod_")
                ref_dir = os.path.join(pod_root, "ref")
                pod_dir = os.path.join(pod_root, "pod")
                ref = run_pod_probe(ref_dir, n_processes=1,
                                    local_devices=8, k=4, align=2)
                pod = run_pod_probe(pod_dir, n_processes=2,
                                    local_devices=4, k=4)
                match = bitwise_match(pod_dir, ref_dir)
                pod_ab.update({
                    "processes": pod[0]["processes"],
                    "devices": pod[0]["devices"],
                    "k": pod[0]["k"],
                    "rounds_per_sec": round(pod[0]["rounds_per_sec"], 4),
                    "ref_rounds_per_sec": round(ref[0]["rounds_per_sec"], 4),
                    "ckpt_write_s": [round(r["ckpt_write_s"], 4)
                                     for r in pod],
                    "ckpt_shard_write_s": [round(r["ckpt_shard_write_s"], 4)
                                           for r in pod],
                    "dcn_axes": pod[0]["dcn_axes"],
                    "wire": pod[0]["wire"],
                    "reshards": pod[0]["reshards"],
                    "dcn_one_reduction": pod[0]["dcn_one_reduction"],
                    "bitwise_vs_single_process": match["match"],
                })
                if not match["match"]:
                    pod_ab.update({
                        "error": "2-process run is NOT bitwise-identical "
                                 "to the 1-process reference",
                        "mismatches": match["mismatches"][:20]})
            except Exception as e:
                pod_ab.update({"error": repr(e)})
                print(f"bench: pod probe failed: {e!r}", file=sys.stderr)
        emit(ctx, timed_rounds, strategies=strategies or None)


if __name__ == "__main__":
    if os.environ.get("BENCH_CPU") == "1" or os.environ.get("BENCH_SUPERVISED") == "1":
        main()
    else:
        sys.exit(_supervise())
