#!/usr/bin/env python
"""Headline benchmark: federated rounds/sec on the BASELINE.json config --
100-client CIFAR10 ResNet-18, 5-level heterogeneity a1-b1-c1-d1-e1, 10 active
clients x 5 local epochs x 50 steps per round, full HeteroFL semantics
(masked widths, Scaler, sBN-free local BN, label masks, counted-average
aggregation), all inside one jitted round program.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} where
vs_baseline is rounds/sec relative to the 10 rounds/sec north star
(BASELINE.json; the reference itself publishes no wall-clock numbers).

Env knobs: BENCH_ROUNDS (timed rounds, default 5), BENCH_USERS (default 100),
BENCH_SYNTH_N (train images, default 50000), BENCH_CPU=1 to force the
virtual-CPU path (debug), BENCH_TPU_TIMEOUT (seconds the supervised TPU
attempt may take before the CPU fallback, default 1500).
"""

import json
import os
import subprocess
import sys
import time


def _force_cpu():
    for _v in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE",
               "AXON_LOOPBACK_RELAY", "AXON_POOL_SVC_OVERRIDE"):
        os.environ.pop(_v, None)
    os.environ["JAX_PLATFORMS"] = "cpu"


def _supervise() -> int:
    """Run the real bench in a child with a hard timeout.

    The TPU tunnel here is single-client and can hang indefinitely (stale
    grants); probing and then re-initialising would claim the chip twice, so
    instead the ONE child owns the whole attempt, and on timeout we kill it
    and rerun on CPU.  A bench that never prints is worse than a CPU bench.
    """
    env = dict(os.environ)
    env["BENCH_SUPERVISED"] = "1"
    budget = int(os.environ.get("BENCH_TPU_TIMEOUT", "1500"))

    def emit_if_json(text) -> bool:
        """Forward the child's result if it printed one; keeps the contract
        of exactly ONE JSON line on stdout even when the child wedges during
        teardown AFTER finishing the measurement."""
        for line in reversed((text or "").strip().splitlines()):
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict) and "metric" in rec:
                print(line)
                return True
        return False

    try:
        r = subprocess.run([sys.executable, os.path.abspath(__file__)], env=env,
                           timeout=budget, capture_output=True, text=True)
        sys.stderr.write(r.stderr or "")
        if r.returncode == 0 and emit_if_json(r.stdout):
            return 0
        print(f"bench: TPU attempt exited {r.returncode}; falling back to CPU",
              file=sys.stderr)
    except subprocess.TimeoutExpired as e:
        out = e.stdout.decode() if isinstance(e.stdout, bytes) else (e.stdout or "")
        if emit_if_json(out):
            print(f"bench: TPU child wedged after printing its result "
                  f"(teardown hang); using it", file=sys.stderr)
            return 0
        print(f"bench: TPU attempt exceeded {budget}s (wedged tunnel?); "
              f"falling back to CPU", file=sys.stderr)
    env["BENCH_CPU"] = "1"
    env.pop("BENCH_SUPERVISED", None)
    return subprocess.run([sys.executable, os.path.abspath(__file__)], env=env).returncode


def main():
    if os.environ.get("BENCH_CPU") == "1":
        _force_cpu()

    import jax
    import jax.numpy as jnp
    import numpy as np

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from heterofl_tpu import config as C
    from heterofl_tpu.data import fetch_dataset, label_split_masks, split_dataset, stack_client_shards
    from heterofl_tpu.models import make_model
    from heterofl_tpu.parallel import RoundEngine, make_mesh

    users = int(os.environ.get("BENCH_USERS", "100"))
    n_train = int(os.environ.get("BENCH_SYNTH_N", "50000"))
    timed_rounds = int(os.environ.get("BENCH_ROUNDS", "5"))

    cfg = C.default_cfg()
    cfg["control"] = C.parse_control_name(f"1_{users}_0.1_iid_fix_a1-b1-c1-d1-e1_bn_1_1")
    cfg["data_name"] = "CIFAR10"
    cfg["model_name"] = "resnet18"
    cfg["synthetic"] = True
    # bf16 matmul/conv operands with f32 accumulation: the TPU MXU recipe.
    cfg["compute_dtype"] = os.environ.get("BENCH_DTYPE", "bfloat16")
    cfg = C.process_control(cfg)

    hidden = os.environ.get("BENCH_HIDDEN")
    degraded = None
    if hidden:  # debug-only shrink, e.g. BENCH_HIDDEN=8,16,16,16
        cfg["resnet"] = {"hidden_size": [int(h) for h in hidden.split(",")]}
    elif jax.devices()[0].platform == "cpu":
        # full-width ResNet-18 takes >9 min to compile on CPU; keep the
        # fallback line honest but finishable
        cfg["resnet"] = {"hidden_size": [16, 32, 64, 128]}
        degraded = "cpu-fallback-quarter-width"

    ds = fetch_dataset("CIFAR10", synthetic=True, seed=0,
                       synthetic_sizes={"train": n_train, "test": 1000})
    rng = np.random.default_rng(0)
    split, lsplit = split_dataset(ds, users, "iid", rng)
    x, y, m = stack_client_shards(ds["train"].data, ds["train"].target, split["train"],
                                  list(range(users)))
    lm = label_split_masks(lsplit, users, 10)
    cfg["classes_size"] = 10
    model = make_model(cfg)
    params = model.init(jax.random.key(0))
    mesh = make_mesh(len(jax.devices()), 1)
    engine = RoundEngine(model, cfg, mesh)
    data = (jnp.asarray(x), jnp.asarray(y), jnp.asarray(m), jnp.asarray(lm))

    n_active = int(np.ceil(cfg["frac"] * users))
    def round_once(params, r):
        user_idx = rng.permutation(users)[:n_active].astype(np.int32)
        params, ms = engine.train_round(params, jax.random.key(r), 0.1, user_idx, data)
        return params, ms

    # compile + warmup
    t0 = time.time()
    params, ms = round_once(params, 0)
    jax.block_until_ready(params)
    compile_s = time.time() - t0
    # timed
    t0 = time.time()
    for r in range(1, timed_rounds + 1):
        params, ms = round_once(params, r)
    jax.block_until_ready(params)
    dt = (time.time() - t0) / timed_rounds
    rps = 1.0 / dt

    loss = float(np.asarray(ms["loss_sum"]).sum() / np.asarray(ms["n"]).sum())
    print(json.dumps({
        "metric": "federated_rounds_per_sec_cifar10_resnet18_a1-e1_100c",
        "value": round(rps, 4),
        "unit": "rounds/sec",
        "vs_baseline": round(rps / 10.0, 4),
        "extra": {"round_sec": round(dt, 3), "compile_sec": round(compile_s, 1),
                  "devices": len(jax.devices()), "platform": jax.devices()[0].platform,
                  "active_clients": n_active, "final_loss": round(loss, 4),
                  **({"degraded": degraded} if degraded else {})},
    }))


if __name__ == "__main__":
    if os.environ.get("BENCH_CPU") == "1" or os.environ.get("BENCH_SUPERVISED") == "1":
        main()
    else:
        sys.exit(_supervise())
