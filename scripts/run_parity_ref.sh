#!/bin/bash
# Torch-reference sides of the round-2 trajectory-parity runs (VERDICT r1
# item 4). Sequential: single-core box. Writes /tmp/PARITY_REF_*.json and a
# progress log. Detach with nohup; takes a few hours.
set -u
cd /root/repo
RUN() {
  env -u PALLAS_AXON_POOL_IPS -u PALLAS_AXON_REMOTE_COMPILE -u AXON_LOOPBACK_RELAY \
    JAX_PLATFORMS=cpu PYTHONPATH=/root/repo \
    python -u -m heterofl_tpu.analysis.compare_reference "$@"
}
for s in 0 1 2; do
  echo "=== CIFAR resnet18 ref seed $s $(date -u +%H:%M:%S) ==="
  RUN --data CIFAR10 --model resnet18 --hidden 64,128 --users 100 --frac 0.1 \
      --rounds 25 --local_epochs 1 --n_train 2000 --n_test 1000 --seed $s \
      --skip mine --out /tmp/PARITY_REF_CIFAR_S$s.json 2>&1 | tail -1
done
for s in 0 1 2; do
  echo "=== MNIST conv non-iid ref seed $s $(date -u +%H:%M:%S) ==="
  RUN --data MNIST --model conv --hidden 64,128,256,512 --users 100 --frac 0.1 \
      --split non-iid-2 --rounds 25 --local_epochs 5 --n_train 2000 --n_test 1000 \
      --seed $s --skip mine --out /tmp/PARITY_REF_MNIST_NONIID_S$s.json 2>&1 | tail -1
done
echo "=== ALL_REF_DONE $(date -u +%H:%M:%S) ==="
