#!/usr/bin/env python
"""One TPU tunnel claim, the whole round-4 device program (VERDICT r3 items
2+4): the mine-side convergence campaigns (100-round curves; each run writes
its /tmp/PARITY_R3_MINE_*.json on completion, so a mid-session kill keeps all
finished runs) followed by the measurement session (bench rehearsal, MFU,
client-fold A/B).

A watchdog aborts with exit code 3 if the tunnel claim itself does not
complete within TPU_CLAIM_TIMEOUT (default 600 s) -- the retry loop
(tpu_r4_loop.sh) treats that as "tunnel still wedged, try again later".
Progress goes to stderr; artifacts to /tmp and stdout JSON lines.
"""

import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CLAIMED = False


def _watchdog():
    budget = float(os.environ.get("TPU_CLAIM_TIMEOUT", "600"))
    time.sleep(budget)
    if not CLAIMED:
        print(f"tpu_r4_session: claim exceeded {budget:.0f}s, aborting",
              file=sys.stderr, flush=True)
        os._exit(3)


def main():
    global CLAIMED
    # share bench.py's fingerprinted cache dir: a successful session
    # pre-warms the driver's end-of-round bench compile
    import bench as _bench
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _bench._cache_dir())
    threading.Thread(target=_watchdog, daemon=True).start()
    t0 = time.time()
    print("tpu_r4_session: claiming devices...", file=sys.stderr, flush=True)
    import jax

    devs = jax.devices()
    CLAIMED = True
    print(f"tpu_r4_session: claimed {devs[0].device_kind} "
          f"in {time.time() - t0:.1f}s", file=sys.stderr, flush=True)
    if devs[0].platform == "cpu":
        print("tpu_r4_session: got CPU, refusing (this session is for the "
              "real chip)", file=sys.stderr, flush=True)
        return 4
    # the CPU fallback twin of this campaign (run_parity_r3_mine.py) is now
    # redundant and would fight this session for the single core
    # anchored pattern: a bare filename match can kill unrelated processes
    # (an editor/tail/grep touching the file) -- ADVICE r4.  Interpreter
    # flags like `python -u` may sit between the binary and the script path.
    os.system(r"pkill -f 'python[0-9.]*( -[^ ]+)* [^ ]*run_parity_r3_mine\.py' 2>/dev/null")

    from heterofl_tpu.analysis import compare_reference as cr

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from parity_r4_specs import RUNS, run_one

    def log(msg):
        print(f"tpu_r4_session: {msg}", file=sys.stderr, flush=True)

    for _family, name, args, out in RUNS:
        t = time.time()
        # on the TPU the direct conv lowering is the measured product default
        if run_one(cr.main, name, args, out, log=log):
            log(f"campaign {name} done in {time.time() - t:.0f}s")

    print("tpu_r4_session: measurements ...", file=sys.stderr, flush=True)
    import importlib

    meas = importlib.import_module("tpu_measure_r4")
    meas.main()
    print("tpu_r4_session: ALL DONE", file=sys.stderr, flush=True)
    return 0


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    sys.exit(main())
