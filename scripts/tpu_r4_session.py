#!/usr/bin/env python
"""One TPU tunnel claim, the whole round-4 device program (VERDICT r3 items
2+4): the mine-side convergence campaigns (100-round curves; each run writes
its /tmp/PARITY_R3_MINE_*.json on completion, so a mid-session kill keeps all
finished runs) followed by the measurement session (bench rehearsal, MFU,
client-fold A/B).

A watchdog aborts with exit code 3 if the tunnel claim itself does not
complete within TPU_CLAIM_TIMEOUT (default 600 s) -- the retry loop
(tpu_r4_loop.sh) treats that as "tunnel still wedged, try again later".
Progress goes to stderr; artifacts to /tmp and stdout JSON lines.
"""

import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CLAIMED = False


def _watchdog():
    budget = float(os.environ.get("TPU_CLAIM_TIMEOUT", "600"))
    time.sleep(budget)
    if not CLAIMED:
        print(f"tpu_r4_session: claim exceeded {budget:.0f}s, aborting",
              file=sys.stderr, flush=True)
        os._exit(3)


def main():
    global CLAIMED
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     ".jax_cache", "tpu"))
    threading.Thread(target=_watchdog, daemon=True).start()
    t0 = time.time()
    print("tpu_r4_session: claiming devices...", file=sys.stderr, flush=True)
    import jax

    devs = jax.devices()
    CLAIMED = True
    print(f"tpu_r4_session: claimed {devs[0].device_kind} "
          f"in {time.time() - t0:.1f}s", file=sys.stderr, flush=True)
    if devs[0].platform == "cpu":
        print("tpu_r4_session: got CPU, refusing (this session is for the "
              "real chip)", file=sys.stderr, flush=True)
        return 4
    # the CPU fallback twin of this campaign (run_parity_r3_mine.py) is now
    # redundant and would fight this session for the single core
    os.system("pkill -f run_parity_r3_mine 2>/dev/null")

    from heterofl_tpu.analysis import compare_reference as cr

    MNIST = ["--data", "MNIST", "--model", "conv", "--hidden", "64,128,256,512",
             "--users", "100", "--frac", "0.1", "--rounds", "100",
             "--local_epochs", "5", "--n_train", "2000", "--n_test", "1000",
             "--skip", "reference"]
    CIFAR = ["--data", "CIFAR10", "--model", "resnet18", "--hidden", "64,128",
             "--users", "100", "--frac", "0.1", "--rounds", "100",
             "--local_epochs", "1", "--n_train", "2000", "--n_test", "1000",
             "--skip", "reference"]

    runs = []
    for s in (0, 1, 2):
        runs.append((f"MNIST non-iid S{s}",
                     MNIST + ["--split", "non-iid-2", "--seed", str(s),
                              "--out", f"/tmp/PARITY_R3_MINE_MNIST_NONIID_S{s}.json"]))
    runs.append(("MNIST dynamic", MNIST + ["--model_split", "dynamic", "--mode", "a1-e1",
                                           "--seed", "0", "--out", "/tmp/PARITY_R3_MINE_DYNAMIC_S0.json"]))
    runs.append(("MNIST interp a1-b9", MNIST + ["--mode", "a1-b9", "--seed", "0",
                                                "--out", "/tmp/PARITY_R3_MINE_INTERP_A1B9_S0.json"]))
    runs.append(("MNIST interp a5-e5", MNIST + ["--mode", "a5-e5", "--seed", "0",
                                                "--out", "/tmp/PARITY_R3_MINE_INTERP_A5E5_S0.json"]))
    for s in (0, 1, 2):
        runs.append((f"CIFAR resnet18 S{s}",
                     CIFAR + ["--seed", str(s),
                              "--out", f"/tmp/PARITY_R3_MINE_CIFAR_S{s}.json"]))

    for name, args in runs:
        out = args[args.index("--out") + 1]
        if os.path.exists(out):
            print(f"tpu_r4_session: skip {name} (artifact exists)",
                  file=sys.stderr, flush=True)
            continue
        t = time.time()
        print(f"tpu_r4_session: campaign {name} ...", file=sys.stderr, flush=True)
        cr.main(args)
        print(f"tpu_r4_session: campaign {name} done in {time.time() - t:.0f}s",
              file=sys.stderr, flush=True)

    print("tpu_r4_session: measurements ...", file=sys.stderr, flush=True)
    import importlib

    meas = importlib.import_module("tpu_measure_r4")
    meas.main()
    print("tpu_r4_session: ALL DONE", file=sys.stderr, flush=True)
    return 0


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    sys.exit(main())
