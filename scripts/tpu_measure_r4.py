#!/usr/bin/env python
"""TPU measurement session (VERDICT r3 item 4 + r4 item 1): one tunnel
claim, five measurements, one JSON line each (flushed immediately so a wedge
keeps the partials):

1. flagship-bench rehearsal  -- the BASELINE.json config (100-client CIFAR10
   ResNet-18 a1-e1, bf16) timed for rounds/sec; also warms the repo compile
   cache the driver's bench.py will hit.
2. MFU accounting            -- compiled-program FLOPs (XLA cost_analysis) /
   measured round time vs the chip's peak; answers "how far from the MXU
   ceiling is the 20 ms step".
3. client-fold A/B           -- the same local-SGD scan with (a) 10 vmapped
   clients x batch 10 (the engine's form: per-client weights => grouped
   convs), (b) one shared-weight batch-100 program (the fold), (c) one
   shared-weight batch-10 program (the per-chip pod proxy).  (b)~(a) means
   steps are latency-bound and the fold buys nothing; (b)<<(a) means the
   batched-kernel lowering is the bottleneck and a block-diagonal/bmm conv
   path is the next optimization.
4. engine-round variants     -- norm=none floor and the im2col conv lowering
   timed through the real flagship round.
5. rate-grouped engine A/B   -- dense per-level programs (parallel/grouped.py)
   vs the masked round, best-vs-best round times (per-round lists reported
   so per-bucket compile spikes are attributable).

Peak FLOP/s table keyed by device_kind prefix; defaults to v5e bf16.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PEAK_BF16 = {
    "TPU v5e": 197e12, "TPU v5 lite": 197e12, "TPU v4": 275e12,
    "TPU v5p": 459e12, "TPU v6e": 918e12,
}


def emit(rec):
    print(json.dumps(rec), flush=True)


def main():
    # share bench.py's fingerprinted cache dir: a successful session
    # pre-warms the driver's end-of-round bench compile
    import bench as _bench
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _bench._cache_dir())
    import jax
    import jax.numpy as jnp

    from heterofl_tpu import config as C
    from heterofl_tpu.data import (fetch_dataset, label_split_masks, split_dataset,
                                   stack_client_shards)
    from heterofl_tpu.models import make_model
    from heterofl_tpu.parallel import RoundEngine, make_mesh

    t_claim = time.time()
    devs = jax.devices()
    kind = devs[0].device_kind
    emit({"measure": "platform", "platform": devs[0].platform,
          "device_kind": kind, "claim_sec": round(time.time() - t_claim, 1)})
    peak = next((v for k, v in PEAK_BF16.items() if kind.startswith(k)), 197e12)

    smoke = os.environ.get("MEAS_SMOKE") == "1"  # CPU logic check only
    users, timed = (20, 1) if smoke else (100, 5)
    n_synth = 2000 if smoke else 50000
    cfg = C.default_cfg()
    cfg["control"] = C.parse_control_name(f"1_{users}_0.1_iid_fix_a1-b1-c1-d1-e1_bn_1_1")
    cfg["data_name"], cfg["model_name"], cfg["synthetic"] = "CIFAR10", "resnet18", True
    cfg["compute_dtype"] = "bfloat16"
    cfg = C.process_control(cfg)
    cfg["classes_size"] = 10

    if smoke:
        cfg["resnet"] = {"hidden_size": [8, 16, 16, 16]}
    ds = fetch_dataset("CIFAR10", synthetic=True, seed=0,
                       synthetic_sizes={"train": n_synth, "test": 1000})
    rng = np.random.default_rng(0)
    split, lsplit = split_dataset(ds, users, "iid", rng)
    x, y, m = stack_client_shards(ds["train"].data, ds["train"].target,
                                  split["train"], list(range(users)))
    lm = label_split_masks(lsplit, users, 10)
    data = (jnp.asarray(x), jnp.asarray(y), jnp.asarray(m), jnp.asarray(lm))

    model = make_model(cfg)
    params = model.init(jax.random.key(0))
    mesh = make_mesh(len(devs), 1)
    eng = RoundEngine(model, cfg, mesh)
    srng = np.random.default_rng(1)

    def once(p, r):
        uidx = srng.permutation(users)[:10].astype(np.int32)
        return eng.train_round(p, jax.random.key(r), 0.1, uidx, data)

    # ---- 1. flagship rehearsal -------------------------------------------
    t0 = time.time()
    params, _ = once(params, 0)
    jax.block_until_ready(params)
    compile_s = time.time() - t0
    emit({"measure": "flagship_compile", "compile_sec": round(compile_s, 1)})
    masked_rounds = []
    for r in range(1, timed + 1):
        t0 = time.time()
        params, ms = once(params, r)
        jax.block_until_ready(params)
        masked_rounds.append(time.time() - t0)
        dt = sum(masked_rounds) / len(masked_rounds)
        emit({"measure": "flagship_round", "r": r, "avg_round_sec": round(dt, 3),
              "round_sec": round(masked_rounds[-1], 3),
              "rounds_per_sec": round(1.0 / dt, 4)})

    # ---- 2. MFU from compiled-program FLOPs ------------------------------
    # Re-lower the already-jitted round program with the concrete args the
    # engine passes (replicated placement) and read XLA's flop count.
    try:
        user_idx = srng.permutation(users)[:10].astype(np.int32)
        a = len(user_idx)
        pad = (-a) % mesh.shape["clients"]
        uglob = np.concatenate([user_idx, -np.ones(pad, np.int32)]).astype(np.int32)
        args = (params, jax.random.key(99), jnp.asarray(0.1, jnp.float32),
                jnp.asarray(uglob), jnp.asarray(uglob)) + tuple(data) + (eng.fix_rates,)
        lowered = eng._train.lower(*args)
        cost = lowered.compile().cost_analysis()
        cost = cost[0] if isinstance(cost, (list, tuple)) else cost
        flops = float(cost.get("flops", float("nan")))
        mfu = flops / dt / peak
        emit({"measure": "mfu", "program_flops": flops,
              "round_sec": round(dt, 3), "peak_flops_per_sec": peak,
              "mfu": round(mfu, 4),
              "note": "program_flops is XLA's static count for ONE round "
                      "(250 local steps x 10 clients x batch 10, masked "
                      "full-width)"})
    except Exception as e:  # cost_analysis availability varies by backend
        emit({"measure": "mfu", "error": repr(e)[:300]})

    # ---- 3. client-fold A/B ----------------------------------------------
    # One local-epoch scan (250 steps) stripped to fwd+bwd+SGD, no aggregation:
    # isolates the step engine from the round program.
    from heterofl_tpu.ops.augment import normalize_image

    stats = None
    try:
        from heterofl_tpu.data.datasets import DATASET_STATS
        stats = DATASET_STATS.get("CIFAR10")
    except Exception:
        pass

    def norm_img(xb):
        xb = xb.astype(jnp.float32)
        return normalize_image(xb, *stats) if stats else xb / 255.0

    def loss_fn(p, xb, yb):
        out, _ = model.apply(p, {"img": norm_img(xb), "label": yb}, train=True)
        return out["loss"]

    def sgd_scan(p, xs, ys, lr=0.1):
        def step(p, inp):
            xb, yb = inp
            g = jax.grad(loss_fn)(p, xb, yb)
            return jax.tree_util.tree_map(lambda a, b: a - lr * b, p, g), 0.0
        p, _ = jax.lax.scan(step, p, (xs, ys))
        return p

    # 250 steps = 5 local epochs x 50 steps over each client's 500 images,
    # so the step stream tiles the client shard 5x (mirrors the engine)
    per = np.asarray(x).shape[1]
    spe = per // 10                       # steps per epoch at batch 10
    n_ep = 1 if smoke else 5
    S = spe * n_ep
    xc = np.asarray(x)[:10, : spe * 10].reshape(10, spe, 10, 32, 32, 3)
    yc = np.asarray(y)[:10, : spe * 10].reshape(10, spe, 10)
    xs10 = jnp.asarray(np.tile(xc, (1, n_ep, 1, 1, 1, 1)))
    ys10 = jnp.asarray(np.tile(yc, (1, n_ep, 1)))

    def timeit(name, fn, *args):
        t0 = time.time()
        out = fn(*args)
        jax.block_until_ready(out)
        c = time.time() - t0
        reps = 3
        t0 = time.time()
        for _ in range(reps):
            out = fn(*args)
        jax.block_until_ready(out)
        d = (time.time() - t0) / reps
        emit({"measure": name, "sec": round(d, 3), "ms_per_step": round(d / S * 1e3, 3),
              "compile_sec": round(c - d, 1)})
        return d

    # (a) engine form: vmapped clients, per-client weights
    pv = jax.tree_util.tree_map(lambda a: jnp.broadcast_to(a, (10,) + a.shape), params)
    fa = jax.jit(jax.vmap(sgd_scan))
    da = timeit("fold_ab_a_vmap10x10", fa, pv, xs10, ys10)
    # (b) the fold: shared weights, batch 100
    xs100 = jnp.asarray(np.asarray(xs10).transpose(1, 0, 2, 3, 4, 5).reshape(S, 100, 32, 32, 3))
    ys100 = jnp.asarray(np.asarray(ys10).transpose(1, 0, 2).reshape(S, 100))
    fb = jax.jit(sgd_scan)
    db = timeit("fold_ab_b_shared_batch100", fb, params, xs100, ys100)
    # (c) pod per-chip proxy: shared weights, batch 10
    dc = timeit("fold_ab_c_shared_batch10", fb, params, xs10[0], ys10[0])
    emit({"measure": "fold_ab_summary",
          "vmap10x10_ms": round(da / S * 1e3, 3),
          "shared100_ms": round(db / S * 1e3, 3),
          "shared10_ms": round(dc / S * 1e3, 3),
          "verdict": ("latency-bound: fold buys nothing"
                      if db > 0.8 * da else
                      "batched-kernel lowering is the bottleneck")})

    # shared scaffolding for engine-round variants (norm=none, im2col):
    # build a variant cfg from the flagship one, time compile + 3 rounds
    def time_engine_round(name, **overrides):
        c = dict(cfg)
        c.update(overrides)
        mdl = make_model(c)
        p = mdl.init(jax.random.key(0))
        eng_v = RoundEngine(mdl, c, mesh)

        def once_v(p, r):
            uidx = srng.permutation(users)[:10].astype(np.int32)
            return eng_v.train_round(p, jax.random.key(r), 0.1, uidx, data)

        t0 = time.time()
        p, _ = once_v(p, 0)
        jax.block_until_ready(p)
        c_s = time.time() - t0
        t0 = time.time()
        ms_v = None
        for r in range(1, 4):
            p, ms_v = once_v(p, r)
        jax.block_until_ready(p)
        d = (time.time() - t0) / 3
        loss_v = float(np.asarray(ms_v["loss_sum"]).sum()
                       / max(float(np.asarray(ms_v["n"]).sum()), 1.0))
        emit({"measure": name, "round_sec": round(d, 3),
              "ms_per_step": round(d / 250 * 1e3, 2), "compile_sec": round(c_s, 1),
              "rounds_per_sec": round(1.0 / d, 4), "loss": round(loss_v, 4),
              "speedup_vs_direct": round(dt / d, 3)})
        return d

    # norm=none floor re-check for the attribution table; the control string
    # carries the norm field, so rebuild it with 'none'
    cfg_none = C.default_cfg()
    cfg_none["control"] = C.parse_control_name(
        f"1_{users}_0.1_iid_fix_a1-b1-c1-d1-e1_none_1_1")
    cfg_none["data_name"], cfg_none["model_name"] = "CIFAR10", "resnet18"
    cfg_none["synthetic"], cfg_none["compute_dtype"] = True, "bfloat16"
    cfg_none = C.process_control(cfg_none)
    cfg_none["classes_size"] = 10
    if smoke:
        cfg_none["resnet"] = {"hidden_size": [8, 16, 16, 16]}
    time_engine_round("norm_none_round", **cfg_none)

    # ---- 4. im2col conv lowering in the REAL engine round ----------------
    # The candidate speedup: swap the grouped-conv lowering of the vmapped
    # per-client kernels for patch-extraction + batched matmul
    # (cfg conv_impl='im2col', ops/layers.py) and re-time the flagship round.
    time_engine_round("im2col_round", conv_impl="im2col")

    # ---- 5. rate-grouped dense engine A/B (round 5) ----------------------
    # The roofline's prescription realised (parallel/grouped.py): dense
    # per-level programs vs the masked full-width round on the same inputs.
    # Per-round times are reported individually because the per-level
    # programs recompile per slot-count bucket -- warm rounds show the
    # steady state, spikes show a fresh bucket.
    from heterofl_tpu.parallel import GroupedRoundEngine

    grp = GroupedRoundEngine(cfg, mesh)
    rates_vec = np.asarray(cfg["model_rate"], np.float32)

    def once_g(p, r):
        uidx = srng.permutation(users)[:10].astype(np.int32)
        return grp.train_round(p, uidx, rates_vec[uidx], data, 0.1, jax.random.key(r))

    pg = model.init(jax.random.key(0))
    t0 = time.time()
    pg, _ = once_g(pg, 0)
    jax.block_until_ready(pg)
    emit({"measure": "grouped_compile", "compile_sec": round(time.time() - t0, 1)})
    per_round = []
    for r in range(1, 7 if not smoke else 2):
        t0 = time.time()
        pg, ms_g = once_g(pg, r)
        jax.block_until_ready(pg)
        per_round.append(round(time.time() - t0, 3))
    warm = min(per_round)
    masked_best = min(masked_rounds)  # best-vs-best, not avg-vs-best
    emit({"measure": "grouped_round", "per_round_sec": per_round,
          "best_round_sec": warm, "rounds_per_sec": round(1.0 / warm, 4),
          "masked_per_round_sec": [round(t, 3) for t in masked_rounds],
          "speedup_vs_masked_best": round(masked_best / warm, 3),
          "loss": round(float(np.asarray(ms_g["loss_sum"]).sum()
                              / max(float(np.asarray(ms_g["n"]).sum()), 1.0)), 4)})
    emit({"measure": "DONE"})


if __name__ == "__main__":
    main()
