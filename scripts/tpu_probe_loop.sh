#!/bin/bash
# Retry the TPU probe until it succeeds; append outcomes to the log.
# Claim attempts can block ~30 min before failing, so no extra sleep needed
# between failures beyond a short backoff.
LOG=${1:-/tmp/tpu_probe.log}
for i in $(seq 1 40); do
  echo "=== probe attempt $i $(date -u +%H:%M:%S) ===" >> "$LOG"
  python -u "$(dirname "$0")/tpu_probe.py" >> "$LOG" 2>&1
  if grep -q PROBE_OK "$LOG"; then
    echo "=== PROBE SUCCEEDED attempt $i $(date -u +%H:%M:%S) ===" >> "$LOG"
    exit 0
  fi
  sleep 120
done
echo "=== probe gave up $(date -u +%H:%M:%S) ===" >> "$LOG"
