#!/bin/bash
# Retry the TPU probe until it succeeds; append outcomes to the log.
# Claim attempts can block ~30 min before failing, so no extra sleep needed
# between failures beyond a short backoff.
LOG=${1:-/tmp/tpu_probe.log}
for i in $(seq 1 40); do
  echo "=== probe attempt $i $(date -u +%H:%M:%S) ===" >> "$LOG"
  # per-attempt capture: grepping the cumulative log would match a stale
  # PROBE_OK from an earlier run
  ATTEMPT=$(mktemp)
  python -u "$(dirname "$0")/tpu_probe.py" > "$ATTEMPT" 2>&1
  cat "$ATTEMPT" >> "$LOG"
  if grep -q PROBE_OK "$ATTEMPT"; then
    rm -f "$ATTEMPT"
    echo "=== PROBE SUCCEEDED attempt $i $(date -u +%H:%M:%S) ===" >> "$LOG"
    exit 0
  fi
  rm -f "$ATTEMPT"
  sleep 120
done
echo "=== probe gave up $(date -u +%H:%M:%S) ===" >> "$LOG"
