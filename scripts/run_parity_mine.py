#!/usr/bin/env python
"""This-framework sides of the round-2 trajectory-parity runs (VERDICT r1
item 4), all in ONE process (one TPU tunnel claim; rapid claim cycling
degrades the link).  Mirrors scripts/run_parity_ref.sh seed-for-seed."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from heterofl_tpu.analysis import compare_reference as cr


def main():
    for s in (0, 1, 2):
        print(f"=== CIFAR resnet18 mine seed {s} ===", flush=True)
        cr.main(["--data", "CIFAR10", "--model", "resnet18", "--hidden", "64,128",
                 "--users", "100", "--frac", "0.1", "--rounds", "25",
                 "--local_epochs", "1", "--n_train", "2000", "--n_test", "1000",
                 "--seed", str(s), "--skip", "reference",
                 "--out", f"/tmp/PARITY_MINE_CIFAR_S{s}.json"])
    for s in (0, 1, 2):
        print(f"=== MNIST conv non-iid mine seed {s} ===", flush=True)
        cr.main(["--data", "MNIST", "--model", "conv", "--hidden", "64,128,256,512",
                 "--users", "100", "--frac", "0.1", "--split", "non-iid-2",
                 "--rounds", "25", "--local_epochs", "5", "--n_train", "2000",
                 "--n_test", "1000", "--seed", str(s), "--skip", "reference",
                 "--out", f"/tmp/PARITY_MINE_MNIST_NONIID_S{s}.json"])
    print("=== ALL_MINE_DONE ===", flush=True)


if __name__ == "__main__":
    main()
