"""CI arms smoke (ISSUE 14 satellite): one E=2 masked k8 MNIST-pair
multiplexed run through the driver.

Asserts the per-arm ``{"tag": "arms"}`` log lines exist for both arms,
carry 8 train rounds each, and DIVERGE across the two distinct seed
streams (a degenerate multiplexer that runs one trajectory twice would
pass every shape check -- the divergence is the semantic smoke).  Also
checks the per-arm checkpoints landed.  Runs in ~30s on a CI CPU.
"""

import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from heterofl_tpu import config as C  # noqa: E402
from heterofl_tpu.entry.common import ArmsExperiment  # noqa: E402


def main() -> int:
    cfg = C.default_cfg()
    cfg["control"] = C.parse_control_name(
        "1_8_0.5_iid_fix_a1-b1-c1-d1-e1_bn_1_1")
    cfg["data_name"] = "MNIST"
    cfg["model_name"] = "conv"
    cfg["synthetic"] = True
    cfg["synthetic_sizes"] = {"train": 200, "test": 80}
    cfg["output_dir"] = tempfile.mkdtemp(prefix="arms_smoke_")
    cfg["override"] = {"num_epochs": {"global": 8, "local": 1},
                       "conv": {"hidden_size": [8, 16]},
                       "batch_size": {"train": 10, "test": 20}}
    cfg["superstep_rounds"] = 8
    cfg["eval_interval"] = 8
    cfg["arms"] = {"count": 2, "seeds": [None, 7], "lr_scales": [1.0, 1.0]}
    cfg = C.process_control(cfg)
    exp = ArmsExperiment(cfg, 0)
    exp.run("Global-Accuracy", "max")
    tag = exp._arms_tag()
    log = os.path.join(cfg["output_dir"], "runs", f"train_{tag}",
                       "log.jsonl")
    lines = [json.loads(ln) for ln in open(log)]
    tr = [ln for ln in lines
          if ln.get("tag") == "arms" and ln["event"] == "train"]
    l0 = [ln["loss"] for ln in tr if ln["arm"] == 0]
    l1 = [ln["loss"] for ln in tr if ln["arm"] == 1]
    assert len(l0) == len(l1) == 8, (len(l0), len(l1))
    assert l0 != l1, f"per-arm losses identical across seeds: {l0}"
    for e in range(2):
        ck = os.path.join(cfg["output_dir"], "model",
                          f"{tag}_a{e}_checkpoint.pkl")
        assert os.path.exists(ck), ck
    print(f"arms driver smoke ok: 2 arms x 8 rounds, per-arm losses "
          f"diverge (arm0 {l0[-1]:.4f} vs arm1 {l1[-1]:.4f}), per-arm "
          f"checkpoints present")
    return 0


if __name__ == "__main__":
    sys.exit(main())
