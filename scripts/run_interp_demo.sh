#!/bin/bash
# End-to-end interpolation figure through this framework's own pipeline
# (VERDICT r2 item 4): make.py grid -> train_classifier_fed ->
# test_classifier_fed -> summary profiles -> process.py, small scale on
# synthetic MNIST.  Produces output_interp/result.csv and
# output_interp/fig/interp_Global-Accuracy.png.
#
# Usage: bash scripts/run_interp_demo.sh [OUTDIR]  (default ./output_interp)
set -eu
cd /root/repo
OUT=${1:-output_interp}
MODES="a1,b1,a1-b9,a3-b7,a5-b5,a7-b3,a9-b1"
OVERRIDE='{"num_epochs": {"global": 30, "local": 2}, "conv": {"hidden_size": [16, 32]}, "batch_size": {"train": 10, "test": 50}}'
ENV() {
  env -u PALLAS_AXON_POOL_IPS -u PALLAS_AXON_REMOTE_COMPILE -u AXON_LOOPBACK_RELAY \
    JAX_PLATFORMS=cpu JAX_COMPILATION_CACHE_DIR=/tmp/jaxcache PYTHONPATH=/root/repo "$@"
}
# JSON kept single-quoted INSIDE the value: the generated grid scripts re-eval
# this string, and unquoted {...} would hit bash brace expansion and split into
# two words, failing argparse (advisor r3, medium).
EXTRA="--output_dir $OUT --synthetic_sizes '{\"train\":4000,\"test\":1000}' --override '$OVERRIDE'"

# 1. grids (one job per line, wait barriers -> sequential on this box)
ENV python -m heterofl_tpu.analysis.make --run train --model conv --fed 1 \
  --data_split_mode iid --modes "$MODES" --synthetic --round 1 --extra "$EXTRA" > /dev/null
ENV python -m heterofl_tpu.analysis.make --run test --model conv --fed 1 \
  --data_split_mode iid --modes "$MODES" --synthetic --round 1 --extra "$EXTRA" > /dev/null

# 2. train + test every grid point (the generated scripts run the entry
#    points; PYTHONPATH/env comes from this shell)
ENV bash train_conv_iid.sh
ENV bash test_conv_iid.sh

# 3. per-level profiler bundles (x axis = measured params ratio)
ENV python - "$OUT" <<'EOF'
import json, sys
from heterofl_tpu import config as C
from heterofl_tpu.analysis.summary import make_summary

cfg = C.default_cfg()
cfg["data_name"], cfg["model_name"] = "MNIST", "conv"
cfg = C.process_control(cfg)
cfg["conv"] = {"hidden_size": [16, 32]}
cfg["classes_size"], cfg["data_shape"] = 10, [28, 28, 1]
make_summary(cfg, rates=[1.0, 0.5, 0.25, 0.125, 0.0625], output_dir=sys.argv[1])
EOF

# 4. aggregate + figures
ENV python -m heterofl_tpu.analysis.process --output_dir "$OUT"
ls -l "$OUT"/result.csv "$OUT"/fig/
