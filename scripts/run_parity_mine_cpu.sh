#!/bin/bash
# CPU fallback for the mine-side trajectory runs (wedged TPU tunnel).
# Waits for the torch-reference script to finish (single-core box), then
# runs this framework's sides on the virtual CPU backend with a persistent
# compilation cache.
set -u
cd /root/repo
# wait on the ref script's LAST output artifact (robust to where its log
# was redirected), or its conventional log sentinel
while ! { [ -s /tmp/PARITY_REF_MNIST_NONIID_S2.json ] \
          || grep -q ALL_REF_DONE /tmp/parity_ref.log 2>/dev/null; }; do sleep 60; done
RUN() {
  env -u PALLAS_AXON_POOL_IPS -u PALLAS_AXON_REMOTE_COMPILE -u AXON_LOOPBACK_RELAY \
    JAX_PLATFORMS=cpu JAX_COMPILATION_CACHE_DIR=/tmp/jaxcache PYTHONPATH=/root/repo \
    python -u -m heterofl_tpu.analysis.compare_reference "$@"
}
for s in 0 1 2; do
  echo "=== CIFAR resnet18 mine seed $s $(date -u +%H:%M:%S) ==="
  RUN --data CIFAR10 --model resnet18 --hidden 64,128 --users 100 --frac 0.1 \
      --rounds 25 --local_epochs 1 --n_train 2000 --n_test 1000 --seed $s \
      --skip reference --out /tmp/PARITY_MINE_CIFAR_S$s.json 2>&1 | tail -1
done
for s in 0 1 2; do
  echo "=== MNIST conv non-iid mine seed $s $(date -u +%H:%M:%S) ==="
  RUN --data MNIST --model conv --hidden 64,128,256,512 --users 100 --frac 0.1 \
      --split non-iid-2 --rounds 25 --local_epochs 5 --n_train 2000 --n_test 1000 \
      --seed $s --skip reference --out /tmp/PARITY_MINE_MNIST_NONIID_S$s.json 2>&1 | tail -1
done
echo "=== ALL_MINE_DONE $(date -u +%H:%M:%S) ==="
