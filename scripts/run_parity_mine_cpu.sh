#!/bin/bash
# CPU fallback for the mine-side trajectory runs (wedged TPU tunnel).
# Waits for the torch-reference script to finish (single-core box), then
# delegates to run_parity_mine.py -- the single source of truth for the
# run matrix -- on the virtual CPU backend with a persistent compilation
# cache.
set -u
cd /root/repo
# wait on the ref script's LAST output artifact (robust to where its log
# was redirected), or its conventional log sentinel
while ! { [ -s /tmp/PARITY_REF_MNIST_NONIID_S2.json ] \
          || grep -q ALL_REF_DONE /tmp/parity_ref.log 2>/dev/null; }; do sleep 60; done
env -u PALLAS_AXON_POOL_IPS -u PALLAS_AXON_REMOTE_COMPILE -u AXON_LOOPBACK_RELAY \
  JAX_PLATFORMS=cpu JAX_COMPILATION_CACHE_DIR=/tmp/jaxcache PYTHONPATH=/root/repo \
  python -u scripts/run_parity_mine.py
