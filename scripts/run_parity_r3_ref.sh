#!/bin/bash
# Round-3 convergence-grade trajectory campaigns, torch-reference side
# (VERDICT r2 item 3): 100 rounds x 3 seeds for CIFAR-ResNet18 and
# MNIST-conv non-iid, plus one dynamic-mode and two interpolated-mode
# (a1-b9, a5-e5) campaigns on MNIST-conv.  Sequential, nice'd to idle
# priority (single-core box shared with the build).  Writes
# /tmp/PARITY_R3_REF_*.json; detach with nohup, takes hours.
set -u
cd /root/repo
RUN() {
  env -u PALLAS_AXON_POOL_IPS -u PALLAS_AXON_REMOTE_COMPILE -u AXON_LOOPBACK_RELAY \
    JAX_PLATFORMS=cpu PYTHONPATH=/root/repo \
    nice -n 19 python -u -m heterofl_tpu.analysis.compare_reference "$@"
}
# MNIST first: cheap rounds, gives early full-length artifacts
for s in 0 1 2; do
  echo "=== MNIST conv non-iid ref seed $s $(date -u +%H:%M:%S) ==="
  RUN --data MNIST --model conv --hidden 64,128,256,512 --users 100 --frac 0.1 \
      --split non-iid-2 --rounds 100 --local_epochs 5 --n_train 2000 --n_test 1000 \
      --seed $s --skip mine --out /tmp/PARITY_R3_REF_MNIST_NONIID_S$s.json 2>&1 | tail -1
done
echo "=== MNIST_REF_DONE $(date -u +%H:%M:%S) ==="
# dynamic + interpolation modes (ref make.py:55-66), one seed each
echo "=== MNIST dynamic a1-e1 ref $(date -u +%H:%M:%S) ==="
RUN --data MNIST --model conv --hidden 64,128,256,512 --users 100 --frac 0.1 \
    --split iid --rounds 100 --local_epochs 5 --n_train 2000 --n_test 1000 \
    --model_split dynamic --mode a1-e1 \
    --seed 0 --skip mine --out /tmp/PARITY_R3_REF_DYNAMIC_S0.json 2>&1 | tail -1
echo "=== MNIST interp a1-b9 ref $(date -u +%H:%M:%S) ==="
RUN --data MNIST --model conv --hidden 64,128,256,512 --users 100 --frac 0.1 \
    --split iid --rounds 100 --local_epochs 5 --n_train 2000 --n_test 1000 \
    --mode a1-b9 \
    --seed 0 --skip mine --out /tmp/PARITY_R3_REF_INTERP_A1B9_S0.json 2>&1 | tail -1
echo "=== MNIST interp a5-e5 ref $(date -u +%H:%M:%S) ==="
RUN --data MNIST --model conv --hidden 64,128,256,512 --users 100 --frac 0.1 \
    --split iid --rounds 100 --local_epochs 5 --n_train 2000 --n_test 1000 \
    --mode a5-e5 \
    --seed 0 --skip mine --out /tmp/PARITY_R3_REF_INTERP_A5E5_S0.json 2>&1 | tail -1
echo "=== MODES_REF_DONE $(date -u +%H:%M:%S) ==="
for s in 0 1 2; do
  echo "=== CIFAR resnet18 ref seed $s $(date -u +%H:%M:%S) ==="
  RUN --data CIFAR10 --model resnet18 --hidden 64,128 --users 100 --frac 0.1 \
      --rounds 100 --local_epochs 1 --n_train 2000 --n_test 1000 --seed $s \
      --skip mine --out /tmp/PARITY_R3_REF_CIFAR_S$s.json 2>&1 | tail -1
done
echo "=== ALL_R3_REF_DONE $(date -u +%H:%M:%S) ==="
