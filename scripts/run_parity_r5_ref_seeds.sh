#!/bin/bash
# Round-5 non-iid calibration (VERDICT r4 item 4): the reference against
# itself at the PARITY_R3_MNIST_NONIID config on extra seeds 3-5, to measure
# the ref-vs-ref seed band that the +4.5pp mine-vs-ref mean gap must be
# compared against.  nice'd below the CIFAR campaign on this single core.
set -u
cd /root/repo
for s in 3 4 5; do
  out=/tmp/PARITY_R5_REF_MNIST_NONIID_S$s.json
  if [ ! -f "$out" ]; then
    echo "=== MNIST conv non-iid ref seed $s $(date -u +%H:%M:%S) ==="
    env -u PALLAS_AXON_POOL_IPS -u PALLAS_AXON_REMOTE_COMPILE -u AXON_LOOPBACK_RELAY \
      JAX_PLATFORMS=cpu PYTHONPATH=/root/repo \
      nice -n 12 python -u -m heterofl_tpu.analysis.compare_reference \
        --data MNIST --model conv --hidden 64,128,256,512 --users 100 --frac 0.1 \
        --split non-iid-2 --rounds 100 --local_epochs 5 --n_train 2000 --n_test 1000 \
        --seed $s --skip mine --out "$out" 2>&1 | tail -2
  else
    echo "skip seed $s"
  fi
  # persist the ref curve into the repo so the seed band survives a /tmp
  # wipe (this is the CAMPAIGN's side effect; the assemble summarizer only
  # reads -- ADVICE r5 item 4)
  [ -f "$out" ] && python - "$out" "PARITY_R5_REF_MNIST_NONIID_S$s.json" <<'PYEOF'
import json, sys
src, dst = sys.argv[1], sys.argv[2]
with open(src) as fin:
    curve = json.load(fin).get("reference_acc") or []
if curve:
    with open(dst, "w") as fout:
        json.dump({"reference_acc": curve}, fout)
PYEOF
done
echo "=== R5_REF_SEEDS_DONE $(date -u +%H:%M:%S) ==="
