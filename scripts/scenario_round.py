#!/usr/bin/env python
"""Scenario accuracy round (ISSUE 9 / MEASUREMENTS.md Round 13): lockstep
vs availability / straggler / buffered-async regimes, end-to-end through
the fed driver on the synthetic MNIST pair.

Runs one FedExperiment per scenario (same seed, same data split, same
100-round horizon at superstep_rounds=10, eval every 10) and reports the
Global-Accuracy trajectory facts the scenario comparison needs: final/best
accuracy, rounds-to-target (first eval reaching the target accuracy), and
the realised participation statistics of the schedule.

    JAX_PLATFORMS=cpu python scripts/scenario_round.py [--fast] [--out f]

``--fast`` shrinks the horizon for smoke runs.  Writes one JSON object to
stdout (and ``--out`` if given).
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SCENARIOS = {
    "lockstep": None,
    "markov": {"kind": "markov",
               "markov": {"p_on": 0.5, "p_off": 0.25, "length": 32,
                          "seed": 0}},
    "deadline": {"deadline": {"min_frac": 0.25}},
    "buffered": {"aggregation": "buffered", "staleness": 0.5},
    "markov+deadline+buffered": {
        "kind": "markov",
        "markov": {"p_on": 0.5, "p_off": 0.25, "length": 32, "seed": 0},
        "deadline": {"min_frac": 0.25},
        "aggregation": "buffered", "staleness": 0.5},
}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--fast", action="store_true",
                    help="20-round smoke instead of the 100-round round")
    ap.add_argument("--out", default=None)
    ap.add_argument("--target", type=float, default=60.0,
                    help="rounds-to-target accuracy threshold (Global-Acc)")
    args = ap.parse_args()

    import numpy as np

    from heterofl_tpu import config as C
    from heterofl_tpu.entry.common import FedExperiment
    from heterofl_tpu.fed.core import superstep_user_schedule
    from heterofl_tpu.sched import resolve_schedule_cfg

    rounds = 20 if args.fast else 100
    k = 10
    results = {}
    for name, sched in SCENARIOS.items():
        cfg = C.default_cfg()
        cfg["control"] = C.parse_control_name(
            "1_10_0.5_iid_fix_a1-b1-c1-d1-e1_bn_1_1")
        cfg["data_name"] = "MNIST"
        cfg["model_name"] = "conv"
        cfg["synthetic"] = True
        cfg["synthetic_sizes"] = {"train": 2000, "test": 500}
        cfg["output_dir"] = f"/tmp/scenario_round/{name.replace('+', '_')}"
        cfg["schedule"] = sched
        cfg["override"] = {"num_epochs": {"global": rounds, "local": 5},
                           "conv": {"hidden_size": [8, 16]},
                           "superstep_rounds": k, "eval_interval": k}
        cfg = C.process_control(cfg)
        exp = FedExperiment(cfg, 0)
        out = exp.run("Global-Accuracy")
        hist = out["logger"].history
        accs = [float(a) for a in hist.get("test/Global-Accuracy", [])]
        eval_epochs = list(range(k, rounds + 1, k))
        to_target = next((e for e, a in zip(eval_epochs, accs)
                          if a >= args.target), None)
        spec = resolve_schedule_cfg(cfg)
        us = superstep_user_schedule(exp.host_key, 1, rounds,
                                     cfg["num_users"], exp.num_active,
                                     schedule=spec)
        filled = (us >= 0).sum(axis=1)
        results[name] = {
            "final_acc": round(accs[-1], 2) if accs else None,
            "best_acc": round(max(accs), 2) if accs else None,
            "rounds_to_target": to_target,
            "target": args.target,
            "eval_accs": [round(a, 2) for a in accs],
            "participation": {
                "slots_per_round": int(exp.num_active),
                "mean_active": round(float(np.mean(filled)), 2),
                "min_active": int(filled.min()),
                "max_active": int(filled.max()),
            },
        }
        print(f"# {name}: final {results[name]['final_acc']} best "
              f"{results[name]['best_acc']} to-target "
              f"{results[name]['rounds_to_target']} mean-active "
              f"{results[name]['participation']['mean_active']}",
              file=sys.stderr, flush=True)
    rec = {"rounds": rounds, "superstep_rounds": k, "seed": 0,
           "pair": "synthetic MNIST conv[8,16] 1_10_0.5 a1-e1 fix",
           "scenarios": results}
    text = json.dumps(rec, indent=1)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
