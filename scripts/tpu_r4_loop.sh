#!/bin/bash
# Retry scripts/tpu_r4_session.py until the tunnel clears and the session
# completes (or attempts run out).  Exit 3 from the session = claim wedged
# (watchdog); other non-zero = fast failure (e.g. UNAVAILABLE from the
# relay).  Fast failures burn no claim budget, so space them out and keep
# trying for a whole working day rather than exhausting attempts in an hour.
LOG=${1:-/tmp/tpu_r4_session.log}
SLEEP=${TPU_RETRY_SLEEP:-600}
ATTEMPTS=${TPU_RETRY_ATTEMPTS:-60}
SLOW_BUDGET=${TPU_RETRY_SLOW_BUDGET:-6}   # attempts that burned a real claim
cd /root/repo
slow=0
TMP=$(mktemp)
trap 'rm -f "$TMP"' EXIT
for i in $(seq 1 "$ATTEMPTS"); do
  echo "=== r4 session attempt $i $(date -u +%H:%M:%S) ===" >> "$LOG"
  t0=$(date +%s)
  : > "$TMP"
  # tee keeps $LOG streaming live (a killed loop still leaves diagnostics)
  # while $TMP holds this attempt's output for the claimed-marker check
  timeout 7200 python -u scripts/tpu_r4_session.py 2>&1 | tee -a "$LOG" > "$TMP"
  rc=${PIPESTATUS[0]}
  dur=$(( $(date +%s) - t0 ))
  echo "=== attempt $i rc=$rc dur=${dur}s $(date -u +%H:%M:%S) ===" >> "$LOG"
  if [ "$rc" = "0" ]; then exit 0; fi
  # only attempts that actually CLAIMED the chip and then failed burn real
  # claim budget (a claim-stage hang, however long, held nothing); those
  # get a separate, smaller cap
  if grep -q "tpu_r4_session: claimed" "$TMP"; then
    slow=$((slow + 1))
    if [ "$slow" -ge "$SLOW_BUDGET" ]; then
      echo "=== r4 session: $slow claimed-then-failed attempts, stopping $(date -u +%H:%M:%S) ===" >> "$LOG"
      exit 2
    fi
  fi
  sleep "$SLEEP"
done
echo "=== r4 session gave up $(date -u +%H:%M:%S) ===" >> "$LOG"
exit 1
