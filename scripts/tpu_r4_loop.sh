#!/bin/bash
# Retry scripts/tpu_r4_session.py until the tunnel clears and the session
# completes (or attempts run out).  Exit 3 from the session = claim wedged.
LOG=${1:-/tmp/tpu_r4_session.log}
cd /root/repo
for i in $(seq 1 24); do
  echo "=== r4 session attempt $i $(date -u +%H:%M:%S) ===" >> "$LOG"
  timeout 7200 python -u scripts/tpu_r4_session.py >> "$LOG" 2>&1
  rc=$?
  echo "=== attempt $i rc=$rc $(date -u +%H:%M:%S) ===" >> "$LOG"
  if [ "$rc" = "0" ]; then exit 0; fi
  sleep 240
done
echo "=== r4 session gave up $(date -u +%H:%M:%S) ===" >> "$LOG"
exit 1
