#!/bin/bash
# Transformer/WikiText2 trajectory-parity runs (round 2): chained after the
# vision campaign (single-core box).  Both sides run in one invocation per
# seed; reference hyperparameters (SGD lr 0.1, batch_rows 100, ref
# utils.py:195-206) at reduced bptt for CPU budget.
set -u
cd /root/repo
# Wait for the vision campaign's sentinel, but never forever: if the chain
# upstream died without printing it, start anyway after the deadline (the LM
# runs are independent of the vision artifacts).
deadline=$(( $(date +%s) + ${PARITY_LM_WAIT_S:-28800} ))
while ! { [ -s /tmp/PARITY_MINE_MNIST_NONIID_S2.json ] \
          || grep -q ALL_MINE_DONE /tmp/parity_mine.log 2>/dev/null; }; do
  if [ "$(date +%s)" -ge "$deadline" ]; then
    echo "=== WAIT_TIMEOUT: starting LM runs without the vision sentinel ==="
    break
  fi
  sleep 60
done
for s in 0 1 2; do
  echo "=== WikiText2 transformer parity seed $s $(date -u +%H:%M:%S) ==="
  env -u PALLAS_AXON_POOL_IPS -u PALLAS_AXON_REMOTE_COMPILE -u AXON_LOOPBACK_RELAY \
    JAX_PLATFORMS=cpu JAX_COMPILATION_CACHE_DIR=/tmp/jaxcache PYTHONPATH=/root/repo \
    python -u -m heterofl_tpu.analysis.compare_reference \
      --model transformer --data WikiText2 --users 100 --frac 0.1 \
      --rounds 15 --n_train 100000 --n_test_tokens 20000 --batch_rows 100 \
      --bptt 32 --emb 64 --layers 2 --lr 0.1 --seed $s \
      --out /tmp/PARITY_LM_S$s.json 2>&1 | tail -1
done
echo "=== ALL_LM_DONE $(date -u +%H:%M:%S) ==="
