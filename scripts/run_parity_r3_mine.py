#!/usr/bin/env python
"""This-framework sides of the round-3 convergence-grade trajectory campaigns
(VERDICT r2 item 3), mirroring scripts/run_parity_r3_ref.sh run-for-run.
One process so a TPU run claims the tunnel once; on CPU set JAX_PLATFORMS=cpu
and a persistent JAX_COMPILATION_CACHE_DIR.

Usage: run_parity_r3_mine.py [mnist|cifar|modes]  (default: all, in the
pairing-priority order of parity_r4_specs.RUNS).  Finished artifacts are
skipped, so a killed campaign resumes where it left off.  On CPU hosts the
engine uses the im2col conv lowering (numerically equivalent, measured 3.7x
faster there -- MEASUREMENTS.md round 4).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from heterofl_tpu.analysis import compare_reference as cr
from parity_r4_specs import RUNS, run_one


def main():
    only = sys.argv[1] if len(sys.argv) > 1 else None
    # im2col is the CPU-host lowering (3.7x there, MEASUREMENTS.md round 4);
    # on a TPU host the default direct conv is the right one, so gate on the
    # platform jax actually selects (ADVICE r4).  default_backend() performs
    # the device claim, which this campaign process needs anyway.
    import jax

    extra = ("--conv_impl", "im2col") if jax.default_backend() == "cpu" else ()
    for family, name, args, out in RUNS:
        if only in (None, family):
            run_one(cr.main, name, args, out, extra_args=extra,
                    log=lambda m: print(m, flush=True))
    print("=== ALL_R3_MINE_DONE ===", flush=True)


if __name__ == "__main__":
    main()
