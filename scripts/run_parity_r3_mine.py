#!/usr/bin/env python
"""This-framework sides of the round-3 convergence-grade trajectory campaigns
(VERDICT r2 item 3), mirroring scripts/run_parity_r3_ref.sh run-for-run.
One process so a TPU run claims the tunnel once; on CPU set JAX_PLATFORMS=cpu
and a persistent JAX_COMPILATION_CACHE_DIR.

Usage: run_parity_r3_mine.py [mnist|cifar|modes]  (default: all, in
pairing-priority order).  Finished artifacts are skipped, so a killed
campaign resumes where it left off.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from heterofl_tpu.analysis import compare_reference as cr

MNIST_ARGS = ["--data", "MNIST", "--model", "conv", "--hidden", "64,128,256,512",
              "--users", "100", "--frac", "0.1", "--rounds", "100",
              "--local_epochs", "5", "--n_train", "2000", "--n_test", "1000",
              "--skip", "reference",
              "--conv_impl", "im2col"]
CIFAR_ARGS = ["--data", "CIFAR10", "--model", "resnet18", "--hidden", "64,128",
              "--users", "100", "--frac", "0.1", "--rounds", "100",
              "--local_epochs", "1", "--n_train", "2000", "--n_test", "1000",
              "--skip", "reference",
              "--conv_impl", "im2col"]

# the single source of run specs: (family, name, args, artifact path)
RUNS = []
for s in (0, 1, 2):
    # pairing-priority order for a slow CPU fallback: alternate families so
    # every finished run immediately pairs with an existing ref artifact
    RUNS.append(("mnist", f"MNIST conv non-iid mine seed {s}",
                 MNIST_ARGS + ["--split", "non-iid-2", "--seed", str(s)],
                 f"/tmp/PARITY_R3_MINE_MNIST_NONIID_S{s}.json"))
    RUNS.append(("cifar", f"CIFAR resnet18 mine seed {s}",
                 CIFAR_ARGS + ["--seed", str(s)],
                 f"/tmp/PARITY_R3_MINE_CIFAR_S{s}.json"))
RUNS += [
    ("modes", "MNIST dynamic a1-e1 mine",
     MNIST_ARGS + ["--model_split", "dynamic", "--mode", "a1-e1", "--seed", "0"],
     "/tmp/PARITY_R3_MINE_DYNAMIC_S0.json"),
    ("modes", "MNIST interp a1-b9 mine",
     MNIST_ARGS + ["--mode", "a1-b9", "--seed", "0"],
     "/tmp/PARITY_R3_MINE_INTERP_A1B9_S0.json"),
    ("modes", "MNIST interp a5-e5 mine",
     MNIST_ARGS + ["--mode", "a5-e5", "--seed", "0"],
     "/tmp/PARITY_R3_MINE_INTERP_A5E5_S0.json"),
]


def _run(name, args, out):
    if os.path.exists(out):
        print(f"=== skip {name} (artifact exists) ===", flush=True)
        return
    print(f"=== {name} ===", flush=True)
    cr.main(args + ["--out", out])


def main():
    only = sys.argv[1] if len(sys.argv) > 1 else None
    for family, name, args, out in RUNS:
        if only in (None, family):
            _run(name, args, out)
    print("=== ALL_R3_MINE_DONE ===", flush=True)


if __name__ == "__main__":
    main()
