#!/usr/bin/env python
"""TPU measurement battery for the round program (VERDICT r1 item 2).

Answers, with wall-clock numbers on real TPU hardware:
  1. masked vs sliced at the headline a1-b1-c1-d1-e1 mix -- the masked
     strategy runs every client at full width (~3.9x the FLOPs of true
     sliced sub-models); is it still faster than 5 per-level programs?
  2. bf16 vs f32 round time.
  3. width -> round-time curve (is the chip FLOPs-bound or latency-bound
     at these shapes?).
  4. vmapped-client-count -> round-time curve (occupancy headroom; informs
     slot padding waste under sharded placement).

Run on the TPU box: `python -u scripts/tpu_measure.py [--quick]`.
Prints one JSON line per measurement (incremental -- a wedge mid-battery
still leaves everything before it on stdout), plus a final summary line.
Never kill it mid-run: the tunnel is single-client and stale grants wedge it.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true", help="1 timed round each")
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--users", type=int, default=100)
    ap.add_argument("--n_train", type=int, default=50000)
    args = ap.parse_args()
    timed = 1 if args.quick else args.rounds

    import jax
    import jax.numpy as jnp

    from heterofl_tpu import config as C
    from heterofl_tpu.data import (fetch_dataset, label_split_masks, split_dataset,
                                   stack_client_shards)
    from heterofl_tpu.models import make_model
    from heterofl_tpu.parallel import RoundEngine, make_mesh

    platform = jax.devices()[0].platform
    print(json.dumps({"measure": "platform", "platform": platform,
                      "device_kind": jax.devices()[0].device_kind,
                      "n_devices": len(jax.devices())}), flush=True)

    def build_cfg(control, dtype="bfloat16"):
        cfg = C.default_cfg()
        cfg["control"] = C.parse_control_name(control)
        cfg["data_name"] = "CIFAR10"
        cfg["model_name"] = "resnet18"
        cfg["synthetic"] = True
        cfg["compute_dtype"] = dtype
        return C.process_control(cfg)

    users = args.users
    base = build_cfg(f"1_{users}_0.1_iid_fix_a1-b1-c1-d1-e1_bn_1_1")
    ds = fetch_dataset("CIFAR10", synthetic=True, seed=0,
                       synthetic_sizes={"train": args.n_train, "test": 1000})
    rng = np.random.default_rng(0)
    split, lsplit = split_dataset(ds, users, "iid", rng)
    x, y, m = stack_client_shards(ds["train"].data, ds["train"].target,
                                  split["train"], list(range(users)))
    lm = label_split_masks(lsplit, users, 10)
    data = (jnp.asarray(x), jnp.asarray(y), jnp.asarray(m), jnp.asarray(lm))
    n_active = int(np.ceil(base["frac"] * users))

    def time_masked(name, cfg, active=None, extra=None):
        cfg = dict(cfg)
        cfg["classes_size"] = 10
        model = make_model(cfg)
        params = model.init(jax.random.key(0))
        engine = RoundEngine(model, cfg, make_mesh(len(jax.devices()), 1))
        a = active if active is not None else n_active
        srng = np.random.default_rng(1)

        def once(params, r):
            uidx = srng.permutation(users)[:a].astype(np.int32)
            return engine.train_round(params, jax.random.key(r), 0.1, uidx, data)

        t0 = time.time()
        params, _ = once(params, 0)
        jax.block_until_ready(params)
        compile_s = time.time() - t0
        t0 = time.time()
        for r in range(1, timed + 1):
            params, ms = once(params, r)
        jax.block_until_ready(params)
        dt = (time.time() - t0) / timed
        rec = {"measure": name, "round_sec": round(dt, 4),
               "compile_sec": round(compile_s, 1), "active": a,
               **(extra or {})}
        print(json.dumps(rec), flush=True)
        return dt

    results = {}

    # 1a. masked, headline mix, bf16 (the bench configuration)
    results["masked_bf16"] = time_masked("masked_a1-e1_bf16", base)
    # 2. masked, f32
    results["masked_f32"] = time_masked(
        "masked_a1-e1_f32", build_cfg(f"1_{users}_0.1_iid_fix_a1-b1-c1-d1-e1_bn_1_1",
                                      "float32"))

    # 1b. sliced strategy, same mix, bf16: 5 per-level programs + host scatter
    # (MEASURE_SKIP_SLICED=1 skips it: ~25 min through the tunnel)
    if os.environ.get("MEASURE_SKIP_SLICED") == "1":
        print(json.dumps({"measure": "sliced_a1-e1_bf16", "skipped": True}), flush=True)
        results["sliced_bf16"] = float("nan")
    else:
        from heterofl_tpu.fed.sliced import SlicedFederation
        cfg_s = dict(base)
        cfg_s["classes_size"] = 10
        model = make_model(cfg_s)
        params = {k: np.asarray(v) for k, v in model.init(jax.random.key(0)).items()}
        sliced = SlicedFederation(cfg_s)
        fix_rates = np.asarray(cfg_s["model_rate"], np.float32)
        srng = np.random.default_rng(1)

        def sliced_once(params, r):
            uidx = srng.permutation(users)[:n_active].astype(np.int32)
            return sliced.train_round(params, uidx, fix_rates[uidx], data, 0.1,
                                      jax.random.key(r))

        t0 = time.time()
        params, _ = sliced_once(params, 0)
        compile_s = time.time() - t0
        t0 = time.time()
        for r in range(1, timed + 1):
            params, _ = sliced_once(params, r)
        dt = (time.time() - t0) / timed
        print(json.dumps({"measure": "sliced_a1-e1_bf16", "round_sec": round(dt, 4),
                          "compile_sec": round(compile_s, 1), "active": n_active}),
              flush=True)
        results["sliced_bf16"] = dt

    # 3. width -> time (homogeneous masked rounds; all clients one level)
    for mode, label in (("a1", "w1.0"), ("c1", "w0.25"), ("e1", "w0.0625")):
        results[f"width_{label}"] = time_masked(
            f"masked_homog_{label}_bf16",
            build_cfg(f"1_{users}_0.1_iid_fix_{mode}_bn_1_1"))

    # 4. active-client scaling at the headline mix
    for a in (1, 2, 5, 10, 20):
        results[f"clients_{a}"] = time_masked(f"masked_a1-e1_bf16_active{a}",
                                              base, active=a, extra={"sweep": "clients"})

    sliced_ratio = results["sliced_bf16"] / results["masked_bf16"]
    summary = {
        "measure": "summary",
        # null (valid JSON), not NaN, when the sliced leg was skipped
        "masked_vs_sliced_speedup": round(sliced_ratio, 2) if np.isfinite(sliced_ratio) else None,
        "bf16_vs_f32_speedup": round(results["masked_f32"] / results["masked_bf16"], 2),
        "width_ratio_w1_over_w116": round(results["width_w1.0"] / results["width_w0.0625"], 2),
        "rounds_per_sec_masked_bf16": round(1.0 / results["masked_bf16"], 3),
    }
    print(json.dumps(summary), flush=True)


if __name__ == "__main__":
    main()
