#!/usr/bin/env python
"""Merge the round-2 trajectory-parity artifacts (/tmp/PARITY_{REF,MINE}_*)
into the repo's PARITY_RUN_*.json files.

Each output file carries both trajectories plus the final-round gap; vision
gaps in accuracy points (mine - ref, positive = mine ahead), LM gaps in
perplexity (negative = mine ahead).  Run after the campaign scripts finish.
"""

import json
import os

PAIRS = [
    # (ref artifact, mine artifact, repo output, kind)
    *[(f"/tmp/PARITY_REF_CIFAR_S{s}.json", f"/tmp/PARITY_MINE_CIFAR_S{s}.json",
       f"PARITY_RUN_CIFAR_RESNET_S{s}.json", "acc") for s in (0, 1, 2)],
    *[(f"/tmp/PARITY_REF_MNIST_NONIID_S{s}.json", f"/tmp/PARITY_MINE_MNIST_NONIID_S{s}.json",
       f"PARITY_RUN_MNIST_NONIID_S{s}.json", "acc") for s in (0, 1, 2)],
    *[(f"/tmp/PARITY_LM_S{s}.json", None, f"PARITY_RUN_LM_S{s}.json", "ppl")
      for s in (0, 1, 2)],
]


def main():
    os.chdir(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    done = []
    for ref_p, mine_p, out_p, kind in PAIRS:
        if not os.path.exists(ref_p):
            print(f"skip {out_p}: missing {ref_p}")
            continue
        with open(ref_p) as f:
            ref = json.load(f)
        k = "reference_acc" if kind == "acc" else "reference_ppl"
        km = "mine_acc" if kind == "acc" else "mine_ppl"
        if mine_p is None:  # LM runs carry both sides in one artifact
            rep = ref
        else:
            if not os.path.exists(mine_p):
                print(f"skip {out_p}: missing {mine_p}")
                continue
            with open(mine_p) as f:
                mine = json.load(f)
            rep = {k: ref[k], km: mine[km]}
        if rep.get(k) and rep.get(km):
            gap_key = "final_gap_pp" if kind == "acc" else "final_gap_ppl"
            rep[gap_key] = round(rep[km][-1] - rep[k][-1], 2)
        with open(out_p, "w") as f:
            json.dump(rep, f)
        tail = {kk: ([round(v, 2) for v in vv[-3:]] if isinstance(vv, list) else vv)
                for kk, vv in rep.items()}
        print(f"{out_p}: {tail}")
        done.append(out_p)
    print(f"assembled {len(done)} files")


if __name__ == "__main__":
    main()
