"""Single source of truth for the round-3/4 mine-side campaign run specs.

Both campaign runners -- the CPU fallback (run_parity_r3_mine.py) and the
one-claim TPU session (tpu_r4_session.py) -- import RUNS and run_one from
here, so artifact names, seeds, and round counts can never desynchronize
between them.  Artifacts land in /tmp/PARITY_R3_MINE_*.json (written
atomically by compare_reference) and finished runs are skipped, so a killed
campaign resumes where it left off.
"""

import os

MNIST_ARGS = ["--data", "MNIST", "--model", "conv", "--hidden", "64,128,256,512",
              "--users", "100", "--frac", "0.1", "--rounds", "100",
              "--local_epochs", "5", "--n_train", "2000", "--n_test", "1000",
              "--skip", "reference"]
CIFAR_ARGS = ["--data", "CIFAR10", "--model", "resnet18", "--hidden", "64,128",
              "--users", "100", "--frac", "0.1", "--rounds", "100",
              "--local_epochs", "1", "--n_train", "2000", "--n_test", "1000",
              "--skip", "reference"]

# (family, name, args, artifact path) in pairing-priority order: families
# alternate so every finished run immediately pairs with an existing ref
# artifact even when a slow CPU fallback only gets through a prefix
RUNS = []
for _s in (0, 1, 2):
    RUNS.append(("mnist", f"MNIST conv non-iid mine seed {_s}",
                 MNIST_ARGS + ["--split", "non-iid-2", "--seed", str(_s)],
                 f"/tmp/PARITY_R3_MINE_MNIST_NONIID_S{_s}.json"))
    RUNS.append(("cifar", f"CIFAR resnet18 mine seed {_s}",
                 CIFAR_ARGS + ["--seed", str(_s)],
                 f"/tmp/PARITY_R3_MINE_CIFAR_S{_s}.json"))
RUNS += [
    ("modes", "MNIST dynamic a1-e1 mine",
     MNIST_ARGS + ["--model_split", "dynamic", "--mode", "a1-e1", "--seed", "0"],
     "/tmp/PARITY_R3_MINE_DYNAMIC_S0.json"),
    ("modes", "MNIST interp a1-b9 mine",
     MNIST_ARGS + ["--mode", "a1-b9", "--seed", "0"],
     "/tmp/PARITY_R3_MINE_INTERP_A1B9_S0.json"),
    ("modes", "MNIST interp a5-e5 mine",
     MNIST_ARGS + ["--mode", "a5-e5", "--seed", "0"],
     "/tmp/PARITY_R3_MINE_INTERP_A5E5_S0.json"),
]


def run_one(cr_main, name, args, out, extra_args=(), log=print):
    """Run one campaign through ``compare_reference.main`` unless its artifact
    already exists.  Returns True if the run executed."""
    if os.path.exists(out):
        log(f"=== skip {name} (artifact exists) ===")
        return False
    log(f"=== {name} ===")
    cr_main(list(args) + list(extra_args) + ["--out", out])
    return True
