#!/usr/bin/env python
"""Compiled-program FLOP account: masked vs rate-grouped engine at the
flagship config (VERDICT r4 item 1 'done' bar).

The round-4 roofline (MEASUREMENTS.md) derived ~72.7 TFLOP/round for the
masked strategy vs ~18.6 for ideal dense per-level execution analytically;
this script asks XLA itself via :func:`heterofl_tpu.staticcheck.audit.
flop_account` -- the SAME implementation the staticcheck FLOP-budget audit
runs, so there is one source of truth for the level FLOP numbers (the
analytic shares come from ``fed.core.level_flop_shares``, which also drives
the grouped engine's slices row allocation).  CPU-safe: nothing is
executed, only compiled.  Prints one JSON line; run under
JAX_PLATFORMS=cpu with the axon env scrubbed (see tests/conftest.py).

MFU column: set BENCH_PEAK_FLOPS (hardware peak in FLOP/s -- the SAME knob
bench.py's extra.mfu consumes, e.g. 2.75e14 for one v4 chip in bf16 x
devices) and the account gains `mfu`: the ideal round seconds at peak per
engine (flops / peak) and the per-engine `mfu_x_round_sec` factor -- divide
by a measured round time to get achieved utilisation, so the FLOP account
and the bench speak one unit.

Usage: [SMALL=1] [BENCH_PEAK_FLOPS=...] python scripts/grouped_flops.py
       (SMALL=1: test widths)
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from heterofl_tpu import config as C
from heterofl_tpu.data import fetch_dataset, label_split_masks, split_dataset, stack_client_shards
from heterofl_tpu.parallel import make_mesh
from heterofl_tpu.staticcheck.audit import flop_account


def main():
    small = os.environ.get("SMALL") == "1"
    users, n_train = (20, 2000) if small else (100, 50000)
    cfg = C.default_cfg()
    cfg["control"] = C.parse_control_name(f"1_{users}_0.1_iid_fix_a1-b1-c1-d1-e1_bn_1_1")
    cfg["data_name"], cfg["model_name"], cfg["synthetic"] = "CIFAR10", "resnet18", True
    cfg["compute_dtype"] = "bfloat16"
    cfg = C.process_control(cfg)
    if small:
        cfg["resnet"] = {"hidden_size": [8, 16, 16, 16]}
    cfg["classes_size"] = 10

    ds = fetch_dataset("CIFAR10", synthetic=True, seed=0,
                       synthetic_sizes={"train": n_train, "test": 100})
    rng = np.random.default_rng(0)
    split, lsplit = split_dataset(ds, users, "iid", rng)
    x, y, m = stack_client_shards(ds["train"].data, ds["train"].target,
                                  split["train"], list(range(users)))
    lm = label_split_masks(lsplit, users, 10)
    data = (x, y, m, lm)
    mesh = make_mesh(1, 1)

    # active set: the expected mix, 2 clients per level (fix-mode rate vector
    # is level-blocked: users [0..U/5) are level a, etc.)
    rates_vec = np.asarray(cfg["model_rate"], np.float64)
    user_idx = []
    for r in sorted(set(rates_vec), reverse=True):
        user_idx += list(np.where(rates_vec == r)[0][:2])
    user_idx = np.asarray(user_idx, np.int32)

    t0 = time.time()
    account = flop_account(cfg, data, mesh, user_idx, rates_vec[user_idx])
    mfu = None
    try:
        peak = float(os.environ.get("BENCH_PEAK_FLOPS") or 0) or None
    except ValueError:
        print(f"grouped_flops: ignoring malformed BENCH_PEAK_FLOPS="
              f"{os.environ['BENCH_PEAK_FLOPS']!r}", file=sys.stderr)
        peak = None
    if peak:
        # the FLOP-time floor per engine; divide by a MEASURED round time
        # to get achieved MFU (bench.py's extra.mfu does exactly that with
        # its own wall clock)
        mfu = {"peak_flops": peak,
               "ideal_round_sec_at_peak": {
                   "masked": account["masked_flops_per_round"] / peak,
                   "grouped": account["grouped_flops_per_round"] / peak},
               "note": "mfu = ideal_round_sec_at_peak / measured_round_sec"}
    print(json.dumps({
        "config": f"CIFAR10 resnet18 {cfg['resnet']['hidden_size']} "
                  f"{users}u/10a a1-e1, batch {cfg['batch_size']['train']}, "
                  f"local_epochs {cfg['num_epochs']['local']}, bf16",
        **account,
        **({"mfu": mfu} if mfu else {}),
        "compile_sec": round(time.time() - t0, 1),
    }), flush=True)


if __name__ == "__main__":
    main()
