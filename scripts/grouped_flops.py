#!/usr/bin/env python
"""Compiled-program FLOP account: masked vs rate-grouped engine at the
flagship config (VERDICT r4 item 1 'done' bar).

The round-4 roofline (MEASUREMENTS.md) derived ~72.7 TFLOP/round for the
masked strategy vs ~18.6 for ideal dense per-level execution analytically;
this script asks XLA itself: lower + compile both engines' round programs at
the BASELINE.json config (CIFAR10 ResNet-18, hidden [64,128,256,512],
100 users, 10 active, a1-b1-c1-d1-e1 -> 2 clients per level) and report
``compile().cost_analysis()`` FLOPs.  CPU-safe: nothing is executed, only
compiled.  Prints one JSON line; run under JAX_PLATFORMS=cpu with the axon
env scrubbed (see tests/conftest.py).

Usage: [SMALL=1] python scripts/grouped_flops.py   (SMALL=1: test widths)
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from heterofl_tpu import config as C
from heterofl_tpu.data import fetch_dataset, label_split_masks, split_dataset, stack_client_shards
from heterofl_tpu.models import make_model
from heterofl_tpu.analysis import cost_analysis_dict as _ca_dict
from heterofl_tpu.parallel import GroupedRoundEngine, RoundEngine, make_mesh


def main():
    small = os.environ.get("SMALL") == "1"
    users, n_train = (20, 2000) if small else (100, 50000)
    cfg = C.default_cfg()
    cfg["control"] = C.parse_control_name(f"1_{users}_0.1_iid_fix_a1-b1-c1-d1-e1_bn_1_1")
    cfg["data_name"], cfg["model_name"], cfg["synthetic"] = "CIFAR10", "resnet18", True
    cfg["compute_dtype"] = "bfloat16"
    cfg = C.process_control(cfg)
    if small:
        cfg["resnet"] = {"hidden_size": [8, 16, 16, 16]}
    cfg["classes_size"] = 10

    ds = fetch_dataset("CIFAR10", synthetic=True, seed=0,
                       synthetic_sizes={"train": n_train, "test": 100})
    rng = np.random.default_rng(0)
    split, lsplit = split_dataset(ds, users, "iid", rng)
    x, y, m = stack_client_shards(ds["train"].data, ds["train"].target,
                                  split["train"], list(range(users)))
    lm = label_split_masks(lsplit, users, 10)
    data = (jnp.asarray(x), jnp.asarray(y), jnp.asarray(m), jnp.asarray(lm))
    model = make_model(cfg)
    params = model.init(jax.random.key(0))
    mesh = make_mesh(1, 1)
    key, lr = jax.random.key(0), jnp.float32(0.1)

    # active set: the expected mix, 2 clients per level (fix-mode rate vector
    # is level-blocked: users [0..U/5) are level a, etc.)
    rates_vec = np.asarray(cfg["model_rate"], np.float64)
    user_idx = []
    for r in sorted(set(rates_vec), reverse=True):
        user_idx += list(np.where(rates_vec == r)[0][:2])
    user_idx = np.asarray(user_idx, np.int32)
    rates = rates_vec[user_idx]

    eng = RoundEngine(model, cfg, mesh)
    if eng._train is None:
        eng._train = eng._build_train()
    ug = jnp.asarray(user_idx)
    args = tuple(data) + ((jnp.asarray(eng.fix_rates),) if eng.fix_rates is not None else ())
    t0 = time.time()
    masked = _ca_dict(eng._train.lower(params, key, lr, ug, ug, *args).compile())
    t_masked = time.time() - t0
    print(f"masked compiled in {t_masked:.0f}s: {masked['flops']:.3e} flops",
          file=sys.stderr, flush=True)

    grp = GroupedRoundEngine(cfg, mesh)
    by = {}
    for pos, r in enumerate(rates):
        by.setdefault(float(r), []).append(pos)
    per_level = {}
    sums, cnts = [], []
    t0 = time.time()
    for r in sorted(by, reverse=True):
        u = jnp.asarray(user_idx[by[r]])
        prog = grp._level_prog(r, len(by[r]))
        ca = _ca_dict(prog.lower(params, key, lr, u, *data).compile())
        per_level[str(r)] = ca["flops"]
        print(f"level {r}: {ca['flops']:.3e} flops", file=sys.stderr, flush=True)
        # avals only (keeps the 'nothing is executed' contract): the combine
        # lowering needs shapes/dtypes of the level partials, not values
        s, c, _ = jax.eval_shape(prog, params, key, lr, u, *data)
        sums.append(s)
        cnts.append(c)
    combine = _ca_dict(grp._combine_prog(len(sums)).lower(params, sums, cnts).compile())
    t_grouped = time.time() - t0
    grouped_total = sum(per_level.values()) + combine["flops"]
    print(json.dumps({
        "config": f"CIFAR10 resnet18 {cfg['resnet']['hidden_size']} "
                  f"{users}u/10a a1-e1, batch {cfg['batch_size']['train']}, "
                  f"local_epochs {cfg['num_epochs']['local']}, bf16",
        "masked_flops_per_round": masked["flops"],
        "grouped_flops_per_round": grouped_total,
        "grouped_per_level_flops": per_level,
        "combine_flops": combine["flops"],
        "flop_ratio_masked_over_grouped": round(masked["flops"] / grouped_total, 3),
        "compile_sec": {"masked": round(t_masked, 1), "grouped": round(t_grouped, 1)},
    }), flush=True)


if __name__ == "__main__":
    main()
