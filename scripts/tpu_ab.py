#!/usr/bin/env python
"""A/B timings for the masked round program: where do the 20ms/step go?

Each variant disables ONE ingredient of the round step (augmentation, global
-norm clip, per-step gradient masking is load-bearing and not toggled, BN vs
no norm) and re-times the bench round.  Monkeypatched, not config-driven:
these are measurements, not features.  Run after/instead of tpu_measure.py
inside one TPU claim; prints one JSON line per variant.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp

    from heterofl_tpu import config as C
    from heterofl_tpu.data import (fetch_dataset, label_split_masks, split_dataset,
                                   stack_client_shards)
    from heterofl_tpu.models import make_model
    from heterofl_tpu.parallel import RoundEngine, make_mesh
    import heterofl_tpu.parallel.round_engine as re_mod

    users, n_train, timed = 100, 50000, 3
    print(json.dumps({"measure": "platform",
                      "platform": jax.devices()[0].platform,
                      "device_kind": jax.devices()[0].device_kind}), flush=True)

    ds = fetch_dataset("CIFAR10", synthetic=True, seed=0,
                       synthetic_sizes={"train": n_train, "test": 1000})
    rng = np.random.default_rng(0)
    split, lsplit = split_dataset(ds, users, "iid", rng)
    x, y, m = stack_client_shards(ds["train"].data, ds["train"].target,
                                  split["train"], list(range(users)))
    lm = label_split_masks(lsplit, users, 10)
    data = (jnp.asarray(x), jnp.asarray(y), jnp.asarray(m), jnp.asarray(lm))

    def run(name, norm="bn", dtype="bfloat16", augment=True, clip=True,
            pallas_norm=False, scan_unroll=1):
        cfg = C.default_cfg()
        cfg["control"] = C.parse_control_name(f"1_{users}_0.1_iid_fix_a1-b1-c1-d1-e1_{norm}_1_1")
        cfg["data_name"] = "CIFAR10"
        cfg["model_name"] = "resnet18"
        cfg["synthetic"] = True
        cfg["compute_dtype"] = dtype
        cfg = C.process_control(cfg)
        cfg["classes_size"] = 10
        cfg["pallas_norm"] = pallas_norm
        cfg["scan_unroll"] = scan_unroll

        orig_clip = re_mod.clip_by_global_norm
        orig_aug = re_mod.augment_cifar
        if not clip:
            re_mod.clip_by_global_norm = lambda g, c: (g, jnp.zeros(()))
        if not augment:
            re_mod.augment_cifar = lambda k, xx: xx
        try:
            model = make_model(cfg)
            params = model.init(jax.random.key(0))
            eng = RoundEngine(model, cfg, make_mesh(len(jax.devices()), 1))
            srng = np.random.default_rng(1)

            def once(p, r):
                uidx = srng.permutation(users)[:10].astype(np.int32)
                return eng.train_round(p, jax.random.key(r), 0.1, uidx, data)

            t0 = time.time()
            params, _ = once(params, 0)
            jax.block_until_ready(params)
            compile_s = time.time() - t0
            t0 = time.time()
            for r in range(1, timed + 1):
                params, ms = once(params, r)
            jax.block_until_ready(params)
            dt = (time.time() - t0) / timed
        finally:
            re_mod.clip_by_global_norm = orig_clip
            re_mod.augment_cifar = orig_aug
        print(json.dumps({"measure": name, "round_sec": round(dt, 4),
                          "ms_per_step": round(dt / 250 * 1000, 2),
                          "compile_sec": round(compile_s, 1)}), flush=True)
        return dt

    base = run("base_bf16_bn_aug_clip")

    # A/B one-pass (sum, sumsq) BN moments against the two-pass
    # mean-then-centered-var base (ops/layers.py:batch_norm): measured
    # perf-neutral (19.71 base vs 19.85 ms/step) -- XLA fusion makes the
    # second read ~free at these shapes -- so the numerically tighter
    # two-pass form is the product default.
    import heterofl_tpu.models.norms as norms_mod

    def batch_norm_one_pass(x, g, b, *, mode="batch", running=None,
                            sample_weight=None, eps=1e-5, axis_name=None):
        assert mode in ("batch", "collect") and axis_name is None
        axes = tuple(range(x.ndim - 1))
        if sample_weight is None:
            n = 1.0
            for a in axes:
                n *= x.shape[a]
            s1 = jnp.sum(x, axis=axes, keepdims=True)
            s2 = jnp.sum(x * x, axis=axes, keepdims=True)
            d = n
        else:
            w = jnp.broadcast_to(
                sample_weight.reshape((-1,) + (1,) * (x.ndim - 1)), x.shape)
            s1 = jnp.sum(x * w, axis=axes, keepdims=True)
            s2 = jnp.sum(w * x * x, axis=axes, keepdims=True)
            d = jnp.maximum(jnp.sum(w, axis=axes, keepdims=True), 1e-6)
        mean = s1 / d
        var = jnp.maximum(s2 / d - mean * mean, 0.0)
        y = (x - mean) / jnp.sqrt(var + eps) * g + b
        return y, None

    orig_bn = norms_mod.batch_norm
    norms_mod.batch_norm = batch_norm_one_pass
    try:
        run("bn_one_pass_moments")
    finally:
        norms_mod.batch_norm = orig_bn

    run("scan_unroll_2", scan_unroll=2)
    run("scan_unroll_4", scan_unroll=4)
    run("no_augment", augment=False)
    run("no_clip", clip=False)
    run("no_augment_no_clip", augment=False, clip=False)
    run("norm_none", norm="none")
    run("f32_all_on", dtype="float32")
    run("pallas_norm", pallas_norm=True)
    run("pallas_norm_f32", pallas_norm=True, dtype="float32")


if __name__ == "__main__":
    main()
