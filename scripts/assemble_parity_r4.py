#!/usr/bin/env python
"""Merge the round-3/4 convergence-campaign artifacts (/tmp/PARITY_R3_*)
into repo PARITY_R3_*.json files and print the mean±std curve summary that
PARITY.md quotes (VERDICT r3 item 2).

Each campaign ran one side at a time (--skip): REF files carry
``reference_acc``, MINE files carry ``mine_acc``.  The merged repo artifact
holds both full 100-round curves plus final-gap and curve-distance stats.
"""

import json
import os

import numpy as np

CAMPAIGNS = [
    # (name, ref /tmp stem, mine /tmp stem, seeds)
    ("MNIST_NONIID", "PARITY_R3_REF_MNIST_NONIID_S{s}", "PARITY_R3_MINE_MNIST_NONIID_S{s}", (0, 1, 2)),
    ("DYNAMIC", "PARITY_R3_REF_DYNAMIC_S{s}", "PARITY_R3_MINE_DYNAMIC_S{s}", (0,)),
    ("INTERP_A1B9", "PARITY_R3_REF_INTERP_A1B9_S{s}", "PARITY_R3_MINE_INTERP_A1B9_S{s}", (0,)),
    ("INTERP_A5E5", "PARITY_R3_REF_INTERP_A5E5_S{s}", "PARITY_R3_MINE_INTERP_A5E5_S{s}", (0,)),
    ("CIFAR", "PARITY_R3_REF_CIFAR_S{s}", "PARITY_R3_MINE_CIFAR_S{s}", (0, 1, 2)),
]


def main():
    os.chdir(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    summary = {}
    for name, ref_t, mine_t, seeds in CAMPAIGNS:
        finals_ref, finals_mine, gaps = [], [], []
        for s in seeds:
            ref_p = f"/tmp/{ref_t.format(s=s)}.json"
            mine_p = f"/tmp/{mine_t.format(s=s)}.json"
            if not (os.path.exists(ref_p) and os.path.exists(mine_p)):
                print(f"skip {name} S{s}: missing "
                      f"{[p for p in (ref_p, mine_p) if not os.path.exists(p)]}")
                continue
            with open(ref_p) as f:
                ref = json.load(f)["reference_acc"]
            with open(mine_p) as f:
                mine = json.load(f)["mine_acc"]
            if not ref or not mine:
                print(f"skip {name} S{s}: empty curve")
                continue
            n = min(len(ref), len(mine))
            ref, mine = ref[:n], mine[:n]
            curve_gap = [m - r for m, r in zip(mine, ref)]
            rep = {"reference_acc": ref, "mine_acc": mine,
                   "final_gap_pp": round(curve_gap[-1], 2),
                   "mean_abs_curve_gap_pp": round(float(np.mean(np.abs(curve_gap))), 2),
                   "rounds": n}
            out_p = f"PARITY_R3_{name}_S{s}.json"
            with open(out_p, "w") as f:
                json.dump(rep, f)
            print(f"{out_p}: ref_final={ref[-1]:.2f} mine_final={mine[-1]:.2f} "
                  f"gap={rep['final_gap_pp']:+.2f}pp mean|gap|={rep['mean_abs_curve_gap_pp']:.2f}pp")
            finals_ref.append(ref[-1])
            finals_mine.append(mine[-1])
            gaps.append(curve_gap)
        if finals_ref:
            # seeds can carry different round counts (a killed run truncates
            # its curve); the curve stat aligns to the shortest, but the
            # final gap is each seed's OWN last round so it always agrees
            # with the per-seed artifacts (ADVICE r4)
            n_min = min(len(r) for r in gaps)
            g = np.array([r[:n_min] for r in gaps])
            summary[name] = {
                "seeds": len(finals_ref),
                "ref_final": f"{np.mean(finals_ref):.2f}±{np.std(finals_ref):.2f}",
                "mine_final": f"{np.mean(finals_mine):.2f}±{np.std(finals_mine):.2f}",
                "final_gap_pp": f"{np.mean([r[-1] for r in gaps]):+.2f}",
                "mean_abs_curve_gap_pp": f"{np.mean(np.abs(g)):.2f} (aligned to {n_min} rounds)",
            }
    # ref-vs-ref seed-band calibration (VERDICT r4 item 4): the reference at
    # extra seeds 3-5 (scripts/run_parity_r5_ref_seeds.sh) vs the original
    # 0-2; mine's finals must sit inside the ref's own seed band for the
    # +4.5pp mean gap to be noise rather than a semantic divergence
    ref_finals, mine_finals = [], []
    for s in range(6):
        # /tmp is the fresh-campaign source; the repo-persisted copies (now
        # written by the CAMPAIGN script, run_parity_r5_ref_seeds.sh -- this
        # summarizer only reads) keep the band reproducible after a /tmp wipe
        cands = ([f"/tmp/PARITY_R3_REF_MNIST_NONIID_S{s}.json",
                  f"PARITY_R3_MNIST_NONIID_S{s}.json"] if s < 3
                 else [f"/tmp/PARITY_R5_REF_MNIST_NONIID_S{s}.json",
                       f"PARITY_R5_REF_MNIST_NONIID_S{s}.json"])
        for p in cands:
            if os.path.exists(p):
                with open(p) as f:
                    curve = json.load(f)["reference_acc"]
                if curve:
                    ref_finals.append((s, curve[-1]))
                break
    for s in range(3):
        for p in (f"/tmp/PARITY_R3_MINE_MNIST_NONIID_S{s}.json",
                  f"PARITY_R3_MNIST_NONIID_S{s}.json"):
            if os.path.exists(p):
                with open(p) as f:
                    curve = json.load(f)["mine_acc"]
                if curve:
                    mine_finals.append((s, curve[-1]))
                break
    if len(ref_finals) >= 4 and mine_finals:
        rf = [v for _, v in ref_finals]
        mf = [v for _, v in mine_finals]
        summary["NONIID_SEED_BAND"] = {
            "ref_finals": {f"S{s}": v for s, v in ref_finals},
            "mine_finals": {f"S{s}": v for s, v in mine_finals},
            "ref_band": f"[{min(rf):.1f}, {max(rf):.1f}] "
                        f"(mean {np.mean(rf):.2f} ± {np.std(rf):.2f})",
            "mine_mean": f"{np.mean(mf):.2f} ± {np.std(mf):.2f}",
            "mine_inside_ref_band": bool(min(rf) <= np.mean(mf) <= max(rf)),
        }
    print(json.dumps(summary, indent=1))
    # decile curve table for PARITY.md (mean across seeds at rounds 10..100)
    for name, ref_t, mine_t, seeds in CAMPAIGNS:
        rows_r, rows_m = [], []
        for s in seeds:
            out_p = f"PARITY_R3_{name}_S{s}.json"
            if not os.path.exists(out_p):
                continue
            with open(out_p) as f:
                d = json.load(f)
            rows_r.append(d["reference_acc"])
            rows_m.append(d["mine_acc"])
        if not rows_r:
            continue
        n = min(len(r) for r in rows_r + rows_m)
        rr = np.mean([r[:n] for r in rows_r], axis=0)
        mm = np.mean([m[:n] for m in rows_m], axis=0)
        idx = [i for i in range(max(0, n // 10 - 1), n, max(1, n // 10))]
        print(f"curve {name} rounds:    " + " ".join(f"{i+1:6d}" for i in idx))
        print(f"curve {name} ref mean:  " + " ".join(f"{rr[i]:6.2f}" for i in idx))
        print(f"curve {name} mine mean: " + " ".join(f"{mm[i]:6.2f}" for i in idx))


if __name__ == "__main__":
    main()
