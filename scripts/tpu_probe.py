#!/usr/bin/env python
"""Minimal TPU tunnel health probe: device init + one matmul, with timings.

Run in background with `python -u`; never kill it mid-claim (stale grants wedge
the single-client tunnel).
"""
import sys
import time

t0 = time.time()
print(f"[{time.strftime('%H:%M:%S')}] importing jax...", flush=True)
import jax
import jax.numpy as jnp

print(f"[{time.strftime('%H:%M:%S')}] jax {jax.__version__} imported "
      f"({time.time()-t0:.1f}s); calling jax.devices()...", flush=True)
t1 = time.time()
devs = jax.devices()
print(f"[{time.strftime('%H:%M:%S')}] devices ({time.time()-t1:.1f}s): "
      f"{[(d.platform, d.device_kind) for d in devs]}", flush=True)
t2 = time.time()
x = jnp.ones((1024, 1024), jnp.bfloat16)
y = (x @ x).block_until_ready()
print(f"[{time.strftime('%H:%M:%S')}] matmul ok ({time.time()-t2:.1f}s), "
      f"sum={float(jnp.sum(y.astype(jnp.float32)))}", flush=True)
print("PROBE_OK", flush=True)
