"""End-to-end entry-point tests: CLI -> experiment -> checkpoint -> test entry."""

import json
import os
import pickle

import numpy as np
import pytest

# end-to-end CLI experiments, several jit compiles each (fast gate excludes this module)
pytestmark = pytest.mark.slow


def _override(tmp, extra=None):
    ov = {
        "num_epochs": {"global": 2, "local": 1},
        "conv": {"hidden_size": [8, 16]},
        "transformer": {"embedding_size": 32, "num_heads": 4, "hidden_size": 64,
                        "num_layers": 2, "dropout": 0.0},
        "batch_size": {"train": 10, "test": 20},
    }
    ov.update(extra or {})
    return [
        "--synthetic", "1",
        "--synthetic_sizes", json.dumps({"train": 200, "test": 80}),
        "--output_dir", str(tmp),
        "--override", json.dumps(ov),
    ]


def test_train_classifier_fed_end_to_end(tmp_path):
    from heterofl_tpu.entry import train_classifier_fed, test_classifier_fed

    argv = ["--control_name", "1_8_0.5_iid_fix_a1-b1-c1-d1-e1_bn_1_1",
            "--data_name", "MNIST", "--model_name", "conv"] + _override(
                tmp_path, {"use_tensorboard": True})
    res = train_classifier_fed.main(argv)
    assert len(res) == 1
    hist = res[0]["logger"].history
    assert len(hist["test/Global-Accuracy"]) == 2
    # TB channel exercised through a real round (ref logger.py:57-84 writes
    # scalars+text every round); event files land beside the jsonl log
    run_dir = tmp_path / "runs" / "train_0_MNIST_label_conv_1_8_0.5_iid_fix_a1-b1-c1-d1-e1_bn_1_1"
    try:
        import torch.utils.tensorboard  # noqa: F401
        assert any(f.startswith("events.out.tfevents")
                   for f in os.listdir(run_dir)), os.listdir(run_dir)
    except ImportError:
        pass
    tag = "0_MNIST_label_conv_1_8_0.5_iid_fix_a1-b1-c1-d1-e1_bn_1_1"
    ck = tmp_path / "model" / f"{tag}_checkpoint.pkl"
    best = tmp_path / "model" / f"{tag}_best.pkl"
    assert ck.exists() and best.exists()
    # the test entry reproduces a result bundle from the best checkpoint
    out = test_classifier_fed.main(argv)
    bundle = tmp_path / "result" / f"{tag}.pkl"
    assert bundle.exists()
    with open(bundle, "rb") as f:
        result = pickle.load(f)
    assert "test/Global-Accuracy" in result["logger_history"]


def test_train_fed_sharded_placement(tmp_path):
    """The full fed entry with cfg data_placement=sharded trains, evaluates
    and checkpoints like the replicated default."""
    from heterofl_tpu.entry import train_classifier_fed

    argv = ["--control_name", "1_8_0.5_iid_fix_a1-b1_bn_1_1",
            "--data_name", "MNIST", "--model_name", "conv"] \
        + _override(tmp_path, {"data_placement": "sharded"})
    res = train_classifier_fed.main(argv)
    hist = res[0]["logger"].history
    assert len(hist["test/Global-Accuracy"]) == 2
    assert np.isfinite(hist["train/Local-Loss"]).all()


def test_train_fed_grouped_strategy(tmp_path):
    """The full fed entry with cfg strategy=grouped (rate-grouped dense
    per-level programs on the mesh) trains, evaluates and checkpoints like
    the masked default."""
    from heterofl_tpu.entry import train_classifier_fed

    argv = ["--control_name", "1_8_0.5_iid_fix_a1-b1-c1_bn_1_1",
            "--data_name", "MNIST", "--model_name", "conv"] \
        + _override(tmp_path, {"strategy": "grouped"})
    res = train_classifier_fed.main(argv)
    hist = res[0]["logger"].history
    assert len(hist["test/Global-Accuracy"]) == 2
    assert np.isfinite(hist["train/Local-Loss"]).all()


def test_resume_modes(tmp_path):
    from heterofl_tpu.entry import train_classifier_fed

    argv = ["--control_name", "1_4_0.5_iid_fix_a1_bn_1_1",
            "--data_name", "MNIST", "--model_name", "conv"] + _override(tmp_path)
    train_classifier_fed.main(argv)
    # resume_mode 1: continues from stored epoch (3 > 2 rounds -> no new rounds)
    res = train_classifier_fed.main(argv + ["--resume_mode", "1"])
    assert res[0]["params"] is not None
    # resume_mode 2: weights+splits only, reruns rounds 1..2
    res2 = train_classifier_fed.main(argv + ["--resume_mode", "2"])
    assert len(res2[0]["logger"].history["test/Global-Accuracy"]) == 2


def test_resume_logger_fidelity(tmp_path):
    """Resume-mode 1 restores the FULL logger state (running means, counters,
    TB step counters, history), not just history -- matching the reference,
    which pickles the whole Logger into the checkpoint (ref
    utils.py:302-312)."""
    from heterofl_tpu.entry import train_classifier_fed
    from heterofl_tpu.utils import load_checkpoint

    argv = ["--control_name", "1_4_0.5_iid_fix_a1_bn_1_1",
            "--data_name", "MNIST", "--model_name", "conv"] + _override(tmp_path)
    train_classifier_fed.main(argv)
    tag = "0_MNIST_label_conv_1_4_0.5_iid_fix_a1_bn_1_1"
    blob = load_checkpoint(str(tmp_path / "model" / f"{tag}_checkpoint.pkl"))
    st = blob["logger_state"]
    # pre-reset snapshot (iterator only counts with a live TB writer)
    assert st["counter"] and st["mean"]
    assert len(st["history"]["test/Global-Accuracy"]) == 2
    # a resumed run (no rounds left) carries the state forward verbatim
    res = train_classifier_fed.main(argv + ["--resume_mode", "1"])
    lg = res[0]["logger"]
    assert dict(lg.counter) == st["counter"]
    assert dict(lg.mean) == st["mean"]
    assert dict(lg.iterator) == st["iterator"]
    assert {k: list(v) for k, v in lg.history.items()} == st["history"]


def test_train_transformer_fed_end_to_end(tmp_path):
    from heterofl_tpu.entry import train_transformer_fed

    argv = ["--control_name", "1_4_0.5_iid_fix_a1-b1_bn_1_1",
            "--data_name", "WikiText2", "--model_name", "transformer"] + _override(
        tmp_path, {"bptt": 16, "batch_size": {"train": 4, "test": 2}})
    res = train_transformer_fed.main(argv)
    hist = res[0]["logger"].history
    assert len(hist["test/Global-Perplexity"]) == 2
    assert np.isfinite(hist["test/Global-Perplexity"]).all()


def test_train_classifier_central(tmp_path):
    from heterofl_tpu.entry import train_classifier, test_classifier

    argv = ["--control_name", "1_1_1_none_fix_a1_bn_1_1",
            "--data_name", "MNIST", "--model_name", "conv"] + _override(
        tmp_path, {"num_epochs": 2, "batch_size": {"train": 40, "test": 40}})
    res = train_classifier.main(argv)
    hist = res[0]["logger"].history
    assert len(hist["test/Accuracy"]) == 2
    out = test_classifier.main(argv)
    assert "Accuracy" in out[0]["metrics"]


def test_train_transformer_central(tmp_path):
    from heterofl_tpu.entry import train_transformer, test_transformer

    argv = ["--control_name", "1_1_1_none_fix_a1_bn_1_1",
            "--data_name", "WikiText2", "--model_name", "transformer"] + _override(
        tmp_path, {"num_epochs": 2, "bptt": 16,
                   "batch_size": {"train": 4, "test": 2}})
    res = train_transformer.main(argv)
    hist = res[0]["logger"].history
    assert len(hist["test/Perplexity"]) == 2
    assert np.isfinite(hist["test/Perplexity"]).all()
    out = test_transformer.main(argv)
    assert "Perplexity" in out[0]["metrics"]
