"""Fault-tolerant runtime (ISSUE 15): chaos harness, durable generational
checkpoints, watchdog auto-rollback and in-program client quarantine.

Contracts under test:

* ``quarantine='off'`` (the default) and all-clean updates under
  ``quarantine='on'`` are BIT-IDENTICAL to the pre-quarantine engines
  across masked x {replicated, sharded} / grouped span x K in {1, 8} --
  the gate is a pure observer until an update is actually poisoned.
* a NaN-poisoned client update (``cfg['chaos_poison']``) is quarantined
  in-program: finite final params, a zero-count participant, and the
  ``quarantined`` counter riding the probe record; un-gated the same
  poison reaches the globals (the watchdog-rollback drill's trigger).
* every checkpoint write is durable + checksummed: corruption (bit-flip
  or truncation) raises the typed :class:`CheckpointCorruptError`,
  ``resume`` falls back generation-by-generation to the newest verifying
  blob, and rotation keeps exactly ``checkpoint_keep`` generations.
* the chaos drill's recovery contract holds: for every named kill point
  the resumed run's final params are bitwise equal to the uninterrupted
  run's (fast subset here; the full kill matrix is slow-marked), and a
  NaN-poisoned run under ``action='rollback'`` completes without human
  intervention, leaving the trip instant as the last on-disk event
  before each rollback's recovery record.
"""

import json
import os
import pickle

import jax
import numpy as np
import pytest

from heterofl_tpu import config as C
from heterofl_tpu.chaos import (ChaosKill, FaultInjector, corrupt_blob,
                                resolve_fault_plan, resolve_poison_cfg)
from heterofl_tpu.fed.core import (superstep_rate_schedule,
                                   superstep_user_schedule)
from heterofl_tpu.models import make_model
from heterofl_tpu.obs import resolve_quarantine_cfg, split_probes
from heterofl_tpu.parallel import GroupedRoundEngine, RoundEngine, make_mesh
from heterofl_tpu.utils.checkpoint import (CheckpointCorruptError,
                                           checkpoint_path, copy_best,
                                           generation_path, generation_paths,
                                           load_checkpoint,
                                           load_newest_verifying, resume,
                                           save_checkpoint)

from test_obs import _metrics_equal, _params_equal
from test_round import _vision_setup

HOST_KEY = jax.random.key(0)


# ---------------------------------------------------------------------------
# config validation: quarantine / fault plans / poison tables
# ---------------------------------------------------------------------------

def test_quarantine_config_validation():
    assert not resolve_quarantine_cfg({"quarantine": "off"}).enabled
    assert not resolve_quarantine_cfg({}).enabled
    on = resolve_quarantine_cfg({"quarantine": "on"})
    assert on.enabled and on.max_norm is None
    nm = resolve_quarantine_cfg({"quarantine": {"max_norm": 2.5}})
    assert nm.enabled and nm.max_norm == 2.5
    for bad in ("loud", {"max_norm": -1.0}, {"max_norm": True},
                {"bogus": 1}, 7):
        with pytest.raises(ValueError):
            resolve_quarantine_cfg({"quarantine": bad})


def test_poison_table_validation():
    assert resolve_poison_cfg({}) is None
    t = resolve_poison_cfg({"chaos_poison": [[3, 1], [4, 0]]})
    assert t.dtype == np.int32 and t.shape == (2, 2)
    for bad in ([], [[1]], [[1, 2, 3]], [[-1, 0]], [[1, -2]], [[1.5, 0]],
                [[True, 0]], "3,1"):
        with pytest.raises(ValueError):
            resolve_poison_cfg({"chaos_poison": bad})


def test_fault_plan_validation():
    plan = resolve_fault_plan({"kills": [{"point": "fetch", "at": 2},
                                         {"point": "fetch", "at": 4}],
                               "corrupt": [{"which": "best",
                                            "mode": "truncate",
                                            "generation": 1}],
                               "poison": [[2, 5]]})
    assert plan.kills == {"fetch": [2, 4]} and plan.n_kills == 2
    assert plan.corrupt[0]["mode"] == "truncate"
    assert plan.poison.shape == (1, 2)
    for bad in ("x", {"bogus": []}, {"kills": [{"point": "nope"}]},
                {"kills": [{"point": "fetch", "at": 0}]},
                {"corrupt": [{"which": "live"}]},
                {"corrupt": [{"mode": "scramble"}]},
                {"corrupt": [{"generation": -1}]}):
        with pytest.raises(ValueError):
            resolve_fault_plan(bad)


def test_fault_injector_counts_and_kills():
    inj = FaultInjector(resolve_fault_plan(
        {"kills": [{"point": "superstep", "at": 2}]}))
    inj.check("superstep")  # occurrence 1: survives
    with pytest.raises(ChaosKill) as e:
        inj.check("superstep")
    assert e.value.point == "superstep" and e.value.occurrence == 2
    assert inj.fired == [("superstep", 2)]
    assert not issubclass(ChaosKill, Exception)  # uncatchable by recovery
    with pytest.raises(ValueError):
        inj.check("reboot")


# ---------------------------------------------------------------------------
# durable generational checkpoints
# ---------------------------------------------------------------------------

def _blob(epoch, val=0.0):
    return {"epoch": epoch, "params": {"w": np.full(64, val, np.float32)}}


def test_checkpoint_corruption_raises_typed(tmp_path):
    path = checkpoint_path(str(tmp_path), "tag")
    save_checkpoint(path, _blob(1))
    assert load_checkpoint(path)["epoch"] == 1
    raw = open(path, "rb").read()
    # bit-flip deep in the payload: the checksum must catch it
    corrupt_blob(path, "flip")
    with pytest.raises(CheckpointCorruptError, match="SHA-256"):
        load_checkpoint(path)
    open(path, "wb").write(raw)
    corrupt_blob(path, "truncate")
    with pytest.raises(CheckpointCorruptError):
        load_checkpoint(path)
    os.remove(path)
    with pytest.raises(FileNotFoundError):
        load_checkpoint(path)


def test_checkpoint_legacy_headerless_blob(tmp_path):
    # pre-ISSUE-15 blobs are raw pickles: still loadable, and an
    # unpickling failure maps onto the typed error (satellite: the bare
    # pickle.load no longer leaks raw tracebacks)
    path = checkpoint_path(str(tmp_path), "tag")
    os.makedirs(os.path.dirname(path))
    with open(path, "wb") as f:
        pickle.dump(_blob(7), f)
    assert load_checkpoint(path)["epoch"] == 7
    with open(path, "wb") as f:
        f.write(b"not a pickle at all")
    with pytest.raises(CheckpointCorruptError, match="unpickling"):
        load_checkpoint(path)


def test_checkpoint_rotation_keeps_generations(tmp_path):
    path = checkpoint_path(str(tmp_path), "tag")
    for e in range(1, 6):
        save_checkpoint(path, _blob(e, float(e)), keep=3)
    gens = generation_paths(path)
    assert [os.path.basename(p) for p in gens] == [
        "tag_checkpoint.pkl", "tag_checkpoint.pkl.g1",
        "tag_checkpoint.pkl.g2"]
    assert [load_checkpoint(p)["epoch"] for p in gens] == [5, 4, 3]
    # keep=1 (the seed behaviour): no rotated generations ever appear
    p1 = checkpoint_path(str(tmp_path), "solo")
    for e in range(1, 4):
        save_checkpoint(p1, _blob(e), keep=1)
    assert generation_paths(p1) == [p1]
    assert load_checkpoint(p1)["epoch"] == 3


def test_generation_walk_tolerates_rotation_gap(tmp_path):
    # a crash between _rotate's renames can leave {live, .g2} with no
    # .g1: the fallback walk must still reach the older verifying blob
    out = str(tmp_path)
    path = checkpoint_path(out, "tag")
    for e in (1, 2, 3):
        save_checkpoint(path, _blob(e, float(e)), keep=3)
    os.remove(generation_path(path, 1))  # the gap
    assert [load_checkpoint(p)["epoch"] for p in generation_paths(path)] \
        == [3, 1]
    corrupt_blob(path, "flip")
    with pytest.warns(UserWarning, match="checkpoint-corrupt"):
        blob = resume(out, "tag", mode=1)
    assert blob["epoch"] == 1  # crossed the gap to .g2


def test_resume_falls_back_a_generation_loudly(tmp_path):
    out = str(tmp_path)
    path = checkpoint_path(out, "tag")
    for e in (1, 2, 3):
        save_checkpoint(path, _blob(e, float(e)), keep=3)
    corrupt_blob(path, "flip")
    with pytest.warns(UserWarning, match="checkpoint-corrupt"):
        blob = resume(out, "tag", mode=1)
    assert blob["epoch"] == 2  # newest VERIFYING generation
    # every generation corrupt -> typed error, never a silent fresh start
    corrupt_blob(generation_path(path, 1), "truncate")
    corrupt_blob(generation_path(path, 2), "flip")
    with pytest.raises(CheckpointCorruptError, match="refusing"):
        with pytest.warns(UserWarning):
            resume(out, "tag", mode=1)
    # absent is still a clean fresh start, not an error
    assert resume(out, "ghost", mode=1) is None
    assert load_newest_verifying(checkpoint_path(out, "ghost")) is None


def test_copy_best_is_durable_and_checksummed(tmp_path):
    out = str(tmp_path)
    save_checkpoint(checkpoint_path(out, "tag"), _blob(4, 1.5))
    copy_best(out, "tag")
    best = load_checkpoint(checkpoint_path(out, "tag", "best"))
    assert best["epoch"] == 4
    # the copy carries the checksum header: corruption is detected
    corrupt_blob(checkpoint_path(out, "tag", "best"), "flip")
    with pytest.raises(CheckpointCorruptError):
        load_checkpoint(checkpoint_path(out, "tag", "best"))
    # no stray tmp file survives the write
    assert not any(f.endswith(".tmp") for f in os.listdir(
        os.path.join(out, "model")))


# ---------------------------------------------------------------------------
# quarantine bit-identity: off == on when every update is clean
# ---------------------------------------------------------------------------

def test_masked_k1_quarantine_on_off_bit_identical():
    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    mesh = make_mesh(4, 1)
    uidx = np.array([0, 2, 4, 6])
    results = {}
    for q in ("off", "on"):
        eng = RoundEngine(model, dict(cfg, quarantine=q), mesh)
        p = model.init(jax.random.key(0))
        p, ms = eng.train_round(p, jax.random.key(1), 0.05, uidx, data)
        results[q] = (p, {k: np.asarray(v) for k, v in ms.items()})
    p_off, ms_off = results["off"]
    p_on, ms_on = results["on"]
    _params_equal(p_off, p_on)
    assert not any(k.startswith("obs_") for k in ms_off)
    clean, probes = split_probes(ms_on, 4)
    assert probes[0]["quarantined"] == 0
    for name in ms_off:
        np.testing.assert_array_equal(ms_off[name], clean[name], err_msg=name)


@pytest.mark.parametrize("q", [
    "on",
    pytest.param({"max_norm": 1e6}, marks=pytest.mark.slow),
])
def test_masked_superstep_quarantine_on_off_bit_identical(q):
    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    mesh = make_mesh(4, 1)
    k = 8
    outs = {}
    for mode in ("off", q):
        eng = RoundEngine(model, dict(cfg, quarantine=mode), mesh)
        p = model.init(jax.random.key(0))
        p, pending = eng.train_superstep(p, HOST_KEY, 1, k, data,
                                         num_active=4)
        outs[str(mode)] = (p, pending.fetch())
    _params_equal(outs["off"][0], outs[str(q)][0])
    _metrics_equal(outs["off"][1], outs[str(q)][1], k)
    probes = outs[str(q)][1]["obs"]
    assert len(probes) == k
    assert all(rec["quarantined"] == 0 for rec in probes)


@pytest.mark.slow
@pytest.mark.parametrize("placement,k", [("span", 8), ("span", 1),
                                         ("slices", 8)])
def test_grouped_quarantine_on_off_bit_identical(placement, k):
    cfg, ds, data = _vision_setup()
    mesh = make_mesh(8, 1)  # slices needs >= 5 device rows (one per level)
    model = make_model(cfg)
    users = cfg["num_users"]
    sched = superstep_user_schedule(HOST_KEY, 1, k, users, users)
    rates = superstep_rate_schedule(HOST_KEY, 1, k, cfg, sched)
    outs = {}
    for q in ("off", "on"):
        grp = GroupedRoundEngine(dict(cfg, level_placement=placement,
                                      quarantine=q), mesh)
        p = model.init(jax.random.key(0))
        p, pending = grp.train_superstep(p, HOST_KEY, 1, k, sched, rates,
                                         data)
        outs[q] = (p, pending.fetch())
    _params_equal(outs["off"][0], outs["on"][0])
    _metrics_equal(outs["off"][1], outs["on"][1], k)
    probes = outs["on"][1]["obs"]
    assert all(rec["quarantined"] == 0 for rec in probes)


# ---------------------------------------------------------------------------
# poisoned updates: quarantined in-program, or poisoning the globals un-gated
# ---------------------------------------------------------------------------

def test_masked_k1_poison_quarantined():
    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    mesh = make_mesh(4, 1)
    uidx = np.array([0, 2, 4, 6])
    poison = [[1, 2]]  # round 1, uid 2 (slot 1 of the cohort)
    # un-gated: the poison reaches the globals through the psum
    bad = RoundEngine(model, dict(cfg, chaos_poison=poison), mesh)
    p = model.init(jax.random.key(0))
    p_bad, _ = bad.train_round(p, jax.random.key(1), 0.05, uidx, data,
                               epoch=1)
    assert not all(bool(np.all(np.isfinite(np.asarray(v))))
                   for v in p_bad.values())
    # gated: finite params, zero-count participant, counted probe
    eng = RoundEngine(model, dict(cfg, quarantine="on",
                                  chaos_poison=poison), mesh)
    clean = RoundEngine(model, cfg, mesh)
    p0 = model.init(jax.random.key(0))
    p_q, ms_q = eng.train_round(p0, jax.random.key(1), 0.05, uidx, data,
                                epoch=1)
    assert all(bool(np.all(np.isfinite(np.asarray(v))))
               for v in p_q.values())
    ms_q, probes = split_probes({k: np.asarray(v) for k, v in ms_q.items()},
                                4)
    assert probes[0]["quarantined"] == 1
    assert float(ms_q["n"][1]) == 0.0 and float(ms_q["rate"][1]) == 0.0
    # a non-poisoned round of the same engine is bit-identical to clean
    p1, _ = eng.train_round(model.init(jax.random.key(0)),
                            jax.random.key(1), 0.05, uidx, data, epoch=2)
    p2, _ = clean.train_round(model.init(jax.random.key(0)),
                              jax.random.key(1), 0.05, uidx, data)
    _params_equal(p1, p2)


@pytest.mark.slow
def test_masked_superstep_poison_quarantined():
    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    mesh = make_mesh(4, 1)
    k = 4
    sched = np.asarray(superstep_user_schedule(HOST_KEY, 1, k,
                                               cfg["num_users"], 4))
    uid = int(sched[2][0])  # poison a drawn (round 3, uid) update
    eng = RoundEngine(model, dict(cfg, quarantine="on",
                                  chaos_poison=[[3, uid]]), mesh)
    p = model.init(jax.random.key(0))
    p, pending = eng.train_superstep(p, HOST_KEY, 1, k, data, num_active=4)
    out = pending.fetch()
    assert all(bool(np.all(np.isfinite(np.asarray(v))))
               for v in p.values())
    probes = out["obs"]
    assert [rec["quarantined"] for rec in probes] == [0, 0, 1, 0]


@pytest.mark.slow
def test_grouped_span_superstep_poison_quarantined():
    cfg, ds, data = _vision_setup()
    mesh = make_mesh(8, 1)
    model = make_model(cfg)
    users = cfg["num_users"]
    k = 4
    sched = np.asarray(superstep_user_schedule(HOST_KEY, 1, k, users, users))
    rates = superstep_rate_schedule(HOST_KEY, 1, k, cfg, sched)
    uid = int(sched[1][0])
    grp = GroupedRoundEngine(dict(cfg, quarantine="on",
                                  chaos_poison=[[2, uid]]), mesh)
    p = model.init(jax.random.key(0))
    p, pending = grp.train_superstep(p, HOST_KEY, 1, k, sched, rates, data)
    out = pending.fetch()
    assert all(bool(np.all(np.isfinite(np.asarray(v))))
               for v in p.values())
    assert [rec["quarantined"] for rec in out["obs"]] == [0, 1, 0, 0]


def test_max_norm_gate_quarantines_outlier():
    # a tiny norm bound quarantines EVERY update: counts go zero and the
    # counted average keeps the previous globals (stale fallback)
    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    mesh = make_mesh(4, 1)
    uidx = np.array([0, 2, 4, 6])
    eng = RoundEngine(model, dict(cfg, quarantine={"max_norm": 1e-12}),
                      mesh)
    p0 = model.init(jax.random.key(0))
    p0_host = {k: np.asarray(v) for k, v in p0.items()}  # p0 is donated
    p1, ms = eng.train_round(p0, jax.random.key(1), 0.05, uidx, data)
    _, probes = split_probes({k: np.asarray(v) for k, v in ms.items()}, 4)
    assert probes[0]["quarantined"] == 4
    _params_equal(p0_host, p1)


# ---------------------------------------------------------------------------
# driver-level recovery: rollback completes, artifacts are durable
# ---------------------------------------------------------------------------

def _read_log(cfg, tag):
    path = os.path.join(cfg["output_dir"], "runs", f"train_{tag}",
                        "log.jsonl")
    return [json.loads(line) for line in open(path)]


def test_driver_rollback_recovers_from_poison(tmp_path):
    from heterofl_tpu.chaos.drill import drill_cfg, pick_poison_uid
    from heterofl_tpu.entry.common import FedExperiment

    base = drill_cfg(str(tmp_path))
    uid = pick_poison_uid(base, 0, 3)
    assert uid is not None
    trace_dir = str(tmp_path / "trace")
    cfg = drill_cfg(str(tmp_path), chaos_poison=[[3, int(uid)]],
                    telemetry="on", trace_dir=trace_dir, ledger="on",
                    watchdog={"action": "rollback", "max_retries": 3,
                              "backoff": 0.0})
    exp = FedExperiment(cfg, 0)
    with pytest.warns(UserWarning, match="rollback attempt"):
        res = exp.run("Global-Accuracy")
    assert all(bool(np.all(np.isfinite(np.asarray(v))))
               for v in res["params"].values())
    log = _read_log(cfg, exp.tag)
    trips = [i for i, r in enumerate(log) if r.get("tag") == "obs"
             and r.get("event") == "watchdog"]
    recs = [i for i, r in enumerate(log) if r.get("tag") == "recovery"]
    assert len(trips) == 1 and len(recs) == 1
    # durability parity (satellite): the trip instant is on disk BEFORE
    # the recovery record -- the last pre-rollback event is the trip
    assert trips[0] < recs[0]
    assert log[recs[0]]["attempt"] == 1
    assert log[recs[0]]["restored_epoch"] is not None
    # the budget re-armed on the clean post-recovery checkpoint
    assert exp._rollback_attempts == 0
    # the abort path's artifacts, on the ROLLBACK path too (satellite):
    # events.jsonl carries the watchdog trip instant before the recovery
    # instant, and ledger.npz was snapshotted
    events = [json.loads(l) for l in
              open(os.path.join(trace_dir, exp.tag, "events.jsonl"))]
    names = [e.get("name") for e in events]
    assert "watchdog" in names and "recovery" in names
    assert names.index("watchdog") < names.index("recovery")
    assert os.path.exists(os.path.join(trace_dir, exp.tag, "ledger.npz"))


def test_rollback_blob_rejects_nonfinite_carries(tmp_path):
    # a checksum-clean generation whose params are finite but whose
    # restored CARRY holds the NaN must fall back a generation -- else
    # the retry budget burns on one poisoned blob
    from heterofl_tpu.chaos.drill import drill_cfg
    from heterofl_tpu.entry.common import FedExperiment

    cfg = drill_cfg(str(tmp_path))
    exp = FedExperiment(cfg, 0)
    path = checkpoint_path(cfg["output_dir"], exp.tag)
    good = {"epoch": 2, "params": {"w": np.ones(4, np.float32)},
            "sched_buf": None}
    bad = {"epoch": 3, "params": {"w": np.ones(4, np.float32)},
           "sched_buf": np.full((2, 4), np.nan, np.float32)}
    save_checkpoint(path, good, keep=3)
    save_checkpoint(path, bad, keep=3)
    with pytest.warns(UserWarning, match="non-finite params or carries"):
        blob = exp._load_rollback_blob()
    assert blob["epoch"] == 2


def test_driver_rollback_recovers_trip_from_final_drain(tmp_path):
    # metrics_fetch_every == K defers each superstep's fetch by one push:
    # a poison in the LAST superstep only surfaces at the post-loop
    # drain, which must roll back and re-enter the round loop instead of
    # degrading to an abort
    from heterofl_tpu.chaos.drill import drill_cfg, pick_poison_uid
    from heterofl_tpu.entry.common import FedExperiment

    base = drill_cfg(str(tmp_path))
    uid = pick_poison_uid(base, 0, 4)
    assert uid is not None
    cfg = drill_cfg(str(tmp_path), chaos_poison=[[4, int(uid)]],
                    telemetry="on", metrics_fetch_every=2,
                    eval_interval=5,  # no eval boundary flushes the defer
                    watchdog={"action": "rollback", "max_retries": 3,
                              "backoff": 0.0})
    exp = FedExperiment(cfg, 0)
    with pytest.warns(UserWarning, match="rollback attempt"):
        res = exp.run("Global-Accuracy")
    assert all(bool(np.all(np.isfinite(np.asarray(v))))
               for v in res["params"].values())
    log = _read_log(cfg, exp.tag)
    assert sum(1 for r in log if r.get("tag") == "recovery") >= 1


def test_driver_rollback_budget_escalates_to_abort(tmp_path):
    from heterofl_tpu.chaos.drill import drill_cfg
    from heterofl_tpu.entry.common import FedExperiment
    from heterofl_tpu.obs.watchdog import WatchdogError

    # poison EVERY cohort member at rounds 3 and 4: no salted redraw can
    # dodge it, so the rollback budget burns down and escalates
    cfg = drill_cfg(str(tmp_path),
                    chaos_poison=[[r, u] for r in (3, 4) for u in range(8)],
                    telemetry="on",
                    watchdog={"action": "rollback", "max_retries": 2,
                              "backoff": 0.0})
    exp = FedExperiment(cfg, 0)
    with pytest.warns(UserWarning):
        with pytest.raises(WatchdogError, match="budget spent"):
            exp.run("Global-Accuracy")
    log = _read_log(cfg, exp.tag)
    recs = [r for r in log if r.get("tag") == "recovery"]
    assert len(recs) == 2  # both attempts, then the escalation


# ---------------------------------------------------------------------------
# the chaos drill: fast smoke subset (full kill matrix is slow-marked)
# ---------------------------------------------------------------------------

def test_kill_drill_checkpoint_resume_bitwise(tmp_path):
    from heterofl_tpu.chaos.drill import run_kill_drill

    plan = resolve_fault_plan({"kills": [{"point": "checkpoint", "at": 2}]})
    rep = run_kill_drill(plan, {}, str(tmp_path))
    assert rep["ok"] and rep["bitwise_equal"]
    assert rep["kills_fired"] == [("checkpoint", 2)] and rep["resumes"] == 1


@pytest.mark.slow
def test_corrupt_drill_falls_back_a_generation(tmp_path):
    from heterofl_tpu.chaos.drill import run_kill_drill

    # kill before the 3rd checkpoint write (two generations on disk),
    # corrupt the newest: resume must fall back to .g1 and still land
    # bitwise on the uninterrupted trajectory
    plan = resolve_fault_plan(
        {"kills": [{"point": "checkpoint", "at": 3}],
         "corrupt": [{"which": "checkpoint", "mode": "flip",
                      "generation": 0}]})
    with pytest.warns(UserWarning, match="checkpoint-corrupt"):
        rep = run_kill_drill(plan, {"num_epochs": {"global": 6, "local": 1}},
                             str(tmp_path))
    assert rep["ok"] and rep["bitwise_equal"], rep
    assert len(rep["corruptions"]) == 1


@pytest.mark.slow
@pytest.mark.parametrize("strategy,store,point", [
    ("masked", "eager", "superstep"),
    ("masked", "eager", "fetch"),
    ("masked", "eager", "checkpoint"),
    ("masked", "stream", "prefetch"),
    ("grouped", "eager", "superstep"),
    ("grouped", "eager", "fetch"),
    ("grouped", "eager", "checkpoint"),
    ("grouped", "stream", "prefetch"),
])
def test_kill_matrix_resume_bitwise(tmp_path, strategy, store, point):
    from heterofl_tpu.chaos.drill import run_kill_drill

    plan = resolve_fault_plan({"kills": [{"point": point, "at": 1}]})
    over = {"strategy": strategy, "client_store": store}
    rep = run_kill_drill(plan, over, str(tmp_path))
    assert rep["ok"] and rep["bitwise_equal"], rep


@pytest.mark.slow
@pytest.mark.parametrize("strategy", ["masked", "grouped"])
@pytest.mark.parametrize("mode", ["quarantine", "rollback"])
def test_poison_drill_matrix(tmp_path, strategy, mode):
    from heterofl_tpu.chaos.drill import run_poison_drill

    rep = run_poison_drill(mode, {"strategy": strategy}, str(tmp_path))
    assert rep["ok"] and rep["final_params_finite"], rep
