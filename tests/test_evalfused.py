"""Eval-fused superstep (ISSUE 4): sBN recalibration + Local/Global eval
folded into the scanned multi-round program, for both engines.

The contract under test: a superstep whose static eval mask fires on round r
produces eval metrics BIT-IDENTICAL to the host-loop path (train to round r
with the plain superstep, then dispatch the Evaluator's standalone sBN /
eval_users / eval_global programs) -- same bodies, same committed operands,
same ``fold_in(key, epoch)`` streams, and the eval phase fenced from the
surrounding program with ``optimization_barrier`` so XLA cannot context-fuse
its reductions differently.  Plus: zero implicit H2D per eval window in
steady state (transfer guard), a flat program cache (one compiled dispatch
per superstep at eval_interval=1), and the driver-level relaxations.
"""

import json
import math

import jax
import numpy as np
import pytest

from heterofl_tpu.fed.core import round_users
from heterofl_tpu.models import make_model
from heterofl_tpu.parallel import (GroupedRoundEngine, RoundEngine, make_mesh,
                                   shard_client_data)
from heterofl_tpu.parallel.evaluation import Evaluator
from heterofl_tpu.parallel.round_engine import superstep_eval_groups

from test_round import _vision_setup

HOST_KEY = jax.random.key(0)
U = 8


def _batch(x, b):
    n = x.shape[0]
    s = math.ceil(n / b)
    pad = s * b - n
    w = np.concatenate([np.ones(n, np.float32), np.zeros(pad, np.float32)])
    if pad:
        x = np.concatenate([x, np.zeros((pad,) + x.shape[1:], x.dtype)])
    return x.reshape((s, b) + x.shape[1:]), w.reshape(s, b)


@pytest.fixture(scope="module")
def eval_setup():
    """cfg + train stacks + the three eval operand groups (sbn batches,
    per-user local shards, batched global test set), mirroring the driver's
    ``stage()``."""
    cfg, ds, data = _vision_setup()
    te = ds["test"]
    sbn_b = _batch(ds["train"].data, 20)
    xu = te.data[:96].reshape(U, 1, 12, 28, 28, 1)
    yu = te.target[:96].reshape(U, 1, 12)
    wu = np.ones((U, 1, 12), np.float32)
    lmu = np.ones((U, 10), np.float32)
    xg, wg = _batch(te.data, 20)
    yg, _ = _batch(te.target, 20)
    return {"cfg": cfg, "data": data, "sbn": sbn_b,
            "local": (xu, yu, wu, lmu), "global": (xg, yg, wg)}


def _host_reference(model, cfg, mesh, data, chunks, es, scheds=None):
    """Train with plain supersteps in ``chunks`` of (epoch0, k) and run the
    host-loop eval after each chunk -- the bit-exact baseline."""
    ev = Evaluator(model, cfg, mesh, seed=0)
    eng = RoundEngine(model, cfg, mesh)
    p = model.init(jax.random.key(0))
    refs = []
    for epoch0, k in chunks:
        sched = scheds(epoch0, k) if scheds is not None else None
        p, pend = eng.train_superstep(p, HOST_KEY, epoch0, k, data,
                                      num_active=4, user_schedule=sched)
        pend.fetch()
        ep = epoch0 + k - 1
        bn = ev.sbn_stats(p, *es["sbn"])
        local = ev.eval_users(p, bn, *es["local"], epoch=ep)
        g = ev.eval_global(p, bn, *es["global"], epoch=ep)
        refs.append((ep, bn, local, g))
    return p, refs


def _fused(model, cfg, mesh, es):
    ev = Evaluator(model, cfg, mesh, seed=0)
    return ev.fused(sbn_batches=es["sbn"], local_eval=es["local"],
                    global_eval=es["global"])


def _assert_evals_bitwise(refs, fused_evals):
    assert [e["epoch"] for e in fused_evals] == [ep for ep, *_ in refs]
    for (ep, bn, local, g), fe in zip(refs, fused_evals):
        for site in bn:
            np.testing.assert_array_equal(np.asarray(bn[site][0]),
                                          fe["bn"][site][0], err_msg=site)
            np.testing.assert_array_equal(np.asarray(bn[site][1]),
                                          fe["bn"][site][1], err_msg=site)
        for nm in local:
            np.testing.assert_array_equal(local[nm], fe["local"][nm],
                                          err_msg=f"epoch {ep} local {nm}")
        for nm in g:
            assert g[nm] == fe["global"][nm], (ep, nm, g[nm], fe["global"][nm])


# ---------------------------------------------------------------------------
# the mask -> scan-group compression
# ---------------------------------------------------------------------------

def test_superstep_eval_groups():
    # eval_interval=1: one repeated (round + eval) group
    assert superstep_eval_groups((True,) * 8) == [(1, True, 8)]
    # eval_interval divides K: one repeated group of e rounds + eval
    assert superstep_eval_groups((False, True) * 4) == [(2, True, 4)]
    # eval on the final round only (eval_interval == K or a multiple)
    assert superstep_eval_groups((False,) * 7 + (True,)) == [(8, True, 1)]
    # no eval in this window (eval_interval > K): one train-only group
    assert superstep_eval_groups((False,) * 8) == [(8, False, 1)]
    # trailing train-only rounds stay a separate group
    assert superstep_eval_groups((True, False)) == [(1, True, 1), (1, False, 1)]
    # irregular lead (misaligned epoch0): distinct groups, still covers k
    groups = superstep_eval_groups((False, False, True, False, True))
    assert groups == [(3, True, 1), (2, True, 1)]
    assert sum(n * c for n, _, c in groups) == 5


# ---------------------------------------------------------------------------
# bit-identical equivalence vs the host-loop eval path
# ---------------------------------------------------------------------------

def test_evalfused_masked_replicated_bit_identical(eval_setup):
    """Masked engine, replicated placement, evals mid-superstep (repeated
    scan group): params, train metrics and every eval result are bitwise
    equal to chunked supersteps + the standalone eval programs."""
    es = eval_setup
    cfg, data = es["cfg"], es["data"]
    model = make_model(cfg)
    mesh = make_mesh(8, 1)
    p1, refs = _host_reference(model, cfg, mesh, data, [(1, 2), (3, 2)], es)

    eng = RoundEngine(model, cfg, mesh)
    p2 = model.init(jax.random.key(0))
    p2, pend = eng.train_superstep(p2, HOST_KEY, 1, 4, data, num_active=4,
                                   eval_mask=(False, True, False, True),
                                   fused_eval=_fused(model, cfg, mesh, es))
    out = pend.fetch()
    for name in p1:
        np.testing.assert_array_equal(np.asarray(p1[name]), np.asarray(p2[name]),
                                      err_msg=name)
    assert len(out["train"]) == 4
    _assert_evals_bitwise(refs, out["eval"])


def test_evalfused_eval_interval_one(eval_setup):
    """The ISSUE 4 acceptance cadence: eval EVERY round, still one compiled
    dispatch per superstep, every eval bitwise vs the host loop."""
    es = eval_setup
    cfg, data = es["cfg"], es["data"]
    model = make_model(cfg)
    mesh = make_mesh(8, 1)
    p1, refs = _host_reference(model, cfg, mesh, data,
                               [(1, 1), (2, 1), (3, 1)], es)

    eng = RoundEngine(model, cfg, mesh)
    p2 = model.init(jax.random.key(0))
    p2, pend = eng.train_superstep(p2, HOST_KEY, 1, 3, data, num_active=4,
                                   eval_mask=(True, True, True),
                                   fused_eval=_fused(model, cfg, mesh, es))
    out = pend.fetch()
    for name in p1:
        np.testing.assert_array_equal(np.asarray(p1[name]), np.asarray(p2[name]),
                                      err_msg=name)
    _assert_evals_bitwise(refs, out["eval"])


@pytest.mark.slow
def test_evalfused_masked_sharded_bit_identical(eval_setup):
    """Sharded placement: the host-packed slot schedule rides the scan, the
    eval operands stay mesh-committed, results bitwise."""
    es = eval_setup
    cfg = dict(es["cfg"], data_placement="sharded")
    model = make_model(cfg)
    mesh = make_mesh(8, 1)
    data_s = shard_client_data(mesh, tuple(np.asarray(d) for d in es["data"]))

    def scheds(epoch0, k):
        return np.stack([
            np.asarray(round_users(jax.random.fold_in(HOST_KEY, epoch0 + r), U, 4))
            for r in range(k)])

    p1, refs = _host_reference(model, cfg, mesh, data_s, [(1, 2), (3, 2)], es,
                               scheds=scheds)
    eng = RoundEngine(model, cfg, mesh)
    p2 = model.init(jax.random.key(0))
    p2, pend = eng.train_superstep(p2, HOST_KEY, 1, 4, data_s,
                                   user_schedule=scheds(1, 4),
                                   eval_mask=(False, True, False, True),
                                   fused_eval=_fused(model, cfg, mesh, es))
    out = pend.fetch()
    for name in p1:
        np.testing.assert_array_equal(np.asarray(p1[name]), np.asarray(p2[name]),
                                      err_msg=name)
    _assert_evals_bitwise(refs, out["eval"])


@pytest.mark.parametrize("placement", ["span", "slices"])
def test_evalfused_grouped_bit_identical(eval_setup, placement):
    """Grouped engine, both level placements: the fused eval runs on the
    combined globals outside the slices-mode switch; results bitwise vs the
    plain grouped superstep + host evaluator."""
    es = eval_setup
    cfg = dict(es["cfg"], level_placement=placement)
    data = es["data"]
    model = make_model(cfg)
    mesh = make_mesh(8, 1)
    rates_vec = np.asarray(cfg["model_rate"], np.float32)
    users = np.stack([
        np.asarray(round_users(jax.random.fold_in(HOST_KEY, 1 + r), U, 4))
        for r in range(2)])
    rates = rates_vec[users]

    g1 = GroupedRoundEngine(cfg, make_mesh(8, 1))
    p1 = model.init(jax.random.key(0))
    p1, pend = g1.train_superstep(p1, HOST_KEY, 1, 2, users, rates, data)
    pend.fetch()
    ev = Evaluator(model, cfg, mesh, seed=0)
    bn = ev.sbn_stats(p1, *es["sbn"])
    local = ev.eval_users(p1, bn, *es["local"], epoch=2)
    g = ev.eval_global(p1, bn, *es["global"], epoch=2)

    g2 = GroupedRoundEngine(cfg, make_mesh(8, 1))
    p2 = model.init(jax.random.key(0))
    p2, pend = g2.train_superstep(p2, HOST_KEY, 1, 2, users, rates, data,
                                  eval_mask=(False, True),
                                  fused_eval=_fused(model, cfg, mesh, es))
    out = pend.fetch()
    for name in p1:
        np.testing.assert_array_equal(np.asarray(p1[name]), np.asarray(p2[name]),
                                      err_msg=name)
    _assert_evals_bitwise([(2, bn, local, g)], out["eval"])


@pytest.mark.slow
def test_evalfused_lm_global_only(eval_setup):
    """LM path: no sBN, no Local eval -- the fused phase is the Global pass
    alone, bitwise vs eval_global (the LM train scan itself is pinned
    near-exact in test_superstep)."""
    from test_round import _lm_setup

    cfg, data = _lm_setup()
    model = make_model(cfg)
    mesh = make_mesh(2, 1)
    rng = np.random.default_rng(1)
    rows = rng.integers(0, cfg["num_tokens"], size=(2, 2, 48)).astype(np.int64)
    w = np.ones(rows.shape, np.float32)

    eng1 = RoundEngine(model, cfg, mesh)
    p1 = model.init(jax.random.key(0))
    p1, pend = eng1.train_superstep(p1, HOST_KEY, 1, 2, data, num_active=4)
    pend.fetch()
    ev = Evaluator(model, cfg, mesh, seed=0)
    g = ev.eval_global(p1, {}, rows, w, epoch=2)

    ev2 = Evaluator(model, cfg, mesh, seed=0)
    fe = ev2.fused(global_eval=(rows, w))
    eng2 = RoundEngine(model, cfg, mesh)
    p2 = model.init(jax.random.key(0))
    p2, pend = eng2.train_superstep(p2, HOST_KEY, 1, 2, data, num_active=4,
                                    eval_mask=(False, True), fused_eval=fe)
    out = pend.fetch()
    fe_out = out["eval"][0]
    assert fe_out["local"] == {} and fe_out["bn"] == {}
    for nm in g:
        np.testing.assert_allclose(g[nm], fe_out["global"][nm], rtol=1e-6,
                                   err_msg=nm)


# ---------------------------------------------------------------------------
# zero implicit H2D per eval window + flat program cache in steady state
# ---------------------------------------------------------------------------

def test_evalfused_transfer_guard_and_cache_flat_masked(eval_setup):
    """The ISSUE 4 acceptance: with eval firing every round, steady-state
    supersteps are ONE jitted dispatch each -- no implicit H2D under the
    transfer guard (the eval operands are committed once) and zero program
    cache growth."""
    es = eval_setup
    cfg, data = es["cfg"], es["data"]
    model = make_model(cfg)
    mesh = make_mesh(8, 1)
    eng = RoundEngine(model, cfg, mesh)
    fe = _fused(model, cfg, mesh, es)
    p = model.init(jax.random.key(0))
    p, pend = eng.train_superstep(p, HOST_KEY, 1, 2, data, num_active=4,
                                  eval_mask=(True, True), fused_eval=fe)
    pend.fetch()
    size0 = eng.program_cache_size()
    with jax.transfer_guard_host_to_device("disallow"):
        p, pend = eng.train_superstep(p, HOST_KEY, 3, 2, data, num_active=4,
                                      eval_mask=(True, True), fused_eval=fe)
        p, pend = eng.train_superstep(p, HOST_KEY, 5, 2, data, num_active=4,
                                      eval_mask=(True, True), fused_eval=fe)
    out = pend.fetch()
    assert eng.program_cache_size() == size0
    assert len(out["eval"]) == 2
    assert np.isfinite(out["eval"][-1]["global"]["loss_sum"])


@pytest.mark.parametrize("placement", ["span", "slices"])
def test_evalfused_transfer_guard_grouped(eval_setup, placement):
    es = eval_setup
    cfg = dict(es["cfg"], level_placement=placement)
    data = es["data"]
    model = make_model(cfg)
    grp = GroupedRoundEngine(cfg, make_mesh(8, 1))
    fe = _fused(model, cfg, grp.mesh, es)
    rates_vec = np.asarray(cfg["model_rate"], np.float32)

    def sched(epoch0, k):
        # a count-stable schedule (same per-level membership every round) so
        # the flat-cache assertion sees steady state, not the documented
        # slot-bucket recompile that fluctuating level counts trigger
        users = np.stack([np.array([0, 2, 4, 6], np.int32)] * k)
        return users, rates_vec[users]

    p = model.init(jax.random.key(0))
    u, r = sched(1, 2)
    p, pend = grp.train_superstep(p, HOST_KEY, 1, 2, u, r, data,
                                  eval_mask=(True, True), fused_eval=fe)
    pend.fetch()
    size0 = grp.program_cache_size()
    u3, r3 = sched(3, 2)
    u5, r5 = sched(5, 2)
    with jax.transfer_guard_host_to_device("disallow"):
        p, pend = grp.train_superstep(p, HOST_KEY, 3, 2, u3, r3, data,
                                      eval_mask=(True, True), fused_eval=fe)
        p, pend = grp.train_superstep(p, HOST_KEY, 5, 2, u5, r5, data,
                                      eval_mask=(True, True), fused_eval=fe)
    out = pend.fetch()
    assert grp.program_cache_size() == size0
    assert np.isfinite(out["eval"][-1]["global"]["loss_sum"])


def test_evalfused_donation_releases_previous_params(eval_setup):
    """The eval-fused superstep still donates the params carry."""
    es = eval_setup
    cfg, data = es["cfg"], es["data"]
    model = make_model(cfg)
    mesh = make_mesh(1, 1)
    eng = RoundEngine(model, cfg, mesh)
    fe = _fused(model, cfg, mesh, es)
    p0 = model.init(jax.random.key(0))
    p1, pend = eng.train_superstep(p0, HOST_KEY, 1, 2, data, num_active=4,
                                   eval_mask=(False, True), fused_eval=fe)
    jax.block_until_ready(p1)
    pend.fetch()
    assert all(v.is_deleted() for v in p0.values())


# ---------------------------------------------------------------------------
# driver level: plateau-in-superstep + end-to-end at eval_interval=1
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_driver_end_to_end_eval_every_round(tmp_path):
    """superstep_rounds=2 with eval_interval=1: every round evaluates inside
    the scan; the driver still makes one dispatch per superstep and the
    history carries one Global-Accuracy entry per round."""
    from heterofl_tpu.entry import train_classifier_fed

    ov = {"num_epochs": {"global": 4, "local": 1},
          "conv": {"hidden_size": [8, 16]},
          "batch_size": {"train": 10, "test": 20},
          "superstep_rounds": 2, "eval_interval": 1, "strategy": "masked"}
    argv = ["--control_name", "1_8_0.5_iid_fix_a1-b1-c1_bn_1_1",
            "--data_name", "MNIST", "--model_name", "conv",
            "--synthetic", "1",
            "--synthetic_sizes", json.dumps({"train": 200, "test": 80}),
            "--output_dir", str(tmp_path),
            "--override", json.dumps(ov)]
    res = train_classifier_fed.main(argv)
    hist = res[0]["logger"].history
    # 4 rounds in 2 supersteps -> 2 loop iterations; every round evaluated,
    # so each iteration's mean covers that superstep's 2 evals
    assert len(hist["test/Global-Accuracy"]) == 2
    assert np.isfinite(hist["test/Global-Accuracy"]).all()
    assert res[0]["bn_state"]  # the LAST fused eval's sBN stats landed


@pytest.mark.slow
def test_driver_end_to_end_plateau_superstep(tmp_path):
    """ReduceLROnPlateau inside superstep mode (the ISSUE 4 relaxation):
    LR rides as a per-superstep scalar and steps on the fused eval metrics
    at superstep boundaries."""
    from heterofl_tpu.entry import train_classifier_fed

    ov = {"num_epochs": {"global": 4, "local": 1},
          "conv": {"hidden_size": [8, 16]},
          "batch_size": {"train": 10, "test": 20},
          "superstep_rounds": 2, "eval_interval": 2,
          "scheduler_name": "ReduceLROnPlateau", "strategy": "masked"}
    argv = ["--control_name", "1_8_0.5_iid_fix_a1-b1_bn_1_1",
            "--data_name", "MNIST", "--model_name", "conv",
            "--synthetic", "1",
            "--synthetic_sizes", json.dumps({"train": 160, "test": 80}),
            "--output_dir", str(tmp_path),
            "--override", json.dumps(ov)]
    res = train_classifier_fed.main(argv)
    hist = res[0]["logger"].history
    assert len(hist["test/Global-Accuracy"]) == 2
    assert np.isfinite(hist["train/Local-Loss"]).all()
