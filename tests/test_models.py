import jax
import jax.numpy as jnp
import numpy as np
import pytest

from heterofl_tpu import config as C
from heterofl_tpu.models import make_model


def small_cfg(model_name="conv", data_name="MNIST", norm="bn", control="1_10_0.5_iid_fix_a1_bn_1_1"):
    cfg = C.default_cfg()
    cfg["control"] = C.parse_control_name(control)
    cfg["control"]["norm"] = norm
    cfg["data_name"] = data_name
    cfg["model_name"] = model_name
    cfg = C.process_control(cfg)
    # shrink for CPU tests
    cfg["conv"] = {"hidden_size": [8, 16]}
    cfg["resnet"] = {"hidden_size": [8, 16, 16, 16]}
    cfg["transformer"] = {"embedding_size": 32, "num_heads": 4, "hidden_size": 64,
                          "num_layers": 2, "dropout": 0.0}
    cfg["classes_size"] = 10
    cfg["num_tokens"] = 50
    if "bptt" not in cfg:
        cfg["bptt"] = 16
        cfg["mask_rate"] = 0.15
    return cfg


def vision_batch(cfg, n=4, seed=0):
    rng = np.random.default_rng(seed)
    shape = tuple(cfg["data_shape"])
    return {
        "img": jnp.asarray(rng.normal(size=(n,) + shape), jnp.float32),
        "label": jnp.asarray(rng.integers(0, cfg["classes_size"], n)),
    }


@pytest.mark.parametrize("model_name", ["conv", "resnet18", "resnet50"])
@pytest.mark.parametrize("norm", ["bn", "in", "ln", "gn", "none"])
def test_vision_smoke(model_name, norm):
    cfg = small_cfg(model_name, norm=norm)
    model = make_model(cfg)
    params = model.init(jax.random.key(0))
    batch = vision_batch(cfg)
    out, collected = model.apply(params, batch, train=True)
    assert out["score"].shape == (4, 10)
    assert jnp.isfinite(out["loss"])
    if norm == "bn":
        out2, col = model.apply(params, batch, train=True, bn_mode="collect")
        assert len(col) == len(model.bn_sites) > 0
        state = {k: v for k, v in col.items()}
        out3, _ = model.apply(params, batch, train=False, bn_mode="running", bn_state=state)
        assert jnp.isfinite(out3["loss"])


def test_transformer_smoke():
    cfg = small_cfg("transformer", data_name="WikiText2")
    model = make_model(cfg)
    params = model.init(jax.random.key(0))
    labels = jnp.asarray(np.random.default_rng(0).integers(0, 50, (2, 16)))
    out, _ = model.apply(params, {"label": labels}, train=True, rng=jax.random.key(1))
    assert out["score"].shape == (2, 16, 50)
    assert jnp.isfinite(out["loss"])


def test_label_mask_zero_fill():
    cfg = small_cfg("conv")
    model = make_model(cfg)
    params = model.init(jax.random.key(0))
    batch = vision_batch(cfg)
    lm = jnp.zeros(10).at[jnp.array([1, 3])].set(1.0)
    out, _ = model.apply(params, batch, train=True, label_mask=lm)
    score = np.asarray(out["score"])
    masked_cols = [c for c in range(10) if c not in (1, 3)]
    assert np.all(score[:, masked_cols] == 0.0)
    assert np.any(score[:, [1, 3]] != 0.0)


def test_scaler_train_only():
    cfg = small_cfg("conv")
    model = make_model(cfg)
    params = model.init(jax.random.key(0))
    batch = vision_batch(cfg)
    # with norm='none' the scaler changes the forward; check train != eval scale behavior
    cfg2 = small_cfg("conv", norm="none")
    m2 = make_model(cfg2)
    p2 = m2.init(jax.random.key(0))
    o_tr, _ = m2.apply(p2, batch, train=True, scaler_rate=0.5)
    o_ev, _ = m2.apply(p2, batch, train=False, scaler_rate=0.5)
    assert not np.allclose(o_tr["score"], o_ev["score"])


def test_sample_weight_neutralises_padding():
    cfg = small_cfg("conv", norm="none")
    model = make_model(cfg)
    params = model.init(jax.random.key(0))
    b4 = vision_batch(cfg, n=4)
    # pad with junk + zero weight -> same loss as unpadded
    img6 = jnp.concatenate([b4["img"], 100.0 * jnp.ones((2,) + b4["img"].shape[1:])])
    lab6 = jnp.concatenate([b4["label"], jnp.zeros(2, b4["label"].dtype)])
    w = jnp.array([1, 1, 1, 1, 0, 0], jnp.float32)
    o4, _ = model.apply(params, b4, train=True)
    o6, _ = model.apply(params, {"img": img6, "label": lab6}, train=True, sample_weight=w)
    assert np.allclose(o4["loss"], o6["loss"], rtol=1e-5)


def test_conv2d_im2col_matches_direct():
    """The im2col/bmm conv lowering (cfg conv_impl='im2col') is numerically
    equivalent to lax.conv across the kernel/stride/padding shapes the model
    zoo uses, at the op level and through a full masked ResNet forward +
    gradient."""
    import jax
    import jax.numpy as jnp

    from heterofl_tpu.ops.layers import conv2d

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(2, 8, 8, 5)).astype(np.float32))
    for kh, kw, stride, pad in ((3, 3, 1, 1), (3, 3, 2, 1), (1, 1, 1, 0), (1, 1, 2, 0)):
        w = jnp.asarray(rng.normal(size=(kh, kw, 5, 7)).astype(np.float32))
        b = jnp.asarray(rng.normal(size=(7,)).astype(np.float32))
        ref = conv2d(x, w, b, stride=stride, padding=pad)
        alt = conv2d(x, w, b, stride=stride, padding=pad, impl="im2col")
        np.testing.assert_allclose(np.asarray(alt), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5,
                                   err_msg=f"k={kh} s={stride} p={pad}")
    # model level: full forward + grad through vmapped per-client kernels
    cfg = small_cfg("resnet18")
    m_dir = make_model(cfg)
    cfg2 = dict(cfg)
    cfg2["conv_impl"] = "im2col"
    m_alt = make_model(cfg2)
    params = m_dir.init(jax.random.key(0))
    batch = vision_batch(cfg)

    def loss(m):
        def f(p):
            out, _ = m.apply(p, batch, train=True)
            return out["loss"]
        return f

    l1, g1 = jax.value_and_grad(loss(m_dir))(params)
    l2, g2 = jax.value_and_grad(loss(m_alt))(params)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    for k in g1:
        np.testing.assert_allclose(np.asarray(g1[k]), np.asarray(g2[k]),
                                   rtol=1e-4, atol=1e-5, err_msg=k)


def test_bf16_compute_dtype_close_to_f32():
    """bfloat16 MXU operands with f32 accumulation stay close to the f32
    forward, and masked zeros remain exactly zero."""
    import jax

    from heterofl_tpu.models.spec import mask_params

    cfg = small_cfg("resnet18")
    m32 = make_model(cfg)
    cfg16 = dict(cfg)
    cfg16["compute_dtype"] = "bfloat16"
    m16 = make_model(cfg16)
    params = m32.init(jax.random.key(0))
    batch = vision_batch(cfg, n=4)
    o32, _ = m32.apply(params, batch, train=True)
    o16, _ = m16.apply(params, batch, train=True)
    assert abs(float(o32["loss"]) - float(o16["loss"])) < 0.05
    # masked suffix stays exactly zero through bf16 forward+grad
    masked = mask_params(params, m16.specs, m16.groups, 0.25)
    g = jax.grad(lambda p: m16.apply(p, batch, train=True, width_rate=0.25,
                                     scaler_rate=0.25)[0]["loss"])(masked)
    import numpy as np

    tail = np.asarray(g["layer3.1.conv2.w"])[:, :, 4:, :]
    assert np.all(tail == 0.0)


def test_augment_cifar_shapes_and_determinism():
    import jax

    from heterofl_tpu.ops.augment import augment_cifar

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, 255, (6, 32, 32, 3)), jnp.uint8)
    a1 = augment_cifar(jax.random.key(3), x)
    a2 = augment_cifar(jax.random.key(3), x)
    a3 = augment_cifar(jax.random.key(4), x)
    assert a1.shape == x.shape
    np.testing.assert_array_equal(np.asarray(a1), np.asarray(a2))  # same key
    assert not np.array_equal(np.asarray(a1), np.asarray(a3))  # new key
    # crop+flip only rearranges pixels from the padded canvas
    assert np.asarray(a1).max() <= 255 and np.asarray(a1).min() >= 0


# ---------------------------------------------------------------------------
# the explicit layout/dtype policy (ISSUE 5 pass 2)
# ---------------------------------------------------------------------------

def test_layout_policy_every_family_compliant():
    """Trailing axes are feature axes (width-group or label) for every
    model family -- the lane-packing convention models/layout.py pins."""
    from heterofl_tpu.models import layout as L

    for name in ("conv", "resnet18", "resnet50", "transformer"):
        cfg = small_cfg(name, data_name="WikiText2" if name == "transformer"
                        else "MNIST")
        model = make_model(cfg)
        params = model.init(jax.random.key(0))
        bad = L.check_policy(model.specs,
                             {k: v.shape for k, v in params.items()})
        assert bad == {}, (name, bad)


def test_layout_policy_flags_transposed_weight():
    """A torch-style [out, in] weight (reduction axis in the lanes) fails
    the policy audit."""
    from heterofl_tpu.models import layout as L
    from heterofl_tpu.models.spec import ParamSpec

    assert L.check_policy({"w": ParamSpec(axis_groups={0: "h"})},
                          {"w": (8, 10)}) == {"w": 1}
    assert L.check_policy({"w": ParamSpec(axis_groups={1: "h"})},
                          {"w": (10, 8)}) == {}


def test_pin_params_cpu_passthrough_and_formats():
    """On the CPU test mesh pin_params is the identity (XLA:CPU ignores
    custom layouts); the Format objects themselves pin row-major
    major-to-minor, and an unknown policy raises."""
    import pytest

    from heterofl_tpu.models.layout import param_formats, pin_params

    cfg = small_cfg("conv")
    model = make_model(cfg)
    params = model.init(jax.random.key(0))
    pinned = pin_params(params, mesh=None, policy="auto")
    assert all(pinned[k] is params[k] for k in params)
    assert pin_params(params, mesh=None, policy="none") is params
    with pytest.raises(ValueError, match="layout_policy"):
        pin_params(params, mesh=None, policy="fastest")
    fmts = param_formats(params)
    for k, v in params.items():
        dll = fmts[k].device_local_layout
        assert tuple(dll.major_to_minor) == tuple(range(v.ndim)), k


def test_conv_dimension_numbers_one_owner():
    """The conv convention has one owner (ops/layers.py) and the layout
    policy re-exports it."""
    from heterofl_tpu.models.layout import CONV_DIMENSION_NUMBERS as A
    from heterofl_tpu.ops.layers import CONV_DIMENSION_NUMBERS as B

    assert A is B == ("NHWC", "HWIO", "NHWC")
