import os

import jax
import jax.numpy as jnp
import numpy as np

from heterofl_tpu.utils import (
    Logger,
    Metric,
    accuracy,
    checkpoint_path,
    copy_best,
    load_checkpoint,
    make_optimizer,
    make_scheduler,
    perplexity,
    resume,
    save_checkpoint,
    summarize_sums,
)


def test_accuracy_and_perplexity():
    score = np.array([[2.0, 1.0, 0.0], [0.0, 3.0, 1.0]])
    assert accuracy(score, np.array([0, 1])) == 100.0
    assert accuracy(score, np.array([1, 1])) == 50.0
    p = perplexity(np.zeros((2, 4)), np.array([0, 1]))
    assert abs(p - 4.0) < 1e-6  # uniform logits over 4 classes


def test_metric_registry():
    m = Metric()
    out = {"loss": jnp.asarray(1.5), "score": np.array([[5.0, 0.0]])}
    ev = m.evaluate(["Local-Loss", "Local-Accuracy"], {"label": np.array([0])}, out)
    assert ev == {"Local-Loss": 1.5, "Local-Accuracy": 100.0}


def test_summarize_sums():
    s = {"loss_sum": np.array([2.0, 4.0]), "score_sum": np.array([1.0, 2.0]), "n": np.array([2.0, 2.0])}
    out = summarize_sums(s, "conv")
    assert out["Local-Loss"] == 1.5
    assert out["Local-Accuracy"] == 75.0
    lm = summarize_sums(s, "transformer", prefix="Global-")
    assert abs(lm["Global-Perplexity"] - 0.75) < 1e-9


def test_logger_weighted_mean_and_history(tmp_path):
    lg = Logger(str(tmp_path / "run"))
    lg.safe(True)
    lg.append({"Loss": 2.0}, "train", n=10)
    lg.append({"Loss": 1.0}, "train", n=30)
    assert abs(lg.mean["train/Loss"] - 1.25) < 1e-9
    lg.append({"info": ["Model: x", "Epoch: 1"]}, "train", mean=False)
    line = lg.write("train", ["Loss"])
    assert "Loss: 1.2500" in line
    lg.safe(False)
    assert lg.history["train/Loss"] == [1.25]
    lg.reset()
    assert lg.mean == {}
    assert os.path.exists(tmp_path / "run" / "log.jsonl")


def test_logger_tensorboard_scalar_and_text(tmp_path):
    """TB channel parity (ref ``src/logger.py:57-84``): with
    ``use_tensorboard=True`` one ``write()`` lands a scalar per metric AND the
    info line on the text channel, verifiable from the event files on disk."""
    import pytest

    pytest.importorskip("torch.utils.tensorboard")
    ea_mod = pytest.importorskip(
        "tensorboard.backend.event_processing.event_accumulator")
    lg = Logger(str(tmp_path / "run"), use_tensorboard=True)
    lg.safe(True)
    assert lg.writer is not None, "SummaryWriter did not open"
    lg.append({"Loss": 2.0, "Accuracy": 50.0}, "train", n=10)
    lg.append({"info": ["Model: x", "Epoch: 1"]}, "train", mean=False)
    lg.write("train", ["Loss", "Accuracy"])
    lg.flush()
    lg.safe(False)
    acc = ea_mod.EventAccumulator(str(tmp_path / "run"),
                                  size_guidance={"scalars": 0, "tensors": 0})
    acc.Reload()
    tags = acc.Tags()
    assert "train/Loss" in tags["scalars"]
    assert "train/Accuracy" in tags["scalars"]
    assert len(acc.Scalars("train/Loss")) == 1
    assert abs(acc.Scalars("train/Loss")[0].value - 2.0) < 1e-6
    # add_text lands on the tensors channel as <tag>/text_summary
    assert any(t.startswith("train/info") for t in tags["tensors"]), tags["tensors"]


def test_checkpoint_roundtrip_and_modes(tmp_path):
    out = str(tmp_path)
    blob = {
        "cfg": {"a": 1},
        "epoch": 7,
        "params": {"w": jnp.ones((2, 2))},
        "bn_state": {},
        "data_split": {"train": {0: [1, 2]}},
        "label_split": {0: [1]},
        "scheduler_state": None,
        "logger_history": {"test/Global-Accuracy": [50.0]},
    }
    save_checkpoint(checkpoint_path(out, "tag"), blob)
    copy_best(out, "tag")
    full = resume(out, "tag", mode=1)
    assert full["epoch"] == 7
    assert isinstance(full["params"]["w"], np.ndarray)
    part = resume(out, "tag", mode=2)
    assert set(part) == {"params", "bn_state", "data_split", "label_split"}
    assert resume(out, "tag", mode=0) is None
    assert resume(out, "missing", mode=1) is None
    best = load_checkpoint(checkpoint_path(out, "tag", "best"))
    assert best["epoch"] == 7


def test_schedulers():
    cfg = {"scheduler_name": "MultiStepLR", "lr": 0.1, "factor": 0.1,
           "milestones": [2, 4], "num_epochs": {"global": 10}}
    s = make_scheduler(cfg)
    assert [round(s(i), 4) for i in (1, 2, 3, 4, 5)] == [0.1, 0.1, 0.01, 0.01, 0.001]
    cfg["scheduler_name"] = "None"
    assert make_scheduler(cfg)(99) == 0.1
    cfg["scheduler_name"] = "ExponentialLR"
    assert abs(make_scheduler(cfg)(2) - 0.099) < 1e-9
    cfg["scheduler_name"] = "CosineAnnealingLR"
    cfg["min_lr"] = 0.0
    sc = make_scheduler(cfg)
    assert abs(sc(1) - 0.1) < 1e-9 and sc(11) < 1e-9
    cfg["scheduler_name"] = "ReduceLROnPlateau"
    cfg["patience"] = 1
    cfg["threshold"] = 1e-3
    pl = make_scheduler(cfg)
    for _ in range(5):
        pl.step_metric(1.0)
    assert pl(1) < 0.1


def test_optimizer_sgd_matches_torch():
    import torch

    w0 = np.random.default_rng(0).normal(size=(4, 3)).astype(np.float32)
    g = np.random.default_rng(1).normal(size=(4, 3)).astype(np.float32)
    tw = torch.nn.Parameter(torch.tensor(w0.copy()))
    opt = torch.optim.SGD([tw], lr=0.1, momentum=0.9, weight_decay=5e-4)
    cfg = {"optimizer_name": "SGD", "momentum": 0.9, "weight_decay": 5e-4}
    init, update = make_optimizer(cfg)
    p = {"w": jnp.asarray(w0)}
    st = init(p)
    for _ in range(3):
        tw.grad = torch.tensor(g.copy())
        opt.step()
        p, st = update(p, {"w": jnp.asarray(g)}, st, 0.1)
    np.testing.assert_allclose(np.asarray(p["w"]), tw.detach().numpy(), rtol=1e-5, atol=1e-6)


def test_optimizer_rmsprop_adam_adamax_match_torch():
    import torch

    w0 = np.random.default_rng(0).normal(size=(4, 3)).astype(np.float32)
    g = np.random.default_rng(1).normal(size=(4, 3)).astype(np.float32)
    for name, mk in (("RMSprop", lambda p: torch.optim.RMSprop([p], lr=0.01, momentum=0.9,
                                                               weight_decay=5e-4)),
                     ("Adam", lambda p: torch.optim.Adam([p], lr=0.01, weight_decay=5e-4)),
                     ("Adamax", lambda p: torch.optim.Adamax([p], lr=0.01, weight_decay=5e-4))):
        tw = torch.nn.Parameter(torch.tensor(w0.copy()))
        topt = mk(tw)
        cfg = {"optimizer_name": name, "momentum": 0.9, "weight_decay": 5e-4}
        init, update = make_optimizer(cfg)
        p = {"w": jnp.asarray(w0)}
        st = init(p)
        for _ in range(4):
            tw.grad = torch.tensor(g.copy())
            topt.step()
            p, st = update(p, {"w": jnp.asarray(g)}, st, 0.01)
        np.testing.assert_allclose(np.asarray(p["w"]), tw.detach().numpy(),
                                   rtol=2e-4, atol=2e-5, err_msg=name)
