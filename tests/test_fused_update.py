"""Fused masked-SGD epilogue (ISSUE 5): the ops/fused_update.py primitive
and the engine-level fused-vs-reference matrix.

The contract, in three tiers:

* PRIMITIVE: the fused update is bit-identical to the reference op chain
  on the same inputs -- XLA fallback unconditionally (including the
  global-norm clip decision: same reduces over the same per-leaf arrays in
  the same order); the Pallas kernel (interpret mode here) matches
  elementwise exactly and associates the norm per lane-block, so it is
  bit-exact whenever clipping does not engage and float-tolerant when it
  does.
* STEP RESULTS: fused-vs-reference engine programs produce BIT-IDENTICAL
  params at the step level across the whole matrix -- masked x
  {replicated, sharded}, grouped x {span, slices}, K in {1, 8}, with and
  without the eval mask (proven with one-local-step rounds, where nothing
  can amortise a mismatch away).
* TRAJECTORIES: over many multi-step rounds the two programs agree at
  float-association level, NOT bitwise -- the flat scan carry changes
  XLA's global fusion choices, which shifts some reduce emission by 1 ulp
  that SGD amplifies chaotically.  This is the same agreement class as the
  repo's standing masked-vs-sliced / grouped-vs-masked engine contracts;
  the within-engine bitwise contracts (superstep-vs-sequential,
  eval-fused-vs-host) are untouched because both sides share one body.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from heterofl_tpu.models import make_model
from heterofl_tpu.models.spec import param_mask
from heterofl_tpu.ops.fused_update import (FlatSpec, masked_sgd_step,
                                           resolve_fused_mode)
from heterofl_tpu.parallel import (GroupedRoundEngine, RoundEngine, make_mesh,
                                   shard_client_data)
from heterofl_tpu.fed.core import round_users
from heterofl_tpu.utils.optim import clip_by_global_norm

from test_round import _vision_setup, _lm_setup

HOST_KEY = jax.random.key(0)


# ---------------------------------------------------------------------------
# unit level: the primitive vs the reference op chain
# ---------------------------------------------------------------------------

def _reference_chain(p, g, bufs, m, n_glob, lr, momentum, wd, has):
    """The seed engines' epilogue, verbatim semantics."""
    g = {k: v / jnp.maximum(n_glob, 1e-6) for k, v in g.items()}
    g = {k: v * m[k] for k, v in g.items()}
    g, _ = clip_by_global_norm(g, 1.0)
    nb = jax.tree_util.tree_map(lambda pp, gg, bb: momentum * bb + gg + wd * pp,
                                p, g, bufs)
    np_ = jax.tree_util.tree_map(lambda pp, bb: pp - lr * bb, p, nb)
    if has is not None:
        np_ = jax.tree_util.tree_map(lambda a, c: jnp.where(has, a, c), np_, p)
        nb = jax.tree_util.tree_map(lambda a, c: jnp.where(has, a, c), nb, bufs)
    return np_, nb


def _rand_trees(seed=0, gscale=1.0):
    rng = np.random.default_rng(seed)
    shapes = {"blk.conv.w": (3, 3, 4, 8), "blk.norm.g": (8,),
              "blk.norm.b": (8,), "fc.w": (8, 10), "fc.b": (10,)}
    p = {k: jnp.asarray(rng.normal(size=s), jnp.float32) for k, s in shapes.items()}
    b = {k: jnp.asarray(rng.normal(size=s) * 0.1, jnp.float32) for k, s in shapes.items()}
    g = {k: jnp.asarray(rng.normal(size=s) * gscale, jnp.float32) for k, s in shapes.items()}
    m = {k: jnp.asarray(rng.random(s) > 0.3, jnp.float32) for k, s in shapes.items()}
    return p, g, b, m


def _assert_tree_equal(a, b, exact=True, err=""):
    for k in a:
        if exact:
            np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]),
                                          err_msg=f"{err} leaf {k}")
        else:
            np.testing.assert_allclose(np.asarray(a[k]), np.asarray(b[k]),
                                       rtol=2e-7, atol=2e-7,
                                       err_msg=f"{err} leaf {k}")


def test_flatspec_roundtrip_and_order():
    p, *_ = _rand_trees()
    spec = FlatSpec.of(p)
    assert spec.names == sorted(p)  # jax dict-flatten order
    flat = spec.flatten(p)
    assert flat.shape == (spec.total,)
    back = spec.unflatten(flat)
    _assert_tree_equal(back, p)


@pytest.mark.parametrize("gscale", [1e-3, 1e2])  # no-clip / clip regimes
@pytest.mark.parametrize("has", [True, None])
def test_xla_fallback_bit_identical(gscale, has):
    """The XLA fallback is bit-identical to the reference chain
    UNCONDITIONALLY -- including when the global-norm clip engages."""
    p, g, b, m = _rand_trees(gscale=gscale)
    hv = None if has is None else jnp.asarray(has)
    rp, rb = jax.jit(lambda *a: _reference_chain(*a, 0.9, 5e-4, hv))(
        p, g, b, m, jnp.float32(37.0), jnp.float32(0.05))
    fp, fb = jax.jit(lambda *a: masked_sgd_step(
        *a, momentum=0.9, weight_decay=5e-4, has=hv, mode="xla"))(
        p, g, b, m, jnp.float32(37.0), jnp.float32(0.05))
    _assert_tree_equal(fp, rp)
    _assert_tree_equal(fb, rb)


def test_pallas_kernel_bit_identical_no_clip():
    """Interpret-mode kernel forward bit-identity vs the reference chain in
    the no-clip regime (elementwise path is exactly the reference's; the
    clip scale is exactly 1.0 in both)."""
    p, g, b, m = _rand_trees(gscale=1e-3)
    has = jnp.asarray(True)
    rp, rb = jax.jit(lambda *a: _reference_chain(*a, 0.9, 5e-4, has))(
        p, g, b, m, jnp.float32(37.0), jnp.float32(0.05))
    fp, fb = jax.jit(lambda *a: masked_sgd_step(
        *a, momentum=0.9, weight_decay=5e-4, has=has, mode="pallas",
        interpret=True))(p, g, b, m, jnp.float32(37.0), jnp.float32(0.05))
    _assert_tree_equal(fp, rp)
    _assert_tree_equal(fb, rb)


def test_pallas_kernel_clip_engaged_value_agreement():
    """When clipping engages, the kernel's two-phase block-associated norm
    may differ from the per-leaf association in the last ulp -- value
    agreement is pinned at float tolerance (the XLA fallback, which the CPU
    engines actually run, stays bit-exact -- see above)."""
    p, g, b, m = _rand_trees(gscale=1e2)
    rp, rb = _reference_chain(p, g, b, m, jnp.float32(37.0), jnp.float32(0.05),
                              0.9, 5e-4, None)
    fp, fb = masked_sgd_step(p, g, b, m, 37.0, 0.05, momentum=0.9,
                             weight_decay=5e-4, mode="pallas", interpret=True)
    _assert_tree_equal(fp, rp, exact=False)
    _assert_tree_equal(fb, rb, exact=False)


@pytest.mark.parametrize("mode", ["xla", "pallas"])
def test_all_padding_batch_has_gating(mode):
    """``has=False`` (an all-padding batch) must return params and momentum
    UNTOUCHED, bit-for-bit -- no weight-decay or momentum drift."""
    p, g, b, m = _rand_trees()
    fp, fb = masked_sgd_step(p, g, b, m, 0.0, 0.05, momentum=0.9,
                             weight_decay=5e-4, has=jnp.asarray(False),
                             mode=mode, interpret=True)
    _assert_tree_equal(fp, p)
    _assert_tree_equal(fb, b)


@pytest.mark.parametrize("mode", ["xla", "pallas"])
def test_zero_width_mask_rows_at_level_e(mode):
    """Level-e width masks on a real model spec zero whole channel rows;
    the fused update must match the reference chain there AND keep the
    masked tail of masked params identically zero (weight decay sees p=0,
    momentum starts 0 -- nothing can move the inactive region)."""
    from test_models import small_cfg

    cfg = small_cfg("conv")
    model = make_model(cfg)
    params = model.init(jax.random.key(0))
    wr = 0.0625  # level e
    masks = {k: param_mask(v.shape, model.specs[k], model.groups, wr)
             for k, v in params.items()}
    p = {k: v * masks[k] for k, v in params.items()}
    b = {k: jnp.zeros_like(v) for k, v in params.items()}
    rng = np.random.default_rng(3)
    g = {k: jnp.asarray(rng.normal(size=v.shape) * 1e-3, jnp.float32)
         for k, v in params.items()}
    # jit BOTH sides: that is how the engines run them, and eager-vs-jit
    # comparisons differ by FMA contraction in the last ulp
    rp, rb = jax.jit(lambda *a: _reference_chain(*a, 0.9, 5e-4, None))(
        p, g, b, masks, jnp.float32(10.0), jnp.float32(0.05))
    fp, fb = jax.jit(lambda *a: masked_sgd_step(
        *a, momentum=0.9, weight_decay=5e-4, mode=mode, interpret=True))(
        p, g, b, masks, jnp.float32(10.0), jnp.float32(0.05))
    _assert_tree_equal(fp, rp)
    _assert_tree_equal(fb, rb)
    for k in fp:
        inactive = np.asarray(masks[k]) == 0.0
        assert np.all(np.asarray(fp[k])[inactive] == 0.0), k


def test_resolve_fused_mode():
    assert resolve_fused_mode({"fused_update": False,
                               "optimizer_name": "SGD"}) is None
    assert resolve_fused_mode({"fused_update": True,
                               "optimizer_name": "Adam"}) is None
    # True resolves by backend: xla on the CPU test mesh
    assert resolve_fused_mode({"fused_update": True,
                               "optimizer_name": "SGD"}) == "xla"
    assert resolve_fused_mode({"fused_update": "pallas",
                               "optimizer_name": "SGD"}) == "pallas"
    with pytest.raises(ValueError, match="fused_update"):
        resolve_fused_mode({"fused_update": "turbo", "optimizer_name": "SGD"})


# ---------------------------------------------------------------------------
# engine level: the acceptance matrix
# ---------------------------------------------------------------------------

def _metrics_agree(a, b, exact=True):
    for lx, ly in zip(jax.tree_util.tree_leaves(a),
                      jax.tree_util.tree_leaves(b)):
        if exact:
            np.testing.assert_array_equal(np.asarray(lx), np.asarray(ly))
        else:
            # association-level trajectories: loss/weight sums within 2%,
            # DISCRETE correct-counts may flip by a sample or two once the
            # params drift an ulp (argmax is a step function)
            np.testing.assert_allclose(np.asarray(lx), np.asarray(ly),
                                       rtol=2e-2, atol=2.0)


def _assert_tree_close(a, b):
    """Association-level trajectory agreement (see module docstring)."""
    for k in a:
        np.testing.assert_allclose(np.asarray(a[k]), np.asarray(b[k]),
                                   rtol=5e-3, atol=1e-3,
                                   err_msg=f"leaf {k}")


@pytest.fixture(scope="module")
def tiny():
    """One-local-step rounds: 8 users x 10-sample shards (== one batch),
    local_epochs=1 -- every round is exactly ONE optimizer step per client,
    so fused-vs-reference step results must match bit-for-bit (nothing can
    amortise a mismatch away)."""
    from test_models import small_cfg
    from heterofl_tpu.data import (fetch_dataset, label_split_masks,
                                   split_dataset, stack_client_shards)
    from heterofl_tpu.parallel.evaluation import Evaluator
    from test_evalfused import _batch

    cfg = small_cfg("conv", data_name="MNIST",
                    control="1_8_0.5_iid_fix_a1-b1-c1-d1-e1_bn_1_1")
    cfg["num_epochs"] = dict(cfg["num_epochs"], local=1)
    ds = fetch_dataset("MNIST", synthetic=True, seed=0,
                       synthetic_sizes={"train": 80, "test": 40})
    rng = np.random.default_rng(0)
    split, lsplit = split_dataset(ds, 8, "iid", rng, classes_size=10)
    x, y, m = stack_client_shards(ds["train"].data, ds["train"].target,
                                  split["train"], list(range(8)))
    lm = label_split_masks(lsplit, 8, 10)
    data = (jnp.asarray(x), jnp.asarray(y), jnp.asarray(m), jnp.asarray(lm))
    model = make_model(cfg)
    mesh = make_mesh(8, 1)
    te = ds["test"]
    ev = Evaluator(model, cfg, mesh, seed=0)
    xg, wg = _batch(te.data, 20)
    yg, _ = _batch(te.target, 20)
    fe = ev.fused(
        sbn_batches=_batch(ds["train"].data, 20),
        local_eval=(te.data[:32].reshape(8, 1, 4, 28, 28, 1),
                    te.target[:32].reshape(8, 1, 4),
                    np.ones((8, 1, 4), np.float32),
                    np.ones((8, 10), np.float32)),
        global_eval=(xg, yg, wg))
    return {"cfg": cfg, "data": data, "model": model, "mesh": mesh,
            "fused_eval": fe}


@pytest.mark.parametrize("cell", ["masked-replicated", "masked-sharded",
                                  "grouped-span", "grouped-slices"])
def test_fused_step_results_bit_identical_matrix(tiny, cell):
    """THE acceptance matrix: fused-epilogue step results are BIT-IDENTICAL
    to the reference op chain for masked x {replicated, sharded} and
    grouped x {span, slices}, K in {1, 8}, with and without the eval mask
    -- params and metrics, after 17 one-step rounds spanning the one-round
    program, the train superstep and the eval-fused superstep."""
    cfg, model, mesh, data = (tiny["cfg"], tiny["model"], tiny["mesh"],
                              tiny["data"])
    fe = tiny["fused_eval"]
    rates_vec = np.asarray(cfg["model_rate"], np.float32)
    outs = {}
    for name, over in [("fused", {}), ("ref", {"fused_update": False})]:
        if cell.startswith("grouped"):
            eng = GroupedRoundEngine(
                dict(cfg, level_placement=cell.split("-")[1], **over), mesh)
            p = model.init(jax.random.key(0))
            ui = np.array([0, 2, 4, 6, 1, 3])
            p, ms1 = eng.train_round(p, ui, rates_vec[ui], data, 0.05,
                                     jax.random.key(1))
            us = _sched(cfg, 2, 8)
            p, pend = eng.train_superstep(p, HOST_KEY, 2, 8, us,
                                          rates_vec[us], data)
            ms8 = pend.fetch()
            us = _sched(cfg, 10, 8)
            p, pend = eng.train_superstep(p, HOST_KEY, 10, 8, us,
                                          rates_vec[us], data,
                                          eval_mask=(False,) * 7 + (True,),
                                          fused_eval=fe)
            mse = pend.fetch()
        else:
            d = data
            if cell == "masked-sharded":
                d = shard_client_data(mesh, data)
                eng = RoundEngine(model,
                                  dict(cfg, data_placement="sharded", **over),
                                  mesh)
            else:
                eng = RoundEngine(model, dict(cfg, **over), mesh)
            p = model.init(jax.random.key(0))
            p, ms1 = eng.train_round(p, jax.random.key(1), 0.05,
                                     np.array([0, 2, 4, 6]), d)
            kw = {"user_schedule": _sched(cfg, 2, 8)} \
                if cell == "masked-sharded" else {"num_active": 4}
            p, pend = eng.train_superstep(p, HOST_KEY, 2, 8, d, **kw)
            ms8 = pend.fetch()
            kw = {"user_schedule": _sched(cfg, 10, 8)} \
                if cell == "masked-sharded" else {"num_active": 4}
            p, pend = eng.train_superstep(p, HOST_KEY, 10, 8, d,
                                          eval_mask=(False,) * 7 + (True,),
                                          fused_eval=fe, **kw)
            mse = pend.fetch()
        outs[name] = (jax.device_get(p), jax.device_get(ms1), ms8, mse)
    _assert_tree_equal(outs["fused"][0], outs["ref"][0], err=cell)
    _metrics_agree(outs["fused"][1], outs["ref"][1])
    _metrics_agree(outs["fused"][2], outs["ref"][2])
    _metrics_agree(outs["fused"][3], outs["ref"][3])


@pytest.fixture(scope="module")
def vision():
    cfg, ds, data = _vision_setup()
    return {"cfg": cfg, "ds": ds, "data": data,
            "model": make_model(cfg), "mesh": make_mesh(8, 1)}


@pytest.fixture(scope="module")
def fused_eval(vision):
    """One FusedEval shared by the fused and reference engines (the eval
    phase is untouched by fused_update; sharing pins identical operands)."""
    from test_evalfused import _batch
    from heterofl_tpu.parallel.evaluation import Evaluator

    ds, cfg = vision["ds"], vision["cfg"]
    te = ds["test"]
    sbn_b = _batch(ds["train"].data, 20)
    xu = te.data[:96].reshape(8, 1, 12, 28, 28, 1)
    yu = te.target[:96].reshape(8, 1, 12)
    wu = np.ones((8, 1, 12), np.float32)
    lmu = np.ones((8, 10), np.float32)
    xg, wg = _batch(te.data, 20)
    yg, _ = _batch(te.target, 20)
    ev = Evaluator(vision["model"], cfg, vision["mesh"], seed=0)
    return ev.fused(sbn_batches=sbn_b, local_eval=(xu, yu, wu, lmu),
                    global_eval=(xg, yg, wg))


def _sched(cfg, epoch0, k, num_active=4):
    return np.stack([
        np.asarray(round_users(jax.random.fold_in(HOST_KEY, epoch0 + r),
                               cfg["num_users"], num_active))
        for r in range(k)])


def test_fused_masked_replicated_trajectory(vision, fused_eval):
    """masked x replicated, K in {1, 8}, with and without the eval mask:
    multi-step-round trajectories agree at float-association level (the
    bitwise step-level contract is test_fused_step_results_bit_identical_
    matrix)."""
    cfg, model, mesh, data = (vision["cfg"], vision["model"], vision["mesh"],
                              vision["data"])
    outs = {}
    for name, over in [("fused", {}), ("ref", {"fused_update": False})]:
        eng = RoundEngine(model, dict(cfg, **over), mesh)
        p = model.init(jax.random.key(0))
        # K=1: the one-round program
        p, ms1 = eng.train_round(p, jax.random.key(1), 0.05,
                                 np.array([0, 2, 4, 6]), data)
        # K=8 train-only superstep (in-jit sampling)
        p, pend = eng.train_superstep(p, HOST_KEY, 2, 8, data, num_active=4)
        ms8 = pend.fetch()
        # K=8 with the eval mask (eval inside the scanned program)
        p, pend = eng.train_superstep(p, HOST_KEY, 10, 8, data, num_active=4,
                                      eval_mask=(False,) * 7 + (True,),
                                      fused_eval=fused_eval)
        mse = pend.fetch()
        outs[name] = (jax.device_get(p), jax.device_get(ms1), ms8, mse)
    _assert_tree_close(outs["fused"][0], outs["ref"][0])
    _metrics_agree(outs["fused"][1], outs["ref"][1], exact=False)
    _metrics_agree(outs["fused"][2], outs["ref"][2], exact=False)
    _metrics_agree(outs["fused"][3], outs["ref"][3], exact=False)


def test_fused_masked_sharded_trajectory(vision, fused_eval):
    """masked x sharded placement, K in {1, 8}, with and without eval
    (association-level; see the step-level matrix test for bitwise)."""
    cfg, model, mesh = vision["cfg"], vision["model"], vision["mesh"]
    data_sh = shard_client_data(mesh, vision["data"])
    outs = {}
    for name, over in [("fused", {}), ("ref", {"fused_update": False})]:
        eng = RoundEngine(model, dict(cfg, data_placement="sharded", **over),
                          mesh)
        p = model.init(jax.random.key(0))
        p, ms1 = eng.train_round(p, jax.random.key(1), 0.05,
                                 np.array([1, 3, 5, 7]), data_sh)
        p, pend = eng.train_superstep(p, HOST_KEY, 2, 8, data_sh,
                                      user_schedule=_sched(cfg, 2, 8))
        ms8 = pend.fetch()
        p, pend = eng.train_superstep(p, HOST_KEY, 10, 8, data_sh,
                                      user_schedule=_sched(cfg, 10, 8),
                                      eval_mask=(False,) * 7 + (True,),
                                      fused_eval=fused_eval)
        mse = pend.fetch()
        outs[name] = (jax.device_get(p), jax.device_get(ms1), ms8, mse)
    _assert_tree_close(outs["fused"][0], outs["ref"][0])
    _metrics_agree(outs["fused"][1], outs["ref"][1], exact=False)
    _metrics_agree(outs["fused"][2], outs["ref"][2], exact=False)
    _metrics_agree(outs["fused"][3], outs["ref"][3], exact=False)


@pytest.mark.parametrize("placement", ["span", "slices"])
def test_fused_grouped_trajectory(vision, fused_eval, placement):
    """grouped x {span, slices}, K in {1, 8}, with and without eval
    (association-level; see the step-level matrix test for bitwise)."""
    cfg, model, mesh, data = (vision["cfg"], vision["model"], vision["mesh"],
                              vision["data"])
    rates_vec = np.asarray(cfg["model_rate"], np.float32)
    user_idx = np.array([0, 2, 4, 6, 1, 3])
    outs = {}
    for name, over in [("fused", {}), ("ref", {"fused_update": False})]:
        grp = GroupedRoundEngine(
            dict(cfg, level_placement=placement, **over), mesh)
        p = model.init(jax.random.key(0))
        p, ms1 = grp.train_round(p, user_idx, rates_vec[user_idx], data,
                                 0.05, jax.random.key(1))
        us = _sched(cfg, 2, 8)
        p, pend = grp.train_superstep(p, HOST_KEY, 2, 8, us, rates_vec[us],
                                      data)
        ms8 = pend.fetch()
        us = _sched(cfg, 10, 8)
        p, pend = grp.train_superstep(p, HOST_KEY, 10, 8, us, rates_vec[us],
                                      data, eval_mask=(False,) * 7 + (True,),
                                      fused_eval=fused_eval)
        mse = pend.fetch()
        outs[name] = (jax.device_get(p), ms1, ms8, mse)
    _assert_tree_close(outs["fused"][0], outs["ref"][0])
    _metrics_agree(outs["fused"][1], outs["ref"][1], exact=False)
    _metrics_agree(outs["fused"][2], outs["ref"][2], exact=False)
    _metrics_agree(outs["fused"][3], outs["ref"][3], exact=False)


@pytest.mark.slow
def test_fused_lm_round_bit_identical():
    """The LM local step (no has-gating, sequence-parallel axis) keeps the
    same contract."""
    cfg, data = _lm_setup()
    model = make_model(cfg)
    mesh = make_mesh(2, 2)
    outs = {}
    for name, over in [("fused", {}), ("ref", {"fused_update": False})]:
        eng = RoundEngine(model, dict(cfg, **over), mesh)
        p = model.init(jax.random.key(0))
        p, _ = eng.train_round(p, jax.random.key(1), 0.05,
                               np.array([0, 1, 2, 3]), data)
        outs[name] = jax.device_get(p)
    _assert_tree_equal(outs["fused"], outs["ref"])


def test_non_sgd_optimizer_keeps_reference_chain(vision):
    """A non-SGD optimizer silently keeps the reference chain (fused mode
    resolves to None) and the round still runs."""
    cfg, model, mesh, data = (vision["cfg"], vision["model"], vision["mesh"],
                              vision["data"])
    eng = RoundEngine(model, dict(cfg, optimizer_name="Adam"), mesh)
    assert eng._fused_mode is None
    p = model.init(jax.random.key(0))
    p, ms = eng.train_round(p, jax.random.key(1), 0.01,
                            np.array([0, 2]), data)
    assert np.isfinite(np.asarray(ms["loss_sum"])).all()


@pytest.mark.slow
def test_fused_resnet_single_step_bit_identical():
    """ResNet-18 depth: one local step is bitwise exact fused-vs-reference
    -- the per-step math is the reference chain's.  (Multi-round ResNet
    trajectories diverge at float-association level: XLA's global fusion
    choices shift one reduce emission by 1 ulp somewhere in the ~400-fusion
    loop body and SGD amplifies it chaotically -- the same class of
    agreement as the masked-vs-sliced engine contract.  The conv/LM matrix
    above is bitwise at trajectory level.)"""
    from heterofl_tpu import config as C
    from heterofl_tpu.data import (fetch_dataset, label_split_masks,
                                   split_dataset, stack_client_shards)

    users = 8
    cfg = C.default_cfg()
    cfg["control"] = C.parse_control_name(
        f"1_{users}_0.5_iid_fix_a1-b1-c1-d1-e1_bn_1_1")
    cfg["data_name"], cfg["model_name"], cfg["synthetic"] = \
        "MNIST", "resnet18", True
    cfg = C.process_control(cfg)
    cfg["resnet"] = {"hidden_size": [8, 16, 16, 16]}
    cfg["classes_size"] = 10
    cfg["num_epochs"] = dict(cfg["num_epochs"], local=1)
    ds = fetch_dataset("MNIST", synthetic=True, seed=0,
                       synthetic_sizes={"train": 80, "test": 40})
    rng = np.random.default_rng(0)
    split, lsplit = split_dataset(ds, users, "iid", rng, classes_size=10)
    x, y, m = stack_client_shards(ds["train"].data, ds["train"].target,
                                  split["train"], list(range(users)))
    lm = label_split_masks(lsplit, users, 10)
    data = (jnp.asarray(x), jnp.asarray(y), jnp.asarray(m), jnp.asarray(lm))
    model = make_model(cfg)
    mesh = make_mesh(8, 1)
    outs = {}
    for name, over in [("fused", {}), ("ref", {"fused_update": False})]:
        eng = RoundEngine(model, dict(cfg, **over), mesh)
        p = model.init(jax.random.key(0))
        p, _ = eng.train_round(p, jax.random.key(0), 0.1, np.arange(8), data)
        outs[name] = jax.device_get(p)
    _assert_tree_equal(outs["fused"], outs["ref"])
