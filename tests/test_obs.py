"""Runtime telemetry (ISSUE 10): in-program health probes, run tracing,
and the non-finite watchdog.

Contracts under test:

* ``telemetry='off'`` (the default) changes NOTHING: engines build the
  same outputs and ``telemetry='on'`` runs produce BIT-IDENTICAL params
  and train metrics to off runs across masked x {replicated, sharded} /
  grouped x {span, slices} x K in {1, 8} -- the probes are pure
  observers of the round, never participants.
* probe values equal host-recomputed references on a small program
  (update norm vs the sequential param trajectory, per-level
  participation vs the rate table, grad == update under dense sync).
* the watchdog trips on an injected NaN (and on loss spikes vs the
  rolling median), warn and abort modes both.
* the trace recorder's ``trace.json`` is a loadable Chrome trace and
  every ``events.jsonl`` line round-trips through the schema validator.
"""

import json
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from heterofl_tpu import config as C
from heterofl_tpu.fed.core import (round_users, superstep_rate_schedule,
                                   superstep_user_schedule)
from heterofl_tpu.models import make_model
from heterofl_tpu.obs import (TelemetrySpec, resolve_telemetry_cfg,
                              split_probes)
from heterofl_tpu.obs.trace import TraceRecorder, validate_event
from heterofl_tpu.obs.watchdog import Watchdog, WatchdogError
from heterofl_tpu.parallel import (GroupedRoundEngine, RoundEngine,
                                   make_mesh, shard_client_data)
from heterofl_tpu.utils.logger import Logger

from test_round import _vision_setup

HOST_KEY = jax.random.key(0)


def _params_equal(a, b):
    for k in a:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]),
                                      err_msg=k)


def _train_rounds(out):
    return out["train"] if isinstance(out, dict) else out


def _metrics_equal(off_out, on_out, k):
    off_r, on_r = _train_rounds(off_out), _train_rounds(on_out)
    for r in range(k):
        for name in ("loss_sum", "score_sum", "n", "rate"):
            np.testing.assert_array_equal(np.asarray(off_r[r][name]),
                                          np.asarray(on_r[r][name]),
                                          err_msg=f"round {r} {name}")


# ---------------------------------------------------------------------------
# telemetry-off bit-identity: on-vs-off params + metrics, probe presence
# ---------------------------------------------------------------------------

def test_masked_replicated_k1_on_off_bit_identical():
    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    mesh = make_mesh(4, 1)
    uidx = np.array([0, 2, 4, 6])
    results = {}
    for tel in ("off", "on"):
        eng = RoundEngine(model, dict(cfg, telemetry=tel), mesh)
        p = model.init(jax.random.key(0))
        p, ms = eng.train_round(p, jax.random.key(1), 0.05, uidx, data)
        results[tel] = (p, {k: np.asarray(v) for k, v in ms.items()})
    p_off, ms_off = results["off"]
    p_on, ms_on = results["on"]
    assert not any(k.startswith("obs_") for k in ms_off)
    _params_equal(p_off, p_on)
    clean, probes = split_probes(ms_on, 4)
    assert len(probes) == 1 and set(clean) == set(ms_off)
    for name in ms_off:
        np.testing.assert_array_equal(ms_off[name], clean[name], err_msg=name)
    rec = probes[0]
    assert rec["nonfinite"] == 0 and np.isfinite(rec["update_norm"])


@pytest.mark.parametrize("k", [1, 8])
def test_masked_replicated_superstep_on_off_bit_identical(k):
    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    mesh = make_mesh(4, 1)
    outs = {}
    for tel in ("off", "on"):
        eng = RoundEngine(model, dict(cfg, telemetry=tel), mesh)
        p = model.init(jax.random.key(0))
        p, pending = eng.train_superstep(p, HOST_KEY, 1, k, data, num_active=4)
        outs[tel] = (p, pending.fetch())
    _params_equal(outs["off"][0], outs["on"][0])
    _metrics_equal(outs["off"][1], outs["on"][1], k)
    assert isinstance(outs["off"][1], list)
    probes = outs["on"][1]["obs"]
    assert len(probes) == k
    for rec in probes:
        assert rec["nonfinite"] == 0
        assert sum(rec["participation"]) == 4.0  # the active cohort


def test_masked_sharded_superstep_on_off_bit_identical():
    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    mesh = make_mesh(4, 1)
    k = 8
    sched = superstep_user_schedule(HOST_KEY, 1, k, cfg["num_users"], 4)
    outs = {}
    for tel in ("off", "on"):
        eng = RoundEngine(model, dict(cfg, data_placement="sharded",
                                      telemetry=tel), mesh)
        data_sh = shard_client_data(mesh, tuple(np.asarray(a) for a in data))
        p = model.init(jax.random.key(0))
        p, pending = eng.train_superstep(p, HOST_KEY, 1, k, data_sh,
                                         user_schedule=sched)
        outs[tel] = (p, pending.fetch())
    _params_equal(outs["off"][0], outs["on"][0])
    _metrics_equal(outs["off"][1], outs["on"][1], k)
    assert len(outs["on"][1]["obs"]) == k


@pytest.mark.parametrize("placement,k", [("span", 1), ("span", 8),
                                         ("slices", 8)])
def test_grouped_superstep_on_off_bit_identical(placement, k):
    cfg, ds, data = _vision_setup()
    mesh = make_mesh(8, 1)  # slices needs >= 5 device rows (one per level)
    model = make_model(cfg)
    users = cfg["num_users"]
    sched = superstep_user_schedule(HOST_KEY, 1, k, users, users)
    rates = superstep_rate_schedule(HOST_KEY, 1, k, cfg, sched)
    outs = {}
    for tel in ("off", "on"):
        grp = GroupedRoundEngine(dict(cfg, level_placement=placement,
                                      telemetry=tel), mesh)
        p = model.init(jax.random.key(0))
        p, pending = grp.train_superstep(p, HOST_KEY, 1, k, sched, rates, data)
        outs[tel] = (p, pending.fetch())
    _params_equal(outs["off"][0], outs["on"][0])
    _metrics_equal(outs["off"][1], outs["on"][1], k)
    probes = outs["on"][1]["obs"]
    assert len(probes) == k
    for rec in probes:
        assert rec["nonfinite"] == 0
        assert sum(rec["participation"]) == users  # all users active


def test_grouped_k1_host_path_refuses_telemetry():
    cfg, ds, data = _vision_setup()
    mesh = make_mesh(4, 1)
    grp = GroupedRoundEngine(dict(cfg, telemetry="on"), mesh)
    rates = np.asarray(cfg["model_rate"], np.float32)
    uidx = np.array([0, 1, 2, 3])
    p = make_model(cfg).init(jax.random.key(0))
    with pytest.raises(ValueError, match="telemetry"):
        grp.train_round(p, uidx, rates[uidx], data, 0.05, jax.random.key(1))


# ---------------------------------------------------------------------------
# probe values vs host-recomputed references
# ---------------------------------------------------------------------------

def test_probe_values_match_host_reference():
    """update_norm matches the sequential param trajectory, participation
    matches the drawn cohort's rate table, grad == update under dense sync
    (the stale rule zeroes both where no client contributed)."""
    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    mesh = make_mesh(4, 1)
    k, A = 2, 4
    # sequential reference: train_round consuming the same streams is
    # bit-identical to the superstep (the PR 2 contract), so its param
    # trajectory IS the reference for the in-program update norm
    eng_ref = RoundEngine(model, cfg, mesh)
    p = model.init(jax.random.key(0))
    ref_norm, ref_part = [], []
    rates_vec = np.asarray(cfg["model_rate"], np.float32)
    levels = sorted({float(r) for r in rates_vec}, reverse=True)
    from heterofl_tpu.utils.optim import make_traced_lr_fn

    lr_fn = make_traced_lr_fn(cfg)
    for r in range(k):
        key = jax.random.fold_in(HOST_KEY, 1 + r)
        uidx = np.asarray(round_users(key, cfg["num_users"], A))
        lr = float(np.asarray(lr_fn(jnp.int32(1 + r))))
        # host snapshot BEFORE the dispatch: train_round donates the carry
        p_host = {n: np.asarray(v, np.float64) for n, v in p.items()}
        p, _ = eng_ref.train_round(p, key, lr, uidx, data)
        delta_sq = sum(np.sum((np.asarray(p[n], np.float64)
                               - p_host[n]) ** 2) for n in p)
        ref_norm.append(float(np.sqrt(delta_sq)))
        ref_part.append([float((rates_vec[uidx] == lvl).sum())
                         for lvl in levels])

    eng = RoundEngine(model, dict(cfg, telemetry="on"), mesh)
    p0 = model.init(jax.random.key(0))
    _, pending = eng.train_superstep(p0, HOST_KEY, 1, k, data, num_active=A)
    probes = pending.fetch()["obs"]
    for r in range(k):
        np.testing.assert_allclose(probes[r]["update_norm"], ref_norm[r],
                                   rtol=1e-4, err_msg=f"round {r}")
        assert probes[r]["participation"] == ref_part[r], f"round {r}"
        # dense sync: the pseudo-gradient IS the applied update
        np.testing.assert_allclose(probes[r]["grad_norm"],
                                   probes[r]["update_norm"], rtol=1e-6)
        assert probes[r]["resid_norm"] == 0.0
        assert probes[r]["stale_norm"] == 0.0
        assert probes[r]["nonfinite"] == 0


def test_probe_resid_norm_under_wire_codec():
    """A lossy codec's error-feedback residual shows up in the probes (and
    the codec program still runs telemetry without new carries)."""
    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    mesh = make_mesh(4, 1)
    eng = RoundEngine(model, dict(cfg, telemetry="on", wire_codec="int8"),
                      mesh)
    p = model.init(jax.random.key(0))
    _, pending = eng.train_superstep(p, HOST_KEY, 1, 2, data, num_active=4)
    probes = pending.fetch()["obs"]
    assert probes[-1]["resid_norm"] > 0.0  # stochastic rounding left error
    assert np.isfinite(probes[-1]["resid_norm"])


def test_probe_stale_mass_under_buffered_aggregation():
    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    mesh = make_mesh(4, 1)
    eng = RoundEngine(model, dict(cfg, telemetry="on",
                                  schedule={"aggregation": "buffered"}), mesh)
    p = model.init(jax.random.key(0))
    _, pending = eng.train_superstep(p, HOST_KEY, 1, 2, data, num_active=4)
    probes = pending.fetch()["obs"]
    # every round buffers its fresh reduction: the pending mass is nonzero
    assert probes[0]["stale_norm"] > 0.0
    assert probes[1]["stale_norm"] > 0.0


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------

def test_watchdog_trips_on_injected_nan():
    """A NaN planted in the params carry reaches the in-program non-finite
    counter, and the watchdog trips on it at the fetch boundary."""
    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    mesh = make_mesh(4, 1)
    eng = RoundEngine(model, dict(cfg, telemetry="on"), mesh)
    p = model.init(jax.random.key(0))
    name = next(iter(p))
    bad = np.asarray(p[name]).copy()
    bad.flat[0] = np.nan
    p[name] = jnp.asarray(bad)
    _, ms = eng.train_round(p, jax.random.key(1), 0.05,
                            np.array([0, 2, 4, 6]), data)
    _, probes = split_probes({k: np.asarray(v) for k, v in ms.items()}, 4)
    assert probes[0]["nonfinite"] >= 1
    spec = resolve_telemetry_cfg({"telemetry": "on"}).watchdog
    wd = Watchdog(spec)
    with pytest.warns(UserWarning, match="nonfinite"):
        events = wd.check(1, probes=probes[0], loss=2.0)
    assert events and wd.fired and events[0]["kind"] == "nonfinite"
    spec_abort = resolve_telemetry_cfg(
        {"telemetry": "on", "watchdog": {"action": "abort"}}).watchdog
    wd2 = Watchdog(spec_abort)
    with pytest.warns(UserWarning):
        with pytest.raises(WatchdogError, match="nonfinite"):
            wd2.check(1, probes=probes[0], loss=2.0)


def test_watchdog_loss_spike_rolling_median():
    spec = resolve_telemetry_cfg(
        {"telemetry": "on",
         "watchdog": {"spike_factor": 3.0, "window": 4}}).watchdog
    wd = Watchdog(spec)
    for e, loss in enumerate([1.0, 1.1, 0.9, 1.0], start=1):
        assert wd.check(e, probes={"nonfinite": 0}, loss=loss) == []
    with pytest.warns(UserWarning, match="loss-spike"):
        events = wd.check(5, probes={"nonfinite": 0}, loss=10.0)
    assert events[0]["kind"] == "loss-spike"
    # a non-finite loss trips its own kind without median history
    with pytest.warns(UserWarning, match="loss-nonfinite"):
        wd.check(6, probes={"nonfinite": 0}, loss=float("nan"))
    assert len(wd.fired) == 2


def test_telemetry_config_validation():
    with pytest.raises(ValueError, match="telemetry"):
        resolve_telemetry_cfg({"telemetry": "sometimes"})
    with pytest.raises(ValueError, match="watchdog"):
        resolve_telemetry_cfg({"watchdog": {"action": "warn"}})  # off mode
    with pytest.raises(ValueError, match="spike_factor"):
        resolve_telemetry_cfg({"telemetry": "on",
                               "watchdog": {"spike_factor": 0.5}})
    with pytest.raises(ValueError, match="watchdog keys"):
        resolve_telemetry_cfg({"telemetry": "on", "watchdog": {"limit": 1}})
    spec = resolve_telemetry_cfg({"telemetry": "on",
                                  "watchdog": {"action": "off"}})
    assert isinstance(spec, TelemetrySpec)
    assert spec.probes and spec.watchdog is None
    assert resolve_telemetry_cfg({}).probes is False


# ---------------------------------------------------------------------------
# trace recorder: Chrome trace + events.jsonl schema round-trip
# ---------------------------------------------------------------------------

def test_trace_events_schema_roundtrip(tmp_path):
    from heterofl_tpu.parallel import PhaseTimer

    rec = TraceRecorder(str(tmp_path / "t"))
    timer = PhaseTimer()
    timer.trace = rec  # the PhaseTimer hook files phases on the timeline
    with timer.phase("dispatch"):
        pass
    with rec.span("superstep", args={"epoch0": 1, "k": 8}):
        rec.instant("probes", cat="obs", args={"epoch": 1, "nonfinite": 0})
    path = rec.close()
    assert rec.close() == path  # idempotent
    trace = json.load(open(path))
    names = [e["name"] for e in trace["traceEvents"]]
    assert {"dispatch", "superstep", "probes"} <= set(names)
    for ev in trace["traceEvents"]:
        assert {"name", "cat", "ph", "ts", "pid", "tid"} <= set(ev)
        if ev["ph"] == "X":
            assert "dur" in ev
    lines = [json.loads(l) for l in open(rec.events_path)]
    assert len(lines) == len(trace["traceEvents"])
    for line in lines:
        # schema round-trip: validate -> serialize -> parse -> validate
        again = json.loads(json.dumps(validate_event(line)))
        assert validate_event(again) == line
    # the X events carry durations, the instants do not
    sup = next(l for l in lines if l["name"] == "superstep")
    assert sup["ph"] == "X" and sup["dur_s"] >= 0
    assert sup["args"] == {"epoch0": 1, "k": 8}


def test_validate_event_rejects_malformed():
    good = {"v": 1, "t": 0.0, "name": "x", "cat": "driver", "ph": "i",
            "args": {}}
    validate_event(good)
    with pytest.raises(ValueError, match="version"):
        validate_event({**good, "v": 2})
    with pytest.raises(ValueError, match="required"):
        validate_event({k: v for k, v in good.items() if k != "name"})
    with pytest.raises(ValueError, match="dur_s"):
        validate_event({**good, "ph": "X"})
    with pytest.raises(ValueError, match="unknown"):
        validate_event({**good, "extra": 1})


# ---------------------------------------------------------------------------
# Logger satellites: structured emit + the un-swallowed tensorboard failure
# ---------------------------------------------------------------------------

def test_logger_emit_structured_obs_event(tmp_path):
    logger = Logger(str(tmp_path / "runs"))
    logger.emit({"event": "probes", "epoch": 1})  # closed writer: no-op
    logger.safe(True)
    logger.emit({"event": "probes", "epoch": 2, "update_norm": 1.5})
    logger.safe(False)
    recs = [json.loads(l) for l in open(tmp_path / "runs" / "log.jsonl")]
    obs = [r for r in recs if r.get("tag") == "obs"]
    assert len(obs) == 1
    assert obs[0]["event"] == "probes" and obs[0]["epoch"] == 2
    assert obs[0]["update_norm"] == 1.5 and "t" in obs[0]


def test_logger_warns_on_tensorboard_import_failure(tmp_path, monkeypatch):
    # poison the import: a None sys.modules entry raises ImportError
    monkeypatch.setitem(sys.modules, "torch.utils.tensorboard", None)
    logger = Logger(str(tmp_path / "runs"), use_tensorboard=True)
    with pytest.warns(UserWarning, match="tensorboard"):
        logger.safe(True)
    assert logger.writer is None
    logger.safe(False)
    logger.safe(True)  # warned ONCE per Logger, degraded mode proceeds
    logger.safe(False)


# ---------------------------------------------------------------------------
# driver integration: end-to-end telemetry + tracing, and loud conflicts
# ---------------------------------------------------------------------------

def _driver_cfg(out_dir, **over):
    cfg = C.default_cfg()
    cfg["control"] = C.parse_control_name("1_8_0.5_iid_fix_a1-b1-c1-d1-e1_bn_1_1")
    cfg["data_name"] = "MNIST"
    cfg["model_name"] = "conv"
    cfg["synthetic"] = True
    cfg["synthetic_sizes"] = {"train": 400, "test": 100}
    cfg["output_dir"] = str(out_dir)
    cfg["override"] = {"num_epochs": {"global": 4, "local": 2},
                       "conv": {"hidden_size": [8, 16]},
                       "superstep_rounds": 2, "eval_interval": 2, **over}
    return C.process_control(cfg)


def test_driver_run_with_telemetry_and_trace(tmp_path):
    from heterofl_tpu.entry.common import FedExperiment

    cfg = _driver_cfg(tmp_path, telemetry="on",
                      trace_dir=str(tmp_path / "trace"))
    exp = FedExperiment(cfg, 0)
    exp.run("Global-Accuracy")
    tdir = tmp_path / "trace" / exp.tag
    trace = json.load(open(tdir / "trace.json"))
    names = {e["name"] for e in trace["traceEvents"]}
    assert {"superstep", "checkpoint", "probes", "dispatch"} <= names
    for line in open(tdir / "events.jsonl"):
        validate_event(json.loads(line))
    log = tmp_path / "runs" / f"train_{exp.tag}" / "log.jsonl"
    obs = [json.loads(l) for l in open(log)
           if json.loads(l).get("tag") == "obs"]
    assert len(obs) == 4  # one probe record per round
    assert [o["epoch"] for o in obs] == [1, 2, 3, 4]
    assert all(o["nonfinite"] == 0 for o in obs)


def test_driver_telemetry_conflicts_fail_loudly(tmp_path):
    from heterofl_tpu.entry.common import FedExperiment

    with pytest.raises(ValueError, match="mesh-native"):
        FedExperiment(_driver_cfg(tmp_path, telemetry="on",
                                  strategy="sliced", superstep_rounds=1), 0)
    with pytest.raises(ValueError, match="fused superstep"):
        FedExperiment(_driver_cfg(tmp_path, telemetry="on",
                                  strategy="grouped", superstep_rounds=1), 0)
