"""The two driver-facing contracts: bench.py's single JSON line and
__graft_entry__'s compile/dry-run hooks."""

import pytest

import json
import os
import subprocess
import sys

import numpy as np

# runs bench.py / dryrun children with multi-minute timeouts (fast gate excludes this module)
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_emits_schema_json():
    env = dict(os.environ)
    env.update({"BENCH_CPU": "1", "BENCH_USERS": "5", "BENCH_SYNTH_N": "100",
                "BENCH_ROUNDS": "1", "BENCH_HIDDEN": "4,8,8,8",
                "PYTHONPATH": REPO})
    out = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                         capture_output=True, text=True, timeout=420, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    line = out.stdout.strip().splitlines()[-1]
    rec = json.loads(line)
    assert set(rec) >= {"metric", "value", "unit", "vs_baseline"}
    assert rec["unit"] == "rounds/sec" and rec["value"] > 0
    # degraded runs (here: BENCH_HIDDEN shrink) must NOT claim comparability
    # to the 10 rps north star (VERDICT r4 item 5)
    assert rec["vs_baseline"] is None
    assert rec["extra"]["degraded"].startswith("hidden-shrink")
    assert np.isfinite(rec["extra"]["final_loss"])


def test_bench_deadline_wedged_tpu_falls_back():
    """A wedged TPU claim (simulated) must be killed at BENCH_TPU_TIMEOUT and
    the CPU fallback must still print the one JSON line, rc 0."""
    env = dict(os.environ)
    env.update({"BENCH_FAKE_WEDGE": "1", "BENCH_TPU_TIMEOUT": "3",
                "BENCH_DEADLINE": "400", "BENCH_USERS": "5",
                "BENCH_SYNTH_N": "100", "BENCH_ROUNDS": "1",
                "BENCH_HIDDEN": "4,8,8,8", "PYTHONPATH": REPO})
    out = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                         capture_output=True, text=True, timeout=420, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["value"] > 0 and rec["extra"]["platform"] == "cpu"


def test_bench_total_failure_still_prints_line():
    """Even when the TPU wedges AND the fallback crashes, bench.py prints a
    parseable record and exits 0 (the round-1 parsed:null failure mode)."""
    env = dict(os.environ)
    env.update({"BENCH_FAKE_WEDGE": "1", "BENCH_TPU_TIMEOUT": "3",
                "BENCH_DEADLINE": "60", "BENCH_HIDDEN": "bogus",
                "PYTHONPATH": REPO})
    out = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                         capture_output=True, text=True, timeout=120, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert set(rec) >= {"metric", "value", "unit", "vs_baseline"}
    assert rec["value"] == 0.0 and "error" in rec["extra"]


def test_graft_entry_contract():
    import jax

    import __graft_entry__ as g

    fn, args = g.entry()
    loss, score = jax.jit(fn)(*args)
    assert np.isfinite(float(loss))
    assert score.shape[-1] == 10
    g.dryrun_multichip(2)
    g.dryrun_multichip(8)  # 2-D mesh path (4 clients x 2 data)
