"""Federation algebra: distribute/combine identities, nesting, counted
averaging, label-split restriction, stale-value fallback (ref fed.py:180-298)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from heterofl_tpu import config as C
from heterofl_tpu.fed import (
    active_indices,
    client_count_masks,
    combine_counted,
    distribute_masked,
    embed_sliced,
    extract_sliced,
    sample_model_rates,
)
from heterofl_tpu.models import make_model
from heterofl_tpu.models.spec import mask_params

from test_models import small_cfg


def _model_and_params(model_name="conv", **kw):
    cfg = small_cfg(model_name, **kw)
    m = make_model(cfg)
    p = m.init(jax.random.key(0))
    return cfg, m, p


def test_nesting_invariant():
    """rate r's active set is a subset of rate r' for every r < r' (every group)."""
    _, m, p = _model_and_params("resnet18")
    rates = [0.0625, 0.125, 0.25, 0.5, 1.0]
    for g in m.groups.values():
        for lo, hi in zip(rates, rates[1:]):
            a, b = set(active_indices(g, lo).tolist()), set(active_indices(g, hi).tolist())
            assert a <= b, f"group {g.name}: {lo} not nested in {hi}"


def test_extract_embed_matches_mask():
    """embed_sliced(extract_sliced(p)) == mask_params(p): the sliced and masked
    views of distribute are the same object."""
    _, m, p = _model_and_params("conv")
    rate = 0.25
    pn = {k: np.asarray(v) for k, v in p.items()}
    sliced = extract_sliced(pn, m.specs, m.groups, rate)
    back = embed_sliced(sliced, m.specs, m.groups, rate, {k: v.shape for k, v in pn.items()})
    masked = mask_params(p, m.specs, m.groups, rate)
    for k in pn:
        np.testing.assert_allclose(back[k], np.asarray(masked[k]), err_msg=k)


def test_combine_identity_homogeneous():
    """All clients at rate 1 with unchanged params -> global unchanged."""
    _, m, p = _model_and_params("conv")
    lm = jnp.ones(10)
    n_clients = 3
    summed = {k: jnp.zeros_like(v) for k, v in p.items()}
    counts = {k: jnp.zeros_like(v) for k, v in p.items()}
    for _ in range(n_clients):
        cm = client_count_masks(p, m, 1.0, lm)
        local = distribute_masked(p, m, 1.0)
        summed = {k: summed[k] + local[k] * cm[k] for k in p}
        counts = {k: counts[k] + cm[k] for k in p}
    new = combine_counted(p, summed, counts)
    for k in p:
        np.testing.assert_allclose(np.asarray(new[k]), np.asarray(p[k]), rtol=1e-6, err_msg=k)


def test_combine_counted_average_and_stale():
    """Two clients at rates 1 and 0.5 with constant deltas: overlap averages,
    exclusive region takes the sole contributor, untouched keeps global."""
    _, m, p = _model_and_params("conv")
    lm = jnp.ones(10)
    k = "block1.conv.w"  # [3,3,8,16], group h0=8 in, h1=16 out
    c1 = {k2: jnp.full_like(v, 2.0) for k2, v in p.items()}
    c2_full = {k2: jnp.full_like(v, 4.0) for k2, v in p.items()}
    c1m = {k2: c1[k2] * (distribute_masked(p, m, 1.0)[k2] * 0 + 1) for k2 in p}  # rate 1: no mask
    c2m = mask_params(c2_full, m.specs, m.groups, 0.5)
    cm1 = client_count_masks(p, m, 1.0, lm)
    cm2 = client_count_masks(p, m, 0.5, lm)
    summed = {k2: c1m[k2] * cm1[k2] + c2m[k2] * cm2[k2] for k2 in p}
    counts = {k2: cm1[k2] + cm2[k2] for k2 in p}
    new = combine_counted(p, summed, counts)
    w = np.asarray(new[k])
    # overlap: first 4 in-ch x first 8 out-ch -> (2+4)/2 = 3
    assert np.allclose(w[:, :, :4, :8], 3.0)
    # only client1 (rate 1) holds the suffix -> 2
    assert np.allclose(w[:, :, 4:, :], 2.0)
    assert np.allclose(w[:, :, :4, 8:], 2.0)


def test_label_split_restricts_output_rows():
    """Client labels restrict which classifier rows it contributes
    (ref fed.py:193-198): other rows keep the global value."""
    _, m, p = _model_and_params("conv")
    lm = jnp.zeros(10).at[jnp.array([1, 3])].set(1.0)
    local = {k: jnp.full_like(v, 7.0) for k, v in p.items()}
    cm = client_count_masks(p, m, 1.0, lm)
    summed = {k: local[k] * cm[k] for k in p}
    counts = dict(cm)
    new = combine_counted(p, summed, counts)
    wb = np.asarray(new["linear.b"])
    assert np.allclose(wb[[1, 3]], 7.0)
    np.testing.assert_allclose(wb[[0, 2, 4, 5, 6, 7, 8, 9]],
                               np.asarray(p["linear.b"])[[0, 2, 4, 5, 6, 7, 8, 9]])
    ww = np.asarray(new["linear.w"])  # [hidden, classes], label axis 1
    assert np.allclose(ww[:, [1, 3]], 7.0)
    np.testing.assert_allclose(ww[:, [0, 2]], np.asarray(p["linear.w"])[:, [0, 2]])


def test_transformer_label_split_on_embedding_and_decoder():
    cfg = small_cfg("transformer", data_name="WikiText2")
    m = make_model(cfg)
    p = m.init(jax.random.key(0))
    lm = jnp.zeros(50).at[jnp.array([5])].set(1.0)
    local = {k: jnp.full_like(v, 9.0) for k, v in p.items()}
    cm = client_count_masks(p, m, 1.0, lm)
    new = combine_counted(p, {k: local[k] * cm[k] for k in p}, dict(cm))
    tok = np.asarray(new["embedding.tok.w"])  # [51, E] label axis 0
    assert np.allclose(tok[5], 9.0)
    np.testing.assert_allclose(tok[6], np.asarray(p["embedding.tok.w"])[6])
    # the <mask> token row (id 50) is never aggregated
    np.testing.assert_allclose(tok[50], np.asarray(p["embedding.tok.w"])[50])
    dec = np.asarray(new["dec.l2.w"])  # [E, V] label axis 1
    assert np.allclose(dec[:, 5], 9.0)
    np.testing.assert_allclose(dec[:, 6], np.asarray(p["dec.l2.w"])[:, 6])
    # positional embedding has no label restriction
    assert np.allclose(np.asarray(new["embedding.pos.w"]), 9.0)


def test_fix_rates_indexed_by_user_ids():
    """Partial participation must pick the *selected* users' rates
    (ref fed.py self.model_rate[user_idx[m]]), not the first-n users'."""
    cfg = small_cfg("conv", control="1_10_0.5_iid_fix_a1-b1-c1-d1-e1_bn_1_1")
    # users 0-1 -> a, 2-3 -> b, 4-5 -> c, 6-7 -> d, 8-9 -> e
    r = sample_model_rates(jax.random.key(0), cfg, jnp.array([9, 0, 4]))
    np.testing.assert_allclose(np.asarray(r), [0.0625, 1.0, 0.25])


def test_non_a_global_mode_width_rates():
    """Global mode 'b': group sizes are already halved, so masks must use the
    relative rate model_rate/global_rate (ref fed.py:46), not the absolute."""
    from heterofl_tpu.fed import to_width_rates

    cfg = small_cfg("conv", control="1_10_0.5_iid_fix_b1-c1_bn_1_1")
    assert cfg["global_model_rate"] == 0.5
    m = make_model(cfg)  # built at rate 0.5: hidden [8,16] -> [4,8]
    assert m.groups["h0"].size == 4 and m.groups["h1"].size == 8
    rates = sample_model_rates(jax.random.key(0), cfg, jnp.array([0, 9]))
    wr = np.asarray(to_width_rates(rates, cfg))
    np.testing.assert_allclose(wr, [1.0, 0.5])
    # a 'b' client at width_rate 1.0 is the FULL global model
    assert int(m.groups["h1"].active_count(wr[0])) == 8
    # a 'c' client gets ceil(8*0.5)=4 channels, matching ceil(16*0.25)
    assert int(m.groups["h1"].active_count(wr[1])) == 4


def test_validate_width_geometry():
    """Per-head vs prefix slice consistency (ref fed.py:115-131): flagship
    dims pass at every level; a 16-dim 2-head embedding breaks at rate 1/16
    (the 16-device dryrun NaN, round 5) and must raise."""
    from heterofl_tpu.fed.core import validate_width_geometry
    from heterofl_tpu.models import make_model

    from test_models import small_cfg

    cfg = small_cfg("transformer", data_name="WikiText2",
                    control="1_8_0.5_iid_fix_a1-b1-c1_none_1_1")
    model = make_model(cfg)  # emb 32, 4 heads: consistent down to rate 1/4
    validate_width_geometry(model, cfg)
    cfg_bad = small_cfg("transformer", data_name="WikiText2",
                        control="1_8_0.5_iid_fix_a1-e1_none_1_1")  # min rate 1/16
    cfg_bad["transformer"] = {"embedding_size": 16, "num_heads": 2,
                              "hidden_size": 32, "num_layers": 1, "dropout": 0.0}
    bad = make_model(cfg_bad)
    with pytest.raises(ValueError, match="width geometry"):
        validate_width_geometry(bad, cfg_bad)
    # vision models have no per-head groups: always fine
    validate_width_geometry(make_model(small_cfg("conv")), small_cfg("conv"))


def test_sample_model_rates_fix_and_dynamic():
    cfg = small_cfg("conv", control="1_10_0.5_iid_fix_a1-b1_bn_1_1")
    r = sample_model_rates(jax.random.key(0), cfg)
    assert r.shape == (10,)
    assert np.allclose(np.asarray(r)[:5], 1.0) and np.allclose(np.asarray(r)[5:], 0.5)
    cfg_d = small_cfg("conv", control="1_1000_0.5_iid_dynamic_a1-e1_bn_1_1")
    draws = np.asarray(sample_model_rates(jax.random.key(1), cfg_d, jnp.arange(1000)))
    assert set(np.unique(draws).tolist()) <= {1.0, 0.0625}
    assert 0.35 < np.mean(draws == 1.0) < 0.65
