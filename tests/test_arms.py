"""Experiment arms multiplexer (ISSUE 14): E sweep arms in ONE fused
superstep program.

The contracts under test:

* **arms=1 == unbatched, bitwise**: an E=1 arms program with the identity
  arm (seed ``None``) produces bit-identical params and metrics to the
  plain superstep -- the arms axis is pure structure.
* **arm i == solo**: arm *i* of a batched run equals an ``arms=1`` run
  carrying the same seed/lr_scale (same stream derivation,
  ``fed.core.arm_stream_keys``) -- BITWISE for the masked engine across
  {replicated, sharded} x K x +-eval, including the int8 EF-residual
  carry and the stacked telemetry probes.  The grouped span engine is
  pinned at an explicit association tolerance instead (GROUPED_ARM_TOL):
  XLA:CPU batch-lowers the small SLICED per-level convs with a different
  accumulation order once the arms axis batches them (measured ~3e-7
  relative on single weights), so bitwise equality would be a
  lowering-choice lottery -- the standing-gates rule says pin the
  tolerance explicitly rather than silently weaken the contract.
* **per-arm checkpoint -> resume round-trip**: the multiplexed driver
  blob resumes bit-identically to an uninterrupted run, and each arm's
  exportable checkpoint carries that arm's params slice.
* **loud refusals**: every unsupported combination fails at construction
  with a ValueError, never as a silent single-arm fallback.
"""

import json
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from heterofl_tpu import config as C
from heterofl_tpu.fed.core import (arm_stream_keys, superstep_rate_schedule,
                                   superstep_user_schedule)
from heterofl_tpu.models import make_model
from heterofl_tpu.multi import (MAX_ARMS, ArmsSpec, default_seeds,
                                resolve_arms_cfg)
from heterofl_tpu.multi.sweep import arms_cfg_of, partition_grid
from heterofl_tpu.parallel import (GroupedRoundEngine, RoundEngine,
                                   make_mesh, shard_client_data)
from heterofl_tpu.parallel.evaluation import Evaluator

from test_round import _vision_setup

HOST_KEY = jax.random.key(0)
METRICS = ("loss_sum", "score_sum", "n", "rate")


@pytest.fixture(scope="module")
def setup():
    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    mesh = make_mesh(n_clients=2, n_data=1)

    def batch(x, b):
        n = x.shape[0]
        s = math.ceil(n / b)
        pad = s * b - n
        w = np.concatenate([np.ones(n, np.float32),
                            np.zeros(pad, np.float32)])
        if pad:
            x = np.concatenate([x, np.zeros((pad,) + x.shape[1:], x.dtype)])
        return x.reshape((s, b) + x.shape[1:]), w.reshape(s, b)

    te = ds["test"]
    xu = te.data[:96].reshape(8, 1, 12, 28, 28, 1)
    yu = te.target[:96].reshape(8, 1, 12)
    eval_ops = {"sbn": batch(ds["train"].data, 20),
                "local": (xu, yu, np.ones((8, 1, 12), np.float32),
                          np.ones((8, 10), np.float32)),
                "global": batch(te.data, 20)[:1] + (batch(te.target, 20)[0],
                                                    batch(te.data, 20)[1])}
    xg, wg = batch(te.data, 20)
    yg, _ = batch(te.target, 20)
    eval_ops["global"] = (xg, yg, wg)
    return {"cfg": cfg, "model": model, "mesh": mesh, "data": data,
            "eval": eval_ops}


def _p0(model):
    return model.init(jax.random.key(0))


def _stack(tree, n):
    return jax.tree_util.tree_map(lambda v: jnp.stack([v] * n), tree)


def _fused(setup, cfg):
    es = setup["eval"]
    ev = Evaluator(setup["model"], cfg, setup["mesh"], seed=0)
    return ev.fused(sbn_batches=es["sbn"], local_eval=es["local"],
                    global_eval=es["global"])


#: the grouped arm-vs-solo association tolerance (see module docstring):
#: explicit and pinned, NOT a convenience fudge -- masked stays bitwise
GROUPED_ARM_TOL = dict(rtol=3e-6, atol=1e-7)


def _assert_arm_close(p_batched, e, p_solo, out_batched, out_solo, k,
                      tol=None):
    def eq(a, b, msg):
        a, b = np.asarray(a), np.asarray(b)
        if tol is None:
            np.testing.assert_array_equal(a, b, err_msg=msg)
        else:
            np.testing.assert_allclose(a, b, err_msg=msg, **tol)

    for name in p_solo:
        eq(p_batched[name][e], p_solo[name][0], name)
    a_b, a_s = out_batched["arms"][e], out_solo["arms"][0]
    rounds_b = a_b["train"] if isinstance(a_b, dict) else a_b
    rounds_s = a_s["train"] if isinstance(a_s, dict) else a_s
    for r in range(k):
        for name in METRICS:
            eq(rounds_b[r][name], rounds_s[r][name],
               f"round {r} metric {name}")
    if isinstance(a_s, dict) and a_s.get("eval"):
        for ev_b, ev_s in zip(a_b["eval"], a_s["eval"]):
            assert ev_b["epoch"] == ev_s["epoch"]
            for n in ev_s["global"]:
                eq(ev_b["global"][n], ev_s["global"][n], n)
            for n in ev_s["local"]:
                eq(ev_b["local"][n], ev_s["local"][n], n)
            for site in ev_s["bn"]:
                eq(np.asarray(ev_b["bn"][site][0]),
                   np.asarray(ev_s["bn"][site][0]), site)


# ---------------------------------------------------------------------------
# config validation (multi.resolve_arms_cfg: THE one validator)
# ---------------------------------------------------------------------------

def test_resolve_arms_cfg_forms():
    assert resolve_arms_cfg({}) is None
    assert resolve_arms_cfg({"arms": None}) is None
    spec = resolve_arms_cfg({"arms": 3})
    assert spec.count == 3
    assert spec.seeds == (None, 1, 2) == default_seeds(3)
    assert spec.lr_scales == (1.0, 1.0, 1.0)
    spec = resolve_arms_cfg({"arms": {"count": 2, "seeds": [7, None],
                                      "lr_scales": [0.5, 2]}})
    assert spec.seeds == (7, None) and spec.lr_scales == (0.5, 2.0)
    assert spec.solo(0) == ArmsSpec(1, (7,), (0.5,))
    assert hash(spec.solo(1)) == hash(ArmsSpec(1, (None,), (2.0,)))


@pytest.mark.parametrize("raw,msg", [
    (True, "Not valid arms"),
    (0, "Not valid arms count"),
    (-2, "Not valid arms count"),
    (MAX_ARMS + 1, "MAX_ARMS"),
    ("4", "Not valid arms"),
    ({"count": 2, "bogus": 1}, "Not valid arms keys"),
    ({"count": 2, "seeds": [1]}, "Not valid arms seeds"),
    ({"count": 2, "seeds": [1, -3]}, "Not valid arm seed"),
    ({"count": 2, "seeds": [1, True]}, "Not valid arm seed"),
    ({"count": 2, "lr_scales": [1.0]}, "Not valid arms lr_scales"),
    ({"count": 2, "lr_scales": [1.0, 0.0]}, "Not valid arm lr_scale"),
    ({"count": 2, "lr_scales": [1.0, -1.0]}, "Not valid arm lr_scale"),
])
def test_resolve_arms_cfg_rejects(raw, msg):
    with pytest.raises(ValueError, match=msg):
        resolve_arms_cfg({"arms": raw})


def test_process_control_validates_arms():
    cfg = C.default_cfg()
    cfg["control"]["num_users"] = "8"
    cfg["data_name"] = "MNIST"
    cfg["arms"] = {"count": 0}
    with pytest.raises(ValueError, match="Not valid arms count"):
        C.process_control(cfg)


def test_arm_stream_keys_identity_and_fold():
    keys = arm_stream_keys(HOST_KEY, (None, 3))
    assert np.array_equal(jax.random.key_data(keys[0]),
                          jax.random.key_data(HOST_KEY))
    assert not np.array_equal(jax.random.key_data(keys[1]),
                              jax.random.key_data(HOST_KEY))
    # per-seed streams are distinct and deterministic
    again = arm_stream_keys(HOST_KEY, (None, 3))
    assert np.array_equal(jax.random.key_data(keys[1]),
                          jax.random.key_data(again[1]))


# ---------------------------------------------------------------------------
# sweep partitioning (multi.sweep)
# ---------------------------------------------------------------------------

def test_partition_grid_arm_vs_structural():
    launches = partition_grid({"seed": [0, 1], "lr": [0.1, 0.01],
                               "wire_codec": ["dense", "int8"]}, max_arms=8)
    assert len(launches) == 2  # one per structural value, 4 arms each
    structs = sorted(s["wire_codec"] for s, _ in launches)
    assert structs == ["dense", "int8"]
    assert all(len(batch) == 4 for _, batch in launches)
    # chunking at max_arms
    launches = partition_grid({"seed": list(range(5))}, max_arms=2)
    assert [len(b) for _, b in launches] == [2, 2, 1]


def test_partition_grid_rejects():
    with pytest.raises(ValueError, match="Not valid grid"):
        partition_grid({}, max_arms=2)
    with pytest.raises(ValueError, match="empty value list"):
        partition_grid({"seed": []})
    with pytest.raises(ValueError, match="both 'seed' and 'init_seed'"):
        partition_grid({"seed": [0], "init_seed": [1]})
    with pytest.raises(ValueError, match="Not valid grid seed"):
        partition_grid({"seed": [-1]})
    with pytest.raises(ValueError, match="Not valid grid lr"):
        partition_grid({"lr": [0.0]})
    with pytest.raises(ValueError, match="Not valid max_arms"):
        partition_grid({"seed": [0]}, max_arms=0)


def test_arms_cfg_of_scales_against_resolved_lr():
    cfg = {"lr": 0.1}
    arms = arms_cfg_of(cfg, [(0, None), (1, 0.05)])
    assert arms["count"] == 2 and arms["seeds"] == [0, 1]
    np.testing.assert_allclose(arms["lr_scales"], [1.0, 0.5])


def test_sweep_dry_run(capsys):
    from heterofl_tpu.multi.sweep import main

    rc = main(["--grid", json.dumps({"seed": [0, 1]}), "--dry_run", "1"])
    assert rc == 0
    outp = capsys.readouterr().out
    assert "launch 0" in outp and "E=2" in outp
    # a typo'd structural key fails UP FRONT (dry-run included), never
    # mid-sweep after earlier launches already burned their compiles
    with pytest.raises(ValueError, match="structural grid key"):
        main(["--grid", json.dumps({"seed": [0, 1], "superstep": [4]}),
              "--dry_run", "1"])


def test_launch_cfg_isolated_output_dirs(tmp_path):
    """Launches share model tags (make_model_tag ignores structural
    keys), so each must get its own output root -- a flat dir would
    clobber sibling launches' per-arm checkpoints and cross-resume."""
    from heterofl_tpu.multi.sweep import launch_cfg, partition_grid

    base = _driver_args(tmp_path)
    launches = partition_grid({"seed": [0, 1, 2, 3]}, max_arms=2)
    cfgs = [launch_cfg(base, i, s, b) for i, (s, b) in enumerate(launches)]
    assert len(cfgs) == 2
    assert cfgs[0]["output_dir"] != cfgs[1]["output_dir"]
    assert all(c["output_dir"].startswith(str(tmp_path)) for c in cfgs)
    assert cfgs[0]["arms"]["seeds"] == [0, 1]
    assert cfgs[1]["arms"]["seeds"] == [2, 3]


# ---------------------------------------------------------------------------
# loud refusals
# ---------------------------------------------------------------------------

def test_refusals(setup):
    cfg, model, mesh = setup["cfg"], setup["model"], setup["mesh"]
    with pytest.raises(ValueError, match="buffered"):
        RoundEngine(model, dict(cfg, arms=2,
                                schedule={"aggregation": "buffered"}), mesh)
    with pytest.raises(ValueError, match="client_store"):
        RoundEngine(model, dict(cfg, arms=2, client_store="stream"), mesh)
    eng = RoundEngine(model, dict(cfg, arms=2), mesh)
    with pytest.raises(ValueError, match="fused superstep"):
        eng.train_round(_stack(_p0(model), 2), HOST_KEY, 0.01,
                        np.array([0, 1]), setup["data"])
    with pytest.raises(ValueError, match="dense wire codec"):
        GroupedRoundEngine(dict(cfg, arms=2, wire_codec="int8"), mesh)
    with pytest.raises(ValueError, match="telemetry"):
        GroupedRoundEngine(dict(cfg, arms=2, telemetry="on"), mesh)
    with pytest.raises(ValueError, match="span"):
        GroupedRoundEngine(dict(cfg, arms=2, level_placement="slices"),
                           make_mesh(8, 1))
    geng = GroupedRoundEngine(dict(cfg, arms=2), mesh)
    with pytest.raises(ValueError, match="fused grouped superstep"):
        geng.train_round(_p0(model), np.array([0, 1]),
                         np.array([1.0, 1.0]), setup["data"], 0.01, HOST_KEY)


# ---------------------------------------------------------------------------
# E=1 == unbatched, bitwise (the identity-arm contract)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("k", [1, 8])
def test_e1_bitwise_unbatched_masked(setup, k):
    cfg, model, mesh, data = (setup["cfg"], setup["model"], setup["mesh"],
                              setup["data"])
    eng0 = RoundEngine(model, dict(cfg), mesh)
    p_ref, pm = eng0.train_superstep(_p0(model), HOST_KEY, 1, k, data=data)
    ms_ref = pm.fetch()
    eng1 = RoundEngine(model, dict(cfg, arms=1), mesh)
    p1, pm1 = eng1.train_superstep(_stack(_p0(model), 1), HOST_KEY, 1, k,
                                   data=data)
    out1 = pm1.fetch()
    for name in p_ref:
        np.testing.assert_array_equal(np.asarray(p1[name][0]),
                                      np.asarray(p_ref[name]), err_msg=name)
    for r in range(k):
        for name in METRICS:
            np.testing.assert_array_equal(
                np.asarray(out1["arms"][0][r][name]),
                np.asarray(ms_ref[r][name]), err_msg=f"{r}/{name}")


@pytest.mark.slow
def test_e1_bitwise_unbatched_grouped(setup):
    cfg, model, mesh, data = (setup["cfg"], setup["model"], setup["mesh"],
                              setup["data"])
    k = 4
    users = superstep_user_schedule(HOST_KEY, 1, k, cfg["num_users"], 4)
    rates = superstep_rate_schedule(HOST_KEY, 1, k, cfg, users)
    eng0 = GroupedRoundEngine(dict(cfg), mesh)
    p_ref, pm = eng0.train_superstep(_p0(model), HOST_KEY, 1, k, users,
                                     rates, data)
    pm.fetch()
    eng1 = GroupedRoundEngine(dict(cfg, arms=1), mesh)
    p1, pm1 = eng1.train_superstep(_stack(_p0(model), 1), HOST_KEY, 1, k,
                                   users, rates, data)
    pm1.fetch()
    for name in p_ref:
        np.testing.assert_array_equal(np.asarray(p1[name][0]),
                                      np.asarray(p_ref[name]), err_msg=name)


# ---------------------------------------------------------------------------
# arm-vs-solo equivalence matrix
# ---------------------------------------------------------------------------

ARMS3 = {"count": 3, "seeds": [None, 7, 11], "lr_scales": [1.0, 0.5, 2.0]}
SOLO1 = {"count": 1, "seeds": [7], "lr_scales": [0.5]}


@pytest.mark.parametrize("k,with_eval", [
    (1, False), (8, False),
    pytest.param(8, True, marks=pytest.mark.slow)])
def test_arm_vs_solo_masked_replicated(setup, k, with_eval):
    cfg, model, mesh, data = (setup["cfg"], setup["model"], setup["mesh"],
                              setup["data"])
    mask = tuple((r + 1) % 4 == 0 for r in range(k)) if with_eval else None
    cfg_b = dict(cfg, arms=ARMS3)
    eng_b = RoundEngine(model, cfg_b, mesh)
    p_b, pm_b = eng_b.train_superstep(
        _stack(_p0(model), 3), HOST_KEY, 1, k, data=data, eval_mask=mask,
        fused_eval=_fused(setup, cfg_b) if with_eval else None)
    out_b = pm_b.fetch()
    cfg_s = dict(cfg, arms=SOLO1)
    eng_s = RoundEngine(model, cfg_s, mesh)
    p_s, pm_s = eng_s.train_superstep(
        _stack(_p0(model), 1), HOST_KEY, 1, k, data=data, eval_mask=mask,
        fused_eval=_fused(setup, cfg_s) if with_eval else None)
    out_s = pm_s.fetch()
    _assert_arm_close(p_b, 1, p_s, out_b, out_s, k)
    # distinct seeds produce distinct trajectories (not a degenerate pass)
    a0 = out_b["arms"][0]["train"] if with_eval else out_b["arms"][0]
    a1 = out_b["arms"][1]["train"] if with_eval else out_b["arms"][1]
    assert any(not np.array_equal(np.asarray(a0[r]["loss_sum"]),
                                  np.asarray(a1[r]["loss_sum"]))
               for r in range(k))


@pytest.mark.slow
def test_arm_vs_solo_masked_sharded(setup):
    cfg, model, mesh = setup["cfg"], setup["model"], setup["mesh"]
    k = 4
    sdata = shard_client_data(mesh, tuple(np.asarray(a)
                                          for a in setup["data"]))
    sched = superstep_user_schedule(HOST_KEY, 1, k, cfg["num_users"], 4)
    eng_b = RoundEngine(model, dict(cfg, arms=ARMS3,
                                    data_placement="sharded"), mesh)
    p_b, pm_b = eng_b.train_superstep(_stack(_p0(model), 3), HOST_KEY, 1, k,
                                      data=sdata, user_schedule=sched)
    out_b = pm_b.fetch()
    eng_s = RoundEngine(model, dict(cfg, arms=SOLO1,
                                    data_placement="sharded"), mesh)
    p_s, pm_s = eng_s.train_superstep(_stack(_p0(model), 1), HOST_KEY, 1, k,
                                      data=sdata, user_schedule=sched)
    out_s = pm_s.fetch()
    _assert_arm_close(p_b, 1, p_s, out_b, out_s, k)


@pytest.mark.parametrize("k,with_eval", [
    pytest.param(1, False, marks=pytest.mark.slow),
    pytest.param(8, True, marks=pytest.mark.slow)])
def test_arm_vs_solo_grouped_span(setup, k, with_eval):
    cfg, model, mesh, data = (setup["cfg"], setup["model"], setup["mesh"],
                              setup["data"])
    users = superstep_user_schedule(HOST_KEY, 1, k, cfg["num_users"], 4)
    rates = superstep_rate_schedule(HOST_KEY, 1, k, cfg, users)
    mask = tuple((r + 1) % 4 == 0 for r in range(k)) if with_eval else None
    cfg_b = dict(cfg, arms=ARMS3)
    eng_b = GroupedRoundEngine(cfg_b, mesh)
    p_b, pm_b = eng_b.train_superstep(
        _stack(_p0(model), 3), HOST_KEY, 1, k, users, rates, data,
        eval_mask=mask, fused_eval=_fused(setup, cfg_b) if with_eval
        else None)
    out_b = pm_b.fetch()
    cfg_s = dict(cfg, arms=SOLO1)
    eng_s = GroupedRoundEngine(cfg_s, mesh)
    p_s, pm_s = eng_s.train_superstep(
        _stack(_p0(model), 1), HOST_KEY, 1, k, users, rates, data,
        eval_mask=mask, fused_eval=_fused(setup, cfg_s) if with_eval
        else None)
    out_s = pm_s.fetch()
    _assert_arm_close(p_b, 1, p_s, out_b, out_s, k, tol=GROUPED_ARM_TOL)


# ---------------------------------------------------------------------------
# the arms MESH placement (the 'experiments' mesh dimension)
# ---------------------------------------------------------------------------

MESH_ARMS = {"count": 4, "seeds": [None, 7, 9, 11],
             "lr_scales": [1.0, 0.5, 2.0, 1.0]}


def test_mesh_arms_placement_bitwise(setup):
    """Arms laid over a dedicated mesh axis (make_mesh(n_arms=E): each
    arm's federation on its own device rows, executing concurrently) are
    BITWISE-identical to the vmap placement -- and therefore to solo runs:
    the placement is pure layout, never semantics."""
    cfg, model, data = setup["cfg"], setup["model"], setup["data"]
    k, E = 4, 4
    eng_v = RoundEngine(model, dict(cfg, arms=MESH_ARMS), make_mesh(2, 1))
    p_v, pm_v = eng_v.train_superstep(_stack(_p0(model), E), HOST_KEY, 1, k,
                                      data=data)
    out_v = pm_v.fetch()
    mesh_m = make_mesh(2, 1, n_arms=E)
    assert mesh_m.shape["arms"] == E
    eng_m = RoundEngine(model, dict(cfg, arms=MESH_ARMS), mesh_m)
    p_m, pm_m = eng_m.train_superstep(_stack(_p0(model), E), HOST_KEY, 1, k,
                                      data=data)
    out_m = pm_m.fetch()
    for name in p_v:
        np.testing.assert_array_equal(np.asarray(p_m[name]),
                                      np.asarray(p_v[name]), err_msg=name)
    for e in range(E):
        for r in range(k):
            for nm in METRICS:
                np.testing.assert_array_equal(
                    np.asarray(out_m["arms"][e][r][nm]),
                    np.asarray(out_v["arms"][e][r][nm]),
                    err_msg=f"arm {e} round {r} {nm}")


def test_mesh_arms_refusals(setup):
    cfg, model = setup["cfg"], setup["model"]
    mesh_m = make_mesh(2, 1, n_arms=4)
    with pytest.raises(ValueError, match="'arms' axis but cfg"):
        RoundEngine(model, dict(cfg), mesh_m)
    with pytest.raises(ValueError, match="arms axis size"):
        RoundEngine(model, dict(cfg, arms=2), mesh_m)
    with pytest.raises(ValueError, match="grouped engine"):
        GroupedRoundEngine(dict(cfg, arms=4), mesh_m)


# ---------------------------------------------------------------------------
# wire codec x arms: the EF residual batches per arm
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_codec_arms_resid_batches_and_roundtrips(setup):
    cfg, model, mesh, data = (setup["cfg"], setup["model"], setup["mesh"],
                              setup["data"])
    k, E = 4, 2
    arms2 = {"count": 2, "seeds": [None, 7], "lr_scales": [1.0, 0.5]}
    eng_b = RoundEngine(model, dict(cfg, arms=arms2, wire_codec="int8"),
                        mesh)
    p_b, pm_b = eng_b.train_superstep(_stack(_p0(model), E), HOST_KEY, 1, k,
                                      data=data)
    out_b = pm_b.fetch()
    assert eng_b._resid.shape[0] == E  # [E, n_dev, slots, total]
    eng_s = RoundEngine(model, dict(cfg, arms={"count": 1, "seeds": [7],
                                               "lr_scales": [0.5]},
                                    wire_codec="int8"), mesh)
    p_s, pm_s = eng_s.train_superstep(_stack(_p0(model), 1), HOST_KEY, 1, k,
                                      data=data)
    out_s = pm_s.fetch()
    _assert_arm_close(p_b, 1, p_s, out_b, out_s, k)
    np.testing.assert_array_equal(np.asarray(eng_b._resid[1]),
                                  np.asarray(eng_s._resid[0]))
    # checkpoint round-trip of the stacked carry: restore + redispatch
    # bit-identical to the uninterrupted engine
    host = eng_b.wire_resid_host()
    assert host.shape[0] == E
    eng_c = RoundEngine(model, dict(cfg, arms=arms2, wire_codec="int8"),
                        mesh)
    eng_c.set_wire_resid(host)
    p_c, pm_c = eng_c.train_superstep(p_b, HOST_KEY, 1 + k, k, data=data)
    pm_c.fetch()
    p_u, pm_u = eng_b.train_superstep(
        jax.tree_util.tree_map(lambda v: v + 0, p_b), HOST_KEY, 1 + k, k,
        data=data)
    pm_u.fetch()
    for name in p_u:
        np.testing.assert_array_equal(np.asarray(p_c[name]),
                                      np.asarray(p_u[name]), err_msg=name)


# ---------------------------------------------------------------------------
# telemetry x arms: probes come back stacked per arm
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_obs_arms_probes_per_arm(setup):
    cfg, model, mesh, data = (setup["cfg"], setup["model"], setup["mesh"],
                              setup["data"])
    k = 4
    arms2 = {"count": 2, "seeds": [None, 7], "lr_scales": [1.0, 1.0]}
    eng_on = RoundEngine(model, dict(cfg, arms=arms2, telemetry="on"), mesh)
    p_on, pm_on = eng_on.train_superstep(_stack(_p0(model), 2), HOST_KEY, 1,
                                         k, data=data)
    out_on = pm_on.fetch()
    for e in range(2):
        arm = out_on["arms"][e]
        assert "obs" in arm and len(arm["obs"]) == k
        for rec in arm["obs"]:
            assert rec["nonfinite"] == 0
            assert rec["update_norm"] > 0
    assert out_on["arms"][0]["obs"][0]["update_norm"] != \
        out_on["arms"][1]["obs"][0]["update_norm"]
    # telemetry on == off, bitwise, per arm
    eng_off = RoundEngine(model, dict(cfg, arms=arms2), mesh)
    p_off, pm_off = eng_off.train_superstep(_stack(_p0(model), 2), HOST_KEY,
                                            1, k, data=data)
    out_off = pm_off.fetch()
    for name in p_off:
        np.testing.assert_array_equal(np.asarray(p_on[name]),
                                      np.asarray(p_off[name]), err_msg=name)
    for e in range(2):
        rounds_on = out_on["arms"][e]["train"]
        for r in range(k):
            for name in METRICS:
                np.testing.assert_array_equal(
                    np.asarray(rounds_on[r][name]),
                    np.asarray(out_off["arms"][e][r][name]))


# ---------------------------------------------------------------------------
# the multiplexed driver: per-arm logs, checkpoints, resume
# ---------------------------------------------------------------------------

def _driver_args(tmp, n_rounds=4):
    ov = {"num_epochs": {"global": n_rounds, "local": 1},
          "conv": {"hidden_size": [8, 16]},
          "batch_size": {"train": 10, "test": 20}}
    cfg = C.default_cfg()
    cfg["control"] = C.parse_control_name("1_8_0.5_iid_fix_a1-b1-c1-d1-e1_bn_1_1")
    cfg["data_name"] = "MNIST"
    cfg["model_name"] = "conv"
    cfg["synthetic"] = True
    cfg["synthetic_sizes"] = {"train": 200, "test": 80}
    cfg["output_dir"] = str(tmp)
    cfg["override"] = ov
    cfg["superstep_rounds"] = 2
    cfg["eval_interval"] = 2
    return cfg


def test_fedexperiment_refuses_arms_cfg(tmp_path):
    from heterofl_tpu.entry.common import FedExperiment

    cfg = _driver_args(tmp_path)
    cfg["arms"] = 2
    cfg = C.process_control(cfg)
    with pytest.raises(ValueError, match="multiplexed driver"):
        FedExperiment(cfg, 0)


def test_arms_experiment_requires_arms(tmp_path):
    from heterofl_tpu.entry.common import ArmsExperiment

    cfg = C.process_control(_driver_args(tmp_path))
    with pytest.raises(ValueError, match="needs cfg\\['arms'\\]"):
        ArmsExperiment(cfg, 0)


def test_arms_driver_refusals(tmp_path):
    from heterofl_tpu.entry.common import ArmsExperiment

    # trace_dir x arms: the multiplexed loop builds no TraceRecorder, so
    # the trace would be silently empty -- refused at config-resolution
    # time by resolve_arms_cfg (ISSUE 18: one validator per axis)...
    cfg = _driver_args(tmp_path)
    cfg["arms"] = 2
    cfg["trace_dir"] = str(tmp_path / "tr")
    with pytest.raises(ValueError, match="trace_dir"):
        C.process_control(cfg)
    # ...and the driver constructor keeps the same refusal as
    # defense-in-depth for cfgs that dodged the resolver
    cfg = C.process_control(_driver_args(tmp_path) | {"arms": 2})
    cfg["trace_dir"] = str(tmp_path / "tr")
    with pytest.raises(ValueError, match="trace_dir"):
        ArmsExperiment(cfg, 0)
    # an explicit arms mesh axis the device count cannot honor must
    # raise, not silently fall back to the vmap placement
    cfg = _driver_args(tmp_path)
    cfg["arms"] = 2
    cfg["mesh"] = {"clients": len(jax.devices()), "data": 1, "arms": 2}
    cfg = C.process_control(cfg)
    with pytest.raises(ValueError, match="devices"):
        ArmsExperiment(cfg, 0)


@pytest.mark.slow
def test_driver_arms_end_to_end_and_resume(tmp_path):
    """4-round 2-arm multiplexed run: per-arm JSONL lines + checkpoints,
    then a mid-run resume that matches the uninterrupted run bitwise."""
    from heterofl_tpu.entry.common import ArmsExperiment

    arms = {"count": 2, "seeds": [None, 7], "lr_scales": [1.0, 0.5]}

    def run(tmp, n_rounds):
        cfg = _driver_args(tmp, n_rounds=n_rounds)
        cfg["arms"] = dict(arms)
        cfg = C.process_control(cfg)
        exp = ArmsExperiment(cfg, 0)
        return exp, exp.run("Global-Accuracy", "max")

    exp, res = run(tmp_path / "full", 4)
    tag = exp._arms_tag()
    # per-arm log lines with the arm field
    log = tmp_path / "full" / "runs" / f"train_{tag}" / "log.jsonl"
    lines = [json.loads(ln) for ln in open(log)]
    arms_lines = [ln for ln in lines if ln.get("tag") == "arms"]
    trains = [ln for ln in arms_lines if ln["event"] == "train"]
    evals = [ln for ln in arms_lines if ln["event"] == "eval"]
    assert {ln["arm"] for ln in arms_lines} == {0, 1}
    assert len(trains) == 2 * 4 and len(evals) == 2 * 2
    # per-arm metrics differ across seeds
    l0 = [ln["loss"] for ln in trains if ln["arm"] == 0]
    l1 = [ln["loss"] for ln in trains if ln["arm"] == 1]
    assert l0 != l1
    # per-arm checkpoints carry each arm's params slice
    for e in range(2):
        ck = tmp_path / "full" / "model" / f"{tag}_a{e}_checkpoint.pkl"
        assert ck.exists(), os.listdir(tmp_path / "full" / "model")
    import pickle
    with open(tmp_path / "full" / "model" / f"{tag}_a1_checkpoint.pkl",
              "rb") as f:
        blob1 = pickle.load(f)
    assert blob1["arm"] == 1 and blob1["arm_seed"] == 7
    for name, v in blob1["params"].items():
        np.testing.assert_array_equal(v, np.asarray(res["params"][name][1]),
                                      err_msg=name)
    # resume round-trip: 2 rounds, stop, resume 2 more == 4 uninterrupted
    exp_a, res_a = run(tmp_path / "half", 2)
    cfg_b = _driver_args(tmp_path / "half", n_rounds=4)
    cfg_b["arms"] = dict(arms)
    cfg_b["resume_mode"] = 1
    cfg_b = C.process_control(cfg_b)
    exp_b = ArmsExperiment(cfg_b, 0)
    res_b = exp_b.run("Global-Accuracy", "max")
    for name in res["params"]:
        np.testing.assert_array_equal(np.asarray(res_b["params"][name]),
                                      np.asarray(res["params"][name]),
                                      err_msg=name)


@pytest.mark.slow
def test_driver_arms_plateau_per_arm(tmp_path):
    """ReduceLROnPlateau x arms: each arm owns its own scheduler state,
    staged into the program as the [E] LR vector -- and the arm's
    lr_scale multiplies the scheduler's output (a Plateau LR sweep must
    train each arm at ITS grid value, not silently at the base LR)."""
    from heterofl_tpu.entry.common import ArmsExperiment

    cfg = _driver_args(tmp_path, n_rounds=4)
    cfg["arms"] = {"count": 2, "seeds": [None, 7], "lr_scales": [1.0, 0.25]}
    cfg["override"] = dict(cfg["override"],
                           scheduler_name="ReduceLROnPlateau")
    cfg = C.process_control(cfg)
    exp = ArmsExperiment(cfg, 0)
    res = exp.run("Global-Accuracy", "max")
    assert len(exp._arm_scheds) == 2
    log = (tmp_path / "runs" / f"train_{exp._arms_tag()}" / "log.jsonl")
    lines = [json.loads(ln) for ln in open(log)]
    trains = [ln for ln in lines
              if ln.get("tag") == "arms" and ln["event"] == "train"]
    assert all(np.isfinite(ln["lr"]) for ln in trains)
    lr_by_arm = {e: {ln["epoch"]: ln["lr"] for ln in trains
                     if ln["arm"] == e} for e in (0, 1)}
    for ep, lr0 in lr_by_arm[0].items():
        assert lr_by_arm[1][ep] == pytest.approx(0.25 * lr0)
    assert all(np.isfinite(v) for name in res["params"]
               for v in [float(np.abs(np.asarray(res["params"][name])).max())])
    # the STAGED [E] LR vector carries the scale too: identical seeds with
    # scales (1.0, 0.25) must diverge (the LR is the arms' only delta)
    cfg2 = _driver_args(tmp_path / "scaled", n_rounds=2)
    cfg2["arms"] = {"count": 2, "seeds": [None, None],
                    "lr_scales": [1.0, 0.25]}
    cfg2["override"] = dict(cfg2["override"],
                            scheduler_name="ReduceLROnPlateau")
    cfg2 = C.process_control(cfg2)
    res2 = ArmsExperiment(cfg2, 0).run("Global-Accuracy", "max")
    assert any(not np.array_equal(np.asarray(v[0]), np.asarray(v[1]))
               for v in res2["params"].values())


@pytest.mark.slow
def test_driver_arms_telemetry_probes(tmp_path):
    """telemetry='on' x arms: the multiplexed loop surfaces the stacked
    obs records it fetches -- per-arm probes events land on the run log
    (each arm also feeds its own watchdog; one shared spike window would
    mix E loss streams)."""
    from heterofl_tpu.entry.common import ArmsExperiment

    cfg = _driver_args(tmp_path, n_rounds=2)
    cfg["arms"] = {"count": 2, "seeds": [None, 7], "lr_scales": [1.0, 1.0]}
    cfg["telemetry"] = "on"
    cfg = C.process_control(cfg)
    exp = ArmsExperiment(cfg, 0)
    assert exp._arm_watchdogs is None or len(exp._arm_watchdogs) == 2
    exp.run("Global-Accuracy", "max")
    log = tmp_path / "runs" / f"train_{exp._arms_tag()}" / "log.jsonl"
    probes = [ln for ln in map(json.loads, open(log))
              if ln.get("event") == "probes"]
    assert {ln["arm"] for ln in probes} == {0, 1}
    assert len(probes) == 2 * 2  # E arms x n_rounds
    assert all(ln["update_norm"] > 0 and ln["nonfinite"] == 0
               for ln in probes)
