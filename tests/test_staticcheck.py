"""staticcheck (ISSUE 3): the AST lint rules (positive / pragma-suppressed /
path-scoped), the jaxpr walkers, and the full program-audit matrix -- the
tier-1 gate that every engine variant keeps its compiled-program contract:
no host callbacks or f64, full donation coverage, exactly one global psum
per fused round, no recompile on fresh-but-identical inputs, and the
level-table FLOP budget."""

import json
import os
import subprocess
import sys
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from heterofl_tpu.staticcheck import audit as audit_mod
from heterofl_tpu.staticcheck.audit import (audit_program, build_setup,
                                            run_audit, _masked_targets)
from heterofl_tpu.staticcheck.jaxpr_walk import (count_psum_over,
                                                find_callbacks, find_f64)
from heterofl_tpu.staticcheck.rules import lint_source, lint_tree

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
IN_SCOPE = "heterofl_tpu/parallel/somefile.py"


# ---------------------------------------------------------------------------
# front 2: AST lint rules
# ---------------------------------------------------------------------------

def _lint(src, relpath=IN_SCOPE):
    return lint_source(textwrap.dedent(src), relpath)


def test_banned_asarray_flagged_and_pragma_suppressed():
    src = """
    import numpy as np
    def f(a):
        return np.asarray(a)
    """
    fs = _lint(src)
    assert [f.rule for f in fs] == ["no-asarray"]
    assert fs[0].where == f"{IN_SCOPE}:4"
    # same-line pragma
    assert _lint("""
    import numpy as np
    def f(a):
        return np.asarray(a)  # staticcheck: allow(no-asarray): reason
    """) == []
    # preceding-comment-block pragma (multi-line reason style)
    assert _lint("""
    import numpy as np
    def f(a):
        # staticcheck: allow(no-asarray): a longer reason that
        # spans two comment lines before the call it licenses
        return np.asarray(a)
    """) == []


def test_pragma_is_rule_scoped():
    """A pragma for one rule must not silence another on the same line."""
    fs = _lint("""
    import numpy as np
    def f(a):
        return float(np.asarray(a))  # staticcheck: allow(no-asarray)
    """)
    assert [f.rule for f in fs] == ["no-float-coercion"]


def test_path_scoping():
    src = """
    import numpy as np
    def f(a):
        return np.asarray(a)
    """
    # ISSUE 5: ops/ and models/ are hot-path scope now (kernel/model code
    # runs inside the round programs); analysis/ stays host-side
    assert len(_lint(src, "heterofl_tpu/models/conv.py")) == 1
    assert len(_lint(src, "heterofl_tpu/ops/kern.py")) == 1
    assert _lint(src, "heterofl_tpu/analysis/summary.py") == []
    assert len(_lint(src, "heterofl_tpu/parallel/engine.py")) == 1
    # nested checkouts still match (prefix anywhere after a slash)
    assert len(_lint(src, "work/heterofl_tpu/parallel/engine.py")) == 1


def test_alias_resolution_variants():
    flagged = _lint("""
    from jax import numpy as weird
    def f(a):
        return weird.asarray(a)
    """)
    assert [f.rule for f in flagged] == ["no-asarray"]
    flagged = _lint("""
    import jax.numpy as jnp
    def f(a):
        return jnp.asarray(a)
    """)
    assert [f.rule for f in flagged] == ["no-asarray"]


def test_wallclock_and_fresh_rng_scoped_to_fed_too():
    src = """
    import time
    import numpy as np
    def f():
        t = time.perf_counter()
        g = np.random.default_rng()
        return t, g
    """
    rules_hit = sorted(f.rule for f in _lint(src, "heterofl_tpu/fed/core.py"))
    assert rules_hit == ["no-fresh-rng", "no-wallclock"]
    assert _lint(src, "heterofl_tpu/data/pipeline.py") == []


def test_block_until_ready_method_call():
    fs = _lint("""
    def f(x):
        return x.block_until_ready()
    """)
    assert [f.rule for f in fs] == ["no-block-until-ready"]


def test_jit_donation_rule():
    base = """
    import jax
    def mk(f):
        return jax.jit(f{})
    """
    assert [f.rule for f in _lint(base.format(""))] == ["jit-needs-donation"]
    assert _lint(base.format(", donate_argnums=(0,)")) == []
    assert _lint(base.format(", donate_argnames='params'")) == []
    # an explicit empty donation IS a stance (the span-mode level programs)
    assert _lint(base.format(", donate_argnums=()")) == []
    # a bare decorator takes no stance either
    fs = _lint("""
    import jax
    @jax.jit
    def f(x):
        return x
    """)
    assert [f.rule for f in fs] == ["jit-needs-donation"]


def test_host_eval_in_driver_rule():
    """ISSUE 4 satellite: host-side eval dispatch (sbn_stats / eval_users /
    eval_global) in driver code is a lint finding -- the superstep fuses
    those phases in-program -- escapable by pragma for the K=1 path."""
    src = """
    def run(exp, params, d):
        bn = exp.evaluator.sbn_stats(params, d)
        local = exp.evaluator.eval_users(params, bn, d)
        return exp.evaluator.eval_global(params, bn, d)
    """
    fs = _lint(src, "heterofl_tpu/entry/common.py")
    assert [f.rule for f in fs] == ["no-host-eval-in-driver"] * 3
    # pragma escape (the K=1 host-loop path carries one per call)
    assert _lint("""
    def run(exp, params, d):
        # staticcheck: allow(no-host-eval-in-driver): K=1 host-loop path
        return exp.evaluator.eval_global(params, {}, d)
    """, "heterofl_tpu/entry/common.py") == []
    # scoped to the driver: engine/eval code and offline analysis are free
    assert _lint(src, "heterofl_tpu/parallel/evaluation.py") == []
    assert _lint(src, "heterofl_tpu/analysis/compare_reference.py") == []


def test_repo_tree_is_lint_clean():
    """The gate itself: the shipped tree has zero unsuppressed findings."""
    fs = lint_tree(REPO, subdirs=["heterofl_tpu"])
    assert fs == [], "\n".join(str(f) for f in fs)


# ---------------------------------------------------------------------------
# front 1: jaxpr walkers
# ---------------------------------------------------------------------------

def test_find_callbacks_inside_scan_body():
    """An op smuggled inside a lax.scan round body is found like a
    top-level one, with provenance."""
    def step(c, _):
        jax.debug.callback(lambda v: None, c)
        return c + 1.0, None

    def f(x):
        out, _ = jax.lax.scan(step, x, None, length=3)
        return out

    hits = find_callbacks(jax.jit(f).trace(np.float32(0.0)).jaxpr)
    assert len(hits) == 1
    name, prov = hits[0]
    assert name == "debug_callback"
    assert "test_staticcheck" in prov


def test_find_f64():
    with jax.experimental.enable_x64():
        jaxpr = jax.make_jaxpr(lambda x: x.astype(jnp.float64) * 2.0)(
            np.ones(3, np.float32))
    hits = find_f64(jaxpr)
    assert hits and "float64" in hits[0][0]


def test_count_psum_binds_not_leaves():
    """One psum bind over a (sums, counts) tuple is ONE collective launch
    -- the budget the fused round is audited against."""
    def f2(a, b):
        return jax.lax.psum((a, b), "clients")

    def f1(a, b):
        return jax.lax.psum(a, "clients"), jax.lax.psum(b, "clients")

    import functools
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:2]).reshape(2, 1), ("clients", "data"))
    sm = functools.partial(shard_map, mesh=mesh,
                           in_specs=(P("clients"), P("clients")),
                           out_specs=P(), check_rep=False)
    x = np.ones((4, 2), np.float32)
    assert count_psum_over(jax.jit(sm(f2)).trace(x, x).jaxpr) == 1
    assert count_psum_over(jax.jit(sm(f1)).trace(x, x).jaxpr) == 2


# ---------------------------------------------------------------------------
# the program-audit matrix (the tier-1 gate for the engines)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def audit_report():
    return run_audit()


def test_audit_matrix_is_green(audit_report):
    assert audit_report.ok, "\n".join(str(f) for f in audit_report.all_findings())


def test_fused_superstep_single_global_psum(audit_report):
    """The PR 2 invariant, now statically enforced: the grouped fused round
    (both placements) performs exactly ONE global psum."""
    for name in ("grouped/span/k8-fused", "grouped/slices/k8-fused"):
        p = audit_report.programs[name]
        assert p.psum_clients == 1, name
        assert p.all_gather == 0, name
        assert set(p.collective_axes) <= {"clients", "data"}, name


def test_eval_fused_program_budgets(audit_report):
    """ISSUE 4: the eval-fused superstep variants keep ONE training psum per
    fused round, with the eval phase's joint (clients, data) reductions --
    sBN moments + Global sums, 2 per traced eval point -- audited as their
    own budget, and full donation coverage intact."""
    from heterofl_tpu.staticcheck.audit import EVAL_PSUM_BUDGET

    k = 8
    expected = {"masked/replicated/k8-eval1": EVAL_PSUM_BUDGET * k,
                "masked/replicated/k8-eval8": EVAL_PSUM_BUDGET,
                "masked/sharded/k8-eval1": EVAL_PSUM_BUDGET * k,
                "grouped/span/k8-eval1-fused": EVAL_PSUM_BUDGET * k,
                "grouped/slices/k8-eval1-fused": EVAL_PSUM_BUDGET * k}
    for name, want in expected.items():
        p = audit_report.programs[name]
        assert p.psum_clients == 1, name
        assert p.psum_eval == want, (name, p.psum_eval)
        assert p.all_gather == 0, name
        assert p.aliased == p.donation_expected > 0, name


def test_donation_coverage_both_engines_both_placements(audit_report):
    """Every program that carries the params donates ALL param leaves and
    every donated leaf is consumed by input-output aliasing."""
    donating = ["masked/replicated/k1", "masked/replicated/k8",
                "masked/sharded/k1", "masked/sharded/k8",
                "grouped/span/combine", "grouped/span/k8-fused",
                "grouped/slices/k8-fused"]
    for name in donating:
        p = audit_report.programs[name]
        assert p.donation_expected > 0, name
        assert p.donated == p.donation_expected, (name, p.donated)
        assert p.aliased == p.donation_expected, (name, p.aliased)


def test_recompile_hazard_flat(audit_report):
    rc = audit_report.recompile
    assert rc["ok"], rc
    for which in ("masked_round", "masked_superstep",
                  "masked_sharded_superstep", "masked_superstep_eval",
                  "grouped_round"):
        assert rc[which]["after_repeat"] == rc[which]["after_warm"], (which, rc)


def test_flop_budget_and_artifact_roundtrip(audit_report):
    fb = audit_report.flop_budget
    assert fb["ok"], fb
    meas = fb["measured_flops"]
    rates = sorted((float(r) for r in meas), reverse=True)
    # strictly decreasing with the level rate: the dense-per-level win
    for hi, lo in zip(rates, rates[1:]):
        assert meas[f"{hi:g}"] > meas[f"{lo:g}"]
    # the artifact serialises and carries per-program memory bytes
    rec = json.loads(audit_report.to_json())
    assert rec["ok"] is True and rec["version"] == 2
    mem = rec["programs"]["masked/replicated/k1"]["memory"]
    assert mem and mem["temp_size_in_bytes"] > 0


def test_wire_memory_reshard_sections_on_every_program(audit_report):
    """ISSUE 7 acceptance: STATICCHECK.json grows wire/memory/reshards
    sections for every audited program variant, and the wire budget of
    every fused training round equals ONE dense global reduction of the
    level-a parameter footprint (sums + count masks, f32) -- or, for the
    ISSUE 8 codec variants, that codec's compressed level-a payload from
    the same table family."""
    from heterofl_tpu.compress import LOSSY_CODECS
    from heterofl_tpu.fed.core import level_byte_table, level_codec_byte_table
    from heterofl_tpu.staticcheck.audit import build_setup, default_audit_cfg

    cfg = default_audit_cfg()
    bt = level_byte_table(cfg)
    level_a_wire = bt[max(bt)]["wire_bytes"]
    assert level_a_wire == 2 * bt[max(bt)]["param_bytes"]
    n_leaves = len(build_setup()["params"])
    codec_wire = {c: level_codec_byte_table(cfg, c, n_leaves=n_leaves)[max(bt)]
                  for c in LOSSY_CODECS}
    for name, p in audit_report.programs.items():
        assert p.wire is not None, name
        assert p.reshards is not None and p.reshards["total"] == 0, name
        if name.endswith("/mh"):
            # ISSUE 17 multi-host variants: the fake 2-process grid puts
            # the clients axis on DCN -- the whole (one-reduction) train
            # payload crosses, and NOTHING else does.  These entries
            # re-audit the SAME program as their single-process twin
            # under the multi-process link model only (wire_only), so
            # they carry no duplicate memory/step-body sections.
            assert p.wire["dcn_bytes"] == p.wire["train_bytes_per_round"], name
            assert p.wire["other_bytes"] == 0, name
            assert p.memory is None, name
        else:
            assert p.memory is not None, name
            assert p.wire["dcn_bytes"] == 0, name  # single-slice audit mesh
        codec = next((c for c in LOSSY_CODECS if name.endswith(f"-{c}")), None)
        if name == "grouped/span/combine":
            assert p.wire["train_bytes_per_round"] == 0
        elif "/level-" in name:  # per-level partial: that level's slice
            rate = float(name.split("level-")[1].split("/")[0])
            assert p.wire["train_bytes_per_round"] == bt[rate]["wire_bytes"], name
        elif name.endswith("-perlevel"):
            # per-level codec map (ISSUE 9 satellite): the bind's payload is
            # the per-level sum -- level-a under its codec, the rest dense
            from heterofl_tpu.fed.core import level_codec_map_byte_table

            cmap = {r: ("int8" if r == max(bt) else "dense") for r in bt}
            expected = sum(level_codec_map_byte_table(
                cfg, cmap, n_leaves=n_leaves).values())
            assert p.wire["train_bytes_per_round"] == expected, name
        elif codec:  # compressed fused round: that codec's level-a payload
            assert p.wire["train_bytes_per_round"] == codec_wire[codec], name
        elif "-arms" in name:
            # arms multiplexer (ISSUE 14): the masked engine's per-arm
            # cohorts batch sums AND counts -- E x the dense reduction;
            # grouped span arms share the host schedule, so the counts
            # payload is arm-invariant: E sum payloads + ONE counts
            e = int(name.split("-arms")[1])
            expected = (e + 1) * level_a_wire // 2 \
                if name.startswith("grouped") else e * level_a_wire
            assert p.wire["train_bytes_per_round"] == expected, name
        else:  # every fused training round (incl. the ISSUE 9 trace/
            # deadline/buffered scheduler variants -- selection arithmetic
            # and post-psum buffering add no wire): the dense level-a
            # reduction
            assert p.wire["train_bytes_per_round"] == level_a_wire, name


def test_ratchet_roundtrip_against_fresh_audit(audit_report):
    """Pinning a baseline from an audit and diffing the same audit against
    it is clean (the --update-baseline / --diff-baseline round-trip), and
    the ratchet only tightens: a doctored baseline below the measured
    metrics regresses the diff."""
    import copy

    from heterofl_tpu.staticcheck.ratchet import baseline_view, diff_reports

    rec = audit_report.to_dict()
    base = baseline_view(rec)
    diff = diff_reports(rec, base)
    assert diff["ok"], diff["regressions"]
    assert not diff["regressions"] and not diff["missing_programs"]

    doctored = copy.deepcopy(base)
    doctored["programs"]["masked/replicated/k1"]["wire.train_bytes_per_round"] -= 4
    diff = diff_reports(rec, doctored)
    assert not diff["ok"]
    assert any(r["metric"] == "wire.train_bytes_per_round"
               for r in diff["regressions"])


def test_auditor_flags_smuggled_io_callback(monkeypatch):
    """End-to-end seeded violation: an io_callback smuggled into the round
    body makes the auditor fail loudly, naming the op AND where it was
    bound."""
    from jax.experimental import io_callback

    from heterofl_tpu.parallel.round_engine import RoundEngine

    orig = RoundEngine._round_core

    def smuggled(self, params, key, lr, user_loc, user_glob, data,
                 resid=None, sched_buf=None):
        new_p, ms, new_resid, new_buf = orig(self, params, key, lr, user_loc,
                                             user_glob, data, resid=resid,
                                             sched_buf=sched_buf)
        # the smuggled host hook (e.g. a sneaky metrics push); the result is
        # discarded but the bind stays in the jaxpr, where the walk finds it
        _ = io_callback(lambda v: np.float32(0.0),
                        jax.ShapeDtypeStruct((), np.float32), lr)
        return new_p, ms, new_resid, new_buf

    monkeypatch.setattr(RoundEngine, "_round_core", smuggled)
    setup = build_setup()
    name, prog, args, expect = _masked_targets(setup)[0]
    rep = audit_program(name, prog, args, expect, setup["mesh"])
    assert not rep.ok
    hits = [f for f in rep.findings if f.rule == "no-host-callback"]
    assert hits, rep.findings
    assert "io_callback" in hits[0].message
    assert "test_staticcheck" in hits[0].message  # provenance of the bind


def test_auditor_flags_lost_donation():
    """Seeded donation regression: a program that stopped donating its
    params (here: a span-mode level program, which donates nothing by
    design) trips both donation checks when held to the donating
    programs' expectation."""
    from heterofl_tpu.staticcheck.audit import _grouped_targets

    setup = build_setup()
    grouped, _names, _ = _grouped_targets(setup)
    name, prog, args, expect = grouped[0]  # span level prog: donates 0
    assert expect["donated"] == 0
    bad_expect = dict(expect,
                      donated=len(jax.tree_util.tree_leaves(setup["params"])))
    rep = audit_program(name, prog, args, bad_expect, setup["mesh"])
    rules = {f.rule for f in rep.findings}
    assert "donation-coverage" in rules and "donation-consumed" in rules, \
        rep.findings


# ---------------------------------------------------------------------------
# donation warnings are errors now (conftest/pytest.ini satellite)
# ---------------------------------------------------------------------------

def test_unused_donation_warning_is_error():
    """'donated buffer unused' can never land silently again: the warning is
    promoted to an error by the test-gate filters."""
    # both inputs are used, both donated, but the single output can consume
    # only one buffer -- the other donation is unusable and must raise
    f = jax.jit(lambda x, y: x + y, donate_argnums=(0, 1))
    with pytest.raises(UserWarning, match="donated buffers were not usable"):
        out = f(jnp.ones((4, 4)), jnp.ones((4, 4)))
        jax.block_until_ready(out)


# ---------------------------------------------------------------------------
# the CLI
# ---------------------------------------------------------------------------

def _run_cli(extra_args, tmp_path, env_extra=None):
    env = dict(os.environ)
    env.update(env_extra or {})
    return subprocess.run(
        [sys.executable, "-m", "heterofl_tpu.staticcheck", "--json",
         "--out", str(tmp_path / "STATICCHECK.json")] + extra_args,
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)


def test_cli_exits_nonzero_on_seeded_lint_violation(tmp_path):
    bad = tmp_path / "tree" / "heterofl_tpu" / "parallel"
    bad.mkdir(parents=True)
    (bad / "bad.py").write_text(
        "import numpy as np\n\ndef f(a):\n    return np.asarray(a)\n")
    res = _run_cli(["--skip-audit", "--lint-root", str(tmp_path / "tree"),
                    "--no-artifact"], tmp_path)
    assert res.returncode == 1, res.stderr
    rec = json.loads(res.stdout)
    assert rec["ok"] is False
    assert [f["rule"] for f in rec["lint"]] == ["no-asarray"]
    # and the same invocation on a clean tree exits 0
    good = tmp_path / "clean" / "heterofl_tpu" / "parallel"
    good.mkdir(parents=True)
    (good / "ok.py").write_text("def f(a):\n    return a\n")
    res = _run_cli(["--skip-audit", "--lint-root", str(tmp_path / "clean"),
                    "--no-artifact"], tmp_path)
    assert res.returncode == 0, res.stdout + res.stderr


def test_bench_refuses_failing_audit_artifact():
    """bench.py must not record a run against a tree whose program audit
    failed: with a failing STATICCHECK.json it emits one refusal line
    (value 0.0, vs_baseline null) and never claims devices."""
    path = os.path.join(REPO, "STATICCHECK.json")
    saved = None
    if os.path.exists(path):
        with open(path) as f:
            saved = f.read()
    try:
        with open(path, "w") as f:
            json.dump({"ok": False, "programs": {}, "lint": []}, f)
        env = dict(os.environ, BENCH_CPU="1")
        res = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                             env=env, capture_output=True, text=True,
                             timeout=300, cwd=REPO)
        rec = json.loads(res.stdout.strip().splitlines()[-1])
        assert rec["value"] == 0.0 and rec["vs_baseline"] is None
        assert "refusing" in rec["extra"]["error"]
        assert rec["extra"]["staticcheck"]["ok"] is False
    finally:
        if saved is None:
            os.remove(path)
        else:
            with open(path, "w") as f:
                f.write(saved)


def test_bench_refuses_regressed_ratchet_artifact():
    """ISSUE 7: a GREEN audit whose baseline ratchet regressed must block
    bench recording the same way a failing audit does."""
    path = os.path.join(REPO, "STATICCHECK.json")
    saved = None
    if os.path.exists(path):
        with open(path) as f:
            saved = f.read()
    try:
        with open(path, "w") as f:
            json.dump({"ok": True, "programs": {}, "lint": [],
                       "ratchet": {"checked": True, "ok": False,
                                   "regressions": [{"program": "p",
                                                    "metric": "flops",
                                                    "baseline": 1,
                                                    "current": 2,
                                                    "tolerance": 0.0,
                                                    "message": "grew"}]}}, f)
        env = dict(os.environ, BENCH_CPU="1")
        res = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                             env=env, capture_output=True, text=True,
                             timeout=300, cwd=REPO)
        rec = json.loads(res.stdout.strip().splitlines()[-1])
        assert rec["value"] == 0.0 and rec["vs_baseline"] is None
        assert "ratchet" in rec["extra"]["error"]
        assert rec["extra"]["staticcheck"]["ratchet_ok"] is False
        assert rec["extra"]["staticcheck"]["ratchet_regressions"] == 1
    finally:
        if saved is None:
            os.remove(path)
        else:
            with open(path, "w") as f:
                f.write(saved)


@pytest.mark.slow
def test_cli_full_audit_green_and_writes_artifact(tmp_path):
    """`python -m heterofl_tpu.staticcheck --json` exits 0 on the repo and
    the artifact asserts the acceptance invariants."""
    env_extra = {}
    if jax.config.jax_compilation_cache_dir:
        env_extra["JAX_COMPILATION_CACHE_DIR"] = jax.config.jax_compilation_cache_dir
    res = _run_cli([], tmp_path, env_extra=env_extra)
    assert res.returncode == 0, res.stdout[-2000:] + res.stderr[-2000:]
    rec = json.loads((tmp_path / "STATICCHECK.json").read_text())
    assert rec["ok"] is True
    assert rec["programs"]["grouped/span/k8-fused"]["psum_clients"] == 1
    assert rec["programs"]["grouped/slices/k8-fused"]["psum_clients"] == 1
    for name, p in rec["programs"].items():
        assert p["aliased"] == p["donation_expected"], name


# ---------------------------------------------------------------------------
# the hot-step kernel budget (ISSUE 5)
# ---------------------------------------------------------------------------

def test_step_body_kernel_counts_recorded_and_budgeted(audit_report):
    """Every audited program records its scan-body kernel stats; the two
    level-a critical-path programs are held to STEP_BODY_FUSION_BUDGET."""
    from heterofl_tpu.staticcheck.audit import STEP_BODY_FUSION_BUDGET

    for name, budget in STEP_BODY_FUSION_BUDGET.items():
        p = audit_report.programs[name]
        assert p.step_body is not None and p.step_body["fusions"] > 0, name
        assert p.step_body_budget == budget, name
        assert p.step_body["fusions"] <= budget, (name, p.step_body)
    # recorded (not budgeted) everywhere else too
    k8 = audit_report.programs["masked/replicated/k8"]
    assert k8.step_body is not None and k8.step_body["instructions"] > 0


def test_step_body_budget_catches_unhoisted_masks():
    """The seeded regression the budget exists for: re-materialising the
    per-param masks inside the scan body AND dropping back to the
    reference op chain (the pre-ISSUE-5 step body) must trip the
    step-body-budget check on the masked k1 program."""
    from heterofl_tpu.parallel import RoundEngine
    from heterofl_tpu.staticcheck.audit import PSUM_BUDGET

    setup = build_setup()
    cfg, model, mesh = setup["cfg"], setup["model"], setup["mesh"]
    eng = RoundEngine(model, dict(cfg, fused_update=False,
                                  _masks_in_body=True), mesh)
    fix = (eng.fix_rates,) if eng.fix_rates is not None else ()
    data = tuple(setup["data"]) + fix
    n_dev = mesh.shape["clients"]
    slots = setup["users"] + ((-setup["users"]) % n_dev)
    sds = jax.ShapeDtypeStruct((slots,), np.int32)
    n_leaves = len(jax.tree_util.tree_leaves(setup["params"]))
    rep = audit_program(
        "masked/replicated/k1", eng._build_train(),
        (setup["params"], setup["key"], setup["lr"], sds, sds) + data,
        {"donated": n_leaves, "psum": PSUM_BUDGET}, mesh)
    assert not rep.ok
    hits = [f for f in rep.findings if f.rule == "step-body-budget"]
    assert hits, rep.findings
    assert rep.step_body["fusions"] > rep.step_body_budget


def test_scan_body_kernel_count_parses_hlo():
    """The HLO walker finds the while body and counts its fusions on a
    minimal scanned program."""
    from heterofl_tpu.staticcheck.jaxpr_walk import (scan_body_kernel_count,
                                                     while_body_stats)

    def f(c, _):
        return jnp.sin(c) * 2.0 + jnp.cos(c), None

    prog = jax.jit(lambda c: jax.lax.scan(f, c, None, length=64),
                   donate_argnums=())
    text = prog.lower(jnp.ones((128,), jnp.float32)).compile().as_text()
    stats = while_body_stats(text)
    assert stats, "no while body found in scanned program HLO"
    body = scan_body_kernel_count(text)
    assert body["body"] in stats and body["instructions"] > 0


def test_shadowed_inline_import_rule():
    """ISSUE 6 satellite: a function-body import of a module the file
    already imports at module level is flagged in entry/ (the
    entry/common.py inline `import math` regression); genuinely lazy
    imports (name not bound at module level) stay legal, and the pragma
    suppresses with a reason."""
    src = """
    import math
    import json

    def f(x):
        import math
        return math.ceil(x)
    """
    fs = _lint(src, "heterofl_tpu/entry/common.py")
    assert [f.rule for f in fs] == ["no-shadowed-inline-import"]
    # scoped to entry/: engine code may structure imports freely
    assert _lint(src, "heterofl_tpu/parallel/engine.py") == []
    # a lazy import of something NOT bound at module level is fine
    assert _lint("""
    import math

    def f():
        from heterofl_tpu.parallel.grouped import GroupedRoundEngine
        return GroupedRoundEngine
    """, "heterofl_tpu/entry/common.py") == []
    # from-import shadowing counts; aliases resolve by bound name
    fs = _lint("""
    from os import path

    def g():
        from os import path
        return path
    """, "heterofl_tpu/entry/x.py")
    assert [f.rule for f in fs] == ["no-shadowed-inline-import"]
    assert _lint("""
    import math

    def f():
        import math  # staticcheck: allow(no-shadowed-inline-import): reason
        return math
    """, "heterofl_tpu/entry/x.py") == []
    # module-level conditional imports (try/except fallback, platform
    # guard) rebind the module name on purpose -- not a shadow
    assert _lint("""
    import json

    try:
        import ujson as json
    except ImportError:
        import json
    """, "heterofl_tpu/entry/x.py") == []


def test_lint_scope_covers_ops_and_models():
    """ISSUE 5 satellite: the banned-call rules now apply to ops/ and
    models/ (kernel/model code runs INSIDE the round programs)."""
    src = """
    import numpy as np
    import time
    def f(a):
        t = time.time()
        return np.asarray(a), float(a[0]), t
    """
    for scope in ("heterofl_tpu/ops/kernel.py", "heterofl_tpu/models/m.py"):
        rules = {f.rule for f in _lint(src, scope)}
        assert {"no-asarray", "no-float-coercion", "no-wallclock"} <= rules, \
            (scope, rules)
    # data/ stays out of scope for the kernel rules
    assert _lint(src, "heterofl_tpu/data/pipeline.py") == []
