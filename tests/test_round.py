"""Round engine integration: multi-device federated rounds on the virtual
8-device CPU mesh (the multi-chip validation path, SURVEY §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from heterofl_tpu import config as C
from heterofl_tpu.data import fetch_dataset, label_split_masks, split_dataset, stack_client_shards
from heterofl_tpu.models import make_model
from heterofl_tpu.models.spec import mask_params
from heterofl_tpu.parallel import RoundEngine, make_mesh
from heterofl_tpu.parallel.evaluation import Evaluator

from test_models import small_cfg


def _vision_setup(control="1_8_0.5_iid_fix_a1-b1-c1-d1-e1_bn_1_1", data="MNIST", users=8):
    cfg = small_cfg("conv", data_name=data, control=control)
    ds = fetch_dataset(data, synthetic=True, seed=0, synthetic_sizes={"train": 400, "test": 100})
    rng = np.random.default_rng(0)
    split, lsplit = split_dataset(ds, users, cfg["data_split_mode"], rng, classes_size=10)
    x, y, m = stack_client_shards(ds["train"].data, ds["train"].target, split["train"],
                                  list(range(users)))
    lm = label_split_masks(lsplit, users, 10)
    return cfg, ds, (jnp.asarray(x), jnp.asarray(y), jnp.asarray(m), jnp.asarray(lm))


def test_vision_round_loss_decreases_multidevice():
    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    params = model.init(jax.random.key(0))
    mesh = make_mesh(n_clients=4, n_data=2)
    eng = RoundEngine(model, cfg, mesh)
    user_idx = np.array([0, 2, 4, 6])  # rates 1, .5, .25, .0625 territory
    losses = []
    for r in range(3):
        params, ms = eng.train_round(params, jax.random.key(r), 0.05, user_idx, data)
        ms = {k: np.asarray(v) for k, v in ms.items()}
        losses.append(float(ms["loss_sum"].sum() / ms["n"].sum()))
    assert losses[-1] < losses[0], losses
    assert np.isfinite(losses).all()
    # padded slots report zero weight
    params2, ms2 = eng.train_round(params, jax.random.key(9), 0.05, np.array([1, 3, 5]), data)
    n = np.asarray(ms2["n"])
    assert n.shape[0] == 4 and n[-1] == 0.0
    # masked suffix of aggregated params stays identically zero under e-rate view
    sm = mask_params(params2, model.specs, model.groups, 0.0625)
    tail = np.asarray(params2["block1.conv.w"])[:, :, :, 1:] - np.asarray(sm["block1.conv.w"])[:, :, :, 1:]
    assert np.isfinite(np.asarray(params2["block1.conv.w"])).all()


@pytest.mark.slow
def test_tiny_shards_smaller_than_batch():
    """Shards with N < batch size (and N < B/2) must still trace and train:
    the epoch permutation is tiled, dead steps are skipped (review regression)."""
    cfg, ds, _ = _vision_setup()
    model = make_model(cfg)
    params = model.init(jax.random.key(0))
    mesh = make_mesh(n_clients=2, n_data=1)
    eng = RoundEngine(model, cfg, mesh)
    # 4 samples per client with train batch 10 -> SB-N=6 > N=4
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, 255, (8, 4, 28, 28, 1)), jnp.uint8)
    y = jnp.asarray(rng.integers(0, 10, (8, 4)))
    m = jnp.ones((8, 4), jnp.float32)
    # client 1 has only 2 real samples
    m = m.at[1, 2:].set(0.0)
    lm = jnp.ones((8, 10), jnp.float32)
    p2, ms = eng.train_round(params, jax.random.key(0), 0.05, np.array([0, 1]), (x, y, m, lm))
    ms = {k: np.asarray(v) for k, v in ms.items()}
    assert np.isfinite(ms["loss_sum"]).all()
    E = cfg["num_epochs"]["local"]
    assert ms["n"][0] == 4.0 * E  # every real sample seen once per local epoch
    assert ms["n"][1] == 2.0 * E


def test_round_deterministic():
    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    params = model.init(jax.random.key(0))
    mesh = make_mesh(n_clients=2, n_data=1)
    eng = RoundEngine(model, cfg, mesh)
    p1, m1 = eng.train_round(params, jax.random.key(5), 0.05, np.array([0, 1]), data)
    eng2 = RoundEngine(model, cfg, mesh)
    params_b = model.init(jax.random.key(0))
    p2, m2 = eng2.train_round(params_b, jax.random.key(5), 0.05, np.array([0, 1]), data)
    for k in p1:
        np.testing.assert_allclose(np.asarray(p1[k]), np.asarray(p2[k]), rtol=1e-6, err_msg=k)


@pytest.mark.slow
def test_dynamic_mode_round():
    cfg, ds, data = _vision_setup(control="1_8_0.5_iid_dynamic_a1-e1_bn_1_1")
    model = make_model(cfg)
    params = model.init(jax.random.key(0))
    mesh = make_mesh(n_clients=4, n_data=1)
    eng = RoundEngine(model, cfg, mesh)
    params, ms = eng.train_round(params, jax.random.key(0), 0.05, np.array([0, 1, 2, 3]), data)
    rates = np.asarray(ms["rate"])
    assert set(np.unique(rates).tolist()) <= {1.0, 0.0625}
    assert np.isfinite(float(np.asarray(ms["loss_sum"]).sum()))


@pytest.mark.slow
def test_lm_round():
    cfg = small_cfg("transformer", data_name="WikiText2")
    users = 4
    # 4 users x 2 rows x 48 tokens
    rng = np.random.default_rng(0)
    rows = rng.integers(0, 50, size=(users, 2, 48)).astype(np.int64)
    lm = np.ones((users, 50), np.float32)
    model = make_model(cfg)
    params = model.init(jax.random.key(0))
    mesh = make_mesh(n_clients=2, n_data=1)
    eng = RoundEngine(model, cfg, mesh)
    data = (jnp.asarray(rows), jnp.asarray(lm))
    losses = []
    for r in range(3):
        params, ms = eng.train_round(params, jax.random.key(r), 0.5, np.arange(users), data)
        ms = {k: np.asarray(v) for k, v in ms.items()}
        losses.append(float(ms["loss_sum"].sum() / ms["n"].sum()))
    assert losses[-1] < losses[0], losses


def _lm_setup(control="1_4_0.5_iid_fix_a1-b1_bn_1_1", users=4):
    cfg = small_cfg("transformer", data_name="WikiText2", control=control)
    rng = np.random.default_rng(0)
    rows = rng.integers(0, 50, size=(users, 2, 48)).astype(np.int64)
    lm = np.ones((users, 50), np.float32)
    return cfg, (jnp.asarray(rows), jnp.asarray(lm))


@pytest.mark.slow
def test_lm_seq_parallel_matches_single_device():
    """Sequence parallelism over the 'data' axis (ring attention + psum'd
    grads, shard-invariant token corruption) matches the clients-only mesh:
    a (2,2) mesh LM round equals a (2,1) mesh round with the same keys
    (dropout 0 -- dropout shards are decorrelated by design)."""
    cfg, data = _lm_setup()
    model = make_model(cfg)
    user_idx = np.arange(4)

    p1 = model.init(jax.random.key(0))
    eng1 = RoundEngine(model, cfg, make_mesh(2, 1))
    out1, ms1 = eng1.train_round(p1, jax.random.key(5), 0.5, user_idx, data)

    p2 = model.init(jax.random.key(0))
    eng2 = RoundEngine(model, cfg, make_mesh(2, 2))
    out2, ms2 = eng2.train_round(p2, jax.random.key(5), 0.5, user_idx, data)

    for k in out1:
        np.testing.assert_allclose(np.asarray(out1[k]), np.asarray(out2[k]),
                                   rtol=2e-3, atol=1e-5, err_msg=k)
    np.testing.assert_allclose(np.asarray(ms1["loss_sum"]), np.asarray(ms2["loss_sum"]),
                               rtol=1e-4)
    np.testing.assert_allclose(np.asarray(ms1["n"]), np.asarray(ms2["n"]))


@pytest.mark.slow
def test_lm_seq_parallel_four_way_with_dropout_runs():
    """4-way sequence sharding with dropout>0 trains and the loss falls."""
    cfg, data = _lm_setup()
    cfg["transformer"]["dropout"] = 0.1
    model = make_model(cfg)
    mesh = make_mesh(2, 4)
    eng = RoundEngine(model, cfg, mesh)
    params = model.init(jax.random.key(0))
    losses = []
    for r in range(3):
        params, ms = eng.train_round(params, jax.random.key(r), 0.5, np.arange(4), data)
        ms = {k: np.asarray(v) for k, v in ms.items()}
        losses.append(float(ms["loss_sum"].sum() / ms["n"].sum()))
    assert np.isfinite(losses).all() and losses[-1] < losses[0], losses


@pytest.mark.slow
def test_sbn_and_eval():
    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    params = model.init(jax.random.key(0))
    mesh = make_mesh(n_clients=4, n_data=2)
    ev = Evaluator(model, cfg, mesh)
    # batch the train set [S, B, ...]
    B = 20
    xtr = ds["train"].data[:400].reshape(-1, B, 28, 28, 1)
    wtr = np.ones(xtr.shape[:2], np.float32)
    bn = ev.sbn_stats(params, xtr, wtr)
    assert set(bn.keys()) == set(model.bn_sites)
    for site, (mu, var) in bn.items():
        assert np.isfinite(np.asarray(mu)).all() and (np.asarray(var) >= 0).all()
    # global eval
    xte = ds["test"].data.reshape(-1, 20, 28, 28, 1)
    yte = ds["test"].target.reshape(-1, 20)
    wte = np.ones(xte.shape[:2], np.float32)
    out = ev.eval_global(params, bn, xte, yte, wte)
    assert out["n"] == 100.0
    assert 0 <= out["score_sum"] <= 100
    # per-user local eval: 4 users, shards of 25 -> 1 batch of 25 (pad to B=25)
    xu = ds["test"].data[:100].reshape(4, 1, 25, 28, 28, 1)
    yu = ds["test"].target[:100].reshape(4, 1, 25)
    wu = np.ones((4, 1, 25), np.float32)
    lmu = np.ones((4, 10), np.float32)
    res = ev.eval_users(params, bn, xu, yu, wu, lmu)
    assert res["n"].shape == (4,) and np.all(res["n"] == 25.0)


@pytest.mark.slow
def test_eval_rng_varies_across_epochs():
    """Eval-time LM token corruption draws fresh noise per round: keys are
    fold_in(key, epoch), so a frozen model yields *different* Global metrics
    across epochs (ref draws fresh Bernoulli noise per eval pass,
    src/models/transformer.py:148-151) while the same epoch reproduces
    exactly."""
    cfg, _ = _lm_setup()
    model = make_model(cfg)
    params = model.init(jax.random.key(0))
    ev = Evaluator(model, cfg, make_mesh(2, 1))
    rng = np.random.default_rng(1)
    rows = rng.integers(0, 50, size=(2, 2, 48)).astype(np.int64)
    w = np.ones(rows.shape, np.float32)
    g0a = ev.eval_global(params, {}, rows, w, epoch=0)
    g0b = ev.eval_global(params, {}, rows, w, epoch=0)
    g1 = ev.eval_global(params, {}, rows, w, epoch=1)
    assert g0a["loss_sum"] == g0b["loss_sum"]
    assert g0a["loss_sum"] != g1["loss_sum"]


@pytest.mark.slow
def test_eval_rng_varies_across_seeds():
    """Eval RNG descends from the EXPERIMENT seed (ref: the eval pass draws
    from the seed-controlled global torch RNG, src/models/transformer.py:148-151):
    two experiments with different seeds see different LM corruption noise on
    the same frozen model, while the same seed reproduces exactly."""
    cfg, _ = _lm_setup()
    model = make_model(cfg)
    params = model.init(jax.random.key(0))
    rng = np.random.default_rng(1)
    rows = rng.integers(0, 50, size=(2, 2, 48)).astype(np.int64)
    w = np.ones(rows.shape, np.float32)
    g_s0 = Evaluator(model, cfg, make_mesh(2, 1), seed=0).eval_global(params, {}, rows, w, epoch=0)
    g_s0b = Evaluator(model, cfg, make_mesh(2, 1), seed=0).eval_global(params, {}, rows, w, epoch=0)
    g_s1 = Evaluator(model, cfg, make_mesh(2, 1), seed=1).eval_global(params, {}, rows, w, epoch=0)
    assert g_s0["loss_sum"] == g_s0b["loss_sum"]
    assert g_s0["loss_sum"] != g_s1["loss_sum"]


@pytest.mark.slow
def test_client_failure_injection():
    """Failed clients' updates never reach aggregation; an all-failed round
    leaves the global model untouched (stale rule)."""
    cfg, ds, data = _vision_setup()
    cfg["client_failure_rate"] = 1.0
    model = make_model(cfg)
    params = model.init(jax.random.key(0))
    p_np = {k: np.asarray(v) for k, v in params.items()}
    eng = RoundEngine(model, cfg, make_mesh(2, 1))
    new, ms = eng.train_round(params, jax.random.key(0), 0.05, np.array([0, 1]), data)
    for k in p_np:
        np.testing.assert_array_equal(np.asarray(new[k]), p_np[k], err_msg=k)
    assert float(np.asarray(ms["n"]).sum()) == 0.0
    # partial failure still trains
    cfg2 = dict(cfg)
    cfg2["client_failure_rate"] = 0.5
    eng2 = RoundEngine(model, cfg2, make_mesh(2, 1))
    params2 = model.init(jax.random.key(0))
    new2, ms2 = eng2.train_round(params2, jax.random.key(3), 0.05,
                                 np.arange(8, dtype=np.int32), data)
    n2 = np.asarray(ms2["n"])
    assert 0 < (n2 > 0).sum() < 8  # some failed, some trained


@pytest.mark.slow
def test_data_parallel_axis_matches_single_device():
    """Intra-client batch DP over the 'data' axis (psum'd grads + sync BN) is
    numerically identical to running each client on one device: a (2,2) mesh
    round equals a (4,1) mesh round with the same keys (MNIST: no augment)."""
    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    user_idx = np.array([0, 2, 4, 6])

    p1 = model.init(jax.random.key(0))
    eng1 = RoundEngine(model, cfg, make_mesh(4, 1))
    out1, ms1 = eng1.train_round(p1, jax.random.key(5), 0.05, user_idx, data)

    p2 = model.init(jax.random.key(0))
    eng2 = RoundEngine(model, cfg, make_mesh(2, 2))
    out2, ms2 = eng2.train_round(p2, jax.random.key(5), 0.05, user_idx, data)

    for k in out1:
        np.testing.assert_allclose(np.asarray(out1[k]), np.asarray(out2[k]),
                                   rtol=5e-3, atol=5e-5, err_msg=k)
    np.testing.assert_allclose(np.asarray(ms1["loss_sum"]), np.asarray(ms2["loss_sum"]),
                               rtol=1e-4)
    np.testing.assert_allclose(np.asarray(ms1["n"]), np.asarray(ms2["n"]))


@pytest.mark.slow
def test_sharded_placement_matches_replicated():
    """Client-sharded data placement (each client trains on the device owning
    its shard, VERDICT r1 item 6): numerically identical global params to the
    replicated layout (per-client RNG is keyed by global user id, so the
    client->device assignment cannot matter), and per-device train-stack
    buffers hold exactly U/n_dev client shards."""
    from heterofl_tpu.parallel import shard_client_data

    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    user_idx = np.array([0, 2, 5, 6])  # owners {0,1,2,3} on a 4-dev axis: 0,1,2,3
    mesh = make_mesh(n_clients=4, n_data=1)

    p1 = model.init(jax.random.key(0))
    eng1 = RoundEngine(model, cfg, mesh)
    out1, ms1 = eng1.train_round(p1, jax.random.key(5), 0.05, user_idx, data)

    cfg2 = dict(cfg)
    cfg2["data_placement"] = "sharded"
    sharded = shard_client_data(mesh, data)
    # the big per-user stacks live 1/n_dev per device
    for arr, orig in zip(sharded, data):
        shard0 = arr.addressable_shards[0].data
        assert shard0.shape[0] == arr.shape[0] // 4
        assert shard0.nbytes * 4 == arr.nbytes
    p2 = model.init(jax.random.key(0))
    eng2 = RoundEngine(model, cfg2, mesh)
    out2, ms2 = eng2.train_round(p2, jax.random.key(5), 0.05, user_idx, data=sharded)

    for k in out1:
        np.testing.assert_allclose(np.asarray(out1[k]), np.asarray(out2[k]),
                                   rtol=1e-6, atol=1e-7, err_msg=k)
    # metric sums are slot-order independent
    np.testing.assert_allclose(np.asarray(ms1["loss_sum"]).sum(),
                               np.asarray(ms2["loss_sum"]).sum(), rtol=1e-6)
    assert np.asarray(ms1["n"]).sum() == np.asarray(ms2["n"]).sum()


@pytest.mark.slow
def test_sharded_placement_lm_matches_replicated():
    """Sharded placement on the LM path: token-row stacks sharded over the
    clients axis give the same round as replicated."""
    from heterofl_tpu.parallel import shard_client_data

    cfg, data = _lm_setup()
    model = make_model(cfg)
    mesh = make_mesh(2, 1)
    user_idx = np.arange(4)

    p1 = model.init(jax.random.key(0))
    out1, ms1 = RoundEngine(model, cfg, mesh).train_round(
        p1, jax.random.key(5), 0.5, user_idx, data)

    cfg2 = dict(cfg)
    cfg2["data_placement"] = "sharded"
    sharded = shard_client_data(mesh, data)
    assert sharded[0].addressable_shards[0].data.shape[0] == 2
    p2 = model.init(jax.random.key(0))
    out2, ms2 = RoundEngine(model, cfg2, mesh).train_round(
        p2, jax.random.key(5), 0.5, user_idx, sharded)

    for k in out1:
        np.testing.assert_allclose(np.asarray(out1[k]), np.asarray(out2[k]),
                                   rtol=1e-6, atol=1e-7, err_msg=k)
    np.testing.assert_allclose(np.asarray(ms1["n"]).sum(), np.asarray(ms2["n"]).sum())


@pytest.mark.slow
def test_sharded_placement_unbalanced_and_padded():
    """Sharded placement with a non-divisible user count and an unbalanced
    active set (3 actives owned by one device) trains correctly; padded users
    are never touched."""
    from heterofl_tpu.parallel import shard_client_data

    cfg, ds, data = _vision_setup(control="1_6_0.5_iid_fix_a1-b1_bn_1_1", users=6)
    model = make_model(cfg)
    mesh = make_mesh(n_clients=4, n_data=1)  # U=6 pads to 8, 2 users per device
    sharded = shard_client_data(mesh, data)
    assert sharded[0].shape[0] == 8
    cfg = dict(cfg)
    cfg["data_placement"] = "sharded"
    eng = RoundEngine(model, cfg, mesh)
    params = model.init(jax.random.key(0))
    user_idx = np.array([0, 1, 2, 5])  # devices 0,0,1,2 -> slots=2, dev 3 idle
    out, ms = eng.train_round(params, jax.random.key(1), 0.05, user_idx, sharded)
    ms = {k: np.asarray(v) for k, v in ms.items()}
    E = cfg["num_epochs"]["local"]
    expect = float(np.asarray(data[2])[user_idx].sum()) * E
    assert ms["n"].sum() == expect  # every active shard fully visited
    assert np.isfinite(ms["loss_sum"]).all()
    for k in out:
        assert np.isfinite(np.asarray(out[k])).all(), k


@pytest.mark.slow
def test_scan_unroll_equivalent():
    """``scan_unroll`` is a pure perf knob: unrolled local-step loops (incl. a
    non-dividing factor) give the same round up to XLA fusion reassociation."""
    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    outs = []
    for unroll in (1, 3):
        cfg_u = dict(cfg)
        cfg_u["scan_unroll"] = unroll
        p = model.init(jax.random.key(0))
        eng = RoundEngine(model, cfg_u, make_mesh(1, 1))
        out, _ = eng.train_round(p, jax.random.key(3), 0.05,
                                 np.arange(2, dtype=np.int32), data)
        outs.append({k: np.asarray(v) for k, v in out.items()})
    for k in outs[0]:
        # fusion reassociation compounds over the local steps; a semantic bug
        # (skipped/duplicated step) would show as O(1e-1) differences
        np.testing.assert_allclose(outs[0][k], outs[1][k], rtol=2e-2, atol=2e-4,
                                   err_msg=k)


@pytest.mark.slow
def test_scan_unroll_single_step_exact():
    """With exactly ONE local step (E*S=1) the unrolled and non-unrolled
    programs must agree near-exactly -- a tight complement to the loose
    multi-step tolerance above that would catch an off-by-one in the unroll
    remainder handling (advisor finding, round 2)."""
    cfg, ds, _ = _vision_setup()
    cfg["num_epochs"]["local"] = 1
    model = make_model(cfg)
    rng = np.random.default_rng(0)
    # one batch per client: shard size == train batch size -> S=1
    b = cfg["batch_size"]["train"]
    x = jnp.asarray(rng.integers(0, 255, (8, b, 28, 28, 1)), jnp.uint8)
    y = jnp.asarray(rng.integers(0, 10, (8, b)))
    m = jnp.ones((8, b), jnp.float32)
    lm = jnp.ones((8, 10), jnp.float32)
    data = (x, y, m, lm)
    outs = []
    for unroll in (1, 3):
        cfg_u = dict(cfg)
        cfg_u["scan_unroll"] = unroll
        p = model.init(jax.random.key(0))
        eng = RoundEngine(model, cfg_u, make_mesh(1, 1))
        out, ms = eng.train_round(p, jax.random.key(3), 0.05,
                                  np.arange(2, dtype=np.int32), data)
        assert float(np.asarray(ms["n"]).sum()) == 2.0 * b  # exactly one pass
        outs.append({k: np.asarray(v) for k, v in out.items()})
    for k in outs[0]:
        np.testing.assert_allclose(outs[0][k], outs[1][k], rtol=1e-6, atol=1e-7,
                                   err_msg=k)
