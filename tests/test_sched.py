"""Scheduler subsystem (ISSUE 9): availability traces, deadline stragglers,
buffered-async aggregation, per-level codec maps, rolling eval cohorts.

The contracts under test:

* **lockstep untouched** -- ``schedule=None`` and ``{"kind": "uniform"}``
  build the same programs and the same trajectories (zero new carry args);
* **replayable sampling** -- trace/markov schedules reproduce identical
  cohorts across runs and across a resume-style re-draw, the in-jit trace
  path is bit-identical to the host-schedule path, and all-ones
  availability IS the uniform stream;
* **deadline + buffered** -- superstep == sequential bit for bit (the
  staleness buffer carried across dispatches via its checkpoint pair),
  both engines;
* **per-level codec map** -- the grouped fused superstep compresses each
  level under its own codec in one psum bind, with the concatenated EF
  residual round-tripping through save/restore;
* **rolling eval cohort** -- O(cohort) Local eval on the streaming store
  with loud validation and the O(U) warning retired.
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from heterofl_tpu import config as C
from heterofl_tpu.fed.core import (round_rates, round_users,
                                   superstep_rate_schedule,
                                   superstep_user_schedule)
from heterofl_tpu.models import make_model
from heterofl_tpu.parallel import GroupedRoundEngine, RoundEngine, make_mesh
from heterofl_tpu.sched import (ScheduleSpec, markov_trace,
                                resolve_schedule_cfg, staleness_weight)

from test_round import _vision_setup

HOST_KEY = jax.random.key(0)


def _lr_host(cfg, epoch):
    """Sequential baselines consume the traced LR schedule host-evaluated
    (f32) -- exactly what the superstep computes in-jit (test_superstep's
    convention)."""
    from heterofl_tpu.utils.optim import make_traced_lr_fn

    return float(np.asarray(make_traced_lr_fn(cfg)(jnp.int32(epoch))))


def _params_equal(a, b):
    for k in a:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]),
                                      err_msg=k)


def _trace_cfg(cfg, trace, **extra):
    return dict(cfg, schedule={"kind": "trace", "trace": trace.tolist(),
                               **extra})


# ---------------------------------------------------------------------------
# the sampling stream
# ---------------------------------------------------------------------------

def test_round_users_all_ones_availability_is_uniform():
    """An all-ones availability row must select exactly the uniform cohort
    (the stable sort preserves permutation order) -- trace replay is a
    strict generalisation of the uniform stream."""
    key = jax.random.key(3)
    base = np.asarray(round_users(key, 16, 6))
    avail = np.asarray(round_users(key, 16, 6, avail=np.ones(16, np.uint8)))
    np.testing.assert_array_equal(base, avail)


def test_round_users_partial_availability_pads_with_minus_one():
    key = jax.random.key(4)
    avail = np.zeros(16, np.uint8)
    avail[[2, 5]] = 1
    got = np.asarray(round_users(key, 16, 6, avail=avail))
    assert got.shape == (6,)
    assert set(got[got >= 0].tolist()) == {2, 5}
    assert (got[2:] == -1).all()  # available users drawn first, then padding
    # deterministic: the same key + row reproduces the draw
    np.testing.assert_array_equal(
        got, np.asarray(round_users(jax.random.key(4), 16, 6, avail=avail)))


def test_markov_trace_replayable_and_binary():
    t1 = markov_trace(12, 9, 0.5, 0.3, seed=7)
    t2 = markov_trace(12, 9, 0.5, 0.3, seed=7)
    np.testing.assert_array_equal(t1, t2)
    assert t1.shape == (9, 12) and set(np.unique(t1)) <= {0, 1}
    assert markov_trace(12, 9, 0.5, 0.3, seed=8).tolist() != t1.tolist()


def test_schedule_replay_across_runs_and_resume():
    """Trace-driven cohorts reproduce across independent draws AND across a
    checkpoint-resume-style re-draw from a later epoch: the [k, A] schedule
    is a pure function of (host key, epochs, spec)."""
    spec = resolve_schedule_cfg({
        "num_users": 10,
        "schedule": {"kind": "markov",
                     "markov": {"p_on": 0.6, "p_off": 0.4, "length": 6,
                                "seed": 3}}})
    full = superstep_user_schedule(HOST_KEY, 1, 8, 10, 4, schedule=spec)
    again = superstep_user_schedule(HOST_KEY, 1, 8, 10, 4, schedule=spec)
    np.testing.assert_array_equal(full, again)
    resumed = superstep_user_schedule(HOST_KEY, 5, 4, 10, 4, schedule=spec)
    np.testing.assert_array_equal(full[4:], resumed)
    # the trace cycles past its length (epoch 7 reuses row (7-1) % 6)
    assert spec.avail_row(7).tolist() == spec.avail_row(1).tolist()


def test_resolve_schedule_cfg_validation():
    ok = resolve_schedule_cfg({"schedule": None})
    assert ok.lockstep and ok.trace is None
    assert resolve_schedule_cfg({"schedule": {"kind": "uniform"}}).lockstep
    with pytest.raises(ValueError, match="schedule kind"):
        resolve_schedule_cfg({"schedule": {"kind": "round-robin"}})
    with pytest.raises(ValueError, match="schedule keys"):
        resolve_schedule_cfg({"schedule": {"knd": "uniform"}})
    with pytest.raises(ValueError, match="needs a 'trace'"):
        resolve_schedule_cfg({"schedule": {"kind": "trace"}})
    with pytest.raises(ValueError, match="0/1 only"):
        resolve_schedule_cfg({"schedule": {"kind": "trace",
                                           "trace": [[2, 0], [1, 1]]}})
    with pytest.raises(ValueError, match="num_users"):
        resolve_schedule_cfg({"num_users": 3,
                              "schedule": {"kind": "trace",
                                           "trace": [[1, 0], [1, 1]]}})
    with pytest.raises(ValueError, match="min_frac"):
        resolve_schedule_cfg({"schedule": {"deadline": {"min_frac": 1.5}}})
    with pytest.raises(ValueError, match="aggregation"):
        resolve_schedule_cfg({"schedule": {"aggregation": "async"}})
    with pytest.raises(ValueError, match="staleness"):
        resolve_schedule_cfg({"schedule": {"staleness": 0.0}})
    with pytest.raises(ValueError, match="markov"):
        resolve_schedule_cfg({"num_users": 4,
                              "schedule": {"kind": "markov",
                                           "markov": {"p_on": 2.0}}})
    assert staleness_weight(0.5, 1) == pytest.approx(0.5 / np.sqrt(2.0))


# ---------------------------------------------------------------------------
# lockstep bit-identity (the zero-new-args contract)
# ---------------------------------------------------------------------------

def test_uniform_schedule_is_bit_identical_to_no_schedule():
    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    mesh = make_mesh(4, 1)
    k, A = 2, 4

    def run(c):
        eng = RoundEngine(model, c, mesh)
        p = model.init(jax.random.key(0))
        p, pending = eng.train_superstep(p, HOST_KEY, 1, k, data, num_active=A)
        return p, pending.fetch()

    p0, ms0 = run(cfg)
    p1, ms1 = run(dict(cfg, schedule={"kind": "uniform",
                                      "aggregation": "sync"}))
    _params_equal(p0, p1)
    for r in range(k):
        np.testing.assert_array_equal(np.asarray(ms0[r]["n"]),
                                      np.asarray(ms1[r]["n"]))


# ---------------------------------------------------------------------------
# availability traces inside the engines
# ---------------------------------------------------------------------------

def test_trace_superstep_in_jit_matches_host_schedule_bitwise():
    """The masked engine's in-jit trace sampling (the trace rides as a
    program argument) is bit-identical to dispatching the SAME engine with
    the host-drawn schedule -- the two halves of the one stream."""
    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    mesh = make_mesh(4, 1)
    k, A = 2, 4
    trace = markov_trace(cfg["num_users"], 5, 0.6, 0.5, seed=2)
    assert trace.sum() not in (0, trace.size)  # a real mix of on/off
    scfg = _trace_cfg(cfg, trace)
    spec = resolve_schedule_cfg(scfg)

    eng = RoundEngine(model, scfg, mesh)
    p_jit = model.init(jax.random.key(0))
    p_jit, pend = eng.train_superstep(p_jit, HOST_KEY, 1, k, data,
                                      num_active=A)
    ms_jit = pend.fetch()

    sched = superstep_user_schedule(HOST_KEY, 1, k, cfg["num_users"], A,
                                    schedule=spec)
    eng2 = RoundEngine(model, scfg, mesh)
    p_host = model.init(jax.random.key(0))
    p_host, pend = eng2.train_superstep(p_host, HOST_KEY, 1, k, data,
                                        user_schedule=sched)
    ms_host = pend.fetch()
    _params_equal(p_jit, p_host)
    for r in range(k):
        np.testing.assert_array_equal(np.asarray(ms_jit[r]["n"]),
                                      np.asarray(ms_host[r]["n"]))
    # unavailable slots really sat out: round r's participants are capped
    # by the trace row's availability
    for r in range(k):
        avail = int(spec.avail_row(1 + r).sum())
        active = int((np.asarray(ms_jit[r]["n"]) > 0).sum())
        assert active <= min(A, avail)


def test_trace_schedule_grouped_handles_unfilled_slots():
    cfg, ds, data = _vision_setup()
    mesh = make_mesh(4, 1)
    k, A = 2, 4
    trace = np.zeros((3, cfg["num_users"]), np.uint8)
    trace[:, :2] = 1  # only users 0/1 ever available -> 2 of 4 slots fill
    scfg = _trace_cfg(cfg, trace)
    spec = resolve_schedule_cfg(scfg)
    sched = superstep_user_schedule(HOST_KEY, 1, k, cfg["num_users"], A,
                                    schedule=spec)
    assert (sched == -1).any()
    rates = superstep_rate_schedule(HOST_KEY, 1, k, scfg, sched)
    grp = GroupedRoundEngine(scfg, mesh)
    model = make_model(cfg)
    p = model.init(jax.random.key(0))
    p, pending = grp.train_superstep(p, HOST_KEY, 1, k, sched, rates, data)
    ms = pending.fetch()
    for r in range(k):
        n = np.asarray(ms[r]["n"])
        assert (n[sched[r] == -1] == 0).all()
        assert (n[sched[r] >= 0] > 0).all()
        assert np.isfinite(np.asarray(ms[r]["loss_sum"])).all()
    assert all(np.isfinite(np.asarray(v)).all() for v in p.values())


# ---------------------------------------------------------------------------
# deadline stragglers
# ---------------------------------------------------------------------------

def test_deadline_superstep_masked_bit_identical_to_sequential():
    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    mesh = make_mesh(4, 1)
    k, A = 3, 4
    dcfg = dict(cfg, schedule={"deadline": {"min_frac": 0.3}})

    eng_seq = RoundEngine(model, dcfg, mesh)
    p_seq = model.init(jax.random.key(0))
    seq_ms = []
    for r in range(k):
        e = 1 + r
        key = jax.random.fold_in(HOST_KEY, e)
        uidx = np.asarray(round_users(key, cfg["num_users"], A))
        p_seq, ms = eng_seq.train_round(p_seq, key, _lr_host(dcfg, e), uidx,
                                        data)
        seq_ms.append({n: np.asarray(v) for n, v in ms.items()})

    eng = RoundEngine(model, dcfg, mesh)
    p = model.init(jax.random.key(0))
    p, pending = eng.train_superstep(p, HOST_KEY, 1, k, data, num_active=A)
    ss_ms = pending.fetch()
    _params_equal(p_seq, p)
    for r in range(k):
        for name in ("loss_sum", "score_sum", "n", "rate"):
            np.testing.assert_array_equal(seq_ms[r][name],
                                          np.asarray(ss_ms[r][name]),
                                          err_msg=f"round {r} {name}")


def test_deadline_truncates_training_and_metrics():
    """A tight deadline must actually shrink the per-client processed
    sample counts vs lockstep, and produce different params (the step
    truncation is real, not a no-op)."""
    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    mesh = make_mesh(4, 1)
    uidx = np.array([0, 1, 2, 3])
    key = jax.random.key(11)

    eng0 = RoundEngine(model, cfg, mesh)
    p0, ms0 = eng0.train_round(model.init(jax.random.key(0)), key, 0.05,
                               uidx, data)
    engd = RoundEngine(model, dict(cfg, schedule={"deadline":
                                                  {"min_frac": 0.2}}), mesh)
    pd, msd = engd.train_round(model.init(jax.random.key(0)), key, 0.05,
                               uidx, data)
    n0 = float(np.asarray(ms0["n"]).sum())
    nd = float(np.asarray(msd["n"]).sum())
    assert 0 < nd < n0
    assert any(not np.array_equal(np.asarray(p0[k]), np.asarray(pd[k]))
               for k in p0)
    assert all(np.isfinite(np.asarray(v)).all() for v in pd.values())


def test_deadline_grouped_superstep_bit_identical_to_k1_sequence():
    cfg, ds, data = _vision_setup()
    mesh = make_mesh(4, 1)
    model = make_model(cfg)
    k, A = 2, 4
    dcfg = dict(cfg, schedule={"deadline": {"min_frac": 0.3}})
    sched = superstep_user_schedule(HOST_KEY, 1, k, cfg["num_users"], A)
    rates = superstep_rate_schedule(HOST_KEY, 1, k, dcfg, sched)

    grp_seq = GroupedRoundEngine(dcfg, mesh)
    p_seq = model.init(jax.random.key(0))
    for r in range(k):
        p_seq, pend = grp_seq.train_superstep(
            p_seq, HOST_KEY, 1 + r, 1, sched[r:r + 1], rates[r:r + 1], data)
        pend.fetch()

    grp = GroupedRoundEngine(dcfg, mesh)
    p = model.init(jax.random.key(0))
    p, pend = grp.train_superstep(p, HOST_KEY, 1, k, sched, rates, data)
    pend.fetch()
    _params_equal(p_seq, p)


# ---------------------------------------------------------------------------
# buffered asynchronous aggregation
# ---------------------------------------------------------------------------

BUF_SCHED = {"aggregation": "buffered", "staleness": 0.5}


def test_buffered_masked_superstep_matches_sequential_with_carried_buffer():
    """superstep == sequential with the staleness buffer carried bit for
    bit: K=1 rounds on one engine (the buffer rides the engine state)
    reproduce one K-round superstep on a fresh engine exactly."""
    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    mesh = make_mesh(4, 1)
    k, A = 3, 4
    bcfg = dict(cfg, schedule=dict(BUF_SCHED))

    eng_seq = RoundEngine(model, bcfg, mesh)
    p_seq = model.init(jax.random.key(0))
    for r in range(k):
        e = 1 + r
        key = jax.random.fold_in(HOST_KEY, e)
        uidx = np.asarray(round_users(key, cfg["num_users"], A))
        p_seq, _ = eng_seq.train_round(p_seq, key, _lr_host(bcfg, e), uidx,
                                       data)

    eng = RoundEngine(model, bcfg, mesh)
    p = model.init(jax.random.key(0))
    p, pending = eng.train_superstep(p, HOST_KEY, 1, k, data, num_active=A)
    pending.fetch()
    _params_equal(p_seq, p)
    # the carries agree too (the buffer holds round k's pending update)
    np.testing.assert_array_equal(eng_seq.sched_buf_host(),
                                  eng.sched_buf_host())
    # and buffering genuinely changes the trajectory vs sync lockstep
    eng0 = RoundEngine(model, cfg, mesh)
    p0 = model.init(jax.random.key(0))
    p0, pend0 = eng0.train_superstep(p0, HOST_KEY, 1, k, data, num_active=A)
    pend0.fetch()
    assert any(not np.array_equal(np.asarray(p0[k_]), np.asarray(p[k_]))
               for k_ in p0)


def test_buffered_carry_checkpoint_roundtrip_masked():
    """Save/restore the staleness buffer mid-run: the resumed trajectory is
    bit-identical to the uninterrupted one (the ISSUE 9 checkpoint
    contract, engine level -- what the driver's blob round-trips)."""
    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    mesh = make_mesh(4, 1)
    A = 4
    bcfg = dict(cfg, schedule=dict(BUF_SCHED))

    eng = RoundEngine(model, bcfg, mesh)
    p = model.init(jax.random.key(0))
    p, pend = eng.train_superstep(p, HOST_KEY, 1, 2, data, num_active=A)
    pend.fetch()
    p, pend = eng.train_superstep(p, HOST_KEY, 3, 2, data, num_active=A)
    pend.fetch()
    full_buf = eng.sched_buf_host()

    eng_a = RoundEngine(model, bcfg, mesh)
    p_a = model.init(jax.random.key(0))
    p_a, pend = eng_a.train_superstep(p_a, HOST_KEY, 1, 2, data, num_active=A)
    pend.fetch()
    saved_p = {k_: np.asarray(v) for k_, v in p_a.items()}
    saved_buf = np.array(eng_a.sched_buf_host())  # the checkpoint blob
    assert saved_buf.ndim == 2 and saved_buf.shape[0] == 2

    eng_b = RoundEngine(model, bcfg, mesh)  # a fresh process, post-resume
    eng_b.set_sched_buf(saved_buf)
    p_b = {k_: jnp.asarray(v) for k_, v in saved_p.items()}
    p_b, pend = eng_b.train_superstep(p_b, HOST_KEY, 3, 2, data, num_active=A)
    pend.fetch()
    _params_equal(p, p_b)
    np.testing.assert_array_equal(full_buf, eng_b.sched_buf_host())


def test_buffered_grouped_superstep_and_roundtrip():
    cfg, ds, data = _vision_setup()
    mesh = make_mesh(4, 1)
    model = make_model(cfg)
    k, A = 2, 4
    bcfg = dict(cfg, schedule=dict(BUF_SCHED))
    sched = superstep_user_schedule(HOST_KEY, 1, 2 * k, cfg["num_users"], A)
    rates = superstep_rate_schedule(HOST_KEY, 1, 2 * k, bcfg, sched)

    grp = GroupedRoundEngine(bcfg, mesh)
    p = model.init(jax.random.key(0))
    p, pend = grp.train_superstep(p, HOST_KEY, 1, 2 * k, sched, rates, data)
    pend.fetch()

    grp_a = GroupedRoundEngine(bcfg, mesh)
    p_a = model.init(jax.random.key(0))
    p_a, pend = grp_a.train_superstep(p_a, HOST_KEY, 1, k, sched[:k],
                                      rates[:k], data)
    pend.fetch()
    buf = np.array(grp_a.sched_buf_host())
    grp_b = GroupedRoundEngine(bcfg, mesh)
    grp_b.set_sched_buf(buf)
    p_b = {k_: jnp.asarray(np.asarray(v)) for k_, v in p_a.items()}
    p_b, pend = grp_b.train_superstep(p_b, HOST_KEY, 1 + k, k, sched[k:],
                                      rates[k:], data)
    pend.fetch()
    _params_equal(p, p_b)
    np.testing.assert_array_equal(grp.sched_buf_host(),
                                  grp_b.sched_buf_host())


def test_buffered_grouped_k1_train_round_refused():
    cfg, ds, data = _vision_setup()
    mesh = make_mesh(4, 1)
    grp = GroupedRoundEngine(dict(cfg, schedule=dict(BUF_SCHED)), mesh)
    with pytest.raises(ValueError, match="buffered"):
        grp.train_round(make_model(cfg).init(jax.random.key(0)),
                        np.array([0, 1]), np.array([1.0, 1.0]), data, 0.05,
                        jax.random.key(1))


def test_buffered_plus_lossy_codec_refused():
    cfg, ds, data = _vision_setup()
    mesh = make_mesh(4, 1)
    bad = dict(cfg, schedule=dict(BUF_SCHED), wire_codec="int8")
    with pytest.raises(ValueError, match="buffered"):
        RoundEngine(make_model(cfg), bad, mesh)
    with pytest.raises(ValueError, match="buffered"):
        GroupedRoundEngine(bad, mesh)


# ---------------------------------------------------------------------------
# per-level codec map (satellite)
# ---------------------------------------------------------------------------

def _level_map(cfg, lossy="int8"):
    rates = sorted({float(r) for r in cfg["model_rate"]}, reverse=True)
    return {f"{r:g}": (lossy if i == 0 else "dense")
            for i, r in enumerate(rates)}


def test_per_level_codec_map_close_to_dense_and_roundtrips():
    cfg, ds, data = _vision_setup()
    mesh = make_mesh(4, 1)
    model = make_model(cfg)
    k, A = 2, 8  # every user active so all levels populate
    sched = superstep_user_schedule(HOST_KEY, 1, k, cfg["num_users"], A)
    rates = superstep_rate_schedule(HOST_KEY, 1, k, cfg, sched)

    mcfg = dict(cfg, wire_codec=_level_map(cfg))
    grp = GroupedRoundEngine(mcfg, mesh)
    assert grp._codec_map is not None
    p = model.init(jax.random.key(0))
    p, pend = grp.train_superstep(p, HOST_KEY, 1, k, sched, rates, data)
    pend.fetch()

    grp_d = GroupedRoundEngine(cfg, mesh)
    p_d = model.init(jax.random.key(0))
    p_d, pend = grp_d.train_superstep(p_d, HOST_KEY, 1, k, sched, rates, data)
    pend.fetch()
    # level-a int8 / rest dense: a lossy but small perturbation vs dense
    num = den = 0.0
    for k_ in p:
        d = np.asarray(p[k_], np.float64) - np.asarray(p_d[k_], np.float64)
        num += float((d ** 2).sum())
        den += float((np.asarray(p_d[k_], np.float64) ** 2).sum())
    rel = np.sqrt(num / max(den, 1e-12))
    assert rel < 0.3, rel
    assert all(np.isfinite(np.asarray(v)).all() for v in p.values())

    # concatenated EF residual: [n_dev, 2, total_lossy], checkpoint
    # round-trip bit-identical (the _WireCodecCarry pair, map layout)
    resid = grp.wire_resid_host()
    assert resid is not None and resid.ndim == 3 and resid.shape[1] == 2
    assert resid.shape[2] == grp._map_layout(p)["total_lossy"]

    grp_a = GroupedRoundEngine(mcfg, mesh)
    p_a = model.init(jax.random.key(0))
    p_a, pend = grp_a.train_superstep(p_a, HOST_KEY, 1, 1, sched[:1],
                                      rates[:1], data)
    pend.fetch()
    saved = np.array(grp_a.wire_resid_host())
    grp_b = GroupedRoundEngine(mcfg, mesh)
    grp_b.set_wire_resid(saved)
    p_b = {k_: jnp.asarray(np.asarray(v)) for k_, v in p_a.items()}
    p_b, pend = grp_b.train_superstep(p_b, HOST_KEY, 2, 1, sched[1:],
                                      rates[1:], data)
    pend.fetch()
    grp_c = GroupedRoundEngine(mcfg, mesh)
    p_c = model.init(jax.random.key(0))
    for r in range(k):
        p_c, pend = grp_c.train_superstep(p_c, HOST_KEY, 1 + r, 1,
                                          sched[r:r + 1], rates[r:r + 1],
                                          data)
        pend.fetch()
    _params_equal(p_c, p_b)


def test_per_level_codec_map_single_psum_bind():
    """The per-level payload rides ONE psum bind (the PR 2 invariant): count
    the clients-axis psums in the traced fused superstep."""
    from heterofl_tpu.staticcheck.jaxpr_walk import count_psum_over

    cfg, ds, data = _vision_setup()
    mesh = make_mesh(4, 1)
    model = make_model(cfg)
    mcfg = dict(cfg, wire_codec=_level_map(cfg))
    grp = GroupedRoundEngine(mcfg, mesh)
    from heterofl_tpu.utils.optim import make_traced_lr_fn
    grp._lr_fn = make_traced_lr_fn(mcfg)
    params = model.init(jax.random.key(0))
    prog = grp._superstep_prog(2, 2, "span")
    n_dev = mesh.shape["clients"]
    L = len(grp.levels)
    resid_sds = jax.ShapeDtypeStruct(
        grp._resid_shape(params), np.float32)
    sched_sds = jax.ShapeDtypeStruct((2, L, 2 * n_dev), np.int32)
    jaxpr = prog.trace(params, resid_sds, jax.random.key(0), np.int32(1),
                       sched_sds, *data).jaxpr
    assert count_psum_over(jaxpr, "clients") == 1


@pytest.mark.slow
def test_per_level_codec_map_slices_layout():
    """The per-level map on the SLICES layout (ISSUE 14 satellite,
    retiring the PR 9 refusal): each device row runs one level's switch
    branch yet emits EVERY level's payload structure (identity payloads
    -- codec.zero_payload -- for the non-owned levels, each level's
    codec counting its slice rows as participants).  Same contracts as
    the span map: close to dense, finite, ONE psum bind, EF-residual
    checkpoint round-trip bitwise."""
    cfg, ds, data = _vision_setup()
    mesh = make_mesh(8, 1)  # >= 5 device rows so the slices layout exists
    model = make_model(cfg)
    k, A = 2, 8
    sched = superstep_user_schedule(HOST_KEY, 1, k, cfg["num_users"], A)
    rates = superstep_rate_schedule(HOST_KEY, 1, k, cfg, sched)
    mcfg = dict(cfg, wire_codec=_level_map(cfg), level_placement="slices")
    grp = GroupedRoundEngine(mcfg, mesh)
    assert grp.level_placement == "slices" and grp._codec_map is not None
    assert grp._fused_layout()[0] == "slices"
    p = model.init(jax.random.key(0))
    p, pend = grp.train_superstep(p, HOST_KEY, 1, k, sched, rates, data)
    pend.fetch()
    assert all(np.isfinite(np.asarray(v)).all() for v in p.values())

    grp_d = GroupedRoundEngine(dict(cfg, level_placement="slices"), mesh)
    p_d = model.init(jax.random.key(0))
    p_d, pend = grp_d.train_superstep(p_d, HOST_KEY, 1, k, sched, rates,
                                      data)
    pend.fetch()
    num = den = 0.0
    for k_ in p:
        d = np.asarray(p[k_], np.float64) - np.asarray(p_d[k_], np.float64)
        num += float((d ** 2).sum())
        den += float((np.asarray(p_d[k_], np.float64) ** 2).sum())
    assert np.sqrt(num / max(den, 1e-12)) < 0.3

    # EF residual round-trip: 1 round, checkpoint, 1 more == 2 straight
    grp_a = GroupedRoundEngine(mcfg, mesh)
    p_a = model.init(jax.random.key(0))
    p_a, pend = grp_a.train_superstep(p_a, HOST_KEY, 1, 1, sched[:1],
                                      rates[:1], data)
    pend.fetch()
    saved = np.array(grp_a.wire_resid_host())
    assert saved.shape[1] == 2 \
        and saved.shape[2] == grp_a._map_layout(p_a)["total_lossy"]
    grp_b = GroupedRoundEngine(mcfg, mesh)
    grp_b.set_wire_resid(saved)
    p_b = {k_: jnp.asarray(np.asarray(v)) for k_, v in p_a.items()}
    p_b, pend = grp_b.train_superstep(p_b, HOST_KEY, 2, 1, sched[1:],
                                      rates[1:], data)
    pend.fetch()
    grp_c = GroupedRoundEngine(mcfg, mesh)
    p_c = model.init(jax.random.key(0))
    for r in range(k):
        p_c, pend = grp_c.train_superstep(p_c, HOST_KEY, 1 + r, 1,
                                          sched[r:r + 1], rates[r:r + 1],
                                          data)
        pend.fetch()
    _params_equal(p_c, p_b)


def test_per_level_codec_map_slices_single_psum_bind():
    """Every slices-map switch branch emits every level's payload into
    ONE clients-axis psum bind (the PR 2 invariant)."""
    from heterofl_tpu.staticcheck.jaxpr_walk import count_psum_over
    from heterofl_tpu.utils.optim import make_traced_lr_fn

    cfg, ds, data = _vision_setup()
    mesh = make_mesh(8, 1)
    model = make_model(cfg)
    mcfg = dict(cfg, wire_codec=_level_map(cfg), level_placement="slices")
    grp = GroupedRoundEngine(mcfg, mesh)
    assert grp._fused_layout()[0] == "slices"
    grp._lr_fn = make_traced_lr_fn(mcfg)
    params = model.init(jax.random.key(0))
    n_dev = mesh.shape["clients"]
    resid_sds = jax.ShapeDtypeStruct(grp._resid_shape(params), np.float32)
    sched_sds = jax.ShapeDtypeStruct((2, 1 * n_dev), np.int32)
    prog = grp._superstep_prog(2, 1, "slices")
    jaxpr = prog.trace(params, resid_sds, jax.random.key(0), np.int32(1),
                       sched_sds, *data).jaxpr
    assert count_psum_over(jaxpr, "clients") == 1


def test_all_dense_map_collapses_to_dense():
    from heterofl_tpu.compress import resolve_codec_cfg

    name, ef = resolve_codec_cfg({"wire_codec": {"1.0": "dense",
                                                 "0.5": "dense"}})
    assert name == "dense"
    with pytest.raises(ValueError, match="level key"):
        resolve_codec_cfg({"wire_codec": {"a": "int8"}})
    with pytest.raises(ValueError, match="assigned twice"):
        # "1" and "1.0" coerce to the same rate: loud, never last-wins
        resolve_codec_cfg({"wire_codec": {"1": "int8", "1.0": "dense"}})
    with pytest.raises(ValueError, match="wire_codec for level"):
        resolve_codec_cfg({"wire_codec": {"1.0": "zstd"}})


def test_per_level_map_needs_grouped_engine_and_matching_levels():
    cfg, ds, data = _vision_setup()
    mesh = make_mesh(4, 1)
    model = make_model(cfg)
    eng = RoundEngine(model, dict(cfg, wire_codec=_level_map(cfg)), mesh)
    with pytest.raises(ValueError, match="grouped"):
        eng.train_round(model.init(jax.random.key(0)), jax.random.key(1),
                        0.05, np.array([0, 1]), data)
    with pytest.raises(ValueError, match="level table"):
        GroupedRoundEngine(dict(cfg, wire_codec={"1.0": "int8"}), mesh)
    # the config-RESOLUTION path (driver) still refuses a map under any
    # other strategy -- the ISSUE 18 promotion lives in resolve_codec_cfg
    from heterofl_tpu.compress import resolve_codec_cfg
    with pytest.raises(ValueError, match="strategy='grouped'"):
        resolve_codec_cfg(dict(cfg, wire_codec=_level_map(cfg)))


# ---------------------------------------------------------------------------
# driver integration: config plumbing + checkpointed carries + eval cohort
# ---------------------------------------------------------------------------

def _driver_cfg(tmp_path, **over):
    cfg = C.default_cfg()
    cfg["control"] = C.parse_control_name("1_8_0.5_iid_fix_a1-b1_bn_1_1")
    cfg["data_name"] = "MNIST"
    cfg["model_name"] = "conv"
    cfg["synthetic"] = True
    cfg["synthetic_sizes"] = {"train": 80, "test": 40}
    cfg["output_dir"] = str(tmp_path)
    cfg["override"] = {"num_epochs": {"global": 4, "local": 1},
                       "conv": {"hidden_size": [4, 8]},
                       "batch_size": {"train": 10, "test": 20}, **over}
    return C.process_control(cfg)


def test_driver_scenario_run_and_resume_reproduce(tmp_path):
    """End-to-end: a markov + deadline + buffered streaming run completes,
    checkpoints its staleness buffer, and a resumed run finishes with the
    exact params of an uninterrupted one."""
    from heterofl_tpu.entry.common import FedExperiment

    sched = {"kind": "markov",
             "markov": {"p_on": 0.7, "p_off": 0.4, "length": 8, "seed": 1},
             "deadline": {"min_frac": 0.4},
             "aggregation": "buffered", "staleness": 0.5}
    mk = lambda d: _driver_cfg(d, schedule=sched, client_store="stream",  # noqa: E731
                               superstep_rounds=2, eval_interval=2)
    full = FedExperiment(mk(tmp_path / "full"), 0).run("Global-Accuracy")

    part_dir = tmp_path / "part"
    cfg_p = mk(part_dir)
    cfg_short = dict(cfg_p)
    cfg_short["num_epochs"] = dict(cfg_p["num_epochs"], **{"global": 2})
    FedExperiment(cfg_short, 0).run("Global-Accuracy")
    cfg_res = dict(cfg_p)
    cfg_res["resume_mode"] = 1
    resumed = FedExperiment(cfg_res, 0).run("Global-Accuracy")
    for k_ in full["params"]:
        np.testing.assert_array_equal(np.asarray(full["params"][k_]),
                                      np.asarray(resumed["params"][k_]),
                                      err_msg=k_)


def test_eval_cohort_validation(tmp_path):
    from heterofl_tpu.entry.common import FedExperiment

    with pytest.raises(ValueError, match="client_store='stream'"):
        FedExperiment(_driver_cfg(tmp_path, eval_cohort=2), 0)
    with pytest.raises(ValueError, match="eval_cohort"):
        C.resolve_eval_cohort({"eval_cohort": 0})
    with pytest.raises(ValueError, match="exceeds"):
        C.resolve_eval_cohort({"eval_cohort": 9, "num_users": 8})
    assert C.resolve_eval_cohort({"eval_cohort": None}) is None


def test_eval_cohort_rolling_window_stages_o_cohort(tmp_path):
    """Streaming + eval_cohort: the fused Local eval covers exactly the
    rolling window (O(cohort), not O(population)), windows advance with the
    eval cadence, and the >1e5-user warning path is retired (no warning
    fires on this configuration)."""
    from heterofl_tpu.entry.common import FedExperiment

    cfg = _driver_cfg(tmp_path, client_store="stream", superstep_rounds=2,
                      eval_interval=2, eval_cohort=3)
    exp = FedExperiment(cfg, 0)
    with warnings.catch_warnings():
        # the satellite retires the O(U) local-eval warning on this path
        warnings.filterwarnings("error",
                                message="local eval stages every user")
        out = exp.run("Global-Accuracy")
    assert exp._fused is not None and exp._fused.n_users == 3
    assert exp._eval_widx is not None
    # windows roll deterministically over the population
    assert exp._eval_cohort_users(1) == [3, 4, 5]
    assert exp._eval_cohort_users(3) == [1, 2, 3]  # wraps mod num_users
    hist = out["logger"].history
    assert any(k_.startswith("test/") for k_ in hist)
