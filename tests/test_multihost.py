"""Multi-host bring-up path (VERDICT r1 weak 6).

``initialize_distributed`` is a no-op in ordinary tests; here it runs for
real: a subprocess joins a single-process JAX distributed runtime (the
coordinator lives in-process), builds the (clients, data) mesh over the
virtual CPU devices, and runs a psum collective -- the same bring-up a TPU
pod takes with multiple processes (ref SURVEY §2.4: the reference has no
distributed backend at all; this is the TPU-native equivalent's smoke
test).  The process-0 checkpoint gate itself cannot be meaningfully
exercised with process_count == 1; its condition lives in
entry/common.py and is asserted by inspection there.
"""

import os
import socket
import subprocess
import sys

import pytest

# spawns a JAX distributed subprocess (fast gate excludes this module)
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = r"""
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from heterofl_tpu.parallel.mesh import initialize_distributed, make_mesh
from heterofl_tpu.parallel.round_engine import _shard_map  # version-compat shim

assert initialize_distributed() is True, "env vars present -> must initialise"
assert jax.process_count() == 1
assert jax.process_index() == 0
devs = jax.devices()
assert len(devs) == 8, devs
mesh = make_mesh(4, 2, devices=devs)

def body(x):
    return jax.lax.psum(x, "clients")

fn = jax.jit(_shard_map(body, mesh, P("clients"), P("clients")))
x = jnp.arange(8.0).reshape(4, 2)
out = np.asarray(fn(x))
np.testing.assert_allclose(out, np.tile(x.sum(0), (4, 1)))
print("MULTIHOST_OK")
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_initialize_distributed_single_process_runtime():
    env = dict(os.environ)
    for v in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE",
              "AXON_LOOPBACK_RELAY", "AXON_POOL_SVC_OVERRIDE"):
        env.pop(v, None)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "PYTHONPATH": REPO,
        "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{_free_port()}",
        "JAX_NUM_PROCESSES": "1",
        "JAX_PROCESS_ID": "0",
    })
    res = subprocess.run([sys.executable, "-c", _CHILD], env=env,
                         capture_output=True, text=True, timeout=600)
    assert res.returncode == 0, res.stderr[-2000:]
    assert "MULTIHOST_OK" in res.stdout


def test_initialize_distributed_noop_without_env(monkeypatch):
    from heterofl_tpu.parallel.mesh import initialize_distributed

    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    assert initialize_distributed() is False
