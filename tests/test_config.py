import numpy as np
import pytest

from heterofl_tpu import config as C


def _cfg(control_name, data_name="CIFAR10", model_name="resnet18"):
    cfg = C.default_cfg()
    cfg["control"] = C.parse_control_name(control_name)
    cfg["data_name"] = data_name
    cfg["model_name"] = model_name
    return C.process_control(cfg)


def test_control_roundtrip():
    s = "1_100_0.1_iid_fix_a2-b8_bn_1_1"
    ctl = C.parse_control_name(s)
    assert C.control_name_of(ctl) == s
    assert ctl["model_mode"] == "a2-b8"


def test_control_bad_arity():
    with pytest.raises(ValueError):
        C.parse_control_name("1_100_0.1")


def test_fix_rate_vector_proportional_fill():
    # a2-b8 with 100 users: sum(prop)=10 -> 10 users/unit -> 20 a's, 80 b's.
    cfg = _cfg("1_100_0.1_iid_fix_a2-b8_bn_1_1")
    rates = cfg["model_rate"]
    assert len(rates) == 100
    assert rates[:20] == [1.0] * 20
    assert rates[20:] == [0.5] * 80


def test_fix_rate_vector_remainder_gets_smallest():
    # a1-b1-c1 with 100 users: 33 users/unit -> 99 assigned, 1 leftover -> c.
    cfg = _cfg("1_100_0.1_iid_fix_a1-b1-c1_bn_1_1")
    rates = cfg["model_rate"]
    assert len(rates) == 100
    assert rates[:33] == [1.0] * 33
    assert rates[33:66] == [0.5] * 33
    assert rates[66:99] == [0.25] * 33
    assert rates[99] == 0.25


def test_five_level_fix():
    cfg = _cfg("1_100_0.1_iid_fix_a1-b1-c1-d1-e1_bn_1_1")
    rates = cfg["model_rate"]
    assert len(rates) == 100
    assert rates.count(1.0) == 20 and rates.count(0.0625) == 20


def test_dynamic_mode_stores_distribution():
    cfg = _cfg("1_100_0.1_iid_dynamic_a1-e1_bn_1_1")
    assert cfg["model_rate"] == [1.0, 0.0625]
    assert np.allclose(cfg["proportion"], [0.5, 0.5])


def test_global_rate_is_first_level():
    cfg = _cfg("1_100_0.1_iid_fix_b1-c1_bn_1_1")
    assert cfg["global_model_rate"] == 0.5
    assert cfg["global_model_mode"] == "b"


def test_dataset_tables():
    cfg = _cfg("1_100_0.1_iid_fix_a1_bn_1_1", data_name="MNIST", model_name="conv")
    assert cfg["num_epochs"] == {"global": 200, "local": 5}
    assert cfg["lr"] == 1e-2 and cfg["milestones"] == [100]
    cfg = _cfg("1_100_0.1_non-iid-2_fix_a1_bn_1_1")
    assert cfg["num_epochs"]["global"] == 800 and cfg["milestones"] == [300, 500]
    cfg = _cfg("1_100_0.01_iid_fix_a1_bn_1_1", data_name="WikiText2", model_name="transformer")
    assert cfg["bptt"] == 64 and cfg["mask_rate"] == 0.15
    assert cfg["num_epochs"] == {"global": 200, "local": 1}


def test_flags_parsed():
    cfg = _cfg("1_100_0.1_iid_fix_a1_bn_0_0")
    assert cfg["scale"] is False and cfg["mask"] is False
    cfg = _cfg("1_100_0.1_iid_fix_a1_gn_1_1")
    assert cfg["norm"] == "gn"


def test_model_tag():
    cfg = _cfg("1_100_0.1_iid_fix_a1_bn_1_1")
    assert C.make_model_tag(0, cfg) == "0_CIFAR10_label_resnet18_1_100_0.1_iid_fix_a1_bn_1_1"


def test_ceil_width():
    assert C.scaled_hidden([64, 128, 256, 512], 0.0625) == [4, 8, 16, 32]
    assert C.ceil_width(250, 0.125) == 32
