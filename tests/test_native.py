"""Native loader: build, parse-parity with the Python paths, gather parity."""

import gzip
import os
import struct

import numpy as np
import pytest

from heterofl_tpu import native
from heterofl_tpu.data.datasets import _read_idx, _load_cifar


@pytest.fixture(scope="module")
def lib_ok():
    if not native.available():
        pytest.skip("g++ unavailable; native loader not built")
    return True


def _write_idx(path, arr):
    with open(path, "wb") as f:
        f.write(struct.pack(">BBBB", 0, 0, 0x08, arr.ndim))
        for d in arr.shape:
            f.write(struct.pack(">I", d))
        f.write(arr.tobytes())


def test_idx_native_matches_python(tmp_path, lib_ok):
    rng = np.random.default_rng(0)
    arr = rng.integers(0, 255, (50, 28, 28), dtype=np.uint8)
    p = str(tmp_path / "images-idx3-ubyte")
    _write_idx(p, arr)
    out_native = native.read_idx(p)
    np.testing.assert_array_equal(out_native, arr)
    # gz path uses the python parser; same result
    with open(p, "rb") as f:
        blob = f.read()
    with gzip.open(str(tmp_path / "images-idx3-ubyte.gz"), "wb") as f:
        f.write(blob)
    np.testing.assert_array_equal(_read_idx(str(tmp_path / "images-idx3-ubyte.gz")), arr)


def test_cifar_bin_native(tmp_path, lib_ok):
    rng = np.random.default_rng(1)
    n = 20
    imgs_chw = rng.integers(0, 255, (n, 3, 32, 32), dtype=np.uint8)
    labels = rng.integers(0, 10, n, dtype=np.uint8)
    base = tmp_path / "CIFAR10" / "cifar-10-batches-bin"
    os.makedirs(base)
    for fn, sl in [("data_batch_%d.bin" % i, slice(0, n)) for i in range(1, 6)] + \
                  [("test_batch.bin", slice(0, n))]:
        with open(base / fn, "rb+" if (base / fn).exists() else "wb") as f:
            for i in range(n):
                f.write(bytes([labels[i]]))
                f.write(imgs_chw[i].tobytes())
    imgs, labs = native.read_cifar_bin(str(base / "test_batch.bin"), n, 1)
    np.testing.assert_array_equal(labs, labels.astype(np.int64))
    np.testing.assert_array_equal(imgs, imgs_chw.transpose(0, 2, 3, 1))
    # full dataset path through _load_cifar (binary takes priority)
    ds = _load_cifar(str(tmp_path / "CIFAR10"), "test", "CIFAR10")
    assert ds is not None and ds.data.shape == (n, 32, 32, 3)


def test_permute_gather_parity(lib_ok):
    rng = np.random.default_rng(2)
    src = rng.integers(0, 255, (3000, 40, 40), dtype=np.uint8)  # > 1MB: native path
    idx = rng.permutation(3000)[:2048]
    np.testing.assert_array_equal(native.permute_gather(src, idx), src[idx])
    # small/float arrays fall back to numpy
    srcf = rng.normal(size=(100, 4)).astype(np.float32)
    np.testing.assert_array_equal(native.permute_gather(srcf, idx[:10] % 100),
                                  srcf[idx[:10] % 100])
