"""Pallas fused batch-norm kernel vs the XLA op (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from heterofl_tpu.ops.layers import batch_norm
from heterofl_tpu.ops.pallas_norm import batch_norm_pallas

# pallas interpreter-mode kernels on CPU (fast gate excludes this module)
pytestmark = pytest.mark.slow


@pytest.mark.parametrize("shape", [(10, 8, 8, 64), (6, 32), (10, 4, 4, 48)])
def test_matches_xla_batch_norm(shape):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=shape), jnp.float32)
    C = shape[-1]
    g = jnp.asarray(rng.normal(size=C), jnp.float32)
    b = jnp.asarray(rng.normal(size=C), jnp.float32)
    ref, _ = batch_norm(x, g, b, mode="batch")
    out = batch_norm_pallas(x, g, b, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_matches_with_sample_weight_and_masked_channels():
    """Padded samples excluded from stats; masked channels (g=b=0) output 0."""
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(8, 4, 4, 16)), jnp.float32)
    g = jnp.asarray(rng.normal(size=16), jnp.float32).at[8:].set(0.0)
    b = jnp.asarray(rng.normal(size=16), jnp.float32).at[8:].set(0.0)
    w = jnp.asarray([1, 1, 1, 1, 1, 0, 0, 0], jnp.float32)
    ref, _ = batch_norm(x, g, b, mode="batch", sample_weight=w)
    out = batch_norm_pallas(x, g, b, sample_weight=w, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)
    assert np.all(np.asarray(out)[..., 8:] == 0.0)


def test_multiple_blocks_accumulate():
    """M larger than one block exercises the two-phase scratch accumulation."""
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(4, 16, 16, 8)), jnp.float32)  # M=1024
    g = jnp.ones(8)
    b = jnp.zeros(8)
    ref, _ = batch_norm(x, g, b, mode="batch")
    out = batch_norm_pallas(x, g, b, block_m=256, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_grad_and_vmap():
    """The kernel differentiates and vmaps (the round engine uses it under
    vmap over clients and takes gradients through it)."""
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(3, 5, 4, 4, 8)), jnp.float32)  # [U, N, H, W, C]
    g = jnp.ones(8)
    b = jnp.zeros(8)

    def loss_p(xu):
        return jnp.sum(batch_norm_pallas(xu, g, b, interpret=True) ** 2)

    def loss_x(xu):
        return jnp.sum(batch_norm(xu, g, b, mode="batch")[0] ** 2)

    yp = jax.vmap(loss_p)(x)
    yx = jax.vmap(loss_x)(x)
    np.testing.assert_allclose(np.asarray(yp), np.asarray(yx), rtol=1e-4)
    gp = jax.grad(lambda xx: jnp.sum(jax.vmap(loss_p)(xx)))(x)
    gx = jax.grad(lambda xx: jnp.sum(jax.vmap(loss_x)(xx)))(x)
    np.testing.assert_allclose(np.asarray(gp), np.asarray(gx), rtol=1e-3, atol=1e-4)


def test_model_flag_end_to_end():
    """cfg['pallas_norm']=True: a conv forward matches the XLA-norm model."""
    from test_models import small_cfg, vision_batch

    from heterofl_tpu.models import make_model

    cfg = small_cfg("conv")
    batch = vision_batch(cfg, n=6)
    m1 = make_model(cfg)
    params = m1.init(jax.random.key(0))
    out1, _ = m1.apply(params, batch, train=True)
    cfg2 = dict(cfg)
    cfg2["pallas_norm"] = True
    m2 = make_model(cfg2)
    out2, _ = m2.apply(params, batch, train=True)
    np.testing.assert_allclose(np.asarray(out1["score"]), np.asarray(out2["score"]),
                               rtol=2e-4, atol=2e-4)
