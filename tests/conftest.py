"""Test harness: force an 8-device virtual CPU platform before JAX loads.

This is the TPU-native analogue of a fake distributed backend (SURVEY.md §4):
multi-chip sharding is validated on a virtual CPU mesh via
``--xla_force_host_platform_device_count``.

NOTE: this environment boots a TPU-tunnel PJRT plugin via sitecustomize that
pins ``jax_platforms`` and hangs CPU-only init; we scrub its env hooks and
re-pin the platform to cpu before any backend initialises.
"""

import os

for _v in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE",
           "AXON_LOOPBACK_RELAY", "AXON_POOL_SVC_OVERRIDE"):
    os.environ.pop(_v, None)
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# Persistent XLA compile cache for the test gate: repeat tier-1 runs skip
# the expensive round-program compiles (BENCH_r05 measured 40.3s for the
# flagship program).  The dir is CPU-feature-fingerprinted per host; an
# operator-set JAX_COMPILATION_CACHE_DIR wins (utils/compile_cache.py).
from heterofl_tpu.utils.compile_cache import enable_persistent_cache  # noqa: E402

_CACHE_DIR = enable_persistent_cache()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# The tier-1 gate MUST run with the persistent compile cache active: without
# it every session re-pays the multi-second round-program compiles, and a
# superstep recompile (one program shape per K, ISSUE 2) silently eats the
# budget instead of showing up as a cache miss.  Fail the whole session
# loudly if the wiring ever breaks.
if not jax.config.jax_compilation_cache_dir:
    raise RuntimeError(
        "tier-1 gate requires the persistent XLA compile cache; "
        "utils/compile_cache.enable_persistent_cache() did not take effect")
if not os.path.isdir(jax.config.jax_compilation_cache_dir):
    raise RuntimeError(
        f"persistent compile cache dir {jax.config.jax_compilation_cache_dir!r} "
        f"does not exist")

import warnings  # noqa: E402

# JAX donation warnings are ERRORS in the gate (ISSUE 3 satellite): a
# "donated buffers were not usable" warning means a program claims donation
# it cannot honour -- silent memory doubling on the round path.  pytest.ini
# carries the matching filterwarnings entries for pytest runs; these module
# filters cover bare/in-process harnesses that import this conftest.  The
# staticcheck auditor additionally promotes them to audit failures.
warnings.filterwarnings("error", message="Some donated buffers were not usable")
warnings.filterwarnings("error", message="Donation is not implemented")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
