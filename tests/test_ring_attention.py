"""Ring attention == dense attention, sequence sharded over 8 devices."""

import pytest

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from heterofl_tpu.parallel import make_mesh
from heterofl_tpu.parallel.ring_attention import dense_attention, ring_attention
from heterofl_tpu.parallel.round_engine import _shard_map

# ppermute ring fwd+bwd compiles (fast gate excludes this module)
pytestmark = pytest.mark.slow


def _run(h, S, d, n_dev, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(h, S, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(h, S, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(h, S, d)), jnp.float32)
    temp = jnp.sqrt(float(d))
    mesh = make_mesh(1, n_dev)

    def body(q, k, v):
        return ring_attention(q, k, v, axis_name="data", axis_size=n_dev, temperature=temp)

    fn = jax.jit(_shard_map(body, mesh,
                            in_specs=(P(None, "data"), P(None, "data"), P(None, "data")),
                            out_specs=P(None, "data")))
    out_ring = fn(q, k, v)
    out_dense = dense_attention(q, k, v, temp)
    return np.asarray(out_ring), np.asarray(out_dense)


def test_ring_matches_dense_8dev():
    ring, dense = _run(h=4, S=64, d=16, n_dev=8)
    np.testing.assert_allclose(ring, dense, rtol=2e-5, atol=2e-5)


def test_ring_matches_dense_2dev_long():
    ring, dense = _run(h=2, S=256, d=8, n_dev=2, seed=3)
    np.testing.assert_allclose(ring, dense, rtol=2e-5, atol=2e-5)


def test_ring_single_device_is_dense():
    ring, dense = _run(h=1, S=32, d=4, n_dev=1, seed=5)
    np.testing.assert_allclose(ring, dense, rtol=2e-5, atol=2e-5)


def test_ring_attention_gradients_match_dense():
    """Backward through the ppermute ring equals dense-attention gradients."""
    rng = np.random.default_rng(7)
    h, S, d, n_dev = 2, 64, 8, 8
    q = jnp.asarray(rng.normal(size=(h, S, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(h, S, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(h, S, d)), jnp.float32)
    temp = jnp.sqrt(float(d))
    mesh = make_mesh(1, n_dev)

    def ring_loss(q, k, v):
        body = _shard_map(
            lambda q_, k_, v_: ring_attention(q_, k_, v_, axis_name="data",
                                              axis_size=n_dev, temperature=temp),
            mesh, in_specs=(P(None, "data"),) * 3, out_specs=P(None, "data"))
        return jnp.sum(body(q, k, v) ** 2)

    def dense_loss(q, k, v):
        return jnp.sum(dense_attention(q, k, v, temp) ** 2)

    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for gr, gd, name in zip(g_ring, g_dense, "qkv"):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd), rtol=5e-4,
                                   atol=5e-5, err_msg=name)
