import json
import os
import pickle

import numpy as np

from heterofl_tpu.analysis.make import build_controls, combination_modes, interp_modes, make_script
from heterofl_tpu.analysis.process import aggregate, export_table, load_results, parse_tag
from heterofl_tpu.analysis.summary import make_summary, profile_model

from test_models import small_cfg


def test_grid_modes():
    combos = combination_modes()
    assert "a1-b1" in combos and "a1-b1-c1-d1-e1" in combos
    assert all("-" in c for c in combos)  # singles excluded
    assert len(combos) == 2 ** 5 - 1 - 5
    interp = interp_modes()
    assert "a1-b9" in interp and "a9-b1" in interp and "d5-e5" in interp
    assert len(interp) == 9 * 10  # 9 proportions x C(5,2) pairs


def test_build_controls_and_script(tmp_path, monkeypatch):
    controls = build_controls("resnet18", 1, "iid")
    assert "1_100_0.1_iid_fix_a1_bn_1_1" in controls
    assert any(c.startswith("1_100_0.1_iid_dynamic_a1-b1") for c in controls)
    s = make_script("train", "resnet18", 1, "iid", round_size=4, num_experiments=2)
    assert "python -m heterofl_tpu.entry.train_classifier_fed" in s
    assert "wait" in s and s.count("--init_seed 1") == len(controls)
    ab = build_controls("resnet18", 1, "iid", ablation=True)
    assert any("_gn_" in c for c in ab) and any("_0_1" in c for c in ab)


def test_profile_and_summary(tmp_path):
    cfg = small_cfg("conv")
    cfg["output_dir"] = str(tmp_path)
    prof = profile_model(cfg, 1.0, batch_size=2)
    # conv [8,16]: block0 3*3*1*8(+8) + block1 3*3*8*16(+16) + bn params + linear 16*10+10
    assert prof["num_params"] > 1000
    half = profile_model(cfg, 0.5, batch_size=2)
    assert half["num_params"] < prof["num_params"]
    out = make_summary(cfg, rates=[1.0, 0.5], output_dir=str(tmp_path))
    assert "| a | 1 |" in out["report"]
    assert os.path.exists(tmp_path / "summary.md")
    assert os.path.exists(tmp_path / "result" / "MNIST_conv_a.pkl")
    assert "Per-module profile" in (tmp_path / "summary.md").read_text()


def test_module_table_conv_exact():
    """Per-leaf-module profile (ref summary.py:68-152): conv MACs follow the
    reference's hand formulas; params across rows account for every model
    parameter."""
    import jax

    from heterofl_tpu.analysis.summary import module_table
    from heterofl_tpu.models import make_model

    cfg = small_cfg("conv")  # hidden [8,16], MNIST 28x28x1
    bs = 2
    rows = module_table(cfg, 1.0, batch_size=bs)
    by_name = {r[0]: r for r in rows}
    # block0.conv: 3*3*1*8 MACs per output position + bias, 28x28 out
    assert by_name["block0.conv"][4] == 3 * 3 * 1 * 8 * bs * 28 * 28 + 8 * bs * 28 * 28
    # block1 after one pool: 14x14
    assert by_name["block1.conv"][4] == 3 * 3 * 8 * 16 * bs * 14 * 14 + 16 * bs * 14 * 14
    assert by_name["linear"][4] == bs * 16 * 10
    params = make_model(cfg).init(jax.random.key(0))
    total = sum(int(v.size) for v in params.values())
    assert sum(r[3] for r in rows) == total


def test_module_table_params_complete_all_families():
    """Row param counts sum to the model's param count for resnet and
    transformer too (catches drift between the table and the real models)."""
    import jax

    from heterofl_tpu.analysis.summary import module_table
    from heterofl_tpu.models import make_model

    for name in ("resnet18", "resnet50", "transformer"):
        cfg = small_cfg(name, data_name="WikiText2" if name == "transformer" else "MNIST")
        rows = module_table(cfg, 1.0, batch_size=2)
        params = make_model(cfg).init(jax.random.key(0))
        total = sum(int(v.size) for v in params.values())
        assert sum(r[3] for r in rows) == total, (name, sum(r[3] for r in rows), total)
        assert all(r[4] >= 0 for r in rows)


def test_parse_tag_hardened():
    ctl = "1_8_0.5_iid_fix_a1_bn_1_1"
    # canonical: with + without subset
    m = parse_tag(f"0_MNIST_label_conv_{ctl}")
    assert m["data_name"] == "MNIST" and m["subset"] == "label" and m["model_name"] == "conv"
    m = parse_tag(f"0_WikiText2_transformer_{ctl}")
    assert m["data_name"] == "WikiText2" and m["subset"] == "" and m["model_name"] == "transformer"
    # underscored data name must not shift fields: model anchors by registry
    m = parse_tag(f"3_My_Custom_Data_conv_{ctl}")
    assert m is not None and m["model_name"] == "conv" and m["seed"] == "3"
    assert m["data_name"] == "My_Custom_Data" and m["subset"] == "" and m["fed"] == "1"
    # junk is refused, not mislabelled
    assert parse_tag("not_a_tag") is None
    assert parse_tag(f"x_MNIST_label_conv_{ctl}") is None  # non-int seed
    assert parse_tag(f"0_MNIST_label_notamodel_{ctl}") is None  # unknown model
    assert parse_tag("0_MNIST_label_conv_1_8_0.5_iid_fix_a1_zz_1_1") is None  # bad norm


def test_process_aggregation(tmp_path):
    os.makedirs(tmp_path / "result")
    for seed in (0, 1):
        tag = f"{seed}_MNIST_label_conv_1_8_0.5_iid_fix_a1_bn_1_1"
        bundle = {"logger_history": {"test/Global-Accuracy": [50.0 + seed * 10],
                                     "test/Global-Loss": [1.0]},
                  "train_history": {"test/Global-Accuracy": [10.0, 50.0 + seed * 10]}}
        with open(tmp_path / "result" / f"{tag}.pkl", "wb") as f:
            pickle.dump(bundle, f)
    rows = load_results(str(tmp_path))
    assert len(rows) == 2
    meta = parse_tag(rows[0]["tag"])
    assert meta["model_mode"] == "a1" and meta["data_name"] == "MNIST"
    agg = aggregate(rows)
    assert len(agg) == 1
    g = next(iter(agg.values()))
    assert g["n_seeds"] == 2
    assert g["mean"]["Global-Accuracy"] == 55.0 and abs(g["std"]["Global-Accuracy"] - 5.0) < 1e-9
    csv_path = export_table(agg, str(tmp_path))
    assert os.path.exists(csv_path)
    content = open(csv_path).read()
    assert "Global-Accuracy_mean" in content and "55" in content


def test_process_produces_figures(tmp_path):
    """The figure path must actually emit PNGs, end to end through
    ``process.main`` (guards the silent-matplotlib-fallback no-op,
    VERDICT r3 weak 7): interpolation figure across two modes + a learning
    curve per experiment."""
    import pytest

    pytest.importorskip("matplotlib")
    from heterofl_tpu.analysis import process

    os.makedirs(tmp_path / "result")
    for mode, acc in (("a1", 60.0), ("a5-b5", 50.0), ("b1", 40.0)):
        tag = f"0_MNIST_label_conv_1_8_0.5_iid_fix_{mode}_bn_1_1"
        bundle = {"logger_history": {"test/Global-Accuracy": [acc]},
                  "train_history": {"test/Global-Accuracy": [10.0, acc]}}
        with open(tmp_path / "result" / f"{tag}.pkl", "wb") as f:
            pickle.dump(bundle, f)
    process.main(["--output_dir", str(tmp_path)])
    interp = tmp_path / "fig" / "interp_Global-Accuracy.png"
    assert interp.exists() and interp.stat().st_size > 0, \
        "interpolation figure was not produced"
    lcs = list((tmp_path / "fig").glob("lc_*.png"))
    assert len(lcs) == 3, f"expected 3 learning curves, got {lcs}"


def test_norm_stats_fallback(tmp_path):
    """Datasets absent from DATASET_STATS get computed (and cached) channel
    stats wired into the engines via cfg['norm_stats']."""
    from heterofl_tpu.data.stats import compute_stats, dataset_stats
    from heterofl_tpu.entry.common import _maybe_compute_norm_stats
    from heterofl_tpu.data import fetch_dataset

    rng = np.random.default_rng(0)
    data = rng.integers(0, 255, (200, 8, 8, 3), dtype=np.uint8)
    mean, std = compute_stats(data)
    ref = (data.astype(np.float64) / 255.0).reshape(-1, 3)
    np.testing.assert_allclose(mean, ref.mean(0), rtol=1e-5)
    np.testing.assert_allclose(std, ref.std(0, ddof=1), rtol=1e-3)
    m2, s2 = dataset_stats("FakeSet", data, str(tmp_path))
    assert os.path.exists(tmp_path / "stats" / "FakeSet.npz")
    m3, _ = dataset_stats("FakeSet", np.zeros_like(data), str(tmp_path))  # cache hit
    np.testing.assert_allclose(m2, m3)

    class FakeDS:
        pass

    ds = FakeDS()
    ds.data = data
    cfg = {"data_name": "FakeSet", "data_dir": str(tmp_path)}
    _maybe_compute_norm_stats(cfg, {"train": ds})
    assert "norm_stats" in cfg and len(cfg["norm_stats"][0]) == 3
    # known datasets are untouched
    cfg2 = {"data_name": "MNIST", "data_dir": str(tmp_path)}
    _maybe_compute_norm_stats(cfg2, {"train": ds})
    assert "norm_stats" not in cfg2


def test_cifar_bin_python_fallback(tmp_path, monkeypatch):
    """CIFAR binary parses identically without the native library."""
    from heterofl_tpu import native
    from heterofl_tpu.data.datasets import _load_cifar_bin

    rng = np.random.default_rng(4)
    n = 10
    imgs_chw = rng.integers(0, 255, (n, 3, 32, 32), dtype=np.uint8)
    labels = rng.integers(0, 10, n, dtype=np.uint8)
    base = tmp_path / "cifar-10-batches-bin"
    os.makedirs(base)
    for fn in [f"data_batch_{i}.bin" for i in range(1, 6)] + ["test_batch.bin"]:
        with open(base / fn, "wb") as f:
            for i in range(n):
                f.write(bytes([labels[i]]))
                f.write(imgs_chw[i].tobytes())
    monkeypatch.setattr(native, "read_cifar_bin", lambda *a, **k: None)
    ds = _load_cifar_bin(str(tmp_path), "test", "CIFAR10")
    assert ds is not None
    np.testing.assert_array_equal(ds.data, imgs_chw.transpose(0, 2, 3, 1))
    np.testing.assert_array_equal(ds.target, labels.astype(np.int64))
