"""Wire codecs (ISSUE 8): in-program compressed aggregation with error
feedback inside the fused round (heterofl_tpu/compress/ + ops/quant.py).

Contracts under test:

* **dense default**: ``wire_codec='dense'`` engines are bit-identical to
  engines built without the key, masked x {replicated, sharded} and
  grouped x {span, slices}, K in {1, 8}.  (The dense codec path IS the
  pre-PR program -- no new arguments, no residual -- so the whole
  pre-existing equivalence suite keeps guarding the pre-PR trajectories;
  these tests pin the config plumbing on top.)
* **lane packing**: pack/unpack roundtrip, and word-sum == per-lane sum
  under the no-carry capacity the codecs size for -- the "int8 on the
  wire, int32 in the accumulator" contract that makes ONE integer psum an
  exact per-lane accumulation.
* **pallas fast path**: the fused quantise+pack kernel (interpret mode
  off-TPU) is bit-identical to the XLA path.
* **superstep == sequential**: a lossy codec's K-round superstep equals K
  sequential k=1 dispatches with the residual carried across them, bit
  for bit, both engines -- the EF carry in the scan state is exactly the
  sequential one.
* **tolerance contracts**: each lossy codec's K-round masked trajectory
  stays within its pinned relative distance of the dense trajectory (and
  actually diverges -- a silently-dense "lossy" codec fails), with the
  final-loss delta bounded.
* **error feedback**: EF-on tracks the dense trajectory strictly better
  than EF-off on the MNIST pair (int8; signsgd pinned on final loss), and
  the topk residual provably carries the unsent blocks EF-off drops.
* **checkpoint round-trip**: save (params, residual) at a superstep
  boundary, restore into a FRESH engine, continue -- bit-identical to the
  uninterrupted run, for each lossy codec.
* **config lint** (ISSUE 8 satellite): unknown ``wire_codec`` /
  ``error_feedback`` / ``stream_prefetch_depth`` values fail loudly at
  config validation (the PR 6 convention).
* **staticcheck pricing**: the traced compressed psum payload equals
  ``compress.codec_payload_bytes`` (the one formula behind
  ``fed.core.level_codec_byte_table`` and the audit's equality budget),
  and the analytic flagship frontier holds int8 at <= 25% of dense.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from heterofl_tpu import config as C
from heterofl_tpu.compress import (CODEC_NAMES, LOSSY_CODECS, TOPK_BLOCKS,
                                   codec_payload_bytes, lane_words,
                                   make_codec, resid_slots,
                                   resolve_codec_cfg)
from heterofl_tpu.models import make_model
from heterofl_tpu.ops.fused_update import FlatSpec
from heterofl_tpu.ops.quant import (pack_lanes, quantize_pack,
                                    stochastic_round, unpack_lanes)
from heterofl_tpu.parallel import GroupedRoundEngine, RoundEngine, make_mesh

from test_round import _vision_setup
from test_superstep import _grouped_schedules

HOST = jax.random.key(0)


def _cfg(codec=None, ef=True, **over):
    cfg, ds, data = _vision_setup()
    if codec is not None:
        cfg = dict(cfg, wire_codec=codec, error_feedback=ef)
    return dict(cfg, **over), data


def _host(tree):
    return {k: np.asarray(v) for k, v in tree.items()}


def _assert_trees_equal(a, b, msg=""):
    for k in sorted(a):
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]),
                                      err_msg=f"{msg}{k}")


# ---------------------------------------------------------------------------
# the analytic half: byte formula, registry, config validation
# ---------------------------------------------------------------------------

def test_codec_payload_bytes_formula():
    n, leaves = 1000, 7
    assert lane_words(1000, 8) == 250 and lane_words(1001, 8) == 251
    assert codec_payload_bytes("dense", n) == 8 * n
    assert codec_payload_bytes("int8", n) == 4 * 250 + 4 * 250
    assert codec_payload_bytes("signsgd", n, leaves) == \
        4 * 125 + 4 * 250 + 4 * leaves
    assert codec_payload_bytes("topk", n) == 8 * (-(-n // TOPK_BLOCKS))
    with pytest.raises(ValueError, match="wire_codec"):
        codec_payload_bytes("fp7", n)
    # the compression claims: int8/topk at 25%, signsgd below
    assert codec_payload_bytes("int8", n) * 4 == codec_payload_bytes("dense", n)
    assert codec_payload_bytes("signsgd", n, leaves) \
        < codec_payload_bytes("int8", n)


def test_resolve_codec_cfg_defaults_and_errors():
    assert resolve_codec_cfg({}) == ("dense", True)
    assert resolve_codec_cfg({"wire_codec": None}) == ("dense", True)
    for name in CODEC_NAMES:
        assert resolve_codec_cfg({"wire_codec": name})[0] == name
    with pytest.raises(ValueError, match="wire_codec"):
        resolve_codec_cfg({"wire_codec": "int4"})
    with pytest.raises(ValueError, match="error_feedback"):
        resolve_codec_cfg({"error_feedback": "yes"})
    assert resid_slots("dense") == 0
    assert resid_slots("int8") == resid_slots("signsgd") == 1
    assert resid_slots("topk") == 2  # value AND count residuals


def test_config_validation_rejects_stale_codec_keys():
    """ISSUE 8 satellite: a typo'd wire_codec / error_feedback /
    stream_prefetch_depth fails at process_control, never as a silent
    dense fallback mid-run (the PR 6 loud-ValueError convention)."""
    def base():
        cfg = C.default_cfg()
        cfg["control"] = C.parse_control_name(
            "1_8_0.5_iid_fix_a1-b1-c1-d1-e1_bn_1_1")
        cfg["data_name"] = "MNIST"
        return cfg

    C.process_control(base())  # defaults are valid
    for bad in ({"wire_codec": "int9"}, {"wire_codec": "Dense"},
                {"error_feedback": 1}, {"error_feedback": "off"},
                {"stream_prefetch_depth": 0},
                {"stream_prefetch_depth": "two"},
                {"stream_prefetch_depth": True}):
        cfg = base()
        cfg.update(bad)
        with pytest.raises(ValueError, match="Not valid"):
            C.process_control(cfg)


def test_codec_participant_capacity_loud():
    """Lane capacity is checked at construction: more participants than the
    lanes can accumulate without carries must fail loudly, not corrupt."""
    spec = FlatSpec({"w": (64,)})
    make_codec("signsgd", spec, 15)
    with pytest.raises(ValueError, match="participants"):
        make_codec("signsgd", spec, 16)
    make_codec("int8", spec, 64)
    with pytest.raises(ValueError, match="participants"):
        make_codec("int8", spec, 65)
    with pytest.raises(ValueError, match="flat elements"):
        make_codec("topk", FlatSpec({"w": (2,)}), 4)


# ---------------------------------------------------------------------------
# lane packing: the int32-accumulator contract
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("lane_bits,n", [(8, 77), (8, 256), (4, 33)])
def test_pack_unpack_roundtrip(lane_bits, n):
    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.integers(0, 1 << lane_bits, n), jnp.int32)
    w = pack_lanes(q, lane_bits)
    assert w.dtype == jnp.int32 and w.shape == (lane_words(n, lane_bits),)
    np.testing.assert_array_equal(np.asarray(unpack_lanes(w, lane_bits, n)),
                                  np.asarray(q))


def test_packed_word_sum_is_per_lane_sum():
    """The psum-accumulation contract: adding packed words == adding lanes,
    as long as each cross-device lane sum fits its lane (the codecs size
    participants/levels to guarantee that)."""
    rng = np.random.default_rng(7)
    n, p = 101, 8
    vals = rng.integers(0, 32, (p, n))  # 5-bit values, 8-bit lanes: no carry
    words = sum(pack_lanes(jnp.asarray(v, jnp.int32), 8) for v in vals)
    np.testing.assert_array_equal(np.asarray(unpack_lanes(words, 8, n)),
                                  vals.sum(0))


def test_stochastic_round_unbiased_and_exact_on_grid():
    x = jnp.full((20000,), 0.3)
    m = float(np.asarray(stochastic_round(x, jax.random.key(1))).mean())
    assert abs(m - 0.3) < 0.02
    g = jnp.arange(-5.0, 6.0)  # grid points round to themselves, any key
    np.testing.assert_array_equal(
        np.asarray(stochastic_round(g, jax.random.key(2))), np.asarray(g))


def test_quantize_pack_pallas_matches_xla():
    """The Pallas fused quantise+pack (interpret mode on CPU) must be
    bit-identical to the XLA path -- same noise draw, same clip, same
    word layout -- so the TPU fast path cannot drift the wire format."""
    rng = np.random.default_rng(11)
    n = 1000  # not a multiple of the 128-lane rows: exercises padding
    x = jnp.asarray(rng.normal(0, 2, n), jnp.float32)
    s = jnp.asarray(rng.uniform(0.5, 2, n), jnp.float32)
    key = jax.random.key(5)
    w_x, q_x = quantize_pack(x, s, key, qmax=15, bias=16, mode="xla")
    w_p, q_p = quantize_pack(x, s, key, qmax=15, bias=16, mode="pallas",
                             interpret=True)
    np.testing.assert_array_equal(np.asarray(q_x), np.asarray(q_p))
    np.testing.assert_array_equal(np.asarray(w_x), np.asarray(w_p))
    with pytest.raises(ValueError, match="quantize_pack mode"):
        quantize_pack(x, s, key, 15, 16, mode="fast")


# ---------------------------------------------------------------------------
# dense default: bit-identical to engines built without the key
# ---------------------------------------------------------------------------

def test_dense_codec_bit_identical_masked():
    """wire_codec='dense' (explicit) == no key at all, masked replicated,
    K in {1, 8}: the dense path adds no arguments and no residual."""
    cfg, data = _cfg()
    model = make_model(cfg)
    mesh = make_mesh(4, 1)
    outs = []
    for c in (cfg, dict(cfg, wire_codec="dense")):
        eng = RoundEngine(model, c, mesh)
        p = model.init(jax.random.key(0))
        p, _ = eng.train_round(p, jax.random.key(1), 0.05,
                               np.array([0, 2, 4, 6]), data)  # K=1
        p, pend = eng.train_superstep(p, HOST, 1, 8, data=data, num_active=4)
        pend.fetch()
        assert eng.wire_resid_host() is None
        outs.append(_host(p))
    _assert_trees_equal(*outs, msg="masked dense ")


@pytest.mark.parametrize("placement", ["span", "slices"])
def test_dense_codec_bit_identical_grouped(placement):
    cfg, data = _cfg(level_placement=placement)
    model = make_model(cfg)
    k, epoch0, A = 8, 1, 4
    users, rates = _grouped_schedules(cfg, epoch0, k, A)
    outs = []
    for c in (cfg, dict(cfg, wire_codec="dense")):
        g = GroupedRoundEngine(c, make_mesh(8, 1))
        p = model.init(jax.random.key(0))
        p, _ = g.train_round(p, users[0], rates[0], data, 0.05,
                             jax.random.key(1))  # K=1 host-per-level path
        p, pend = g.train_superstep(p, HOST, epoch0, k, users, rates, data)
        pend.fetch()
        assert g.wire_resid_host() is None
        outs.append(_host(p))
    _assert_trees_equal(*outs, msg=f"grouped/{placement} dense ")


# ---------------------------------------------------------------------------
# lossy codecs: superstep == sequential with the residual carried
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("codec", LOSSY_CODECS)
def test_codec_superstep_matches_sequential_masked(codec):
    """A K-round compressed superstep == K sequential k=1 dispatches with
    the EF residual carried across them, bit for bit (params, metrics AND
    the residual): the scan-carry residual is exactly the sequential one."""
    cfg, data = _cfg(codec)
    model = make_model(cfg)
    mesh = make_mesh(4, 1)
    k, epoch0, A = 3, 1, 4

    eng1 = RoundEngine(model, cfg, mesh)
    p1 = model.init(jax.random.key(0))
    seq_ms = []
    for r in range(k):
        p1, pend = eng1.train_superstep(p1, HOST, epoch0 + r, 1, data=data,
                                        num_active=A)
        seq_ms.extend(pend.fetch())

    eng2 = RoundEngine(model, cfg, mesh)
    p2 = model.init(jax.random.key(0))
    p2, pend = eng2.train_superstep(p2, HOST, epoch0, k, data=data,
                                    num_active=A)
    ss_ms = pend.fetch()

    _assert_trees_equal(_host(p1), _host(p2), msg=f"{codec} params ")
    np.testing.assert_array_equal(eng1.wire_resid_host(),
                                  eng2.wire_resid_host(),
                                  err_msg=f"{codec} residual")
    for r in range(k):
        for name in ("loss_sum", "score_sum", "n", "rate"):
            np.testing.assert_array_equal(
                np.asarray(seq_ms[r][name]), np.asarray(ss_ms[r][name]),
                err_msg=f"{codec} round {r} {name}")


@pytest.mark.parametrize("placement", ["span", "slices"])
def test_codec_superstep_matches_sequential_grouped(placement):
    """Full occupancy (A = all users) keeps the slot layout -- and with it
    the static ``cmax`` sizing the quantisation grid -- identical between
    the k=1 and k=2 programs; the bitwise contract is per-layout (a
    round-varying slices schedule may bucket different slot counts, which
    legitimately re-sizes the shared grid)."""
    cfg, data = _cfg("int8", level_placement=placement)
    model = make_model(cfg)
    k, epoch0 = 2, 1
    A = cfg["num_users"]
    users, rates = _grouped_schedules(cfg, epoch0, k, A)

    g1 = GroupedRoundEngine(cfg, make_mesh(8, 1))
    p1 = model.init(jax.random.key(0))
    for r in range(k):
        p1, pend = g1.train_superstep(p1, HOST, epoch0 + r, 1,
                                      users[r:r + 1], rates[r:r + 1], data)
        pend.fetch()

    g2 = GroupedRoundEngine(cfg, make_mesh(8, 1))
    p2 = model.init(jax.random.key(0))
    p2, pend = g2.train_superstep(p2, HOST, epoch0, k, users, rates, data)
    pend.fetch()
    _assert_trees_equal(_host(p1), _host(p2), msg=f"{placement} int8 ")
    np.testing.assert_array_equal(g1.wire_resid_host(), g2.wire_resid_host())


def test_grouped_train_round_refuses_lossy_codec():
    """The K=1 host-orchestrated grouped path reduces per level -- there is
    no single global psum to compress; it must refuse, loudly."""
    cfg, data = _cfg("int8")
    g = GroupedRoundEngine(cfg, make_mesh(8, 1))
    p = make_model(cfg).init(jax.random.key(0))
    with pytest.raises(ValueError, match="fused grouped superstep"):
        g.train_round(p, np.array([0, 1]), np.array([1.0, 0.5]), data, 0.05,
                      jax.random.key(1))


# ---------------------------------------------------------------------------
# tolerance contracts + error feedback on the MNIST pair
# ---------------------------------------------------------------------------

_RUNS = {}


def _codec_run(codec=None, ef=True, k=6):
    """Memoised K-round masked superstep at a fixed seed: the shared
    measurement behind the tolerance and error-feedback contracts."""
    key_ = (codec, ef)
    if key_ not in _RUNS:
        cfg, data = _cfg(codec, ef)
        model = make_model(cfg)
        eng = RoundEngine(model, cfg, make_mesh(4, 1))
        p = model.init(jax.random.key(0))
        p, pend = eng.train_superstep(p, HOST, 1, k, data=data, num_active=4)
        ms = pend.fetch()
        loss = float(np.asarray(ms[-1]["loss_sum"]).sum()
                     / np.asarray(ms[-1]["n"]).sum())
        _RUNS[key_] = (_host(p), loss)
    return _RUNS[key_]


def _rel_dist(pa, pb):
    num = np.sqrt(sum(((pa[k] - pb[k]) ** 2).sum() for k in pa))
    den = np.sqrt(sum((pb[k] ** 2).sum() for k in pb))
    return float(num / den)


#: the per-codec tolerance contracts (ISSUE 8): max relative L2 distance of
#: the 6-round EF-on masked trajectory from the dense one, and the max
#: final-loss penalty.  Pinned at ~2x the measured values on the MNIST pair
#: (int8 0.083 / signsgd 1.29 / topk 0.31; losses within +0.30) -- a codec
#: drifting past these has broken its quantisation, not just moved bits.
CODEC_TOL = {"int8": (0.25, 0.25), "signsgd": (2.0, 0.6),
             "topk": (0.6, 0.45)}


@pytest.mark.parametrize("codec", LOSSY_CODECS)
def test_codec_tolerance_contract(codec):
    pd, loss_d = _codec_run()
    pc, loss_c = _codec_run(codec)
    d = _rel_dist(pc, pd)
    d_tol, l_tol = CODEC_TOL[codec]
    assert 1e-4 < d < d_tol, \
        f"{codec}: rel trajectory distance {d:.4f} outside (1e-4, {d_tol})"
    assert np.isfinite(loss_c) and loss_c - loss_d < l_tol, \
        f"{codec}: loss {loss_c:.4f} vs dense {loss_d:.4f} (tol +{l_tol})"


def test_error_feedback_on_beats_off_int8():
    """The EF convergence contract on the MNIST pair: re-injecting the
    compression error keeps the int8 trajectory strictly closer to dense
    AND at a strictly better final loss than dropping it."""
    pd, loss_d = _codec_run()
    p_on, loss_on = _codec_run("int8", True)
    p_off, loss_off = _codec_run("int8", False)
    assert _rel_dist(p_on, pd) < _rel_dist(p_off, pd)
    assert loss_on < loss_off


def test_error_feedback_on_beats_off_signsgd_loss():
    _, loss_on = _codec_run("signsgd", True)
    _, loss_off = _codec_run("signsgd", False)
    assert loss_on < loss_off


def test_topk_error_feedback_carries_unsent_blocks():
    """The topk EF residual provably holds what EF-off drops: after one
    encode, every coordinate outside the shipped block sits in the value
    AND count residuals (so a later ship carries a consistent mean), and
    EF-off leaves the residual zero."""
    spec = FlatSpec({"w": (40,)})
    rng = np.random.default_rng(0)
    sums = jnp.asarray(rng.normal(size=40), jnp.float32)
    cnts = jnp.asarray(rng.integers(0, 3, 40), jnp.float32)
    key = jax.random.key(9)
    for ef in (True, False):
        codec = make_codec("topk", spec, 1, error_feedback=ef, axis=None)
        resid0 = jnp.zeros((2, 40), jnp.float32)
        payload, resid = codec.encode(sums, cnts, resid0, {}, key, 1)
        off = int(np.asarray(codec._offset(key)))
        blk = slice(off, off + codec.block_len)
        np.testing.assert_array_equal(np.asarray(payload["v"]),
                                      np.asarray(sums[blk]))
        if ef:
            expect_v = np.asarray(sums).copy()
            expect_c = np.asarray(cnts).copy()
            expect_v[blk] = 0.0
            expect_c[blk] = 0.0
            np.testing.assert_array_equal(np.asarray(resid[0]), expect_v)
            np.testing.assert_array_equal(np.asarray(resid[1]), expect_c)
        else:
            assert not np.asarray(resid).any()
        # decode of the 1-participant "psum" reconstructs exactly the block
        s_hat, c_hat = codec.decode(payload, {}, key, 1)
        np.testing.assert_array_equal(np.asarray(s_hat[blk]),
                                      np.asarray(sums[blk]))
        assert not np.asarray(s_hat).sum() - np.asarray(s_hat[blk]).sum()


# ---------------------------------------------------------------------------
# checkpoint round-trip of the error-feedback carry
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("codec", LOSSY_CODECS)
def test_resid_checkpoint_roundtrip_masked(codec):
    """Save (params, residual) at a superstep boundary, restore into a
    FRESH engine, continue: bit-identical to the uninterrupted run (the
    satellite contract -- without the carry the first resumed round
    re-loses error a checkpointed run already accounted for)."""
    cfg, data = _cfg(codec)
    model = make_model(cfg)
    mesh = make_mesh(4, 1)
    k, A = 2, 4

    eng_a = RoundEngine(model, cfg, mesh)
    pa = model.init(jax.random.key(0))
    pa, pend = eng_a.train_superstep(pa, HOST, 1, k, data=data, num_active=A)
    pend.fetch()
    blob_params = _host(pa)                 # the checkpoint boundary
    blob_resid = eng_a.wire_resid_host()
    pa, pend = eng_a.train_superstep(pa, HOST, 1 + k, k, data=data,
                                     num_active=A)
    pend.fetch()

    eng_b = RoundEngine(model, cfg, mesh)   # fresh process stand-in
    eng_b.set_wire_resid(blob_resid)
    pb = {n: jnp.asarray(v) for n, v in blob_params.items()}
    pb, pend = eng_b.train_superstep(pb, HOST, 1 + k, k, data=data,
                                     num_active=A)
    pend.fetch()
    _assert_trees_equal(_host(pa), _host(pb), msg=f"{codec} resumed ")
    np.testing.assert_array_equal(eng_a.wire_resid_host(),
                                  eng_b.wire_resid_host())


def test_resid_checkpoint_roundtrip_grouped():
    cfg, data = _cfg("int8")
    model = make_model(cfg)
    k, A = 2, 4
    users, rates = _grouped_schedules(cfg, 1, 2 * k, A)

    g_a = GroupedRoundEngine(cfg, make_mesh(8, 1))
    pa = model.init(jax.random.key(0))
    pa, pend = g_a.train_superstep(pa, HOST, 1, k, users[:k], rates[:k], data)
    pend.fetch()
    blob_params, blob_resid = _host(pa), g_a.wire_resid_host()
    pa, pend = g_a.train_superstep(pa, HOST, 1 + k, k, users[k:], rates[k:],
                                   data)
    pend.fetch()

    g_b = GroupedRoundEngine(cfg, make_mesh(8, 1))
    g_b.set_wire_resid(blob_resid)
    pb = {n: jnp.asarray(v) for n, v in blob_params.items()}
    pb, pend = g_b.train_superstep(pb, HOST, 1 + k, k, users[k:], rates[k:],
                                   data)
    pend.fetch()
    _assert_trees_equal(_host(pa), _host(pb), msg="grouped int8 resumed ")
    np.testing.assert_array_equal(g_a.wire_resid_host(), g_b.wire_resid_host())


# ---------------------------------------------------------------------------
# staticcheck pricing: traced payload == the one byte formula
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("codec", LOSSY_CODECS)
def test_traced_codec_payload_matches_formula(codec):
    """The compressed psum's traced operand avals ARE the wire format:
    pricing the traced superstep with staticcheck's wire walk must equal
    ``codec_payload_bytes`` exactly -- the equality that lets the audit
    budget compressed rounds the same way it budgets dense ones."""
    from heterofl_tpu.staticcheck.wire import program_wire
    from heterofl_tpu.utils.optim import make_traced_lr_fn

    cfg, data = _cfg(codec)
    model = make_model(cfg)
    mesh = make_mesh(4, 1)
    eng = RoundEngine(model, cfg, mesh)
    eng._lr_fn = make_traced_lr_fn(cfg)
    params = model.init(jax.random.key(0))
    spec = FlatSpec.of(params)
    fix = (eng.fix_rates,) if eng.fix_rates is not None else ()
    k = 2
    prog = eng._build_superstep(k, 1, True, num_active=4)
    resid = jax.ShapeDtypeStruct((4, resid_slots(codec), spec.total),
                                 np.float32)
    jaxpr = prog.trace(params, resid, HOST, np.int32(1),
                       *(tuple(data) + fix)).jaxpr
    wire = program_wire(jaxpr, mesh)
    assert wire["train_bytes_per_round"] == \
        codec_payload_bytes(codec, spec.total, len(params))
    assert wire["other_bytes"] == 0 and wire["eval_bytes_total"] == 0


def test_flagship_codec_frontier_analytic():
    """The ISSUE 8 acceptance line, analytically: flagship int8 bytes are
    <= 25% of the dense 89.4 MB baseline (and the frontier section the
    audit embeds in STATICCHECK.json agrees)."""
    from heterofl_tpu.staticcheck.audit import codec_frontier_check
    from heterofl_tpu.staticcheck.report import AuditReport

    rep = AuditReport()
    sec = codec_frontier_check(rep)
    assert rep.ok and sec["ok"]
    assert sec["flagship_dense_bytes"] == 89377360  # MEASUREMENTS Round 11
    int8 = sec["codecs"]["int8"]
    assert int8["reduction_x"] >= 4.0
    assert 4 * int8["payload_bytes_per_round"] <= sec["flagship_dense_bytes"] + 32
    assert sec["codecs"]["signsgd"]["payload_bytes_per_round"] \
        < int8["payload_bytes_per_round"]
