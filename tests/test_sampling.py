"""O(active) population sampler (ISSUE 11, heterofl_tpu/fed/sampling.py).

The contracts under test:

* the PRP index map is an EXACT bijection on ``[0, num_users)`` for
  awkward sizes (1, 2, 7, primes, powers of two and their neighbours, 1e6)
  and is key-dependent;
* ``round_users`` draws the identical cohort in-jit and on the host for
  BOTH samplers (the one-stream contract), ``sampler='perm'`` reproduces
  the pre-ISSUE-11 draw bit for bit, and an all-ones availability row
  selects exactly the uniform cohort under both samplers;
* the PRP availability walk returns available ids in PRP order with
  ``-1`` spill, deterministically;
* cohort frequencies under the PRP are uniform (chi-square smoke);
* the 1e6-user draw is O(active): >= 10x faster than the permutation
  path, no ``[num_users]``-sized value anywhere in its jaxpr, and O(A)
  python-side allocation (tracemalloc);
* loud ``ValueError``s for num_active/epoch0/k/sampler misuse (ISSUE 11
  satellite);
* schedule commitment: ``ScheduleCommitment`` ledger semantics, and a
  streaming driver run under ``sample_horizon=1`` is bit-identical to the
  stateless default WITH the prefetch overlap intact.
"""

import time
import tracemalloc
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from heterofl_tpu import config as C
from heterofl_tpu.fed.core import (USER_SAMPLE_SALT, round_users,
                                   superstep_user_schedule)
from heterofl_tpu.fed.sampling import (AVAIL_OVERDRAW, ScheduleCommitment,
                                       SamplerSpec, prp_map, prp_round_users,
                                       resolve_sampler_cfg)
from heterofl_tpu.models import make_model
from heterofl_tpu.parallel import RoundEngine, make_mesh

from test_round import _vision_setup

HOST_KEY = jax.random.key(0)


# ---------------------------------------------------------------------------
# PRP bijection properties
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("users", [1, 2, 3, 7, 100, 127, 128, 129, 1023,
                                   1024, 1025, 4096, 4097])
def test_prp_bijection_awkward_sizes(users):
    """The keyed index map permutes [0, U) exactly -- including U=1, tiny
    U, primes and powers of two +- 1 (cycle-walking handles every
    non-power-of-4 domain)."""
    img = np.asarray(prp_map(HOST_KEY, np.arange(users), users))
    assert sorted(img.tolist()) == list(range(users))


def test_prp_bijection_1e6():
    """The acceptance scale: an exact bijection on [0, 1e6) (vectorised
    full-image check)."""
    users = 1_000_000
    img = np.sort(np.asarray(prp_map(HOST_KEY, np.arange(users), users)))
    np.testing.assert_array_equal(img, np.arange(users))


def test_prp_key_dependence():
    """Different keys give different permutations (and different rounds'
    fold_in keys give different cohorts)."""
    users = 100
    a = np.asarray(prp_map(jax.random.key(1), np.arange(users), users))
    b = np.asarray(prp_map(jax.random.key(2), np.arange(users), users))
    assert (a != b).any()
    r1 = np.asarray(round_users(jax.random.fold_in(HOST_KEY, 1), users, 10))
    r2 = np.asarray(round_users(jax.random.fold_in(HOST_KEY, 2), users, 10))
    assert (r1 != r2).any()


def test_prp_draw_is_prefix_of_bijection():
    """round_users under 'prp' is exactly the PRP image of [0, A) at the
    salted per-round key -- the O(active) contract (no hidden dependence
    on num_active: growing A extends the cohort, never reshuffles it)."""
    users = 37
    skey = jax.random.fold_in(HOST_KEY, USER_SAMPLE_SALT)
    full = np.asarray(prp_map(skey, np.arange(users), users))
    for a in (1, 5, 17, 37):
        got = np.asarray(round_users(HOST_KEY, users, a, sampler="prp"))
        np.testing.assert_array_equal(got, full[:a], err_msg=f"A={a}")


# ---------------------------------------------------------------------------
# one stream: in-jit == host, perm unchanged, all-ones == uniform
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("sampler", ["perm", "prp"])
def test_in_jit_equals_host_bitwise(sampler):
    users, a = 50, 8
    avail = np.zeros(users, np.uint8)
    avail[::3] = 1
    for av in (None, avail):
        host = np.asarray(round_users(HOST_KEY, users, a, avail=av,
                                      sampler=sampler))
        jitd = np.asarray(jax.jit(
            lambda k, v=None: round_users(k, users, a, avail=v,
                                          sampler=sampler))(
            HOST_KEY, *(() if av is None else (av,))))
        np.testing.assert_array_equal(host, jitd,
                                      err_msg=f"{sampler} avail={av is not None}")


def test_perm_sampler_preserves_legacy_stream_bitwise():
    """sampler='perm' IS the pre-ISSUE-11 draw: the salted full
    permutation prefix (uniform) and the gather + stable-argsort filter
    (availability), reproduced here as the frozen reference."""
    users, a = 23, 7
    key = jax.random.fold_in(HOST_KEY, 5)
    skey = jax.random.fold_in(key, USER_SAMPLE_SALT)
    perm = np.asarray(jax.random.permutation(skey, users))
    np.testing.assert_array_equal(
        np.asarray(round_users(key, users, a, sampler="perm")),
        perm[:a].astype(np.int32))
    avail = np.zeros(users, np.uint8)
    avail[[2, 4, 8, 16]] = 1
    av = avail[perm].astype(np.float32)
    order = np.argsort(-av, kind="stable")[:a]
    ref = np.where(av[order] > 0, perm[order], -1).astype(np.int32)
    np.testing.assert_array_equal(
        np.asarray(round_users(key, users, a, avail=avail, sampler="perm")),
        ref)


@pytest.mark.parametrize("sampler", ["perm", "prp"])
def test_all_ones_availability_is_uniform(sampler):
    users, a = 41, 9
    uni = np.asarray(round_users(HOST_KEY, users, a, sampler=sampler))
    ones = np.asarray(round_users(HOST_KEY, users, a,
                                  avail=np.ones(users, np.uint8),
                                  sampler=sampler))
    np.testing.assert_array_equal(uni, ones)


def test_prp_availability_membership_spill_and_determinism():
    users, a = 32, 6
    avail = np.zeros(users, np.uint8)
    avail[[3, 9, 27]] = 1
    got = np.asarray(round_users(HOST_KEY, users, a, avail=avail,
                                 sampler="prp"))
    # budget = min(U, 4A) = 24 < U: the walk may MISS available users past
    # the window (bounded spill) but may never select an unavailable one
    assert set(got.tolist()) - {-1} <= {3, 9, 27}
    assert (got == np.asarray(round_users(jax.random.key(0), users, a,
                                          avail=avail, sampler="prp"))).all()
    # full-window case: every available user is found, in PRP order
    users2 = 20  # budget = min(20, 24) = 20 = U
    avail2 = np.zeros(users2, np.uint8)
    avail2[[1, 5, 11]] = 1
    got2 = np.asarray(round_users(HOST_KEY, users2, a, avail=avail2,
                                  sampler="prp"))
    assert set(got2.tolist()) - {-1} == {1, 5, 11}
    assert (got2[3:] == -1).all()
    skey = jax.random.fold_in(HOST_KEY, USER_SAMPLE_SALT)
    walk = np.asarray(prp_map(skey, np.arange(users2), users2))
    np.testing.assert_array_equal(got2[:3],
                                  [u for u in walk if avail2[u]][:3])


def test_chi_square_uniform_cohort_frequencies():
    """Selection frequencies over many PRP rounds are uniform: chi-square
    over 50 users at 600 draws of 10 stays well under the df=49 tail
    (mean 49, sd ~9.9; bound 120 is ~7 sd -- a smoke test, not a PRF
    certification)."""
    users, a, rounds = 50, 10, 600
    sched = superstep_user_schedule(HOST_KEY, 0, rounds, users, a,
                                    sampler="prp")
    counts = np.bincount(sched.reshape(-1), minlength=users)
    assert counts.sum() == rounds * a
    expected = rounds * a / users
    chi2 = float(((counts - expected) ** 2 / expected).sum())
    assert chi2 < 120.0, f"chi2={chi2}, counts={counts.tolist()}"


def test_prp_and_perm_are_different_streams():
    """The re-baseline is real: the two samplers draw different cohorts at
    the same key (which is why bench.py refuses cross-stream comparisons)."""
    got_prp = np.asarray(round_users(HOST_KEY, 100, 10, sampler="prp"))
    got_perm = np.asarray(round_users(HOST_KEY, 100, 10, sampler="perm"))
    assert (got_prp != got_perm).any()


# ---------------------------------------------------------------------------
# engine stream consistency: in-jit draw == host-packed schedule
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("sampler", ["perm", "prp"])
def test_masked_superstep_in_jit_draw_matches_host_schedule(sampler):
    """The masked engine's in-jit sampler (replicated placement) and a
    host-packed schedule drawn from the same stream produce bit-identical
    params and metrics -- the contract that lets sharded/streaming/grouped
    paths consume host schedules without forking the stream."""
    cfg, ds, data = _vision_setup()
    cfg = dict(cfg, sampler=sampler)
    model = make_model(cfg)
    mesh = make_mesh(4, 1)
    k, a = 2, 4

    eng_jit = RoundEngine(model, cfg, mesh)
    p1 = model.init(jax.random.key(0))
    p1, pend1 = eng_jit.train_superstep(p1, HOST_KEY, 1, k, data,
                                        num_active=a)
    ms1 = pend1.fetch()

    sched = superstep_user_schedule(HOST_KEY, 1, k, cfg["num_users"], a,
                                    sampler=sampler)
    eng_host = RoundEngine(model, cfg, mesh)
    p2 = model.init(jax.random.key(0))
    p2, pend2 = eng_host.train_superstep(p2, HOST_KEY, 1, k, data,
                                         user_schedule=sched)
    ms2 = pend2.fetch()
    for r in range(k):
        for name in ("loss_sum", "score_sum", "n", "rate"):
            np.testing.assert_array_equal(
                np.asarray(ms1[r][name]), np.asarray(ms2[r][name]),
                err_msg=f"{sampler} round {r} {name}")
    for n in sorted(p1):
        np.testing.assert_array_equal(np.asarray(p1[n]), np.asarray(p2[n]),
                                      err_msg=f"{sampler} params {n}")


# ---------------------------------------------------------------------------
# validation (ISSUE 11 satellite)
# ---------------------------------------------------------------------------

def test_round_users_validation():
    with pytest.raises(ValueError, match="num_active=17"):
        round_users(HOST_KEY, 16, 17)
    with pytest.raises(ValueError, match="num_active=-1"):
        round_users(HOST_KEY, 16, -1)
    with pytest.raises(ValueError, match="Not valid sampler"):
        round_users(HOST_KEY, 16, 4, sampler="fisher-yates")


def test_superstep_user_schedule_validation():
    with pytest.raises(ValueError, match="epoch0=-1"):
        superstep_user_schedule(HOST_KEY, -1, 2, 16, 4)
    with pytest.raises(ValueError, match="k=-2"):
        superstep_user_schedule(HOST_KEY, 1, -2, 16, 4)
    with pytest.raises(ValueError, match="num_active=20"):
        superstep_user_schedule(HOST_KEY, 1, 2, 16, 20)
    assert superstep_user_schedule(HOST_KEY, 1, 0, 16, 4).shape == (0, 4)


def test_resolve_sampler_cfg_validation():
    assert resolve_sampler_cfg({}).kind == "prp"
    assert resolve_sampler_cfg({}).horizon is None
    assert not resolve_sampler_cfg({}).committed
    spec = resolve_sampler_cfg({"sampler": "perm", "sample_horizon": 1})
    assert (spec.kind, spec.horizon, spec.committed) == ("perm", 1, True)
    with pytest.raises(ValueError, match="Not valid sampler"):
        resolve_sampler_cfg({"sampler": "uniform"})
    with pytest.raises(ValueError, match="Not valid sample_horizon"):
        resolve_sampler_cfg({"sample_horizon": -1})
    with pytest.raises(ValueError, match="Not valid sample_horizon"):
        resolve_sampler_cfg({"sample_horizon": True})
    with pytest.raises(ValueError, match="Not valid sampler"):
        C.process_control(dict(C.default_cfg(), sampler="bogus"))


# ---------------------------------------------------------------------------
# O(active): draw time, jaxpr footprint, python allocation
# ---------------------------------------------------------------------------

def test_prp_jaxpr_carries_no_population_sized_value():
    """The static O(A)-memory proof: NO value in the traced uniform PRP
    draw has num_users-scale size (the perm path's [U] permutation is the
    counterexample the same walk flags)."""
    users, a = 1_000_000, 100

    def max_aval(sampler):
        jxp = jax.make_jaxpr(
            lambda k: round_users(k, users, a, sampler=sampler))(HOST_KEY)
        sizes = [int(np.prod(v.aval.shape))
                 for eqn in jxp.eqns for v in eqn.outvars]
        return max(sizes) if sizes else 0

    assert max_aval("prp") <= 10 * a
    assert max_aval("perm") >= users  # the walk sees what it should see


@pytest.mark.slow
def test_prp_draw_1e6_time_and_memory():
    """The ISSUE 11 acceptance bound, in-suite: at 1e6 users the PRP draw
    is >= 10x faster than the permutation draw (best of 3, the bench
    microbench's procedure) and allocates O(A) python-side."""
    users, a = 1_000_000, 100

    def best_of(sampler, reps=3):
        round_users(jax.random.fold_in(HOST_KEY, 0), users, a,
                    sampler=sampler)  # warm dispatch caches
        best = float("inf")
        for i in range(reps):
            t0 = time.perf_counter()
            np.asarray(round_users(jax.random.fold_in(HOST_KEY, 1 + i),
                                   users, a, sampler=sampler))
            best = min(best, time.perf_counter() - t0)
        return best

    t_prp, t_perm = best_of("prp"), best_of("perm")
    assert t_perm / t_prp >= 10.0, f"prp {t_prp:.4f}s perm {t_perm:.4f}s"
    tracemalloc.start()
    np.asarray(round_users(jax.random.fold_in(HOST_KEY, 9), users, a,
                           sampler="prp"))
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    assert peak < 1 << 20, f"python-side peak {peak} bytes"


# ---------------------------------------------------------------------------
# schedule commitment (sample_horizon)
# ---------------------------------------------------------------------------

def test_schedule_commitment_ledger():
    c = ScheduleCommitment(1)
    # nothing fetched: superstep 1 and 2 read pre-run state, 3 does not
    assert c.may_draw(1) and c.may_draw(2) and not c.may_draw(3)
    c.commit(1, state={"loss": 1.0})
    assert c.may_draw(3) and not c.may_draw(4)
    assert c.state_for(3) == {"loss": 1.0}
    assert c.state_for(2) is None  # pre-run state
    c.commit(2, state={"loss": 0.5})
    assert c.committed_through == 2
    assert c.may_draw(4) and c.state_for(4) == {"loss": 0.5}
    # horizon 0: strictly output-dependent -- N+1 needs N's own state
    c0 = ScheduleCommitment(0)
    assert c0.may_draw(1) and not c0.may_draw(2)
    c0.commit(1)
    assert c0.may_draw(2)


def _stream_driver_cfg(d, **over):
    cfg = C.default_cfg()
    cfg["control"] = C.parse_control_name("1_8_0.5_iid_fix_a1-b1_bn_1_1")
    cfg["data_name"] = "MNIST"
    cfg["model_name"] = "conv"
    cfg["synthetic"] = True
    cfg["synthetic_sizes"] = {"train": 80, "test": 40}
    cfg["output_dir"] = str(d)
    cfg["override"] = {"num_epochs": {"global": 4, "local": 1},
                       "conv": {"hidden_size": [4, 8]},
                       "batch_size": {"train": 10, "test": 20},
                       "client_store": "stream",
                       "superstep_rounds": 2, "eval_interval": 2, **over}
    return C.process_control(cfg)


def test_driver_sample_horizon_bit_identical_with_prefetch(tmp_path):
    """A streaming driver run under sample_horizon=1 (schedule commitment)
    finishes with the EXACT params of the stateless default, keeps the
    prefetch overlap (no synchronous-staging warning fires), and commits
    every fetched superstep's state."""
    from heterofl_tpu.entry.common import FedExperiment

    mk = _stream_driver_cfg
    base = FedExperiment(mk(tmp_path / "base"), 0).run("Global-Accuracy")
    exp = FedExperiment(mk(tmp_path / "committed", sample_horizon=1), 0)
    assert exp._commitment is not None
    with warnings.catch_warnings():
        warnings.filterwarnings("error", message=".*SYNCHRONOUSLY.*")
        got = exp.run("Global-Accuracy")
    assert exp._commitment.committed_through == exp._ss_fetched > 0
    for n in sorted(base["params"]):
        np.testing.assert_array_equal(np.asarray(base["params"][n]),
                                      np.asarray(got["params"][n]),
                                      err_msg=n)


def test_driver_sample_horizon_zero_serialises_loudly(tmp_path):
    """sample_horizon=0 (strictly output-dependent): each cohort needs the
    PREVIOUS superstep's own fetched state, so the commitment blocks
    prefetch and staging serialises -- with a loud one-time warning naming
    horizon=1 as the overlap-preserving fix -- while the trajectory stays
    bit-identical (stateless samplers ignore the committed state)."""
    from heterofl_tpu.entry.common import FedExperiment

    mk = _stream_driver_cfg
    base = FedExperiment(mk(tmp_path / "base"), 0).run("Global-Accuracy")
    exp = FedExperiment(mk(tmp_path / "h0", sample_horizon=0), 0)
    with pytest.warns(UserWarning, match="sample_horizon=0.*SYNCHRONOUSLY"):
        got = exp.run("Global-Accuracy")
    for n in sorted(base["params"]):
        np.testing.assert_array_equal(np.asarray(base["params"][n]),
                                      np.asarray(got["params"][n]),
                                      err_msg=n)


def test_take_cohort_refuses_uncommitted_state(tmp_path):
    """The commitment guard: if a (hypothetical future) fetch deferral
    left the needed state uncommitted, the synchronous fallback REFUSES to
    draw instead of silently consuming pre-run state."""
    from heterofl_tpu.entry.common import FedExperiment

    exp = FedExperiment(_stream_driver_cfg(tmp_path, sample_horizon=0), 0)
    exp._ss_dispatched = 3  # superstep 4 next; its draw needs state 3
    exp._ss_fetched = 2     # ...which a deferred fetch has not committed
    exp._commitment.commit(2)
    with pytest.raises(RuntimeError, match="sample_horizon=0"):
        exp._take_cohort(7, 2)


def test_sampler_spec_defaults():
    spec = SamplerSpec()
    assert spec.kind == "prp" and spec.horizon is None
    assert AVAIL_OVERDRAW >= 2
    assert prp_round_users(HOST_KEY, 5, 0).shape == (0,)
