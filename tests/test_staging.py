"""Zero-resharding steady-state dispatch (parallel/staging.py, ISSUE 1).

The contract under test: after the first (staging + compile) round, a round
performs NO implicit host->device transfer under either engine and either
placement -- the data stacks are committed once, per-round values move via
explicit ``device_put`` only, and ``jax.transfer_guard_host_to_device``
("disallow" blocks *implicit* transfers, allows explicit ones) is the
oracle.  Plus: donation actually releases the previous round's param
buffers, rate snapping fails loudly at staging, and the pipeline/timer/
packer utilities behave.
"""

import jax
import numpy as np
import pytest

from heterofl_tpu.fed.core import snap_to_levels
from heterofl_tpu.models import make_model
from heterofl_tpu.parallel import (GroupedRoundEngine, MetricsPipeline,
                                   PendingMetrics, PhaseTimer, PlacementCache,
                                   RoundEngine, SlotPacker, make_mesh,
                                   shard_client_data)

from test_round import _vision_setup


# ---------------------------------------------------------------------------
# unit pieces
# ---------------------------------------------------------------------------

def test_snap_to_levels():
    table = [1.0, 0.5, 0.25, 0.125, 0.0625]
    # exact dyadic rates pass through
    np.testing.assert_array_equal(snap_to_levels([1.0, 0.0625], table), [1.0, 0.0625])
    # float32 round-trips snap back onto the table
    f32 = np.asarray([0.1, 0.2], np.float32)  # non-dyadic table, f32-rounded
    out = snap_to_levels(np.asarray(f32, np.float64), [0.1, 0.2])
    np.testing.assert_allclose(out, [0.1, 0.2], rtol=1e-6)
    # unknown / non-dyadic rates against a dyadic table fail loudly, by name
    with pytest.raises(ValueError, match="0.3"):
        snap_to_levels([1.0, 0.3], table)
    assert snap_to_levels([], table).size == 0


def test_grouped_unknown_rate_fails_at_staging():
    """A rate outside the level table raises ValueError in train_round's
    stage phase -- not a KeyError deep in level dispatch (ADVICE r5 item 2)."""
    cfg, ds, data = _vision_setup()
    grp = GroupedRoundEngine(cfg, make_mesh(1, 1))
    with pytest.raises(ValueError, match="level table"):
        grp.train_round(make_model(cfg).init(jax.random.key(0)),
                        np.array([0, 1], np.int32), np.array([1.0, 0.3]),
                        data, 0.05, jax.random.key(0))


def test_placement_cache_commits_once():
    mesh = make_mesh(8, 1)
    cache = PlacementCache(mesh)
    data = (np.arange(16, dtype=np.float32), np.ones(8, np.float32))
    a = cache.replicated("d", data)
    b = cache.replicated("d", data)
    assert all(x is y for x, y in zip(a, b))  # steady state: identity hits
    # a different source tuple restages
    c = cache.replicated("d", (np.arange(16, dtype=np.float32), data[1]))
    assert c[0] is not a[0]
    # sub-mesh entries are keyed by their static (lo, hi) range
    s1 = cache.replicated("d", data, srange=(0, 4))
    s2 = cache.replicated("d", data, srange=(0, 4))
    assert s1[0] is s2[0] and s1[0] is not a[0]
    assert cache.submesh(0, 4) is cache.submesh(0, 4)
    assert cache.submesh(0, 4).devices.size == 4
    # scalars are cached by value
    assert cache.scalar(0.1) is cache.scalar(0.1)
    assert cache.scalar(0.1) is not cache.scalar(0.2)


def test_broadcast_is_donation_safe():
    """PlacementCache.broadcast severs buffer aliasing: donating its output
    must NOT delete the source (device_put's output can alias the source
    shard, which is exactly the bug this method exists to avoid)."""
    import jax.numpy as jnp

    cache = PlacementCache(make_mesh(4, 1))
    x = jnp.arange(8.0)
    y = cache.broadcast(x, (0, 2))
    f = jax.jit(lambda v: v * 2, donate_argnums=(0,))
    jax.block_until_ready(f(y))
    assert not x.is_deleted()


def test_slot_packer_reuses_buffers():
    p = SlotPacker()
    b1 = p.buffer("k", (8,))
    b1[:3] = [5, 6, 7]
    b2 = p.buffer("k", (8,))
    assert b2 is b1  # steady state: no reallocation
    assert (b2 == -1).all()  # and the pad value is reset
    assert p.buffer("k", (16,)) is not b1  # layout change reallocates


def test_phase_timer_accounting():
    t = PhaseTimer()
    with t.phase("stage"):
        pass
    with t.phase("dispatch"):
        pass
    with t.phase("dispatch"):
        pass
    assert set(t.summary()) == {"stage", "dispatch"}
    assert t.calls["dispatch"] == 2
    snap = t.snapshot()
    with t.phase("fetch"):
        pass
    assert set(t.delta(snap)) == {"fetch"}
    # per-superstep amortization: one stage+dispatch cycle pays for K rounds
    t2 = PhaseTimer()
    t2.totals["dispatch"] = 8.0
    assert t2.amortized({}, 4) == {"dispatch": 2.0}
    assert t2.amortized({"dispatch": 4.0}, 2) == {"dispatch": 2.0}


def test_tier1_persistent_compile_cache_active():
    """The ISSUE 2 CI satellite: the tier-1 session must run with the
    persistent compile cache wired up (conftest also hard-fails), so
    superstep recompiles show as cache misses instead of silent 40s stalls."""
    import os

    assert jax.config.jax_compilation_cache_dir
    assert os.path.isdir(jax.config.jax_compilation_cache_dir)


def test_install_cache_counters_counts_compiles():
    from heterofl_tpu.utils.compile_cache import install_cache_counters

    c = install_cache_counters()
    assert set(c) == {"requests", "hits"}
    before = dict(c)
    # a FRESH program shape (unique constant) must consult the enabled
    # persistent cache and strictly bump the request counter -- the strict
    # inequality is the test that the monitoring listener actually fires
    jax.jit(lambda x: x * 3 + 1)(np.arange(931.0)).block_until_ready()
    assert c["requests"] > before["requests"]


def test_metrics_pipeline_batches_and_flushes():
    fetched = []

    def mk(i):
        return PendingMetrics({"n": np.float32(i)},
                              assemble=lambda h: fetched.append(i) or h)

    pipe = MetricsPipeline(fetch_every=3)
    assert pipe.push(1, mk(1)) == [] and pipe.push(2, mk(2)) == []
    assert fetched == []  # nothing materialised yet
    due = pipe.push(3, mk(3))
    assert [tag for tag, _ in due] == [1, 2, 3] and fetched == [1, 2, 3]
    assert len(pipe) == 0
    pipe.push(4, mk(4))
    assert [tag for tag, _ in pipe.flush()] == [4]  # boundary flush
    # fetch_every=1 degenerates to synchronous (parity default)
    pipe1 = MetricsPipeline(1)
    assert [tag for tag, _ in pipe1.push(9, mk(9))] == [9]


# ---------------------------------------------------------------------------
# the tentpole contract: zero implicit H2D transfers in steady state
# ---------------------------------------------------------------------------

def _steady_state_rounds(run_round, params, keys):
    """Round 1 stages + compiles; rounds 2..3 must run under a host->device
    transfer guard that disallows implicit transfers."""
    params, _ = run_round(params, keys[0])
    with jax.transfer_guard_host_to_device("disallow"):
        params, ms = run_round(params, keys[1])
        params, ms = run_round(params, keys[2])
    return params, ms


def test_transfer_guard_masked_replicated():
    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    eng = RoundEngine(model, cfg, make_mesh(8, 1))
    user_idx = np.array([0, 2, 4, 6], np.int32)
    keys = [jax.random.key(r) for r in range(3)]

    def run(params, key):
        return eng.train_round(params, key, 0.05, user_idx, data)

    params, ms = _steady_state_rounds(run, model.init(jax.random.key(0)), keys)
    assert np.isfinite(np.asarray(ms["loss_sum"])).all()


def test_transfer_guard_masked_sharded():
    cfg, ds, data = _vision_setup()
    cfg = dict(cfg, data_placement="sharded")
    model = make_model(cfg)
    eng = RoundEngine(model, cfg, make_mesh(8, 1))
    data_s = shard_client_data(eng.mesh, tuple(np.asarray(d) for d in data))
    user_idx = np.array([0, 2, 4, 6], np.int32)
    keys = [jax.random.key(r) for r in range(3)]

    def run(params, key):
        return eng.train_round(params, key, 0.05, user_idx, data_s)

    params, ms = _steady_state_rounds(run, model.init(jax.random.key(0)), keys)
    assert np.isfinite(np.asarray(ms["loss_sum"])).all()


@pytest.mark.parametrize("placement", ["span", "slices"])
def test_transfer_guard_grouped(placement):
    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    grp = GroupedRoundEngine(dict(cfg, level_placement=placement), make_mesh(8, 1))
    assert grp.level_placement == placement
    user_idx = np.array([0, 2, 4, 6, 1, 3], np.int32)
    rates = np.asarray(cfg["model_rate"], np.float32)[user_idx]
    keys = [jax.random.key(r) for r in range(3)]

    def run(params, key):
        # async_metrics: the sums stay on device inside the guard; the D2H
        # fetch (allowed anyway) happens after
        p, pending = grp.train_round(params, user_idx, rates, data, 0.05, key,
                                     async_metrics=True)
        return p, pending

    params, pending = _steady_state_rounds(run, model.init(jax.random.key(0)), keys)
    ms = pending.fetch()
    assert (ms["n"] > 0).all() and np.isfinite(ms["loss_sum"]).all()


# ---------------------------------------------------------------------------
# donation: the previous round's param buffers are actually released
# ---------------------------------------------------------------------------

def test_donation_releases_previous_round_params():
    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    user_idx = np.array([0, 2, 4, 6], np.int32)
    rates = np.asarray(cfg["model_rate"], np.float32)[user_idx]

    # masked engine: the round program donates its params argument
    eng = RoundEngine(model, cfg, make_mesh(1, 1))
    p0 = model.init(jax.random.key(0))
    p1, _ = eng.train_round(p0, jax.random.key(1), 0.05, user_idx, data)
    jax.block_until_ready(p1)
    assert all(v.is_deleted() for v in p0.values())

    # grouped engine: the combine donates the old globals
    grp = GroupedRoundEngine(cfg, make_mesh(1, 1))
    g0 = model.init(jax.random.key(0))
    g1, _ = grp.train_round(g0, user_idx, rates, data, 0.05, jax.random.key(1))
    jax.block_until_ready(g1)
    assert all(v.is_deleted() for v in g0.values())


def test_transfer_guard_superstep_masked():
    """A steady-state SUPERSTEP dispatch performs no implicit H2D either:
    data committed once, epoch index via explicit scalar staging, sampling
    in-jit -- rounds 2..3 of supersteps run under the disallow guard."""
    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    eng = RoundEngine(model, cfg, make_mesh(8, 1))
    params = model.init(jax.random.key(0))
    base_key = jax.random.key(7)
    params, pending = eng.train_superstep(params, base_key, 1, 2, data, num_active=4)
    pending.fetch()
    with jax.transfer_guard_host_to_device("disallow"):
        params, pending = eng.train_superstep(params, base_key, 3, 2, data,
                                              num_active=4)
        params, pending = eng.train_superstep(params, base_key, 5, 2, data,
                                              num_active=4)
    ms = pending.fetch()
    assert len(ms) == 2 and np.isfinite(ms[-1]["loss_sum"]).all()


@pytest.mark.parametrize("placement", ["span", "slices"])
def test_transfer_guard_superstep_grouped(placement):
    """Grouped fused superstep: per-superstep slot schedules move via
    explicit device_put only; steady-state supersteps pass the guard."""
    from heterofl_tpu.fed.core import round_users

    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    grp = GroupedRoundEngine(dict(cfg, level_placement=placement), make_mesh(8, 1))
    base_key = jax.random.key(7)
    rates_vec = np.asarray(cfg["model_rate"], np.float32)

    def sched(epoch0, k):
        users = np.stack([
            np.asarray(round_users(jax.random.fold_in(base_key, epoch0 + r),
                                   cfg["num_users"], 4)) for r in range(k)])
        return users, rates_vec[users]

    params = model.init(jax.random.key(0))
    users, rates = sched(1, 2)
    params, pending = grp.train_superstep(params, base_key, 1, 2, users, rates, data)
    pending.fetch()
    # schedule drawing is host-side sampling (like the drivers' rng), not
    # part of the dispatch contract -- draw outside, dispatch inside
    u3, r3 = sched(3, 2)
    u5, r5 = sched(5, 2)
    with jax.transfer_guard_host_to_device("disallow"):
        params, pending = grp.train_superstep(params, base_key, 3, 2, u3, r3, data)
        params, pending = grp.train_superstep(params, base_key, 5, 2, u5, r5, data)
    ms = pending.fetch()
    assert len(ms) == 2 and np.isfinite(ms[-1]["loss_sum"]).all()


def test_superstep_donation_releases_previous_params():
    """The superstep program donates the params carry: after a dispatch the
    input buffers are released (the liveness contract train_round already
    honors, extended to the scan)."""
    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    base_key = jax.random.key(0)

    eng = RoundEngine(model, cfg, make_mesh(1, 1))
    p0 = model.init(jax.random.key(0))
    p1, pending = eng.train_superstep(p0, base_key, 1, 2, data, num_active=4)
    jax.block_until_ready(p1)
    pending.fetch()
    assert all(v.is_deleted() for v in p0.values())

    grp = GroupedRoundEngine(cfg, make_mesh(1, 1))
    users = np.array([[0, 2, 4, 6], [1, 3, 5, 7]], np.int32)
    rates = np.asarray(cfg["model_rate"], np.float32)[users]
    g0 = model.init(jax.random.key(0))
    g1, pending = grp.train_superstep(g0, base_key, 1, 2, users, rates, data)
    jax.block_until_ready(g1)
    pending.fetch()
    assert all(v.is_deleted() for v in g0.values())


def test_slices_broadcast_donation_leaves_globals_alive():
    """In slices mode each level program donates its private params
    broadcast; the GLOBAL params must survive all level dispatches (they
    feed the combine) -- the regression the jitted broadcast copy exists
    for."""
    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    grp = GroupedRoundEngine(dict(cfg, level_placement="slices"), make_mesh(8, 1))
    assert grp.level_placement == "slices"
    user_idx = np.array([0, 2, 4, 6, 1, 3], np.int32)
    rates = np.asarray(cfg["model_rate"], np.float32)[user_idx]
    g0 = model.init(jax.random.key(0))
    g1, ms = grp.train_round(g0, user_idx, rates, data, 0.05, jax.random.key(1))
    jax.block_until_ready(g1)
    assert (ms["n"] > 0).all() and np.isfinite(ms["loss_sum"]).all()
