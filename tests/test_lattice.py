"""latticecheck (ISSUE 18): config-lattice exhaustiveness + RNG key-stream
provenance.  Everything here is jax-free by construction -- the lattice
pass replays the config validator chain and the key-stream pass walks the
source tree with ast -- so this file never boots a backend.

The seeded-regression tests are the teeth: each finding type the audit
can emit (unclassified combo, silently-falling-back refusal, rotted
evidence, duplicated salt, drifted constant, undeclared fold site,
reused raw key, unrooted bind) is deliberately injected through the
injectable tables and must trip its named finding."""

import os
import textwrap

import pytest

from heterofl_tpu import config as C
from heterofl_tpu.compress import CODEC_NAMES
from heterofl_tpu.fed.sampling import SAMPLER_KINDS
from heterofl_tpu.staticcheck import keys as K
from heterofl_tpu.staticcheck import lattice as L

PKG = os.path.dirname(os.path.dirname(os.path.abspath(L.__file__)))
REPO = os.path.dirname(PKG)


def _defaults():
    return {axis: vals[0] for axis, vals in L.AXES}


def _axes(**overrides):
    """Shrunken axis table: every axis pinned to its default except the
    overridden ones -- keeps seeded-regression lattices tiny."""
    return tuple((a, overrides.get(a, (vals[0],))) for a, vals in L.AXES)


# ---------------------------------------------------------------------------
# the real tree is exhaustively classified and green
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def section():
    return L.lattice_check()


def test_lattice_green_and_exhaustive(section):
    n = 1
    for _axis, vals in L.AXES:
        n *= len(vals)
    assert section["points"] == n
    assert section["supported"] + section["refused"] == n
    assert section["unreached"] == 0
    assert section["ok"] and section["findings"] == []
    # both classes are populated: an all-SUPPORTED (or all-REFUSED)
    # lattice would mean the axis table rotted into triviality
    assert section["supported"] > 0 and section["refused"] > 0


def test_every_declared_refusal_rule_fires(section):
    assert [r["id"] for r in section["refusal_rules"]] == \
        [r["id"] for r in L.REFUSAL_RULES]
    dead = [r["id"] for r in section["refusal_rules"] if r["points"] == 0]
    assert dead == []


def test_every_contract_carries_points(section):
    # a contract no surviving point uses is dead weight (or a rider rot)
    dead = [c["name"] for c in section["contracts"] if c["points"] == 0]
    assert dead == []


def test_axes_mirror_config_registries():
    """The lattice's axis table cannot drift from the live config
    registries: a value added to one side must show up on the other."""
    axes = dict(L.AXES)
    assert axes["engine"] == C.STRATEGIES
    assert axes["placement"] == C.DATA_PLACEMENTS
    assert axes["levels"] == C.LEVEL_PLACEMENTS
    assert axes["store"] == C.CLIENT_STORES
    assert axes["codec"] == CODEC_NAMES
    assert set(axes["sampler"]) == set(SAMPLER_KINDS)


def test_refusal_owners_exist_in_chain():
    owners = {name for name, _fn in C.validator_chain()}
    for rule in L.REFUSAL_RULES:
        assert rule["owner"] in owners, rule["id"]


def test_rule_keys_come_from_axis_cfg_map():
    declared = {k for keys in L.AXIS_CFG_KEYS.values() for k in keys}
    for rule in L.REFUSAL_RULES:
        assert set(rule["keys"]) <= declared, rule["id"]


# ---------------------------------------------------------------------------
# ISSUE 18 satellite: every REFUSED point's ValueError names the
# offending cfg keys (parametrized over the declared refusal rules)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("rule", L.REFUSAL_RULES, ids=lambda r: r["id"])
def test_refused_point_message_names_offending_keys(rule):
    point = _defaults()
    for axis, want in rule["when"].items():
        point[axis] = want[0] if isinstance(want, tuple) else want
    res = L.classify_point(point)
    assert res["class"] == "REFUSED", point
    # provenance: SOME declared rule matching this point has the same
    # owning validator AND every one of its offending cfg keys is named
    # verbatim in the ValueError message
    matching = [r for r in L.REFUSAL_RULES
                if L._rule_matches(r, point) and r["owner"] == res["owner"]]
    assert matching, (point, res)
    named = [r for r in matching
             if all(k in res["message"] for k in r["keys"])]
    assert named, (res["owner"], res["message"])


# ---------------------------------------------------------------------------
# seeded lattice regressions: each finding type trips by name
# ---------------------------------------------------------------------------


def test_seeded_unclassified_axis_value_trips_unreached():
    # an axis value nobody declared a refusal rule (or support) for:
    # resolve_strategy_cfg refuses it, but with no declared provenance
    axes = _axes(engine=("masked", "quantum"))
    sec = L.lattice_check(axes=axes, rules=())
    assert not sec["ok"]
    assert sec["unreached"] == 1
    hits = [f for f in sec["findings"] if f["rule"] == "lattice-unreached"]
    assert hits and "quantum" in hits[0]["where"]


def test_seeded_uncovered_combo_trips_unreached():
    # validators pass but no anchor covers the core -> unclassified combo
    sec = L.lattice_check(axes=_axes(), anchors={})
    assert not sec["ok"]
    assert any(f["rule"] == "lattice-unreached"
               and "unclassified combo" in f["message"]
               for f in sec["findings"])


def test_seeded_phantom_rule_trips_silent_fallback():
    # a declared refusal the validators do NOT deliver: the combo would
    # run and silently degrade -- the exact mid-run-fallback smell the
    # lattice pass exists to kill
    phantom = {"id": "phantom-sharded", "when": {"placement": "sharded"},
               "owner": "resolve_placement_cfg", "keys": ("data_placement",)}
    sec = L.lattice_check(axes=_axes(placement=("replicated", "sharded")),
                          rules=(phantom,))
    assert not sec["ok"]
    assert any(f["rule"] == "lattice-silent-fallback"
               for f in sec["findings"])


def test_seeded_unknown_owner_trips_silent_fallback():
    rule = {"id": "ghost", "when": {"engine": "masked"},
            "owner": "resolve_ghost_cfg", "keys": ("strategy",)}
    sec = L.lattice_check(axes=_axes(), rules=(rule,))
    assert any(f["rule"] == "lattice-silent-fallback"
               and "resolve_ghost_cfg" in f["message"]
               for f in sec["findings"])


def test_seeded_rotted_evidence_trips_evidence_missing():
    # audited set given but empty: the anchor program backing the
    # default point is not audited green
    sec = L.lattice_check(axes=_axes(), rules=(), audited=())
    assert not sec["ok"]
    assert sec["evidence_checked"]
    assert any(f["rule"] == "lattice-evidence-missing"
               for f in sec["findings"])


# ---------------------------------------------------------------------------
# key streams: the real tree is green
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def ks_section():
    return K.key_streams_check(PKG)


def test_key_streams_green_on_real_tree(ks_section):
    assert ks_section["ok"]
    assert ks_section["findings_total"] == 0
    # every declared registry row matched at least one live fold site
    # (zero-hit rows would be key-registry-stale findings)
    assert ks_section["fold_in_sites"] >= 50
    assert ks_section["registry_rows"] == len(K.SALT_REGISTRY)


def test_declared_roots_intervals_disjoint(ks_section):
    # the collision that motivated this pass: ARM_STREAM_SALT=17 sat
    # inside the host key's per-round epoch family -- prove the fixed
    # intervals stay disjoint per root
    assert K._check_intervals(K.ROOTS) == []
    host = {s["stream"]: (s["lo"], s["hi"])
            for s in ks_section["roots"]["host_key"]}
    for stream in ("epoch", "arms", "retry"):
        assert stream in host


# ---------------------------------------------------------------------------
# seeded key-stream regressions: each finding type trips by name
# ---------------------------------------------------------------------------


def test_seeded_duplicated_salt_trips_collision():
    roots = dict(K.ROOTS)
    # an interval landing inside the host key's epoch family -- exactly
    # the old ARM_STREAM_SALT=17 bug, re-seeded
    roots["host_key"] = roots["host_key"] + (("evil-dup", 17, 18),)
    sec = K.key_streams_check(PKG, roots=roots)
    assert not sec["ok"]
    assert any(f["rule"] == "key-salt-collision"
               and "evil-dup" in f["message"]
               for f in sec["findings"])


def test_seeded_salt_drift_trips_by_name():
    constants = {m: dict(c) for m, c in K.SALT_CONSTANTS.items()}
    constants["fed/core.py"]["ROUND_RATE_SALT"] = 8
    sec = K.key_streams_check(PKG, constants=constants)
    assert not sec["ok"]
    assert any(f["rule"] == "key-salt-drift"
               and "ROUND_RATE_SALT" in f["message"]
               for f in sec["findings"])


def test_seeded_undeclared_fold_site(tmp_path):
    (tmp_path / "mod.py").write_text(textwrap.dedent("""
        import jax

        def f(key):
            return jax.random.fold_in(key, 42)
    """))
    sec = K.key_streams_check(tmp_path, registry=(), roots={}, constants={})
    assert not sec["ok"]
    assert any(f["rule"] == "key-undeclared-stream"
               for f in sec["findings"])


def test_seeded_registry_stale_row(tmp_path):
    registry = (("ghost_root", "ghost", "no/such/file.py",
                 r"key", r"42", "a rotted declared stream"),)
    sec = K.key_streams_check(tmp_path, registry=registry, constants={},
                              roots={"ghost_root": (("ghost", None, None),)})
    assert not sec["ok"]
    assert any(f["rule"] == "key-registry-stale" and "rotted" in f["message"]
               for f in sec["findings"])
    # a row naming a (root, stream) absent from ROOTS is the other
    # stale shape
    sec = K.key_streams_check(tmp_path, registry=registry, constants={},
                              roots={})
    assert any(f["rule"] == "key-registry-stale"
               and "undeclared stream" in f["message"]
               for f in sec["findings"])


def test_seeded_raw_key_reuse(tmp_path):
    (tmp_path / "mod.py").write_text(textwrap.dedent("""
        import jax

        def bad(key):
            a = jax.random.normal(key, (4,))
            b = jax.random.uniform(key, (4,))
            return a + b

        def ok_exclusive(key, flag):
            if flag:
                return jax.random.normal(key, (4,))
            else:
                return jax.random.uniform(key, (4,))

        def ok_rebound(key):
            for t in range(3):
                key = jax.random.fold_in(key, t)
            return jax.random.normal(key, (4,))
    """))
    findings = K.scan_raw_reuse(tmp_path)
    assert [f["rule"] for f in findings] == ["key-raw-reuse"]
    assert "bad()" in findings[0]["where"]
    # ...and end-to-end through the section entrypoint
    sec = K.key_streams_check(tmp_path, registry=(), roots={}, constants={})
    assert not sec["ok"]
    assert any(f["rule"] == "key-raw-reuse" for f in sec["findings"])


def test_seeded_unrooted_bind():
    findings = K.check_binds(["heterofl_tpu/nowhere/mystery.py"])
    assert [f["rule"] for f in findings] == ["key-unrooted-bind"]
    # files the registry models pass, as do declared derived-key
    # consumers (ops/quant.py draws on the codec-derived key)
    assert K.check_binds(["fed/core.py", "parallel/round_engine.py",
                          "ops/quant.py"]) == []
    # ...but the consumer declaration is provenance, not a waiver: with
    # an empty derived map the same bind trips again
    fs = K.check_binds(["ops/quant.py"], derived_consumers={})
    assert [f["rule"] for f in fs] == ["key-unrooted-bind"]


# ---------------------------------------------------------------------------
# ratchet wiring: the declared coverage is pinned and cannot shrink
# ---------------------------------------------------------------------------


def test_ratchet_pins_lattice_and_key_coverage():
    from heterofl_tpu.staticcheck.ratchet import baseline_view, diff_reports
    rep = {"programs": {}, "config": {},
           "lattice": {"points": 10, "refusal_rules": [{"id": "a"},
                                                       {"id": "b"}]},
           "key_streams": {"fold_in_sites": 5, "registry_rows": 3}}
    base = baseline_view(rep)
    assert base["coverage"] == {
        "lattice.points": 10, "lattice.refusal_rules": 2,
        "key_streams.fold_in_sites": 5, "key_streams.registry_rows": 3}
    assert diff_reports(rep, base)["ok"]
    # shrinkage regresses...
    shrunk = dict(rep, lattice=dict(rep["lattice"], points=9))
    d = diff_reports(shrunk, base)
    assert not d["ok"]
    assert d["regressions"][0]["metric"] == "lattice.points"
    # ...growth is an improvement, never a failure
    grown = dict(rep, key_streams=dict(rep["key_streams"], fold_in_sites=6))
    d = diff_reports(grown, base)
    assert d["ok"] and any(i["metric"] == "key_streams.fold_in_sites"
                           for i in d["improvements"])


# ---------------------------------------------------------------------------
# README's "Compatibility lattice" section is the generated artifact
# ---------------------------------------------------------------------------


def test_readme_lattice_section_in_sync(section):
    md = L.lattice_markdown(section)
    with open(os.path.join(REPO, "README.md")) as f:
        readme = f.read()
    assert "## Compatibility lattice" in readme
    assert md.strip() in readme, (
        "README's Compatibility-lattice section is stale: regenerate with "
        "`python -m heterofl_tpu.staticcheck --lattice-md`")
