"""wirecheck (ISSUE 7): the static wire-bytes model, the HBM footprint
budgets, the reshard detector, the baseline ratchet, and the stale-pragma
lint -- including the four seeded regressions the acceptance criteria name
(an extra psum, an un-donated leaf, an injected reshard, inflated peak
bytes), each tripping its distinct named finding."""

import copy
import functools
import json
import os
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from heterofl_tpu.staticcheck.audit import (_grouped_targets, _masked_targets,
                                            audit_program, build_setup)
from heterofl_tpu.staticcheck.jaxpr_walk import (collective_payload_rows,
                                                 find_reshards, reshard_ops)
from heterofl_tpu.staticcheck.memory import (analytic_budget, check_memory,
                                             collect_memory)
from heterofl_tpu.staticcheck.ratchet import (baseline_view, diff_reports,
                                              load_baseline, write_baseline)
from heterofl_tpu.staticcheck.report import AuditReport, ProgramReport
from heterofl_tpu.staticcheck.rules import lint_source
from heterofl_tpu.staticcheck.wire import (check_wire, classify, dcn_axes_of,
                                           participants_of, program_wire,
                                           ring_allreduce_bytes)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def setup():
    """One small audit setup shared by the seeded-regression tests."""
    return build_setup()


# ---------------------------------------------------------------------------
# the wire model
# ---------------------------------------------------------------------------

def test_ring_allreduce_bytes():
    # 2 (p-1)/p x payload; a single participant reduces locally (0 wire)
    assert ring_allreduce_bytes(1000, 1) == 0
    assert ring_allreduce_bytes(1000, 2) == 1000
    assert ring_allreduce_bytes(1000, 8) == 1750


class _Dev:
    def __init__(self, process_index):
        self.process_index = process_index


class _FakeMesh:
    def __init__(self, devices, axis_names):
        self.devices = devices
        self.axis_names = axis_names


def test_dcn_axis_classification():
    """A mesh axis whose traversal crosses a process boundary is
    DCN-eligible; single-process meshes are all-ICI."""
    one_proc = _FakeMesh(np.array([[_Dev(0)], [_Dev(0)]]), ("clients", "data"))
    assert dcn_axes_of(one_proc) == ()
    # two processes split along the clients axis
    two_proc = _FakeMesh(np.array([[_Dev(0), _Dev(0)], [_Dev(1), _Dev(1)]]),
                         ("clients", "data"))
    assert dcn_axes_of(two_proc) == ("clients",)
    assert classify(("clients",), ("clients",)) == "dcn"
    assert classify(("data",), ("clients",)) == "ici"
    assert participants_of(("clients", "data"), _FakeMesh(
        np.array([[_Dev(0)] * 3] * 4), ("clients", "data"))) == 12


def _tiny_mesh(n=2):
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()[:n]).reshape(n, 1),
                ("clients", "data"))


def test_program_wire_prices_psum_payload():
    """One psum bind over a (sums, counts) pair is priced at the summed
    per-participant operand bytes, attributed to the training axis."""
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    mesh = _tiny_mesh()

    def f(a, b):
        return jax.lax.psum((a, b), "clients")

    sm = shard_map(f, mesh=mesh, in_specs=(P("clients"), P("clients")),
                   out_specs=(P(), P()), check_rep=False)
    x = np.ones((4, 8), np.float32)  # per-device (2, 8) f32 = 64 bytes
    jaxpr = jax.jit(sm).trace(x, x).jaxpr
    rows = collective_payload_rows(jaxpr)
    assert len(rows) == 1 and rows[0]["primitive"] == "psum"
    assert rows[0]["payload_bytes"] == 2 * 2 * 8 * 4
    wire = program_wire(jaxpr, mesh)
    assert wire["train_bytes_per_round"] == 128
    assert wire["eval_bytes_total"] == 0 and wire["dcn_bytes"] == 0
    assert wire["collectives"][0]["scope"] == "ici"
    assert wire["collectives"][0]["ring_bytes_per_device"] == \
        ring_allreduce_bytes(128, 2)

    rep = ProgramReport(name="t")
    check_wire(rep, wire, expected_train_bytes=128, n_eval_points=0)
    assert rep.ok
    rep2 = ProgramReport(name="t")
    check_wire(rep2, wire, expected_train_bytes=64, n_eval_points=0)
    assert not rep2.ok
    assert [f.rule for f in rep2.findings] == ["wire-budget"]


def test_wire_dcn_budget():
    rep = ProgramReport(name="t")
    wire = {"train_bytes_per_round": 0, "eval_bytes_total": 0,
            "eval_payloads": [], "other_bytes": 0, "collectives": [],
            "dcn_bytes": 100, "dcn_axes": ["clients"]}
    check_wire(rep, wire, expected_train_bytes=0, n_eval_points=0,
               dcn_budget_bytes=0)
    assert [f.rule for f in rep.findings] == ["wire-dcn"]


def test_wire_unbudgeted_collective_trips(setup):
    """A reduction smuggled past the psum bind count (pmax over clients)
    still shows up by its payload: bytes outside the train/eval buckets
    are zero in every green program."""
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    mesh = _tiny_mesh()

    def f(a, b):
        s = jax.lax.psum((a, b), "clients")
        return s, jax.lax.pmax(a, "clients")  # the smuggled reduction

    sm = shard_map(f, mesh=mesh, in_specs=(P("clients"), P("clients")),
                   out_specs=((P(), P()), P()), check_rep=False)
    x = np.ones((4, 8), np.float32)
    wire = program_wire(jax.jit(sm).trace(x, x).jaxpr, mesh)
    assert wire["other_bytes"] == 64  # the per-device pmax operand
    rep = ProgramReport(name="t")
    check_wire(rep, wire, expected_train_bytes=128, n_eval_points=0)
    assert [f_.rule for f_ in rep.findings] == ["wire-unbudgeted"]
    assert "pmax" in rep.findings[0].message


def test_level_param_table_is_byte_table_view():
    """level_param_table is a count view over level_byte_table -- one
    source of truth for parameter footprints."""
    from heterofl_tpu.fed.core import (PARAM_ITEMSIZE, level_byte_table,
                                       level_param_table)
    from heterofl_tpu.staticcheck.audit import default_audit_cfg

    cfg = default_audit_cfg()
    bt, pt = level_byte_table(cfg), level_param_table(cfg)
    assert set(bt) == set(pt)
    for r in bt:
        assert bt[r]["param_bytes"] == pt[r] * PARAM_ITEMSIZE
        assert bt[r]["wire_bytes"] == 2 * bt[r]["param_bytes"]


# ---------------------------------------------------------------------------
# seeded regression 1: an EXTRA PSUM trips wire-budget (and psum-budget)
# ---------------------------------------------------------------------------

def test_seeded_extra_psum_trips_wire_budget(setup, monkeypatch):
    """A second global reduction smuggled into the round body is caught by
    BOTH the bind-count budget and the byte-accurate wire budget."""
    from heterofl_tpu.parallel.round_engine import RoundEngine

    orig = RoundEngine._round_core

    def doubled(self, params, key, lr, user_loc, user_glob, data,
                resid=None, sched_buf=None):
        new_p, ms, new_resid, new_buf = orig(self, params, key, lr, user_loc,
                                             user_glob, data, resid=resid,
                                             sched_buf=sched_buf)
        leak = jax.lax.psum(lr, "clients")  # the extra 4-byte global psum
        k0 = next(iter(new_p))
        new_p = dict(new_p)
        new_p[k0] = new_p[k0] + 0.0 * leak
        return new_p, ms, new_resid, new_buf

    monkeypatch.setattr(RoundEngine, "_round_core", doubled)
    name, prog, args, expect = _masked_targets(setup)[0]
    rep = audit_program(name, prog, args, expect, setup["mesh"])
    rules = {f.rule for f in rep.findings}
    assert "psum-budget" in rules
    assert "wire-budget" in rules, rep.findings
    msg = next(f for f in rep.findings if f.rule == "wire-budget").message
    # the finding names measured vs budgeted bytes (payload grew by 4)
    assert str(expect["wire_bytes"] + 4) in msg and str(expect["wire_bytes"]) in msg


# ---------------------------------------------------------------------------
# seeded regression 2: an UN-DONATED LEAF trips hbm-donation-savings
# ---------------------------------------------------------------------------

def test_seeded_undonated_leaf_trips_donation_savings(setup):
    """A program that stopped donating its carry loses the aliasing bytes:
    besides the count mismatches, the HBM accounting names the bytes that
    are now silently double-buffered."""
    grouped, _names, _ = _grouped_targets(setup)
    name, prog, args, expect = grouped[0]  # span level prog: donates 0
    assert expect["donated"] == 0
    n_leaves = len(jax.tree_util.tree_leaves(setup["params"]))
    rep = audit_program(name, prog, args, dict(expect, donated=n_leaves),
                        setup["mesh"])
    rules = {f.rule for f in rep.findings}
    assert "hbm-donation-savings" in rules, rep.findings
    acct = rep.memory_budget["donation"]
    assert acct["saved_bytes"] == 0
    assert acct["expected_saved_bytes"] == expect["mem"]["param_bytes"] > 0


# ---------------------------------------------------------------------------
# seeded regression 3: an INJECTED RESHARD trips the reshard detector
# ---------------------------------------------------------------------------

def test_seeded_reshard_trips_detector(setup, monkeypatch):
    """A ppermute smuggled into the round body is an explicit data-movement
    collective: zero are allowed in any round program."""
    from heterofl_tpu.parallel.round_engine import RoundEngine

    orig = RoundEngine._round_core

    def shifted(self, params, key, lr, user_loc, user_glob, data,
                resid=None, sched_buf=None):
        new_p, ms, new_resid, new_buf = orig(self, params, key, lr, user_loc,
                                             user_glob, data, resid=resid,
                                             sched_buf=sched_buf)
        n = self.mesh.shape["clients"]
        k0 = next(iter(new_p))
        new_p = dict(new_p)
        new_p[k0] = jax.lax.ppermute(
            new_p[k0], "clients", [(i, (i + 1) % n) for i in range(n)])
        return new_p, ms, new_resid, new_buf

    monkeypatch.setattr(RoundEngine, "_round_core", shifted)
    name, prog, args, expect = _masked_targets(setup)[0]
    jaxpr = prog.trace(*args).jaxpr
    hits = find_reshards(jaxpr)
    assert hits and hits[0][0] == "ppermute"
    assert "test_wirecheck" in hits[0][1]  # provenance of the bind
    rep = audit_program(name, prog, args, expect, setup["mesh"])
    assert not rep.ok
    hits = [f for f in rep.findings if f.rule == "reshard"]
    assert hits and "ppermute" in hits[0].message
    assert rep.reshards["total"] >= 1


def test_reshard_ops_parses_optimized_hlo_text():
    """The HLO half counts GSPMD-introduced data movement: sync and async
    `-start` forms count once, `-done` halves are skipped."""
    text = textwrap.dedent("""\
        %a2a.1 = f32[4]{0} all-to-all(f32[4]{0} %p), dimensions={0}
        %cp = f32[4]{0} collective-permute(f32[4]{0} %p), channel_id=1
        %cps = (f32[4]{0}, f32[4]{0}) collective-permute-start(f32[4]{0} %p)
        %cpd = f32[4]{0} collective-permute-done((f32[4]{0}, f32[4]{0}) %cps)
        %ar = f32[4]{0} all-reduce(f32[4]{0} %p), to_apply=%sum
        """)
    counts = reshard_ops(text)
    assert counts["all-to-all"] == 1
    assert counts["collective-permute"] == 2  # sync + start, not done
    assert counts["total"] == 3
    assert reshard_ops("%ar = f32[4]{0} all-reduce(f32[4]{0} %p)")["total"] == 0


# ---------------------------------------------------------------------------
# seeded regression 4: INFLATED PEAK BYTES trip hbm-budget
# ---------------------------------------------------------------------------

def test_seeded_inflated_temp_trips_hbm_budget(setup):
    """A program whose HBM footprint blows past what its declared shapes
    justify fails the audit instead of the TPU: a 4 MiB working set against
    a few-bytes analytic model lands far over the bound."""
    def f(x):
        a = jnp.full((1024, 1024), x)  # 4 MiB materialised temp
        return (a @ a).sum()

    rep = audit_program(
        "seeded/inflated-temp", jax.jit(f), (np.float32(1.0),),
        {"donated": 0, "psum": 0, "wire_bytes": 0,
         "mem": {"param_bytes": 4, "activation_bytes": 4,
                 "clients_per_device": 1}},
        setup["mesh"])
    hits = [f_ for f_ in rep.findings if f_.rule == "hbm-budget"]
    assert hits, rep.findings
    assert "temp_size_in_bytes" in hits[0].message
    assert rep.memory["temp_size_in_bytes"] > rep.memory_budget["temp_budget"]


def test_check_memory_budget_fields():
    budget = analytic_budget(param_bytes=100, activation_bytes=50,
                             clients_per_device=2, staged_arg_bytes=1000,
                             train_payload_bytes=200)
    rep = ProgramReport(name="t")
    check_memory(rep, {"temp_size_in_bytes": budget["temp_budget"],
                       "argument_size_in_bytes": 0,
                       "output_size_in_bytes": 0}, budget)
    assert rep.ok  # at the bound is fine
    rep2 = ProgramReport(name="t")
    check_memory(rep2, {"temp_size_in_bytes": budget["temp_budget"] + 1,
                        "argument_size_in_bytes": 0,
                        "output_size_in_bytes": 0}, budget)
    assert [f.rule for f in rep2.findings] == ["hbm-budget"]


# ---------------------------------------------------------------------------
# satellite: absent memory_analysis() fields are LOUD findings
# ---------------------------------------------------------------------------

def test_missing_memory_analysis_is_loud():
    """The old getattr-skip silently produced an empty record; now an
    absent field on a compiled flagship program is a named finding."""
    fields, findings = collect_memory(None, "p")
    assert fields is None
    assert [f.rule for f in findings] == ["memory-analysis-missing"]

    class Partial:  # argument/output there, temp gone dark
        argument_size_in_bytes = 10
        output_size_in_bytes = 5

    fields, findings = collect_memory(Partial(), "p")
    assert [f.rule for f in findings] == ["memory-analysis-missing"]
    assert "temp_size_in_bytes" in findings[0].message
    assert fields == {"argument_size_in_bytes": 10, "output_size_in_bytes": 5}

    class Full(Partial):
        temp_size_in_bytes = 7

    fields, findings = collect_memory(Full(), "p")
    assert not findings
    assert fields["peak_bytes"] == 22


# ---------------------------------------------------------------------------
# satellite: stale-pragma lint
# ---------------------------------------------------------------------------

IN_SCOPE = "heterofl_tpu/parallel/somefile.py"


def _lint(src, relpath=IN_SCOPE):
    return lint_source(textwrap.dedent(src), relpath)


def test_stale_pragma_dead_suppression():
    """A pragma whose rule no longer fires on the lines it covers is
    reported instead of rotting silently."""
    live = _lint("""
    import numpy as np
    def f(a):
        return np.asarray(a)  # staticcheck: allow(no-asarray): reason
    """)
    assert live == []
    dead = _lint("""
    import numpy as np
    def f(a):
        return np.array(a)  # staticcheck: allow(no-asarray): rotted
    """)
    assert [f.rule for f in dead] == ["stale-pragma"]
    assert "no-asarray" in dead[0].message


def test_stale_pragma_unknown_and_out_of_scope_rule():
    fs = _lint("""
    def f(a):
        return a  # staticcheck: allow(no-such-rule): typo'd id
    """)
    assert [f.rule for f in fs] == ["stale-pragma"]
    assert "unknown rule id" in fs[0].message
    # a driver-only rule pragma'd in parallel/ can never suppress anything
    fs = _lint("""
    def f(ev):
        return ev  # staticcheck: allow(no-host-eval-in-driver): wrong tree
    """)
    assert [f.rule for f in fs] == ["stale-pragma"]
    assert "not scoped" in fs[0].message


def test_stale_pragma_reports_only_dead_half_of_multi_id():
    fs = _lint("""
    import numpy as np
    def f(a):
        return np.asarray(a)  # staticcheck: allow(no-asarray, no-device-get): half-dead
    """)
    assert [f.rule for f in fs] == ["stale-pragma"]
    assert "no-device-get" in fs[0].message
    assert "allow(no-asarray)" not in fs[0].message


def test_stale_pragma_comment_block_coverage():
    """A pragma in a comment block covers the statement the block precedes
    -- it is live when that statement violates the rule."""
    assert _lint("""
    import numpy as np
    def f(a):
        # staticcheck: allow(no-asarray): a longer reason that
        # spans two comment lines before the call it licenses
        return np.asarray(a)
    """) == []


# ---------------------------------------------------------------------------
# the baseline ratchet (jax-free)
# ---------------------------------------------------------------------------

def _mini_report(fusions=10, temp=1000, donated=2, wire=64, flops=100.0,
                 fail=False, extra_program=None):
    rep = AuditReport()
    rep.config = {"flagship": False, "data_name": "X", "model_name": "m",
                  "num_users": 2, "levels": [1.0],
                  "mesh": {"clients": 8, "data": 1}}
    p = ProgramReport(name="prog/a", donation_expected=donated)
    p.psum_clients = 1
    p.donated = p.aliased = donated
    p.flops = flops
    p.memory = {"temp_size_in_bytes": temp, "argument_size_in_bytes": 10,
                "output_size_in_bytes": 5}
    p.wire = {"train_bytes_per_round": wire, "eval_bytes_total": 0,
              "other_bytes": 0, "dcn_bytes": 0}
    p.reshards = {"total": 0}
    p.step_body = {"fusions": fusions, "instructions": 200}
    if fail:
        p.fail("psum-budget", "seeded failure")
    rep.add_program(p)
    if extra_program:
        rep.add_program(ProgramReport(name=extra_program))
    rep.flop_budget = {"ok": True}
    rep.recompile = {"ok": True}
    rep.generated_at = "2026-01-01T00:00:00+00:00"
    return rep


def test_ratchet_clean_roundtrip_and_file_io(tmp_path):
    rep = _mini_report()
    path = str(tmp_path / "BASE.json")
    write_baseline(path, rep.to_dict())
    base = load_baseline(path)
    assert base["version"] == 2
    diff = diff_reports(rep.to_dict(), base)
    assert diff["ok"] and not diff["regressions"]
    assert diff["baseline_generated_at"] == rep.generated_at


def test_ratchet_headroom_and_exact_metrics():
    base = baseline_view(_mini_report(fusions=100).to_dict())
    # +10% fusions sits inside the 15% headroom; +20% regresses
    ok = diff_reports(_mini_report(fusions=110).to_dict(), base)
    assert ok["ok"], ok["regressions"]
    bad = diff_reports(_mini_report(fusions=120).to_dict(), base)
    assert not bad["ok"]
    assert [r["metric"] for r in bad["regressions"]] == ["step_body.fusions"]
    # wire bytes are exact: +1 byte regresses
    bad = diff_reports(_mini_report(wire=65).to_dict(), base)
    assert [r["metric"] for r in bad["regressions"]] == \
        ["wire.train_bytes_per_round"]
    # improvements are recorded, never failed: the ratchet only tightens
    better = diff_reports(_mini_report(fusions=50, wire=32).to_dict(), base)
    assert better["ok"]
    assert {i["metric"] for i in better["improvements"]} >= \
        {"step_body.fusions", "wire.train_bytes_per_round"}


def test_ratchet_change_bad_and_dark_metrics():
    base = baseline_view(_mini_report(donated=2).to_dict())
    # donation coverage has ONE right answer: shrinking it also regresses
    bad = diff_reports(_mini_report(donated=1).to_dict(), base)
    assert any(r["metric"] == "donated" for r in bad["regressions"])
    # a metric going dark (None where the baseline had a number) regresses
    rep = _mini_report()
    rep.programs["prog/a"].wire = None
    bad = diff_reports(rep.to_dict(), base)
    assert any(r["metric"] == "wire.train_bytes_per_round"
               and r["current"] is None for r in bad["regressions"])


def test_ratchet_program_set_and_config_drift():
    base = baseline_view(_mini_report(extra_program="prog/b").to_dict())
    shrunk = diff_reports(_mini_report().to_dict(), base)
    assert not shrunk["ok"] and shrunk["missing_programs"] == ["prog/b"]
    grown = diff_reports(_mini_report(extra_program="prog/c").to_dict(),
                         baseline_view(_mini_report().to_dict()))
    assert grown["ok"] and grown["new_programs"] == ["prog/c"]
    # incomparable configs are a single loud regression, not a metric soup
    other = _mini_report()
    other.config = dict(other.config, num_users=1000)
    drift = diff_reports(other.to_dict(),
                         baseline_view(_mini_report().to_dict()))
    assert not drift["ok"]
    assert [r["metric"] for r in drift["regressions"]] == ["config"]
    assert "--update-baseline" in drift["regressions"][0]["message"]


# ---------------------------------------------------------------------------
# the CLI: exit codes, --json schema, ratchet round-trip
# ---------------------------------------------------------------------------

@pytest.fixture
def cli(monkeypatch, tmp_path):
    """In-process CLI runner with the program audit stubbed to a fabricated
    report (the real-audit CLI path is covered by the slow test in
    test_staticcheck.py): returns (run, paths)."""
    import heterofl_tpu.staticcheck.__main__ as cli_mod
    import heterofl_tpu.staticcheck.audit as audit_mod

    state = {"report": _mini_report()}
    monkeypatch.setattr(cli_mod, "_scrub_env_for_cpu_audit", lambda: None)
    monkeypatch.setattr(audit_mod, "run_audit",
                        lambda **kw: copy.deepcopy(state["report"]))
    out = str(tmp_path / "STATICCHECK.json")
    baseline = str(tmp_path / "BASELINE.json")

    def run(*extra):
        return cli_mod.main(["--skip-lint", "--out", out,
                             "--baseline", baseline] + list(extra))

    run.state = state
    run.out = out
    run.baseline = baseline
    return run


def test_cli_green_exit_and_json_schema(cli, capsys):
    assert cli("--json") == 0
    rec = json.loads(capsys.readouterr().out)
    assert sorted(rec) == ["arms", "config", "flop_budget", "generated_at",
                           "key_streams", "lattice", "lint", "ok", "programs",
                           "ratchet", "recompile", "sampler", "version",
                           "wire_frontier"]
    prog = rec["programs"]["prog/a"]
    for key in ("wire", "memory", "reshards", "step_body", "psum_clients",
                "donated", "aliased", "flops", "findings"):
        assert key in prog, key
    assert rec["ratchet"] == {"checked": False}
    assert json.loads(open(cli.out).read())["ok"] is True


def test_cli_ratchet_roundtrip_then_regress(cli, capsys):
    # pin, then diff the identical audit: clean, exit 0
    assert cli("--update-baseline") == 0
    assert os.path.exists(cli.baseline)
    assert cli("--diff-baseline") == 0
    rec = json.loads(open(cli.out).read())
    assert rec["ratchet"]["checked"] and rec["ratchet"]["ok"]
    capsys.readouterr()
    # regress a metric past its headroom: exit 2 (audit itself stays green)
    cli.state["report"] = _mini_report(fusions=20)
    assert cli("--diff-baseline", "--json") == 2
    rec = json.loads(capsys.readouterr().out)
    assert rec["ok"] is True and rec["ratchet"]["ok"] is False
    assert [r["metric"] for r in rec["ratchet"]["regressions"]] == \
        ["step_body.fusions"]
    # and re-pinning after the intentional change makes the diff clean again
    assert cli("--update-baseline") == 0
    assert cli("--diff-baseline") == 0


def test_cli_audit_failure_beats_ratchet_exit(cli, capsys):
    assert cli("--update-baseline") == 0
    cli.state["report"] = _mini_report(fail=True)
    assert cli("--diff-baseline") == 1  # audit failure keeps exit 1
    capsys.readouterr()


def test_cli_refuses_to_pin_failing_audit(cli, capsys):
    cli.state["report"] = _mini_report(fail=True)
    assert cli("--update-baseline") == 1
    assert not os.path.exists(cli.baseline)
    captured = capsys.readouterr()
    assert "refusing" in captured.err
    # the refusal does NOT short-circuit the run: the failing artifact is
    # still written and the findings still print, like a plain failing run
    assert json.loads(open(cli.out).read())["ok"] is False
    assert "psum-budget" in captured.out


def test_cli_missing_baseline_is_a_regression(cli, capsys):
    assert cli("--diff-baseline", "--json") == 2
    rec = json.loads(capsys.readouterr().out)
    assert rec["ratchet"]["checked"] and not rec["ratchet"]["ok"]
    assert "--update-baseline" in rec["ratchet"]["regressions"][0]["message"]


def test_cli_diff_needs_audit(cli):
    with pytest.raises(SystemExit):
        cli("--diff-baseline", "--skip-audit")


def test_committed_baseline_matches_committed_artifact():
    """The repo's committed STATICCHECK_BASELINE.json is the pinned view of
    the committed STATICCHECK.json: the ratchet diff between them is clean,
    so CI's --diff-baseline run starts from a green line."""
    with open(os.path.join(REPO, "STATICCHECK.json")) as f:
        artifact = json.load(f)
    baseline = load_baseline(os.path.join(REPO, "STATICCHECK_BASELINE.json"))
    diff = diff_reports(artifact, baseline)
    assert diff["ok"], diff["regressions"]
