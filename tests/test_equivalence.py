"""The masked-execution correctness theorem.

HeteroFL sub-models are prefix slices of the global tensors (ref
src/fed.py:46-48).  The framework's default strategy runs every client at full
global width with the suffix masked to zero.  These tests verify that this is
*exactly* the sliced computation: forward outputs, losses, and gradients (on
the active support) agree between

  (a) the global model applied to masked params with ``width_rate=r``, and
  (b) a truly sliced sub-model (reference-shaped) with the gathered params.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from heterofl_tpu import config as C
from heterofl_tpu.fed import extract_sliced
from heterofl_tpu.models import make_model
from heterofl_tpu.models.spec import mask_params

from test_models import small_cfg, vision_batch

# compiles a sliced sub-model per rate per family (fast gate excludes this module)
pytestmark = pytest.mark.slow


def _grads(model, params, batch, **kw):
    def loss_fn(p):
        out, _ = model.apply(p, batch, **kw)
        return out["loss"]

    return jax.grad(loss_fn)(params)


def _assert_close(a, b, tol=2e-5, msg=""):
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=tol, atol=tol, err_msg=msg)


@pytest.mark.parametrize("model_name", ["conv", "resnet18"])
@pytest.mark.parametrize("norm", ["bn", "in", "ln", "none"])
@pytest.mark.parametrize("rate", [0.5, 0.25])
def test_vision_masked_equals_sliced(model_name, norm, rate):
    cfg = small_cfg(model_name, norm=norm)
    gm = make_model(cfg)
    params = gm.init(jax.random.key(0))
    batch = vision_batch(cfg, n=6, seed=1)
    lm = jnp.zeros(10).at[jnp.array([0, 2, 5])].set(1.0)

    masked = mask_params(params, gm.specs, gm.groups, rate)
    out_m, _ = gm.apply(masked, batch, train=True, width_rate=rate, scaler_rate=rate, label_mask=lm)

    sm = make_model(cfg, model_rate=rate)
    sp = {k: jnp.asarray(v) for k, v in
          extract_sliced({k: np.asarray(v) for k, v in params.items()}, gm.specs, gm.groups, rate).items()}
    out_s, _ = sm.apply(sp, batch, train=True, width_rate=1.0, scaler_rate=rate, label_mask=lm)

    _assert_close(out_m["score"], out_s["score"], msg="scores diverge")
    _assert_close(out_m["loss"], out_s["loss"], msg="loss diverges")

    # Gradients agree on the active support.
    gm_grads = _grads(gm, masked, batch, train=True, width_rate=rate, scaler_rate=rate, label_mask=lm)
    sm_grads = _grads(sm, sp, batch, train=True, width_rate=1.0, scaler_rate=rate, label_mask=lm)
    gm_grads_sliced = extract_sliced({k: np.asarray(v) for k, v in gm_grads.items()},
                                     gm.specs, gm.groups, rate)
    for k in sm_grads:
        _assert_close(gm_grads_sliced[k], sm_grads[k], tol=1e-4, msg=f"grad {k}")


@pytest.mark.parametrize("rate", [0.5, 0.25])
def test_gn_masked_equals_sliced(rate):
    # gn requires active counts divisible by 4 (torch GroupNorm constraint).
    cfg = small_cfg("conv", norm="gn")
    cfg["conv"] = {"hidden_size": [16, 32]}
    gm = make_model(cfg)
    params = gm.init(jax.random.key(0))
    batch = vision_batch(cfg, n=4, seed=2)
    masked = mask_params(params, gm.specs, gm.groups, rate)
    out_m, _ = gm.apply(masked, batch, train=True, width_rate=rate, scaler_rate=rate)
    sm = make_model(cfg, model_rate=rate)
    sp = {k: jnp.asarray(v) for k, v in
          extract_sliced({k: np.asarray(v) for k, v in params.items()}, gm.specs, gm.groups, rate).items()}
    out_s, _ = sm.apply(sp, batch, train=True, width_rate=1.0, scaler_rate=rate)
    _assert_close(out_m["score"], out_s["score"])


@pytest.mark.parametrize("rate", [0.5, 0.25])
def test_transformer_masked_equals_sliced(rate):
    cfg = small_cfg("transformer", data_name="WikiText2")
    gm = make_model(cfg)
    params = gm.init(jax.random.key(0))
    labels = jnp.asarray(np.random.default_rng(3).integers(0, 50, (2, 16)))
    batch = {"label": labels}
    lm = jnp.zeros(50).at[jnp.arange(0, 50, 3)].set(1.0)
    key = jax.random.key(7)

    masked = mask_params(params, gm.specs, gm.groups, rate)
    out_m, _ = gm.apply(masked, batch, train=True, width_rate=rate, scaler_rate=rate,
                        label_mask=lm, rng=key)

    sm = make_model(cfg, model_rate=rate)
    sp = {k: jnp.asarray(v) for k, v in
          extract_sliced({k: np.asarray(v) for k, v in params.items()}, gm.specs, gm.groups, rate).items()}
    out_s, _ = sm.apply(sp, batch, train=True, width_rate=1.0, scaler_rate=rate,
                        label_mask=lm, rng=key)
    _assert_close(out_m["score"], out_s["score"], tol=1e-4)
    _assert_close(out_m["loss"], out_s["loss"], tol=1e-4)

    gm_grads = _grads(gm, masked, batch, train=True, width_rate=rate, scaler_rate=rate,
                      label_mask=lm, rng=key)
    sm_grads = _grads(sm, sp, batch, train=True, width_rate=1.0, scaler_rate=rate,
                      label_mask=lm, rng=key)
    gm_sliced = extract_sliced({k: np.asarray(v) for k, v in gm_grads.items()}, gm.specs, gm.groups, rate)
    for k in sm_grads:
        _assert_close(gm_sliced[k], sm_grads[k], tol=3e-4, msg=f"grad {k}")


def test_full_rate_mask_is_identity():
    cfg = small_cfg("conv")
    gm = make_model(cfg)
    params = gm.init(jax.random.key(0))
    masked = mask_params(params, gm.specs, gm.groups, 1.0)
    for k in params:
        np.testing.assert_array_equal(np.asarray(params[k]), np.asarray(masked[k]))
