"""Streaming million-user client store + double-buffered cohort staging
(parallel/staging.py ClientStore/CohortStager/StagedCohort, ISSUE 6).

The contracts under test:

* the store materialises cohort shards BYTE-IDENTICAL to the eager
  ``stack_client_shards`` stacks (same padding rule, same masks), so a
  streamed superstep reproduces the eager one bit for bit in both engines;
* steady-state streaming dispatch performs no implicit H2D and compiles
  exactly one program specialization (fresh cohorts every superstep);
* the ring-buffer pipeline can stage superstep N+1 (and N+2) while
  superstep N is still in flight without corrupting N's committed cohort
  (the private-copy fence);
* host memory scales with the SAMPLED cohort, not the population
  (tracemalloc bound independent of num_users);
* driver satellites: boundary-round pivot (no blended fused-eval means)
  and the loud metrics_fetch_every conflict errors.
"""

import tracemalloc

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from heterofl_tpu.data import (fetch_dataset, label_split_masks,
                               span_population, split_dataset,
                               stack_client_shards)
from heterofl_tpu.fed.core import (superstep_rate_schedule,
                                   superstep_user_schedule)
from heterofl_tpu.models import make_model
from heterofl_tpu.parallel import (ClientStore, GroupedRoundEngine,
                                   RoundEngine, make_mesh)

from test_round import _vision_setup

HOST = jax.random.key(0)


def _stream_setup(users=8):
    """_vision_setup's exact data plus the split and a matching store."""
    from test_models import small_cfg

    cfg = small_cfg("conv", data_name="MNIST",
                    control=f"1_{users}_0.5_iid_fix_a1-b1-c1-d1-e1_bn_1_1")
    ds = fetch_dataset("MNIST", synthetic=True, seed=0,
                       synthetic_sizes={"train": 400, "test": 100})
    rng = np.random.default_rng(0)
    split, lsplit = split_dataset(ds, users, cfg["data_split_mode"], rng,
                                  classes_size=10)
    x, y, m = stack_client_shards(ds["train"].data, ds["train"].target,
                                  split["train"], list(range(users)))
    lm = label_split_masks(lsplit, users, 10)
    data = (jnp.asarray(x), jnp.asarray(y), jnp.asarray(m), jnp.asarray(lm))
    store = ClientStore.from_split(ds["train"].data, ds["train"].target,
                                   split["train"], lsplit, 10)
    return cfg, ds, data, (x, y, m, lm), store


# ---------------------------------------------------------------------------
# the store: cohort materialisation == the eager stack, byte for byte
# ---------------------------------------------------------------------------

def test_store_matches_eager_stack_ragged_shards():
    """CSR store vs stack_client_shards on RAGGED shards: identical images,
    targets (including the repeat-first-items pad rows) and sample masks;
    padding slots (-1) materialise user 0's row -- the engines'
    maximum(uid, 0) convention."""
    rng = np.random.default_rng(3)
    data = rng.integers(0, 255, (60, 4, 4, 1)).astype(np.uint8)
    target = rng.integers(0, 10, 60)
    split = {0: list(range(17)), 1: list(range(17, 20)), 2: list(range(20, 60))}
    lsplit = {0: [0, 3], 1: [5], 2: list(range(10))}
    x, y, m = stack_client_shards(data, target, split, [0, 1, 2])
    store = ClientStore.from_split(data, target, split, lsplit, 10)
    assert store.shard_max == x.shape[1] and store.num_users == 3

    ids = np.array([0, 1, 2, -1], np.int32)
    n = store.shard_max
    xx = np.empty((4, n) + data.shape[1:], data.dtype)
    yy = np.empty((4, n), target.dtype)
    mm = np.empty((4, n), np.float32)
    ll = np.empty((4, 10), np.float32)
    store.fill_vision(ids, xx, yy, mm)
    store.fill_labels(ids, ll)
    np.testing.assert_array_equal(xx[:3], x)
    np.testing.assert_array_equal(yy[:3], y)
    np.testing.assert_array_equal(mm[:3], m)
    np.testing.assert_array_equal(ll[:3], label_split_masks(lsplit, 3, 10))
    # the -1 slot IS user 0's row (data and mask and labels)
    np.testing.assert_array_equal(xx[3], x[0])
    np.testing.assert_array_equal(mm[3], m[0])
    np.testing.assert_array_equal(ll[3], ll[0])


def test_span_store_layout():
    """Span populations: O(num_users) metadata windows onto a shared pool,
    rows equal the raw slices, iid (no label split) masks are all-ones."""
    rng = np.random.default_rng(0)
    data = rng.integers(0, 255, (100, 2, 2, 1)).astype(np.uint8)
    target = rng.integers(0, 10, 100)
    starts, sizes = span_population(100, 5000, 16)
    assert starts.shape == (5000,) and (sizes == 16).all()
    assert (starts + sizes <= 100).all()
    store = ClientStore.from_spans(data, target, starts, sizes, 10)
    xx = np.empty((2, 16) + data.shape[1:], data.dtype)
    yy = np.empty((2, 16), target.dtype)
    mm = np.empty((2, 16), np.float32)
    store.fill_vision(np.array([7, 4999]), xx, yy, mm)
    for s, u in enumerate((7, 4999)):
        lo = int(starts[u])
        np.testing.assert_array_equal(xx[s], data[lo:lo + 16])
        np.testing.assert_array_equal(yy[s], target[lo:lo + 16])
    assert (mm == 1.0).all()
    # a stride sharing a factor with hi must not collapse the window walk:
    # hi == stride (10472-500+1 == 9973) would give every user start 0
    st2, _ = span_population(10472, 1000, 500)
    assert len(np.unique(st2)) > 900
    # degenerate hi=1 (shard covers the pool): the only legal start is 0
    st3, _ = span_population(16, 10, 16)
    assert (st3 == 0).all()
    ll = np.empty((2, 10), np.float32)
    store.fill_labels(np.array([7, 4999]), ll)
    assert (ll == 1.0).all()
    # metadata is O(U) small ints, nowhere near a densified stack
    assert store.metadata_nbytes == sizes.nbytes + starts.nbytes


# ---------------------------------------------------------------------------
# engines: streamed supersteps == eager supersteps, bit for bit
# ---------------------------------------------------------------------------

def test_masked_stream_bit_identical_and_steady():
    """Masked engine: a streamed cohort superstep reproduces the eager
    in-jit-sampled superstep bit for bit (params + per-round metrics), and
    steady-state streaming passes the transfer guard with a flat program
    cache (fresh cohorts restage, programs never respecialise)."""
    cfg, ds, data, _, store = _stream_setup()
    model = make_model(cfg)
    mesh = make_mesh(4, 1)
    k, A = 3, 4

    eng = RoundEngine(model, cfg, mesh)
    p = model.init(jax.random.key(0))
    p, pend = eng.train_superstep(p, HOST, 1, k, data, num_active=A)
    ms_e = pend.fetch()

    eng2 = RoundEngine(model, cfg, mesh)
    sched = superstep_user_schedule(HOST, 1, k, cfg["num_users"], A)
    coh = eng2.stage_cohort(store, sched)
    p2 = model.init(jax.random.key(0))
    p2, pend2 = eng2.train_superstep(p2, HOST, 1, k, cohort=coh)
    ms_s = pend2.fetch()
    for name in p:
        np.testing.assert_array_equal(np.asarray(p[name]), np.asarray(p2[name]),
                                      err_msg=name)
    for r in range(k):
        for nme in ("loss_sum", "score_sum", "n", "rate"):
            np.testing.assert_array_equal(np.asarray(ms_e[r][nme]),
                                          np.asarray(ms_s[r][nme]),
                                          err_msg=f"round {r} {nme}")

    size0 = eng2.program_cache_size()
    sched2 = superstep_user_schedule(HOST, 4, k, cfg["num_users"], A)
    coh2 = eng2.stage_cohort(store, sched2)
    with jax.transfer_guard_host_to_device("disallow"):
        p2, pend2 = eng2.train_superstep(p2, HOST, 4, k, cohort=coh2)
    assert np.isfinite(pend2.fetch()[-1]["loss_sum"]).all()
    assert eng2.program_cache_size() == size0


@pytest.mark.parametrize("placement", ["span", "slices"])
def test_grouped_stream_bit_identical_and_steady(placement):
    """Grouped engine (both level placements): streamed == eager bitwise;
    steady-state streaming guard-clean with a flat program cache."""
    cfg, ds, data, _, store = _stream_setup()
    model = make_model(cfg)
    mesh = make_mesh(8, 1)
    k, A = 2, 4
    sched = superstep_user_schedule(HOST, 1, k, cfg["num_users"], A)
    rates = superstep_rate_schedule(HOST, 1, k, cfg, sched)

    grp = GroupedRoundEngine(dict(cfg, level_placement=placement), mesh)
    assert grp.level_placement == placement
    p = model.init(jax.random.key(0))
    p, pend = grp.train_superstep(p, HOST, 1, k, sched, rates, data)
    ms_e = pend.fetch()

    grp2 = GroupedRoundEngine(dict(cfg, level_placement=placement), mesh)
    coh = grp2.stage_cohort(store, sched, rates)
    p2 = model.init(jax.random.key(0))
    p2, pend2 = grp2.train_superstep(p2, HOST, 1, k, cohort=coh)
    ms_s = pend2.fetch()
    for name in p:
        np.testing.assert_array_equal(np.asarray(p[name]), np.asarray(p2[name]),
                                      err_msg=f"{placement}/{name}")
    for r in range(k):
        for nme in ("loss_sum", "score_sum", "n", "rate"):
            np.testing.assert_array_equal(np.asarray(ms_e[r][nme]),
                                          np.asarray(ms_s[r][nme]),
                                          err_msg=f"{placement}/{r}/{nme}")

    sched2 = superstep_user_schedule(HOST, 3, k, cfg["num_users"], A)
    coh2 = grp2.stage_cohort(store, sched2, superstep_rate_schedule(
        HOST, 3, k, cfg, sched2))
    with jax.transfer_guard_host_to_device("disallow"):
        p2, pend2 = grp2.train_superstep(p2, HOST, 3, k, cohort=coh2)
    assert np.isfinite(pend2.fetch()[-1]["loss_sum"]).all()
    # a FRESH draw may legally re-bucket the slot layout when its level
    # mix changes (slices: per_dev = max over levels of the cohort's
    # occupancy; the bench excludes such slot-bucket compiles from its
    # steady average) -- the recompile-hazard contract is that a
    # fresh-but-IDENTICAL schedule hits the cached program
    size1 = grp2.program_cache_size()
    coh3 = grp2.stage_cohort(store, sched2, superstep_rate_schedule(
        HOST, 3, k, cfg, sched2))
    with jax.transfer_guard_host_to_device("disallow"):
        p2, pend3 = grp2.train_superstep(p2, HOST, 5, k, cohort=coh3)
    assert np.isfinite(pend3.fetch()[-1]["loss_sum"]).all()
    assert grp2.program_cache_size() == size1


# ---------------------------------------------------------------------------
# the double-buffered pipeline: overlap without corruption
# ---------------------------------------------------------------------------

def test_ring_reuse_never_corrupts_committed_cohorts():
    """Stage three cohorts back to back (the depth-1 ring reuses cohort 1's
    host buffers for cohort 3): cohort 1's COMMITTED device arrays must
    still hold cohort 1's bytes -- the jitted private copy severs any
    device_put aliasing of the ring buffer."""
    cfg, ds, data, (x, y, m, lm), store = _stream_setup()
    eng = RoundEngine(make_model(cfg), cfg, make_mesh(4, 1))
    k, A = 2, 4
    scheds = [superstep_user_schedule(HOST, 1 + i * k, k, cfg["num_users"], A)
              for i in range(3)]
    cohs = [eng.stage_cohort(store, s) for s in scheds]
    # ring slots were reused by now; verify cohort 0 against the eager stack
    sched0 = np.asarray(cohs[0].sched)
    xs0 = np.asarray(cohs[0].data[0])
    ms0 = np.asarray(cohs[0].data[2])
    assert sched0[:, :A].tolist() == scheds[0].tolist()
    for r in range(k):
        for s in range(sched0.shape[1]):
            u = max(int(sched0[r, s]), 0)
            np.testing.assert_array_equal(xs0[r, s], x[u],
                                          err_msg=f"slot {r}/{s}")
            np.testing.assert_array_equal(ms0[r, s], m[u])


def test_prefetch_overlaps_inflight_superstep():
    """Superstep N+1's (and N+2's) staging runs while superstep N is still
    in flight -- N's results must equal the sequential baseline (the
    overlap can neither corrupt the cohort nor block on the fetch)."""
    cfg, ds, data, _, store = _stream_setup()
    model = make_model(cfg)
    mesh = make_mesh(4, 1)
    k, A = 2, 4

    def sched_at(e0):
        return superstep_user_schedule(HOST, e0, k, cfg["num_users"], A)

    # sequential baseline: stage -> dispatch -> fetch, one at a time
    eng_a = RoundEngine(model, cfg, mesh)
    pa = model.init(jax.random.key(0))
    base = []
    for i in range(3):
        coh = eng_a.stage_cohort(store, sched_at(1 + i * k))
        pa, pend = eng_a.train_superstep(pa, HOST, 1 + i * k, k, cohort=coh)
        base.append(pend.fetch())

    # pipelined: dispatch N, stage N+1 BEFORE touching N's results
    eng_b = RoundEngine(model, cfg, mesh)
    pb = model.init(jax.random.key(0))
    coh = eng_b.stage_cohort(store, sched_at(1))
    pendings = []
    for i in range(3):
        pb, pend = eng_b.train_superstep(pb, HOST, 1 + i * k, k, cohort=coh)
        if i < 2:  # prefetch the NEXT superstep while this one computes
            coh = eng_b.stage_cohort(store, sched_at(1 + (i + 1) * k))
        pendings.append(pend)
    for i, pend in enumerate(pendings):
        got = pend.fetch()
        for r in range(k):
            for nme in ("loss_sum", "score_sum", "n", "rate"):
                np.testing.assert_array_equal(
                    np.asarray(base[i][r][nme]), np.asarray(got[r][nme]),
                    err_msg=f"superstep {i} round {r} {nme}")
    for na, nb in zip(sorted(pa), sorted(pb)):
        np.testing.assert_array_equal(np.asarray(pa[na]), np.asarray(pb[nb]))


def test_prefetch_depth2_ring_reuse_safe():
    """``stream_prefetch_depth=2`` (ISSUE 8 satellite): with TWO cohorts
    staged ahead of the in-flight superstep the ring holds depth+1 = 3
    slots, so cohort N+3 reuses cohort N's host buffers while N's private
    copy may still be the scan's live operand.  Five supersteps with the
    deepest legal pipeline must stay bit-identical to the sequential
    depth-1 baseline (params AND every round metric) -- a refill racing an
    in-flight superstep would corrupt exactly these."""
    cfg, ds, data, _, store = _stream_setup()
    model = make_model(cfg)
    mesh = make_mesh(4, 1)
    k, A, n_ss = 2, 4, 5

    def sched_at(e0):
        return superstep_user_schedule(HOST, e0, k, cfg["num_users"], A)

    # sequential depth-1 baseline: stage -> dispatch -> fetch, one at a time
    eng_a = RoundEngine(model, cfg, mesh)
    pa = model.init(jax.random.key(0))
    base = []
    for i in range(n_ss):
        coh = eng_a.stage_cohort(store, sched_at(1 + i * k))
        pa, pend = eng_a.train_superstep(pa, HOST, 1 + i * k, k, cohort=coh)
        base.append(pend.fetch())

    # depth-2 pipeline: keep TWO staged cohorts in hand at every dispatch
    eng_b = RoundEngine(model, dict(cfg, stream_prefetch_depth=2), mesh)
    assert eng_b._cohort_stager is None
    pb = model.init(jax.random.key(0))
    ready = [eng_b.stage_cohort(store, sched_at(1)),
             eng_b.stage_cohort(store, sched_at(1 + k))]
    assert eng_b._cohort_stager.depth == 2
    pendings = []
    for i in range(n_ss):
        pb, pend = eng_b.train_superstep(pb, HOST, 1 + i * k, k,
                                         cohort=ready.pop(0))
        if i + 2 < n_ss:  # refill to two-ahead while this one computes
            ready.append(eng_b.stage_cohort(store, sched_at(1 + (i + 2) * k)))
        pendings.append(pend)
    for i, pend in enumerate(pendings):
        got = pend.fetch()
        for r in range(k):
            for nme in ("loss_sum", "score_sum", "n", "rate"):
                np.testing.assert_array_equal(
                    np.asarray(base[i][r][nme]), np.asarray(got[r][nme]),
                    err_msg=f"superstep {i} round {r} {nme}")
    for n in sorted(pa):
        np.testing.assert_array_equal(np.asarray(pa[n]), np.asarray(pb[n]),
                                      err_msg=f"depth-2 params {n}")


# ---------------------------------------------------------------------------
# O(active) memory: staging cost independent of the population
# ---------------------------------------------------------------------------

def test_stage_memory_scales_with_cohort_not_population():
    """Cohort staging allocates O(k x active x shard) host bytes no matter
    how large the population is: tracemalloc peaks for a 2k-user and a
    200k-user span population agree within noise, and both stay orders of
    magnitude under the eager [U, ...] stack the store replaces."""
    rng = np.random.default_rng(0)
    data = rng.integers(0, 255, (400, 28, 28, 1)).astype(np.uint8)
    target = rng.integers(0, 10, 400)
    cfg, _, _, _, _ = _stream_setup()
    eng = RoundEngine(make_model(cfg), cfg, make_mesh(4, 1))
    k, A, shard = 2, 4, 16

    def staged_peak(users, epoch0):
        starts, sizes = span_population(400, users, shard)
        store = ClientStore.from_spans(data, target, starts, sizes, 10)
        sched = superstep_user_schedule(HOST, epoch0, k, users, A)
        tracemalloc.start()
        eng.stage_cohort(store, sched)
        _, peak = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        return peak, store

    peak_small, _ = staged_peak(2_000, 1)
    peak_large, store_large = staged_peak(200_000, 3)
    cohort_bytes = k * A * shard * (28 * 28 * 1 + 8 + 4)  # x + y + m
    # peaks bounded by a small multiple of the cohort, NOT the population
    eager_stack_bytes = 200_000 * shard * 28 * 28 * 1
    assert peak_large < 50 * cohort_bytes < eager_stack_bytes / 100
    assert peak_large < 4 * max(peak_small, 1 << 20)
    # and the store's own metadata is O(U) int64s, not O(U x shard) samples
    assert store_large.metadata_nbytes == 2 * 200_000 * 8
    assert store_large.metadata_nbytes < eager_stack_bytes / 100


@pytest.mark.slow
def test_population_1e6_flagship_superstep():
    """The ISSUE 6 acceptance shape: a 1e6-user synthetic population runs
    the flagship CIFAR10/ResNet-18 config on the 8-device CPU mesh through
    the streaming store -- cohort staging time and bytes match a 1e4-user
    store (population-independent), and one streamed superstep trains.
    (The bench's BENCH_POPULATION axis records the RSS/stage-time table;
    this is the in-suite twin, slow-marked.)"""
    import time

    from heterofl_tpu import config as C

    cfg = C.default_cfg()
    cfg["control"] = C.parse_control_name(
        "1_1000000_0.00001_iid_fix_a1-b1-c1-d1-e1_bn_1_1")
    cfg["data_name"] = "CIFAR10"
    cfg["model_name"] = "resnet18"
    cfg["synthetic"] = True
    cfg = C.process_control(cfg)
    cfg["classes_size"] = 10
    cfg["conv_impl"] = "im2col"
    ds = fetch_dataset("CIFAR10", synthetic=True, seed=0,
                       synthetic_sizes={"train": 20000, "test": 100})
    model = make_model(cfg)
    mesh = make_mesh(8, 1)
    k, A, shard = 2, 10, 500

    def build(users):
        starts, sizes = span_population(20000, users, shard)
        return ClientStore.from_spans(ds["train"].data, ds["train"].target,
                                      starts, sizes, 10)

    eng = RoundEngine(model, cfg, mesh)
    times, coh = {}, None
    for users in (10_000, 1_000_000):
        store = build(users)
        # the sampler draw is O(active) under the default PRP sampler
        # (ISSUE 11) but still pays a one-time XLA compile per distinct
        # population shape; the population-independence claim under test
        # is about stage_cohort -- draw the schedule outside the timed
        # window (tests/test_sampling.py owns the draw-time bounds)
        us = superstep_user_schedule(HOST, 1, k, users, A)
        t0 = time.perf_counter()
        coh = eng.stage_cohort(store, us)
        times[users] = time.perf_counter() - t0
    # staging is population-independent (generous 5x bound: these are
    # ~100ms-scale timings on a shared CPU)
    assert times[1_000_000] < 5 * max(times[10_000], 0.05)
    p = model.init(jax.random.key(0))
    p, pend = eng.train_superstep(p, HOST, 1, k, cohort=coh)
    ms = pend.fetch()
    assert len(ms) == k and np.isfinite(ms[-1]["loss_sum"]).all()
    assert float(np.asarray(ms[-1]["n"]).sum()) > 0


# ---------------------------------------------------------------------------
# driver satellites: boundary pivot + loud conflicts + stream end-to-end
# ---------------------------------------------------------------------------

def _driver_cfg(tmp_path, **over):
    from heterofl_tpu import config as C

    cfg = C.default_cfg()
    cfg["control"] = C.parse_control_name("1_8_0.5_iid_fix_a1-b1_bn_1_1")
    cfg["data_name"] = "MNIST"
    cfg["model_name"] = "conv"
    cfg["synthetic"] = True
    cfg["synthetic_sizes"] = {"train": 80, "test": 40}
    cfg["output_dir"] = str(tmp_path)
    cfg["override"] = {"num_epochs": {"global": 4, "local": 1},
                       "conv": {"hidden_size": [4, 8]},
                       "batch_size": {"train": 10, "test": 20}, **over}
    return C.process_control(cfg)


def test_pivot_compares_boundary_eval_only(tmp_path):
    """ISSUE 6 satellite: with eval_interval < superstep_rounds a superstep
    logs SEVERAL fused evals before the checkpoint pivot reads the logger;
    each eval's test means must stand alone (K=1 resets per round), so the
    pivot sees the BOUNDARY round's eval -- not a mean blended over the
    whole superstep's evals."""
    from heterofl_tpu.entry.common import FedExperiment
    from heterofl_tpu.utils import Logger

    exp = FedExperiment(_driver_cfg(tmp_path, superstep_rounds=2,
                                    eval_interval=1), 0)

    def ev(epoch, acc):
        n = 40.0
        g = {"loss_sum": 2.0 * n, "score_sum": acc * n, "n": n}
        return {"epoch": epoch, "bn": {}, "local": dict(g), "global": g}

    ms = {nme: np.ones(4, np.float32) for nme in
          ("loss_sum", "score_sum", "n", "rate")}
    tag = {"kind": "superstep", "epoch0": 1, "k": 2, "dt": 0.1,
           "phases": {}, "lrs": [0.1, 0.1]}
    out = {"train": [ms, ms], "eval": [ev(1, 0.10), ev(2, 0.50)]}
    logger = Logger(str(tmp_path / "runs"))
    logger.safe(True)
    exp._log_superstep(logger, tag, out)
    logger.safe(False)
    # the mean (and the history snapshot the pivot reads) is the round-2
    # eval ALONE: 50%, not the 30% blend of rounds 1 and 2
    assert logger.mean["test/Global-Accuracy"] == pytest.approx(50.0)
    assert logger.history["test/Global-Accuracy"][-1] == pytest.approx(50.0)


def test_stream_driver_conflicts(tmp_path):
    """Streaming needs a mesh-native strategy, a valid mode string, and a
    synchronous metric fetch at superstep_rounds=1 (same silent
    best-checkpoint disable as fetch_every > K)."""
    from heterofl_tpu.entry.common import FedExperiment

    with pytest.raises(ValueError, match="mesh-native"):
        FedExperiment(_driver_cfg(tmp_path, client_store="stream",
                                  strategy="sliced"), 0)
    with pytest.raises(ValueError, match="client_store"):
        FedExperiment(_driver_cfg(tmp_path, client_store="mmap"), 0)
    with pytest.raises(ValueError, match="best-checkpoint|pivot"):
        FedExperiment(_driver_cfg(tmp_path, client_store="stream",
                                  metrics_fetch_every=2), 0)


@pytest.mark.slow
@pytest.mark.parametrize("strategy", ["masked", "grouped"])
def test_stream_driver_end_to_end_matches_eager(tmp_path, strategy):
    """The fed entry with client_store='stream' (prefetched cohorts) runs
    the full loop and reproduces the eager run's history and params
    exactly, for both engines."""
    import json as _json

    from heterofl_tpu.entry import train_classifier_fed

    def run(sub, client_store):
        ov = {"num_epochs": {"global": 4, "local": 1},
              "conv": {"hidden_size": [4, 8]},
              "batch_size": {"train": 10, "test": 20},
              "superstep_rounds": 2, "eval_interval": 2,
              "strategy": strategy, "client_store": client_store}
        argv = ["--control_name", "1_8_0.5_iid_fix_a1-b1-c1_bn_1_1",
                "--data_name", "MNIST", "--model_name", "conv",
                "--synthetic", "1",
                "--synthetic_sizes", _json.dumps({"train": 200, "test": 80}),
                "--output_dir", str(tmp_path / sub),
                "--override", _json.dumps(ov)]
        return train_classifier_fed.main(argv)

    r_e = run("eager", "eager")
    r_s = run("stream", "stream")
    he, hs = r_e[0]["logger"].history, r_s[0]["logger"].history
    for kk in ("test/Global-Accuracy", "test/Global-Loss", "train/Local-Loss"):
        np.testing.assert_array_equal(he[kk], hs[kk], err_msg=kk)
    for name in r_e[0]["params"]:
        np.testing.assert_array_equal(np.asarray(r_e[0]["params"][name]),
                                      np.asarray(r_s[0]["params"][name]),
                                      err_msg=name)
