"""Fused multi-round superstep (ISSUE 2): ``lax.scan`` over K federated
rounds in ONE jitted/donated program, for both engines.

The contract under test: a K-round superstep is BIT-IDENTICAL (params,
per-round metrics, PRNG stream) to K sequential dispatches consuming the
same streams -- sampling from ``fed.core.round_users``, rates from
``fed.core.round_rates``, per-round keys ``fold_in(base_key, epoch)``, LR
from the traced schedule.  For the masked engine the sequential baseline is
``train_round`` itself (the superstep scan body IS ``_round_core``); for
the grouped engine the fused program joins the level partials with a single
global psum where the sequential path psums per level, so the bit-exact
baseline is K dispatches of the fused program (``train_superstep(k=1)``)
and ``train_round`` agreement is pinned at association tolerance.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from heterofl_tpu import config as C
from heterofl_tpu.fed.core import round_rates, round_users
from heterofl_tpu.models import make_model
from heterofl_tpu.parallel import GroupedRoundEngine, RoundEngine, make_mesh, shard_client_data
from heterofl_tpu.utils.optim import make_scheduler, make_traced_lr_fn

from test_round import _vision_setup


HOST_KEY = jax.random.key(0)


def _lr_host(cfg, epoch):
    """The sequential baselines consume the traced schedule host-evaluated
    (f32), exactly what the superstep computes in-jit from the round index."""
    return float(np.asarray(make_traced_lr_fn(cfg)(jnp.int32(epoch))))


def _schedule(cfg, epoch0, k, num_active):
    return np.stack([
        np.asarray(round_users(jax.random.fold_in(HOST_KEY, epoch0 + r),
                               cfg["num_users"], num_active))
        for r in range(k)])


def _assert_rounds_equal(seq_ms, ss_ms, k):
    assert len(ss_ms) == k
    for r in range(k):
        for name in ("loss_sum", "score_sum", "n", "rate"):
            np.testing.assert_array_equal(
                np.asarray(seq_ms[r][name]), np.asarray(ss_ms[r][name]),
                err_msg=f"round {r} metric {name}")


# ---------------------------------------------------------------------------
# the traced LR schedule
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name,extra", [
    ("None", {}),
    ("StepLR", {"step_size": 30}),
    ("MultiStepLR", {"milestones": [100, 150]}),
    ("ExponentialLR", {}),
    ("CosineAnnealingLR", {"min_lr": 1e-4}),
])
def test_traced_lr_fn_matches_host_scheduler(name, extra):
    cfg = {"scheduler_name": name, "lr": 0.1, "factor": 0.1, "step_size": 1,
           "milestones": [100], "num_epochs": {"global": 400}, **extra}
    host = make_scheduler(cfg)
    traced = jax.jit(make_traced_lr_fn(cfg))
    for e in (1, 2, 50, 99, 100, 101, 150, 151, 399, 400):
        # f32 resolution: the traced fn computes pow/cos in f32 while the
        # host schedule is f64 (then staged to an f32 device scalar anyway)
        np.testing.assert_allclose(float(np.asarray(traced(jnp.int32(e)))),
                                   host(e), rtol=1e-4, err_msg=f"{name}@{e}")


def test_traced_lr_fn_rejects_plateau():
    cfg = {"scheduler_name": "ReduceLROnPlateau", "lr": 0.1}
    with pytest.raises(ValueError, match="superstep"):
        make_traced_lr_fn(cfg)


# ---------------------------------------------------------------------------
# masked engine: superstep == K sequential train_round dispatches, bitwise
# ---------------------------------------------------------------------------

def _masked_sequential(cfg, model, mesh, data, epoch0, k, num_active):
    eng = RoundEngine(model, cfg, mesh)
    p = model.init(jax.random.key(0))
    seq_ms = []
    for r in range(k):
        e = epoch0 + r
        key = jax.random.fold_in(HOST_KEY, e)
        uidx = np.asarray(round_users(key, cfg["num_users"], num_active))
        p, ms = eng.train_round(p, key, _lr_host(cfg, e), uidx, data)
        seq_ms.append({n: np.asarray(v) for n, v in ms.items()})
    return p, seq_ms


def test_superstep_masked_replicated_bit_identical():
    """Replicated placement: sampling, rates and the LR schedule all run
    in-jit inside the scan, and the K-round superstep reproduces K
    sequential train_round dispatches bit for bit."""
    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    mesh = make_mesh(4, 1)
    k, epoch0, A = 3, 1, 4
    p_seq, seq_ms = _masked_sequential(cfg, model, mesh, data, epoch0, k, A)

    eng = RoundEngine(model, cfg, mesh)
    p = model.init(jax.random.key(0))
    p, pending = eng.train_superstep(p, HOST_KEY, epoch0, k, data, num_active=A)
    ss_ms = pending.fetch()
    for name in p_seq:
        np.testing.assert_array_equal(np.asarray(p_seq[name]), np.asarray(p[name]),
                                      err_msg=name)
    _assert_rounds_equal(seq_ms, ss_ms, k)


@pytest.mark.slow
def test_superstep_masked_sharded_bit_identical():
    """Sharded placement: the slot->owner packing comes from a host-packed
    [k, A] schedule drawn from the SAME stream; rounds are still bitwise
    equal to sequential dispatches."""
    cfg, ds, data = _vision_setup()
    cfg = dict(cfg, data_placement="sharded")
    model = make_model(cfg)
    mesh = make_mesh(4, 1)
    data_s = shard_client_data(mesh, tuple(np.asarray(d) for d in data))
    k, epoch0, A = 3, 1, 4
    sched = _schedule(cfg, epoch0, k, A)

    eng1 = RoundEngine(model, cfg, mesh)
    p1 = model.init(jax.random.key(0))
    seq_ms = []
    for r in range(k):
        e = epoch0 + r
        key = jax.random.fold_in(HOST_KEY, e)
        p1, ms = eng1.train_round(p1, key, _lr_host(cfg, e), sched[r], data_s)
        seq_ms.append({n: np.asarray(v) for n, v in ms.items()})

    eng2 = RoundEngine(model, cfg, mesh)
    p2 = model.init(jax.random.key(0))
    p2, pending = eng2.train_superstep(p2, HOST_KEY, epoch0, k, data_s,
                                       user_schedule=sched)
    ss_ms = pending.fetch()
    for name in p1:
        np.testing.assert_array_equal(np.asarray(p1[name]), np.asarray(p2[name]),
                                      err_msg=name)
    # sequential slot counts can differ per round; compare the ACTIVE slots'
    # totals (slot order is owner-packed identically here)
    for r in range(k):
        assert float(seq_ms[r]["n"].sum()) == float(np.asarray(ss_ms[r]["n"]).sum())


@pytest.mark.slow
def test_superstep_masked_dynamic_and_failure_bit_identical():
    """Dynamic rate re-roll AND failure injection inside the scan consume
    the sequential per-round streams (fold_in(key, 7)/98)."""
    cfg, ds, data = _vision_setup(control="1_8_0.5_iid_dynamic_a1-e1_bn_1_1")
    cfg = dict(cfg, client_failure_rate=0.5)
    model = make_model(cfg)
    mesh = make_mesh(2, 1)
    k, epoch0, A = 2, 5, 4
    p_seq, seq_ms = _masked_sequential(cfg, model, mesh, data, epoch0, k, A)

    eng = RoundEngine(model, cfg, mesh)
    p = model.init(jax.random.key(0))
    p, pending = eng.train_superstep(p, HOST_KEY, epoch0, k, data, num_active=A)
    ss_ms = pending.fetch()
    for name in p_seq:
        np.testing.assert_array_equal(np.asarray(p_seq[name]), np.asarray(p[name]),
                                      err_msg=name)
    _assert_rounds_equal(seq_ms, ss_ms, k)
    rates = np.concatenate([np.asarray(m["rate"]) for m in ss_ms])
    assert set(np.unique(rates).tolist()) <= {0.0, 1.0, 0.0625}


@pytest.mark.slow
def test_superstep_masked_lm_matches_sequential():
    """LM path: XLA fuses the attention chain differently inside the scan
    body than in the standalone round program (measured ~5e-10 abs drift on
    CPU), so the LM pin is near-exact rather than bitwise; a semantic bug
    (wrong key/round/slot) would show at O(1e-2)."""
    from test_round import _lm_setup

    cfg, data = _lm_setup()
    model = make_model(cfg)
    mesh = make_mesh(2, 1)
    k, epoch0, A = 2, 1, 4
    p_seq, seq_ms = _masked_sequential(cfg, model, mesh, data, epoch0, k, A)
    eng = RoundEngine(model, cfg, mesh)
    p = model.init(jax.random.key(0))
    p, pending = eng.train_superstep(p, HOST_KEY, epoch0, k, data, num_active=A)
    ss_ms = pending.fetch()
    for name in p_seq:
        np.testing.assert_allclose(np.asarray(p_seq[name]), np.asarray(p[name]),
                                   rtol=1e-5, atol=1e-7, err_msg=name)
    for r in range(k):
        np.testing.assert_array_equal(seq_ms[r]["n"], np.asarray(ss_ms[r]["n"]))
        np.testing.assert_array_equal(seq_ms[r]["rate"], np.asarray(ss_ms[r]["rate"]))
        np.testing.assert_allclose(seq_ms[r]["loss_sum"],
                                   np.asarray(ss_ms[r]["loss_sum"]),
                                   rtol=1e-5, atol=1e-7)


# ---------------------------------------------------------------------------
# grouped engine: fused per-level programs + combine, scanned
# ---------------------------------------------------------------------------

def _grouped_schedules(cfg, epoch0, k, num_active):
    users = _schedule(cfg, epoch0, k, num_active)
    if cfg["model_split_mode"] == "dynamic":
        rates = np.stack([
            np.asarray(round_rates(jax.random.fold_in(HOST_KEY, epoch0 + r),
                                   cfg, jnp.asarray(users[r])))
            for r in range(k)])
    else:
        rates = np.asarray(cfg["model_rate"], np.float32)[users]
    return users, rates


@pytest.mark.parametrize("placement", ["span", "slices"])
def test_superstep_grouped_bit_identical_to_sequential_fused(placement):
    """K scanned rounds == K sequential dispatches of the fused round
    program (train_superstep(k=1)), bit for bit, both layouts."""
    cfg, ds, data = _vision_setup()
    cfg = dict(cfg, level_placement=placement)
    model = make_model(cfg)
    k, epoch0, A = 2, 1, 4
    users, rates = _grouped_schedules(cfg, epoch0, k, A)

    g1 = GroupedRoundEngine(cfg, make_mesh(8, 1))
    p1 = model.init(jax.random.key(0))
    seq_ms = []
    for r in range(k):
        p1, pend = g1.train_superstep(p1, HOST_KEY, epoch0 + r, 1,
                                      users[r:r + 1], rates[r:r + 1], data)
        seq_ms.extend(pend.fetch())

    g2 = GroupedRoundEngine(cfg, make_mesh(8, 1))
    p2 = model.init(jax.random.key(0))
    p2, pend = g2.train_superstep(p2, HOST_KEY, epoch0, k, users, rates, data)
    ss_ms = pend.fetch()
    for name in p1:
        np.testing.assert_array_equal(np.asarray(p1[name]), np.asarray(p2[name]),
                                      err_msg=name)
    _assert_rounds_equal(seq_ms, ss_ms, k)


@pytest.mark.slow
@pytest.mark.parametrize("placement", ["span", "slices"])
def test_superstep_grouped_matches_train_round(placement):
    """The fused program agrees with the per-level dispatch path
    (train_round) at association tolerance: identical per-client math, one
    global psum instead of per-level psums.  Metrics n/rate are exact."""
    cfg, ds, data = _vision_setup()
    cfg = dict(cfg, level_placement=placement)
    model = make_model(cfg)
    k, epoch0, A = 2, 1, 4
    users, rates = _grouped_schedules(cfg, epoch0, k, A)

    g1 = GroupedRoundEngine(cfg, make_mesh(8, 1))
    p1 = model.init(jax.random.key(0))
    seq_ms = []
    for r in range(k):
        e = epoch0 + r
        key = jax.random.fold_in(HOST_KEY, e)
        p1, ms = g1.train_round(p1, users[r], rates[r], data, _lr_host(cfg, e), key)
        seq_ms.append(ms)

    g2 = GroupedRoundEngine(cfg, make_mesh(8, 1))
    p2 = model.init(jax.random.key(0))
    p2, pend = g2.train_superstep(p2, HOST_KEY, epoch0, k, users, rates, data)
    ss_ms = pend.fetch()
    for name in p1:
        np.testing.assert_allclose(np.asarray(p1[name]), np.asarray(p2[name]),
                                   rtol=1e-5, atol=1e-6, err_msg=name)
    for r in range(k):
        np.testing.assert_array_equal(seq_ms[r]["n"], ss_ms[r]["n"])
        np.testing.assert_array_equal(seq_ms[r]["rate"], ss_ms[r]["rate"])
        np.testing.assert_allclose(seq_ms[r]["loss_sum"], ss_ms[r]["loss_sum"],
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_superstep_grouped_dynamic_mode():
    """Dynamic mode: host-drawn rate schedules (round_rates stream) group
    the levels; the superstep trains and every active slot reports a
    table rate."""
    cfg, ds, data = _vision_setup(control="1_8_0.5_iid_dynamic_a1-b1-c1-d1-e1_bn_1_1")
    model = make_model(cfg)
    k, epoch0, A = 2, 3, 4
    users, rates = _grouped_schedules(cfg, epoch0, k, A)
    g = GroupedRoundEngine(cfg, make_mesh(8, 1))
    p = model.init(jax.random.key(0))
    p, pend = g.train_superstep(p, HOST_KEY, epoch0, k, users, rates, data)
    ss_ms = pend.fetch()
    for r in range(k):
        np.testing.assert_array_equal(ss_ms[r]["rate"], rates[r])
        assert (ss_ms[r]["n"] > 0).all()
        assert np.isfinite(ss_ms[r]["loss_sum"]).all()


def test_grouped_fused_slices_keeps_slices_with_data_axis():
    """ISSUE 17 lifted the old data-axis refusal: the fused slices program
    is now expressed with GSPMD NamedSharding placement (not shard_map), so
    the per-level collectives stay uniform per device row and a data axis
    no longer forces the span fallback."""
    # 3 levels so a 4-row clients axis still admits the slices partition
    cfg, ds, data = _vision_setup(control="1_8_0.5_iid_fix_a1-b1-c1_bn_1_1")
    cfg = dict(cfg, level_placement="slices")
    g = GroupedRoundEngine(cfg, make_mesh(4, 2))
    assert g.level_placement == "slices"
    mode, los = g._fused_layout()
    assert mode == "slices" and los[0] == 0
    # and without the data axis, same partition
    g2 = GroupedRoundEngine(cfg, make_mesh(4, 1))
    mode2, los2 = g2._fused_layout()
    assert mode2 == "slices" and los2[0] == 0


# ---------------------------------------------------------------------------
# driver-level config validation + end-to-end superstep loop
# ---------------------------------------------------------------------------

def _driver_cfg(tmp_path, **over):
    cfg = C.default_cfg()
    cfg["control"] = C.parse_control_name("1_8_0.5_iid_fix_a1-b1_bn_1_1")
    cfg["data_name"] = "MNIST"
    cfg["model_name"] = "conv"
    cfg["synthetic"] = True
    cfg["synthetic_sizes"] = {"train": 80, "test": 40}
    cfg["output_dir"] = str(tmp_path)
    cfg["override"] = {"num_epochs": {"global": 2, "local": 1},
                       "conv": {"hidden_size": [4, 8]},
                       "batch_size": {"train": 10, "test": 20}, **over}
    return C.process_control(cfg)


def test_driver_superstep_config_conflicts(tmp_path):
    """The ISSUE 4 relaxation: config combinations the eval-fused superstep
    expresses in-jit are accepted; only genuinely conflicting settings stay
    loud errors -- one case per surviving branch, one per relaxation."""
    from heterofl_tpu.entry.common import FedExperiment

    # still conflicting: a fetch batch that is not whole supersteps
    with pytest.raises(ValueError, match="metrics_fetch_every"):
        FedExperiment(_driver_cfg(tmp_path, superstep_rounds=4,
                                  metrics_fetch_every=3, eval_interval=4), 0)
    # still conflicting: the host-orchestrated sliced engine
    with pytest.raises(ValueError, match="mesh-native"):
        FedExperiment(_driver_cfg(tmp_path, superstep_rounds=2,
                                  eval_interval=2, strategy="sliced"), 0)
    # still conflicting: Plateau with an eval MID-superstep (an LR step
    # inside the compiled scan)
    with pytest.raises(ValueError, match="ReduceLROnPlateau"):
        FedExperiment(_driver_cfg(tmp_path, superstep_rounds=4,
                                  eval_interval=2,
                                  scheduler_name="ReduceLROnPlateau"), 0)
    # still conflicting: a metric feed deferred past the superstep that
    # needs it -- refused for ANY scheduler at config resolution now
    # (ISSUE 18 promotion subsumes the Plateau-specific driver check)
    with pytest.raises(ValueError, match="metrics_fetch_every"):
        FedExperiment(_driver_cfg(tmp_path, superstep_rounds=2,
                                  eval_interval=2, metrics_fetch_every=4,
                                  scheduler_name="ReduceLROnPlateau"), 0)
    # RELAXED: eval_interval no longer needs to divide into K -- the eval
    # mask is scan structure now, not a clamp
    FedExperiment(_driver_cfg(tmp_path, superstep_rounds=4, eval_interval=6), 0)
    FedExperiment(_driver_cfg(tmp_path, superstep_rounds=4, eval_interval=3), 0)
    # RELAXED: Plateau runs when evals land on superstep boundaries (the LR
    # is a staged per-superstep scalar, stepped on the fused eval metrics)
    FedExperiment(_driver_cfg(tmp_path, superstep_rounds=2, eval_interval=2,
                              scheduler_name="ReduceLROnPlateau"), 0)
    FedExperiment(_driver_cfg(tmp_path, superstep_rounds=2, eval_interval=4,
                              scheduler_name="ReduceLROnPlateau"), 0)
    # metrics_fetch_every == K stays the unified per-superstep fetch batch
    FedExperiment(_driver_cfg(tmp_path, superstep_rounds=2, eval_interval=2,
                              metrics_fetch_every=2), 0)
    # TIGHTENED (ISSUE 6 satellite): deferring WHOLE supersteps made
    # pivot_fresh never true -- best-checkpoint tracking silently stopped;
    # now a loud config error like every comparable knob conflict
    with pytest.raises(ValueError, match="best-checkpoint"):
        FedExperiment(_driver_cfg(tmp_path, superstep_rounds=2, eval_interval=2,
                                  metrics_fetch_every=4), 0)


@pytest.mark.slow
@pytest.mark.parametrize("strategy", ["masked", "grouped"])
def test_driver_superstep_end_to_end(tmp_path, strategy):
    """The fed entry with superstep_rounds=2 runs the full loop (train ->
    eval -> checkpoint on superstep boundaries) for both engines."""
    from heterofl_tpu.entry import train_classifier_fed

    # 5 rounds with K=2 exercise the clamped tail: supersteps of 2, 2, 1
    # (the k=1 tail still runs through the superstep path, one stream),
    # evals at rounds 2, 4 and the final round 5
    ov = {"num_epochs": {"global": 5, "local": 1},
          "conv": {"hidden_size": [8, 16]},
          "batch_size": {"train": 10, "test": 20},
          "superstep_rounds": 2, "eval_interval": 2, "strategy": strategy}
    argv = ["--control_name", "1_8_0.5_iid_fix_a1-b1-c1_bn_1_1",
            "--data_name", "MNIST", "--model_name", "conv",
            "--synthetic", "1",
            "--synthetic_sizes", json.dumps({"train": 200, "test": 80}),
            "--output_dir", str(tmp_path),
            "--override", json.dumps(ov)]
    res = train_classifier_fed.main(argv)
    hist = res[0]["logger"].history
    assert len(hist["test/Global-Accuracy"]) == 3
    assert len(hist["train/Local-Loss"]) == 3  # one mean per eval window
    assert np.isfinite(hist["train/Local-Loss"]).all()
