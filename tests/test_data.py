import os

import numpy as np
import pytest

from heterofl_tpu.data import (
    batchify,
    bptt_windows,
    fetch_dataset,
    iid,
    label_split_masks,
    non_iid,
    split_dataset,
    stack_client_shards,
    Vocab,
)


def test_synthetic_vision_deterministic():
    d1 = fetch_dataset("CIFAR10", synthetic=True, seed=3)
    d2 = fetch_dataset("CIFAR10", synthetic=True, seed=3)
    assert np.array_equal(d1["train"].data, d2["train"].data)
    assert d1["train"].data.dtype == np.uint8
    assert d1["train"].data.shape[1:] == (32, 32, 3)
    assert d1["train"].classes_size == 10


def test_synthetic_lm():
    d = fetch_dataset("WikiText2", synthetic=True)
    assert d["train"].token.ndim == 1
    assert len(d["train"].vocab) == 512


def test_iid_partition_properties(rng):
    ds = fetch_dataset("MNIST", synthetic=True, seed=0)["train"]
    num_users = 20
    data_split, label_split = iid(ds, num_users, rng)
    sizes = {len(v) for v in data_split.values()}
    assert sizes == {len(ds) // num_users}
    all_idx = np.concatenate([data_split[i] for i in range(num_users)])
    assert len(np.unique(all_idx)) == len(all_idx)  # disjoint
    for i in range(num_users):
        got = set(np.asarray(ds.target)[data_split[i]].tolist())
        assert got == set(label_split[i])


def test_non_iid_partition_properties(rng):
    ds = fetch_dataset("MNIST", synthetic=True, seed=0)["train"]
    num_users, shard_per_user = 20, 2
    data_split, label_split = non_iid(ds, num_users, rng, shard_per_user, 10)
    # every user sees at most shard_per_user distinct labels
    for i in range(num_users):
        labels = set(np.asarray(ds.target)[data_split[i]].tolist())
        assert labels == set(label_split[i])
        assert len(labels) <= shard_per_user
    all_idx = np.concatenate([data_split[i] for i in range(num_users)])
    assert len(np.unique(all_idx)) == len(all_idx)
    # NOTE: full coverage is NOT guaranteed — users whose label row contains
    # duplicates draw fewer shards (np.unique in ref data.py:104-105), leaving
    # shards unassigned. We only require a large majority assigned.
    assert len(all_idx) >= 0.7 * len(ds)


def test_non_iid_test_reuses_label_split(rng):
    ds = fetch_dataset("MNIST", synthetic=True, seed=0)
    data_split, label_split = split_dataset(ds, 20, "non-iid-2", rng)
    for i in range(20):
        test_labels = set(np.asarray(ds["test"].target)[data_split["test"][i]].tolist())
        assert test_labels <= set(label_split[i]) | test_labels  # same shards drawn from same label sets
        assert test_labels == set(np.asarray(ds["test"].target)[data_split["test"][i]].tolist())


def test_batchify_and_windows():
    token = np.arange(1003)
    rows = batchify(token, 10)
    assert rows.shape == (10, 100)
    assert rows[1, 0] == 100
    wins = bptt_windows(rows, 64)
    assert wins[0].shape == (10, 64) and wins[-1].shape == (10, 36)
    assert np.array_equal(np.concatenate(wins, axis=1), rows)


def test_stack_client_shards_pads_and_masks(rng):
    data = np.arange(40).reshape(20, 2)
    target = np.arange(20)
    split = {0: [0, 1, 2], 1: [3, 4]}
    x, y, m = stack_client_shards(data, target, split, [0, 1])
    assert x.shape == (2, 3, 2) and y.shape == (2, 3)
    assert m.tolist() == [[1, 1, 1], [1, 1, 0]]
    assert y[1].tolist() == [3, 4, 3]  # padded by wraparound


def test_label_split_masks():
    m = label_split_masks({0: [1, 3], 1: [0]}, 2, 5)
    assert m.tolist() == [[0, 1, 0, 1, 0], [1, 0, 0, 0, 0]]


def test_vocab_semantics():
    v = Vocab()
    v.add("hello")
    assert v["hello"] == 2 and v[2] == "hello"
    assert v["missing"] == 0 and v[99] == "<ukn>"
    assert "hello" in v and 2 in v and 99 not in v
    assert len(v) == 3


def test_emnist_synthetic_and_idx(tmp_path):
    import struct

    d = fetch_dataset("EMNIST", synthetic=True)
    assert d["train"].classes_size == 47
    # on-disk idx path
    from heterofl_tpu.data.datasets import _load_emnist
    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 255, (30, 28, 28), dtype=np.uint8)
    labels = rng.integers(1, 27, 30, dtype=np.uint8)  # letters: 1-indexed

    def write_idx(path, arr):
        with open(path, "wb") as f:
            f.write(struct.pack(">BBBB", 0, 0, 0x08, arr.ndim))
            for dim in arr.shape:
                f.write(struct.pack(">I", dim))
            f.write(arr.tobytes())

    write_idx(str(tmp_path / "emnist-letters-train-images-idx3-ubyte"), imgs)
    write_idx(str(tmp_path / "emnist-letters-train-labels-idx1-ubyte"), labels)
    ds = _load_emnist(str(tmp_path), "train", "letters")
    assert ds.classes_size == 26
    assert ds.target.min() >= 0 and ds.target.max() <= 25


def test_image_folder_and_omniglot(tmp_path):
    from PIL import Image

    from heterofl_tpu.data.datasets import _load_image_folder

    rng = np.random.default_rng(0)
    for cls in ("cat", "dog"):
        d = tmp_path / "train" / cls
        os.makedirs(d)
        for i in range(3):
            Image.fromarray(rng.integers(0, 255, (16, 16, 3), dtype=np.uint8)).save(d / f"{i}.png")
    ds = _load_image_folder(str(tmp_path), "train", "ImageFolder")
    assert ds.classes_size == 2 and len(ds) == 6
    assert ds.data.shape == (6, 16, 16, 3)
    # omniglot layout: ONE class enumeration over background+evaluation,
    # per-example split by drawing index (<=10 train, >10 test)
    og = tmp_path / "OG"
    for sub, alpha in (("images_background", "Greek"), ("images_evaluation", "Futurama")):
        for ch in ("c1", "c2"):
            d = og / sub / alpha / ch
            os.makedirs(d)
            for draw in (1, 11):
                Image.fromarray(rng.integers(0, 255, (10, 10), dtype=np.uint8)).save(
                    d / f"{ch}_{draw:02d}.png")
    tr = _load_image_folder(str(og), "train", "Omniglot")
    te = _load_image_folder(str(og), "test", "Omniglot")
    assert tr.classes_size == te.classes_size == 4  # shared class set
    assert len(tr) == 4 and len(te) == 4  # one drawing each side per character
    assert set(tr.target.tolist()) == set(te.target.tolist()) == {0, 1, 2, 3}


def test_fetch_folder_dataset_missing_raises(tmp_path):
    import pytest as _pytest

    with _pytest.raises(FileNotFoundError):
        fetch_dataset("Omniglot", data_dir=str(tmp_path))


def test_lm_file_parsing(tmp_path):
    """On-disk WikiText-format token files parse with train-built vocab and
    <ukn> fallback for OOV test tokens (ref lm.py:202-219)."""
    from heterofl_tpu.data.datasets import _load_lm, _VOCAB_CACHE

    d = tmp_path / "WikiText2" / "wikitext-2"
    os.makedirs(d)
    (d / "wiki.train.tokens").write_text("the cat sat\nthe mat\n")
    (d / "wiki.test.tokens").write_text("the dog sat\n")
    _VOCAB_CACHE.clear()
    tr = _load_lm(str(tmp_path / "WikiText2"), "train", "WikiText2")
    te = _load_lm(str(tmp_path / "WikiText2"), "test", "WikiText2")
    # vocab: <ukn>, <eos>, the, cat, sat, mat
    assert len(tr.vocab) == 6
    assert tr.token.tolist() == [2, 3, 4, 1, 2, 5, 1]  # the cat sat <eos> the mat <eos>
    # 'dog' is OOV -> <ukn>=0
    assert te.token.tolist() == [2, 0, 4, 1]
