"""Pod-scale fused superstep (ISSUE 17): the bitwise acceptance gate on a
REAL 2-process ``jax.distributed`` CPU mesh, the host-aligned slices
partition logic, the per-process shard checkpoint format, and the
analytic per-link ICI-vs-DCN split.

The slow half spawns distributed subprocesses through
``heterofl_tpu.parallel.pod`` (the same engine ``bench.py BENCH_POD=1``
and the CI smoke step drive); the fast half unit-tests the pure pieces:
``link_split`` values, shard-blocks assembly + its corruption modes, the
sharded ``copy_best`` mirror, and the multi-host resume guard's
single-process degenerate case.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from heterofl_tpu.staticcheck.wire import link_split, ring_allreduce_bytes
from heterofl_tpu.utils.checkpoint import (
    BLOCKS_KEY, SHARD_SET_KEY, CheckpointCorruptError, checkpoint_path,
    copy_best, dense_from_blocks, is_shard_marker, load_checkpoint_sharded,
    save_checkpoint, save_checkpoint_sharded, shard_path)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# fast: analytic per-link wire split (bench.py's extra.wire record)
# ---------------------------------------------------------------------------

def test_link_split_two_process_blocks():
    """8 participants in 2 host blocks: a ring has 8 links of
    2*(7/8)*payload each; exactly 2 cross a process boundary (DCN)."""
    s = link_split(1000, 8, 2)
    per_link = ring_allreduce_bytes(1000, 8)
    assert per_link == 1750
    assert s["bytes_per_link"] == per_link
    assert s["dcn_links"] == 2 and s["ici_links"] == 6
    assert s["dcn_bytes_total"] == 2 * per_link
    assert s["ici_bytes_total"] == 6 * per_link


def test_link_split_single_process_all_ici():
    s = link_split(1000, 8, 1)
    assert s["dcn_links"] == 0 and s["dcn_bytes_total"] == 0
    assert s["ici_links"] == 8
    # a single participant reduces locally: no links at all
    s1 = link_split(1000, 1, 1)
    assert s1["bytes_per_link"] == 0
    assert s1["dcn_links"] == 0 and s1["ici_links"] == 0


# ---------------------------------------------------------------------------
# fast: shard-blocks checkpoint format (no distributed runtime needed --
# the format is plain files + markers; the collective write itself is
# exercised by the slow 2-process tests below)
# ---------------------------------------------------------------------------

def _fake_sharded_ckpt(path, stamp="e3"):
    """Hand-craft the on-disk layout save_checkpoint_sharded produces from
    a 2-process run: two shard files + a header naming them."""
    full = np.arange(8, dtype=np.float32)
    blocks = [{"/resid": {((0, 4),): full[:4]}},
              {"/resid": {((4, 8),): full[4:]}}]
    for i in (0, 1):
        save_checkpoint(shard_path(path, i, 2),
                        {"stamp": stamp, "process": i, "blocks": blocks[i]})
    header = {
        "epoch": 3,
        "resid": {BLOCKS_KEY: True, "shape": (8,), "dtype": "float32",
                  "key": "/resid"},
        SHARD_SET_KEY: {"count": 2, "stamp": stamp,
                        "files": [os.path.basename(shard_path(path, i, 2))
                                  for i in (0, 1)]},
    }
    save_checkpoint(path, header)
    return full


def test_sharded_checkpoint_merges_blocks(tmp_path):
    ck = str(tmp_path / "model" / "c.pkl")
    full = _fake_sharded_ckpt(ck)
    blob = load_checkpoint_sharded(ck)
    assert blob["epoch"] == 3
    assert is_shard_marker(blob["resid"])
    np.testing.assert_array_equal(dense_from_blocks(blob["resid"]), full)


def test_sharded_checkpoint_stamp_mismatch_refused(tmp_path):
    """A torn multi-file rotation (shard from another generation) must
    fail verification, not silently mix generations."""
    ck = str(tmp_path / "model" / "c.pkl")
    full = _fake_sharded_ckpt(ck)
    save_checkpoint(shard_path(ck, 1, 2),
                    {"stamp": "e99", "process": 1,
                     "blocks": {"/resid": {((4, 8),): full[4:]}}})
    with pytest.raises(CheckpointCorruptError, match="stamp"):
        load_checkpoint_sharded(ck)


def test_sharded_checkpoint_missing_shard_refused(tmp_path):
    ck = str(tmp_path / "model" / "c.pkl")
    _fake_sharded_ckpt(ck)
    os.remove(shard_path(ck, 1, 2))
    with pytest.raises(CheckpointCorruptError, match="missing"):
        load_checkpoint_sharded(ck)


def test_dense_from_blocks_coverage_hole_refused():
    marker = {BLOCKS_KEY: True, "shape": (8,), "dtype": "float32",
              "blocks": {((0, 4),): np.zeros(4, np.float32)}}
    with pytest.raises(CheckpointCorruptError, match="coverage holes"):
        dense_from_blocks(marker)


def test_copy_best_mirrors_shard_files(tmp_path):
    """copy_best on a sharded live checkpoint mirrors every shard under
    the best tag's names and rewrites the header's shard set."""
    out = str(tmp_path)
    ck = checkpoint_path(out, "probe", "checkpoint")
    full = _fake_sharded_ckpt(ck)
    copy_best(out, "probe")
    best = checkpoint_path(out, "probe", "best")
    blob = load_checkpoint_sharded(best)
    np.testing.assert_array_equal(dense_from_blocks(blob["resid"]), full)
    # the mirrored shard files exist under the best names; rotating the
    # live shards can no longer tear the best blob
    assert os.path.exists(shard_path(best, 0, 2))
    assert os.path.exists(shard_path(best, 1, 2))


def test_sharded_save_degenerates_to_plain_single_process(tmp_path):
    """A fully-addressable blob on a single-process runtime writes the
    ordinary plain checkpoint -- no shard files, loadable by both
    readers."""
    ck = str(tmp_path / "model" / "c.pkl")
    blob = {"epoch": 7, "params": {"w": np.ones((2, 3), np.float32)}}
    save_checkpoint_sharded(ck, blob)
    assert not os.path.exists(shard_path(ck, 0, 1))
    loaded = load_checkpoint_sharded(ck)
    assert loaded["epoch"] == 7
    np.testing.assert_array_equal(loaded["params"]["w"], blob["params"]["w"])


def test_check_multihost_resume_single_process():
    from heterofl_tpu.entry.common import check_multihost_resume

    assert check_multihost_resume({"epoch": 9}) == 9
    assert check_multihost_resume(None) == 0


# ---------------------------------------------------------------------------
# slow: the real 2-process distributed gates
# ---------------------------------------------------------------------------

def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _pod_env(n_processes, local_devices):
    env = dict(os.environ)
    for v in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE",
              "AXON_LOOPBACK_RELAY", "AXON_POOL_SVC_OVERRIDE"):
        env.pop(v, None)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": f"--xla_force_host_platform_device_count={local_devices}",
        "PYTHONPATH": REPO,
        "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{_free_port()}",
        "JAX_NUM_PROCESSES": str(n_processes),
    })
    return env


@pytest.mark.slow
def test_pod_two_process_bitwise_and_dcn():
    """THE acceptance gate: a 2-process CPU-mesh fused grouped-slices
    superstep produces params AND per-round metrics bit-identical to the
    single-process run (gloo fixes the reduction association by global
    device rank on both sides), with the REAL process grid classifying
    the clients axis as DCN, the traced program carrying exactly one
    dense reduction per training round, zero reshards, and the sharded
    checkpoint round-tripping."""
    import tempfile

    from heterofl_tpu.parallel.pod import bitwise_match, run_pod_probe

    base = tempfile.mkdtemp(prefix="test_pod_")
    ref_dir = os.path.join(base, "ref")
    pod_dir = os.path.join(base, "pod")
    # align=2 pins the single-process reference to the SAME host-aligned
    # level partition the 2-process mesh forces
    ref = run_pod_probe(ref_dir, n_processes=1, local_devices=8, k=2,
                        align=2)
    pod = run_pod_probe(pod_dir, n_processes=2, local_devices=4, k=2)
    assert ref[0]["slices"] == pod[0]["slices"], "level partitions differ"
    assert ref[0]["dcn_axes"] == []  # one process: nothing crosses hosts
    for r in pod:
        assert r["processes"] == 2 and r["devices"] == 8
        # dcn_axes_of on a REAL 2-process mesh (ISSUE 17 satellite): the
        # clients axis spans both processes
        assert r["dcn_axes"] == ["clients"]
        assert r["dcn_one_reduction"], r["wire"]
        assert r["wire"]["dcn_bytes"] == r["wire"]["train_bytes_per_round"]
        assert r["wire"]["other_bytes"] == 0
        assert r["reshards"] == 0
        assert r["sharded_ckpt_ok"]
    match = bitwise_match(pod_dir, ref_dir)
    assert match["match"], match["mismatches"][:20]


_RESUME_CHILD = r"""
import os, sys
import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from heterofl_tpu.parallel.mesh import initialize_distributed, make_mesh
from heterofl_tpu.parallel.staging import commit_global
from heterofl_tpu.utils.checkpoint import (dense_from_blocks, is_shard_marker,
                                           load_checkpoint_sharded,
                                           save_checkpoint_sharded, shard_path)
from heterofl_tpu.entry.common import check_multihost_resume

initialize_distributed()
pid, n = jax.process_index(), jax.process_count()
assert n == 2, n
out_dir = sys.argv[1]
mesh = make_mesh(len(jax.devices()), 1)
C = mesh.shape["clients"]
resid_host = np.arange(C * 3, dtype=np.float32).reshape(C, 3)
resid = commit_global(resid_host, NamedSharding(mesh, P("clients")))
ck = os.path.join(out_dir, "model", "probe_checkpoint.pkl")
save_checkpoint_sharded(ck, {"epoch": 5, "resid": resid})
# the collective write left both processes' shard files on the SHARED
# filesystem -- every host can reassemble the full state
assert os.path.exists(shard_path(ck, 0, 2)), "shard 0 missing"
assert os.path.exists(shard_path(ck, 1, 2)), "shard 1 missing"
blob = load_checkpoint_sharded(ck)
assert blob["epoch"] == 5
assert is_shard_marker(blob["resid"])
np.testing.assert_array_equal(dense_from_blocks(blob["resid"]), resid_host)
assert check_multihost_resume(blob) == 5
# divergence: a host resuming from a LOCAL (empty) output_dir must refuse
# loudly before any training dispatch (both processes join the broadcast)
err = None
try:
    check_multihost_resume(blob if pid == 0 else None)
except RuntimeError as e:
    err = str(e)
if pid == 0:
    assert err is None, err
else:
    assert err and "shared filesystem" in err, err
print("POD_RESUME_OK")
"""


@pytest.mark.slow
def test_multihost_resume_shared_filesystem(tmp_path):
    """2-process sharded save -> shared-fs reload -> agreed resume epoch;
    and the local-dir divergence raises on the straggler host."""
    env = _pod_env(2, 4)
    procs = []
    for i in (0, 1):
        e = dict(env)
        e["JAX_PROCESS_ID"] = str(i)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _RESUME_CHILD, str(tmp_path)], env=e,
            text=True, stdout=subprocess.PIPE, stderr=subprocess.PIPE))
    for i, pr in enumerate(procs):
        try:
            so, se = pr.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for p2 in procs:
                p2.kill()
            raise
        assert pr.returncode == 0, f"process {i}:\n{se[-3000:]}"
        assert "POD_RESUME_OK" in so
