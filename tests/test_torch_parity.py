"""Numerical parity against the ACTUAL reference implementation.

Loads this framework's parameters into the reference's PyTorch models
(mounted read-only at /root/reference -- imported, never copied) and compares
forward outputs and losses on identical batches.  This pins the model
semantics (conv/BN-sBN/Scaler/masked-CE, width-sliced sub-models) to the
reference at the numerical level, not just by reimplementation reading.

Skipped automatically when the reference tree or torch is unavailable.
"""

import os
import sys

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

REF = "/root/reference/src"
torch = pytest.importorskip("torch")
if not os.path.isdir(REF):
    pytest.skip("reference tree not mounted", allow_module_level=True)

from heterofl_tpu import config as C  # noqa: E402
from heterofl_tpu.fed import extract_sliced  # noqa: E402
from heterofl_tpu.models import make_model  # noqa: E402


@pytest.fixture(scope="module")
def ref_modules():
    """Import the reference's modules, then remove /root/reference/src from
    sys.path so its generic top-level names (config, models, ...) cannot
    shadow anything for later-collected tests."""
    cwd = os.getcwd()
    os.chdir(REF)
    sys.path.insert(0, REF)
    try:
        from config import cfg as ref_cfg  # noqa
        import models as ref_models  # noqa
    finally:
        os.chdir(cwd)
        sys.path.remove(REF)
    return ref_cfg, ref_models


def _my_cfg(norm="bn", hidden=(8, 16)):
    cfg = C.default_cfg()
    cfg["control"] = C.parse_control_name(f"1_4_0.5_iid_fix_a1-b1_{norm}_1_1")
    cfg["data_name"] = "MNIST"
    cfg["model_name"] = "conv"
    cfg = C.process_control(cfg)
    cfg["conv"] = {"hidden_size": list(hidden)}
    cfg["classes_size"] = 10
    return cfg


def _sync_ref_cfg(ref_cfg, my_cfg):
    ref_cfg["norm"] = my_cfg["norm"]
    ref_cfg["scale"] = my_cfg["scale"]
    ref_cfg["mask"] = my_cfg["mask"]
    ref_cfg["global_model_rate"] = my_cfg["global_model_rate"]
    ref_cfg["classes_size"] = my_cfg["classes_size"]
    ref_cfg["conv"] = dict(my_cfg["conv"])
    ref_cfg["data_shape"] = [1, 28, 28]  # reference is CHW
    ref_cfg["device"] = "cpu"


def _to_torch_conv_state(params, n_blocks):
    """My flat params -> the reference Conv's state_dict layout.

    Reference blocks: [Conv2d, Scaler, Norm, ReLU, MaxPool] * n - last pool +
    [AdaptiveAvgPool, Flatten, Linear] (ref models/conv.py:29-60).  Sequential
    indices: conv_i at 5*i, norm at 5*i+2; Linear at 5*n + 1 (pool dropped on
    the last block shifts tail indices by -1).
    """
    sd = {}
    for i in range(n_blocks):
        w = np.asarray(params[f"block{i}.conv.w"]).transpose(3, 2, 0, 1)  # HWIO->OIHW
        sd[f"blocks.{5*i}.weight"] = torch.tensor(w.copy())
        sd[f"blocks.{5*i}.bias"] = torch.tensor(np.asarray(params[f"block{i}.conv.b"]).copy())
        if f"block{i}.norm.g" in params:
            sd[f"blocks.{5*i+2}.weight"] = torch.tensor(np.asarray(params[f"block{i}.norm.g"]).copy())
            sd[f"blocks.{5*i+2}.bias"] = torch.tensor(np.asarray(params[f"block{i}.norm.b"]).copy())
    tail = 5 * n_blocks - 1 + 2  # dropped last pool, then avgpool+flatten
    sd[f"blocks.{tail}.weight"] = torch.tensor(np.asarray(params["linear.w"]).T.copy())
    sd[f"blocks.{tail}.bias"] = torch.tensor(np.asarray(params["linear.b"]).copy())
    return sd


@pytest.mark.parametrize("norm", ["bn", "in", "ln", "none"])
def test_conv_forward_matches_reference(ref_modules, norm):
    ref_cfg, ref_models = ref_modules
    my_cfg = _my_cfg(norm=norm)
    _sync_ref_cfg(ref_cfg, my_cfg)

    model = make_model(my_cfg)
    params = model.init(jax.random.key(0))

    tm = ref_models.conv(model_rate=1.0)
    missing = tm.load_state_dict(_to_torch_conv_state(params, 2), strict=True)
    tm.train(True)

    rng = np.random.default_rng(0)
    img = rng.normal(size=(4, 28, 28, 1)).astype(np.float32)
    label = rng.integers(0, 10, 4)
    out_mine, _ = model.apply(params, {"img": jnp.asarray(img), "label": jnp.asarray(label)},
                              train=True)
    with torch.no_grad():
        out_ref = tm({"img": torch.tensor(img.transpose(0, 3, 1, 2).copy()),
                      "label": torch.tensor(label)})
    np.testing.assert_allclose(np.asarray(out_mine["score"]),
                               out_ref["score"].numpy(), rtol=2e-4, atol=2e-5)
    assert abs(float(out_mine["loss"]) - float(out_ref["loss"])) < 2e-5


def test_sliced_submodel_matches_reference_submodel(ref_modules):
    """A rate-0.5 sub-model: my sliced params in the reference's rate-0.5
    torch model == my masked full-width execution."""
    ref_cfg, ref_models = ref_modules
    my_cfg = _my_cfg(norm="bn")
    _sync_ref_cfg(ref_cfg, my_cfg)

    gm = make_model(my_cfg)
    params = gm.init(jax.random.key(1))
    rate = 0.5
    sliced = extract_sliced({k: np.asarray(v) for k, v in params.items()},
                            gm.specs, gm.groups, rate)

    tm = ref_models.conv(model_rate=rate)
    tm.load_state_dict(_to_torch_conv_state(sliced, 2), strict=True)
    tm.train(True)

    rng = np.random.default_rng(2)
    img = rng.normal(size=(4, 28, 28, 1)).astype(np.float32)
    label = rng.integers(0, 10, 4)
    from heterofl_tpu.models.spec import mask_params

    masked = mask_params(params, gm.specs, gm.groups, rate)
    out_mine, _ = gm.apply(masked, {"img": jnp.asarray(img), "label": jnp.asarray(label)},
                           train=True, width_rate=rate, scaler_rate=rate)
    with torch.no_grad():
        out_ref = tm({"img": torch.tensor(img.transpose(0, 3, 1, 2).copy()),
                      "label": torch.tensor(label)})
    np.testing.assert_allclose(np.asarray(out_mine["score"]),
                               out_ref["score"].numpy(), rtol=2e-4, atol=2e-5)


def test_label_mask_matches_reference(ref_modules):
    ref_cfg, ref_models = ref_modules
    my_cfg = _my_cfg(norm="none")
    _sync_ref_cfg(ref_cfg, my_cfg)
    model = make_model(my_cfg)
    params = model.init(jax.random.key(3))
    tm = ref_models.conv(model_rate=1.0)
    tm.load_state_dict(_to_torch_conv_state(params, 2), strict=True)
    tm.train(True)
    rng = np.random.default_rng(4)
    img = rng.normal(size=(3, 28, 28, 1)).astype(np.float32)
    label = np.array([1, 3, 1])
    lm = jnp.zeros(10).at[jnp.array([1, 3])].set(1.0)
    out_mine, _ = model.apply(params, {"img": jnp.asarray(img), "label": jnp.asarray(label)},
                              train=True, label_mask=lm)
    with torch.no_grad():
        out_ref = tm({"img": torch.tensor(img.transpose(0, 3, 1, 2).copy()),
                      "label": torch.tensor(label),
                      "label_split": torch.tensor([1, 3])})
    np.testing.assert_allclose(np.asarray(out_mine["score"]),
                               out_ref["score"].numpy(), rtol=2e-4, atol=2e-5)
    assert abs(float(out_mine["loss"]) - float(out_ref["loss"])) < 2e-5


def _to_torch_resnet_state(params):
    """My flat resnet params -> reference ResNet state_dict names
    (ref models/resnet.py: conv1, layer{1..4}.{b}.{n1,conv1,n2,conv2,shortcut},
    n4, linear)."""
    sd = {}

    def cw(name):
        return torch.tensor(np.asarray(params[name]).transpose(3, 2, 0, 1).copy())

    sd["conv1.weight"] = cw("conv1.w")
    for s in range(4):
        for b in range(2):
            mine = f"layer{s}.{b}"
            ref = f"layer{s+1}.{b}"
            for n in ("n1", "n2"):
                if f"{mine}.{n}.g" in params:
                    sd[f"{ref}.{n}.weight"] = torch.tensor(np.asarray(params[f"{mine}.{n}.g"]).copy())
                    sd[f"{ref}.{n}.bias"] = torch.tensor(np.asarray(params[f"{mine}.{n}.b"]).copy())
            sd[f"{ref}.conv1.weight"] = cw(f"{mine}.conv1.w")
            sd[f"{ref}.conv2.weight"] = cw(f"{mine}.conv2.w")
            if f"{mine}.shortcut.w" in params:
                sd[f"{ref}.shortcut.weight"] = cw(f"{mine}.shortcut.w")
    if "n4.g" in params:
        sd["n4.weight"] = torch.tensor(np.asarray(params["n4.g"]).copy())
        sd["n4.bias"] = torch.tensor(np.asarray(params["n4.b"]).copy())
    sd["linear.weight"] = torch.tensor(np.asarray(params["linear.w"]).T.copy())
    sd["linear.bias"] = torch.tensor(np.asarray(params["linear.b"]).copy())
    return sd


@pytest.mark.parametrize("rate", [1.0, 0.25])
def test_resnet18_forward_matches_reference(ref_modules, rate):
    ref_cfg, ref_models = ref_modules
    my_cfg = _my_cfg(norm="bn")
    my_cfg["model_name"] = "resnet18"
    my_cfg["data_name"] = "CIFAR10"
    my_cfg["resnet"] = {"hidden_size": [8, 16, 16, 32]}
    my_cfg["data_shape"] = [32, 32, 3]
    _sync_ref_cfg(ref_cfg, my_cfg)
    ref_cfg["resnet"] = dict(my_cfg["resnet"])
    ref_cfg["data_shape"] = [3, 32, 32]

    gm = make_model(my_cfg)
    params = gm.init(jax.random.key(5))
    from heterofl_tpu.models.spec import mask_params

    if rate == 1.0:
        use = {k: np.asarray(v) for k, v in params.items()}
    else:
        use = extract_sliced({k: np.asarray(v) for k, v in params.items()},
                             gm.specs, gm.groups, rate)
    tm = ref_models.resnet18(model_rate=rate)
    tm.load_state_dict(_to_torch_resnet_state(use), strict=True)
    tm.train(True)

    rng = np.random.default_rng(6)
    img = rng.normal(size=(4, 32, 32, 3)).astype(np.float32)
    label = rng.integers(0, 10, 4)
    masked = mask_params(params, gm.specs, gm.groups, rate)
    out_mine, _ = gm.apply(masked, {"img": jnp.asarray(img), "label": jnp.asarray(label)},
                           train=True, width_rate=rate, scaler_rate=rate)
    with torch.no_grad():
        out_ref = tm({"img": torch.tensor(img.transpose(0, 3, 1, 2).copy()),
                      "label": torch.tensor(label)})
    np.testing.assert_allclose(np.asarray(out_mine["score"]),
                               out_ref["score"].numpy(), rtol=5e-4, atol=5e-5)
    assert abs(float(out_mine["loss"]) - float(out_ref["loss"])) < 5e-5
