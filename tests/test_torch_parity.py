"""Numerical parity against the ACTUAL reference implementation.

Loads this framework's parameters into the reference's PyTorch models
(mounted read-only at /root/reference -- imported, never copied) and compares
forward outputs and losses on identical batches.  This pins the model
semantics (conv/BN-sBN/Scaler/masked-CE, width-sliced sub-models) to the
reference at the numerical level, not just by reimplementation reading.

Skipped automatically when the reference tree or torch is unavailable.
"""

import os
import sys

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

REF = "/root/reference/src"
torch = pytest.importorskip("torch")
if not os.path.isdir(REF):
    pytest.skip("reference tree not mounted", allow_module_level=True)

from heterofl_tpu import config as C  # noqa: E402
from heterofl_tpu.fed import extract_sliced  # noqa: E402
from heterofl_tpu.models import make_model  # noqa: E402

# loads the torch reference per test (fast gate excludes this module)
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def ref_modules():
    """Import the reference's modules, then remove /root/reference/src from
    sys.path so its generic top-level names (config, models, ...) cannot
    shadow anything for later-collected tests."""
    cwd = os.getcwd()
    os.chdir(REF)
    sys.path.insert(0, REF)
    try:
        from config import cfg as ref_cfg  # noqa
        import models as ref_models  # noqa
    finally:
        os.chdir(cwd)
        sys.path.remove(REF)
    return ref_cfg, ref_models


def _my_cfg(norm="bn", hidden=(8, 16)):
    cfg = C.default_cfg()
    cfg["control"] = C.parse_control_name(f"1_4_0.5_iid_fix_a1-b1_{norm}_1_1")
    cfg["data_name"] = "MNIST"
    cfg["model_name"] = "conv"
    cfg = C.process_control(cfg)
    cfg["conv"] = {"hidden_size": list(hidden)}
    cfg["classes_size"] = 10
    return cfg


def _sync_ref_cfg(ref_cfg, my_cfg):
    ref_cfg["norm"] = my_cfg["norm"]
    ref_cfg["scale"] = my_cfg["scale"]
    ref_cfg["mask"] = my_cfg["mask"]
    ref_cfg["global_model_rate"] = my_cfg["global_model_rate"]
    ref_cfg["classes_size"] = my_cfg["classes_size"]
    ref_cfg["conv"] = dict(my_cfg["conv"])
    ref_cfg["data_shape"] = [1, 28, 28]  # reference is CHW
    ref_cfg["device"] = "cpu"


def _to_torch_conv_state(params, n_blocks):
    """My flat params -> the reference Conv's state_dict layout.

    Reference blocks: [Conv2d, Scaler, Norm, ReLU, MaxPool] * n - last pool +
    [AdaptiveAvgPool, Flatten, Linear] (ref models/conv.py:29-60).  Sequential
    indices: conv_i at 5*i, norm at 5*i+2; Linear at 5*n + 1 (pool dropped on
    the last block shifts tail indices by -1).
    """
    sd = {}
    for i in range(n_blocks):
        w = np.asarray(params[f"block{i}.conv.w"]).transpose(3, 2, 0, 1)  # HWIO->OIHW
        sd[f"blocks.{5*i}.weight"] = torch.tensor(w.copy())
        sd[f"blocks.{5*i}.bias"] = torch.tensor(np.asarray(params[f"block{i}.conv.b"]).copy())
        if f"block{i}.norm.g" in params:
            sd[f"blocks.{5*i+2}.weight"] = torch.tensor(np.asarray(params[f"block{i}.norm.g"]).copy())
            sd[f"blocks.{5*i+2}.bias"] = torch.tensor(np.asarray(params[f"block{i}.norm.b"]).copy())
    tail = 5 * n_blocks - 1 + 2  # dropped last pool, then avgpool+flatten
    sd[f"blocks.{tail}.weight"] = torch.tensor(np.asarray(params["linear.w"]).T.copy())
    sd[f"blocks.{tail}.bias"] = torch.tensor(np.asarray(params["linear.b"]).copy())
    return sd


@pytest.mark.parametrize("norm", ["bn", "in", "ln", "none"])
def test_conv_forward_matches_reference(ref_modules, norm):
    ref_cfg, ref_models = ref_modules
    my_cfg = _my_cfg(norm=norm)
    _sync_ref_cfg(ref_cfg, my_cfg)

    model = make_model(my_cfg)
    params = model.init(jax.random.key(0))

    tm = ref_models.conv(model_rate=1.0)
    tm.load_state_dict(_to_torch_conv_state(params, 2), strict=True)
    tm.train(True)

    rng = np.random.default_rng(0)
    img = rng.normal(size=(4, 28, 28, 1)).astype(np.float32)
    label = rng.integers(0, 10, 4)
    out_mine, _ = model.apply(params, {"img": jnp.asarray(img), "label": jnp.asarray(label)},
                              train=True)
    with torch.no_grad():
        out_ref = tm({"img": torch.tensor(img.transpose(0, 3, 1, 2).copy()),
                      "label": torch.tensor(label)})
    np.testing.assert_allclose(np.asarray(out_mine["score"]),
                               out_ref["score"].numpy(), rtol=2e-4, atol=2e-5)
    assert abs(float(out_mine["loss"]) - float(out_ref["loss"])) < 2e-5


def test_sliced_submodel_matches_reference_submodel(ref_modules):
    """A rate-0.5 sub-model: my sliced params in the reference's rate-0.5
    torch model == my masked full-width execution."""
    ref_cfg, ref_models = ref_modules
    my_cfg = _my_cfg(norm="bn")
    _sync_ref_cfg(ref_cfg, my_cfg)

    gm = make_model(my_cfg)
    params = gm.init(jax.random.key(1))
    rate = 0.5
    sliced = extract_sliced({k: np.asarray(v) for k, v in params.items()},
                            gm.specs, gm.groups, rate)

    tm = ref_models.conv(model_rate=rate)
    tm.load_state_dict(_to_torch_conv_state(sliced, 2), strict=True)
    tm.train(True)

    rng = np.random.default_rng(2)
    img = rng.normal(size=(4, 28, 28, 1)).astype(np.float32)
    label = rng.integers(0, 10, 4)
    from heterofl_tpu.models.spec import mask_params

    masked = mask_params(params, gm.specs, gm.groups, rate)
    out_mine, _ = gm.apply(masked, {"img": jnp.asarray(img), "label": jnp.asarray(label)},
                           train=True, width_rate=rate, scaler_rate=rate)
    with torch.no_grad():
        out_ref = tm({"img": torch.tensor(img.transpose(0, 3, 1, 2).copy()),
                      "label": torch.tensor(label)})
    np.testing.assert_allclose(np.asarray(out_mine["score"]),
                               out_ref["score"].numpy(), rtol=2e-4, atol=2e-5)


def test_label_mask_matches_reference(ref_modules):
    ref_cfg, ref_models = ref_modules
    my_cfg = _my_cfg(norm="none")
    _sync_ref_cfg(ref_cfg, my_cfg)
    model = make_model(my_cfg)
    params = model.init(jax.random.key(3))
    tm = ref_models.conv(model_rate=1.0)
    tm.load_state_dict(_to_torch_conv_state(params, 2), strict=True)
    tm.train(True)
    rng = np.random.default_rng(4)
    img = rng.normal(size=(3, 28, 28, 1)).astype(np.float32)
    label = np.array([1, 3, 1])
    lm = jnp.zeros(10).at[jnp.array([1, 3])].set(1.0)
    out_mine, _ = model.apply(params, {"img": jnp.asarray(img), "label": jnp.asarray(label)},
                              train=True, label_mask=lm)
    with torch.no_grad():
        out_ref = tm({"img": torch.tensor(img.transpose(0, 3, 1, 2).copy()),
                      "label": torch.tensor(label),
                      "label_split": torch.tensor([1, 3])})
    np.testing.assert_allclose(np.asarray(out_mine["score"]),
                               out_ref["score"].numpy(), rtol=2e-4, atol=2e-5)
    assert abs(float(out_mine["loss"]) - float(out_ref["loss"])) < 2e-5


def _to_torch_resnet_state(params):
    """My flat resnet params -> reference ResNet state_dict names, emitted in
    the reference's module-definition ORDER (n1, conv1, n2, conv2, shortcut
    per block): load_state_dict ignores order, but Federation.split_model's
    index chaining depends on it (ref fed.py:63-103)."""
    sd = {}

    def cw(name):
        return torch.tensor(np.asarray(params[name]).transpose(3, 2, 0, 1).copy())

    def nm(ref, mine):
        if f"{mine}.g" in params:
            sd[f"{ref}.weight"] = torch.tensor(np.asarray(params[f"{mine}.g"]).copy())
            sd[f"{ref}.bias"] = torch.tensor(np.asarray(params[f"{mine}.b"]).copy())

    sd["conv1.weight"] = cw("conv1.w")
    for s in range(4):
        for b in range(2):
            mine, ref = f"layer{s}.{b}", f"layer{s+1}.{b}"
            nm(f"{ref}.n1", f"{mine}.n1")
            sd[f"{ref}.conv1.weight"] = cw(f"{mine}.conv1.w")
            nm(f"{ref}.n2", f"{mine}.n2")
            sd[f"{ref}.conv2.weight"] = cw(f"{mine}.conv2.w")
            if f"{mine}.shortcut.w" in params:
                sd[f"{ref}.shortcut.weight"] = cw(f"{mine}.shortcut.w")
    nm("n4", "n4")
    sd["linear.weight"] = torch.tensor(np.asarray(params["linear.w"]).T.copy())
    sd["linear.bias"] = torch.tensor(np.asarray(params["linear.b"]).copy())
    return sd


@pytest.mark.parametrize("rate", [1.0, 0.25])
def test_resnet18_forward_matches_reference(ref_modules, rate):
    ref_cfg, ref_models = ref_modules
    my_cfg = _my_cfg(norm="bn")
    my_cfg["model_name"] = "resnet18"
    my_cfg["data_name"] = "CIFAR10"
    my_cfg["resnet"] = {"hidden_size": [8, 16, 16, 32]}
    my_cfg["data_shape"] = [32, 32, 3]
    _sync_ref_cfg(ref_cfg, my_cfg)
    ref_cfg["resnet"] = dict(my_cfg["resnet"])
    ref_cfg["data_shape"] = [3, 32, 32]

    gm = make_model(my_cfg)
    params = gm.init(jax.random.key(5))
    from heterofl_tpu.models.spec import mask_params

    if rate == 1.0:
        use = {k: np.asarray(v) for k, v in params.items()}
    else:
        use = extract_sliced({k: np.asarray(v) for k, v in params.items()},
                             gm.specs, gm.groups, rate)
    tm = ref_models.resnet18(model_rate=rate)
    tm.load_state_dict(_to_torch_resnet_state(use), strict=True)
    tm.train(True)

    rng = np.random.default_rng(6)
    img = rng.normal(size=(4, 32, 32, 3)).astype(np.float32)
    label = rng.integers(0, 10, 4)
    masked = mask_params(params, gm.specs, gm.groups, rate)
    out_mine, _ = gm.apply(masked, {"img": jnp.asarray(img), "label": jnp.asarray(label)},
                           train=True, width_rate=rate, scaler_rate=rate)
    with torch.no_grad():
        out_ref = tm({"img": torch.tensor(img.transpose(0, 3, 1, 2).copy()),
                      "label": torch.tensor(label)})
    np.testing.assert_allclose(np.asarray(out_mine["score"]),
                               out_ref["score"].numpy(), rtol=5e-4, atol=5e-5)
    assert abs(float(out_mine["loss"]) - float(out_ref["loss"])) < 5e-5


@pytest.fixture(scope="module")
def ref_federation(ref_modules):
    sys.path.insert(0, REF)
    try:
        from fed import Federation  # noqa
    finally:
        sys.path.remove(REF)
    return Federation


def test_distribute_matches_reference_federation(ref_modules, ref_federation):
    """The reference's Federation.split_model/distribute applied to MY global
    params produces exactly my extract_sliced sub-models (conv family)."""
    ref_cfg, ref_models = ref_modules
    my_cfg = _my_cfg(norm="bn")
    _sync_ref_cfg(ref_cfg, my_cfg)
    ref_cfg["model_name"] = "conv"
    ref_cfg["model_split_mode"] = "fix"
    ref_cfg["model_rate"] = [1.0, 0.5, 0.25, 0.125]

    gm = make_model(my_cfg)
    params = gm.init(jax.random.key(7))
    sd = _to_torch_conv_state(params, 2)

    fed = ref_federation(sd, ref_cfg["model_rate"], label_split={i: list(range(10)) for i in range(4)})
    local_params, param_idx = fed.distribute([1, 2])  # users at rates 0.5, 0.25

    pn = {k: np.asarray(v) for k, v in params.items()}
    for m, rate in zip(range(2), (0.5, 0.25)):
        mine = extract_sliced(pn, gm.specs, gm.groups, rate)
        mine_sd = _to_torch_conv_state(mine, 2)
        for k, v in local_params[m].items():
            np.testing.assert_allclose(v.numpy(), mine_sd[k].numpy(), rtol=0, atol=0,
                                       err_msg=f"user {m} rate {rate} param {k}")


def test_combine_matches_reference_federation(ref_modules, ref_federation):
    """The reference's counted-average combine and my masked-psum combine
    produce the same new global params from identical client updates."""
    ref_cfg, ref_models = ref_modules
    my_cfg = _my_cfg(norm="bn")
    _sync_ref_cfg(ref_cfg, my_cfg)
    ref_cfg["model_name"] = "conv"
    ref_cfg["model_split_mode"] = "fix"
    ref_cfg["model_rate"] = [1.0, 0.5]

    gm = make_model(my_cfg)
    params = gm.init(jax.random.key(8))
    pn = {k: np.asarray(v) for k, v in params.items()}
    sd = {k: v.clone() for k, v in _to_torch_conv_state(params, 2).items()}
    label_split = {0: [0, 1, 2, 3, 4], 1: [5, 6, 7, 8, 9]}

    fed = ref_federation(sd, ref_cfg["model_rate"], label_split)
    local_params, param_idx = fed.distribute([0, 1])
    # fake "trained" updates: add deterministic noise to each client's params
    rngs = [np.random.default_rng(10 + m) for m in range(2)]
    for m in range(2):
        for k in local_params[m]:
            local_params[m][k] = local_params[m][k] + torch.tensor(
                rngs[m].normal(size=tuple(local_params[m][k].shape)).astype(np.float32))
    fed.combine(local_params, param_idx, [0, 1])
    ref_new = {k: v.numpy() for k, v in fed.global_parameters.items()}

    # my combine on the same updates (converted back to my layout)
    from heterofl_tpu.fed import client_count_masks, combine_counted, embed_sliced
    from heterofl_tpu.data import label_split_masks

    lms = label_split_masks(label_split, 2, 10)
    summed = {k: np.zeros_like(v) for k, v in pn.items()}
    counts = {k: np.zeros_like(v, dtype=np.float32) for k, v in pn.items()}
    for m, rate in zip(range(2), (1.0, 0.5)):
        mine = extract_sliced(pn, gm.specs, gm.groups, rate)
        rng_m = np.random.default_rng(10 + m)
        # reproduce the torch-side noise in MY layout: iterate the SAME torch
        # key order, then invert the layout transform
        sdm = _to_torch_conv_state(mine, 2)
        trained = {}
        for k in local_params[m]:  # ordered like the torch state_dict
            noise = rng_m.normal(size=tuple(sdm[k].shape)).astype(np.float32)
            trained[k] = sdm[k].numpy() + noise
        # torch layout -> my layout
        mine_trained = {
            "block0.conv.w": trained["blocks.0.weight"].transpose(2, 3, 1, 0),
            "block0.conv.b": trained["blocks.0.bias"],
            "block0.norm.g": trained["blocks.2.weight"],
            "block0.norm.b": trained["blocks.2.bias"],
            "block1.conv.w": trained["blocks.5.weight"].transpose(2, 3, 1, 0),
            "block1.conv.b": trained["blocks.5.bias"],
            "block1.norm.g": trained["blocks.7.weight"],
            "block1.norm.b": trained["blocks.7.bias"],
            "linear.w": trained["blocks.11.weight"].T,
            "linear.b": trained["blocks.11.bias"],
        }
        back = embed_sliced(mine_trained, gm.specs, gm.groups, rate,
                            {k: v.shape for k, v in pn.items()})
        cm = {k: np.asarray(v) for k, v in client_count_masks(
            {k: jnp.asarray(v) for k, v in pn.items()}, gm, rate,
            jnp.asarray(lms[m])).items()}
        for k in pn:
            summed[k] += back[k] * cm[k]
            counts[k] += cm[k]
    my_new = combine_counted({k: jnp.asarray(v) for k, v in pn.items()},
                             {k: jnp.asarray(v) for k, v in summed.items()},
                             {k: jnp.asarray(v) for k, v in counts.items()})

    my_new_sd = _to_torch_conv_state({k: np.asarray(v) for k, v in my_new.items()}, 2)
    for k in ref_new:
        np.testing.assert_allclose(ref_new[k], my_new_sd[k].numpy(), rtol=1e-5, atol=1e-6,
                                   err_msg=k)


def _to_torch_transformer_state(params, num_layers):
    """My flat transformer params -> reference Transformer state_dict
    (ref models/transformer.py: transformer_embedding / transformer_encoder
    .layers.{i}.{mha.linear_q..o, norm1, linear1, linear2, norm2} / decoder)."""
    t = lambda a: torch.tensor(np.asarray(a).copy())
    tT = lambda a: torch.tensor(np.asarray(a).T.copy())
    sd = {
        "transformer_embedding.embedding.weight": t(params["embedding.tok.w"]),
        "transformer_embedding.positional_embedding.positional_embedding.weight":
            t(params["embedding.pos.w"]),
        "transformer_embedding.norm.weight": t(params["embedding.norm.g"]),
        "transformer_embedding.norm.bias": t(params["embedding.norm.b"]),
        "decoder.linear1.weight": tT(params["dec.l1.w"]),
        "decoder.linear1.bias": t(params["dec.l1.b"]),
        "decoder.norm1.weight": t(params["dec.norm.g"]),
        "decoder.norm1.bias": t(params["dec.norm.b"]),
        "decoder.linear2.weight": tT(params["dec.l2.w"]),
        "decoder.linear2.bias": t(params["dec.l2.b"]),
    }
    for i in range(num_layers):
        for mine, ref in (("q", "linear_q"), ("k", "linear_k"), ("v", "linear_v"),
                          ("o", "linear_o")):
            sd[f"transformer_encoder.layers.{i}.mha.{ref}.weight"] = tT(params[f"enc{i}.mha.{mine}.w"])
            sd[f"transformer_encoder.layers.{i}.mha.{ref}.bias"] = t(params[f"enc{i}.mha.{mine}.b"])
        for n in ("norm1", "norm2"):
            sd[f"transformer_encoder.layers.{i}.{n}.weight"] = t(params[f"enc{i}.{n}.g"])
            sd[f"transformer_encoder.layers.{i}.{n}.bias"] = t(params[f"enc{i}.{n}.b"])
        sd[f"transformer_encoder.layers.{i}.linear1.weight"] = tT(params[f"enc{i}.ff.l1.w"])
        sd[f"transformer_encoder.layers.{i}.linear1.bias"] = t(params[f"enc{i}.ff.l1.b"])
        sd[f"transformer_encoder.layers.{i}.linear2.weight"] = tT(params[f"enc{i}.ff.l2.w"])
        sd[f"transformer_encoder.layers.{i}.linear2.bias"] = t(params[f"enc{i}.ff.l2.b"])
    return sd


@pytest.mark.parametrize("rate", [1.0, 0.5])
def test_transformer_forward_matches_reference(ref_modules, rate):
    """Full transformer stack vs the reference's torch model, incl. the
    per-head q/k/v sliced sub-model at rate 0.5 (corruption/dropout off for a
    deterministic comparison)."""
    ref_cfg, ref_models = ref_modules
    my_cfg = C.default_cfg()
    my_cfg["control"] = C.parse_control_name("1_4_0.5_iid_fix_a1-b1_bn_1_1")
    my_cfg["data_name"] = "WikiText2"
    my_cfg["model_name"] = "transformer"
    my_cfg = C.process_control(my_cfg)
    my_cfg["transformer"] = {"embedding_size": 32, "num_heads": 4, "hidden_size": 64,
                             "num_layers": 2, "dropout": 0.0}
    my_cfg["bptt"] = 16
    my_cfg["mask_rate"] = 0.0
    my_cfg["num_tokens"] = 50
    my_cfg["classes_size"] = 50

    ref_cfg["num_tokens"] = 50
    ref_cfg["bptt"] = 16
    ref_cfg["mask_rate"] = 0.0
    ref_cfg["mask"] = True
    ref_cfg["global_model_rate"] = 1.0
    ref_cfg["transformer"] = dict(my_cfg["transformer"])

    gm = make_model(my_cfg)
    params = gm.init(jax.random.key(9))
    pn = {k: np.asarray(v) for k, v in params.items()}
    use = pn if rate == 1.0 else extract_sliced(pn, gm.specs, gm.groups, rate)

    tm = ref_models.transformer(model_rate=rate)
    tm.load_state_dict(_to_torch_transformer_state(use, 2), strict=True)
    tm.train(True)

    # torch-1.7 fast-path workaround, shared with the trajectory harness
    from heterofl_tpu.analysis.compare_reference import _patch_ref_encoder

    _patch_ref_encoder(tm)

    rng = np.random.default_rng(11)
    labels = rng.integers(0, 50, (2, 16))
    from heterofl_tpu.models.spec import mask_params

    masked = mask_params(params, gm.specs, gm.groups, rate)
    out_mine, _ = gm.apply(masked, {"label": jnp.asarray(labels)}, train=True,
                           width_rate=rate, scaler_rate=rate, rng=jax.random.key(0))
    with torch.no_grad():
        out_ref = tm({"label": torch.tensor(labels)})
    # reference scores are [N, V, S]; mine are [N, S, V]
    np.testing.assert_allclose(np.asarray(out_mine["score"]).transpose(0, 2, 1),
                               out_ref["score"].numpy(), rtol=5e-4, atol=5e-5)
    assert abs(float(out_mine["loss"]) - float(out_ref["loss"])) < 5e-5


@pytest.mark.parametrize("family", ["conv", "resnet18"])
def test_full_round_matches_reference(ref_modules, family):
    """A DETERMINISTIC full federated round vs the reference: one full-batch
    SGD step per client (batch >= shard, local epochs 1, no augmentation)
    removes every RNG dependence, so the reference's distribute -> torch SGD
    -> combine must equal the jitted masked round parameter-for-parameter."""
    from heterofl_tpu.data import label_split_masks
    from heterofl_tpu.parallel import RoundEngine, make_mesh

    ref_cfg, ref_models = ref_modules
    sys.path.insert(0, REF)
    try:
        from fed import Federation
    finally:
        sys.path.remove(REF)

    my_cfg = _my_cfg(norm="bn")
    my_cfg["model_name"] = family
    my_cfg["resnet"] = {"hidden_size": [4, 8, 8, 8]}
    _sync_ref_cfg(ref_cfg, my_cfg)
    ref_cfg["resnet"] = dict(my_cfg["resnet"])
    ref_cfg["model_name"] = family
    ref_cfg["model_split_mode"] = "fix"
    rates = [1.0, 0.5, 0.25, 0.125]
    ref_cfg["model_rate"] = rates
    my_cfg["model_rate"] = rates
    my_cfg["control"]["num_users"] = "4"
    my_cfg["num_users"] = 4
    my_cfg["num_epochs"] = {"global": 1, "local": 1}
    N, B = 12, 16  # single full batch per client
    my_cfg["batch_size"] = {"train": B, "test": B}
    lr = 0.05

    gm = make_model(my_cfg)
    params = gm.init(jax.random.key(21))
    pn = {k: np.asarray(v) for k, v in params.items()}
    to_sd = (_to_torch_conv_state if family == "conv"
             else _to_torch_resnet_state)

    rng = np.random.default_rng(31)
    xs = rng.normal(size=(4, N, 28, 28, 1)).astype(np.float32)
    ys = rng.integers(0, 10, (4, N))
    label_split = {i: sorted(set(ys[i].tolist())) for i in range(4)}

    # ---- reference round
    sd = to_sd(pn, 2) if family == "conv" else to_sd(pn)
    fed = Federation({k: v.clone() for k, v in sd.items()}, rates, label_split)
    local_params, param_idx = fed.distribute([0, 1, 2, 3])
    factory = getattr(ref_models, family)
    for m in range(4):
        tm = factory(model_rate=rates[m])
        tm.load_state_dict(local_params[m])
        tm.train(True)
        opt = torch.optim.SGD(tm.parameters(), lr=lr, momentum=0.9, weight_decay=5e-4)
        inp = {"img": torch.tensor(xs[m].transpose(0, 3, 1, 2).copy()),
               "label": torch.tensor(ys[m]),
               "label_split": torch.tensor(label_split[m])}
        opt.zero_grad()
        out = tm(inp)
        out["loss"].backward()
        torch.nn.utils.clip_grad_norm_(tm.parameters(), 1)
        opt.step()
        local_params[m] = tm.state_dict()
    fed.combine(local_params, param_idx, [0, 1, 2, 3])
    ref_new = {k: v.numpy() for k, v in fed.global_parameters.items()}

    # ---- my round. Neutralise normalisation exactly: the engine computes
    # (stored/255 - mean)/std, so stored = 255*xs with mean 0, std 1 feeds the
    # model precisely xs (scale tricks like std=1/255 are NOT safe: BN cancels
    # input scale through conv+BN stacks, but ResNet's identity residuals
    # don't -- which is how this test caught its own earlier bug).
    my_cfg["norm_stats"] = ((0.0,), (1.0,))
    eng = RoundEngine(gm, my_cfg, make_mesh(1, 1))
    lm = label_split_masks(label_split, 4, 10)
    data = (jnp.asarray((xs * 255.0).astype(np.float64)).astype(jnp.float32),
            jnp.asarray(ys), jnp.ones((4, N), jnp.float32), jnp.asarray(lm))
    new_params, _ = eng.train_round(params, jax.random.key(0), lr,
                                    np.arange(4, dtype=np.int32), data)
    mine = {k: np.asarray(v) for k, v in new_params.items()}
    mine_sd = to_sd(mine, 2) if family == "conv" else to_sd(mine)
    for k in ref_new:
        np.testing.assert_allclose(ref_new[k], mine_sd[k].numpy(), rtol=2e-3, atol=2e-4,
                                   err_msg=f"{family}: {k}")


def test_full_round_matches_reference_transformer(ref_modules):
    """Transformer analogue of the deterministic full-round test: corruption
    (mask_rate=0) and dropout off, windows iterate in order with no shuffle,
    so the reference's distribute -> per-window torch SGD -> combine
    (incl. the per-head q/k/v slicing, embedding column slice and the
    label-split row restriction on decoder/embedding, ref fed.py:115-131,
    263-274) must equal the jitted masked LM round parameter-for-parameter."""
    from heterofl_tpu.data import label_split_masks
    from heterofl_tpu.parallel import RoundEngine, make_mesh

    ref_cfg, ref_models = ref_modules
    sys.path.insert(0, REF)
    try:
        from fed import Federation
    finally:
        sys.path.remove(REF)

    V, bptt, R, T = 50, 16, 2, 32
    my_cfg = C.default_cfg()
    my_cfg["control"] = C.parse_control_name("1_4_1_iid_fix_a1-b1_bn_1_1")
    my_cfg["data_name"] = "WikiText2"
    my_cfg["model_name"] = "transformer"
    my_cfg = C.process_control(my_cfg)
    my_cfg["transformer"] = {"embedding_size": 32, "num_heads": 4,
                             "hidden_size": 64, "num_layers": 2, "dropout": 0.0}
    my_cfg["bptt"] = bptt
    my_cfg["mask_rate"] = 0.0
    my_cfg["num_tokens"] = V
    my_cfg["classes_size"] = V
    my_cfg["num_users"] = 4
    my_cfg["num_epochs"] = {"global": 1, "local": 1}
    my_cfg["batch_size"] = {"train": 10, "test": 10}
    my_cfg["optimizer_name"] = "SGD"
    my_cfg["momentum"] = 0.9
    my_cfg["weight_decay"] = 5e-4
    rates = [1.0, 0.5, 0.25, 0.125]
    my_cfg["model_rate"] = rates
    lr = 0.05

    ref_cfg["num_tokens"] = V
    ref_cfg["bptt"] = bptt
    ref_cfg["mask_rate"] = 0.0
    ref_cfg["mask"] = True
    ref_cfg["scale"] = True
    ref_cfg["global_model_rate"] = 1.0
    ref_cfg["classes_size"] = V
    ref_cfg["transformer"] = dict(my_cfg["transformer"])
    ref_cfg["model_name"] = "transformer"
    ref_cfg["model_split_mode"] = "fix"
    ref_cfg["model_rate"] = rates
    ref_cfg["device"] = "cpu"

    gm = make_model(my_cfg)
    params = gm.init(jax.random.key(5))
    pn = {k: np.asarray(v) for k, v in params.items()}

    rng = np.random.default_rng(17)
    rows = rng.integers(0, V, (4, R, T))
    label_split = {i: sorted(set(rows[i].reshape(-1).tolist())) for i in range(4)}

    # ---- reference round
    from heterofl_tpu.analysis.compare_reference import _patch_ref_encoder

    sd = _to_torch_transformer_state(pn, 2)
    fed = Federation({k: v.clone() for k, v in sd.items()}, rates, label_split)
    local_params, param_idx = fed.distribute([0, 1, 2, 3])
    for m in range(4):
        tm = _patch_ref_encoder(ref_models.transformer(model_rate=rates[m]))
        tm.load_state_dict(local_params[m])
        tm.train(True)
        opt = torch.optim.SGD(tm.parameters(), lr=lr, momentum=0.9, weight_decay=5e-4)
        urows = torch.tensor(rows[m])
        for s in range(0, T, bptt):
            inp = {"label": urows[:, s: s + bptt],
                   "label_split": torch.tensor(label_split[m])}
            opt.zero_grad()
            out = tm(inp)
            out["loss"].backward()
            torch.nn.utils.clip_grad_norm_(tm.parameters(), 1)
            opt.step()
        local_params[m] = tm.state_dict()
    fed.combine(local_params, param_idx, [0, 1, 2, 3])
    ref_new = {k: v.numpy() for k, v in fed.global_parameters.items()}

    # ---- my round
    eng = RoundEngine(gm, my_cfg, make_mesh(1, 1))
    lm = label_split_masks(label_split, 4, V)
    data = (jnp.asarray(rows), jnp.asarray(lm))
    new_params, _ = eng.train_round(params, jax.random.key(0), lr,
                                    np.arange(4, dtype=np.int32), data)
    mine = {k: np.asarray(v) for k, v in new_params.items()}
    mine_sd = _to_torch_transformer_state(mine, 2)
    for k in ref_new:
        np.testing.assert_allclose(ref_new[k], mine_sd[k].numpy(), rtol=2e-3, atol=2e-4,
                                   err_msg=k)
