"""Round-level strategy equivalence: the sliced runner (reference-shaped
sub-models) and the masked engine (full-width + channel masks) produce the
SAME new global parameters from the same inputs and PRNG keys."""

import pytest

import jax
import jax.numpy as jnp
import numpy as np

from heterofl_tpu.fed.sliced import SlicedFederation
from heterofl_tpu.models import make_model
from heterofl_tpu.parallel import RoundEngine, make_mesh

from test_round import _vision_setup

# compiles five per-level programs plus the masked engine (fast gate excludes this module)
pytestmark = pytest.mark.slow


def test_sliced_round_matches_masked_round():
    cfg, ds, data = _vision_setup(control="1_8_0.5_iid_fix_a1-b1-c1-d1-e1_bn_1_1")
    model = make_model(cfg)
    params = model.init(jax.random.key(0))
    user_idx = np.array([0, 2, 4, 6], np.int32)  # levels a, b, c, d
    rates = np.asarray(cfg["model_rate"], np.float32)[user_idx]
    key = jax.random.key(42)
    lr = 0.05

    params_np = {k: np.asarray(v) for k, v in params.items()}  # engine donates params
    # masked engine on a SINGLE-device mesh so slot keys line up
    eng = RoundEngine(model, cfg, make_mesh(1, 1))
    new_masked, _ = eng.train_round(params, key, lr, user_idx, data)

    sl = SlicedFederation(cfg)
    new_sliced, ms = sl.train_round(params_np, user_idx, rates, data, lr, key)
    assert np.isfinite(ms['loss_sum']).all() and (ms['n'] > 0).all()

    for k in params_np:
        np.testing.assert_allclose(np.asarray(new_masked[k]), new_sliced[k],
                                   rtol=5e-4, atol=5e-5, err_msg=k)


def test_sliced_round_loss_progression():
    cfg, ds, data = _vision_setup(control="1_8_0.5_iid_fix_a1-e1_bn_1_1")
    sl = SlicedFederation(cfg)
    model = sl.global_model
    params = {k: np.asarray(v) for k, v in model.init(jax.random.key(0)).items()}
    user_idx = np.array([0, 7], np.int32)
    rates = np.asarray(cfg["model_rate"], np.float32)[user_idx]
    p1, _ = sl.train_round(params, user_idx, rates, data, 0.05, jax.random.key(1))
    # params actually move on the active support
    assert not np.allclose(p1["block0.conv.w"], params["block0.conv.w"])
