"""Download/extract helpers, class-hierarchy trees, dict-aware transforms
(parity: ref src/datasets/utils.py, src/datasets/transforms.py)."""

import gzip
import os
import tarfile
import zipfile

import numpy as np
import pytest

from heterofl_tpu.data import (BoundingBoxCrop, ClassNode, Compose, CustomTransform,
                               check_integrity, download_url, extract_file,
                               make_flat_index, make_tree, tree_from_paths)
from heterofl_tpu.data.download import calculate_md5
from heterofl_tpu.data.hierarchy import preorder


def test_check_integrity_and_md5(tmp_path):
    p = tmp_path / "f.bin"
    p.write_bytes(b"hello world")
    md5 = calculate_md5(str(p))
    assert check_integrity(str(p), md5)
    assert check_integrity(str(p), None)
    assert not check_integrity(str(p), "0" * 32)
    assert not check_integrity(str(tmp_path / "missing"), None)


def test_download_url_uses_verified_local_copy(tmp_path):
    # offline box: a pre-verified file short-circuits the network entirely
    p = tmp_path / "data.bin"
    p.write_bytes(b"payload")
    md5 = calculate_md5(str(p))
    out = download_url("https://nonexistent.invalid/data.bin", str(tmp_path), md5=md5)
    assert out == str(p)


def test_download_url_bad_checksum_raises(tmp_path):
    p = tmp_path / "x.bin"
    p.write_bytes(b"zzz")
    with pytest.raises((RuntimeError, OSError)):
        download_url("file://" + str(p), str(tmp_path), filename="y.bin", md5="0" * 32)


def test_extract_file_zip_tar_gz(tmp_path):
    src = tmp_path / "inner.txt"
    src.write_text("content")
    z = tmp_path / "a.zip"
    with zipfile.ZipFile(z, "w") as zf:
        zf.write(src, "inner.txt")
    d1 = tmp_path / "out_zip"
    d1.mkdir()
    extract_file(str(z), str(d1))
    assert (d1 / "inner.txt").read_text() == "content"

    t = tmp_path / "a.tar.gz"
    with tarfile.open(t, "w:gz") as tf:
        tf.add(src, "inner.txt")
    d2 = tmp_path / "out_tar"
    d2.mkdir()
    extract_file(str(t), str(d2))
    assert (d2 / "inner.txt").read_text() == "content"

    g = tmp_path / "b.txt.gz"
    with gzip.open(g, "wb") as gf:
        gf.write(b"gz-content")
    extract_file(str(g), delete=True)
    assert (tmp_path / "b.txt").read_bytes() == b"gz-content"
    assert not g.exists()

    with pytest.raises(ValueError):
        extract_file(str(tmp_path / "weird.rar"))


def test_make_tree_and_flat_index_preorder():
    # two nested synset chains sharing a prefix + one flat class
    root = ClassNode("U", index=[])
    make_tree(root, ["animal", "dog"])
    make_tree(root, ["animal", "cat"])
    make_tree(root, ["rock"])
    n = make_flat_index(root)
    assert n == 3
    leaves = {l.name: l.flat_index for l in root.leaves}
    # pre-order: dog (under animal) before cat before rock
    assert leaves == {"dog": 0, "cat": 1, "rock": 2}
    # trie indexes record child positions
    assert root.find("animal").index == [0]
    assert root.find("cat").index == [0, 1]


def test_make_flat_index_given_order():
    """ImageNet semantics: flat_index follows the given (meta) order, not the
    walk order -- the exact gap VERDICT r1 flagged in _class_dirs."""
    root = tree_from_paths([["b", "leaf_b"], ["a", "leaf_a"]],
                           given=["leaf_a", "leaf_b"])
    leaves = {l.name: l.flat_index for l in root.leaves}
    assert leaves == {"leaf_a": 0, "leaf_b": 1}


def test_make_tree_attributes_thread_per_level():
    root = ClassNode("U", index=[])
    make_tree(root, ["x", "y"], {"id": [1, 2]})
    assert root.find("x").attrs["id"] == 1
    assert root.find("y").attrs["id"] == 2
    assert len(list(preorder(root))) == 3


def test_compose_dict_aware():
    sample = {"img": np.arange(16, dtype=np.uint8).reshape(4, 4),
              "bbox": np.array([1, 1, 2, 2]), "label": 3}
    pipeline = Compose([BoundingBoxCrop(), lambda img: img * 2])
    out = pipeline(sample)
    np.testing.assert_array_equal(out["img"], np.array([[5, 6], [9, 10]]) * 2)
    assert out["label"] == 3
    assert isinstance(BoundingBoxCrop(), CustomTransform)
    assert "BoundingBoxCrop" in repr(pipeline)


def test_imagenet_loader_uses_meta_order(tmp_path):
    """A tiny fake ImageNet: 3 wnid dirs + meta.mat; labels must follow the
    meta's synset order, not sorted dirs."""
    scipy = pytest.importorskip("scipy")
    from PIL import Image

    from heterofl_tpu.data.datasets import _load_image_folder

    # meta order: n03, n01, n02 (deliberately not sorted)
    wnids = ["n03", "n01", "n02"]
    root = tmp_path / "imagenet"
    train = root / "train"
    for i, w in enumerate(wnids):
        d = train / w
        d.mkdir(parents=True)
        Image.fromarray(np.full((8, 8, 3), 10 * (i + 1), np.uint8)).save(d / "img.png")
    # meta.mat rows: (id, wnid, classes, gloss, num_children, children, ...)
    rows = np.zeros(3, dtype=[("ILSVRC2012_ID", "O"), ("WNID", "O"), ("words", "O"),
                              ("gloss", "O"), ("num_children", "O"), ("children", "O")])
    for i, w in enumerate(wnids):
        rows[i] = (i + 1, w, f"class {w}", "", 0, np.array([], np.int32))
    scipy.io.savemat(root / "meta.mat", {"synsets": rows})
    ds = _load_image_folder(str(root), "train", "ImageNet")
    assert ds is not None and ds.classes_size == 3
    # image with value 10*(i+1) belongs to wnids[i] -> label i (meta order)
    for img, lab in zip(ds.data, ds.target):
        assert wnids[int(lab)] == wnids[(int(img[0, 0, 0]) // 10) - 1]
