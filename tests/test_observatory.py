"""Population observatory (ISSUE 12): cohort histograms, the client
ledger, the report surface, and abort-evidence durability.

Contracts under test:

* ``telemetry='hist'`` changes NOTHING observable but the metrics tree:
  params and train metrics stay BIT-IDENTICAL to ``'off'`` across masked
  (replicated, streaming, deadline, buffered, int8-codec) and grouped
  (span, slices) paths, and the ``hist_*`` records appear only on 'hist';
* hist bucket counts equal host-recomputed references EXACTLY (the same
  float32 ops + ``searchsorted`` rule on the fetched per-slot metrics;
  deadline budgets re-derived from the pure ``(key, uid)`` stream);
* the :class:`~heterofl_tpu.obs.ledger.ClientLedger` updates O(active),
  its loss EMA matches a host reference, its state round-trips through
  ``state_dict``/``ledger.npz`` bitwise, and a checkpoint-resumed driver
  run CONTINUES the ledger bit-identically to an uninterrupted one;
* ``python -m heterofl_tpu.obs.report`` renders a snapshot from
  ``ledger.npz`` (+ events.jsonl);
* a watchdog ABORT leaves its evidence on disk: the last events.jsonl
  record is the watchdog instant, the Chrome trace is closed/fsync'd and
  the ledger snapshot is written BEFORE the error propagates.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from heterofl_tpu import config as C
from heterofl_tpu.fed.core import (superstep_rate_schedule,
                                   superstep_user_schedule)
from heterofl_tpu.models import make_model
from heterofl_tpu.obs import (HIST_FIELDS, resolve_ledger_cfg,
                              resolve_telemetry_cfg, split_probes)
from heterofl_tpu.obs.hist import (LOSS_EDGES, STALE_EDGES, STEP_EDGES,
                                   bucket_counts)
from heterofl_tpu.obs.ledger import (LEDGER_FIELDS, LOSS_EMA_DECAY,
                                     ClientLedger, gini)
from heterofl_tpu.obs.watchdog import WatchdogError
from heterofl_tpu.parallel import (ClientStore, GroupedRoundEngine,
                                   RoundEngine, make_mesh)
from heterofl_tpu.utils.logger import Logger

from test_round import _vision_setup

HOST_KEY = jax.random.key(0)


def _params_equal(a, b):
    for k in a:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]),
                                      err_msg=k)


def _np_hist(values, weights, edges):
    """The host twin of obs.hist.bucket_counts: float32 values, same
    searchsorted(side='left') rule -- EXACT equality is the contract."""
    e = np.asarray(edges, np.float32)
    idx = np.searchsorted(e, np.asarray(values, np.float32), side="left")
    out = np.zeros(len(e) + 1, np.float64)
    np.add.at(out, idx, np.asarray(weights, np.float64))
    return out


# ---------------------------------------------------------------------------
# hist mode: bit identity + presence
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("k", [1, 8])
def test_masked_hist_superstep_bit_identical(k):
    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    mesh = make_mesh(4, 1)
    outs = {}
    for tel in ("off", "hist"):
        eng = RoundEngine(model, dict(cfg, telemetry=tel), mesh)
        p = model.init(jax.random.key(0))
        p, pending = eng.train_superstep(p, HOST_KEY, 1, k, data, num_active=4)
        outs[tel] = (p, pending.fetch())
    _params_equal(outs["off"][0], outs["hist"][0])
    off_rounds = outs["off"][1]
    hist_rounds = outs["hist"][1]["train"]
    for r in range(k):
        for name in ("loss_sum", "score_sum", "n", "rate"):
            np.testing.assert_array_equal(np.asarray(off_rounds[r][name]),
                                          np.asarray(hist_rounds[r][name]))
    probes = outs["hist"][1]["obs"]
    assert len(probes) == k
    for rec in probes:
        assert set(HIST_FIELDS) <= set(rec)
        # the membership histogram IS the participation probe
        assert rec["hist_level"] == rec["participation"]
        assert sum(rec["hist_loss"]) == 4.0  # every active client has loss
        # no deadline: every valid client sits in the full-budget bucket
        full = list(STEP_EDGES).index(1.0)
        assert rec["hist_steps"][full] == 4.0
        assert sum(rec["hist_steps"]) == 4.0
        assert rec["hist_stale"] == [0.0] * (len(STALE_EDGES) + 1)


def test_masked_stream_hist_bit_identical():
    """Streaming cohort path (AC: streaming included): hist vs off."""
    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    mesh = make_mesh(4, 1)
    rng = np.random.default_rng(0)
    from heterofl_tpu.data import label_split_masks, split_dataset
    split, lsplit = split_dataset(ds, 8, "iid", rng, classes_size=10)
    store = ClientStore.from_split(ds["train"].data, ds["train"].target,
                                   split["train"], lsplit, 10)
    sched = superstep_user_schedule(HOST_KEY, 1, 2, 8, 4)
    outs = {}
    for tel in ("off", "hist"):
        eng = RoundEngine(model, dict(cfg, telemetry=tel,
                                      client_store="stream"), mesh)
        coh = eng.stage_cohort(store, sched)
        p = model.init(jax.random.key(0))
        p, pending = eng.train_superstep(p, HOST_KEY, 1, 2, cohort=coh)
        outs[tel] = (p, pending.fetch())
    _params_equal(outs["off"][0], outs["hist"][0])
    probes = outs["hist"][1]["obs"]
    assert len(probes) == 2 and sum(probes[0]["hist_loss"]) == 4.0


@pytest.mark.parametrize("placement,k", [("span", 8), ("slices", 2)])
def test_grouped_hist_superstep_bit_identical(placement, k):
    cfg, ds, data = _vision_setup()
    mesh = make_mesh(8, 1)  # slices needs >= 5 device rows
    model = make_model(cfg)
    users = cfg["num_users"]
    sched = superstep_user_schedule(HOST_KEY, 1, k, users, users)
    rates = superstep_rate_schedule(HOST_KEY, 1, k, cfg, sched)
    outs = {}
    for tel in ("off", "hist"):
        grp = GroupedRoundEngine(dict(cfg, level_placement=placement,
                                      telemetry=tel), mesh)
        p = model.init(jax.random.key(0))
        p, pending = grp.train_superstep(p, HOST_KEY, 1, k, sched, rates, data)
        outs[tel] = (p, pending.fetch())
    _params_equal(outs["off"][0], outs["hist"][0])
    probes = outs["hist"][1]["obs"]
    assert len(probes) == k
    for rec in probes:
        assert rec["hist_level"] == rec["participation"]
        assert sum(rec["hist_loss"]) == users


# ---------------------------------------------------------------------------
# hist counts vs host-recomputed references (exact)
# ---------------------------------------------------------------------------

def test_hist_loss_counts_match_host_reference_exactly():
    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    mesh = make_mesh(4, 1)
    k = 2
    eng = RoundEngine(model, dict(cfg, telemetry="hist"), mesh)
    p = model.init(jax.random.key(0))
    _, pending = eng.train_superstep(p, HOST_KEY, 1, k, data, num_active=4)
    out = pending.fetch()
    for r in range(k):
        ms = out["train"][r]
        rate = np.asarray(ms["rate"], np.float32)
        n = np.asarray(ms["n"], np.float32)
        loss_sum = np.asarray(ms["loss_sum"], np.float32)
        # the engine's own f32 ops, replayed in numpy: exact equality
        vals = loss_sum / np.maximum(n, np.float32(1.0))
        w = ((rate > 0) & (n > 0)).astype(np.float32)
        expect = _np_hist(vals, w, LOSS_EDGES)
        np.testing.assert_array_equal(out["obs"][r]["hist_loss"], expect)


def test_hist_deadline_steps_match_host_reference_exactly():
    """Deadline scenario (AC: scenario paths included): the step-fraction
    buckets equal a host re-derivation of the pure (key, uid) budget
    stream, and hist mode stays bit-identical to off under the scenario."""
    from heterofl_tpu.sched.deadline import deadline_steps

    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    mesh = make_mesh(4, 1)
    k, A, min_frac = 2, 4, 0.4
    dcfg = dict(cfg, schedule={"deadline": {"min_frac": min_frac}})
    outs = {}
    for tel in ("off", "hist"):
        eng = RoundEngine(model, dict(dcfg, telemetry=tel), mesh)
        p = model.init(jax.random.key(0))
        p, pending = eng.train_superstep(p, HOST_KEY, 1, k, data,
                                         num_active=A)
        outs[tel] = (p, pending.fetch())
    _params_equal(outs["off"][0], outs["hist"][0])
    out = outs["hist"][1]
    sched = superstep_user_schedule(HOST_KEY, 1, k, cfg["num_users"], A)
    shard_n = int(np.asarray(data[0]).shape[1])
    total = cfg["num_epochs"]["local"] * -(-shard_n
                                           // cfg["batch_size"]["train"])
    for r in range(k):
        key_r = jax.random.fold_in(HOST_KEY, 1 + r)
        budgets = np.asarray(deadline_steps(key_r, jnp.asarray(sched[r]),
                                            total, min_frac))
        frac = budgets.astype(np.float32) / np.float32(total)
        rate = np.asarray(out["train"][r]["rate"], np.float32)[:A]
        expect = _np_hist(frac, (rate > 0).astype(np.float32), STEP_EDGES)
        np.testing.assert_array_equal(out["obs"][r]["hist_steps"], expect)
        assert sum(out["obs"][r]["hist_steps"]) == A


def test_hist_stale_under_buffered_counts_whole_carry():
    from heterofl_tpu.ops.fused_update import FlatSpec

    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    mesh = make_mesh(4, 1)
    eng = RoundEngine(model, dict(cfg, telemetry="hist",
                                  schedule={"aggregation": "buffered"}), mesh)
    p = model.init(jax.random.key(0))
    total = FlatSpec.of(p).total
    _, pending = eng.train_superstep(p, HOST_KEY, 1, 2, data, num_active=4)
    probes = pending.fetch()["obs"]
    for rec in probes:
        # every entry of the [2, total] carry lands in exactly one bucket
        assert sum(rec["hist_stale"]) == 2 * total
    # after a buffered round the pending mass is nonzero: some entries
    # leave the exact-zero bucket
    assert sum(probes[-1]["hist_stale"][1:]) > 0.0


def test_hist_rides_int8_codec_path():
    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    mesh = make_mesh(4, 1)
    outs = {}
    for tel in ("off", "hist"):
        eng = RoundEngine(model, dict(cfg, telemetry=tel, wire_codec="int8"),
                          mesh)
        p = model.init(jax.random.key(0))
        p, pending = eng.train_superstep(p, HOST_KEY, 1, 2, data,
                                         num_active=4)
        outs[tel] = (p, pending.fetch())
    _params_equal(outs["off"][0], outs["hist"][0])
    rec = outs["hist"][1]["obs"][-1]
    assert rec["resid_norm"] > 0.0 and sum(rec["hist_loss"]) == 4.0


def test_bucket_counts_edge_semantics():
    """Bucket i covers (edges[i-1], edges[i]]; overflow is the last bin --
    shared by the jax half and the numpy reference."""
    vals = jnp.asarray([0.0, 0.05, 0.0501, 200.0])
    w = jnp.ones(4)
    h = np.asarray(bucket_counts(vals, w, LOSS_EDGES))
    assert h[0] == 2.0      # 0.0 and the 0.05 edge itself
    assert h[1] == 1.0      # just past the first edge
    assert h[-1] == 1.0     # overflow
    np.testing.assert_array_equal(h, _np_hist(np.asarray(vals), np.ones(4),
                                              LOSS_EDGES))


def test_telemetry_hist_config():
    spec = resolve_telemetry_cfg({"telemetry": "hist"})
    assert spec.probes and spec.hist and spec.watchdog is not None
    assert not resolve_telemetry_cfg({"telemetry": "on"}).hist
    with pytest.raises(ValueError, match="telemetry"):
        resolve_telemetry_cfg({"telemetry": "histogram"})


# ---------------------------------------------------------------------------
# ClientLedger: O(active) semantics, EMA reference, persistence
# ---------------------------------------------------------------------------

def test_ledger_update_semantics_and_reference_ema():
    U, levels = 50, [1.0, 0.5, 0.25]
    led = ClientLedger(U, levels)
    rng = np.random.default_rng(0)
    ref_count = np.zeros(U)
    ref_ema = np.zeros(U)
    ref_last = np.zeros(U, int)
    ref_stale = np.zeros(U, int)
    for epoch in range(1, 9):
        uids = rng.choice(U, size=6, replace=False)
        rates = rng.choice(levels, size=6).astype(np.float32)
        losses = rng.uniform(0.5, 4.0, size=6).astype(np.float32)
        ns = np.full(6, 10.0, np.float32)
        led.update(epoch, uids, rates, losses * ns, ns)
        for u, loss in zip(uids, losses):
            if ref_last[u] > 0:
                ref_stale[u] += epoch - ref_last[u]
            ref_ema[u] = loss if ref_count[u] == 0 else \
                (1 - LOSS_EMA_DECAY) * ref_ema[u] + LOSS_EMA_DECAY * loss
            ref_count[u] += 1
            ref_last[u] = epoch
    np.testing.assert_array_equal(led.count, ref_count.astype(np.uint32))
    np.testing.assert_array_equal(led.last_seen, ref_last.astype(np.int32))
    np.testing.assert_array_equal(led.stale_sum, ref_stale.astype(np.uint32))
    # the satellite's EMA tolerance (the arrays are f32; the reference f64)
    np.testing.assert_allclose(led.loss_ema, ref_ema, atol=1e-4)
    assert led.seen == int((ref_count > 0).sum())
    assert int(led.level_counts.sum()) == 8 * 6
    # resident budget: ~27 B/user at 3 levels is well under the 32 B line
    assert led.nbytes / U <= 32


def test_ledger_ignores_padding_and_failed_slots():
    led = ClientLedger(10, [1.0, 0.5])
    s = led.update(1, [3, -1, 7], [1.0, 0.0, 0.0], [2.0, 9.0, 9.0],
                   [1.0, 1.0, 1.0])
    assert s["active"] == 1 and led.count[3] == 1 and led.count[7] == 0
    # participation without samples (n=0): counted, loss EMA untouched
    s = led.update(2, [3], [0.5], [0.0], [0.0])
    assert led.count[3] == 2 and led.loss_ema[3] == np.float32(2.0)
    assert s["loss_ema_mean"] is None
    with pytest.raises(ValueError, match="aligned"):
        led.update(3, [1, 2], [1.0], [1.0], [1.0])
    with pytest.raises(ValueError, match="num_users"):
        led.update(3, [11], [1.0], [1.0], [1.0])


def test_ledger_persistence_roundtrips(tmp_path):
    led = ClientLedger(20, [1.0, 0.5])
    led.update(1, [0, 5], [1.0, 0.5], [3.0, 4.0], [1.0, 2.0])
    led.update(4, [5, 6], [0.5, 1.0], [1.0, 2.0], [1.0, 1.0])
    # state_dict round-trip
    led2 = ClientLedger(20, [1.0, 0.5])
    led2.load_state_dict(led.state_dict())
    for f in LEDGER_FIELDS:
        np.testing.assert_array_equal(getattr(led, f), getattr(led2, f))
    assert (led2.round, led2.updates, led2.seen) == (4, 2, 3)
    # npz round-trip
    path = led.save(str(tmp_path / "obs" / "ledger.npz"))
    led3 = ClientLedger.load(path)
    for f in LEDGER_FIELDS:
        np.testing.assert_array_equal(getattr(led, f), getattr(led3, f))
    # mismatched geometry refuses loudly
    with pytest.raises(ValueError, match="mismatch"):
        ClientLedger(21, [1.0, 0.5]).load_state_dict(led.state_dict())
    with pytest.raises(ValueError, match="ledger"):
        resolve_ledger_cfg({"ledger": "maybe"})
    assert not resolve_ledger_cfg({}).enabled
    assert resolve_ledger_cfg({"ledger": "on"}).enabled


def test_gini_bounds():
    assert gini(np.zeros(10)) == 0.0
    assert gini(np.ones(10)) == pytest.approx(0.0, abs=1e-12)
    one_hot = np.zeros(10)
    one_hot[0] = 5
    assert gini(one_hot) == pytest.approx(0.9)


# ---------------------------------------------------------------------------
# driver integration: fold, resume, report, durability
# ---------------------------------------------------------------------------

def _driver_cfg(out_dir, **over):
    cfg = C.default_cfg()
    cfg["control"] = C.parse_control_name("1_8_0.5_iid_fix_a1-b1-c1-d1-e1_bn_1_1")
    cfg["data_name"] = "MNIST"
    cfg["model_name"] = "conv"
    cfg["synthetic"] = True
    cfg["synthetic_sizes"] = {"train": 400, "test": 100}
    cfg["output_dir"] = str(out_dir)
    cfg["override"] = {"num_epochs": {"global": 4, "local": 2},
                       "conv": {"hidden_size": [8, 16]},
                       "superstep_rounds": 2, "eval_interval": 2, **over}
    return C.process_control(cfg)


def test_driver_ledger_run_emits_and_snapshots(tmp_path):
    from heterofl_tpu.entry.common import FedExperiment

    cfg = _driver_cfg(tmp_path, ledger="on")
    exp = FedExperiment(cfg, 0)
    exp.run("Global-Accuracy")
    log = tmp_path / "runs" / f"train_{exp.tag}" / "log.jsonl"
    led_lines = [json.loads(l) for l in open(log)
                 if json.loads(l).get("tag") == "ledger"]
    assert len(led_lines) == 2  # one per superstep fetch
    assert led_lines[-1]["coverage"] > 0
    assert sum(l["active"] for l in led_lines) == 4 * exp.num_active
    path = exp._ledger_path()
    assert os.path.exists(path)
    led = ClientLedger.load(path)
    assert int(led.count.sum()) == 4 * exp.num_active
    assert led.round == 4


def test_driver_ledger_checkpoint_resume_bit_identical(tmp_path):
    """The acceptance resume contract: counts/EMAs CONTINUE, not reset --
    a 2-round + resumed-2-round run ends with the exact ledger arrays of
    an uninterrupted 4-round run."""
    from heterofl_tpu.entry.common import FedExperiment

    full_exp = FedExperiment(_driver_cfg(tmp_path / "full", ledger="on"), 0)
    full_exp.run("Global-Accuracy")

    part_dir = tmp_path / "part"
    cfg_p = _driver_cfg(part_dir, ledger="on")
    cfg_short = dict(cfg_p)
    cfg_short["num_epochs"] = dict(cfg_p["num_epochs"], **{"global": 2})
    FedExperiment(cfg_short, 0).run("Global-Accuracy")
    cfg_res = dict(cfg_p)
    cfg_res["resume_mode"] = 1
    res_exp = FedExperiment(cfg_res, 0)
    res_exp.run("Global-Accuracy")
    full = ClientLedger.load(full_exp._ledger_path())
    resumed = ClientLedger.load(res_exp._ledger_path())
    for f in LEDGER_FIELDS:
        np.testing.assert_array_equal(getattr(full, f), getattr(resumed, f),
                                      err_msg=f)
    assert (full.round, full.updates) == (resumed.round, resumed.updates)


def test_driver_ledger_conflicts_fail_loudly(tmp_path):
    from heterofl_tpu.entry.common import FedExperiment

    with pytest.raises(ValueError, match="mesh-native"):
        FedExperiment(_driver_cfg(tmp_path, ledger="on", strategy="sliced",
                                  superstep_rounds=1), 0)
    with pytest.raises(ValueError, match="replicated"):
        FedExperiment(_driver_cfg(tmp_path, ledger="on",
                                  data_placement="sharded"), 0)


def test_report_renders_snapshot(tmp_path, capsys):
    from heterofl_tpu.obs import report as R

    led = ClientLedger(100, [1.0, 0.5])
    rng = np.random.default_rng(1)
    for epoch in range(1, 13):
        uids = rng.choice(100, size=8, replace=False)
        rates = rng.choice([1.0, 0.5], size=8).astype(np.float32)
        ns = np.full(8, 4.0, np.float32)
        led.update(epoch, uids, rates,
                   rng.uniform(0.5, 3.0, 8).astype(np.float32) * ns, ns)
    run_dir = tmp_path / "trace" / "run0"
    led.save(str(run_dir / "ledger.npz"))
    assert R.main([str(tmp_path), "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["num_users"] == 100 and rep["round"] == 12
    assert 0 < rep["participation"]["coverage"] <= 1
    assert 0 <= rep["participation"]["gini"] < 1
    classes = {c["class"] for c in rep["staleness"]["by_class"]}
    assert "never-seen" in classes and "frequent" in classes
    assert len(rep["per_level"]) == 2
    # the human-readable table renders too
    assert R.main([str(run_dir)]) == 0
    text = capsys.readouterr().out
    assert "participation" in text and "per-level loss EMA" in text
    with pytest.raises(FileNotFoundError, match="ledger.npz"):
        R.find_ledger(str(tmp_path / "empty"))


def test_watchdog_abort_preserves_evidence_on_disk(tmp_path):
    """The durability satellite: after an induced abort the LAST events
    record is the watchdog instant, the Chrome trace is written, and the
    ledger snapshot exists -- all before WatchdogError reaches the
    caller."""
    from heterofl_tpu.entry.common import FedExperiment
    from heterofl_tpu.obs.trace import TraceRecorder

    cfg = _driver_cfg(tmp_path, telemetry="on", ledger="on",
                      watchdog={"action": "abort"},
                      trace_dir=str(tmp_path / "trace"))
    exp = FedExperiment(cfg, 0)
    exp.tracer = TraceRecorder(str(tmp_path / "trace" / exp.tag))
    logger = Logger(str(tmp_path / "runs" / "x"))
    logger.safe(True)
    ms = {"n": np.ones(2, np.float32), "loss_sum": np.ones(2, np.float32)}
    with pytest.warns(UserWarning, match="nonfinite"):
        with pytest.raises(WatchdogError, match="nonfinite"):
            exp._observe(logger, 3, {"nonfinite": 2}, ms)
    assert exp.tracer.closed
    lines = [json.loads(l) for l in open(exp.tracer.events_path)]
    assert lines[-1]["name"] == "watchdog"
    assert lines[-1]["args"]["kind"] == "nonfinite"
    trace = json.load(open(exp.tracer.trace_path))
    assert any(e["name"] == "watchdog" for e in trace["traceEvents"])
    assert os.path.exists(exp._ledger_path())
    logger.safe(False)


def test_split_probes_passthrough_without_hist():
    """A telemetry='on' (scalar-probe) metrics tree has no hist keys; the
    split must not invent them."""
    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    mesh = make_mesh(4, 1)
    eng = RoundEngine(model, dict(cfg, telemetry="on"), mesh)
    p = model.init(jax.random.key(0))
    _, ms = eng.train_round(p, jax.random.key(1), 0.05,
                            np.array([0, 2, 4, 6]), data)
    _, probes = split_probes({k: np.asarray(v) for k, v in ms.items()}, 4)
    assert probes and not any(k.startswith("hist_") for k in probes[0])
