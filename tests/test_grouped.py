"""Mesh-native rate-grouped engine (parallel/grouped.py): round-level
equivalence with the masked engine on single- and multi-device meshes, and
the FLOP account that motivates it (the masked strategy's ~3.9x overhead at
the canonical a1-e1 mix, MEASUREMENTS.md roofline)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from heterofl_tpu.fed.core import (embed_sliced, embed_sliced_jnp, extract_sliced,
                                   extract_sliced_jnp)
from heterofl_tpu.models import make_model
from heterofl_tpu.parallel import GroupedRoundEngine, RoundEngine, make_mesh

from test_round import _vision_setup


def test_jnp_slice_embed_match_host():
    """The in-jit static slice/pad twins agree with the host gather/scatter
    for every parameter at every level (incl. per-head and label axes)."""
    from test_models import small_cfg

    cfg = small_cfg("transformer", data_name="WikiText2",
                    control="1_8_0.5_iid_fix_a1-e1_none_1_1")
    model = make_model(cfg)
    params = {k: np.asarray(v) for k, v in model.init(jax.random.key(0)).items()}
    shapes = {k: v.shape for k, v in params.items()}
    for wr in (1.0, 0.5, 0.0625):
        host = extract_sliced(params, model.specs, model.groups, wr)
        dev = jax.jit(lambda p: extract_sliced_jnp(p, model.specs, model.groups, wr))(params)
        for k in params:
            np.testing.assert_array_equal(host[k], np.asarray(dev[k]), err_msg=k)
        back_h = embed_sliced(host, model.specs, model.groups, wr, shapes)
        back_d = jax.jit(lambda p: embed_sliced_jnp(p, model.specs, model.groups, wr))(dev)
        for k in params:
            np.testing.assert_array_equal(back_h[k], np.asarray(back_d[k]), err_msg=k)


def test_bucket_pow2_bounds_compile_space():
    from heterofl_tpu.parallel.grouped import _bucket_pow2

    assert [_bucket_pow2(n) for n in (1, 2, 3, 4, 5, 8, 9)] == [1, 2, 4, 4, 8, 8, 16]
    # the per-level program cache keys on (rate, bucketed slots): across any
    # count sequence 1..A the distinct keys per level are O(log A), which is
    # the whole point of bucketing (a per-round pattern key would be the
    # cross-product)
    A = 100
    n_dev = 8
    from heterofl_tpu.parallel.round_engine import _ceil_div

    keys = {_bucket_pow2(_ceil_div(c, n_dev)) * n_dev for c in range(1, A + 1)}
    assert len(keys) <= 5, keys  # log2(100/8) + 1


def _run_pair(n_clients, n_data, user_idx, control="1_8_0.5_iid_fix_a1-b1-c1-d1-e1_bn_1_1"):
    cfg, ds, data = _vision_setup(control=control)
    model = make_model(cfg)
    params = model.init(jax.random.key(0))
    rates = np.asarray(cfg["model_rate"], np.float32)[user_idx]
    key, lr = jax.random.key(42), 0.05

    eng = RoundEngine(model, cfg, make_mesh(n_clients, n_data))
    new_masked, ms_m = eng.train_round(params, key, lr, user_idx, data)

    grp = GroupedRoundEngine(cfg, make_mesh(n_clients, n_data))
    params2 = model.init(jax.random.key(0))
    new_grouped, ms_g = grp.train_round(params2, user_idx, rates, data, lr, key)
    return new_masked, new_grouped, ms_m, ms_g


def test_grouped_matches_masked_single_device():
    user_idx = np.array([0, 2, 4, 6], np.int32)  # levels a, b, c, d
    new_m, new_g, ms_m, ms_g = _run_pair(1, 1, user_idx)
    for k in new_m:
        np.testing.assert_allclose(np.asarray(new_m[k]), np.asarray(new_g[k]),
                                   rtol=5e-4, atol=5e-5, err_msg=k)
    # per-user metrics agree (masked orders by slot = user order here)
    np.testing.assert_allclose(np.asarray(ms_m["n"])[:4], ms_g["n"], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(ms_m["loss_sum"])[:4], ms_g["loss_sum"],
                               rtol=5e-3, atol=1e-4)


@pytest.mark.slow
def test_grouped_matches_masked_multidevice():
    """8-device clients mesh: same new globals as the masked engine -- the
    VERDICT r4 'done' bar.  Clients-axis sharding is association-exact (the
    psum addends are identical), so the tolerance stays as tight as the
    single-device dense-vs-masked comparison."""
    user_idx = np.array([0, 2, 4, 6, 1, 3], np.int32)
    new_m, new_g, _, ms_g = _run_pair(8, 1, user_idx)
    for k in new_m:
        np.testing.assert_allclose(np.asarray(new_m[k]), np.asarray(new_g[k]),
                                   rtol=5e-4, atol=5e-5, err_msg=k)
    assert (ms_g["n"] > 0).all() and np.isfinite(ms_g["loss_sum"]).all()


@pytest.mark.slow
def test_grouped_matches_masked_with_data_axis():
    """(4 clients x 2 data) mesh: the intra-client batch-DP axis changes
    float association inside every local step (grad/BN psums over batch
    halves), which the dense-vs-masked compute difference amplifies --
    measured ~1.4e-4 max abs drift for the MASKED engine alone between 1x1
    and 4x2 meshes.  Equivalence here is at that association tolerance."""
    user_idx = np.array([0, 2, 4, 6, 1, 3], np.int32)
    new_m, new_g, _, ms_g = _run_pair(4, 2, user_idx)
    for k in new_m:
        np.testing.assert_allclose(np.asarray(new_m[k]), np.asarray(new_g[k]),
                                   rtol=5e-2, atol=5e-4, err_msg=k)
    assert (ms_g["n"] > 0).all() and np.isfinite(ms_g["loss_sum"]).all()


@pytest.mark.slow
def test_grouped_lm_matches_masked():
    from test_round import _lm_setup

    # smallest level here is c (0.25): the tiny 32-dim test embedding needs
    # emb*rate >= num_heads(4) for the per-head q/k/v slicing to be valid
    cfg, data = _lm_setup(control="1_4_0.5_iid_fix_a1-b1-c1_bn_1_1")
    model = make_model(cfg)
    params = model.init(jax.random.key(0))
    user_idx = np.array([0, 1, 3], np.int32)
    rates = np.asarray(cfg["model_rate"], np.float32)[user_idx]
    key, lr = jax.random.key(7), 0.1
    eng = RoundEngine(model, cfg, make_mesh(1, 1))
    new_m, _ = eng.train_round(params, key, lr, user_idx, data)
    grp = GroupedRoundEngine(cfg, make_mesh(1, 1))
    new_g, ms_g = grp.train_round(model.init(jax.random.key(0)), user_idx, rates,
                                  data, lr, key)
    for k in new_m:
        np.testing.assert_allclose(np.asarray(new_m[k]), np.asarray(new_g[k]),
                                   rtol=1e-3, atol=1e-4, err_msg=k)
    assert (ms_g["n"] > 0).all()


@pytest.mark.slow
def test_level_slices_placement_matches_span():
    """level_placement='slices': each level's dense program runs on its own
    FLOP-share-proportional slice of the clients axis (concurrent dispatch
    to disjoint devices -- the pod layout of the roofline).  Same round
    result as the default span placement."""
    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    user_idx = np.array([0, 2, 4, 6, 1, 3], np.int32)
    rates = np.asarray(cfg["model_rate"], np.float32)[user_idx]
    key, lr = jax.random.key(5), 0.05

    span = GroupedRoundEngine(cfg, make_mesh(8, 1))
    new_a, ms_a = span.train_round(model.init(jax.random.key(0)), user_idx, rates,
                                   data, lr, key)
    sl = GroupedRoundEngine(dict(cfg, level_placement="slices"), make_mesh(8, 1))
    new_b, ms_b = sl.train_round(model.init(jax.random.key(0)), user_idx, rates,
                                 data, lr, key)
    np.testing.assert_allclose(ms_a["n"], ms_b["n"], rtol=0)
    for k in new_a:
        np.testing.assert_allclose(np.asarray(new_a[k]), np.asarray(new_b[k]),
                                   rtol=5e-4, atol=5e-5, err_msg=k)


def test_mesh_slices_partition():
    """Static row allocation: proportional to expected count x rate^2,
    >=1 row per level, exactly covers the axis, span fallback when
    rows < levels."""
    cfg, ds, data = _vision_setup()  # 5 levels over 8 users
    grp = GroupedRoundEngine(dict(cfg, level_placement="slices"), make_mesh(8, 1))
    assert grp.level_placement == "slices"
    sl = grp._slices
    level_rates = sorted(sl, reverse=True)
    widths = [sl[r][1] - sl[r][0] for r in level_rates]
    assert all(w >= 1 for w in widths) and sum(widths) == 8
    assert widths[0] == max(widths)  # full-width level owns the most rows
    # contiguous non-overlapping cover of [0, 8)
    lo = 0
    for r in level_rates:
        assert sl[r][0] == lo
        lo = sl[r][1]
    assert lo == 8
    # fewer rows than levels: constructor falls back to span
    grp2 = GroupedRoundEngine(dict(cfg, level_placement="slices"), make_mesh(2, 1))
    assert grp2.level_placement == "span"


def test_grouped_slices_multiprocess_k1_refused(monkeypatch):
    """ISSUE 17: slices no longer falls back on a multi-process mesh -- the
    host-aligned partition is derived from the MESH devices, so a
    monkeypatched process_count alone (devices all on process 0) keeps the
    single-row chunks and the slices placement.  What IS refused
    multi-process is the K=1 host-orchestrated train_round, which would
    dispatch each level onto a sub-mesh some processes have no devices in."""
    cfg, ds, data = _vision_setup()
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    g = GroupedRoundEngine(dict(cfg, level_placement="slices"), make_mesh(8, 1))
    assert g.level_placement == "slices" and g._slices
    user_idx = np.array([0, 2, 4, 6], np.int32)
    rates = np.asarray(cfg["model_rate"], np.float32)[user_idx]
    with pytest.raises(ValueError, match="fused superstep"):
        g.train_round(make_model(cfg).init(jax.random.key(0)), user_idx,
                      rates, data, 0.05, jax.random.key(1))


def test_grouped_slices_fallback_is_loud_and_strict_refuses():
    """ISSUE 17 satellite: an unhonourable slices placement falls back to
    span with a STRUCTURED warning naming the reason, and raises under
    strict_placement.  A single-level control leaves nothing to slice --
    the simplest unhonourable case on any mesh."""
    cfg, ds, data = _vision_setup(control="1_8_0.5_iid_fix_a1_bn_1_1")
    with pytest.warns(UserWarning, match="slices-fallback") as rec:
        g = GroupedRoundEngine(dict(cfg, level_placement="slices"), make_mesh(8, 1))
    assert g.level_placement == "span" and not g._slices
    msg = str(rec[0].message)
    assert "nothing to slice" in msg and '"processes"' in msg
    with pytest.raises(ValueError, match="strict_placement"):
        GroupedRoundEngine(dict(cfg, level_placement="slices",
                                strict_placement=True), make_mesh(8, 1))


def test_grouped_slice_align_partitions_and_refuses():
    """cfg['slice_align']=n forces C/n equal row units (the single-process
    pod reference): boundaries land only on multiples of C/n, and a
    non-divisible n is unhonourable (strict -> ValueError)."""
    cfg, ds, data = _vision_setup(control="1_8_0.5_iid_fix_a1-b1_bn_1_1")
    g = GroupedRoundEngine(dict(cfg, level_placement="slices", slice_align=2),
                           make_mesh(8, 1))
    assert g.level_placement == "slices"
    bounds = sorted(hi for _, hi in g._slices.values())
    assert all(hi % 4 == 0 for hi in bounds), g._slices
    assert g._clients_row_chunks() == [(0, 4), (4, 8)]
    with pytest.raises(ValueError, match="strict_placement"):
        GroupedRoundEngine(dict(cfg, level_placement="slices", slice_align=3,
                                strict_placement=True), make_mesh(8, 1))


@pytest.mark.slow
def test_grouped_failure_injection_matches_masked():
    """client_failure_rate: the grouped engine derives the alive set from
    the same failure_stream_key stream as the masked engine, so with the
    same key the same clients crash and the aggregates match."""
    cfg, ds, data = _vision_setup()
    cfg = dict(cfg, client_failure_rate=0.75)  # P(nobody crashes) ~ 0.4%
    model = make_model(cfg)
    user_idx = np.array([0, 2, 4, 6], np.int32)
    rates = np.asarray(cfg["model_rate"], np.float32)[user_idx]
    key, lr = jax.random.key(3), 0.05
    eng = RoundEngine(model, cfg, make_mesh(1, 1))
    new_m, ms_m = eng.train_round(model.init(jax.random.key(0)), key, lr, user_idx, data)
    grp = GroupedRoundEngine(cfg, make_mesh(1, 1))
    new_g, ms_g = grp.train_round(model.init(jax.random.key(0)), user_idx, rates,
                                  data, lr, key)
    # same crash pattern (n==0 <=> failed in both engines) -- the semantic
    # claim; per-element params are pinned by the dedicated equivalence
    # tests, here only guarded against gross divergence (float association
    # between dense and masked compute amplifies over 250 momentum steps)
    np.testing.assert_array_equal(np.asarray(ms_m["n"])[:4] > 0, ms_g["n"] > 0)
    assert (np.asarray(ms_m["n"])[:4] == 0).any(), "rate 0.75 should crash someone"
    for k in new_m:
        np.testing.assert_allclose(np.asarray(new_m[k]), np.asarray(new_g[k]),
                                   rtol=5e-2, atol=5e-4, err_msg=k)


@pytest.mark.slow
def test_grouped_dynamic_mode_matches_masked():
    """Dynamic mode: the masked engine re-rolls rates in-jit from
    fold_in(key, 7); the grouped host wrapper receives rates drawn from the
    same stream (fed.core.round_rates, as entry/common.py does), so the
    level grouping matches the in-jit draw and the rounds agree."""
    from heterofl_tpu.fed.core import round_rates

    cfg, ds, data = _vision_setup(control="1_8_0.5_iid_dynamic_a1-b1-c1-d1-e1_bn_1_1")
    model = make_model(cfg)
    user_idx = np.array([0, 2, 5, 7], np.int32)
    key, lr = jax.random.key(11), 0.05
    eng = RoundEngine(model, cfg, make_mesh(1, 1))
    new_m, ms_m = eng.train_round(model.init(jax.random.key(0)), key, lr, user_idx, data)
    rates = np.asarray(round_rates(key, cfg, jnp.asarray(user_idx)))
    grp = GroupedRoundEngine(cfg, make_mesh(1, 1))
    new_g, ms_g = grp.train_round(model.init(jax.random.key(0)), user_idx, rates,
                                  data, lr, key)
    # the semantic claim: host draw == in-jit draw, level grouping included
    np.testing.assert_allclose(np.asarray(ms_m["rate"])[:4], ms_g["rate"], rtol=0)
    # gross-divergence guard only (see failure-injection test note)
    for k in new_m:
        np.testing.assert_allclose(np.asarray(new_m[k]), np.asarray(new_g[k]),
                                   rtol=5e-2, atol=5e-4, err_msg=k)


def _flops(compiled):
    from heterofl_tpu.analysis import cost_analysis_dict

    return cost_analysis_dict(compiled)["flops"]


def test_grouped_flop_account():
    """The point of the engine: at a heterogeneous mix the grouped program
    spends a small fraction of the masked program's FLOPs (dense per-level
    vs full-width-for-everyone).  Tiny widths here; the flagship-width
    account lives in scripts/grouped_flops.py / MEASUREMENTS.md."""
    cfg, ds, data = _vision_setup()
    model = make_model(cfg)
    params = model.init(jax.random.key(0))
    user_idx = np.array([0, 2, 4, 6], np.int32)  # a, b, c, d -- no full-width-only mix
    rates = np.asarray(cfg["model_rate"], np.float32)[user_idx]
    mesh = make_mesh(1, 1)
    key, lr = jax.random.key(0), jnp.float32(0.05)

    eng = RoundEngine(model, cfg, mesh)
    if eng._train is None:
        eng._train = eng._build_train()
    n_dev = 1
    ug = jnp.asarray(user_idx)
    args = tuple(data) + ((jnp.asarray(eng.fix_rates),) if eng.fix_rates is not None else ())
    masked_flops = _flops(eng._train.lower(params, key, lr, ug, ug, *args).compile())

    grp = GroupedRoundEngine(cfg, mesh)
    by = {}
    for pos, r in enumerate(rates):
        by.setdefault(float(r), []).append(pos)
    grouped_flops = 0.0
    sums, cnts = [], []
    for r in sorted(by, reverse=True):
        u = jnp.asarray(np.asarray(user_idx[by[r]], np.int32))
        prog = grp._level_prog(r, len(by[r]))
        grouped_flops += _flops(prog.lower(params, key, lr, u, *tuple(data)).compile())
        s, c, _ = prog(params, key, lr, u, *tuple(data))
        sums.append(s)
        cnts.append(c)
    grouped_flops += _flops(grp._combine_prog(len(sums)).lower(params, sums, cnts).compile())

    ratio = masked_flops / grouped_flops
    # at the tiny test widths ceil() keeps small levels relatively wide, so
    # the bound is looser than the flagship ~3.9x
    assert ratio > 1.5, (masked_flops, grouped_flops, ratio)
