"""Sequence-parallel transformer: sharded-loss parity with a single device
and long-sequence training progress."""

import pytest

import jax
import jax.numpy as jnp
import numpy as np

from heterofl_tpu import config as C
from heterofl_tpu.models import make_model
from heterofl_tpu.parallel import make_mesh
from heterofl_tpu.parallel.long_context import SeqParallelLM

# ring-attention grad compiles over the data axis (fast gate excludes this module)
pytestmark = pytest.mark.slow


def _cfg(bptt=128):
    cfg = C.default_cfg()
    cfg["control"] = C.parse_control_name("1_4_0.5_iid_fix_a1_bn_1_1")
    cfg["data_name"] = "WikiText2"
    cfg["model_name"] = "transformer"
    cfg = C.process_control(cfg)
    cfg["transformer"] = {"embedding_size": 32, "num_heads": 4, "hidden_size": 64,
                          "num_layers": 2, "dropout": 0.0}
    cfg["bptt"] = bptt
    cfg["mask_rate"] = 0.0  # deterministic forward for the parity check
    cfg["num_tokens"] = 60
    cfg["classes_size"] = 60
    return cfg


def test_seq_parallel_forward_matches_dense():
    cfg = _cfg(bptt=128)
    mesh = make_mesh(1, 8)
    sp = SeqParallelLM(cfg, mesh)
    params = sp.init(jax.random.key(0))
    labels = jnp.asarray(np.random.default_rng(0).integers(0, 60, (2, 128)))
    loss_sp = float(sp.forward(params, labels, jax.random.key(1)))
    dense = make_model(cfg)  # same arch, dense attention
    out, _ = dense.apply(params, {"label": labels}, train=False, rng=jax.random.key(1))
    assert abs(loss_sp - float(out["loss"])) < 2e-4, (loss_sp, float(out["loss"]))


def test_seq_parallel_training_reduces_loss():
    cfg = _cfg(bptt=256)
    cfg["mask_rate"] = 0.15
    mesh = make_mesh(2, 4)  # batch over 'clients', sequence over 'data'
    sp = SeqParallelLM(cfg, mesh)
    params = sp.init(jax.random.key(0))
    opt = sp.init_opt(params)
    rng = np.random.default_rng(1)
    labels = jnp.asarray(rng.integers(0, 60, (4, 256)))
    losses = []
    for i in range(8):
        params, opt, loss = sp.train_step(params, opt, labels, jax.random.key(i), 0.5)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
