"""Experiment grid generator.

Parity: ``src/make.py`` / ``src/make_ablation.py`` -- build the cartesian
product of control strings (singles a1..e1, dynamic multi-level combinations,
9-step two-level interpolation pairs; ablation grids over norm/scale/mask)
and emit a bash script of entry-point invocations with ``wait`` barriers
every ``--round`` jobs (ref make.py:88-98).

TPU flavour: instead of round-robining ``CUDA_VISIBLE_DEVICES`` (ref
make.py:31), jobs are grouped into waves that each own the host's TPU; an
optional ``--hosts`` list round-robins jobs across machines via a
``HOST=<name>`` env prefix your launcher can interpret.
"""

from __future__ import annotations

import argparse
import itertools
from typing import Dict, List

LEVELS = ["a", "b", "c", "d", "e"]


def single_modes(levels: List[str] = LEVELS) -> List[str]:
    return [x + "1" for x in levels]


def combination_modes(levels: List[str] = LEVELS) -> List[str]:
    """All >=2-level equal-proportion combinations (ref make.py:57-61)."""
    singles = single_modes(levels)
    out: List[str] = []
    for i in range(1, len(singles) + 1):
        out.extend("-".join(x) for x in itertools.combinations(singles, i))
    return out[len(singles):]


def interp_modes(levels: List[str] = LEVELS) -> List[str]:
    """Two-level proportion sweeps xi-y(10-i), i=1..9 (ref make.py:62-66)."""
    out = []
    for i in range(1, 10):
        for j in range(len(levels)):
            for k in range(j + 1, len(levels)):
                out.append(f"{levels[j]}{i}-{levels[k]}{10 - i}")
    return out


MODEL_TABLE = {
    "conv": ("MNIST", "classifier"),
    "resnet18": ("CIFAR10", "classifier"),
    "transformer": ("WikiText2", "transformer"),
}


def build_controls(model: str, fed: int, data_split_mode: str, ablation: bool = False
                   ) -> List[str]:
    """Control strings for one model family (ref make.py:67-82 and
    make_ablation.py:55-85)."""
    if ablation:
        levels = ["a", "e"]
        combo = combination_modes(levels)
        norm_1, norm_2 = ["bn", "none"], ["in", "ln", "gn"]
        if data_split_mode == "iid":
            blocks = [
                [["1"], ["100"], ["0.1"], [data_split_mode], ["fix"], single_modes(levels),
                 norm_2 + norm_1, ["1"], ["1"]],
                [["1"], ["100"], ["0.1"], [data_split_mode], ["dynamic"], combo, norm_2, ["1"], ["1"]],
                [["1"], ["100"], ["0.1"], [data_split_mode], ["dynamic"], combo, norm_1, ["0", "1"], ["1"]],
            ]
        else:
            blocks = [
                [["1"], ["100"], ["0.1"], [data_split_mode], ["fix"], single_modes(levels), norm_2, ["1"], ["1"]],
                [["1"], ["100"], ["0.1"], [data_split_mode], ["fix"], single_modes(levels), norm_1, ["1"], ["0", "1"]],
                [["1"], ["100"], ["0.1"], [data_split_mode], ["dynamic"], combo, norm_2, ["1"], ["1"]],
            ]
    elif fed == 0:
        blocks = [[["0"], ["1"], ["1"], [data_split_mode], ["fix"], single_modes(), ["bn"], ["1"], ["1"]]]
    else:
        blocks = [
            [["1"], ["100"], ["0.1"], [data_split_mode], ["fix"], single_modes(), ["bn"], ["1"], ["1"]],
            [["1"], ["100"], ["0.1"], [data_split_mode], ["dynamic"], combination_modes(), ["bn"], ["1"], ["1"]],
            [["1"], ["100"], ["0.1"], [data_split_mode], ["fix"], interp_modes(), ["bn"], ["1"], ["1"]],
        ]
    out: List[str] = []
    for b in blocks:
        out.extend("_".join(x) for x in itertools.product(*b))
    return out


def make_script(run: str, model: str, fed: int, data_split_mode: str, *,
                init_seed: int = 0, num_experiments: int = 1, experiment_step: int = 1,
                resume_mode: int = 0, round_size: int = 1, hosts: List[str] = (),
                ablation: bool = False, synthetic: bool = False,
                modes: List[str] = (), extra_args: str = "") -> str:
    """``modes``: optional model_mode whitelist (6th control field) to carve a
    small-scale slice of the grid; ``extra_args``: verbatim CLI suffix for
    every job (e.g. ``--output_dir ... --override '{...}'``)."""
    data_name, family = MODEL_TABLE[model]
    suffix = "_fed" if fed == 1 else ""
    module = f"heterofl_tpu.entry.{run}_{family}{suffix}"
    controls = build_controls(model, fed, data_split_mode if fed else "none", ablation)
    if modes:
        want = set(modes)
        controls = [c for c in controls if c.split("_")[5] in want]
    seeds = list(range(init_seed, init_seed + num_experiments, experiment_step))
    lines = ["#!/bin/bash"]
    k = 0
    extra = " --synthetic 1" if synthetic else ""
    if extra_args:
        extra += " " + extra_args.strip()
    for seed in seeds:
        for ctl in controls:
            prefix = f"HOST={hosts[k % len(hosts)]} " if hosts else ""
            lines.append(
                f"{prefix}python -m {module} --data_name {data_name} --model_name {model} "
                f"--init_seed {seed} --num_experiments {experiment_step} "
                f"--resume_mode {resume_mode} --control_name {ctl}{extra} &")
            if k % round_size == round_size - 1:
                lines[-1] = lines[-1][:-2]
                lines.append("wait")
            k += 1
    if lines[-1] != "wait":
        lines.append("wait")
    return "\n".join(lines) + "\n"


def main(argv=None):
    parser = argparse.ArgumentParser(description="experiment grid generator")
    parser.add_argument("--run", default="train", type=str)
    parser.add_argument("--model", default="resnet18", type=str)
    parser.add_argument("--fed", default=1, type=int)
    parser.add_argument("--init_seed", default=0, type=int)
    parser.add_argument("--round", default=1, type=int)
    parser.add_argument("--experiment_step", default=1, type=int)
    parser.add_argument("--num_experiments", default=1, type=int)
    parser.add_argument("--resume_mode", default=0, type=int)
    parser.add_argument("--data_split_mode", default="iid", type=str)
    parser.add_argument("--hosts", default="", type=str, help="comma-separated host list")
    parser.add_argument("--ablation", action="store_true")
    parser.add_argument("--synthetic", action="store_true")
    parser.add_argument("--modes", default="", type=str,
                        help="comma-separated model_mode whitelist (e.g. "
                             "'a1,b1,a5-b5') for a small-scale grid slice")
    parser.add_argument("--extra", default="", type=str,
                        help="verbatim CLI suffix appended to every job")
    args = parser.parse_args(argv)
    s = make_script(args.run, args.model, args.fed, args.data_split_mode,
                    init_seed=args.init_seed, num_experiments=args.num_experiments,
                    experiment_step=args.experiment_step, resume_mode=args.resume_mode,
                    round_size=args.round, hosts=[h for h in args.hosts.split(",") if h],
                    ablation=args.ablation, synthetic=args.synthetic,
                    modes=[m for m in args.modes.split(",") if m],
                    extra_args=args.extra)
    name = f"{args.run}_{args.model}_{args.data_split_mode if args.fed else 'none'}"
    if args.ablation:
        name += "_ablation"
    path = f"./{name}.sh"
    with open(path, "w") as f:
        f.write(s)
    print(s)
    print(f"# written to {path}")
    return s


if __name__ == "__main__":
    main()
