"""Post-hoc analysis tooling: model profiler, experiment grid generator,
result aggregation/plots (the reference's ``summary.py`` / ``make.py`` /
``process.py`` layer)."""


def cost_analysis_dict(compiled):
    """Normalise ``compiled.cost_analysis()`` across jax versions: newer
    jax returns the properties dict directly, older versions wrap it in a
    one-element list/tuple.  The one shim for every FLOP account (summary
    profiler, scripts/grouped_flops.py, tests/test_grouped.py)."""
    ca = compiled.cost_analysis()
    return ca[0] if isinstance(ca, (list, tuple)) else ca
