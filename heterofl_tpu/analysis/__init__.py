"""Post-hoc analysis tooling: model profiler, experiment grid generator,
result aggregation/plots (the reference's ``summary.py`` / ``make.py`` /
``process.py`` layer)."""
