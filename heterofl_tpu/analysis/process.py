"""Result aggregation and plotting.

Parity: ``src/process.py`` -- load ``output/result/*.pkl`` bundles produced
by the ``test_*`` entry points, nest/aggregate mean/std across seeds
(process.py:114-179), export a table (csv always, xlsx via pandas when
available, mirroring process.py:196-230), and render learning curves and the
accuracy-vs-params interpolation figures (process.py:233-342) using the
profiler bundles from :mod:`heterofl_tpu.analysis.summary`
(process.py:345-374).
"""

from __future__ import annotations

import glob
import os
import pickle
from collections import defaultdict
from typing import Any, Dict, List, Optional

import numpy as np

from .. import config as C

METRIC_KEYS = ("Global-Accuracy", "Global-Perplexity", "Global-Loss",
               "Local-Accuracy", "Local-Perplexity", "Local-Loss",
               "Accuracy", "Perplexity", "Loss")


def parse_tag(tag: str) -> Optional[Dict[str, str]]:
    """Invert ``make_model_tag``: ``seed_data[_subset]_model_<9 control fields>``.

    Anchored from the right at the exact control-field count
    (``len(C.CONTROL_KEYS)``), with each anchor field validated against its
    known domain so an underscored data name can never silently shift fields;
    the model name is anchored by registry membership (``MODEL_NAMES``), which
    keeps multi-part data names (e.g. ``Stacked_MNIST``) intact rather than
    mislabelling them.  Returns ``None`` for tags that fail validation.
    """
    parts = tag.split("_")
    n_ctl = len(C.CONTROL_KEYS)
    if len(parts) < 3 + n_ctl:
        return None
    ctl = dict(zip(C.CONTROL_KEYS, parts[-n_ctl:]))
    # validate the control anchor: any mismatch means the tag is not ours (or
    # an underscored field shifted the split) -- refuse rather than mislabel
    try:
        int(ctl["num_users"])
        float(ctl["frac"])
    except ValueError:
        return None
    if (ctl["fed"] not in ("0", "1") or ctl["norm"] not in C.NORM_TYPES
            or ctl["model_split_mode"] not in ("fix", "dynamic")
            or ctl["scale"] not in ("0", "1") or ctl["mask"] not in ("0", "1")):
        return None
    head = parts[:-n_ctl]
    try:
        int(head[0])
    except ValueError:
        return None
    if head[-1] not in C.MODEL_NAMES:
        return None
    mid = head[1:-1]  # data name parts + optional subset
    if not mid:
        return None
    # subset is a single token when present.  Longest registry match wins
    # (advisor r3): a full multi-token name that IS registered never loses its
    # tail to a spurious "subset"; only then is a registered prefix + exactly
    # one leftover token read as data_name + subset.  Unregistered names that
    # merely EXTEND a registered one (e.g. a custom "ImageFolder_Pets" with no
    # subset) remain ambiguous by construction and parse as prefix + subset --
    # avoid underscores in custom dataset names.
    DATASET_NAMES = C.VISION_DATASETS + C.FOLDER_DATASETS + C.LM_DATASETS
    if "_".join(mid) in DATASET_NAMES:
        data_name, subset = "_".join(mid), ""
    elif len(mid) >= 2 and "_".join(mid[:-1]) in DATASET_NAMES:
        data_name, subset = "_".join(mid[:-1]), mid[-1]
    else:
        # unknown dataset: keep the multi-token name intact rather than
        # splitting off a spurious "subset" from its tail
        data_name, subset = "_".join(mid), ""
    return {"seed": head[0], "data_name": data_name, "subset": subset,
            "model_name": head[-1], **ctl}


def load_results(output_dir: str) -> List[Dict[str, Any]]:
    rows = []
    for path in sorted(glob.glob(os.path.join(output_dir, "result", "*.pkl"))):
        tag = os.path.splitext(os.path.basename(path))[0]
        meta = parse_tag(tag)
        if meta is None:
            continue
        with open(path, "rb") as f:
            bundle = pickle.load(f)
        metrics: Dict[str, float] = {}
        hist = bundle.get("logger_history", {})
        for k in METRIC_KEYS:
            if f"test/{k}" in hist and hist[f"test/{k}"]:
                metrics[k] = float(hist[f"test/{k}"][-1])
        metrics.update({k: float(v) for k, v in bundle.get("metrics", {}).items()})
        rows.append({"tag": tag, **meta, "metrics": metrics,
                     "train_history": bundle.get("train_history", {})})
    return rows


def aggregate(rows: List[Dict[str, Any]]) -> Dict[str, Dict[str, Any]]:
    """Group by everything except seed; mean/std across seeds
    (ref process.py:114-179)."""
    groups: Dict[str, List[Dict[str, Any]]] = defaultdict(list)
    for r in rows:
        key = "_".join([r["data_name"], r["subset"], r["model_name"]]
                       + [r[k] for k in C.CONTROL_KEYS])
        groups[key].append(r)
    out = {}
    for key, rs in groups.items():
        metrics = defaultdict(list)
        for r in rs:
            for k, v in r["metrics"].items():
                metrics[k].append(v)
        out[key] = {
            "n_seeds": len(rs),
            "mean": {k: float(np.mean(v)) for k, v in metrics.items()},
            "std": {k: float(np.std(v)) for k, v in metrics.items()},
            "rows": rs,
        }
    return out


def export_table(agg: Dict[str, Dict[str, Any]], output_dir: str,
                 name: str = "result") -> str:
    """csv always; xlsx too when pandas+openpyxl are importable."""
    all_metrics = sorted({m for g in agg.values() for m in g["mean"]})
    header = ["experiment", "n_seeds"] + [f"{m}_mean" for m in all_metrics] \
        + [f"{m}_std" for m in all_metrics]
    lines = [",".join(header)]
    for key in sorted(agg):
        g = agg[key]
        row = [key, str(g["n_seeds"])]
        row += [f"{g['mean'].get(m, float('nan')):.6g}" for m in all_metrics]
        row += [f"{g['std'].get(m, float('nan')):.6g}" for m in all_metrics]
        lines.append(",".join(row))
    os.makedirs(output_dir, exist_ok=True)
    csv_path = os.path.join(output_dir, f"{name}.csv")
    with open(csv_path, "w") as f:
        f.write("\n".join(lines) + "\n")
    try:
        import pandas as pd

        df = pd.read_csv(csv_path)
        df.to_excel(os.path.join(output_dir, f"{name}.xlsx"), index=False)
    except Exception:
        pass
    return csv_path


def _plt():
    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        return plt
    except Exception:
        return None


def make_learning_curves(rows: List[Dict[str, Any]], output_dir: str,
                         metric: str = "Global-Accuracy") -> List[str]:
    """Per-experiment learning curves (ref process.py:300-342)."""
    plt = _plt()
    if plt is None:
        return []
    paths = []
    fig_dir = os.path.join(output_dir, "fig")
    os.makedirs(fig_dir, exist_ok=True)
    for r in rows:
        hist = r.get("train_history", {})
        series = hist.get(f"test/{metric}")
        if not series:
            continue
        fig, ax = plt.subplots(figsize=(6, 4))
        ax.plot(range(1, len(series) + 1), series)
        ax.set_xlabel("communication round")
        ax.set_ylabel(metric)
        ax.set_title(r["tag"], fontsize=8)
        ax.grid(True, alpha=0.3)
        p = os.path.join(fig_dir, f"lc_{r['tag']}.png")
        fig.savefig(p, dpi=120, bbox_inches="tight")
        plt.close(fig)
        paths.append(p)
    return paths


def make_interpolation_plot(agg: Dict[str, Dict[str, Any]], output_dir: str,
                            metric: str = "Global-Accuracy") -> Optional[str]:
    """Accuracy vs model-size ratio across model modes (ref process.py:233-299).

    The x position of a mode like ``a1-b9`` is its expected params ratio
    computed from the profiler bundles (``{data}_{model}_{mode}.pkl``,
    ref process.py:345-374); falls back to the width-rate-squared heuristic
    when profiles are absent.
    """
    plt = _plt()
    if plt is None or not agg:
        return None

    def mode_ratio(data_name, model_name, model_mode):
        parts = [(p[0], int(p[1:])) for p in model_mode.split("-")]
        # Use profiler bundles only if EVERY needed level (incl. the 'a'
        # normaliser) has one; otherwise fall back to the width-rate-squared
        # heuristic for ALL levels -- never mix the two unit systems.
        def load_params(level):
            path = os.path.join(output_dir, "result", f"{data_name}_{model_name}_{level}.pkl")
            if not os.path.exists(path):
                return None
            with open(path, "rb") as f:
                return pickle.load(f)["num_params"]

        needed = sorted({lvl for lvl, _ in parts} | {"a"})
        profiled = {lvl: load_params(lvl) for lvl in needed}
        if all(v is not None for v in profiled.values()):
            sizes = [profiled[lvl] / profiled["a"] for lvl, _ in parts]
        else:
            sizes = [C.MODEL_SPLIT_RATE[lvl] ** 2 for lvl, _ in parts]
        w = np.array([prop for _, prop in parts], np.float64)
        w = w / w.sum()
        return float(np.dot(w, np.array(sizes)))

    fig, ax = plt.subplots(figsize=(6, 4))
    xs, ys, labels = [], [], []
    for key in sorted(agg):
        g = agg[key]
        r0 = g["rows"][0]
        if metric not in g["mean"]:
            continue
        xs.append(mode_ratio(r0["data_name"], r0["model_name"], r0["model_mode"]))
        ys.append(g["mean"][metric])
        labels.append(r0["model_mode"])
    if not xs:
        plt.close(fig)
        return None
    order = np.argsort(xs)
    ax.plot(np.array(xs)[order], np.array(ys)[order], "o-")
    for x, y, lab in zip(xs, ys, labels):
        ax.annotate(lab, (x, y), fontsize=6)
    ax.set_xscale("log")
    ax.set_xlabel("model size ratio")
    ax.set_ylabel(metric)
    ax.grid(True, alpha=0.3)
    os.makedirs(os.path.join(output_dir, "fig"), exist_ok=True)
    p = os.path.join(output_dir, "fig", f"interp_{metric}.png")
    fig.savefig(p, dpi=120, bbox_inches="tight")
    plt.close(fig)
    return p


def main(argv=None):
    import argparse

    parser = argparse.ArgumentParser(description="result aggregation (process.py parity)")
    parser.add_argument("--output_dir", default="./output", type=str)
    parser.add_argument("--metric", default="Global-Accuracy", type=str)
    args = parser.parse_args(argv)
    rows = load_results(args.output_dir)
    agg = aggregate(rows)
    csv_path = export_table(agg, args.output_dir)
    lc = make_learning_curves(rows, args.output_dir, args.metric)
    interp = make_interpolation_plot(agg, args.output_dir, args.metric)
    print(f"{len(rows)} results -> {csv_path}; {len(lc)} learning curves; interp={interp}")
    return agg


if __name__ == "__main__":
    main()
