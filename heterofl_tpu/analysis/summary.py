"""Model profiler: params / FLOPs / memory per width level.

Parity: ``src/summary.py`` -- the reference walks every leaf module with
forward hooks and hand-written per-op FLOP formulas (summary.py:200-276),
emits a markdown table and saves ``{num_params, num_flops, space}`` per
``{data}_{model}_{mode}`` to ``output/result/`` (summary.py:44-47,182-197),
which ``process.py`` consumes for the communication/compute ratios.

Here the numbers come from the compiler itself: ``jax.jit(fwd).lower()
.compile().cost_analysis()`` gives exact HLO FLOPs/bytes for the fused
program -- no hand formulas to drift out of date.  Params/space are counted
from the param pytree.  A true *sliced* sub-model is built per rate level, so
the table reports the reference's communicated-model sizes (what a client
downloads), not the masked full-width execution footprint.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import config as C
from ..models import make_model


def profile_model(cfg: Dict[str, Any], model_rate: float, batch_size: Optional[int] = None
                  ) -> Dict[str, Any]:
    """Profile one sliced sub-model at ``model_rate``."""
    model = make_model(cfg, model_rate=model_rate)
    params = model.init(jax.random.key(0))
    num_params = int(sum(int(np.prod(v.shape)) for v in params.values()))
    space_mb = sum(v.size * v.dtype.itemsize for v in params.values()) / (1024 ** 2)
    if batch_size is None:
        bs = cfg["batch_size"]["train"] if isinstance(cfg["batch_size"], dict) \
            else cfg["batch_size"]
    else:
        bs = batch_size
    if model.meta["kind"] == "transformer":
        batch = {"label": jnp.zeros((bs, cfg["bptt"]), jnp.int32)}
    else:
        batch = {"img": jnp.zeros((bs,) + tuple(cfg["data_shape"]), jnp.float32),
                 "label": jnp.zeros((bs,), jnp.int32)}

    def fwd(p, b):
        out, _ = model.apply(p, b, train=True, scaler_rate=model.meta["scaler_rate"],
                             rng=jax.random.key(0))
        return out["loss"]

    flops, flops_error = float("nan"), None
    try:
        from . import cost_analysis_dict

        ca = cost_analysis_dict(jax.jit(fwd).lower(params, batch).compile())
        flops = float(ca.get("flops", float("nan")))
    except Exception as e:  # pragma: no cover - cost analysis availability varies
        flops_error = f"{type(e).__name__}: {e}"
    flops_source = "xla_cost_analysis"
    if not np.isfinite(flops):
        # never degrade silently (VERDICT r1 weak 7): fall back to the
        # analytic per-module count (x2: MACs -> flops, matching the HLO
        # convention so the field is unit-consistent across environments),
        # SAY so, and record the source in the result
        import sys

        flops = 2.0 * float(sum(r[4] for r in module_table(cfg, model_rate, bs)))
        flops_source = "analytic_2x_macs"
        print(f"summary: XLA cost_analysis unavailable"
              f"{' (' + flops_error + ')' if flops_error else ''}; "
              f"using analytic per-module FLOPs (2x MACs)", file=sys.stderr)
    per_param = [(k, tuple(v.shape), int(np.prod(v.shape))) for k, v in params.items()]
    return {"num_params": num_params, "num_flops": flops, "space_mb": space_mb,
            "batch_size": bs, "per_param": per_param, "model_rate": model_rate,
            "flops_source": flops_source,
            **({"flops_error": flops_error} if flops_error else {})}


def module_table(cfg: Dict[str, Any], model_rate: float, batch_size: Optional[int] = None
                 ) -> List[tuple]:
    """Per-leaf-module profile: ``(module, input_size, output_size, params,
    flops)`` rows, mirroring the reference's forward-hook walker + hand
    formulas (ref src/summary.py:68-152, 200-276: convs/linears count MACs,
    norms numel x2 when affine, relu/pool numel; its custom attention module
    is unsupported there and counts 0 -- here the attention matmuls are
    counted honestly as two extra batched-matmul rows per encoder layer).
    """
    from ..models import RESNET_BLOCKS, make_model, scaled_hidden

    model = make_model(cfg, model_rate=model_rate)
    params = model.init(jax.random.key(0))
    psize = {k: int(np.prod(v.shape)) for k, v in params.items()}
    if batch_size is None:
        bs = cfg["batch_size"]["train"] if isinstance(cfg["batch_size"], dict) \
            else cfg["batch_size"]
    else:
        bs = batch_size

    def mods(prefix):
        return sum(v for k, v in psize.items() if k == prefix or k.startswith(prefix + "."))

    rows: List[tuple] = []

    def add(name, insz, outsz, nparam, flops):
        rows.append((name, tuple(insz), tuple(outsz), int(nparam), int(flops)))

    kind = model.meta["kind"]
    if kind in ("conv", "resnet"):
        h0, w0, cin = cfg["data_shape"]

        def conv_row(name, h, w, ci, co, k, stride, bias):
            ho, wo = -(-h // stride), -(-w // stride)
            macs = k * k * ci * co * bs * ho * wo + (co * bs * ho * wo if bias else 0)
            add(name, (bs, h, w, ci), (bs, ho, wo, co), mods(name), macs)
            return ho, wo

        def norm_relu(norm_name, h, w, c):
            numel = bs * h * w * c
            if cfg["norm"] != "none":
                add(norm_name, (bs, h, w, c), (bs, h, w, c), mods(norm_name),
                    numel * 2)
            add(f"{norm_name}.relu", (bs, h, w, c), (bs, h, w, c), 0, numel)

    if kind == "conv":
        hidden = scaled_hidden(cfg["conv"]["hidden_size"], model_rate)
        h, w, ci = h0, w0, cin
        for i, co in enumerate(hidden):
            h_, w_ = conv_row(f"block{i}.conv", h, w, ci, co, 3, 1, True)
            norm_relu(f"block{i}.norm", h_, w_, co)
            if i < len(hidden) - 1:  # last pool dropped (ref conv.py:56)
                add(f"block{i}.pool", (bs, h_, w_, co), (bs, h_ // 2, w_ // 2, co), 0,
                    bs * h_ * w_ * co)
                h_, w_ = h_ // 2, w_ // 2
            h, w, ci = h_, w_, co
        add("avgpool", (bs, h, w, ci), (bs, ci), 0, bs * h * w * ci)
        add("linear", (bs, ci), (bs, cfg["classes_size"]), mods("linear"),
            bs * ci * cfg["classes_size"])
    elif kind == "resnet":
        num_blocks, bottleneck = RESNET_BLOCKS[cfg["model_name"]]
        hidden = scaled_hidden(cfg["resnet"]["hidden_size"], model_rate)
        expansion = 4 if bottleneck else 1
        h, w = h0, w0
        h, w = conv_row("conv1", h, w, cin, hidden[0], 3, 1, False)
        in_planes = hidden[0]
        for s in range(len(hidden)):
            strides = [1 if s == 0 else 2] + [1] * (num_blocks[s] - 1)
            for b, stride in enumerate(strides):
                pfx, planes = f"layer{s}.{b}", hidden[s]
                out_planes = planes * expansion
                norm_relu(f"{pfx}.n1", h, w, in_planes)  # pre-activation
                if bottleneck:
                    conv_row(f"{pfx}.conv1", h, w, in_planes, planes, 1, 1, False)
                    norm_relu(f"{pfx}.n2", h, w, planes)
                    h2, w2 = conv_row(f"{pfx}.conv2", h, w, planes, planes, 3, stride, False)
                    norm_relu(f"{pfx}.n3", h2, w2, planes)
                    conv_row(f"{pfx}.conv3", h2, w2, planes, out_planes, 1, 1, False)
                else:
                    h2, w2 = conv_row(f"{pfx}.conv1", h, w, in_planes, planes, 3, stride, False)
                    norm_relu(f"{pfx}.n2", h2, w2, planes)
                    conv_row(f"{pfx}.conv2", h2, w2, planes, planes, 3, 1, False)
                if stride != 1 or in_planes != out_planes:
                    conv_row(f"{pfx}.shortcut", h, w, in_planes, out_planes, 1, stride, False)
                h, w, in_planes = h2, w2, out_planes
        norm_relu("n4", h, w, in_planes)
        add("avgpool", (bs, h, w, in_planes), (bs, in_planes), 0, bs * h * w * in_planes)
        add("linear", (bs, in_planes), (bs, cfg["classes_size"]), mods("linear"),
            bs * in_planes * cfg["classes_size"])
    else:  # transformer
        from ..config import ceil_width

        E = ceil_width(cfg["transformer"]["embedding_size"], model_rate)
        F = ceil_width(cfg["transformer"]["hidden_size"], model_rate)
        L = cfg["transformer"]["num_layers"]
        T = cfg["bptt"]
        V = cfg["num_tokens"]
        ntok = bs * T
        add("embedding", (bs, T), (bs, T, E), mods("embedding"), ntok * E * 2)  # lookup+pos add, norm below
        for i in range(L):
            p = f"enc{i}"
            for hname in ("q", "k", "v", "o"):
                add(f"{p}.mha.{hname}", (bs, T, E), (bs, T, E), mods(f"{p}.mha.{hname}"),
                    ntok * E * E)
            H = cfg["transformer"]["num_heads"]
            add(f"{p}.mha.qk", (bs, T, E), (bs, H, T, T), 0, bs * H * T * T * (E // max(H, 1)))
            add(f"{p}.mha.av", (bs, H, T, T), (bs, T, E), 0, bs * H * T * T * (E // max(H, 1)))
            add(f"{p}.norm1", (bs, T, E), (bs, T, E), mods(f"{p}.norm1"), ntok * E * 2)
            add(f"{p}.ff.l1", (bs, T, E), (bs, T, F), mods(f"{p}.ff.l1"), ntok * E * F)
            add(f"{p}.gelu", (bs, T, F), (bs, T, F), 0, ntok * F)
            add(f"{p}.ff.l2", (bs, T, F), (bs, T, E), mods(f"{p}.ff.l2"), ntok * F * E)
            add(f"{p}.norm2", (bs, T, E), (bs, T, E), mods(f"{p}.norm2"), ntok * E * 2)
        add("dec.l1", (bs, T, E), (bs, T, E), mods("dec.l1"), ntok * E * E)
        add("dec.norm", (bs, T, E), (bs, T, E), mods("dec.norm"), ntok * E * 2)
        add("dec.l2", (bs, T, E), (bs, T, V), mods("dec.l2"), ntok * E * V)
    return rows


def make_summary(cfg: Dict[str, Any], rates: Optional[List[float]] = None,
                 output_dir: Optional[str] = None, save: bool = True) -> Dict[str, Any]:
    """Profile every width level and emit the markdown report + result pickles
    (ref summary.py:44-47: one bundle per ``{data}_{model}_{mode}``)."""
    if rates is None:
        rates = sorted(set(C.MODEL_SPLIT_RATE.values()), reverse=True)
    output_dir = output_dir or cfg["output_dir"]
    rows = []
    results = {}
    inv_rate = {v: k for k, v in C.MODEL_SPLIT_RATE.items()}
    for rate in rates:
        prof = profile_model(cfg, rate)
        mode = inv_rate.get(rate, f"{rate:g}")
        rows.append((mode, rate, prof["num_params"], prof["num_flops"], prof["space_mb"]))
        results[mode] = prof
        if save:
            path = os.path.join(output_dir, "result",
                                f"{cfg['data_name']}_{cfg['model_name']}_{mode}.pkl")
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(path, "wb") as f:
                pickle.dump({k: prof[k] for k in ("num_params", "num_flops", "space_mb",
                                                  "flops_source")}, f)
    lines = ["| mode | rate | params | fwd FLOPs/batch | space (MB) |",
             "|------|------|--------|-----------------|------------|"]
    base = rows[0]
    for mode, rate, p, fl, sp in rows:
        fl_s = f"{fl:.3e}" if np.isfinite(fl) else "n/a"
        lines.append(f"| {mode} | {rate:g} | {p:,} ({p/base[2]:.4f}x) | {fl_s} | {sp:.2f} |")
    report = "\n".join(lines)
    # per-leaf-module breakdown at the full rate (ref summary.py:126-152's
    # tabulate report: module / input / output / params / FLOPs)
    mt = module_table(cfg, rates[0])
    mod_lines = ["| module | input | output | params | MACs |",
                 "|--------|-------|--------|--------|------|"]
    for name, insz, outsz, p, fl in mt:
        mod_lines.append(f"| {name} | {'x'.join(map(str, insz))} | "
                         f"{'x'.join(map(str, outsz))} | {p:,} | {fl:,} |")
    mod_lines.append(f"| **total** | | | "
                     f"{sum(r[3] for r in mt):,} | {sum(r[4] for r in mt):,} |")
    module_report = "\n".join(mod_lines)
    if save:
        os.makedirs(output_dir, exist_ok=True)
        with open(os.path.join(output_dir, "summary.md"), "w") as f:
            f.write(f"# {cfg['data_name']} {cfg['model_name']} width summary\n\n"
                    f"{report}\n\n## Per-module profile (rate {rates[0]:g})\n\n"
                    f"{module_report}\n")
    return {"rows": rows, "report": report, "results": results,
            "module_table": mt, "module_report": module_report}


def main(argv=None):
    from ..entry.common import build_cli, cfg_from_args
    from ..data import fetch_dataset, process_dataset

    parser = build_cli("heterofl-tpu model profiler (summary.py parity)")
    args = parser.parse_args(argv)
    cfg = cfg_from_args(args)
    if args.control_name:
        cfg["control"] = C.parse_control_name(args.control_name)
    cfg = C.process_control(cfg)
    dataset = fetch_dataset(cfg["data_name"], cfg["data_dir"], synthetic=cfg["synthetic"],
                            synthetic_sizes=cfg.get("synthetic_sizes"),
                            subset=cfg.get("subset", "label"))
    cfg, _ = process_dataset(cfg, dataset)
    out = make_summary(cfg)
    print(out["report"])
    return out


if __name__ == "__main__":
    main()
