"""Model profiler: params / FLOPs / memory per width level.

Parity: ``src/summary.py`` -- the reference walks every leaf module with
forward hooks and hand-written per-op FLOP formulas (summary.py:200-276),
emits a markdown table and saves ``{num_params, num_flops, space}`` per
``{data}_{model}_{mode}`` to ``output/result/`` (summary.py:44-47,182-197),
which ``process.py`` consumes for the communication/compute ratios.

Here the numbers come from the compiler itself: ``jax.jit(fwd).lower()
.compile().cost_analysis()`` gives exact HLO FLOPs/bytes for the fused
program -- no hand formulas to drift out of date.  Params/space are counted
from the param pytree.  A true *sliced* sub-model is built per rate level, so
the table reports the reference's communicated-model sizes (what a client
downloads), not the masked full-width execution footprint.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import config as C
from ..models import make_model


def profile_model(cfg: Dict[str, Any], model_rate: float, batch_size: Optional[int] = None
                  ) -> Dict[str, Any]:
    """Profile one sliced sub-model at ``model_rate``."""
    model = make_model(cfg, model_rate=model_rate)
    params = model.init(jax.random.key(0))
    num_params = int(sum(int(np.prod(v.shape)) for v in params.values()))
    space_mb = sum(v.size * v.dtype.itemsize for v in params.values()) / (1024 ** 2)
    if batch_size is None:
        bs = cfg["batch_size"]["train"] if isinstance(cfg["batch_size"], dict) \
            else cfg["batch_size"]
    else:
        bs = batch_size
    if model.meta["kind"] == "transformer":
        batch = {"label": jnp.zeros((bs, cfg["bptt"]), jnp.int32)}
    else:
        batch = {"img": jnp.zeros((bs,) + tuple(cfg["data_shape"]), jnp.float32),
                 "label": jnp.zeros((bs,), jnp.int32)}

    def fwd(p, b):
        out, _ = model.apply(p, b, train=True, scaler_rate=model.meta["scaler_rate"],
                             rng=jax.random.key(0))
        return out["loss"]

    flops = None
    try:
        compiled = jax.jit(fwd).lower(params, batch).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        flops = float(ca.get("flops", float("nan")))
    except Exception as e:  # pragma: no cover - cost analysis availability varies
        flops = float("nan")
    per_param = [(k, tuple(v.shape), int(np.prod(v.shape))) for k, v in params.items()]
    return {"num_params": num_params, "num_flops": flops, "space_mb": space_mb,
            "batch_size": bs, "per_param": per_param, "model_rate": model_rate}


def make_summary(cfg: Dict[str, Any], rates: Optional[List[float]] = None,
                 output_dir: Optional[str] = None, save: bool = True) -> Dict[str, Any]:
    """Profile every width level and emit the markdown report + result pickles
    (ref summary.py:44-47: one bundle per ``{data}_{model}_{mode}``)."""
    if rates is None:
        rates = sorted(set(C.MODEL_SPLIT_RATE.values()), reverse=True)
    output_dir = output_dir or cfg["output_dir"]
    rows = []
    results = {}
    inv_rate = {v: k for k, v in C.MODEL_SPLIT_RATE.items()}
    for rate in rates:
        prof = profile_model(cfg, rate)
        mode = inv_rate.get(rate, f"{rate:g}")
        rows.append((mode, rate, prof["num_params"], prof["num_flops"], prof["space_mb"]))
        results[mode] = prof
        if save:
            path = os.path.join(output_dir, "result",
                                f"{cfg['data_name']}_{cfg['model_name']}_{mode}.pkl")
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(path, "wb") as f:
                pickle.dump({k: prof[k] for k in ("num_params", "num_flops", "space_mb")}, f)
    lines = ["| mode | rate | params | fwd FLOPs/batch | space (MB) |",
             "|------|------|--------|-----------------|------------|"]
    base = rows[0]
    for mode, rate, p, fl, sp in rows:
        fl_s = f"{fl:.3e}" if np.isfinite(fl) else "n/a"
        lines.append(f"| {mode} | {rate:g} | {p:,} ({p/base[2]:.4f}x) | {fl_s} | {sp:.2f} |")
    report = "\n".join(lines)
    if save:
        os.makedirs(output_dir, exist_ok=True)
        with open(os.path.join(output_dir, "summary.md"), "w") as f:
            f.write(f"# {cfg['data_name']} {cfg['model_name']} width summary\n\n{report}\n")
    return {"rows": rows, "report": report, "results": results}


def main(argv=None):
    from ..entry.common import build_cli, cfg_from_args
    from ..data import fetch_dataset, process_dataset

    parser = build_cli("heterofl-tpu model profiler (summary.py parity)")
    args = parser.parse_args(argv)
    cfg = cfg_from_args(args)
    if args.control_name:
        cfg["control"] = C.parse_control_name(args.control_name)
    cfg = C.process_control(cfg)
    dataset = fetch_dataset(cfg["data_name"], cfg["data_dir"], synthetic=cfg["synthetic"],
                            synthetic_sizes=cfg.get("synthetic_sizes"),
                            subset=cfg.get("subset", "label"))
    cfg, _ = process_dataset(cfg, dataset)
    out = make_summary(cfg)
    print(out["report"])
    return out


if __name__ == "__main__":
    main()
