"""Accuracy-parity harness: federated training trajectories, this framework
vs. the ACTUAL reference implementation, on identical data.

The component-level parity suite (tests/test_torch_parity.py) pins models,
slicing, aggregation and optimizers numerically; the only remaining
divergence is host-side sampling RNG.  This harness closes the loop
empirically: it runs the reference's own ``Federation`` + torch models
(imported from the read-only mount) through the reference's round structure
(distribute -> per-client torch SGD -> combine -> sBN recalibration -> test),
and this framework's jitted round engine, on the SAME synthetic dataset and
client splits, then reports both global-accuracy trajectories.

Usage: ``python -m heterofl_tpu.analysis.compare_reference --rounds 10``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List

import numpy as np

REF = "/root/reference/src"


def _import_reference():
    cwd = os.getcwd()
    os.chdir(REF)
    sys.path.insert(0, REF)
    try:
        from config import cfg as ref_cfg  # noqa
        import models as ref_models  # noqa
        from fed import Federation  # noqa
    finally:
        os.chdir(cwd)
        sys.path.remove(REF)
    return ref_cfg, ref_models, Federation


def _setup(seed: int, users: int, hidden, n_train: int, n_test: int,
           model_name: str = "conv", data_name: str = "MNIST", frac: float = 0.5,
           split_mode: str = "iid", local_epochs: int = 1,
           mode: str = "a1-b1-c1-d1-e1", model_split: str = "fix"):
    from ..config import default_cfg, parse_control_name, process_control
    from ..data import fetch_dataset, label_split_masks, split_dataset, stack_client_shards

    cfg = default_cfg()
    cfg["control"] = parse_control_name(
        f"1_{users}_{frac}_{split_mode}_{model_split}_{mode}_bn_1_1")
    cfg["data_name"] = data_name
    cfg["model_name"] = model_name
    cfg = process_control(cfg)
    cfg["conv"] = {"hidden_size": list(hidden)}
    widths = list(hidden)
    while len(widths) < 4:  # extend monotonically by doubling (resnet stages)
        widths.append(widths[-1] * 2)
    cfg["resnet"] = {"hidden_size": widths[:4]}
    cfg["num_epochs"] = {"global": 1, "local": local_epochs}
    cfg["batch_size"] = {"train": 10, "test": 50}
    # identical raw pixels for both frameworks; augmentation is OFF on both
    # sides (different RNG streams would otherwise blur the comparison)
    from ..data.datasets import DATASET_STATS

    cfg["norm_stats"] = DATASET_STATS[data_name]
    cfg["data_name"] = "SYNTH-" + data_name  # disables the CIFAR augment path
    ds = fetch_dataset(data_name, synthetic=True, seed=seed,
                       synthetic_sizes={"train": n_train, "test": n_test})
    cfg["classes_size"] = 10
    rng = np.random.default_rng(seed)
    split, lsplit = split_dataset(ds, users, split_mode, rng, classes_size=10)
    return cfg, ds, split, lsplit


def run_reference(cfg, ds, split, lsplit, rounds: int, seed: int, lr: float) -> List[float]:
    """The reference's federated loop, driven by its own components."""
    import torch

    ref_cfg, ref_models, Federation = _import_reference()
    model_name = cfg["model_name"]
    h, w, c = cfg["data_shape"]
    ref_cfg.update({
        "norm": "bn", "scale": True, "mask": True, "global_model_rate": 1.0,
        "classes_size": 10, "conv": dict(cfg["conv"]), "resnet": dict(cfg["resnet"]),
        "data_shape": [c, h, w],
        "device": "cpu", "model_name": model_name,
        # dynamic mode: Federation.distribute() re-rolls per-user rates from
        # cfg['proportion'] every round (ref fed.py:15-23,162); fix mode uses
        # the static per-user vector.  model_rate carries the level list in
        # dynamic mode and the per-user vector in fix mode, both sides
        # identically (ref utils.py:127-145 == config.py:189-199).
        "model_split_mode": cfg["model_split_mode"],
        "num_users": cfg["num_users"],
        "model_rate": list(cfg["model_rate"]),
        **({"proportion": list(cfg["proportion"])}
           if cfg["model_split_mode"] == "dynamic" else {}),
    })
    factory = getattr(ref_models, model_name)
    mean = np.asarray(cfg["norm_stats"][0], np.float32)
    std = np.asarray(cfg["norm_stats"][1], np.float32)

    def to_img(idx_list):
        x = ds["train"].data[idx_list].astype(np.float32) / 255.0
        x = (x - mean) / std  # broadcasts over the trailing channel axis
        return torch.tensor(x.transpose(0, 3, 1, 2).copy())

    torch.manual_seed(seed)
    model = factory(model_rate=1.0)
    fed = Federation({k: v.clone() for k, v in model.state_dict().items()},
                     list(cfg["model_rate"]), {i: lsplit[i] for i in lsplit})
    rng = np.random.default_rng(seed + 77)       # user sampling: shared stream
    shuffle_rng = np.random.default_rng(seed + 999)  # batch shuffles: private
    users = cfg["num_users"]
    n_active = int(np.ceil(cfg["frac"] * users))
    accs = []
    for r in range(rounds):
        user_idx = rng.permutation(users)[:n_active].tolist()
        local_params, param_idx = fed.distribute(user_idx)
        for m, u in enumerate(user_idx):
            rate = fed.model_rate[u]
            tm = factory(model_rate=float(rate))
            tm.load_state_dict(local_params[m])
            tm.train(True)
            opt = torch.optim.SGD(tm.parameters(), lr=lr, momentum=0.9, weight_decay=5e-4)
            idx = np.array(split["train"][u])
            B = cfg["batch_size"]["train"]
            for _ in range(cfg["num_epochs"]["local"]):
                perm = shuffle_rng.permutation(len(idx))
                for s in range(0, len(idx), B):
                    batch_idx = idx[perm[s: s + B]]
                    inp = {"img": to_img(batch_idx),
                           "label": torch.tensor(ds["train"].target[batch_idx]),
                           "label_split": torch.tensor(lsplit[u])}
                    opt.zero_grad()
                    out = tm(inp)
                    out["loss"].backward()
                    torch.nn.utils.clip_grad_norm_(tm.parameters(), 1)
                    opt.step()
            local_params[m] = tm.state_dict()
        fed.combine(local_params, param_idx, user_idx)
        # sBN recalibration with a fresh track=True model over the train set
        with torch.no_grad():
            test_model = factory(model_rate=1.0, track=True)
            test_model.load_state_dict(fed.global_parameters, strict=False)
            test_model.train(True)
            for s in range(0, len(ds["train"].data), 100):
                sl = np.arange(s, min(s + 100, len(ds["train"].data)))
                test_model({"img": to_img(sl), "label": torch.tensor(ds["train"].target[sl])})
            test_model.train(False)
            correct = 0
            xt = ds["test"].data.astype(np.float32) / 255.0
            xt = (xt - mean) / std  # broadcasts over the trailing channel axis
            out = test_model({"img": torch.tensor(xt.transpose(0, 3, 1, 2).copy()),
                              "label": torch.tensor(ds["test"].target)})
            correct = (out["score"].argmax(1).numpy() == ds["test"].target).mean()
        accs.append(float(correct * 100))
        if r % 5 == 0 or r == rounds - 1:
            print(f"ref round {r + 1}/{rounds} acc {accs[-1]:.1f}",
                  file=sys.stderr, flush=True)
    return accs


def _setup_lm(seed: int, users: int, n_train_tokens: int, n_test_tokens: int,
              frac: float, local_epochs: int, bptt: int, batch_rows: int, dims):
    """Synthetic-WikiText2 twin, batchified and iid-split over rows
    (ref utils.py:100-110 + data.py:61-76: LM "labels" are the tokens)."""
    from ..config import default_cfg, parse_control_name, process_control
    from ..data import fetch_dataset, split_dataset
    from ..data.pipeline import process_dataset

    cfg = default_cfg()
    cfg["control"] = parse_control_name(
        f"1_{users}_{frac}_iid_fix_a1-b1-c1-d1-e1_bn_1_1")
    cfg["data_name"] = "WikiText2"
    cfg["model_name"] = "transformer"
    cfg = process_control(cfg)
    cfg["transformer"] = dict(dims)
    cfg["bptt"] = bptt
    cfg["num_epochs"] = {"global": 1, "local": local_epochs}
    cfg["batch_size"] = {"train": batch_rows, "test": batch_rows}
    ds = fetch_dataset("WikiText2", synthetic=True, seed=seed,
                       synthetic_sizes={"train": n_train_tokens, "test": n_test_tokens})
    cfg, ds = process_dataset(cfg, ds)
    rng = np.random.default_rng(seed)
    split, lsplit = split_dataset(ds, users, "iid", rng)
    return cfg, ds, split, lsplit


def _patch_ref_encoder(tm):
    """The reference targets torch 1.7; modern ``nn.TransformerEncoder``'s
    fast-path probes ``layer.self_attn``, which its custom layer lacks.
    Replace the encoder forward with the plain layer loop (identical
    semantics)."""
    import types

    def plain_forward(self, src, mask=None, src_key_padding_mask=None):
        out = src
        for mod in self.layers:
            out = mod(out, src_mask=mask)
        if self.norm is not None:
            out = self.norm(out)
        return out

    tm.transformer_encoder.forward = types.MethodType(plain_forward, tm.transformer_encoder)
    return tm


def run_reference_lm(cfg, ds, split, lsplit, rounds: int, seed: int, lr: float) -> List[float]:
    """The reference's transformer federated loop (train_transformer_fed.py:
    100-183): per-user SGD over bptt windows of its rows, counted-average
    combine, global perplexity each round (no sBN for LM)."""
    import math

    import torch

    ref_cfg, ref_models, Federation = _import_reference()
    V = cfg["num_tokens"]
    ref_cfg.update({
        "scale": True, "mask": True, "global_model_rate": 1.0,
        "device": "cpu", "model_name": "transformer", "model_split_mode": "fix",
        "model_rate": list(cfg["model_rate"]), "classes_size": V,
        "num_tokens": V, "bptt": cfg["bptt"], "mask_rate": cfg["mask_rate"],
        "transformer": dict(cfg["transformer"]), "world_size": 1,
    })
    factory = lambda model_rate: _patch_ref_encoder(
        ref_models.transformer(model_rate=model_rate))
    torch.manual_seed(seed)
    model = factory(model_rate=1.0)
    fed = Federation({k: v.clone() for k, v in model.state_dict().items()},
                     list(cfg["model_rate"]), {i: lsplit[i] for i in lsplit})
    rng = np.random.default_rng(seed + 77)  # user sampling: shared stream
    users = cfg["num_users"]
    n_active = int(np.ceil(cfg["frac"] * users))
    rows_all = np.asarray(ds["train"].token, np.int64)
    test_rows = torch.tensor(np.asarray(ds["test"].token, np.int64))
    bptt = cfg["bptt"]
    ppls = []
    for r in range(rounds):
        user_idx = rng.permutation(users)[:n_active].tolist()
        local_params, param_idx = fed.distribute(user_idx)
        for m, u in enumerate(user_idx):
            rate = fed.model_rate[u]
            tm = factory(model_rate=float(rate))
            tm.load_state_dict(local_params[m])
            tm.train(True)
            opt = torch.optim.SGD(tm.parameters(), lr=lr, momentum=0.9,
                                  weight_decay=5e-4)
            urows = torch.tensor(rows_all[np.asarray(split["train"][u], np.int64)])
            T = urows.shape[1]
            for _ in range(cfg["num_epochs"]["local"]):
                # BatchDataset(bptt) iteration order: sequential windows,
                # short final window kept (ref data.py:136-150)
                for s in range(0, T, bptt):
                    inp = {"label": urows[:, s: s + bptt],
                           "label_split": torch.tensor(lsplit[u])}
                    opt.zero_grad()
                    out = tm(inp)
                    out["loss"].backward()
                    torch.nn.utils.clip_grad_norm_(tm.parameters(), 1)
                    opt.step()
            local_params[m] = tm.state_dict()
        fed.combine(local_params, param_idx, user_idx)
        model.load_state_dict(fed.global_parameters)
        model.train(False)
        # Global-Perplexity: row-weighted mean of exp(window CE) over the
        # batchified test stream (ref train_transformer_fed.py:127-143 with
        # metrics.py:16-25); the masked-LM corruption stays on in eval (the
        # reference quirk: Bernoulli draw is unconditional in forward)
        with torch.no_grad():
            tot = n = 0.0
            Tt = test_rows.shape[1]
            for s in range(0, Tt, bptt):
                out = model({"label": test_rows[:, s: s + bptt]})
                w = float(test_rows.shape[0])
                tot += math.exp(float(out["loss"])) * w
                n += w
        ppls.append(tot / max(n, 1.0))
    return ppls


def run_mine_lm(cfg, ds, split, lsplit, rounds: int, seed: int, lr: float) -> List[float]:
    import jax
    import jax.numpy as jnp

    from ..data import label_split_masks
    from ..data.pipeline import bptt_windows, stack_client_token_rows, stack_windows
    from ..models import make_model
    from ..parallel import RoundEngine, make_mesh
    from ..parallel.evaluation import Evaluator

    users = cfg["num_users"]
    rows = stack_client_token_rows(np.asarray(ds["train"].token), split["train"],
                                   list(range(users)))
    lm = label_split_masks(lsplit, users, cfg["num_tokens"])
    data = (jnp.asarray(rows), jnp.asarray(lm))
    model = make_model(cfg)
    params = model.init(jax.random.key(seed))
    mesh = make_mesh(min(len(jax.devices()), users), 1)
    eng = RoundEngine(model, cfg, mesh)
    ev = Evaluator(model, cfg, mesh, seed=seed)
    xs, ws = stack_windows(bptt_windows(np.asarray(ds["test"].token), cfg["bptt"]),
                           cfg["bptt"])
    rng = np.random.default_rng(seed + 77)
    n_active = int(np.ceil(cfg["frac"] * users))
    ppls = []
    for r in range(rounds):
        user_idx = rng.permutation(users)[:n_active].astype(np.int32)
        params, _ = eng.train_round(params, jax.random.fold_in(jax.random.key(seed), r),
                                    lr, user_idx, data)
        g = ev.eval_global(params, {}, xs, ws, epoch=r)
        ppls.append(float(g["score_sum"]) / max(float(g["n"]), 1.0))
    return ppls


def run_mine(cfg, ds, split, lsplit, rounds: int, seed: int, lr: float,
             partial_out: str = None) -> List[float]:
    import jax
    import jax.numpy as jnp

    from ..data import label_split_masks, stack_client_shards
    from ..models import make_model
    from ..parallel import RoundEngine, make_mesh
    from ..parallel.evaluation import Evaluator
    from ..entry.common import _batch_array

    users = cfg["num_users"]
    x, y, m = stack_client_shards(ds["train"].data, ds["train"].target, split["train"],
                                  list(range(users)))
    lm = label_split_masks(lsplit, users, 10)
    data = (jnp.asarray(x), jnp.asarray(y), jnp.asarray(m), jnp.asarray(lm))
    model = make_model(cfg)
    params = model.init(jax.random.key(seed))
    mesh = make_mesh(min(len(jax.devices()), users), 1)
    grouped = cfg.get("strategy") == "grouped"
    if grouped:
        from ..fed.core import round_rates
        from ..parallel import GroupedRoundEngine

        eng = GroupedRoundEngine(cfg, mesh)
    else:
        eng = RoundEngine(model, cfg, mesh)
    # eval/sBN run UNvmapped (no per-client kernels), where the direct conv
    # lowering is the faster one; conv_impl only pays off inside the engine
    cfg_eval = dict(cfg)
    cfg_eval["conv_impl"] = None
    ev = Evaluator(make_model(cfg_eval), cfg_eval, mesh, seed=seed)
    xb, wb = _batch_array(ds["train"].data, 100)
    xg, wg = _batch_array(ds["test"].data, 100)
    yg, _ = _batch_array(ds["test"].target, 100)
    rng = np.random.default_rng(seed + 77)
    n_active = int(np.ceil(cfg["frac"] * users))
    accs = []
    for r in range(rounds):
        user_idx = rng.permutation(users)[:n_active].astype(np.int32)
        key_r = jax.random.fold_in(jax.random.key(seed), r)
        if grouped:
            rates = np.asarray(round_rates(key_r, cfg, jnp.asarray(user_idx)))
            params, _ = eng.train_round(params, user_idx, rates, data, lr, key_r)
        else:
            params, _ = eng.train_round(params, key_r, lr, user_idx, data)
        bn = ev.sbn_stats(params, xb, wb)
        g = ev.eval_global(params, bn, xg, yg, wg)
        accs.append(100.0 * g["score_sum"] / max(g["n"], 1.0))
        if r % 5 == 0 or r == rounds - 1:
            # liveness + trajectory on stderr: multi-hour campaigns are
            # otherwise silent until the final JSON line
            print(f"mine round {r + 1}/{rounds} acc {accs[-1]:.1f}",
                  file=sys.stderr, flush=True)
        if partial_out and (r % 10 == 9 or r == rounds - 1):
            # salvageable partial curve for runs killed by the wall clock
            # (atomic like the final artifact)
            tmp = partial_out + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"mine_acc": accs, "partial_through_round": r + 1}, f)
            os.replace(tmp, partial_out)
    return accs


def main(argv=None):
    parser = argparse.ArgumentParser(description="accuracy parity vs the reference")
    parser.add_argument("--rounds", default=10, type=int)
    parser.add_argument("--users", default=8, type=int)
    parser.add_argument("--hidden", default="16,32", type=str)
    parser.add_argument("--n_train", default=1600, type=int)
    parser.add_argument("--n_test", default=400, type=int)
    parser.add_argument("--lr", default=0.01, type=float)
    parser.add_argument("--seed", default=0, type=int)
    parser.add_argument("--out", default=None, type=str)
    parser.add_argument("--model", default="conv", type=str,
                        choices=["conv", "resnet18", "transformer"])
    parser.add_argument("--data", default="MNIST", type=str,
                        choices=["MNIST", "CIFAR10", "WikiText2"])
    parser.add_argument("--bptt", default=16, type=int, help="LM window (transformer only)")
    parser.add_argument("--batch_rows", default=20, type=int,
                        help="LM batchify rows (transformer only)")
    parser.add_argument("--n_test_tokens", default=4000, type=int, help="transformer only")
    parser.add_argument("--emb", default=64, type=int,
                        help="transformer embedding size (must give >= 1 dim per "
                             "head at the smallest rate: emb*0.0625 >= heads)")
    parser.add_argument("--layers", default=2, type=int, help="transformer layers")
    parser.add_argument("--frac", default=0.5, type=float)
    parser.add_argument("--split", default="iid", type=str,
                        help="iid or non-iid-N (ref src/data.py:79-110)")
    parser.add_argument("--mode", default="a1-b1-c1-d1-e1", type=str,
                        help="model_mode control field, e.g. a1-b9 / a5-e5 "
                             "(ref src/make.py:55-66 interpolation grids)")
    parser.add_argument("--model_split", default="fix", type=str,
                        choices=["fix", "dynamic"],
                        help="fix: static per-user rates; dynamic: re-rolled "
                             "per round (ref fed.py:15-23)")
    parser.add_argument("--local_epochs", default=1, type=int)
    parser.add_argument("--conv_impl", default=None, type=str,
                        choices=["direct", "im2col"],
                        help="engine conv lowering: direct (default) | im2col "
                             "(numerically equivalent; much faster for the "
                             "client-vmapped round on CPU hosts)")
    parser.add_argument("--strategy", default="masked", type=str,
                        choices=["masked", "grouped"],
                        help="mine-side round engine: masked full-width (default) "
                             "or rate-grouped dense per-level programs "
                             "(parallel/grouped.py; round-equivalent)")
    parser.add_argument("--skip", default="", type=str,
                        help="'reference' or 'mine': emit only the other side")
    args = parser.parse_args(argv)
    if args.model == "transformer":
        # vision-only flags are ignored on the LM path -- loudly, not silently
        for flag, attr in (("--n_test", "n_test"), ("--hidden", "hidden"),
                           ("--conv_impl", "conv_impl"), ("--strategy", "strategy")):
            if getattr(args, attr) != parser.get_default(attr):
                print(f"warning: {flag} is ignored for --model transformer "
                      f"(use --n_test_tokens / --emb instead)", file=sys.stderr)
        if args.split != "iid":
            parser.error("--split is iid-only for transformer (the reference LM "
                         "path has no non-iid mode, ref data.py:62-67)")
        if args.emb * 0.0625 < 4:
            parser.error(
                f"--emb {args.emb} is too small: the smallest rate level (e=0.0625) "
                f"must keep at least 1 dim per head (4 heads), i.e. emb >= 64 -- "
                f"otherwise the reference's per-head q/k/v slicing degenerates")
        dims = {"embedding_size": args.emb, "num_heads": 4,
                "hidden_size": 2 * args.emb, "num_layers": args.layers,
                "dropout": 0.2}
        cfg, ds, split, lsplit = _setup_lm(args.seed, args.users, args.n_train,
                                           args.n_test_tokens, args.frac,
                                           args.local_epochs, args.bptt,
                                           args.batch_rows, dims)
        ref = [] if args.skip == "reference" else \
            run_reference_lm(cfg, ds, split, lsplit, args.rounds, args.seed, args.lr)
        mine = [] if args.skip == "mine" else \
            run_mine_lm(cfg, ds, split, lsplit, args.rounds, args.seed, args.lr)
        report = {"reference_ppl": ref, "mine_ppl": mine}
        if ref and mine:
            report["final_gap_ppl"] = round(mine[-1] - ref[-1], 2)
    else:
        hidden = [int(h) for h in args.hidden.split(",")]
        cfg, ds, split, lsplit = _setup(args.seed, args.users, hidden, args.n_train, args.n_test,
                                        model_name=args.model, data_name=args.data,
                                        frac=args.frac, split_mode=args.split,
                                        local_epochs=args.local_epochs,
                                        mode=args.mode, model_split=args.model_split)
        if args.conv_impl:
            cfg["conv_impl"] = args.conv_impl
        cfg["strategy"] = args.strategy
        ref = [] if args.skip == "reference" else \
            run_reference(cfg, ds, split, lsplit, args.rounds, args.seed, args.lr)
        mine = [] if args.skip == "mine" else \
            run_mine(cfg, ds, split, lsplit, args.rounds, args.seed, args.lr,
                     partial_out=args.out + ".partial" if args.out else None)
        report = {"reference_acc": ref, "mine_acc": mine}
        if ref and mine:
            report["final_gap_pp"] = round(mine[-1] - ref[-1], 2)
    print(json.dumps(report))
    if args.out:
        # atomic: campaign runners resume by artifact-exists, so a kill
        # mid-write must never leave a truncated artifact that reads as done
        tmp = args.out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(report, f)
        os.replace(tmp, args.out)
        # the final artifact supersedes the salvage checkpoint; a stale
        # .partial left behind could be misattributed to a later retry
        try:
            os.remove(args.out + ".partial")
        except FileNotFoundError:
            pass
    return report


if __name__ == "__main__":
    main()
