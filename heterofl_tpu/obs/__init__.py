"""Runtime telemetry (ISSUE 10 tentpole): in-program health probes, run
tracing, and a non-finite watchdog.

Once ``superstep_rounds=K`` fuses K federated rounds into one donated XLA
program (PR 2/4), the running system is a black box between fetches: grad
and update norms, per-level participation, the wire-codec residual
magnitude and the buffered-async staleness mass are all computed (or
cheaply derivable) inside the program, yet nothing surfaced them --
``grep isfinite`` over the package returned nothing, and the Round 12/13
instabilities (signsgd long-horizon divergence, the buffered staleness
tax) had to be diagnosed by hand from accuracy trajectories.  This package
makes per-round health statistics first-class (1610.05492 and 2405.20431
treat them as the tuning signal for codec/schedule choices):

* **In-program health probes** (:mod:`.probes`, the jax half): per-round
  scalars -- global grad/update norm, per-level participation, wire-codec
  residual norm, buffered-carry staleness mass, a non-finite leaf counter
  -- computed INSIDE the fused superstep from quantities the scan already
  holds (the post-psum aggregates and the new params carry).  ZERO new
  collectives: every probe is either derived from already-reduced values
  or emitted as a per-device partial that the host finishes at fetch time
  (the probes ride the existing metrics pytree through
  ``PendingMetrics``).  ``telemetry='off'`` (default) builds bit-identical
  programs to the pre-obs engines -- no new outputs, no new arguments.
* **Run tracing** (:mod:`.trace`): a :class:`~.trace.TraceRecorder`
  unifying ``PhaseTimer`` phases, driver events (superstep boundaries,
  checkpoint, eval, prefetch overlap) and ``jax.profiler`` annotations
  into a Chrome-trace-event ``trace.json`` (load it in Perfetto /
  ``chrome://tracing``) plus a schema'd ``events.jsonl`` per run, wired
  through ``entry/common.py`` and ``Logger.emit``.
* **Watchdog** (:mod:`.watchdog`): non-finite counts and a loss-spike
  detector (vs a rolling median) surfaced at fetch boundaries -- loud
  warning by default, configurable abort.  ``bench.py`` refuses to record
  a telemetry A/B whose watchdog fired.

This module is import-light (numpy only): config validation and the
host-side probe assembly live here; :mod:`.probes` is hot-path jax code
(it joins the staticcheck kernel lint scope), :mod:`.trace` and
:mod:`.watchdog` are host-side like ``sched/__init__``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

#: cfg['telemetry'] values: 'off' (default) keeps every engine program
#: bit-identical to the pre-obs tree; 'on' folds the health probes into
#: the metrics pytree of every fused round; 'hist' (ISSUE 12) additionally
#: folds the fixed-bucket cohort histograms (:mod:`.hist`) in -- still
#: zero new collectives, still the same one-psum/wire budgets
TELEMETRY_MODES = ("off", "on", "hist")

#: watchdog reactions (cfg['watchdog']['action']): 'warn' (default) emits
#: a loud warning + structured obs event, 'abort' raises WatchdogError at
#: the fetch boundary, 'rollback' (ISSUE 15) raises WatchdogRollback --
#: the driver restores the newest verifying checkpoint generation, salts
#: the round key stream and retries with bounded attempts + backoff,
#: escalating to abort when the budget is spent -- 'off' disables the
#: watchdog while keeping probes
WATCHDOG_ACTIONS = ("warn", "abort", "rollback", "off")

#: rollback budget defaults (cfg['watchdog']['max_retries'/'backoff']):
#: attempts before escalating to abort, and the base of the exponential
#: backoff in seconds (attempt n sleeps backoff * 2**(n-1))
DEFAULT_MAX_RETRIES = 3
DEFAULT_BACKOFF = 0.5

#: default loss-spike threshold: loss > factor x rolling median trips
DEFAULT_SPIKE_FACTOR = 3.0

#: default rolling-median window (rounds) of the loss-spike detector
DEFAULT_SPIKE_WINDOW = 8

#: key prefix of probe leaves inside the engines' metrics pytree -- the
#: fetch-side split (``split_probes``) and every assemble path key on it
PROBE_PREFIX = "obs_"

#: the finished per-round probe record's fields (the order is the schema).
#: ``quarantined`` (ISSUE 15) is present exactly when quarantine is on --
#: the count of clients whose update the in-program gate zeroed out.
PROBE_FIELDS = ("update_norm", "grad_norm", "participation", "resid_norm",
                "stale_norm", "nonfinite", "quarantined")

#: the finished cohort-histogram fields of a telemetry='hist' record
#: (ISSUE 12; each a list of bucket counts -- see obs/hist.py for edges)
HIST_FIELDS = ("hist_loss", "hist_steps", "hist_level", "hist_stale")

#: hist leaves derived from REPLICATED values: the host takes device 0's
#: row instead of summing the per-device partials (obs/hist.py emits the
#: staleness-carry histogram identically on every device)
HIST_REPLICATED = ("hist_stale",)

#: cfg['ledger'] values: 'on' maintains the host-side ClientLedger
#: (:mod:`.ledger`) -- O(active) per fetch, never a program change
LEDGER_MODES = ("off", "on")


class WatchdogSpec:
    """Resolved watchdog knobs (one immutable object, the ScheduleSpec
    convention).  ``spike_factor=None`` disables the loss-spike detector
    while keeping the non-finite check.  ``max_retries``/``backoff`` only
    matter under ``action='rollback'`` (ISSUE 15): the recovery budget and
    the exponential-backoff base in seconds."""

    def __init__(self, action: str = "warn",
                 spike_factor: Optional[float] = DEFAULT_SPIKE_FACTOR,
                 window: int = DEFAULT_SPIKE_WINDOW,
                 max_retries: int = DEFAULT_MAX_RETRIES,
                 backoff: float = DEFAULT_BACKOFF):
        self.action = action
        self.spike_factor = spike_factor
        self.window = window
        self.max_retries = max_retries
        self.backoff = backoff


class QuarantineSpec:
    """The resolved client-update quarantine configuration (ISSUE 15):
    engines read ``enabled``/``max_norm`` at construction.  Built by
    :func:`resolve_quarantine_cfg` -- there is no second parser."""

    def __init__(self, enabled: bool = False,
                 max_norm: Optional[float] = None):
        self.enabled = enabled
        self.max_norm = max_norm


def resolve_quarantine_cfg(cfg: Dict[str, Any]) -> QuarantineSpec:
    """Validate ``cfg['quarantine']`` and return the :class:`QuarantineSpec`.

    THE one validator (the PR 6/8/9 convention): an unknown mode or a
    malformed ``max_norm`` fails loudly at config time, never as a silent
    quarantine-off fallback mid-run.  ``'off'``/None = disabled (every
    program bit-identical to pre-quarantine); ``'on'`` = finiteness gate
    only; ``{'max_norm': R}`` additionally quarantines updates whose
    masked L2 norm exceeds ``R`` (R > 0)."""
    raw = cfg.get("quarantine", "off")
    if raw is None or raw == "off":
        return QuarantineSpec()
    if raw == "on":
        spec = QuarantineSpec(enabled=True)
    elif isinstance(raw, dict):
        unknown = set(raw) - {"max_norm"}
        if unknown:
            raise ValueError(f"Not valid quarantine keys: {sorted(unknown)} "
                             f"(max_norm)")
        mn = raw.get("max_norm")
        if mn is not None and (not isinstance(mn, (int, float))
                               or isinstance(mn, bool) or float(mn) <= 0.0):
            raise ValueError(f"Not valid quarantine max_norm: {mn!r} (a "
                             f"positive update-norm bound, or None for the "
                             f"finiteness-only gate)")
        spec = QuarantineSpec(enabled=True,
                              max_norm=None if mn is None else float(mn))
    else:
        raise ValueError(f"Not valid quarantine: {raw!r} ('off', 'on' or a "
                         f"{{'max_norm': R}} dict)")
    # quarantine x engine cross-check (ISSUE 18): promoted from the driver.
    # This validator OWNS the quarantine axis in the staticcheck lattice.
    if (cfg.get("strategy", "masked") or "masked") == "sliced":
        raise ValueError(
            "Not valid quarantine with strategy='sliced': the gate lives "
            "in the mesh-native engines' round cores ('masked' or "
            "'grouped'); the sliced debug twin replays the reference host "
            "loop and has no in-program round core to gate")
    return spec


class TelemetrySpec:
    """The resolved telemetry configuration: engines read ``probes`` /
    ``hist``, the driver reads ``watchdog``/``trace_dir``.  Built by
    :func:`resolve_telemetry_cfg` -- there is no second parser."""

    def __init__(self, probes: bool = False,
                 watchdog: Optional[WatchdogSpec] = None,
                 trace_dir: Optional[str] = None, hist: bool = False):
        self.probes = probes
        self.watchdog = watchdog
        self.trace_dir = trace_dir
        self.hist = hist


class LedgerSpec:
    """The resolved ledger configuration (ISSUE 12): ``enabled`` turns the
    driver's per-fetch :class:`~.ledger.ClientLedger` fold on.  Built by
    :func:`resolve_ledger_cfg` -- there is no second parser."""

    def __init__(self, enabled: bool = False):
        self.enabled = enabled


def resolve_ledger_cfg(cfg: Dict[str, Any]) -> LedgerSpec:
    """Validate ``cfg['ledger']`` and return the :class:`LedgerSpec`.

    THE one validator (the PR 6/8/9 convention): an unknown mode fails
    loudly at config time, never as a silent ledger-off fallback mid-run.
    The strategy/placement cross-checks are promoted from the driver
    (ISSUE 18) -- this validator OWNS the ledger axis in the staticcheck
    lattice."""
    mode = cfg.get("ledger", "off") or "off"
    if mode not in LEDGER_MODES:
        raise ValueError(f"Not valid ledger: {mode!r} "
                         f"(one of {LEDGER_MODES})")
    if mode == "on":
        if (cfg.get("strategy", "masked") or "masked") == "sliced":
            raise ValueError(
                "Not valid ledger='on' with strategy='sliced': the sliced "
                "debug twin replays the reference host loop, whose metrics "
                "never ride the fetch path the ledger folds from -- use a "
                "mesh-native strategy ('masked' or 'grouped')")
        if cfg.get("data_placement") == "sharded":
            raise ValueError(
                "Not valid ledger='on' with data_placement='sharded': the "
                "sharded slot packing re-orders metric rows by owning "
                "device, dropping the schedule-order uid alignment the "
                "O(active) fold consumes -- use replicated (or streaming) "
                "placement")
    return LedgerSpec(enabled=mode == "on")


def resolve_telemetry_cfg(cfg: Dict[str, Any]) -> TelemetrySpec:
    """Validate ``cfg['telemetry']`` / ``cfg['watchdog']`` /
    ``cfg['trace_dir']`` and return the :class:`TelemetrySpec`.

    THE one validator (the PR 6/8/9 convention): unknown modes, keys or
    malformed values fail loudly at config time, never as a silent
    telemetry-off fallback mid-run.  ``telemetry='on'`` enables the
    watchdog at warn defaults; ``cfg['watchdog']`` refines it (or turns it
    off with ``{'action': 'off'}``).  ``trace_dir`` is independent of the
    probes -- run tracing is pure host-side bookkeeping."""
    mode = cfg.get("telemetry", "off") or "off"
    if mode not in TELEMETRY_MODES:
        raise ValueError(f"Not valid telemetry: {mode!r} "
                         f"(one of {TELEMETRY_MODES})")
    raw_wd = cfg.get("watchdog")
    if raw_wd is not None and mode == "off":
        raise ValueError("cfg['watchdog'] needs telemetry='on'/'hist': the "
                         "watchdog feeds on the in-program probes (the "
                         "non-finite counter), which telemetry='off' does "
                         "not compute")
    watchdog: Optional[WatchdogSpec] = None
    if mode != "off":
        wd = dict(raw_wd or {})
        unknown = set(wd) - {"action", "spike_factor", "window",
                             "max_retries", "backoff"}
        if unknown:
            raise ValueError(f"Not valid watchdog keys: {sorted(unknown)} "
                             f"(action/spike_factor/window/max_retries/"
                             f"backoff)")
        action = wd.get("action", "warn") or "warn"
        if action not in WATCHDOG_ACTIONS:
            raise ValueError(f"Not valid watchdog action: {action!r} "
                             f"(one of {WATCHDOG_ACTIONS})")
        sf = wd.get("spike_factor", DEFAULT_SPIKE_FACTOR)
        if sf is not None and (not isinstance(sf, (int, float))
                               or isinstance(sf, bool) or float(sf) <= 1.0):
            raise ValueError(f"Not valid watchdog spike_factor: {sf!r} "
                             f"(a factor > 1 over the rolling median loss, "
                             f"or None to disable the spike detector)")
        window = wd.get("window", DEFAULT_SPIKE_WINDOW)
        if not isinstance(window, int) or isinstance(window, bool) \
                or window < 2:
            raise ValueError(f"Not valid watchdog window: {window!r} "
                             f"(an int >= 2, the rolling-median horizon in "
                             f"rounds)")
        retries = wd.get("max_retries", DEFAULT_MAX_RETRIES)
        if not isinstance(retries, int) or isinstance(retries, bool) \
                or retries < 1:
            raise ValueError(f"Not valid watchdog max_retries: {retries!r} "
                             f"(an int >= 1 rollback attempts before "
                             f"escalating to abort)")
        backoff = wd.get("backoff", DEFAULT_BACKOFF)
        if not isinstance(backoff, (int, float)) or isinstance(backoff, bool) \
                or float(backoff) < 0.0:
            raise ValueError(f"Not valid watchdog backoff: {backoff!r} (a "
                             f"non-negative exponential-backoff base in "
                             f"seconds)")
        if action != "off":
            watchdog = WatchdogSpec(action=action,
                                    spike_factor=None if sf is None
                                    else float(sf),
                                    window=window,
                                    max_retries=retries,
                                    backoff=float(backoff))
    trace_dir = cfg.get("trace_dir")
    if trace_dir is not None and not isinstance(trace_dir, str):
        raise ValueError(f"Not valid trace_dir: {trace_dir!r} (a directory "
                         f"path for trace.json + events.jsonl, or None)")
    # telemetry x engine cross-checks (ISSUE 18): promoted from the driver
    # so an unprobeable telemetry config refuses at config resolution.
    # This validator OWNS the telemetry axis in the staticcheck lattice.
    if mode != "off":
        strategy = cfg.get("strategy", "masked") or "masked"
        if strategy == "sliced":
            raise ValueError(
                f"Not valid telemetry={mode!r} with strategy='sliced': the "
                f"sliced debug twin replays the reference host loop and "
                f"has no in-program round core to probe -- use a "
                f"mesh-native strategy ('masked' or 'grouped')")
        if strategy == "grouped" \
                and int(cfg.get("superstep_rounds", 1) or 1) <= 1 \
                and (cfg.get("client_store", "eager") or "eager") != "stream":
            raise ValueError(
                f"Not valid telemetry={mode!r} with strategy='grouped' at "
                f"superstep_rounds<=1 and client_store='eager': the K=1 "
                f"path splits the round across L+1 host-orchestrated "
                f"programs with no shared round core to probe -- telemetry "
                f"needs the fused superstep path (superstep_rounds>1) or "
                f"client_store='stream'")
    return TelemetrySpec(probes=mode != "off", watchdog=watchdog,
                         trace_dir=trace_dir, hist=mode == "hist")


def split_probes(ms: Dict[str, Any], n_dev: int, layout: str = "flat",
                 ) -> Tuple[Dict[str, Any], Optional[List[Dict[str, Any]]]]:
    """Pop the ``obs_*`` probe leaves out of a FETCHED metrics dict and
    finish them into per-round probe records.

    The engines emit every probe as a small per-device row that the
    shard_map out-spec concatenates over the clients axis; this host half
    undoes the concat and applies each probe's finishing rule -- replicated
    scalars (update/grad/stale norms, the non-finite counter) take device
    0's copy, per-device PARTIALS (per-level participation counts, the
    residual sum-of-squares) sum over devices, and the ``_sq`` leaves take
    the final sqrt.  ``layout``: ``'flat'`` = device-major concat on the
    last axis (masked engine, grouped slices); ``'span'`` = device axis
    LAST (grouped span, whose metric leaves are ``[k, L, slots]``).
    Returns ``(metrics-without-probes, [per-round records] or None)``."""
    keys = [k for k in ms if k.startswith(PROBE_PREFIX)]
    if not keys:
        return ms, None
    clean = {k: v for k, v in ms.items() if not k.startswith(PROBE_PREFIX)}
    canon: Dict[str, np.ndarray] = {}
    for name in keys:
        v = np.asarray(ms[name])
        if layout == "span":
            # [k, X, n_dev] -> [k, n_dev, X]
            canon[name] = np.moveaxis(v, -1, 1)
        else:
            if v.ndim == 1:  # the K=1 train_round path: one implicit round
                v = v[None]
            canon[name] = v.reshape(v.shape[0], n_dev, -1)
    k_rounds = next(iter(canon.values())).shape[0]
    rounds: List[Dict[str, Any]] = []
    for r in range(k_rounds):
        rec: Dict[str, Any] = {}
        for name, c in canon.items():
            x = c[r]  # [n_dev, X]
            base = name[len(PROBE_PREFIX):]
            if base == "part":
                rec["participation"] = [float(p) for p in x.sum(axis=0)]
            elif base.startswith("hist_"):
                # cohort histograms (ISSUE 12): per-device bucket-count
                # partials sum across devices; the replicated ones take
                # device 0's row (obs/hist.py emits them identically)
                row = x[0] if base in HIST_REPLICATED else x.sum(axis=0)
                rec[base] = [float(c) for c in row]
            elif base == "resid_sq":
                rec["resid_norm"] = float(np.sqrt(x.sum()))
            elif base == "quarantine":
                # quarantined-client count (ISSUE 15): per-device partials
                # (each device counts its own gated slots) sum across
                # devices -- and across levels on the grouped span layout
                rec["quarantined"] = int(round(float(x.sum())))
            elif base == "nonfinite":
                rec["nonfinite"] = int(x[0, 0])
            elif base.endswith("_sq"):
                rec[base[:-3] + "_norm"] = float(np.sqrt(x[0, 0]))
            else:  # pragma: no cover - future probes default to replicated
                rec[base] = float(x[0, 0])
        rounds.append(rec)
    return clean, rounds
