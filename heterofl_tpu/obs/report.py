"""Population-observatory report surface (ISSUE 12).

``python -m heterofl_tpu.obs.report <run-dir-or-ledger.npz>`` renders a
population snapshot from the artifacts a ledger-enabled run leaves behind:

* ``ledger.npz`` (:class:`~.ledger.ClientLedger`): participation coverage
  and Gini, current-staleness quantiles and mass by availability class
  (participation-count quartiles of the seen population -- the honest
  proxy for the availability rate when no trace is on disk), per-level
  loss-EMA quantiles;
* ``events.jsonl`` (optional, the PR 10 trace stream next to it): event
  counts by name plus the watchdog trips, so an aborted run's report leads
  with the evidence.

``--json`` prints the machine-readable snapshot instead of the table.
Host-side and numpy-only, like the rest of the obs host half -- the
report never imports jax.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

from .ledger import ClientLedger


def find_ledger(path: str) -> str:
    """Resolve a run directory (searched recursively for the newest
    ``ledger.npz``) or a direct ``.npz`` path."""
    if os.path.isfile(path):
        return path
    hits = []
    for root, _dirs, files in os.walk(path):
        if "ledger.npz" in files:
            p = os.path.join(root, "ledger.npz")
            hits.append((os.path.getmtime(p), p))
    if not hits:
        raise FileNotFoundError(f"no ledger.npz under {path!r}: run with "
                                f"cfg['ledger']='on' (or point at the file)")
    return max(hits)[1]


def summarize_events(events_path: str) -> Dict[str, Any]:
    """Count events.jsonl records by name; surface the watchdog trips."""
    counts: Dict[str, int] = {}
    watchdog: List[Dict[str, Any]] = []
    with open(events_path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            name = rec.get("name", "?")
            counts[name] = counts.get(name, 0) + 1
            if name == "watchdog":
                watchdog.append(rec.get("args", {}))
    return {"path": events_path, "events_by_name": counts,
            "watchdog_trips": watchdog[:16]}


def build_report(ledger_path: str,
                 events_path: Optional[str] = None) -> Dict[str, Any]:
    led = ClientLedger.load(ledger_path)
    rep = {"ledger": ledger_path, **led.snapshot()}
    if events_path is None:
        cand = os.path.join(os.path.dirname(ledger_path), "events.jsonl")
        events_path = cand if os.path.exists(cand) else None
    if events_path is not None:
        rep["events"] = summarize_events(events_path)
    return rep


def _fmt_q(q: Dict[str, float]) -> str:
    return "  ".join(f"{k}={v:g}" for k, v in q.items())


def render_text(rep: Dict[str, Any]) -> str:
    """The human-readable table."""
    p = rep["participation"]
    s = rep["staleness"]
    lines = [
        f"population observatory -- {rep['ledger']}",
        f"  users {rep['num_users']}  levels {rep['levels']}  "
        f"round {rep['round']}  updates {rep['updates']}  "
        f"resident {rep['bytes']} B ({rep['bytes_per_user']} B/user)",
        "participation",
        f"  coverage {p['coverage']:.4f}  gini {p['gini']:.4f}  "
        f"total {p['total']}  max {p['count_max']}  "
        f"{_fmt_q(p['count_quantiles'])}",
        "staleness (rounds since last seen)",
        f"  {_fmt_q(s['now_quantiles'])}  cumulative "
        f"{s['cumulative_total']}",
    ]
    for c in s["by_class"]:
        extra = "" if c.get("stale_mean") is None \
            else f"  mean {c['stale_mean']:g}"
        lines.append(f"    class {c['class']:<10} users {c['users']:<8} "
                     f"stale mass {c['stale_mass']:g}{extra}")
    lines.append("per-level loss EMA")
    for lv in rep["per_level"]:
        q = ("(no observations)" if lv["loss_ema_quantiles"] is None
             else _fmt_q(lv["loss_ema_quantiles"]))
        lines.append(f"    level {lv['level']:<8g} users {lv['users_last']:<8}"
                     f" participations {lv['participations']:<8} {q}")
    ev = rep.get("events")
    if ev:
        lines.append(f"events -- {ev['path']}")
        lines.append("  " + "  ".join(f"{k}:{v}" for k, v in
                                      sorted(ev["events_by_name"].items())))
        if ev["watchdog_trips"]:
            lines.append(f"  WATCHDOG TRIPPED {len(ev['watchdog_trips'])}x: "
                         f"{ev['watchdog_trips'][0]}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m heterofl_tpu.obs.report",
        description="Render a population snapshot from ledger.npz "
                    "(+ events.jsonl)")
    ap.add_argument("path", help="run/trace directory or a ledger.npz path")
    ap.add_argument("--events", default=None,
                    help="events.jsonl path (default: next to the ledger)")
    ap.add_argument("--json", action="store_true",
                    help="print the machine-readable snapshot")
    args = ap.parse_args(argv)
    rep = build_report(find_ledger(args.path), events_path=args.events)
    if args.json:
        print(json.dumps(rep))
    else:
        print(render_text(rep))
    return 0


if __name__ == "__main__":  # pragma: no cover - exercised via main() tests
    sys.exit(main())
