"""In-program cohort histogram probes (ISSUE 12, the jax half).

PR 10's probes reduced each round to a handful of scalars (norms, counts);
at a million users the *distribution* across the cohort is the signal the
ROADMAP's sampler follow-ons need -- which clients lose badly, how hard the
deadline truncates, where the buffered staleness mass sits.  One function,
:func:`round_hists`, called next to :func:`~.probes.round_probes` at the
END of a fused round's in-jit core, computes FIXED-BUCKET histograms over
quantities the scan already holds:

* ``obs_hist_loss`` -- per-client mean training loss (``loss_sum / n`` per
  slot), bucketed on :data:`LOSS_EDGES`;
* ``obs_hist_steps`` -- per-client executed local-step FRACTION under the
  deadline scheduler (:func:`~..sched.deadline.deadline_steps` is a pure
  function of ``(round key, uid)``, so the budgets are re-derived here
  rather than threaded out of the step scan); without a deadline every
  valid client sits in the full-budget bucket;
* ``obs_hist_level`` -- per-level cohort membership counts (the width-
  heterogeneity histogram; its per-level sums equal the ``obs_part``
  probe, which the host-reference tests pin);
* ``obs_hist_stale`` -- magnitude histogram of the buffered-async pending
  update entries (:data:`STALE_EDGES`, log-spaced |value| buckets over the
  replicated ``[2, total]`` staleness carry); all-zero under sync
  aggregation.

The hard constraint is the PR 10 one: ZERO new collectives.  Every
histogram is either a per-device PARTIAL over this device's cohort slots
(loss/steps/level -- the host sums bucket counts across devices in
:func:`~heterofl_tpu.obs.split_probes`) or derived from a REPLICATED value
(the staleness carry -- the host takes device 0's copy).  Bucket edges are
static arrays, bucketing is one ``searchsorted`` + scatter-add per
histogram, and the rows ride the engines' existing metrics pytree as
``obs_hist_*`` keys through the one per-superstep fetch -- staticcheck
pins the hist-telemetry program variants at the same one-psum / wire /
donation / step-body budgets as their scalar-probe twins.

Bucket semantics (shared with the host-reference tests, which recompute
the same ``searchsorted(edges, v, side='left')`` in numpy for EXACT
equality): bucket ``i`` covers ``(edges[i-1], edges[i]]`` with bucket
``len(edges)`` collecting overflow, so a histogram row has
``len(edges) + 1`` bins.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax.numpy as jnp

#: per-client mean-loss bucket edges (upper bounds; cross-entropy scale).
#: 11 bins: (-inf, .05], (.05, .1], ... (10, 100], (100, inf)
LOSS_EDGES = (0.05, 0.1, 0.2, 0.5, 1.0, 2.0, 3.0, 5.0, 10.0, 100.0)

#: executed-step FRACTION edges (deadline truncation; budgets are always
#: >= ceil(min_frac * total) and <= total, so the (0.875, 1] bucket is the
#: "met the deadline" bin).  6 bins.
STEP_EDGES = (0.25, 0.5, 0.75, 0.875, 1.0)

#: |pending buffered update| magnitude edges (log-spaced).  7 bins; exact
#: zeros land in bin 0.
STALE_EDGES = (1e-8, 1e-6, 1e-4, 1e-2, 1.0, 100.0)


def bucket_counts(values: jnp.ndarray, weights: jnp.ndarray,
                  edges: Sequence[float]) -> jnp.ndarray:
    """Weighted fixed-bucket histogram: ``[len(edges) + 1]`` f32 counts of
    ``values`` under the ``(edges[i-1], edges[i]]`` rule (see module doc).
    One ``searchsorted`` + one scatter-add -- O(len(values)), no
    collective."""
    # staticcheck: allow(no-asarray): trace-time constant -- the static
    # python edge tuple enters the program once per trace, never per call
    e = jnp.asarray(edges, jnp.float32)
    idx = jnp.searchsorted(e, values.astype(jnp.float32), side="left")
    return jnp.zeros(e.shape[0] + 1, jnp.float32).at[idx].add(
        weights.astype(jnp.float32))


def round_hists(levels: Sequence[float], rate_ms: jnp.ndarray,
                loss_sum: jnp.ndarray, n: jnp.ndarray,
                key=None, uids: Optional[jnp.ndarray] = None,
                total_steps: Optional[int] = None,
                min_frac: Optional[float] = None,
                sched_buf: Optional[jnp.ndarray] = None,
                ) -> Dict[str, jnp.ndarray]:
    """One round's cohort-histogram leaves, shaped as rank-1 per-device
    rows (the :func:`~.probes.round_probes` convention).

    ``rate_ms``: the per-slot ``rate * valid`` metric the engines already
    emit (any rank -- the grouped span layout passes ``[L, slots]``); its
    nonzeros mark this device's valid participants.  ``loss_sum``/``n``:
    the per-slot metric sums of the same shape.  ``key``/``uids``/
    ``total_steps``/``min_frac``: the deadline-budget stream inputs
    (``min_frac=None`` = no deadline scheduler -> every valid client at
    fraction 1.0).  ``sched_buf``: the replicated buffered-async carry
    (None or zeros under sync aggregation)."""
    rate = jnp.ravel(rate_ms)
    valid = (rate > 0).astype(jnp.float32)
    loss = jnp.ravel(loss_sum)
    nn = jnp.ravel(n)
    # per-client mean loss: only slots that contributed samples weigh in
    # (a deadline budget of zero completed steps has no defined loss)
    w_loss = valid * (nn > 0).astype(jnp.float32)
    hist_loss = bucket_counts(loss / jnp.maximum(nn, 1.0), w_loss,
                              LOSS_EDGES)
    if min_frac is None:
        frac = jnp.ones_like(rate)
    else:
        from ..sched.deadline import deadline_steps

        budgets = deadline_steps(key, jnp.ravel(uids), total_steps,
                                 min_frac)
        frac = budgets.astype(jnp.float32) / jnp.float32(total_steps)
    hist_steps = bucket_counts(frac, valid, STEP_EDGES)
    hist_level = jnp.stack([jnp.sum((rate == jnp.float32(lvl))
                                    .astype(jnp.float32))
                            for lvl in levels])
    if sched_buf is None:
        hist_stale = jnp.zeros(len(STALE_EDGES) + 1, jnp.float32)
    else:
        flat = jnp.ravel(jnp.abs(sched_buf))
        hist_stale = bucket_counts(flat, jnp.ones_like(flat), STALE_EDGES)
    return {
        "obs_hist_loss": hist_loss,
        "obs_hist_steps": hist_steps,
        "obs_hist_level": hist_level,
        "obs_hist_stale": hist_stale,
    }
