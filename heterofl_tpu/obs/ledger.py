"""Host-side per-client participation ledger (ISSUE 12, numpy only).

At a million users nothing so far recorded WHICH clients ever participate:
the probes (PR 10) see each round's cohort, the sampler (PR 11) draws it,
and both forget it the moment the fetch completes.  The
:class:`ClientLedger` is the compact persistent record the ROADMAP's
availability-debiasing and loss-prioritized-sampling follow-ons need:

* resident state is a handful of O(num_users) SMALL-int arrays -- about
  ``17 + 2 * levels`` bytes per user (27 B at the 5-level flagship mix,
  under the ~32 B/user acceptance line measured by ``BENCH_LEDGER``);
* every update is **O(active)**: one fetch folds one cohort's uid rows
  (drawn from THE one sampling stream -- the host twin of the in-jit
  draw, contract-tested bit-identical) plus the per-slot ``rate`` /
  ``loss_sum`` / ``n`` metric sums the fetch already carries; nothing ever
  scans the population on the update path;
* the state is checkpointed with the run (:meth:`state_dict` /
  :meth:`load_state_dict` ride the driver's checkpoint blob, so a resumed
  run CONTINUES its counts and EMAs) and snapshotted to ``ledger.npz``
  (:meth:`save` / :meth:`load`) for the offline report surface
  (``python -m heterofl_tpu.obs.report``).

Tracked per user: participation count, last-seen round, cumulative
staleness (the sum of gaps between successive participations), an EMA of
the client's mean training loss (decay :data:`LOSS_EMA_DECAY`; the first
observation seeds it), the last width level and saturating per-level
participation counts.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Sequence

import numpy as np

#: ledger.npz / state_dict schema version
LEDGER_VERSION = 1

#: EMA weight of each NEW loss observation (the first observation seeds)
LOSS_EMA_DECAY = 0.1

#: level_last value of a never-seen user
LEVEL_NONE = 255

#: the per-user arrays (name -> (dtype, per-user shape tail))
LEDGER_FIELDS = ("count", "last_seen", "stale_sum", "loss_ema",
                 "level_last", "level_counts")


def gini(counts: np.ndarray) -> float:
    """Gini coefficient of a non-negative participation-count vector
    (0 = perfectly even, -> 1 = one client holds everything).  O(U log U)
    -- report/snapshot path only, never the per-fetch update."""
    x = np.sort(np.asarray(counts, np.float64))
    total = x.sum()
    if total <= 0 or x.size == 0:
        return 0.0
    n = x.size
    cum = np.cumsum(x)
    return float((n + 1 - 2.0 * (cum / total).sum()) / n)


class ClientLedger:
    """Per-client participation/staleness/loss record; see module doc."""

    def __init__(self, num_users: int, levels: Sequence[float]):
        if num_users < 1:
            raise ValueError(f"ClientLedger needs num_users >= 1, got "
                             f"{num_users}")
        self.num_users = int(num_users)
        self.levels = [float(r) for r in levels]
        if not self.levels or len(self.levels) >= LEVEL_NONE:
            raise ValueError(f"ClientLedger needs 1..{LEVEL_NONE - 1} "
                             f"levels, got {len(self.levels)}")
        self._level_tab = np.asarray(self.levels, np.float64)
        U, L = self.num_users, len(self.levels)
        self.count = np.zeros(U, np.uint32)
        self.last_seen = np.zeros(U, np.int32)   # 0 = never participated
        self.stale_sum = np.zeros(U, np.uint32)
        self.loss_ema = np.zeros(U, np.float32)
        self.level_last = np.full(U, LEVEL_NONE, np.uint8)
        self.level_counts = np.zeros((U, L), np.uint16)
        self.round = 0     # highest round folded in
        self.updates = 0   # fold calls
        self._seen = 0     # distinct users seen (incremental coverage)

    # -- O(active) update ----------------------------------------------

    def update(self, epoch: int, uids, rates, loss_sums, ns
               ) -> Dict[str, Any]:
        """Fold ONE fetched round into the ledger; O(len(uids)).

        ``uids``: the round's cohort uid row (-1 = padding slot);
        ``rates``/``loss_sums``/``ns``: the fetch's per-slot metric sums
        ALIGNED to the uid row (slice the metric arrays to ``len(uids)``
        -- cohort order is schedule order in every supported path).
        Participation is ``rate > 0`` (a failure-injected client is drawn
        but contributes nothing); the loss EMA only updates where the
        client processed samples (``n > 0``).  Returns a compact summary
        (the per-fetch ``{"tag": "ledger"}`` line)."""
        uids = np.asarray(uids).reshape(-1)
        rates = np.asarray(rates, np.float32).reshape(-1)
        loss_sums = np.asarray(loss_sums, np.float32).reshape(-1)
        ns = np.asarray(ns, np.float32).reshape(-1)
        if not (len(uids) == len(rates) == len(loss_sums) == len(ns)):
            raise ValueError(
                f"ledger update needs aligned rows: uids {len(uids)} vs "
                f"rate {len(rates)} / loss_sum {len(loss_sums)} / n "
                f"{len(ns)} -- slice the metric arrays to the uid row")
        m = (uids >= 0) & (rates > 0)
        u = uids[m].astype(np.int64)
        if u.size and (u.max() >= self.num_users):
            raise ValueError(f"ledger update saw uid {int(u.max())} >= "
                             f"num_users={self.num_users}")
        r = rates[m].astype(np.float64)
        lvl = np.argmin(np.abs(r[:, None] - self._level_tab[None, :]),
                        axis=1).astype(np.uint8)
        prev_count = self.count[u].copy()
        new_users = int((prev_count == 0).sum())
        gaps = np.where(self.last_seen[u] > 0,
                        np.maximum(int(epoch) - self.last_seen[u], 0),
                        0).astype(np.uint32)
        self.stale_sum[u] += gaps
        self.count[u] = prev_count + 1
        self.last_seen[u] = np.int32(epoch)
        self.level_last[u] = lvl
        lc = self.level_counts[u, lvl].astype(np.uint32)
        self.level_counts[u, lvl] = np.minimum(lc + 1, 65535).astype(np.uint16)
        has_loss = ns[m] > 0
        lu = u[has_loss]
        loss_mean = None
        if lu.size:
            loss = (loss_sums[m][has_loss]
                    / ns[m][has_loss]).astype(np.float32)
            prev = self.loss_ema[lu]
            first = prev_count[has_loss] == 0
            d = np.float32(LOSS_EMA_DECAY)
            self.loss_ema[lu] = np.where(
                first, loss, (np.float32(1.0) - d) * prev + d * loss)
            loss_mean = float(self.loss_ema[lu].mean())
        self._seen += new_users
        self.round = max(self.round, int(epoch))
        self.updates += 1
        return {"event": "ledger", "epoch": int(epoch),
                "active": int(m.sum()), "new_users": new_users,
                "coverage": round(self._seen / self.num_users, 6),
                "stale_gap_mean": (round(float(gaps.mean()), 3)
                                   if u.size else None),
                "loss_ema_mean": (round(loss_mean, 6)
                                  if loss_mean is not None else None)}

    # -- size accounting ------------------------------------------------

    @property
    def nbytes(self) -> int:
        """Resident bytes of the per-user arrays (the BENCH_LEDGER
        acceptance number: <= ~32 bytes/user at 1e6 users)."""
        return sum(getattr(self, f).nbytes for f in LEDGER_FIELDS)

    @property
    def seen(self) -> int:
        return self._seen

    # -- persistence -----------------------------------------------------

    def _meta(self) -> Dict[str, Any]:
        return {"version": LEDGER_VERSION, "num_users": self.num_users,
                "levels": self.levels, "round": self.round,
                "updates": self.updates, "seen": self._seen,
                "loss_ema_decay": LOSS_EMA_DECAY}

    def state_dict(self) -> Dict[str, Any]:
        """Checkpoint payload (rides the driver blob): a resumed run
        CONTINUES its counts/EMAs instead of resetting them."""
        out = {"meta": self._meta()}
        for f in LEDGER_FIELDS:
            out[f] = getattr(self, f).copy()
        return out

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        meta = state["meta"]
        if meta.get("version") != LEDGER_VERSION:
            raise ValueError(f"ledger state version {meta.get('version')} "
                             f"!= {LEDGER_VERSION}")
        if int(meta["num_users"]) != self.num_users \
                or [float(r) for r in meta["levels"]] != self.levels:
            raise ValueError(
                f"ledger state mismatch: checkpoint is for "
                f"{meta['num_users']} users x levels {meta['levels']}, "
                f"this run has {self.num_users} x {self.levels}")
        for f in LEDGER_FIELDS:
            ref = getattr(self, f)
            arr = np.asarray(state[f], ref.dtype)
            if arr.shape != ref.shape:
                raise ValueError(f"ledger field {f!r} shape {arr.shape} "
                                 f"!= {ref.shape}")
            setattr(self, f, arr.copy())
        self.round = int(meta["round"])
        self.updates = int(meta["updates"])
        self._seen = int(meta["seen"])

    def save(self, path: str) -> str:
        """Write ``ledger.npz`` (arrays + a JSON ``meta`` record) -- the
        report surface's input.  Parent dirs are created; the write is
        atomic (tmp + replace) so an abort mid-save never corrupts an
        earlier snapshot."""
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp.npz"
        np.savez(tmp, meta=np.array(json.dumps(self._meta())),
                 **{f: getattr(self, f) for f in LEDGER_FIELDS})
        os.replace(tmp, path)
        return path

    @classmethod
    def load(cls, path: str) -> "ClientLedger":
        with np.load(path) as z:
            meta = json.loads(str(z["meta"]))
            led = cls(meta["num_users"], meta["levels"])
            led.load_state_dict({"meta": meta,
                                 **{f: z[f] for f in LEDGER_FIELDS}})
        return led

    # -- snapshot statistics (report path; O(U log U) allowed) ----------

    def snapshot(self, quantiles=(0.5, 0.9, 0.99)) -> Dict[str, Any]:
        """Population-level statistics for the report surface: coverage +
        participation Gini, current-staleness quantiles and mass by
        participation class, per-level loss-EMA quantiles."""
        c = self.count.astype(np.float64)
        seen_mask = c > 0
        out: Dict[str, Any] = {
            "version": LEDGER_VERSION,
            "num_users": self.num_users,
            "levels": self.levels,
            "round": self.round,
            "updates": self.updates,
            "bytes": self.nbytes,
            "bytes_per_user": round(self.nbytes / self.num_users, 3),
            "participation": {
                "coverage": round(float(seen_mask.mean()), 6),
                "gini": round(gini(c), 6),
                "total": int(c.sum()),
                "count_quantiles": {f"p{int(q * 100)}":
                                    float(np.quantile(c, q))
                                    for q in quantiles},
                "count_max": int(c.max()) if c.size else 0,
            },
        }
        # current staleness: rounds since last seen (never-seen users are
        # stale since round 0 -- the whole run)
        stale_now = np.where(self.last_seen > 0,
                             self.round - self.last_seen,
                             self.round).astype(np.float64)
        # availability classes: participation-count quartiles of the SEEN
        # population (a proxy for the availability rate the traces encode;
        # the never-seen users are their own class) -- where the staleness
        # mass sits tells the debiasing follow-on whom to up-weight
        classes: List[Dict[str, Any]] = [{
            "class": "never-seen",
            "users": int((~seen_mask).sum()),
            "stale_mass": float(stale_now[~seen_mask].sum()),
        }]
        if seen_mask.any():
            cs = c[seen_mask]
            edges = np.quantile(cs, [0.25, 0.5, 0.75])
            lo = 0.0
            for name, hi in (("rare", edges[0]), ("low", edges[1]),
                             ("mid", edges[2]), ("frequent", np.inf)):
                sel = seen_mask & (c > lo) & (c <= hi)
                classes.append({
                    "class": name,
                    "users": int(sel.sum()),
                    "count_range": [float(lo), None if np.isinf(hi)
                                    else float(hi)],
                    "stale_mass": float(stale_now[sel].sum()),
                    "stale_mean": (round(float(stale_now[sel].mean()), 3)
                                   if sel.any() else None),
                })
                lo = hi
        out["staleness"] = {
            "now_quantiles": {f"p{int(q * 100)}":
                              float(np.quantile(stale_now, q))
                              for q in quantiles},
            "cumulative_total": int(self.stale_sum.sum()),
            "by_class": classes,
        }
        per_level = []
        for li, rate in enumerate(self.levels):
            sel = self.level_last == li
            ls = self.loss_ema[(self.level_last == li)
                               & (self.count > 0)].astype(np.float64)
            per_level.append({
                "level": rate,
                "users_last": int(sel.sum()),
                "participations": int(self.level_counts[:, li]
                                      .astype(np.int64).sum()),
                "loss_ema_quantiles": ({f"p{int(q * 100)}":
                                        round(float(np.quantile(ls, q)), 6)
                                        for q in quantiles}
                                       if ls.size else None),
            })
        out["per_level"] = per_level
        return out
