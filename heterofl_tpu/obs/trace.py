"""Run tracing (ISSUE 10): one Chrome-trace + events-JSONL recorder per run.

The driver already times its phases (``PhaseTimer``: stage / dispatch /
compute / fetch / eval) and jax can annotate device traces
(``jax.profiler``), but the three clocks never met in one artifact: a
stall was a number in a phase table, not a visible gap on a timeline.
:class:`TraceRecorder` unifies them:

* every ``PhaseTimer`` phase becomes a complete ("X") trace event (the
  timer calls :meth:`TraceRecorder.complete` when a recorder is attached
  to its ``trace`` attribute);
* driver events -- superstep boundaries, checkpoint writes, eval windows,
  cohort prefetch -- are recorded via :meth:`span` / :meth:`instant`, and
  ``span`` additionally enters a ``jax.profiler.TraceAnnotation`` so a
  simultaneously-captured device profile (``cfg['profile_dir']``) carries
  the same labels;
* ``close()`` writes ``trace.json`` in the Chrome trace-event format
  (open in Perfetto or ``chrome://tracing``) and every event ALSO streams
  to ``events.jsonl`` as it happens -- one schema'd JSON object per line
  (:data:`EVENT_FIELDS`, checked by :func:`validate_event`), so a killed
  run still leaves its timeline on disk.

Host-side only (stdlib + lazy jax import for the annotation); the traced
programs are never touched -- recording is pure driver bookkeeping.
"""

from __future__ import annotations

import json
import os
import time
from contextlib import contextmanager, nullcontext
from typing import Any, Dict, Optional

#: events.jsonl schema, version 1: required fields -> type.  ``dur_s`` is
#: present exactly on complete ("X") events; ``args`` is a flat JSON
#: object of event-specific facts.
EVENT_VERSION = 1
EVENT_FIELDS = {"v": int, "t": float, "name": str, "cat": str, "ph": str,
                "args": dict}
EVENT_PHASES = ("i", "X")


def validate_event(rec: Dict[str, Any]) -> Dict[str, Any]:
    """Validate one events.jsonl record against the schema; returns the
    record (so loaders can ``[validate_event(json.loads(l)) ...]``) or
    raises ``ValueError`` naming the violation."""
    if not isinstance(rec, dict):
        raise ValueError(f"event record must be an object, got {type(rec)}")
    for field, typ in EVENT_FIELDS.items():
        if field not in rec:
            raise ValueError(f"event record misses required field {field!r}: "
                             f"{rec}")
        if typ is float:
            if not isinstance(rec[field], (int, float)) \
                    or isinstance(rec[field], bool):
                raise ValueError(f"event field {field!r} must be a number, "
                                 f"got {rec[field]!r}")
        elif not isinstance(rec[field], typ):
            raise ValueError(f"event field {field!r} must be {typ.__name__}, "
                             f"got {rec[field]!r}")
    if rec["v"] != EVENT_VERSION:
        raise ValueError(f"event version {rec['v']} != {EVENT_VERSION}")
    if rec["ph"] not in EVENT_PHASES:
        raise ValueError(f"event ph {rec['ph']!r} not in {EVENT_PHASES}")
    if rec["ph"] == "X":
        dur = rec.get("dur_s")
        if not isinstance(dur, (int, float)) or isinstance(dur, bool):
            raise ValueError(f"complete event needs a numeric dur_s: {rec}")
    extra = set(rec) - set(EVENT_FIELDS) - {"dur_s"}
    if extra:
        raise ValueError(f"unknown event fields {sorted(extra)}: {rec}")
    return rec


def _jax_annotation(name: str):
    """A ``jax.profiler.TraceAnnotation`` when jax is importable (it always
    is in the driver), else a no-op -- the recorder itself must work in
    jax-free host tooling/tests."""
    try:
        from jax.profiler import TraceAnnotation

        return TraceAnnotation(name)
    except Exception:  # pragma: no cover - jax is present everywhere we run
        return nullcontext()


class TraceRecorder:
    """One run's trace: collects events in memory for ``trace.json`` and
    streams them to ``events.jsonl`` as they happen.

    Timestamps: the Chrome ``ts``/``dur`` fields are microseconds on the
    ``time.perf_counter`` clock relative to recorder construction (the
    same clock ``PhaseTimer`` uses, so attached phases line up exactly);
    the JSONL ``t`` field is absolute wall-clock seconds for cross-run
    correlation."""

    def __init__(self, out_dir: str):
        os.makedirs(out_dir, exist_ok=True)
        self.out_dir = out_dir
        self.trace_path = os.path.join(out_dir, "trace.json")
        self.events_path = os.path.join(out_dir, "events.jsonl")
        self._events = []
        self._jsonl = open(self.events_path, "w")
        self._t0 = time.perf_counter()
        self._t0_wall = time.time()
        self.closed = False

    # -- recording -----------------------------------------------------

    def _push(self, name: str, cat: str, ph: str, t_perf: float,
              dur: Optional[float], args: Optional[Dict[str, Any]]) -> None:
        if self.closed:
            return
        args = dict(args or {})
        ev = {"name": name, "cat": cat, "ph": ph, "pid": 0, "tid": 0,
              "ts": round((t_perf - self._t0) * 1e6, 1), "args": args}
        if ph == "X":
            ev["dur"] = round((dur or 0.0) * 1e6, 1)
        self._events.append(ev)
        rec = {"v": EVENT_VERSION,
               "t": self._t0_wall + (t_perf - self._t0),
               "name": name, "cat": cat, "ph": ph, "args": args}
        if ph == "X":
            rec["dur_s"] = round(dur or 0.0, 6)
        self._jsonl.write(json.dumps(validate_event(rec)) + "\n")
        self._jsonl.flush()

    def instant(self, name: str, cat: str = "driver",
                args: Optional[Dict[str, Any]] = None) -> None:
        """A point event (watchdog trips, probe snapshots, run markers)."""
        self._push(name, cat, "i", time.perf_counter(), None, args)

    def complete(self, name: str, t0: float, dur: float, cat: str = "phase",
                 args: Optional[Dict[str, Any]] = None) -> None:
        """A finished interval with an explicit ``perf_counter`` start --
        the ``PhaseTimer`` hook (the timer already measured the phase, the
        recorder just files it)."""
        self._push(name, cat, "X", t0, dur, args)

    @contextmanager
    def span(self, name: str, cat: str = "driver",
             args: Optional[Dict[str, Any]] = None):
        """Record an interval around a block AND enter the matching
        ``jax.profiler.TraceAnnotation`` so device-side profiles captured
        in parallel carry the same label."""
        t0 = time.perf_counter()
        try:
            with _jax_annotation(name):
                yield
        finally:
            self.complete(name, t0, time.perf_counter() - t0, cat=cat,
                          args=args)

    # -- finish --------------------------------------------------------

    def sync(self) -> str:
        """Flush + fsync the artifacts WITHOUT closing the recorder: the
        rollback path's durability twin of :meth:`close` (ISSUE 15
        satellite -- the abort path closes, but a rollback continues the
        run, and each recovery attempt must still leave the trip evidence
        on disk: events.jsonl fsync'd with the trip instant as its last
        line, trace.json a point-in-time snapshot).  Returns the trace
        path; no-op after close."""
        if self.closed:
            return self.trace_path
        self._jsonl.flush()
        os.fsync(self._jsonl.fileno())
        with open(self.trace_path, "w") as f:
            json.dump({"traceEvents": self._events,
                       "displayTimeUnit": "ms",
                       "metadata": {"clock": "perf_counter",
                                    "t0_wall": self._t0_wall}}, f)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        return self.trace_path

    def close(self) -> str:
        """Write ``trace.json`` and close the JSONL stream; returns the
        trace path.  Idempotent (a driver finally-block and an explicit
        close may both run).

        Durability (ISSUE 12 satellite): both artifacts are fsync'd --
        close() runs on the abort path BEFORE a ``WatchdogError``
        propagates, and the buffered tail it would otherwise lose IS the
        abort evidence (the watchdog instant must be the last event on
        disk after a crash)."""
        if self.closed:
            return self.trace_path
        self.closed = True
        self._jsonl.flush()
        os.fsync(self._jsonl.fileno())
        self._jsonl.close()
        with open(self.trace_path, "w") as f:
            json.dump({"traceEvents": self._events,
                       "displayTimeUnit": "ms",
                       "metadata": {"clock": "perf_counter",
                                    "t0_wall": self._t0_wall}}, f)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        return self.trace_path
