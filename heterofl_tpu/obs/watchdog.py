"""The non-finite / loss-spike watchdog (ISSUE 10).

Consumes the per-round probe records (:func:`~heterofl_tpu.obs.
split_probes`) at fetch boundaries -- the first host code that SEES a
round's numbers -- and trips on the two silent-divergence signatures the
MEASUREMENTS.md Round 12/13 post-mortems had to reconstruct by hand:

* **non-finite params**: the in-program leaf counter (``nonfinite``) is
  nonzero -- a NaN/Inf entered the params carry.  Under a fused K-round
  superstep the poison can be K rounds old by the time anything is
  fetched, which is exactly why the counter is computed in-program per
  round: the trip names the ROUND, not the fetch.
* **loss spike**: the round's training loss exceeds ``spike_factor`` x
  the rolling median of the last ``window`` finite losses (or is itself
  non-finite).  The median (not mean) keeps one bad round from poisoning
  the baseline it is judged against.

Reaction is configurable (``cfg['watchdog']['action']``): ``warn`` emits
a loud ``warnings.warn`` plus a structured obs event through the caller's
emit hook (``Logger.emit`` in the driver); ``abort`` additionally raises
:class:`WatchdogError` AFTER recording/emitting, so the trace and log
carry the evidence the abort is based on.  ``Watchdog.fired`` accumulates
every trip -- ``bench.py`` refuses to record a telemetry A/B whose
watchdog fired.

Host-side, numpy-only: nothing here runs under trace.
"""

from __future__ import annotations

import math
import warnings
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from . import WatchdogSpec


class WatchdogError(RuntimeError):
    """Raised at a fetch boundary when the watchdog trips under
    ``action='abort'`` -- after the trip was logged/emitted."""


class WatchdogRollback(WatchdogError):
    """Raised at a fetch boundary when the watchdog trips under
    ``action='rollback'`` (ISSUE 15) -- after the trip was logged/emitted.
    The driver catches it, restores the newest verifying checkpoint
    generation, salts the round key stream (the replayed superstep draws a
    fresh cohort) and retries; unhandled (e.g. outside the driver loop) it
    degrades to the abort behaviour, which is why it subclasses
    :class:`WatchdogError`.  ``events`` carries the trip records."""

    def __init__(self, msg: str, events: List[Dict[str, Any]]):
        super().__init__(msg)
        self.events = events


#: the retry-salt stream tag (ISSUE 15): rollback attempt n folds
#: ``RETRY_SALT + n`` into the driver's host key, so every replayed
#: superstep draws a FRESH cohort deterministically.  Shared with the
#: chaos drill, which predicts post-rollback draws to pick poison targets.
RETRY_SALT = 0x5EED


class Watchdog:
    """Stateful per-run watchdog; feed it every fetched round in order."""

    def __init__(self, spec: WatchdogSpec):
        self.spec = spec
        self.fired: List[Dict[str, Any]] = []
        self._losses = deque(maxlen=spec.window)

    def check(self, epoch: int, probes: Optional[Dict[str, Any]] = None,
              loss: Optional[float] = None,
              emit: Optional[Callable[[Dict[str, Any]], None]] = None,
              ) -> List[Dict[str, Any]]:
        """Check one round; returns the trip events (empty = healthy).

        Every trip is appended to :attr:`fired`, pushed through ``emit``
        (structured obs event) and warned loudly; ``action='abort'`` then
        raises :class:`WatchdogError` naming the first trip."""
        events: List[Dict[str, Any]] = []
        nonf = 0 if probes is None else int(probes.get("nonfinite", 0) or 0)
        if nonf > 0:
            events.append({"event": "watchdog", "kind": "nonfinite",
                           "epoch": int(epoch), "nonfinite_leaves": nonf})
        if loss is not None:
            if not math.isfinite(loss):
                events.append({"event": "watchdog", "kind": "loss-nonfinite",
                               "epoch": int(epoch), "loss": repr(loss)})
            else:
                sf = self.spec.spike_factor
                if sf is not None and len(self._losses) >= 3:
                    hist = sorted(self._losses)
                    med = hist[len(hist) // 2]
                    if med > 0.0 and loss > sf * med:
                        events.append({"event": "watchdog",
                                       "kind": "loss-spike",
                                       "epoch": int(epoch),
                                       "loss": round(loss, 6),
                                       "rolling_median": round(med, 6),
                                       "spike_factor": sf})
                self._losses.append(loss)
        for ev in events:
            self.fired.append(ev)
            if emit is not None:
                emit(ev)
            warnings.warn(f"watchdog [{ev['kind']}] at round {epoch}: {ev} "
                          f"(action={self.spec.action})")
        if events and self.spec.action == "abort":
            raise WatchdogError(
                f"watchdog abort at round {epoch}: {events[0]['kind']} "
                f"({events[0]}); set cfg['watchdog']['action']='warn' to "
                f"continue through trips")
        if events and self.spec.action == "rollback":
            raise WatchdogRollback(
                f"watchdog rollback at round {epoch}: {events[0]['kind']} "
                f"({events[0]}); restoring the last good checkpoint "
                f"generation (up to max_retries={self.spec.max_retries} "
                f"attempts)", events)
        return events

    def reset_window(self) -> None:
        """Clear the loss-spike rolling window (ISSUE 15): after a
        rollback the restored trajectory replays rounds whose losses will
        re-enter the window -- keeping the poisoned run's tail would both
        double-count and skew the median the replay is judged against.
        ``fired`` is untouched: it is the run's full trip HISTORY (bench
        refusals read it)."""
        self._losses.clear()
