"""In-program health probes (ISSUE 10, the jax half).

One function: :func:`round_probes`, called at the END of a fused round's
in-jit core -- after the single global psum and the counted-average
combine -- on quantities the scan already holds.  The hard constraint is
ZERO new collectives (staticcheck pins the telemetry-on program variants
at the same one-psum budget and the same wire bytes as their dense
twins), so every probe is one of:

* **derived from already-reduced values**: the post-psum aggregates
  (``summed``/``counts``) and the params carry are replicated, so norms
  over them are global without any exchange -- the global grad norm
  (counted-average client delta), the update norm (new - old params), the
  buffered staleness mass, and the non-finite leaf counter;
* **a per-device PARTIAL** the host finishes at fetch time: per-level
  participation counts and the error-feedback residual sum-of-squares are
  emitted per device, concatenated by the existing metrics out-spec, and
  summed on the host (:func:`~heterofl_tpu.obs.split_probes`).

Probe leaves ride the engines' existing metrics pytree (keys prefixed
``obs_``), stack over the superstep scan like every other metric, and
cross to the host in the one per-superstep fetch -- no extra dispatches,
no host callbacks, no new program arguments.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp


def _sq_norm(tree: Dict[str, jnp.ndarray]) -> jnp.ndarray:
    """Squared L2 norm over a params-shaped tree (f32 scalar)."""
    return sum(jnp.sum(jnp.square(v)) for v in tree.values())


def quarantine_gate(trained: Dict[str, jnp.ndarray],
                    ref: Dict[str, jnp.ndarray],
                    cms: Dict[str, jnp.ndarray],
                    max_norm: Optional[float] = None) -> jnp.ndarray:
    """The per-client update-quarantine gate (ISSUE 15 tentpole): a bool
    ``[slots]`` row -- True keeps the client's update, False quarantines it
    -- computed from values each device ALREADY holds, before the single
    global psum.

    ``trained``: per-slot locally-trained param trees ``{k: [S, ...]}``
    (global shape on the masked engine, sliced shape in a grouped level
    core); ``ref``: the pre-round params the slots trained from
    (broadcast, same per-leaf shape minus the slot axis); ``cms``: the
    per-slot count masks ``{k: [S, ...]}`` -- the exact aggregation
    weights, so the norm term measures what would actually be summed.

    The gate trips on (a) ANY non-finite element in a slot's trained tree
    (a NaN/Inf would otherwise poison the psum: ``NaN * 0-count`` is still
    NaN, which is why the caller must also ``where``-sanitise the trained
    values) and (b), when ``max_norm`` is set, a masked update L2 norm
    above it.  A non-finite delta also fails the norm comparison (NaN
    compares False), so the two conditions compose.  Zero new collectives:
    the row folds into the count masks BEFORE the existing psum and a
    poisoned client becomes a zero-count participant."""
    finite = None
    d_sq = jnp.zeros(()) if max_norm is not None else None
    for k, v in trained.items():
        ax = tuple(range(1, v.ndim))
        f = jnp.all(jnp.isfinite(v), axis=ax)
        finite = f if finite is None else jnp.logical_and(finite, f)
        if max_norm is not None:
            d_sq = d_sq + jnp.sum(jnp.square((v - ref[k]) * cms[k]), axis=ax)
    ok = finite
    if max_norm is not None:
        ok = jnp.logical_and(ok, d_sq <= jnp.float32(max_norm) ** 2)
    return ok


def round_probes(levels: Sequence[float], params: Dict[str, jnp.ndarray],
                 new_params: Dict[str, jnp.ndarray],
                 summed: Dict[str, jnp.ndarray],
                 counts: Dict[str, jnp.ndarray], rate_ms: jnp.ndarray,
                 resid: Optional[jnp.ndarray] = None,
                 sched_buf: Optional[jnp.ndarray] = None,
                 ) -> Dict[str, jnp.ndarray]:
    """One round's probe leaves, shaped as rank-1 per-device rows.

    ``params``/``new_params``: the (replicated) carry before/after the
    combine; ``summed``/``counts``: the POST-psum aggregates (dequantised
    under a wire codec); ``rate_ms``: the per-slot ``rate * valid`` metric
    the engines already emit (its nonzeros ARE this device's valid
    participants, level by level); ``resid``: this device's new
    error-feedback carry (lossy codecs; None under dense); ``sched_buf``:
    the new replicated staleness buffer (buffered-async only).

    Probes (keys are ``obs_``-prefixed; shapes per device):

    * ``obs_update_sq`` ``[1]`` -- squared norm of the applied global
      update ``new - old`` (replicated);
    * ``obs_grad_sq`` ``[1]`` -- squared norm of the counted-average
      client delta ``(summed - old*counts)/max(counts,1)``, the round's
      pseudo-gradient.  Equal to ``obs_update_sq`` under dense synchronous
      aggregation (the stale rule zeroes both where no client
      contributed); under a lossy codec it measures the DEQUANTISED
      aggregate and under buffering the in-flight cohort, which is exactly
      why both exist;
    * ``obs_part`` ``[L]`` -- per-level valid-participant counts, a
      per-device partial (host sums devices);
    * ``obs_resid_sq`` ``[1]`` -- this device's EF-residual sum of squares
      (partial; zeros under dense);
    * ``obs_stale_sq`` ``[1]`` -- squared norm of the pending buffered
      update rows (replicated; zeros under sync aggregation);
    * ``obs_nonfinite`` ``[1]`` -- number of new-params leaves containing
      ANY non-finite element (replicated f32 count).
    """
    upd = _sq_norm({k: new_params[k] - params[k] for k in params})
    grad = _sq_norm({k: (summed[k] - params[k] * counts[k])
                     / jnp.maximum(counts[k], 1.0) for k in params})
    part = jnp.stack([jnp.sum((rate_ms == jnp.float32(lvl))
                              .astype(jnp.float32)) for lvl in levels])
    nonfinite = sum(jnp.any(~jnp.isfinite(v)).astype(jnp.float32)
                    for v in new_params.values())
    resid_sq = jnp.zeros(()) if resid is None else jnp.sum(jnp.square(resid))
    stale_sq = jnp.zeros(()) if sched_buf is None \
        else jnp.sum(jnp.square(sched_buf))
    return {
        "obs_update_sq": jnp.reshape(upd, (1,)),
        "obs_grad_sq": jnp.reshape(grad, (1,)),
        "obs_part": part,
        "obs_resid_sq": jnp.reshape(resid_sq, (1,)),
        "obs_stale_sq": jnp.reshape(stale_sq, (1,)),
        "obs_nonfinite": jnp.reshape(nonfinite, (1,)),
    }
