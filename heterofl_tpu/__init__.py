"""HeteroFL-TPU: a TPU-native federated-learning framework.

A from-scratch JAX/XLA re-design of the capabilities of
``diaoenmao/HeteroFL-Computation-and-Communication-Efficient-Federated-Learning-
for-Heterogeneous-Clients`` (ICLR 2021): federated training of *width-nested*
heterogeneous client sub-models with counted averaging, static batch norm and
activation scaling.

Design stance (vs. the PyTorch reference at ``/root/reference``):

* The reference slices a global model into per-client sub-``state_dict``\\ s in
  Python loops (``src/fed.py:26-178``) and trains clients sequentially.  Here a
  full communication round is **one XLA program**: clients live on a
  ``clients`` mesh axis, local SGD runs under ``vmap``/``shard_map``, and
  aggregation is a masked ``psum`` over ICI.
* Width heterogeneity is expressed with **channel masks over full-width
  tensors** instead of shape-changing slices.  HeteroFL sub-models are always
  *prefix* slices (``src/fed.py:46-48``), so masking the suffix to zero is
  mathematically identical to slicing (proved in ``tests/test_equivalence.py``)
  while keeping every client step the same static shape -- no per-width
  recompiles, runtime (data-dependent) rate assignment, and full MXU tiles.
* A "sliced" execution strategy (true small shapes, one compiled program per
  rate level) is also provided for host-side debugging and parity checks.
"""

__version__ = "0.1.0"

from . import config  # noqa: F401
