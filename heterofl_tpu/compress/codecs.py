"""The jax wire-codec implementations (ISSUE 8; see package docstring).

Every codec transforms one device's partial aggregation contribution --
the flat ``(update sums, count masks)`` pair in the
:class:`~..ops.fused_update.FlatSpec` layout -- into a payload pytree that
rides ONE ``jax.lax.psum`` bind, then decodes the accumulated payload back
to flat sums/counts.  The contract every codec must keep:

* **one bind**: the whole payload is a single psum (a pytree psum is one
  bind); nothing else crosses the wire.
* **shared decode context**: anything the decoder needs that is not in the
  payload (quantisation grids, block offsets) must be derived from values
  every device already holds identically -- the replicated params carry
  and the round key -- so no side-channel collective is ever needed.
* **local own-decode**: the encoder can compute what the decoder will
  attribute to THIS device, which is what the error-feedback residual
  subtracts (e' = (x + e) - decode(encode(x + e))); with
  ``error_feedback=False`` the residual stays zero and the compression
  error is simply dropped (the A/B the convergence contract tests).

Lossy-codec trajectories depend on the mesh shape (per-device partials are
what gets quantised) and on the program's static slot layout (``cmax`` --
the per-device client bound -- sizes the shared quantisation grid, so two
dispatch granularities agree bitwise only when their slot layouts match)
-- unlike ``dense``, which stays bit-identical to the pre-codec engines
everywhere.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from . import (COUNT_LANE_BITS, SIGN_LANE_BITS, TOPK_BLOCKS, VALUE_LANE_BITS,
               codec_payload_bytes, lane_words, resid_slots)
from ..ops.quant import pack_lanes, quantize_pack, unpack_lanes

#: PRNG salts of the codec streams (disjoint from the engines' 13/98 and
#: the rate/user salts in fed.core)
QUANT_NOISE_SALT = 9173
TOPK_BLOCK_SALT = 9177


class WireCodec:
    """Shared scaffolding: spec, participant count, lane-capacity guards."""

    name = "?"

    def __init__(self, spec, participants: int, error_feedback: bool = True,
                 axis: str = "clients"):
        self.spec = spec
        self.p = int(participants)
        self.ef = bool(error_feedback)
        self.axis = axis
        self.resid_slots = resid_slots(self.name)

    def payload_bytes(self) -> int:
        return codec_payload_bytes(self.name, self.spec.total,
                                   len(self.spec.names))

    def _leaf_expand(self, per_leaf: jnp.ndarray) -> jnp.ndarray:
        """[n_leaves] -> flat [total] (each leaf's scalar broadcast over its
        segment of the flat layout)."""
        return jnp.concatenate([
            jnp.broadcast_to(per_leaf[i], (self.spec.sizes[k],))
            for i, k in enumerate(self.spec.names)])

    def _device_key(self, key: jax.Array, salt: int) -> jax.Array:
        """Per-device codec key: decorrelates stochastic rounding across
        participants (inside shard_map) while staying deterministic."""
        k = jax.random.fold_in(key, salt)
        if self.axis is not None:
            k = jax.random.fold_in(k, jax.lax.axis_index(self.axis))
        return k

    def zero_payload(self):
        """The codec's IDENTITY payload: what a non-participating device
        ships into the shared psum bind so the accumulated payload decodes
        as if that device contributed nothing.  All-zero for every codec
        -- int8 lanes carry ``+bias`` per PARTICIPANT and the decoder
        subtracts ``participants x bias``, signsgd's decode subtracts
        ``participants`` from the doubled positive count, and topk/dense
        ship raw values -- PROVIDED the codec was constructed with
        ``participants`` = the devices that actually encode (the grouped
        ``slices`` per-level layout, ISSUE 14 satellite: each level's
        codec counts its slice rows, every other row ships this)."""
        raise NotImplementedError

    def _check_count_capacity(self, cmax: int, lane_bits: int) -> None:
        """Counts ride exact integer lanes: the cross-device lane sum (at
        most participants x per-device clients) must fit ``lane_bits``."""
        if self.p * cmax > (1 << lane_bits) - 1:
            raise ValueError(
                f"wire codec {self.name!r}: count lanes overflow -- "
                f"{self.p} participants x {cmax} clients/device exceeds the "
                f"{lane_bits}-bit lane capacity {(1 << lane_bits) - 1}; "
                f"shrink the per-round cohort or use the dense codec")


class Int8Codec(WireCodec):
    """Per-leaf stochastic-rounding quantisation, int32 psum accumulation.

    Each value is rounded onto a shared per-leaf grid whose scale derives
    from the replicated params carry (``cmax x max|p_leaf|`` bounds the
    magnitude of a partial sum of ``cmax`` clipped sub-models), written
    into an 8-bit lane with enough headroom that the sum over all
    ``participants`` lanes cannot carry -- so the word-wise int32 psum IS
    exact per-lane integer accumulation.  Out-of-range values clip; the
    clip error joins the rounding error in the residual.  Counts are small
    integers and ride their own 8-bit lanes LOSSLESSLY.
    """

    name = "int8"

    def __init__(self, spec, participants, error_feedback=True,
                 axis="clients", mode=None):
        super().__init__(spec, participants, error_feedback, axis)
        # per-device grid: 8-bit lanes keep ceil(log2 p) headroom bits for
        # the cross-device sum, the rest are quantisation levels
        head = (self.p - 1).bit_length()
        if VALUE_LANE_BITS - head < 2:
            raise ValueError(
                f"int8 wire codec supports at most "
                f"{1 << (VALUE_LANE_BITS - 2)} participants on the "
                f"reduction axis (got {self.p}): fewer than 4 quantisation "
                f"levels would remain per lane")
        self.levels = 1 << (VALUE_LANE_BITS - head)
        self.bias = self.levels // 2
        self.qmax = self.bias - 1
        if mode is None:
            mode = "pallas" if jax.default_backend() == "tpu" else "xla"
        self.mode = mode

    def zero_payload(self):
        n = self.spec.total
        return {"q": jnp.zeros(lane_words(n, VALUE_LANE_BITS), jnp.int32),
                "c": jnp.zeros(lane_words(n, COUNT_LANE_BITS), jnp.int32)}

    def _scale_flat(self, params: Dict[str, jnp.ndarray],
                    cmax: int) -> jnp.ndarray:
        per_leaf = jnp.stack([jnp.max(jnp.abs(params[k]))
                              for k in self.spec.names])
        return self._leaf_expand((cmax * per_leaf + 1e-3) / self.qmax)

    def encode(self, sums, cnts, resid, params, key, cmax: int):
        self._check_count_capacity(cmax, COUNT_LANE_BITS)
        s = self._scale_flat(params, cmax)
        x = sums + resid[0] if self.ef else sums
        words, q = quantize_pack(x, s, self._device_key(key, QUANT_NOISE_SALT),
                                 self.qmax, self.bias, mode=self.mode)
        new_resid = (x - q.astype(jnp.float32) * s)[None] if self.ef \
            else jnp.zeros_like(resid)
        payload = {"q": words,
                   "c": pack_lanes(jnp.round(cnts).astype(jnp.int32),
                                   COUNT_LANE_BITS)}
        return payload, new_resid

    def decode(self, agg, params, key, cmax: int):
        s = self._scale_flat(params, cmax)
        qsum = unpack_lanes(agg["q"], VALUE_LANE_BITS, self.spec.total) \
            - self.p * self.bias
        sums = qsum.astype(jnp.float32) * s
        cnts = unpack_lanes(agg["c"], COUNT_LANE_BITS,
                            self.spec.total).astype(jnp.float32)
        return sums, cnts


class SignSGDCodec(WireCodec):
    """1-bit signs with a per-leaf scale, EF-signSGD style.

    Each device sends one sign bit per element (4-bit lanes, so up to 15
    participants can accumulate without carries) plus its per-leaf mean
    magnitude as a tiny f32 vector IN THE SAME psum bind; the decoder
    reconstructs ``mean_scale x (positives - negatives)``.  The residual
    uses the device's OWN scale (what the mean attributes to it in
    expectation) -- the standard EF-signSGD approximation.
    """

    name = "signsgd"

    def __init__(self, spec, participants, error_feedback=True,
                 axis="clients"):
        super().__init__(spec, participants, error_feedback, axis)
        if self.p > (1 << SIGN_LANE_BITS) - 1:
            raise ValueError(
                f"signsgd wire codec supports at most "
                f"{(1 << SIGN_LANE_BITS) - 1} participants on the reduction "
                f"axis (got {self.p}): the sign lanes would carry")

    def zero_payload(self):
        n = self.spec.total
        return {"b": jnp.zeros(lane_words(n, SIGN_LANE_BITS), jnp.int32),
                "s": jnp.zeros(len(self.spec.names), jnp.float32),
                "c": jnp.zeros(lane_words(n, COUNT_LANE_BITS), jnp.int32)}

    def _leaf_means(self, x: jnp.ndarray) -> jnp.ndarray:
        ax = jnp.abs(x)
        return jnp.stack([
            jnp.mean(jax.lax.dynamic_slice(ax, (self.spec.offsets[k],),
                                           (self.spec.sizes[k],)))
            for k in self.spec.names])

    def encode(self, sums, cnts, resid, params, key, cmax: int):
        self._check_count_capacity(cmax, COUNT_LANE_BITS)
        x = sums + resid[0] if self.ef else sums
        s_leaf = self._leaf_means(x)
        s_flat = self._leaf_expand(s_leaf)
        pos = (x >= 0)
        new_resid = (x - jnp.where(pos, s_flat, -s_flat))[None] if self.ef \
            else jnp.zeros_like(resid)
        payload = {"b": pack_lanes(pos.astype(jnp.int32), SIGN_LANE_BITS),
                   "s": s_leaf,
                   "c": pack_lanes(jnp.round(cnts).astype(jnp.int32),
                                   COUNT_LANE_BITS)}
        return payload, new_resid

    def decode(self, agg, params, key, cmax: int):
        npos = unpack_lanes(agg["b"], SIGN_LANE_BITS,
                            self.spec.total).astype(jnp.float32)
        sbar = self._leaf_expand(agg["s"] / self.p)
        sums = sbar * (2.0 * npos - self.p)
        cnts = unpack_lanes(agg["c"], COUNT_LANE_BITS,
                            self.spec.total).astype(jnp.float32)
        return sums, cnts


class TopKCodec(WireCodec):
    """Rotating-block sparsification riding the flat width-mask layout.

    The flat update splits into :data:`~.TOPK_BLOCKS` contiguous blocks;
    each round ships ONE block -- index drawn from the round key, so every
    device (and the decoder) picks the same block with no index exchange
    -- as raw f32 values AND counts.  Both residual slots accumulate the
    unsent blocks, so when a block finally ships it carries matching
    multi-round sums and counts (the combine's sum/count stays a mean);
    coordinates outside the block contribute zero count, and
    ``combine_counted``'s stale rule keeps their previous global value.
    With ``error_feedback=False`` the unsent blocks are simply dropped.
    """

    name = "topk"

    def __init__(self, spec, participants, error_feedback=True,
                 axis="clients"):
        super().__init__(spec, participants, error_feedback, axis)
        self.blocks = TOPK_BLOCKS
        if spec.total < self.blocks:
            raise ValueError(f"topk wire codec needs at least {self.blocks} "
                             f"flat elements (got {spec.total})")
        self.block_len = -(-spec.total // self.blocks)

    def zero_payload(self):
        return {"v": jnp.zeros(self.block_len, jnp.float32),
                "c": jnp.zeros(self.block_len, jnp.float32)}

    def _offset(self, key: jax.Array) -> jnp.ndarray:
        # identical on every device: derived from the (replicated) round key
        b = jax.random.randint(jax.random.fold_in(key, TOPK_BLOCK_SALT),
                               (), 0, self.blocks)
        return jnp.minimum(b * self.block_len,
                           self.spec.total - self.block_len)

    def encode(self, sums, cnts, resid, params, key, cmax: int):
        off = self._offset(key)
        k = self.block_len
        if self.ef:
            xv, xc = sums + resid[0], cnts + resid[1]
            vals = jax.lax.dynamic_slice(xv, (off,), (k,))
            cblk = jax.lax.dynamic_slice(xc, (off,), (k,))
            zero = jnp.zeros((k,), jnp.float32)
            new_resid = jnp.stack([
                jax.lax.dynamic_update_slice(xv, zero, (off,)),
                jax.lax.dynamic_update_slice(xc, zero, (off,))])
        else:
            vals = jax.lax.dynamic_slice(sums, (off,), (k,))
            cblk = jax.lax.dynamic_slice(cnts, (off,), (k,))
            new_resid = jnp.zeros_like(resid)
        return {"v": vals, "c": cblk}, new_resid

    def decode(self, agg, params, key, cmax: int):
        off = self._offset(key)
        zeros = jnp.zeros((self.spec.total,), jnp.float32)
        sums = jax.lax.dynamic_update_slice(zeros, agg["v"], (off,))
        cnts = jax.lax.dynamic_update_slice(zeros, agg["c"], (off,))
        return sums, cnts


def compressed_psum(codec: WireCodec, axis: str,
                    params: Dict[str, jnp.ndarray],
                    summed: Dict[str, jnp.ndarray],
                    counts: Dict[str, jnp.ndarray],
                    resid: jnp.ndarray, key: jax.Array, cmax: int
                    ) -> Tuple[Dict[str, jnp.ndarray],
                               Dict[str, jnp.ndarray], jnp.ndarray]:
    """quantise -> ONE global psum -> dequantise: THE compressed twin of
    the engines' ``psum((summed, counts), axis)``, used by both the masked
    round core and the grouped fused superstep.  ``resid`` is this device's
    ``[resid_slots, total]`` error-feedback carry; ``cmax`` the static
    per-device max contributing clients (it sizes the quantisation range
    and the count-lane capacity check)."""
    spec = codec.spec
    payload, new_resid = codec.encode(spec.flatten(summed),
                                      spec.flatten(counts),
                                      resid, params, key, cmax)
    agg = jax.lax.psum(payload, axis)
    sum_hat, cnt_hat = codec.decode(agg, params, key, cmax)
    return spec.unflatten(sum_hat), spec.unflatten(cnt_hat), new_resid
