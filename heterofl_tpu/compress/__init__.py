"""Wire codecs (ISSUE 8 tentpole): in-program compressed aggregation.

HeteroFL's pitch is *communication*-efficient federated learning, yet the
wire format was dense f32 until this package: every fused round moved ONE
global reduction of ``sum(param_bytes) + count_bytes`` (89.4 MB for the
flagship ResNet-18 round, MEASUREMENTS.md Round 11).  The codecs here
compress each device's partial ``(update sums, count masks)`` contribution
INSIDE the scanned superstep program -- quantise -> ONE global psum ->
dequantise -- preserving the one-global-psum invariant the staticcheck
auditor enforces, with error-feedback residuals carried as a new flat
entry in the scan state so compression error is re-injected next round
instead of lost (PAPERS.md: Konecny et al. 1610.05492; EF-signSGD;
Dynamic Sampling and Selective Masking 2003.09603).

Codecs (``cfg['wire_codec']``):

* ``dense`` (default) -- today's program, bit for bit: no payload
  transform, no residual carry, no new program arguments.  Every
  pre-existing equivalence contract is untouched by construction.
* ``int8`` -- per-leaf stochastic-rounding quantisation with int32 psum
  accumulation: each device's contribution is rounded onto a shared
  per-leaf grid (scale derived from the replicated params carry, so no
  scale exchange is needed), packed 4 values per int32 in 8-bit lanes
  sized so the cross-device lane sums cannot carry, and summed in ONE
  integer psum.  Counts ride the same bind in exact 8-bit integer lanes
  (counts are small integers -- lossless).  Wire: 2 bytes/element = 25%
  of dense.
* ``signsgd`` -- 1-bit sign per element (4-bit lanes, 8 per int32) with a
  per-leaf per-device scale vector summed in the SAME bind (the decoder
  applies the mean scale); counts exact as in ``int8``.  Wire: ~1.5
  bytes/element = ~19% of dense.
* ``topk`` -- block sparsification riding the flat width-mask layout: each
  round transmits one of ``TOPK_BLOCKS`` contiguous blocks of the flat
  update (the block index drawn from the round key, identical on every
  device), with BOTH the value and count residuals accumulated so unsent
  coordinates keep a consistent sum/count ratio when they finally ship.
  Wire: 2 bytes/element = 25% of dense.

This module is import-light (no jax): the analytic byte accounting below
is THE single source of truth consumed by ``fed.core.level_codec_byte_table``,
the staticcheck wire budget (equality against traced psum operand avals)
and ``bench.py``'s ``extra.wire`` -- there is no second bytes formula.
The jax codec implementations live in :mod:`.codecs`.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

#: the codec registry; ``dense`` is the default and the only lossless one
CODEC_NAMES = ("dense", "int8", "signsgd", "topk")

#: lossy codecs carry an error-feedback residual in the scan state
LOSSY_CODECS = ("int8", "signsgd", "topk")

#: blocks of the ``topk`` rotation: one block of ``ceil(N / TOPK_BLOCKS)``
#: flat coordinates ships per round
TOPK_BLOCKS = 4

#: lane widths (bits) of the packed integer payloads
VALUE_LANE_BITS = 8   # int8 codec: quantised values
SIGN_LANE_BITS = 4    # signsgd codec: sign bits with cross-device headroom
COUNT_LANE_BITS = 8   # both: exact integer count masks


def lane_words(n_elems: int, lane_bits: int) -> int:
    """int32 words needed to pack ``n_elems`` lanes of ``lane_bits`` bits."""
    per = 32 // lane_bits
    return -(-n_elems // per)


def resid_slots(name: str) -> int:
    """Flat error-feedback buffers the codec carries per device: ``topk``
    accumulates value AND count residuals (so a block that ships after m
    rounds carries m rounds of counts alongside m rounds of sums -- the
    sum/count ratio stays a mean); the quantising codecs carry one."""
    return 2 if name == "topk" else (0 if name == "dense" else 1)


def codec_payload_bytes(name: str, n_elems: int, n_leaves: int = 0,
                        blocks: int = TOPK_BLOCKS) -> int:
    """Per-participant psum payload bytes of one compressed training round:
    a pure function of the flat element count (and leaf count for the
    signsgd scale vector), exactly matching the traced psum operand avals
    -- which is what lets staticcheck enforce the compressed wire budget
    by EQUALITY, like the dense one."""
    if name == "dense":
        return 2 * 4 * n_elems  # f32 sums + f32 counts
    if name == "int8":
        return 4 * lane_words(n_elems, VALUE_LANE_BITS) \
            + 4 * lane_words(n_elems, COUNT_LANE_BITS)
    if name == "signsgd":
        return 4 * lane_words(n_elems, SIGN_LANE_BITS) \
            + 4 * lane_words(n_elems, COUNT_LANE_BITS) \
            + 4 * n_leaves
    if name == "topk":
        return 2 * 4 * (-(-n_elems // blocks))  # f32 value + count block
    raise ValueError(f"Not valid wire_codec: {name!r} (one of {CODEC_NAMES})")


def normalize_codec_map(raw: Dict[Any, Any]) -> Dict[float, str]:
    """Normalize a per-level codec map (ISSUE 9 satellite): keys are rate
    levels (floats, or their string forms -- JSON objects key by string),
    values codec names.  An all-dense map collapses to the plain ``dense``
    path at the engines; key COVERAGE of the engine's level table is the
    engine's check (it owns the table)."""
    out: Dict[float, str] = {}
    for k, v in raw.items():
        try:
            rate = float(k)  # staticcheck: allow(no-float-coercion): host config-key parse
        except (TypeError, ValueError):
            raise ValueError(f"Not valid wire_codec level key: {k!r} (a rate "
                             f"level, e.g. 1.0 or '0.0625')")
        if v not in CODEC_NAMES:
            raise ValueError(f"Not valid wire_codec for level {rate:g}: "
                             f"{v!r} (one of {CODEC_NAMES})")
        if rate in out:
            # two string keys coercing to one rate ("1" and "1.0") would
            # otherwise silently last-win -- the loud-validation convention
            # says a config collision fails, never resolves arbitrarily
            raise ValueError(f"Not valid wire_codec map: level {rate:g} "
                             f"assigned twice (duplicate keys coerce to "
                             f"the same rate)")
        out[rate] = v
    if not out:
        raise ValueError("Not valid wire_codec: an empty per-level map")
    return out


def resolve_codec_cfg(cfg: Dict[str, Any], engine_strategy: str = None):
    """Validate ``cfg['wire_codec']`` / ``cfg['error_feedback']`` and return
    ``(codec, error_feedback)`` -- ``codec`` is a name, or a normalized
    ``{rate: name}`` per-level map (ISSUE 9 satellite; grouped engine's
    fused superstep only -- the engines enforce that placement).

    Loud ``ValueError`` on unknown values (the PR 6 convention: stale or
    typo'd config keys fail at validation, never as silent defaults
    mid-run).  ``error_feedback`` defaults True and only matters for lossy
    codecs.

    ``engine_strategy`` is the engine-direct re-validation hook: an engine
    constructor passes its own identity and gets codec-local validation
    only (names, map shape, error_feedback).  The strategy-coupled
    cross-checks below belong to the config-RESOLUTION path alone: the
    caller of an engine class picked the strategy (whatever
    ``cfg['strategy']`` says), drives ``k`` per ``train_superstep`` call
    (``cfg['superstep_rounds']`` binds only the driver's schedule), and
    the engines keep their own placement refusals -- the masked engine
    refuses a per-level map at dispatch, the grouped engine checks map
    keys against its level table."""
    name = cfg.get("wire_codec", "dense") or "dense"
    if isinstance(name, dict):
        name = normalize_codec_map(name)
        if all(v == "dense" for v in name.values()):
            name = "dense"
    elif name not in CODEC_NAMES:
        raise ValueError(f"Not valid wire_codec: {name!r} "
                         f"(one of {CODEC_NAMES})")
    ef = cfg.get("error_feedback", True)
    if not isinstance(ef, bool):
        raise ValueError(f"Not valid error_feedback: {ef!r} (must be a bool; "
                         f"it gates the residual re-injection of lossy wire "
                         f"codecs)")
    if engine_strategy is not None:
        return name, ef
    # codec x engine cross-checks (ISSUE 18): promoted from the driver so
    # a codec the engines cannot lower refuses at config resolution, not
    # at experiment construction.  This validator OWNS the codec axis in
    # the staticcheck config lattice.
    strategy = cfg.get("strategy", "masked") or "masked"
    if isinstance(name, dict) and strategy != "grouped":
        raise ValueError(
            f"Not valid wire_codec: a per-level map needs strategy="
            f"'grouped' (its fused superstep compresses each level's "
            f"sliced payload under that level's codec), got strategy="
            f"{strategy!r}")
    if name != "dense":
        if strategy == "sliced":
            raise ValueError(
                f"Not valid wire_codec={name!r} with strategy='sliced': "
                f"the sliced debug twin aggregates on the host, there is "
                f"no psum to compress -- use a mesh-native strategy "
                f"('masked' or 'grouped')")
        if strategy == "grouped" \
                and int(cfg.get("superstep_rounds", 1) or 1) <= 1 \
                and (cfg.get("client_store", "eager") or "eager") != "stream":
            raise ValueError(
                f"Not valid wire_codec={name!r} with strategy='grouped' at "
                f"superstep_rounds<=1 and client_store='eager': the K=1 "
                f"host-orchestrated path reduces per level and has no "
                f"single global psum to compress (set superstep_rounds>1 "
                f"or client_store='stream')")
    return name, ef


def make_codec(name: str, spec, participants: int, error_feedback: bool = True,
               axis: str = "clients"):
    """Build the jax codec object (None for ``dense``); lazy import so the
    analytic half of this package stays jax-free."""
    if name == "dense":
        return None
    from .codecs import Int8Codec, SignSGDCodec, TopKCodec

    cls = {"int8": Int8Codec, "signsgd": SignSGDCodec, "topk": TopKCodec}
    if name not in cls:
        raise ValueError(f"Not valid wire_codec: {name!r} "
                         f"(one of {CODEC_NAMES})")
    return cls[name](spec, participants, error_feedback=error_feedback,
                     axis=axis)
