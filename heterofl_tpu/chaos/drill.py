"""``python -m heterofl_tpu.chaos.drill`` -- run a driver under a fault
plan and assert the recovery contract (ISSUE 15).

The drill is the chaos harness's executable spec, shared verbatim by the
CLI, the tests and ``bench.py``'s ``BENCH_CHAOS`` pass:

* **kill drills** (:func:`run_kill_drill`): run a small synthetic
  federation uninterrupted, then run it again with a
  :class:`~heterofl_tpu.chaos.FaultInjector` killing at the planned
  driver boundaries (plus optional checkpoint-byte corruptions applied
  between the kill and the resume), resuming a FRESH experiment from disk
  after every kill.  Contract: the recovered run's final params are
  **bitwise identical** to the uninterrupted run's -- every per-round
  stream is keyed by (host key, epoch), so a replay from any checkpoint
  generation lands on the same trajectory.
* **poison drills** (:func:`run_poison_drill`): NaN-poison a drawn
  (round, uid) client update and prove the run completes without human
  intervention -- either the in-program quarantine gate zeroes the
  contribution (``mode='quarantine'``), or the watchdog's
  ``action='rollback'`` restores the last good generation and replays
  with a salted cohort stream (``mode='rollback'``).
  :func:`pick_poison_uid` chooses a uid that IS drawn at the poisoned
  round but is NOT drawn by any retry's salted stream, so the rollback
  recovery is deterministic, not probabilistic.

Exit code 0 iff every drilled contract holds; the report is one JSON
object on stdout (``--json``) or a human summary.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _scrub_env_for_cpu() -> None:
    """Force a multi-device virtual CPU platform BEFORE jax initialises
    (the staticcheck __main__ convention: this environment's TPU-tunnel
    plugin hangs CPU-only init)."""
    for v in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE",
              "AXON_LOOPBACK_RELAY", "AXON_POOL_SVC_OVERRIDE"):
        os.environ.pop(v, None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = \
            (flags + " --xla_force_host_platform_device_count=8").strip()


def drill_cfg(out_dir: str, **over) -> Dict[str, Any]:
    """The drill's small synthetic federation (the tests' _driver_cfg
    shape): 8 users, two rate levels, tiny conv widths, 4 rounds."""
    from .. import config as C

    cfg = C.default_cfg()
    cfg["control"] = C.parse_control_name("1_8_0.5_iid_fix_a1-b1_bn_1_1")
    cfg["data_name"] = "MNIST"
    cfg["model_name"] = "conv"
    cfg["synthetic"] = True
    cfg["synthetic_sizes"] = {"train": 80, "test": 40}
    cfg["output_dir"] = out_dir
    cfg["override"] = {"num_epochs": {"global": 4, "local": 1},
                       "conv": {"hidden_size": [4, 8]},
                       "batch_size": {"train": 10, "test": 20},
                       # the drill's contracts NEED the shared epoch-keyed
                       # sampling stream ('prp', the default): the legacy
                       # 'perm' numpy stream is stateful, so a resumed run
                       # could not replay bitwise and pick_poison_uid
                       # could not predict the K=1 draws -- pinned
                       # explicitly so a default change cannot silently
                       # break the drill
                       "sampler": "prp",
                       "superstep_rounds": 2, "eval_interval": 2, **over}
    return C.process_control(cfg)


def _final_params(result) -> Dict[str, Any]:
    import numpy as np

    return {k: np.asarray(v) for k, v in result["params"].items()}


def _params_equal(a: Dict[str, Any], b: Dict[str, Any]) -> bool:
    import numpy as np

    return set(a) == set(b) and all(
        a[k].shape == b[k].shape and np.array_equal(a[k], b[k],
                                                    equal_nan=True)
        for k in a)


def _run_once(cfg: Dict[str, Any], seed: int, injector=None):
    from ..entry.common import FedExperiment
    from ..utils.compile_cache import no_persistent_cache

    # fresh compiles only (no_persistent_cache docstring): in-process
    # kill -> resume with programs deserialized from a warm cache trips
    # the known XLA:CPU donation bug into nondeterministic params
    with no_persistent_cache():
        exp = FedExperiment(cfg, seed)
        exp.chaos = injector
        return exp, exp.run("Global-Accuracy")


def run_kill_drill(plan, cfg_over: Dict[str, Any], out_root: str,
                   seed: int = 0, max_resumes: int = 8) -> Dict[str, Any]:
    """One kill-plan drill: reference run, then kill/corrupt/resume until
    completion; asserts bitwise-equal final params.  ``plan`` is a
    :class:`~heterofl_tpu.chaos.FaultPlan` (poison field ignored here)."""
    from ..chaos import ChaosKill, FaultInjector, corrupt_blob
    from ..utils.checkpoint import checkpoint_path, generation_path

    t0 = time.time()
    cfg_ref = drill_cfg(os.path.join(out_root, "ref"), **cfg_over)
    _, ref = _run_once(cfg_ref, seed)
    ref_params = _final_params(ref)

    cfg_ch = drill_cfg(os.path.join(out_root, "chaos"), **cfg_over)
    injector = FaultInjector(plan)
    resumes, corruptions, applied_corrupt = 0, [], False
    while True:
        cfg_run = dict(cfg_ch, resume_mode=0 if resumes == 0 else 1)
        try:
            exp, res = _run_once(cfg_run, seed, injector)
            break
        except ChaosKill as ck:
            # a real kill -9 frees the process; the in-process simulation
            # must free the dead run's device state explicitly -- the
            # traceback's frame cycle otherwise keeps the killed run's
            # donated buffers alive into the resume, which trips the
            # repo's known XLA:CPU deserialized-executable donation bug
            # (MEASUREMENTS.md Round 10) into nondeterministic params on
            # a warm compile cache
            ck.__traceback__ = None
            import gc

            gc.collect()
            resumes += 1
            if resumes > max_resumes:
                raise RuntimeError(
                    f"kill drill did not converge after {max_resumes} "
                    f"resumes (last kill: {ck})")
            if not applied_corrupt and plan.corrupt:
                # corruptions land between the kill and the resume: the
                # resume must fall back loudly to an older generation
                applied_corrupt = True
                from .. import config as C

                tag = C.make_model_tag(seed, cfg_ch)
                for c in plan.corrupt:
                    p = generation_path(
                        checkpoint_path(cfg_ch["output_dir"], tag,
                                        c["which"]), c["generation"])
                    if os.path.exists(p):
                        corruptions.append(corrupt_blob(p, c["mode"]))
    chaos_params = _final_params(res)
    ok = _params_equal(ref_params, chaos_params)
    return {"drill": "kill", "ok": ok,
            "plan": {"kills": plan.kills, "corrupt": plan.corrupt},
            "kills_fired": injector.fired, "resumes": resumes,
            "corruptions": corruptions,
            "bitwise_equal": ok,
            "wall_sec": round(time.time() - t0, 2)}


def pick_poison_uid(cfg: Dict[str, Any], seed: int, round_: int,
                    max_retries: int = 3) -> Optional[int]:
    """A uid drawn in round ``round_``'s cohort under the base stream but
    NOT drawn by that round under ANY of the first ``max_retries`` salted
    retry streams -- so a rollback recovery deterministically dodges the
    poison on its first replay (and every later one)."""
    import math

    import jax
    import numpy as np

    from ..fed.core import superstep_user_schedule
    from ..fed.sampling import resolve_sampler_cfg
    from ..obs.watchdog import RETRY_SALT
    from ..sched import resolve_schedule_cfg

    sched = resolve_schedule_cfg(cfg)
    samp = resolve_sampler_cfg(cfg).kind
    users = cfg["num_users"]
    active = int(math.ceil(cfg["frac"] * users))

    def row(key):
        r = np.asarray(superstep_user_schedule(key, round_, 1, users, active,
                                               schedule=sched, sampler=samp))
        return {int(u) for u in r[0] if u >= 0}

    base = jax.random.key(seed)
    orig = row(base)
    key = base
    retry_rows = []
    for n in range(1, max_retries + 1):
        key = jax.random.fold_in(key, RETRY_SALT + n)
        retry_rows.append(row(key))
    # prefer a uid absent from EVERY retry draw; dodging the FIRST retry
    # alone is already sufficient (a clean first replay completes the run,
    # so later salted streams never execute)
    for u in sorted(orig):
        if all(u not in rr for rr in retry_rows):
            return u
    for u in sorted(orig):
        if u not in retry_rows[0]:
            return u
    return None


def _read_log(cfg: Dict[str, Any], tag: str) -> List[Dict[str, Any]]:
    path = os.path.join(cfg["output_dir"], "runs", f"train_{tag}",
                        "log.jsonl")
    if not os.path.exists(path):
        return []
    return [json.loads(line) for line in open(path)]


def run_poison_drill(mode: str, cfg_over: Dict[str, Any], out_root: str,
                     seed: int = 0, poison_round: int = 3,
                     max_retries: int = 3) -> Dict[str, Any]:
    """One poison drill: NaN-poison a drawn (round, uid) update and prove
    the run completes -- ``mode='quarantine'`` via the in-program gate,
    ``mode='rollback'`` via watchdog auto-rollback (telemetry on,
    zero-backoff for the drill).  Returns the contract report including
    the rollback MTTR (trip -> first replayed train record)."""
    import numpy as np

    if mode not in ("quarantine", "rollback"):
        raise ValueError(f"Not valid poison drill mode: {mode!r} "
                         f"('quarantine' or 'rollback')")
    t0 = time.time()
    base_cfg = drill_cfg(os.path.join(out_root, mode), **cfg_over)
    uid = pick_poison_uid(base_cfg, seed, poison_round,
                          max_retries=max_retries)
    if uid is None:
        raise RuntimeError(
            f"no dodgeable poison uid at round {poison_round}: every "
            f"cohort member recurs in all {max_retries} salted redraws "
            f"(grow num_users or lower frac)")
    over = dict(cfg_over, chaos_poison=[[poison_round, int(uid)]])
    if mode == "quarantine":
        over["quarantine"] = "on"
    else:
        over["telemetry"] = "on"
        over["watchdog"] = {"action": "rollback", "max_retries": max_retries,
                            "backoff": 0.0}
    cfg = drill_cfg(os.path.join(out_root, mode), **over)
    exp, res = _run_once(cfg, seed)
    params = _final_params(res)
    finite = all(bool(np.all(np.isfinite(v))) for v in params.values())
    log = _read_log(cfg, exp.tag)
    report: Dict[str, Any] = {
        "drill": f"poison-{mode}", "poison": [poison_round, int(uid)],
        "final_params_finite": finite,
        "wall_sec": round(time.time() - t0, 2)}
    if mode == "quarantine":
        quarantined = sum(int(r.get("quarantined") or 0) for r in log
                          if r.get("tag") == "obs"
                          and r.get("event") == "probes")
        report["quarantined_total"] = quarantined
        report["ok"] = finite and quarantined >= 1
    else:
        trips = [r for r in log if r.get("tag") == "obs"
                 and r.get("event") == "watchdog"]
        recoveries = [r for r in log if r.get("tag") == "recovery"]
        report["trips"] = len(trips)
        report["recoveries"] = len(recoveries)
        report["escalated_to_abort"] = False  # run() raised otherwise
        mttr = None
        if trips and recoveries:
            t_trip = trips[0]["t"]
            after = [r["t"] for r in log if r.get("tag") == "train"
                     and r["t"] > recoveries[-1]["t"]]
            if after:
                mttr = round(min(after) - t_trip, 3)
        report["mttr_sec"] = mttr
        report["ok"] = finite and len(recoveries) >= 1
    return report


def run_smoke(out_root: str, json_out: bool = False) -> int:
    """The CI smoke: ONE kill plan (die before the 2nd checkpoint write,
    bitwise resume) + ONE poison plan (rollback recovery), tiny widths."""
    from ..chaos import resolve_fault_plan

    reports = []
    plan = resolve_fault_plan({"kills": [{"point": "checkpoint", "at": 2}]})
    reports.append(run_kill_drill(plan, {}, os.path.join(out_root, "kill")))
    reports.append(run_poison_drill("rollback", {},
                                    os.path.join(out_root, "poison")))
    ok = all(r["ok"] for r in reports)
    out = {"smoke": True, "ok": ok, "drills": reports}
    print(json.dumps(out) if json_out
          else "\n".join(f"[{'ok' if r['ok'] else 'FAIL'}] {r['drill']}: "
                         + json.dumps({k: v for k, v in r.items()
                                       if k not in ('drill', 'ok')})
                         for r in reports))
    return 0 if ok else 1


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m heterofl_tpu.chaos.drill",
        description="chaos drill: kill/corrupt/poison a driver run and "
                    "assert the recovery contract")
    parser.add_argument("--plan", default=None,
                        help="JSON fault plan: {kills: [{point, at}], "
                             "corrupt: [{which, mode, generation}], "
                             "poison: [[round, uid]]}")
    parser.add_argument("--poison-mode", default="rollback",
                        choices=("quarantine", "rollback"),
                        help="recovery mechanism for poison drills")
    parser.add_argument("--strategy", default="masked",
                        choices=("masked", "grouped"))
    parser.add_argument("--store", default="eager",
                        choices=("eager", "stream"))
    parser.add_argument("--superstep", type=int, default=2)
    parser.add_argument("--rounds", type=int, default=4)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--out", default=None,
                        help="work dir (default: a tempdir)")
    parser.add_argument("--smoke", action="store_true",
                        help="the CI smoke: one kill + one rollback poison")
    parser.add_argument("--json", action="store_true")
    args = parser.parse_args(argv)
    _scrub_env_for_cpu()
    # NOTE: deliberately no enable_persistent_cache() here -- every drill
    # sub-run compiles fresh inside no_persistent_cache() (_run_once)
    out_root = args.out or os.path.join(
        os.environ.get("TMPDIR", "/tmp"), f"chaos_drill_{os.getpid()}")
    if args.smoke:
        return run_smoke(out_root, json_out=args.json)
    over = {"strategy": args.strategy, "client_store": args.store,
            "superstep_rounds": args.superstep,
            "num_epochs": {"global": args.rounds, "local": 1}}
    from ..chaos import resolve_fault_plan

    plan = resolve_fault_plan(json.loads(args.plan) if args.plan
                              else {"kills": [{"point": "superstep",
                                               "at": 2}]})
    reports = []
    if plan.kills or plan.corrupt:
        reports.append(run_kill_drill(plan, over,
                                      os.path.join(out_root, "kill"),
                                      seed=args.seed))
    if plan.poison is not None:
        # the plan's poison rounds drive the drill; each pair drills
        # independently so one report names one contract
        for r, _u in [tuple(p) for p in plan.poison.tolist()]:
            reports.append(run_poison_drill(
                args.poison_mode, over,
                os.path.join(out_root, f"poison_r{r}"), seed=args.seed,
                poison_round=int(r)))
    ok = all(r["ok"] for r in reports) and bool(reports)
    out = {"ok": ok, "drills": reports}
    print(json.dumps(out) if args.json else
          "\n".join(f"[{'ok' if r['ok'] else 'FAIL'}] {r['drill']}: "
                    + json.dumps({k: v for k, v in r.items()
                                  if k not in ('drill', 'ok')})
                    for r in reports))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
