"""Chaos fault injection, jax half (ISSUE 15): the in-program NaN poison.

One function, called from both engines' round cores right after local
training -- the poisoned client's *update* goes NaN before aggregation,
exactly the adversarial-client model PAPERS.md 1610.05492 assumes the
aggregator survives.  The poison table is a trace-time constant (resolved
once at engine construction from ``cfg['chaos_poison']``), so unpoisoned
engines build byte-identical programs with zero new arguments.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def poison_updates(trained, table: np.ndarray, epoch, uids):
    """NaN-poison the slots whose (round, uid) matches the plan.

    ``trained``: per-slot trained param trees ``{k: [S, ...]}``;
    ``table``: the int32 ``[N, 2]`` (round, uid) plan
    (:func:`~heterofl_tpu.chaos.resolve_poison_cfg`); ``epoch``: the
    round's traced epoch scalar; ``uids``: the raw per-slot global user
    ids (``-1`` padding never matches a uid >= 0).  Adds ``NaN`` to every
    element of a matched slot's trees -- the poison flows through the
    quarantine gate (or, un-gated, through the psum into the globals,
    which is the watchdog-rollback drill's trigger)."""
    rounds = jnp.asarray(table[:, 0])
    targets = jnp.asarray(table[:, 1])
    hit = jnp.any((rounds[None, :] == epoch)
                  & (targets[None, :] == uids[:, None]), axis=1)
    bad = jnp.where(hit, jnp.float32(jnp.nan), jnp.float32(0.0))

    def bend(v):
        return v + bad.reshape((-1,) + (1,) * (v.ndim - 1)).astype(v.dtype)

    return {k: bend(v) for k, v in trained.items()}
