"""Deterministic chaos harness (ISSUE 15 tentpole piece 4).

Nothing in this repo *exercised* a failure on purpose until this package:
the checkpoint path was only ever tested by clean round trips, the
watchdog only by synthetic probe records, and "a crash between the pickle
write and the rename" was a comment, not a test.  This package makes
failure a first-class, replayable input:

* :class:`FaultPlan` -- a validated spec (the config.py loud-ValueError
  convention) naming **kills** at driver boundaries (``superstep``
  dispatch, ``fetch``, ``checkpoint`` write, ``prefetch``), **corruptions**
  of checkpoint bytes on disk (truncate / bit-flip, by generation), and
  **poisons**: ``(round, uid)`` client updates NaN-poisoned IN-PROGRAM
  after local training, before aggregation (:mod:`.inject`, threaded
  through both engines via ``cfg['chaos_poison']``).
* :class:`FaultInjector` -- counts occurrences per kill point inside the
  driver and raises :class:`ChaosKill` when the plan says die.  The kill
  is a ``BaseException`` so ordinary ``except Exception`` recovery code
  cannot accidentally swallow the simulated process death.
* ``python -m heterofl_tpu.chaos.drill`` -- runs a small driver under a
  plan and asserts the recovery contract: for every kill point, resume
  == the uninterrupted run **bitwise**; for every corruption, resume
  falls back loudly to the previous verifying generation; for every
  poison, quarantine (or watchdog rollback) completes the run without
  human intervention.

Import-light on purpose (numpy only): ``config.process_control``
validates ``cfg['chaos_poison']`` through :func:`resolve_poison_cfg`, and
the config module's jax-free import contract must hold.  The jax half
lives in :mod:`.inject`; the driver-running drill in :mod:`.drill`.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

#: the named driver boundaries a FaultPlan may kill at -- each maps to one
#: ``FedExperiment._chaos(point)`` call site: ``superstep`` fires before a
#: train dispatch (superstep or K=1 round), ``fetch`` before the metrics
#: fetch/push, ``checkpoint`` before the blob write, ``prefetch`` before a
#: streaming cohort stages ahead.
KILL_POINTS = ("superstep", "fetch", "checkpoint", "prefetch")

#: checkpoint-corruption modes: ``truncate`` halves the blob, ``flip``
#: XORs one payload byte (the checksum must catch both).
CORRUPT_MODES = ("truncate", "flip")


class ChaosKill(BaseException):
    """A simulated process death at a named driver boundary.

    Deliberately a ``BaseException`` (like ``KeyboardInterrupt``): a real
    ``kill -9`` is not catchable, so no ``except Exception`` recovery
    path in the code under test may see it either -- only the drill
    harness, which catches it explicitly and then resumes a FRESH
    experiment from disk."""

    def __init__(self, point: str, occurrence: int):
        super().__init__(f"chaos kill at {point!r} occurrence {occurrence}")
        self.point = point
        self.occurrence = occurrence


def resolve_poison_cfg(cfg: Dict[str, Any]) -> Optional[np.ndarray]:
    """Validate ``cfg['chaos_poison']`` and return the int32 ``[N, 2]``
    (round, uid) table, or None when unset.

    THE one validator (the config.py convention): malformed tables fail
    loudly at config time, never as a silently-unpoisoned chaos drill."""
    raw = cfg.get("chaos_poison")
    if raw is None:
        return None
    if not isinstance(raw, (list, tuple)) or not raw:
        raise ValueError(f"Not valid chaos_poison: {raw!r} (a non-empty "
                         f"list of [round, uid] pairs, or None)")
    table = []
    for item in raw:
        if (not isinstance(item, (list, tuple)) or len(item) != 2
                or any(not isinstance(v, int) or isinstance(v, bool)
                       or v < 0 for v in item)):
            raise ValueError(f"Not valid chaos_poison entry: {item!r} "
                             f"(a [round >= 0, uid >= 0] int pair)")
        table.append((int(item[0]), int(item[1])))
    # poison x engine cross-check (ISSUE 18): promoted from the driver.
    if (cfg.get("strategy", "masked") or "masked") == "sliced":
        raise ValueError(
            "Not valid chaos_poison with strategy='sliced': the sliced "
            "debug twin has no in-program update to poison -- use a "
            "mesh-native strategy ('masked' or 'grouped')")
    return np.asarray(table, np.int32)


class FaultPlan:
    """One validated chaos plan: ``kills`` (point -> 1-based occurrence
    indices), ``corrupt`` (checkpoint byte corruptions the drill applies
    between the kill and the resume) and ``poison`` ((round, uid) pairs
    forwarded into ``cfg['chaos_poison']``)."""

    def __init__(self, kills: Sequence[Dict[str, Any]] = (),
                 corrupt: Sequence[Dict[str, Any]] = (),
                 poison: Optional[np.ndarray] = None):
        self.kills: Dict[str, List[int]] = {}
        for k in kills:
            self.kills.setdefault(k["point"], []).append(k["at"])
        self.corrupt = list(corrupt)
        self.poison = poison

    @property
    def n_kills(self) -> int:
        return sum(len(v) for v in self.kills.values())


def resolve_fault_plan(raw: Dict[str, Any]) -> FaultPlan:
    """Validate a plan dict (typically JSON from the drill CLI) into a
    :class:`FaultPlan` -- the config.py loud-ValueError convention."""
    if not isinstance(raw, dict):
        raise ValueError(f"Not valid fault plan: {raw!r} (a dict with "
                         f"optional kills/corrupt/poison lists)")
    unknown = set(raw) - {"kills", "corrupt", "poison"}
    if unknown:
        raise ValueError(f"Not valid fault plan keys: {sorted(unknown)} "
                         f"(kills/corrupt/poison)")
    kills = []
    for k in raw.get("kills") or []:
        if not isinstance(k, dict) or set(k) - {"point", "at"}:
            raise ValueError(f"Not valid kill spec: {k!r} "
                             f"({{'point': ..., 'at': n}})")
        point = k.get("point")
        if point not in KILL_POINTS:
            raise ValueError(f"Not valid kill point: {point!r} "
                             f"(one of {KILL_POINTS})")
        at = k.get("at", 1)
        if not isinstance(at, int) or isinstance(at, bool) or at < 1:
            raise ValueError(f"Not valid kill occurrence: {at!r} "
                             f"(a 1-based int)")
        kills.append({"point": point, "at": at})
    corrupt = []
    for c in raw.get("corrupt") or []:
        if not isinstance(c, dict) or set(c) - {"which", "mode", "generation"}:
            raise ValueError(f"Not valid corrupt spec: {c!r} ({{'which': "
                             f"'checkpoint'|'best', 'mode': 'truncate'|"
                             f"'flip', 'generation': g}})")
        which = c.get("which", "checkpoint")
        if which not in ("checkpoint", "best"):
            raise ValueError(f"Not valid corrupt target: {which!r} "
                             f"('checkpoint' or 'best')")
        mode = c.get("mode", "flip")
        if mode not in CORRUPT_MODES:
            raise ValueError(f"Not valid corrupt mode: {mode!r} "
                             f"(one of {CORRUPT_MODES})")
        gen = c.get("generation", 0)
        if not isinstance(gen, int) or isinstance(gen, bool) or gen < 0:
            raise ValueError(f"Not valid corrupt generation: {gen!r} "
                             f"(an int >= 0; 0 is the live blob)")
        corrupt.append({"which": which, "mode": mode, "generation": gen})
    poison = resolve_poison_cfg({"chaos_poison": raw.get("poison")})
    return FaultPlan(kills=kills, corrupt=corrupt, poison=poison)


class FaultInjector:
    """Counts driver-boundary occurrences and raises :class:`ChaosKill`
    when the plan schedules a death there.

    One injector SURVIVES across kill + resume cycles in the drill (the
    occurrence counters keep running), so a plan can schedule several
    kills along one logical run.  ``fired`` records every kill taken."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self.counts: Dict[str, int] = {p: 0 for p in KILL_POINTS}
        self.fired: List[Tuple[str, int]] = []

    def check(self, point: str) -> None:
        if point not in self.counts:
            raise ValueError(f"unknown chaos point {point!r} "
                             f"(one of {KILL_POINTS})")
        self.counts[point] += 1
        n = self.counts[point]
        if n in self.plan.kills.get(point, ()):
            self.fired.append((point, n))
            raise ChaosKill(point, n)


def corrupt_blob(path: str, mode: str) -> Dict[str, Any]:
    """Corrupt one checkpoint blob on disk: ``truncate`` keeps the first
    half of the bytes, ``flip`` XORs one byte deep in the payload (past
    the header so the magic survives and the CHECKSUM must catch it).
    Returns a small record of what was done (the drill's report)."""
    with open(path, "rb") as f:
        raw = f.read()
    if mode == "truncate":
        out = raw[: max(1, len(raw) // 2)]
    elif mode == "flip":
        pos = min(len(raw) - 1, max(64, len(raw) // 2))
        out = raw[:pos] + bytes([raw[pos] ^ 0xFF]) + raw[pos + 1:]
    else:
        raise ValueError(f"Not valid corrupt mode: {mode!r} "
                         f"(one of {CORRUPT_MODES})")
    with open(path, "wb") as f:
        f.write(out)
    return {"path": path, "mode": mode, "bytes_before": len(raw),
            "bytes_after": len(out)}
