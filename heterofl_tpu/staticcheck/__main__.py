"""CLI: ``python -m heterofl_tpu.staticcheck [--json] [...]``.

Runs the AST lint (jax-free, milliseconds) and then the program audit
(lowers/compiles the flagship program matrix on a CPU mesh).  Exits 0 only
when both fronts are clean; writes the ``STATICCHECK.json`` artifact that
``bench.py`` folds into ``extra.staticcheck`` (and refuses to record
against when stale-failed).

The env scrub below MUST run before jax initialises: this environment
boots a TPU-tunnel PJRT plugin via sitecustomize that pins
``jax_platforms`` and hangs CPU-only init (see tests/conftest.py), and the
audit needs an 8-device virtual CPU platform for the slices placement.
``heterofl_tpu.staticcheck`` itself stays jax-free so the lint front (and
``--skip-audit``) never boots a backend at all.
"""

from __future__ import annotations

import argparse

import os
import sys
from datetime import datetime, timezone

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _scrub_env_for_cpu_audit() -> None:
    for v in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE",
              "AXON_LOOPBACK_RELAY", "AXON_POOL_SVC_OVERRIDE"):
        os.environ.pop(v, None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = \
            (flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ.setdefault("JAX_ENABLE_X64", "0")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m heterofl_tpu.staticcheck",
        description="jaxpr/HLO program auditor + hot-path lint gate")
    parser.add_argument("--json", action="store_true",
                        help="print the full report as JSON (default: "
                             "findings + one summary line)")
    parser.add_argument("--flagship", action="store_true",
                        help="audit at full CIFAR-10 ResNet-18 widths "
                             "(slower; tightens the FLOP-share tolerance "
                             "to 2%%)")
    parser.add_argument("--skip-audit", action="store_true",
                        help="lint only (never imports jax)")
    parser.add_argument("--list", action="store_true",
                        help="print the program/check matrix (names only, "
                             "nothing is audited) and exit")
    parser.add_argument("--only", metavar="GLOB", default=None,
                        help="audit only programs matching this fnmatch "
                             "glob; cross-program checks (flop budget, "
                             "lattice, key streams, ...) are skipped -- "
                             "incompatible with --diff-baseline/"
                             "--update-baseline")
    parser.add_argument("--lattice-md", action="store_true",
                        help="print the compatibility-lattice markdown "
                             "(the README section is generated from this; "
                             "jax-free) and exit")
    parser.add_argument("--aot-v4128", action="store_true",
                        help="also run the subprocess v4-128 AOT multi-"
                             "host check (ISSUE 17); records into "
                             "config.aot_v4128, tries the TPU topology "
                             "then falls back to a 64-device CPU mesh")
    parser.add_argument("--skip-lint", action="store_true",
                        help="program audit only")
    parser.add_argument("--flop-tol", type=float, default=None,
                        help="override the FLOP-share tolerance")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--lint-root", default=_REPO,
                        help="tree to lint (default: this repo)")
    parser.add_argument("--out", default=os.path.join(_REPO, "STATICCHECK.json"),
                        help="artifact path (default: <repo>/STATICCHECK.json)")
    parser.add_argument("--no-artifact", action="store_true",
                        help="do not write the artifact file")
    parser.add_argument("--baseline", default=None,
                        help="ratchet baseline path (default: "
                             "<repo>/STATICCHECK_BASELINE.json)")
    parser.add_argument("--diff-baseline", action="store_true",
                        help="diff the fresh audit against the committed "
                             "baseline; exit 2 on any ratchet regression "
                             "(audit/lint failures still exit 1)")
    parser.add_argument("--update-baseline", action="store_true",
                        help="re-pin the baseline from this (green) audit "
                             "after an intentional metric change")
    args = parser.parse_args(argv)
    if args.baseline is None:
        from .ratchet import BASELINE_BASENAME

        args.baseline = os.path.join(_REPO, BASELINE_BASENAME)
    if (args.diff_baseline or args.update_baseline) and args.skip_audit:
        parser.error("--diff-baseline/--update-baseline need the program "
                     "audit (drop --skip-audit)")
    if args.only and (args.diff_baseline or args.update_baseline):
        parser.error("--only audits a subset -- the ratchet baseline "
                     "covers the full matrix (drop --only)")

    if args.lattice_md:
        # jax-free: the lattice replays the validator chain, nothing is
        # traced.  ``--lattice-md > section.md`` regenerates the README's
        # "Compatibility lattice" section.
        from .lattice import lattice_markdown

        print(lattice_markdown())
        return 0

    if args.list:
        _scrub_env_for_cpu_audit()
        from .audit import CROSS_CHECKS, list_targets

        names = list_targets(flagship=args.flagship, seed=args.seed)
        print(f"# {len(names)} programs (audit matrix)")
        for n in names:
            print(f"program {n}")
        print(f"# {len(CROSS_CHECKS)} cross-program checks "
              f"(skipped under --only)")
        for c in CROSS_CHECKS:
            print(f"check   {c}")
        print("check   lint")
        return 0

    from .report import AuditReport
    from .rules import lint_tree, pragma_sweep

    lint_findings = []
    if not args.skip_lint:
        subdirs = ["heterofl_tpu"] if args.lint_root == _REPO else None
        lint_findings = lint_tree(args.lint_root, subdirs=subdirs)
        if subdirs:
            # ISSUE 18 satellite: pragma liveness sweeps the WHOLE repo
            # (tests/, scripts/, ...), not just the scoped package tree
            lint_findings += pragma_sweep(args.lint_root,
                                          exclude=tuple(subdirs))

    if args.skip_audit:
        report = AuditReport()
    else:
        _scrub_env_for_cpu_audit()
        from ..utils.compile_cache import enable_persistent_cache

        enable_persistent_cache()  # amortise the program-matrix compiles
        from .audit import run_audit

        report = run_audit(flagship=args.flagship, flop_tol=args.flop_tol,
                           seed=args.seed, with_aot=args.aot_v4128,
                           only=args.only)
    report.add_lint(lint_findings)
    report.generated_at = datetime.now(timezone.utc).isoformat()
    report.config["argv"] = list(argv) if argv is not None else sys.argv[1:]
    report.config["skipped"] = {"audit": args.skip_audit,
                                "lint": args.skip_lint}

    # baseline ratchet (ISSUE 7): the analytic budgets are ceilings, the
    # committed baseline is the tight line -- diff before the artifact is
    # written so STATICCHECK.json carries the ratchet section
    from .ratchet import diff_reports, load_baseline, write_baseline

    if args.diff_baseline:
        try:
            baseline = load_baseline(args.baseline)
        except (OSError, ValueError) as e:
            report.ratchet = {
                "checked": True, "ok": False,
                "regressions": [{"program": "<baseline>", "metric": "load",
                                 "baseline": None, "current": None,
                                 "tolerance": 0.0,
                                 "message": f"cannot load baseline "
                                            f"{args.baseline}: {e} -- run "
                                            f"--update-baseline on a green "
                                            f"tree and commit the file"}],
                "improvements": [], "new_programs": [],
                "missing_programs": []}
        else:
            report.ratchet = diff_reports(report.to_dict(), baseline)
    if args.update_baseline:
        if not report.ok:
            # refuse the pin but fall through: the failing artifact still
            # gets written and the findings still print, exactly like a
            # plain failing run
            print("staticcheck: refusing to pin a baseline from a FAILING "
                  "audit -- fix the findings first", file=sys.stderr)
        else:
            write_baseline(args.baseline, report.to_dict())

    if not args.no_artifact:
        with open(args.out, "w") as f:
            f.write(report.to_json())
            f.write("\n")

    ratchet_regressed = report.ratchet.get("checked") \
        and not report.ratchet.get("ok")
    if args.json:
        print(report.to_json())
    else:
        for f in report.all_findings():
            print(f)
        for reg in report.ratchet.get("regressions", []):
            print(f"{reg['program']}: [ratchet:{reg['metric']}] "
                  f"{reg['baseline']} -> {reg['current']}: {reg['message']}")
        n_prog = len(report.programs)
        verdict = "OK" if report.ok else "FAILED"
        if report.ok and ratchet_regressed:
            verdict = "RATCHET REGRESSED"
        print(f"staticcheck: {verdict} -- "
              f"{n_prog} programs audited, "
              f"{len(report.all_findings())} finding(s), "
              f"{len(report.ratchet.get('regressions', []))} ratchet "
              f"regression(s)"
              + ("" if args.no_artifact else f"; artifact: {args.out}"))
    if not report.ok:
        return 1
    return 2 if ratchet_regressed else 0


if __name__ == "__main__":
    sys.exit(main())
