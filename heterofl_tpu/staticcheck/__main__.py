"""CLI: ``python -m heterofl_tpu.staticcheck [--json] [...]``.

Runs the AST lint (jax-free, milliseconds) and then the program audit
(lowers/compiles the flagship program matrix on a CPU mesh).  Exits 0 only
when both fronts are clean; writes the ``STATICCHECK.json`` artifact that
``bench.py`` folds into ``extra.staticcheck`` (and refuses to record
against when stale-failed).

The env scrub below MUST run before jax initialises: this environment
boots a TPU-tunnel PJRT plugin via sitecustomize that pins
``jax_platforms`` and hangs CPU-only init (see tests/conftest.py), and the
audit needs an 8-device virtual CPU platform for the slices placement.
``heterofl_tpu.staticcheck`` itself stays jax-free so the lint front (and
``--skip-audit``) never boots a backend at all.
"""

from __future__ import annotations

import argparse

import os
import sys
from datetime import datetime, timezone

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _scrub_env_for_cpu_audit() -> None:
    for v in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE",
              "AXON_LOOPBACK_RELAY", "AXON_POOL_SVC_OVERRIDE"):
        os.environ.pop(v, None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = \
            (flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ.setdefault("JAX_ENABLE_X64", "0")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m heterofl_tpu.staticcheck",
        description="jaxpr/HLO program auditor + hot-path lint gate")
    parser.add_argument("--json", action="store_true",
                        help="print the full report as JSON (default: "
                             "findings + one summary line)")
    parser.add_argument("--flagship", action="store_true",
                        help="audit at full CIFAR-10 ResNet-18 widths "
                             "(slower; tightens the FLOP-share tolerance "
                             "to 2%%)")
    parser.add_argument("--skip-audit", action="store_true",
                        help="lint only (never imports jax)")
    parser.add_argument("--skip-lint", action="store_true",
                        help="program audit only")
    parser.add_argument("--flop-tol", type=float, default=None,
                        help="override the FLOP-share tolerance")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--lint-root", default=_REPO,
                        help="tree to lint (default: this repo)")
    parser.add_argument("--out", default=os.path.join(_REPO, "STATICCHECK.json"),
                        help="artifact path (default: <repo>/STATICCHECK.json)")
    parser.add_argument("--no-artifact", action="store_true",
                        help="do not write the artifact file")
    args = parser.parse_args(argv)

    from .report import AuditReport
    from .rules import lint_tree

    lint_findings = []
    if not args.skip_lint:
        subdirs = ["heterofl_tpu"] if args.lint_root == _REPO else None
        lint_findings = lint_tree(args.lint_root, subdirs=subdirs)

    if args.skip_audit:
        report = AuditReport()
    else:
        _scrub_env_for_cpu_audit()
        from ..utils.compile_cache import enable_persistent_cache

        enable_persistent_cache()  # amortise the program-matrix compiles
        from .audit import run_audit

        report = run_audit(flagship=args.flagship, flop_tol=args.flop_tol,
                           seed=args.seed)
    report.add_lint(lint_findings)
    report.generated_at = datetime.now(timezone.utc).isoformat()
    report.config["argv"] = list(argv) if argv is not None else sys.argv[1:]
    report.config["skipped"] = {"audit": args.skip_audit,
                                "lint": args.skip_lint}

    if not args.no_artifact:
        with open(args.out, "w") as f:
            f.write(report.to_json())
            f.write("\n")

    if args.json:
        print(report.to_json())
    else:
        for f in report.all_findings():
            print(f)
        n_prog = len(report.programs)
        print(f"staticcheck: {'OK' if report.ok else 'FAILED'} -- "
              f"{n_prog} programs audited, "
              f"{len(report.all_findings())} finding(s)"
              + ("" if args.no_artifact else f"; artifact: {args.out}"))
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
