"""Jaxpr / lowered-IR walking utilities for the program auditor.

Everything here is *static*: programs are traced/lowered/compiled but never
executed.  The walkers recurse through every sub-jaxpr (scan/while bodies,
cond/switch branches, shard_map and custom-derivative bodies), so an op
smuggled inside a ``lax.scan`` round body is found exactly like a top-level
one.
"""

from __future__ import annotations

import re
from collections import Counter
from typing import Any, Iterable, List, Set, Tuple

import jax

#: primitive names that call back into the host (banned in round programs:
#: one callback serialises the whole fused round on the host boundary)
CALLBACK_PRIMITIVES = ("pure_callback", "io_callback", "debug_callback")

#: collective primitives whose axis names must resolve in the mesh
COLLECTIVE_PRIMITIVES = ("psum", "all_gather", "all_to_all", "ppermute",
                        "pmax", "pmin", "reduce_scatter")


def _sub_jaxprs(params: dict) -> Iterable[Any]:
    for v in params.values():
        vs = v if isinstance(v, (tuple, list)) else (v,)
        for item in vs:
            if isinstance(item, jax.core.ClosedJaxpr):
                yield item.jaxpr
            elif isinstance(item, jax.core.Jaxpr):
                yield item


def iter_eqns(jaxpr) -> Iterable[Any]:
    """Yield every eqn of ``jaxpr`` (a ``Jaxpr`` or ``ClosedJaxpr``),
    recursing into all sub-jaxprs."""
    if isinstance(jaxpr, jax.core.ClosedJaxpr):
        jaxpr = jaxpr.jaxpr
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn.params):
            yield from iter_eqns(sub)


def provenance(eqn) -> str:
    """``file:line (fn)`` of the python frame that bound the op, best
    effort -- the loud half of a callback/f64 finding."""
    try:
        from jax._src import source_info_util

        return source_info_util.summarize(eqn.source_info)
    except Exception:
        return "<unknown provenance>"


def primitive_counts(jaxpr) -> Counter:
    return Counter(eqn.primitive.name for eqn in iter_eqns(jaxpr))


#: primitives that derive or consume PRNG state in a traced program --
#: every one of these binds must descend from a declared (salt, purpose)
#: root (ISSUE 18: staticcheck/keys.py)
RANDOM_PRIMITIVE_PREFIXES = ("random_", "threefry")


def random_bind_files(jaxpr, package_root: str) -> Set[str]:
    """Package-relative source files of every PRNG bind in ``jaxpr``.

    Walks all ``random_*``/``threefry*`` eqns (recursing into sub-jaxprs)
    and maps each bind's user frame back to the file that bound it; files
    outside ``package_root`` (jax internals, test harnesses) are dropped.
    The key-stream audit cross-checks the result against the modules its
    SALT_REGISTRY models -- randomness appearing in an unmodeled package
    file has no declared provenance."""
    import os

    root = os.path.abspath(package_root)
    files: Set[str] = set()
    for eqn in iter_eqns(jaxpr):
        name = eqn.primitive.name
        if not any(name.startswith(p) for p in RANDOM_PRIMITIVE_PREFIXES):
            continue
        prov = provenance(eqn)  # "path:line (fn)"
        path = os.path.abspath(prov.rsplit(":", 1)[0])
        if path.startswith(root + os.sep):
            files.add(os.path.relpath(path, root).replace(os.sep, "/"))
    return files


def find_callbacks(jaxpr) -> List[Tuple[str, str]]:
    """(primitive name, provenance) of every host-callback op."""
    out = []
    for eqn in iter_eqns(jaxpr):
        if any(eqn.primitive.name.startswith(p) for p in CALLBACK_PRIMITIVES):
            out.append((eqn.primitive.name, provenance(eqn)))
    return out


def find_f64(jaxpr) -> List[Tuple[str, str]]:
    """(description, provenance) of every float64 value or convert: a silent
    f64 in a round program doubles its bandwidth/footprint (and on TPU
    deoptimises to software emulation)."""
    import numpy as np

    out = []
    for eqn in iter_eqns(jaxpr):
        nd = eqn.params.get("new_dtype")
        if eqn.primitive.name == "convert_element_type" and nd == np.float64:
            out.append((f"convert_element_type -> float64", provenance(eqn)))
            continue
        for var in eqn.outvars:
            aval = getattr(var, "aval", None)
            if getattr(aval, "dtype", None) == np.float64:
                out.append((f"{eqn.primitive.name} produces float64 "
                            f"{getattr(aval, 'shape', ())}", provenance(eqn)))
                break
    return out


def collective_axes(eqn) -> Tuple[str, ...]:
    """Flattened axis names a collective eqn operates over."""
    axes = eqn.params.get("axes", eqn.params.get("axis_name", ()))
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    flat = []
    for a in axes:
        if isinstance(a, (tuple, list)):
            flat.extend(a)
        else:
            flat.append(a)
    return tuple(str(a) for a in flat if isinstance(a, (str,)) or a is not None)


def count_collectives(jaxpr) -> Tuple[Counter, Set[str]]:
    """(per-primitive bind counts, all axis names seen).  A ``psum`` over
    ``(sums, counts)`` is ONE bind -- the budget the engines are audited
    against counts collective launches, not leaves."""
    counts: Counter = Counter()
    axes: Set[str] = set()
    for eqn in iter_eqns(jaxpr):
        name = eqn.primitive.name
        if any(name == p or name.startswith(p + "_") for p in COLLECTIVE_PRIMITIVES):
            counts[name] += 1
            axes.update(collective_axes(eqn))
    return counts, axes


def count_psum_over(jaxpr, axis: str = "clients") -> int:
    """psum binds whose axes include ``axis`` (the global-collective
    budget; a data-axis psum inside intra-client DP is not a global one)."""
    n = 0
    for eqn in iter_eqns(jaxpr):
        if eqn.primitive.name == "psum" and axis in collective_axes(eqn):
            n += 1
    return n


def collective_payload_rows(jaxpr) -> List[dict]:
    """One priced row per collective bind: primitive, sorted axis names,
    per-participant payload bytes (sum of operand aval bytes -- under
    ``shard_map`` the operands are per-device values, so this is exactly
    what each participant contributes to the wire), operand shapes/dtypes,
    and provenance.  The wire model (:mod:`.wire`) turns these into
    ICI/DCN-classified budgets."""
    import numpy as np

    rows = []
    for eqn in iter_eqns(jaxpr):
        name = eqn.primitive.name
        if not any(name == p or name.startswith(p + "_")
                   for p in COLLECTIVE_PRIMITIVES):
            continue
        payload = 0
        operands = []
        for v in eqn.invars:
            aval = getattr(v, "aval", None)
            dt = getattr(aval, "dtype", None)
            if dt is None:
                continue
            try:
                nbytes = int(np.prod(aval.shape)) * np.dtype(dt).itemsize
            except TypeError:  # extended dtypes (PRNG keys) have no itemsize
                continue
            payload += nbytes
            operands.append([list(map(int, aval.shape)), str(dt)])
        rows.append({"primitive": name, "axes": sorted(collective_axes(eqn)),
                     "payload_bytes": payload, "operands": operands,
                     "provenance": provenance(eqn)})
    return rows


#: jaxpr-level primitives that MOVE data between devices without reducing
#: it -- explicit reshards; zero are allowed in any round program
RESHARD_PRIMITIVES = ("all_to_all", "ppermute")

#: optimized-HLO instruction ops GSPMD inserts to fix up sharding
#: mismatches -- implicit reshards the jaxpr never shows; zero allowed
RESHARD_HLO_OPS = ("all-to-all", "collective-permute")


def find_reshards(jaxpr) -> List[Tuple[str, str]]:
    """(primitive, provenance) of every explicit data-movement collective
    bound in the program (jaxpr level)."""
    out = []
    for eqn in iter_eqns(jaxpr):
        name = eqn.primitive.name
        if any(name == p or name.startswith(p + "_")
               for p in RESHARD_PRIMITIVES):
            out.append((name, provenance(eqn)))
    return out


def reshard_ops(compiled_text: str) -> dict:
    """Counts of GSPMD-introduced data-movement instructions in an
    optimized-HLO dump: ``all-to-all`` and ``collective-permute`` (their
    async ``-start`` forms count once; ``-done`` halves are skipped).
    These appear when sharding propagation decides operands live on the
    wrong devices -- data movement the jaxpr walk cannot see, and exactly
    what the multi-host slices work must keep at zero."""
    out = {}
    for op in RESHARD_HLO_OPS:
        # `= <shape> op(`: the shape may be a tuple (async -start forms), so
        # allow anything shape-like between `=` and the op name; `[^=]`
        # keeps the match from crossing into metadata/attribute text
        out[op] = len(re.findall(
            rf"=[ ]*[^=\n]*?\b{re.escape(op)}(?:-start)?\(", compiled_text))
    out["total"] = sum(out.values())
    return out


def count_psum_joint(jaxpr, axes: Tuple[str, ...] = ("clients", "data")) -> int:
    """psum binds whose axis set includes ALL of ``axes`` -- the eval
    phase's whole-mesh reductions (sBN moments, Global metric sums) reduce
    over ``(clients, data)`` jointly, while every training-round psum binds
    a single axis, so this cleanly separates the eval-fused superstep's
    collective budget from the one-global-psum-per-training-round
    invariant."""
    n = 0
    for eqn in iter_eqns(jaxpr):
        if eqn.primitive.name == "psum":
            seen = collective_axes(eqn)
            if all(a in seen for a in axes):
                n += 1
    return n


# ---------------------------------------------------------------------------
# optimized-HLO computation parsing: the step-body kernel count
# ---------------------------------------------------------------------------

def hlo_computations(compiled_text: str) -> dict:
    """``{computation_name: block_text}`` of an optimized HLO module dump.

    Computations start at column 0 (``%name (params) -> type {`` or
    ``ENTRY ...``) and end at a column-0 ``}``."""
    blocks, name, buf = {}, None, []
    for line in compiled_text.splitlines():
        if not line.startswith(" ") and "{" in line and name is None:
            m = re.search(r"%?([\w\.\-]+)\s*\(", line)
            if m:
                name = m.group(1)
                buf = [line]
        elif name is not None:
            buf.append(line)
            if line.startswith("}"):
                blocks[name] = "\n".join(buf)
                name = None
    return blocks


def while_body_stats(compiled_text: str) -> dict:
    """Per-while-loop-body kernel stats of an optimized HLO module:
    ``{body_name: {"fusions": n, "instructions": m}}``.

    ``fusions`` counts fusion-instruction launches inside the body -- the
    CPU/TPU proxy for per-iteration kernel count; ``instructions`` is the
    body's total op count.  Scans lower to whiles, so the LOCAL-STEP body
    of a round program is one of these (in practice the largest)."""
    blocks = hlo_computations(compiled_text)
    out = {}
    for body in set(re.findall(r"body=%?([\w\.\-]+)", compiled_text)):
        blk = blocks.get(body)
        if blk is None:
            continue
        out[body] = {
            "fusions": len(re.findall(r"= \S+ fusion\(", blk)),
            "instructions": len(re.findall(r"^\s+\S+ = ", blk, re.M)),
        }
    return out


def scan_body_kernel_count(compiled_text: str) -> dict:
    """Kernel stats of THE scan body -- the largest while body by
    instruction count (the local-step loop dominates every round program;
    smaller whiles are bookkeeping).  ``{"fusions": n, "instructions": m,
    "body": name}``; zeros when the program has no loop."""
    stats = while_body_stats(compiled_text)
    if not stats:
        return {"fusions": 0, "instructions": 0, "body": None}
    body = max(stats, key=lambda b: stats[b]["instructions"])
    return {**stats[body], "body": body}


# ---------------------------------------------------------------------------
# donation / aliasing, from the lowered & compiled IR text
# ---------------------------------------------------------------------------

def donation_marks(lowered_text: str) -> int:
    """Donated input tensors at lowering: ``jax.buffer_donor`` (donation
    deferred to XLA) + ``tf.aliasing_output`` (aliasing already pinned)."""
    return lowered_text.count("jax.buffer_donor") + \
        lowered_text.count("tf.aliasing_output")


def aliased_outputs(compiled_text: str) -> int:
    """Input-output alias pairs the compiled executable actually
    established -- donation that CONSUMED a buffer, not just permission.

    Parsed from the optimized ``HloModule`` header, which lists one
    ``{out_index}: (param, {}, may-alias)`` entry per aliased tensor inside
    ``input_output_alias={ ... }`` (brace-balanced scan: the entries
    themselves contain ``{}`` sub-indices)."""
    start = compiled_text.find("input_output_alias={")
    if start < 0:
        return 0
    i = compiled_text.index("{", start)
    depth, j = 0, i
    for j in range(i, min(len(compiled_text), i + 1_000_000)):
        c = compiled_text[j]
        if c == "{":
            depth += 1
        elif c == "}":
            depth -= 1
            if depth == 0:
                break
    block = compiled_text[i:j + 1]
    return block.count("may-alias") + block.count("must-alias")
